"""Posterior-sampling benchmark (ISSUE 9): whole-chain-on-device
MCMC vs the per-step dispatch baseline.

The pre-ISSUE-9 ensemble loop paid two supervised dispatches PER MCMC
step (the exact dispatch-tax shape ISSUE 7 eliminated for fitting);
``pint_tpu.sampling`` collapses an entire ensemble run into one
deadline-supervised ``lax.scan`` dispatch per chain chunk. This bench
measures both modes ON THE SAME KERNEL — ``mode="host_loop"`` is the
chunk program compiled at K=1, consuming the identical positional
PRNG stream, so the speedup is pure dispatch-tax amortization. On the
CPU (IEEE) backend the two chains are asserted BIT-IDENTICAL before
any number is reported; on an accelerator the flag is recorded
honestly in the artifact (K=1 and K=256 are different XLA programs —
under the TPU's non-correctly-rounded emulated f64 they may round
differently without either being wrong).

Run:  python bench_posterior.py [--nsteps 512] [--nwalkers 32]
                                [--repeats 3] [--serve]
Prints one JSON line per mode; the LAST line is the artifact
(steps/s per mode, speedup, dispatch_overhead block with the
<10%-target overhead_frac, dispatch_supervisor counters, lint state).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


PAR = """
PSR J0005+0005
RAJ 08:00:00.0
DECJ 25:00:00.0
F0 180.0 1
F1 -2.5e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 12.0
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def build_posterior(ntoa: int = 120):
    """One simulated pulsar's DevicePosterior (fixed noise — the
    bench target is the CHAIN dispatch shape, not the likelihood's
    internals) with proper Gaussian priors so every walker starts
    finite."""
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.models.priors import GaussianPrior
    from pint_tpu.sampling import DevicePosterior
    from pint_tpu.simulation import make_fake_toas_uniform

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        toas = make_fake_toas_uniform(
            54000, 56000, ntoa, model, freq_mhz=1400.0,
            add_noise=True, rng=np.random.default_rng(42))
    for name in ("F0", "F1"):
        p = model.get_param(name)
        p.prior = GaussianPrior(p.value,
                                max(abs(p.value) * 1e-9, 1e-18))
    return DevicePosterior(model, toas)


def _run_mode(post, mode: str, nwalkers: int, nsteps: int,
              repeats: int, seed: int = 7):
    """Best-of-``repeats`` wall for one mode; returns (wall_s,
    sampler) of the best run (compiles paid by a warmup run)."""
    import numpy as np

    from pint_tpu.sampling import DeviceEnsembleSampler

    p0 = post.init_walkers(nwalkers, rng=np.random.default_rng(3))
    walls = []
    # ONE sampler across warmup + repeats: its jitted chunk program
    # compiles on the warmup run, so the timed runs measure dispatch
    # + chain math, not retracing (run_mcmc overwrites chain state;
    # identical seed -> identical chain every run)
    samp = DeviceEnsembleSampler(nwalkers, post.nparams,
                                 post.lnpost_batch)
    for r in range(repeats + 1):  # +1 warmup
        samp.reset_dispatch_count()
        t0 = time.perf_counter()
        samp.run_mcmc(p0, nsteps, seed=seed, mode=mode)
        w = time.perf_counter() - t0
        if r > 0:
            walls.append(w)
    return min(walls), samp


def measure_overhead(post, nwalkers: int, nsteps: int,
                     wall_scan: float, seed: int = 7) -> dict:
    """Dispatch-overhead split for the whole-chain mode: the marginal
    per-step cost comes from the SAME compiled executable via budget
    variation (a full-budget vs half-budget run of one chunk class),
    so ``pure_step_ms`` is what the chain math itself costs and
    ``overhead_frac`` is everything else — dispatch, PRNG host prep,
    D2H readback (<10% target, same contract as bench.py's fit
    artifact)."""
    import numpy as np

    from pint_tpu import config
    from pint_tpu.sampling import DeviceEnsembleSampler

    p0 = post.init_walkers(nwalkers, rng=np.random.default_rng(3))
    # one sampler reused warm->timed, and both step counts chosen to
    # quantize to the SAME chunk class K (nsteps is a runtime budget
    # inside one executable), so the wall difference isolates the
    # marginal in-kernel step cost with zero retracing between runs
    s = DeviceEnsembleSampler(nwalkers, post.nparams,
                              post.lnpost_batch)
    K = config.chain_chunk_steps(nsteps)
    full, half = K, K // 2 + 1   # both -> chunk class K

    def wall_of(n):
        s.run_mcmc(p0, n, seed=seed, mode="scan")  # warm
        t0 = time.perf_counter()
        s.run_mcmc(p0, n, seed=seed, mode="scan")
        return time.perf_counter() - t0

    w_full, w_half = wall_of(full), wall_of(half)
    per_step_ms = max(0.0, (w_full - w_half) / (full - half)) * 1e3
    pure_ms = per_step_ms * nsteps
    wall_ms = wall_scan * 1e3
    return {
        "per_step_ms": round(per_step_ms, 4),
        "pure_step_ms": round(pure_ms, 2),
        "chain_wall_ms": round(wall_ms, 2),
        "overhead_frac": round(
            max(0.0, (wall_ms - pure_ms) / wall_ms), 4)
        if wall_ms > 0 else None,
    }


def measure_serve(nwalkers: int, nsteps: int) -> dict:
    """Coalesced PosteriorRequest serving: a 4-pulsar bucket runs as
    ONE vmapped chunked dispatch sequence; reported against serving
    the same requests one flush at a time."""
    import copy
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.serve import PosteriorRequest, ServeEngine
    from pint_tpu.simulation import make_fake_toas_uniform

    problems = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for k in range(4):
            par = PAR.replace("F0 180.0", f"F0 {180.0 + 40 * k}")
            m = get_model(io.StringIO(par))
            toas = make_fake_toas_uniform(
                54000, 56000, 100 + 10 * k, m, freq_mhz=1400.0,
                add_noise=True, rng=np.random.default_rng(k))
            problems.append(build_problem(toas, m))

    def reqs():
        return [PosteriorRequest(problem=copy.copy(pr),
                                 nwalkers=nwalkers, nsteps=nsteps,
                                 seed=11 + k)
                for k, pr in enumerate(problems)]

    def drive(eng, coalesced: bool):
        futs = []
        for r in reqs():
            futs.append(eng.submit(r))
            if not coalesced:
                eng.flush()
        eng.flush()
        for f in futs:
            f.result(timeout=0)

    seq_eng, co_eng = ServeEngine(), ServeEngine()
    drive(seq_eng, False)   # warmup + sequential compile
    drive(co_eng, True)
    t0 = time.perf_counter()
    drive(seq_eng, False)
    seq_w = time.perf_counter() - t0
    t0 = time.perf_counter()
    drive(co_eng, True)
    co_w = time.perf_counter() - t0
    snap = co_eng.metrics.snapshot()
    return {
        "nreq": 4,
        "sequential_wall_ms": round(seq_w * 1e3, 2),
        "coalesced_wall_ms": round(co_w * 1e3, 2),
        "coalesced_speedup": round(seq_w / co_w, 2),
        "compile_count": snap["compile_count"],
        "router": snap.get("router"),
        "admission": snap.get("admission"),
    }


def run(nwalkers: int = 32, nsteps: int = 512, repeats: int = 3,
        serve: bool = True) -> dict:
    import jax
    import numpy as np

    from pint_tpu import config
    from pint_tpu.runtime import get_supervisor

    backend = jax.default_backend()
    log(f"backend: {backend}")
    post = build_posterior()
    K = config.chain_chunk_steps(nsteps)
    log(f"chain chunk K={K} for nsteps={nsteps}")

    w_host, s_host = _run_mode(post, "host_loop", nwalkers, nsteps,
                               repeats)
    print(json.dumps({
        "metric": "posterior_host_loop_steps_per_s",
        "backend": backend, "unit": "steps/s",
        "value": round(nsteps / w_host, 1),
        "nsteps": nsteps, "nwalkers": nwalkers,
        "dispatches": s_host.dispatches,
        "wall_ms": round(w_host * 1e3, 2)}), flush=True)

    w_scan, s_scan = _run_mode(post, "scan", nwalkers, nsteps,
                               repeats)
    bit_identical = bool(
        np.array_equal(s_host.chain, s_scan.chain)
        and np.array_equal(s_host.lnprob, s_scan.lnprob))
    log(f"scan-vs-host_loop bit-identical: {bit_identical}")
    if backend == "cpu" and not bit_identical:
        # on IEEE hardware the two modes are the SAME kernel on the
        # same stream — divergence is a regression, never a headline
        raise RuntimeError(
            "scan vs host_loop diverged on the CPU oracle backend")

    rec = {
        "metric": "posterior_whole_chain_vs_per_step",
        "backend": backend, "unit": "x",
        "value": round(w_host / w_scan, 2),
        "nsteps": nsteps, "nwalkers": nwalkers,
        "ndim": post.nparams,
        "chunk_steps": K,
        "host_loop_steps_per_s": round(nsteps / w_host, 1),
        "whole_chain_steps_per_s": round(nsteps / w_scan, 1),
        "whole_chain_dispatches": s_scan.dispatches,
        "host_loop_dispatches": s_host.dispatches,
        "acceptance": round(s_scan.acceptance_fraction, 3),
        "bit_identical": bit_identical,
        "dispatch_overhead": measure_overhead(post, nwalkers,
                                              nsteps, w_scan),
        "dispatch_supervisor": get_supervisor().snapshot(),
        "lint": _lint_block(),
    }
    # ISSUE 10: the supervisor's per-(pool,key) dispatch-wall
    # histograms as the top-level `latency` block + tracer/flight
    # state — the same artifact shape as bench.py / bench_serve.py
    rec["latency"] = get_supervisor().metrics.latency.snapshot()
    from pint_tpu import obs

    rec["obs"] = obs.status()
    # ISSUE 15: which executables this run built and what each cost
    # (chain-chunk keys land via the supervisor's first_call ledger)
    try:
        from pint_tpu.obs import perf as operf

        rec["compiles"] = operf.ledger_summary()
    except Exception:
        pass
    if serve:
        rec["serve"] = measure_serve(nwalkers, max(64, nsteps // 4))
    # perf-regression verdict against BENCH_BASELINE.json (ISSUE 11)
    try:
        import bench as _bench

        _bench.attach_regress(rec)
    except Exception:
        pass
    return rec


def _lint_block():
    try:
        from pint_tpu.analysis import lint_state_safe

        return lint_state_safe()
    except Exception as e:  # analyzer package unimportable
        return {"clean": None, "error": repr(e)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nsteps", type=int, default=512)
    ap.add_argument("--nwalkers", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--no-serve", action="store_true",
                    help="skip the coalesced-serving section")
    args = ap.parse_args()

    import os

    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        from bench import accelerator_responsive, cpu_fallback_env

        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    import jax

    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    rec = run(nwalkers=args.nwalkers, nsteps=args.nsteps,
              repeats=args.repeats, serve=not args.no_serve)
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
