#!/usr/bin/env bash
# tools/check.sh — the pre-merge gate, cheapest check first:
#
#   1. graftlint --changed-only (seconds: AST rules on the git diff)
#   2. the lint lane      (pytest -m lint: full repo-clean gate,
#                          mesh-free per tests/conftest.py)
#   3. the fast test lane (pytest -m "not slow": the tier-1 surface)
#
# Every python invocation is timeout-bounded and the PALLAS_AXON_*
# vars are stripped first: a wedged axon tunnel HANGS backend init
# without erroring, even under JAX_PLATFORMS=cpu, unless the plugin
# vars are removed from the environment (CLAUDE.md gotchas).
set -euo pipefail
cd "$(dirname "$0")/.."

for v in "${!PALLAS_AXON@}"; do unset "$v"; done
export JAX_PLATFORMS=cpu

echo "[check 1/3] graftlint --changed-only"
timeout -k 10 180 python -m pint_tpu.analysis.graftlint \
    --changed-only --format json

echo "[check 2/3] lint lane (pytest -m lint)"
timeout -k 10 300 python -m pytest tests/ -q -m lint \
    -p no:cacheprovider

echo "[check 3/3] fast test lane (pytest -m 'not slow')"
timeout -k 10 870 python -m pytest tests/ -q -m "not slow" \
    -p no:cacheprovider

# opt-in perf-regression lane (ISSUE 11): runs the three bench
# drivers in bounded subprocesses and gates their LAST-JSON-line
# artifacts against BENCH_BASELINE.json. Off by default — benches
# take minutes; arm with PINT_TPU_BENCH_REGRESS=1.
if [[ "${PINT_TPU_BENCH_REGRESS:-0}" == "1" ]]; then
    echo "[check 4/4, opt-in] bench perf-regression gate"
    timeout -k 10 3600 python tools/bench_regress.py --run
fi

echo "[check] all gates green"
