"""On-chip benchmark capture — run during a live axon-tunnel window.

The tunnel dies for hours and revives for ~tens of minutes
(ARCHITECTURE.md, round-4 session notes), so every on-chip number must
be captured opportunistically and committed immediately. This tool is
stage-based and ledger-driven:

- each --stage NAME measures one benchmark group on the default
  backend and appends raw JSON lines (UTC-stamped, backend-tagged) to
  BENCH_TPU.jsonl via bench.tpu_record_append;
- --remaining prints the stages whose headline metric is not yet in
  the ledger with backend==tpu (no jax device touch — safe while the
  tunnel is wedged);
- --auto runs all remaining stages in priority order.

tools/tpu_watcher.sh drives this: bounded probe every ~9 min, then
one stage at a time under its own timeout, git-committing the ledger
after each stage so a tunnel death mid-capture loses at most the
in-flight stage. Stage priority mirrors VERDICT.md round-4 item 1:
the production (hybrid-Jacobian) north star first — the re-measure
pending since PR 6 — then the ISSUE-7 async_fit pair (whole-fit
dispatch overhead + pipelined serve), the N-scan, variant
attribution, configs 2-5, and the PTA scaling sweep.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (lazy: imports jax only inside functions)

# stage -> headline metric
STAGES = {
    "north_star": "gls_fit_iteration_throughput_10k_toas_40p",
    "async_fit": "whole_fit_dispatch_overhead",
    "scan": "gls_step_nscaling",
    "attr": "step_variant_attribution",
    "config2": "config2_b1855like_gls_ecorr_5k",
    "config3": "config3_j1713like_wideband_step_2k",
    "config4": "config4_j0613like_fullcov_gls_2k",
    "config5": "config5_pta_batch_67psr",
    "pta_scale": "pta_batch_scaling",
    "pta_gwb": "gwb_sweep",
    "stress": "stress_nanograv_like_10k_fit",
    "stress_wideband": "stress_nanograv_like_10k_fit_wideband",
    "serve": "serve_coalesced_vs_sequential_64req",
    "serve_degraded": "serve_degraded_overload",
    "posterior": "posterior_whole_chain_vs_per_step",
    "trace": "trace_capture_north_star_plus_serve",
    "metrics": "serve_metrics_plane",
    "streaming": "gls_streaming_scan",
    "append": "serve_append_incremental_vs_cold_100k",
    "health": "north_star_health_overhead",
    "perf": "north_star_perf_attribution",
    "fleet": "fleet_degraded",
}
SCAN_NS = (10_000, 30_000, 100_000)
# on-chip streaming points: bounded to fit one watcher stage window
# (the 1M CPU-mesh point is the bench artifact; on chip the curve's
# shape is the evidence, captured at sizes that finish in minutes)
STREAM_NS = (100_000, 1_000_000)
ATTR_VARIANTS = ("production", "no_hybrid_jac", "jac_f64",
                 "matmul_f64", "unanchored", "round3_all_f64")
PTA_SIZES = (67, 134, 268)


def remaining():
    """Stages not fully captured THIS round. A stage whose metric is
    a family (per-N scan points, per-variant attribution, per-size
    PTA sweep) is done only when EVERY member is in the ledger — a
    tunnel death mid-stage must leave the stage on the to-do list.
    Records imported from the round-4 raw capture file (flagged
    "imported": pre-hybrid configuration) don't count as done — the
    whole point of round 5 is measuring the production post-hybrid
    config on chip. Error records don't count either."""
    recs = [r for r in bench.load_tpu_records().values()
            if not r.get("imported") and "error" not in r]

    def have(metric, **kv):
        return any(r.get("metric") == metric
                   and all(r.get(k) == v for k, v in kv.items())
                   for r in recs)

    out = []
    for stage, metric in STAGES.items():
        if stage == "scan":
            done = all(have(metric, ntoa=n) for n in SCAN_NS)
        elif stage == "streaming":
            done = all(have(metric, ntoa=n) for n in STREAM_NS)
        elif stage == "attr":
            done = all(have(metric, variant=v) for v in ATTR_VARIANTS)
        elif stage == "pta_scale":
            done = all(have(metric, npulsars=n) for n in PTA_SIZES)
        else:
            done = have(metric)
        if not done:
            out.append(stage)
    return out


def _init_jax():
    import jax

    # bounded probe BEFORE any in-process backend touch (graftlint
    # G6): run directly (outside the watcher's timeout), a wedged
    # tunnel would hang jax.default_backend() below with no error
    if not bench.accelerator_responsive():
        bench.log("backend probe unresponsive (wedged tunnel?); "
                  "refusing the in-process backend init")
        sys.exit(4)
    jax.config.update("jax_enable_x64", True)
    from pint_tpu.config import enable_compile_cache

    enable_compile_cache("PINT_TPU_BENCH_JIT_CACHE",
                         os.path.join(REPO, ".jax_compile_cache"))
    backend = jax.default_backend()
    bench.log(f"capture backend: {backend} devices: {jax.devices()}")
    if backend != "tpu" and "--allow-cpu" not in sys.argv:
        bench.log("not on TPU; refusing to write the on-chip ledger")
        sys.exit(3)
    return backend


def stage_north_star(backend):
    """Production (post-hybrid) fit step: the number VERDICT.md round 4
    flagged as never measured on chip. Auto flags (anchored + f32
    Jacobian + f32 MXU matmul + hybrid) all engage on TPU."""
    model, toas = bench.build_problem()
    t, chi2, jitted, args, step_fn = bench.measure_step(model, toas)
    rec = {"metric": STAGES["north_star"],
           "backend": backend, "unit": "TOA/s",
           "dispatch_ms": round(t * 1e3, 2), "chi2": round(chi2, 1)}
    per_iter = t
    try:
        tc = bench.measure_step_chained((step_fn, args), k=8)
        rec["step_ms_chained8"] = round(tc * 1e3, 2)
        per_iter = min(per_iter, tc)
    except Exception as e:
        bench.log(f"  chained failed: {e!r}")
    rec["step_ms"] = round(per_iter * 1e3, 2)
    rec["value"] = round(toas.ntoas / per_iter, 1)
    rec.update(bench.roofline_fields(jitted, args, per_iter, backend))
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_async_fit(backend):
    """Whole-fit-on-device + pipelined serve (ISSUE 7): the dispatch
    tax measured ON CHIP — the entire downhill fit as one donated
    lax.while_loop dispatch (the <10% overhead target), plus a small
    pipelined-vs-sync serve run. Queued right after the north star
    so a short tunnel window still captures the headline pair."""
    model, toas = bench.build_problem()
    t, chi2, jitted, args, step_fn = bench.measure_step(model, toas)
    per = t
    try:
        tc = bench.measure_step_chained((step_fn, args), k=8)
        per = min(per, tc)
    except Exception as e:
        bench.log(f"  chained failed: {e!r}")
    rec = {"metric": STAGES["async_fit"], "backend": backend,
           "dispatch_ms": round(t * 1e3, 2),
           "step_ms": round(per * 1e3, 2)}
    rec.update(bench.measure_whole_fit(model, toas, per_step_s=per))
    del jitted, args, step_fn, model, toas
    try:
        import bench_serve

        srec = bench_serve.run(nreq=32, repeats=2)
        rec["serve_pipelined_vs_sync"] = (
            srec.get("dispatch_overhead") or {}).get(
            "pipelined_vs_sync")
        rec["serve_speedup"] = srec.get("value")
    except Exception as e:  # the whole-fit number must survive a
        rec["serve_error"] = repr(e)  # serve-half failure
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_scan(backend):
    bench.scan_nscaling()  # appends per-N records itself on TPU


def stage_attr(backend):
    """Per-variant attribution of the production configuration: what
    each redesign (anchored delta-phase, f32/dd32 Jacobian, f32-MXU
    normal equations, hybrid analytic/AD Jacobian) buys ON CHIP."""
    model, toas = bench.build_problem()
    flag_sets = {
        "production": {},
        "no_hybrid_jac": {"hybrid_jac": False},
        "jac_f64": {"jac_f32": False},
        "matmul_f64": {"matmul_f32": False},
        "unanchored": {"anchored": False},
        "round3_all_f64": {"jac_f32": False, "matmul_f32": False,
                           "anchored": False, "hybrid_jac": False},
    }
    for name in ATTR_VARIANTS:
        flags = flag_sets[name]
        try:
            t, chi2, jitted, args, step_fn = bench.measure_step(
                model, toas, reps=3, **flags)
            rec = {"metric": STAGES["attr"], "variant": name,
                   "backend": backend,
                   "dispatch_ms": round(t * 1e3, 2),
                   "chi2": round(chi2, 2)}
            try:
                tc = bench.measure_step_chained((step_fn, args), k=8)
                rec["chained_ms"] = round(tc * 1e3, 2)
            except Exception as e:
                bench.log(f"  {name} chained failed: {e!r}")
            per_iter = min(t, rec.get("chained_ms", t * 1e3) / 1e3)
            rec.update(bench.roofline_fields(jitted, args, per_iter,
                                             backend))
        except Exception as e:
            rec = {"metric": STAGES["attr"], "variant": name,
                   "backend": backend, "error": repr(e)}
        bench.tpu_record_append(rec)
        print(json.dumps(rec), flush=True)


def _config_stage(fn, backend):
    rec = fn()
    rec["backend"] = backend
    # (config3's one-kernel step record was already appended inside
    # the config function; rec here is its downhill full-fit metric)
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_pta_scale(backend):
    """PTA batch scaling beyond 67 pulsars (VERDICT round-4 item 5):
    grow the array until the chip saturates; report TOA/s and the
    device-solve share at each size."""
    from bench_pta import build_pulsar

    from pint_tpu.parallel import fit_pta

    for npsr in PTA_SIZES:
        t0 = time.perf_counter()
        pulsars = [build_pulsar(k, 100) for k in range(npsr)]
        build_s = time.perf_counter() - t0
        res = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=2)
        stats = fit_pta.last_stats
        n_ok = sum(
            1 for (m, t, truth), r in zip(pulsars, res)
            if abs(m.F0.value - truth["F0"]) < 5 * r["errors"]["F0"])
        rec = {"metric": STAGES["pta_scale"], "backend": backend,
               "npulsars": npsr, "unit": "TOA/s",
               "value": round(stats["toas_per_sec"], 1),
               "ntoa_total": stats["ntoa_total"],
               "device_solve_ms":
                   round(stats["device_solve_s"] * 1e3, 1),
               "build_s": round(build_s, 1),
               "recovered_5sigma": n_ok}
        bench.tpu_record_append(rec)
        print(json.dumps(rec), flush=True)


def stage_pta_gwb(backend):
    """Array GWB likelihood plane ON CHIP (ISSUE 17): Hellings-Downs
    block assembly sharded over the chip's local devices vs
    single-device, then the chunked (log10_A, gamma) detection sweep
    through the supervised outer Schur dispatches. On a 1-device
    chip the sharded leg auto-skips and the sweep throughput +
    roofline are the record."""
    import argparse

    import bench_pta

    rec = bench_pta.run_gwb(argparse.Namespace(
        npulsars=67, ntoa=100, nfreq=5, grid=8))
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_pta.run_gwb ran on {rec.get('backend')!r}, not "
            f"{backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_stress(backend, wideband=False):
    """NANOGrav-scale full production fit (bench_stress): 10k TOAs,
    124 free params, per-receiver noise families — the realistic
    full-fit workload on chip, with the chained device dispatch
    doing real amortization work. ``wideband=True`` runs the joint
    [time; DM] variant (the stress_wideband stage, VERDICT r5 item
    5)."""
    import subprocess

    stage = "stress_wideband" if wideband else "stress"
    cmd = [sys.executable, os.path.join(REPO, "bench_stress.py")]
    if wideband:
        cmd.append("--wideband")
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=2100)
    for line in (r.stdout or "").strip().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == STAGES[stage]:
            if rec.get("backend") != backend:
                # the subprocess has its own hang-proof CPU fallback;
                # a host number must NOT mark the on-chip stage done
                raise RuntimeError(
                    f"bench_stress ran on {rec.get('backend')!r}, "
                    f"not {backend!r} (tunnel died?); stage stays "
                    f"on the to-do list")
            bench.tpu_record_append(rec)
            print(json.dumps(rec), flush=True)
            return
    raise RuntimeError(f"bench_stress produced no record "
                       f"(rc={r.returncode}): {r.stderr[-500:]}")


def stage_serve(backend):
    """Serving-layer coalescing speedup ON CHIP (ISSUE 2): over the
    axon tunnel each sequential dispatch pays the full 0.1-0.25 s
    RTT, so this is where coalescing matters most — the CPU-mesh
    number in BENCH_r*.json is the architectural floor."""
    import bench_serve

    rec = bench_serve.run(nreq=64, repeats=3)
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_serve ran on {rec.get('backend')!r}, not "
            f"{backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_serve_degraded(backend):
    """Coalesced-vs-shed throughput under injected overload (ISSUE
    8): the admission controller's shed policy exercised ON CHIP —
    what the service actually delivers when a burst exceeds
    capacity, with every shed labeled in the record."""
    import bench_serve

    rec = bench_serve.run_degraded(nreq=64)
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_serve.run_degraded ran on {rec.get('backend')!r}"
            f", not {backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_fleet(backend):
    """3-worker kill-one fleet throughput curve ON CHIP (ISSUE 19):
    baseline / degraded-with-mid-burst-kill / recovered — on the
    tunnel the re-home replay pays real dispatch RTTs, so this is
    the honest blast-radius number (lost must still be 0)."""
    import bench_serve

    rec = bench_serve.run_fleet()
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_serve.run_fleet ran on {rec.get('backend')!r}, "
            f"not {backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_streaming(backend):
    """Matrix-free streaming GLS ON CHIP (ISSUE 12): the chunked
    accumulator + CG curve at 100k and 1M TOAs on a single chip —
    the memory-unbounded fit path measured on the hardware it was
    built for. Reuses bench.scan_streaming (its per-point records
    are backend-tagged and self-appended to the ledger; the CPU
    equality oracle auto-skips above 131k)."""
    bench.scan_streaming()


def stage_append(backend):
    """Incremental AppendTOAsRequest vs cold refit ON CHIP (ISSUE
    12): the O(new-TOA) re-convergence under real dispatch RTT —
    over the tunnel the cold refit pays the full (N-row upload +
    solve) while the warm append ships a bucket's worth of rows."""
    import bench_serve

    rec = bench_serve.run_append(ntoa=100_000, nnew=128)
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_serve.run_append ran on {rec.get('backend')!r}, "
            f"not {backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_posterior(backend):
    """Whole-chain-on-device MCMC vs the per-step dispatch baseline
    ON CHIP (ISSUE 9): over the axon tunnel the host-loop mode pays
    the full RTT twice per step, so the whole-chain speedup here is
    the subsystem's real win — the CPU-mesh 13.6x in the bench
    artifact is the architectural floor."""
    import bench_posterior

    rec = bench_posterior.run(nwalkers=32, nsteps=512, repeats=3)
    if rec.get("backend") != backend:
        raise RuntimeError(
            f"bench_posterior ran on {rec.get('backend')!r}, not "
            f"{backend!r} (tunnel died?); stage stays on the "
            f"to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_trace(backend):
    """Chrome-trace capture of the north-star fit + one serve batch
    ON CHIP (ISSUE 10): a live-tunnel window's causal record — every
    supervised dispatch span with its real RTT, retries and breaker
    events — written as trace_tpu_<utc>.json in the repo root
    (viewable in Perfetto / chrome://tracing). The ledger record
    carries the span counts and the measured tracing overhead."""
    from pint_tpu import obs

    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(REPO, f"trace_tpu_{stamp}.json")
    obs.configure(enabled=True)
    try:
        model, toas = bench.build_problem()
        t, chi2, jitted, args, _ = bench.measure_step(model, toas)
        obs_block, _ = bench.measure_obs_overhead(
            lambda: _block(jitted, args))
        # measure_obs_overhead resets the global tracer on exit —
        # re-arm it so the fit + serve legs below are recorded
        obs.configure(enabled=True)
        # one device fit + one coalesced serve batch inside the trace
        from pint_tpu.gls import DeviceDownhillGLSFitter

        DeviceDownhillGLSFitter(toas, model).fit_toas(maxiter=4)
        try:
            from pint_tpu.serve import ServeEngine
            from pint_tpu.serve.workload import build_workload

            eng = ServeEngine()
            futs = [eng.submit(r) for r in build_workload(
                8, sizes=(40, 90), base=5100, prebuild=True,
                entry_name="TRACE")()]
            eng.flush()
            for f in futs:
                f.result(timeout=0)
        except Exception as e:
            bench.log(f"  serve leg of the trace failed: {e!r}")
        n = obs.export(path)
    finally:
        obs.reset()
    rec = {"metric": STAGES["trace"], "backend": backend,
           "unit": "events", "value": n, "path": os.path.basename(path),
           "step_ms": round(t * 1e3, 2), "obs": obs_block}
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_metrics(backend):
    """Metrics-plane scrape during a live-tunnel serve window
    (ISSUE 11): drive a coalesced serve workload with the /metrics
    exposition live, scrape it + the SLO watchdog snapshot, and
    ledger the parse/parity evidence — the on-chip proof that the
    pull surface works against real tunnel-latency dispatches."""
    import urllib.request

    from pint_tpu import obs
    from pint_tpu.obs import metrics as om
    from pint_tpu.obs.slo import SLOSpec, SLOWatchdog

    obs.reset()  # fresh registry: the scrape counts THIS window
    srv = om.MetricsServer(port=0).start()
    wd = SLOWatchdog(specs=[SLOSpec(
        name="e2e_p99_gls", type="latency",
        metric="pint_tpu_serve_latency_seconds",
        labels={"metric": "e2e", "kind": "gls"},
        objective_ms=5000.0, target=0.99, fast_s=5.0, slow_s=20.0)],
        interval_s=1.0)
    try:
        from pint_tpu.serve import ServeEngine
        from pint_tpu.serve.workload import build_workload

        eng = ServeEngine()
        futs = [eng.submit(r) for r in build_workload(
            16, sizes=(40, 90), base=5300, prebuild=True,
            entry_name="METRICS")()]
        eng.flush()
        for f in futs:
            f.result(timeout=0)
        wd.tick()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30) as r:
            text = r.read().decode("utf-8")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz",
                timeout=30) as r:
            health = json.loads(r.read().decode("utf-8"))
        series = sum(1 for ln in text.splitlines()
                     if ln and not ln.startswith("#"))
        completed = om.get_registry().value(
            "pint_tpu_serve_completed_total",
            scope=eng.metrics.scope)
        snap = eng.metrics.snapshot()
        rec = {"metric": STAGES["metrics"], "backend": backend,
               "unit": "series", "value": series,
               "scrape_bytes": len(text),
               "completed": snap["completed"],
               "registry_completed": int(completed),
               "parity_ok": int(completed) == snap["completed"],
               "healthz_ok": bool(health.get("ok")),
               "slo": wd.status()}
    finally:
        srv.close()
        obs.reset()
    if not rec.get("parity_ok"):
        raise RuntimeError(
            "registry-vs-snapshot parity failed in the metrics "
            "stage; stage stays on the to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_health(backend):
    """Numerical-health plane ON CHIP (ISSUE 14): the disarmed-vs-
    armed north-star step walls (the in-trace taps' real cost under
    tunnel dispatch), plus the armed evidence run — CG effort and,
    critically, the REAL emulated-f64 device-vs-host drift in sigma
    from a forced shadow replay. This is the number that makes
    captures past the 131k dense-oracle ceiling trustworthy: the
    drift histogram here is measured against actual TPU numerics,
    not the CPU mesh's exact f64."""
    model, toas = bench.build_problem()
    hblock, evidence = bench.measure_health_overhead(model, toas)
    rec = {"metric": STAGES["health"], "backend": backend,
           "unit": "frac",
           "value": hblock.get("health_overhead_frac"),
           **hblock,
           "monitor": evidence}
    drift_rows = evidence.get("drift") or {}
    if not any(r.get("count") for r in drift_rows.values()):
        # a replay that ran but DECLINED (ok=False solve) still
        # counts in shadow_replays — the gate must demand an actual
        # drift histogram sample, or the record ships no evidence
        raise RuntimeError(
            "no drift sample landed in the health stage (replay "
            "declined or never ran); stage stays on the to-do list")
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def stage_perf(backend):
    """Performance-attribution plane ON CHIP (ISSUE 15): the compile
    ledger + ledger-derived roofline of the production north-star
    step against the REAL v5e peak table (the quantitative target
    line for the >600k TOA/s goal), the dispatch-wall decomposition
    under real tunnel RTT (queue/assembly/device/collect — the
    first direct measurement of where the 0.1-0.25 s dispatch cost
    actually goes), and one bounded profiler window of the step —
    the Perfetto-loadable device trace cross-linked to span ids."""
    from pint_tpu import obs
    from pint_tpu.obs import perf as operf

    model, toas = bench.build_problem()
    t, chi2, jitted, args, step_fn = bench.measure_step(model, toas)
    per = t
    try:
        per = min(per, bench.measure_step_chained((step_fn, args),
                                                  k=8))
    except Exception as e:
        bench.log(f"  chained failed: {e!r}")
    # decomposition first (it resets the plane on exit), then the
    # ledger + window under an explicit configure
    decomp = bench.measure_perf_decomposition(
        lambda: _block(jitted, args))
    pdir = os.path.join(REPO, "profile_tpu")
    obs.configure(enabled=True)  # span ring for the window export
    operf.configure(enabled=True, profile_dir=pdir, max_s=30.0)
    try:
        operf.note_compile("bench.north_star_step", backend=backend,
                           kind="fit_step", jitted=jitted, args=args)
        roof = operf.roofline_block("bench.north_star_step", per,
                                    backend)
        window = operf.request_window(5.0, reason="tpu_capture")
        t_end = time.perf_counter() + 5.5
        while time.perf_counter() < t_end:
            _block(jitted, args)
        # bounded: wait for the window's own close, then read status
        t0 = time.perf_counter()
        while operf.get_profiler().status()["open"] is not None \
                and time.perf_counter() - t0 < 60.0:
            time.sleep(0.25)
        pstat = operf.get_profiler().status()
        ledger = operf.ledger_summary()
    finally:
        obs.reset()
    if roof is None or not roof.get("flops"):
        raise RuntimeError(
            "no cost analysis landed in the ledger (backend did not "
            "report); stage stays on the to-do list")
    rec = {"metric": STAGES["perf"], "backend": backend,
           "unit": "GFLOP/s", "value": roof.get("gflops_achieved"),
           "step_ms": round(per * 1e3, 2),
           "roofline": roof, "dispatch_decomposition": decomp,
           "compiles": ledger, "profile_window": window,
           "profiler": pstat}
    bench.tpu_record_append(rec)
    print(json.dumps(rec), flush=True)


def _block(jitted, args):
    import jax

    return jax.block_until_ready(jitted(*args))


def run_stage(name, backend):
    bench.log(f"=== stage {name} ===")
    t0 = time.perf_counter()
    if name == "north_star":
        stage_north_star(backend)
    elif name == "async_fit":
        stage_async_fit(backend)
    elif name == "scan":
        stage_scan(backend)
    elif name == "attr":
        stage_attr(backend)
    elif name == "config2":
        _config_stage(bench.config2_b1855like, backend)
    elif name == "config3":
        _config_stage(bench.config3_j1713like_wideband, backend)
    elif name == "config4":
        _config_stage(bench.config4_j0613like_fullcov, backend)
    elif name == "config5":
        _config_stage(bench.config5_pta, backend)
    elif name == "pta_scale":
        stage_pta_scale(backend)
    elif name == "pta_gwb":
        stage_pta_gwb(backend)
    elif name == "stress":
        stage_stress(backend)
    elif name == "stress_wideband":
        stage_stress(backend, wideband=True)
    elif name == "serve":
        stage_serve(backend)
    elif name == "serve_degraded":
        stage_serve_degraded(backend)
    elif name == "posterior":
        stage_posterior(backend)
    elif name == "trace":
        stage_trace(backend)
    elif name == "metrics":
        stage_metrics(backend)
    elif name == "streaming":
        stage_streaming(backend)
    elif name == "append":
        stage_append(backend)
    elif name == "health":
        stage_health(backend)
    elif name == "perf":
        stage_perf(backend)
    elif name == "fleet":
        stage_fleet(backend)
    else:
        raise SystemExit(f"unknown stage {name}")
    bench.log(f"=== stage {name} done in "
              f"{time.perf_counter() - t0:.0f}s ===")


def main():
    if "--remaining" in sys.argv:
        print(" ".join(remaining()))
        return
    backend = _init_jax()
    if "--auto" in sys.argv:
        for name in remaining():
            run_stage(name, backend)
        return
    if "--stage" in sys.argv:
        run_stage(sys.argv[sys.argv.index("--stage") + 1], backend)
        return
    raise SystemExit("usage: tpu_capture.py "
                     "[--remaining | --auto | --stage NAME] "
                     "[--allow-cpu]")


if __name__ == "__main__":
    main()
