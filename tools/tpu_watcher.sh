#!/bin/bash
# Axon-tunnel watcher: the tunnel dies for hours and revives for
# ~tens-of-minutes windows (round-4 session: one 40-min window was the
# round's only on-chip access). Poll with a BOUNDED probe (a wedged
# tunnel hangs jax.devices() forever rather than erroring); the moment
# it answers, capture the remaining on-chip benchmark stages
# (tools/tpu_capture.py) one at a time, committing BENCH_TPU.jsonl
# after each so a mid-window death loses at most the in-flight stage.
#
# Usage: nohup tools/tpu_watcher.sh >/tmp/tpu_watcher_repo.log 2>&1 &
# Stateless: stage completion is read from the committed ledger.
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/tpu_watcher_repo.log

# single-instance guard (VERDICT r5 weak #4): a respawn after a
# presumed-dead watcher must not race the live one over the same
# stage list (double-append + double-commit of ledger lines). The
# lock is held on fd 9 for this process's whole lifetime; a second
# launch exits 0 immediately. Repo-local so per-checkout watchers
# stay independent.
LOCKFILE="$REPO/.tpu_watcher.lock"
exec 9>"$LOCKFILE"
if ! flock -n 9; then
  echo "$(date -u '+%F %T') another tpu_watcher holds $LOCKFILE; exiting" >>"$LOG"
  exit 0
fi
# the watcher exists ONLY for on-chip capture: a JAX_PLATFORMS=cpu
# inherited from the launching shell would make the probe see CPU
# devices and report the tunnel "ALIVE" forever (every stage then
# no-ops with rc=3, observed 2026-08-07) — strip it, and make the
# probe require an actual TPU device, not just an answer
unset JAX_PLATFORMS
PROBE_TIMEOUT=${PROBE_TIMEOUT:-150}
STAGE_TIMEOUT=${STAGE_TIMEOUT:-2400}
SLEEP_S=${SLEEP_S:-530}

say() { echo "$(date -u '+%F %T') $*" >>"$LOG"; }

# UTC heartbeat, one line per probe cycle (VERDICT r5 item 5): a
# session can verify the watcher is ALIVE — not just launched — by
# checking this file's last stamp is fresher than one SLEEP_S cycle.
HEARTBEAT="$REPO/.tpu_watcher_heartbeat"
CYCLE=0

while :; do
  CYCLE=$((CYCLE + 1))
  echo "$(date -u '+%FT%TZ') cycle=$CYCLE pid=$$" >"$HEARTBEAT"
  say "heartbeat: cycle $CYCLE"
  # bounded: --remaining only reads the ledger, but every python in
  # this env imports jax via sitecustomize — never trust it unbounded.
  # rc matters: a timeout/crash also yields empty stdout, which must
  # NOT read as "all captured" (that would exit the watcher during
  # exactly the dead-tunnel condition it exists to poll through)
  rem=$(cd "$REPO" && timeout 120 python tools/tpu_capture.py --remaining)
  rc=$?
  if [ "$rc" -ne 0 ]; then
    say "--remaining probe failed rc=$rc; retrying next cycle"
    sleep "$SLEEP_S"
    continue
  fi
  if [ -z "$rem" ]; then
    say "all stages captured; watcher exiting"
    exit 0
  fi
  if timeout "$PROBE_TIMEOUT" python -c \
      "import jax; assert any(d.platform == 'tpu' for d in jax.devices())" \
      >/dev/null 2>&1; then
    say "tunnel ALIVE; remaining stages: $rem"
    for st in $rem; do
      say "stage $st starting"
      ( cd "$REPO" && timeout "$STAGE_TIMEOUT" \
          python tools/tpu_capture.py --stage "$st" \
          >>/tmp/tpu_capture.out 2>>/tmp/tpu_capture.err )
      rc=$?
      say "stage $st rc=$rc"
      if ! git -C "$REPO" diff --quiet -- BENCH_TPU.jsonl 2>/dev/null \
          || [ -n "$(git -C "$REPO" status --porcelain BENCH_TPU.jsonl)" ]; then
        git -C "$REPO" add BENCH_TPU.jsonl
        git -C "$REPO" commit -q -m "On-chip bench capture: $st" \
          -- BENCH_TPU.jsonl && say "committed ledger after $st"
      fi
      # stage failed AND probe now dead -> window closed, back to poll
      if [ "$rc" -ne 0 ]; then
        if ! timeout "$PROBE_TIMEOUT" python -c \
            "import jax; assert any(d.platform == 'tpu' for d in jax.devices())" \
            >/dev/null 2>&1; then
          say "tunnel died mid-window"
          break
        fi
      fi
    done
  else
    say "tunnel dead"
  fi
  sleep "$SLEEP_S"
done
