#!/usr/bin/env python
"""bench_regress — perf-regression gate over the bench artifacts
(ISSUE 11 satellite).

ROADMAP item 5 ("re-take the on-chip record") is unenforceable
without a machine check over the numbers the bench drivers emit:
before this tool a silent 2x regression in the north-star step, the
serve speedup, or the posterior chain throughput survived until a
human diffed artifacts. This tool compares the LAST-JSON-line
artifacts of ``bench.py`` / ``bench_serve.py`` / ``bench_posterior.py``
against the committed ``BENCH_BASELINE.json`` tolerance bands:

- each baseline entry keys on the artifact's ``metric`` name and
  lists per-field checks: ``{"min": x}`` / ``{"max": x}`` hard
  bounds, or ``{"baseline": v, "rel_tol": 0.5, "direction":
  "higher"}`` relative bands (fail when the value falls outside
  ``baseline*(1 - rel_tol)`` for higher-is-better fields, or above
  ``baseline*(1 + rel_tol)`` for lower-is-better ones). Dotted field
  paths reach into nested blocks (``dispatch_overhead.
  pipelined_vs_sync``);
- entries carry ``only_backend`` (default "cpu"): an artifact from a
  different backend SKIPS rather than judging tunnel numbers against
  CPU-mesh bands — the on-chip record is tracked by BENCH_TPU.jsonl,
  not this gate;
- ``regress_block(rec)`` is the library half the drivers embed: each
  artifact now carries its own ``regress`` verdict block, so a
  regressed record is LABELED at the moment it is produced (the
  dispatch-supervisor "degradation is labeled" policy, applied to
  performance);
- the CLI compares artifact files (their last JSON line — the
  committed wire contract of every driver) or, with ``--run``,
  executes the three drivers in bounded subprocesses first. Exit 1
  on any FAIL — the opt-in lane in tools/check.sh
  ($PINT_TPU_BENCH_REGRESS=1).

Bands are deliberately generous (driver container load varies ~2x
run to run); the gate exists to catch ORDER-type regressions — a
lost jit cache, an accidentally-serial drain, a dead coalescing
path — not 10% noise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "BENCH_BASELINE.json")

# driver -> (argv tail, timeout_s) for --run; every subprocess is
# timeout-bounded (graftlint G6: a wedged tunnel hangs, never errors)
DRIVERS = {
    "bench.py": (["--north-star-only"], 1800),
    # the DEFAULT 64-request workload: the committed bands (speedup
    # baseline, occupancy floor) were measured from it, and the
    # artifact's metric name says 64req — a smaller run would judge
    # a different workload against them
    "bench_serve.py": (["--nreq", "64", "--repeats", "2"], 1800),
    "bench_posterior.py": ([], 1500),
}


def last_json_line(text: str) -> Optional[dict]:
    """The LAST parseable JSON object line — the artifact contract
    every bench driver prints. Falls back to parsing the whole text
    as one JSON document (the committed BENCH_rNN.json wrappers,
    whose ``parsed`` key holds the record)."""
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    try:
        obj = json.loads(text)
    except ValueError:
        return None
    if isinstance(obj, dict):
        inner = obj.get("parsed")
        return inner if isinstance(inner, dict) else obj
    return None


def _field(rec: dict, path: str):
    cur = rec
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _check_one(value, band: dict) -> (str, str):
    """(verdict, detail) for one field against one band."""
    if value is None or not isinstance(value, (int, float)):
        return "skip", "field missing or non-numeric"
    v = float(value)
    if "min" in band and v < float(band["min"]):
        return "fail", f"{v} < min {band['min']}"
    if "max" in band and v > float(band["max"]):
        return "fail", f"{v} > max {band['max']}"
    if "baseline" in band:
        base = float(band["baseline"])
        tol = float(band.get("rel_tol", 0.5))
        direction = band.get("direction", "higher")
        if direction == "higher":
            floor = base * (1.0 - tol)
            if v < floor:
                return ("fail", f"{v} < {floor:.4g} "
                                f"(baseline {base} -{tol:.0%})")
        else:
            ceil = base * (1.0 + tol)
            if v > ceil:
                return ("fail", f"{v} > {ceil:.4g} "
                                f"(baseline {base} +{tol:.0%})")
    return "pass", ""


def evaluate(rec: dict, baseline: dict) -> dict:
    """Verdict block for one artifact record against the baseline
    document. Never raises — an unevaluable record SKIPS with a
    reason (the regress block must not be able to fail a bench)."""
    metric = rec.get("metric")
    entry = (baseline.get("artifacts") or {}).get(metric)
    if entry is None:
        return {"verdict": "skip",
                "reason": f"no baseline entry for metric {metric!r}"}
    only = entry.get("only_backend", "cpu")
    if only and rec.get("backend") not in (None, only):
        return {"verdict": "skip",
                "reason": f"backend {rec.get('backend')!r} outside "
                          f"the {only!r} bands (on-chip numbers are "
                          f"tracked by BENCH_TPU.jsonl)"}
    checks = []
    verdict = "pass"
    for path, band in sorted(entry.get("fields", {}).items()):
        res, detail = _check_one(_field(rec, path), band)
        checks.append({"field": path, "verdict": res,
                       **({"detail": detail} if detail else {})})
        if res == "fail":
            verdict = "fail"
    return {"verdict": verdict, "baseline": os.path.basename(
        baseline.get("_path", DEFAULT_BASELINE)), "checks": checks}


def load_baseline(path: Optional[str] = None) -> dict:
    path = path or DEFAULT_BASELINE
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    doc["_path"] = path
    return doc


def regress_block(rec: dict, baseline_path: Optional[str] = None
                  ) -> dict:
    """The block every bench driver embeds in its artifact. Never
    raises."""
    try:
        return evaluate(rec, load_baseline(baseline_path))
    except Exception as e:
        return {"verdict": "skip", "reason": f"baseline unreadable: "
                                             f"{type(e).__name__}: {e}"}


def _run_driver(name: str) -> Optional[dict]:
    import subprocess

    argv_tail, timeout_s = DRIVERS[name]
    env = dict(os.environ)  # graftlint: allow G17 -- whole-env passthrough to the bench subprocess (forwards, never parses)
    env.setdefault("PINT_TPU_BENCH_FALLBACK", "1")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, name)] + argv_tail,
            capture_output=True, text=True, timeout=timeout_s,
            cwd=REPO, env=env)
    except Exception as e:
        print(f"[bench_regress] {name} did not produce an artifact:"
              f" {e!r}", file=sys.stderr)
        return None
    return last_json_line(r.stdout)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/bench_regress.py",
        description="compare bench artifacts (last JSON line) "
                    "against BENCH_BASELINE.json tolerance bands")
    ap.add_argument("artifacts", nargs="*",
                    help="artifact files (the last JSON line of "
                         "each is the record)")
    ap.add_argument("--baseline", default=None)
    ap.add_argument("--run", action="store_true",
                    help="run bench.py/bench_serve.py/"
                         "bench_posterior.py (bounded subprocesses) "
                         "and gate their fresh artifacts")
    ap.add_argument("--json", action="store_true",
                    help="one verdict JSON object per line")
    args = ap.parse_args(argv)

    try:
        baseline = load_baseline(args.baseline)
    except Exception as e:
        print(f"[bench_regress] cannot read baseline: {e!r}",
              file=sys.stderr)
        return 2
    records: List[dict] = []
    for path in args.artifacts:
        try:
            rec = last_json_line(open(path, encoding="utf-8").read())
        except OSError as e:
            print(f"[bench_regress] {path}: {e!r}", file=sys.stderr)
            return 2
        if rec is None:
            print(f"[bench_regress] {path}: no JSON artifact line",
                  file=sys.stderr)
            return 2
        rec["_source"] = path
        records.append(rec)
    if args.run:
        for name in DRIVERS:
            rec = _run_driver(name)
            if rec is not None:
                rec["_source"] = name
                records.append(rec)
    if not records:
        ap.error("no artifacts (pass files or --run)")
    failed = 0
    for rec in records:
        verdict = evaluate(rec, baseline)
        verdict["metric"] = rec.get("metric")
        verdict["source"] = rec.get("_source")
        if args.json:
            print(json.dumps(verdict))
        else:
            line = f"[{verdict['verdict'].upper():4}] " \
                   f"{rec.get('metric')} ({verdict['source']})"
            reasons = [f"{c['field']}: {c.get('detail', '')}"
                       for c in verdict.get("checks", [])
                       if c["verdict"] == "fail"]
            if verdict["verdict"] == "skip":
                reasons = [verdict.get("reason", "")]
            print(line + ("" if not reasons
                          else " — " + "; ".join(reasons)))
        if verdict["verdict"] == "fail":
            failed += 1
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
