"""Simulate fake TOAs from a model, perturb it, and recover the truth
(reference: the PINT "Simulate and fit"/zima workflow — this is also
the framework's strongest self-oracle, SURVEY.md §4).

Usage: python examples/simulate_and_fit.py
"""
import io
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

import numpy as np                                # noqa: E402

from pint_tpu.fitter import Fitter                # noqa: E402
from pint_tpu.models import get_model             # noqa: E402
from pint_tpu.simulation import make_fake_toas_uniform  # noqa: E402

PAR = """
PSR J1855+0943
RAJ 18:57:36.39 1
DECJ 09:43:17.2 1
F0 186.49408156698235 1
F1 -6.2049e-16 1
DM 13.29
PEPOCH 54500
POSEPOCH 54500
TZRMJD 54500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY ELL1
PB 12.32717 1
A1 9.2307805 1
TASC 54500.03 1
EPS1 -2.15e-5 1
EPS2 -3.1e-7 1
"""


def main():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        truth = get_model(io.StringIO(PAR))
        toas = make_fake_toas_uniform(
            53500, 55500, 500, truth, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(42))

    true_vals = {n: truth.get_param(n).value for n in truth.free_params}

    # perturb the model away from the truth, then fit back
    model = truth
    model.F0.value += 2e-9
    model.F1.value *= 1.02
    model.EPS1.value += 3e-6

    fit = Fitter.auto(toas, model)
    fit.fit_toas()

    print(f"{'param':8s} {'fit - truth':>14s} {'sigma':>11s} {'pull':>7s}")
    ok = True
    for n in model.free_params:
        d = model.get_param(n).value - true_vals[n]
        s = fit.errors.get(n, float("nan"))
        pull = d / s if s else float("nan")
        ok &= abs(pull) < 5
        print(f"{n:8s} {d:14.3e} {s:11.3e} {pull:7.2f}")
    print(f"\nchi2/dof = {fit.stats.reduced_chi2:.3f}; "
          f"all within 5 sigma: {ok}")


if __name__ == "__main__":
    main()
