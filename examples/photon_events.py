"""Photon-event workflow: simulate event phases, H-test significance,
template fit (reference: the PINT photonphase/event_optimize
examples, compressed to shipped-data scale).

Usage: python examples/photon_events.py
"""
import io
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

import numpy as np                                # noqa: E402

from pint_tpu.eventstats import h_sig, hmw        # noqa: E402
from pint_tpu.templates import (                  # noqa: E402
    LCFitter,
    LCGaussian,
    LCTemplate,
)

def main():
    rng = np.random.default_rng(3)
    # truth: two Gaussian peaks (state lives in the template's flat
    # theta: norms / peak locations / widths per primitive)
    truth = LCTemplate([LCGaussian(), LCGaussian()],
                       norms=[0.35, 0.35], locs=[0.2, 0.55],
                       widths=[[0.03], [0.08]])
    n = 4000
    phases = truth.random(n, rng=rng)
    weights = np.clip(rng.beta(3, 1.2, n), 0.05, 1.0)

    h = hmw(phases, weights)
    # h_sig works in log space — huge H must not underflow to inf
    print(f"weighted H-test: H = {h:.1f} ({h_sig(h):.1f} sigma)")

    # fit a fresh template to the simulated photons
    guess = LCTemplate([LCGaussian(), LCGaussian()],
                       norms=[0.3, 0.3], locs=[0.25, 0.5],
                       widths=[[0.05], [0.05]])
    fitter = LCFitter(guess, phases, weights=weights)
    fitter.fit()
    peaks = sorted(np.mod(guess.locs, 1.0))
    print(f"recovered peaks at {peaks[0]:.3f}, {peaks[1]:.3f} "
          f"(truth 0.200, 0.550)")


if __name__ == "__main__":
    main()
