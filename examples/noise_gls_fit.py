"""GLS fitting with correlated noise: EFAC/EQUAD/ECORR + power-law
red noise, epoch-averaged residuals, and the ML noise realization
(reference: the PINT "understanding fitters"/B1855 GLS examples).

Usage: python examples/noise_gls_fit.py
"""
import io
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

import numpy as np                                # noqa: E402

from pint_tpu.gls import DownhillGLSFitter        # noqa: E402
from pint_tpu.models import get_model             # noqa: E402
from pint_tpu.residuals import Residuals          # noqa: E402
from pint_tpu.simulation import make_fake_toas_fromMJDs  # noqa: E402

PAR = """
PSR J0034-0534
RAJ 00:34:21.83 1
DECJ -05:34:36.7 1
F0 532.7134 1
F1 -1.4e-15 1
DM 13.76
PEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
EFAC -be GUPPI 1.1
EQUAD -be GUPPI 0.3
ECORR -be GUPPI 0.8
TNREDAMP -13.8
TNREDGAM 3.7
TNREDC 20
"""


def main():
    rng = np.random.default_rng(7)
    # clustered epochs so ECORR's per-epoch blocks have structure
    centers = np.linspace(53001.0, 55999.0, 250)
    mjds = (centers[:, None] + np.linspace(0, 0.02, 4)[None, :]).ravel()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        # flags go in at creation: the EFAC/EQUAD/ECORR noise models
        # select on -be, so the simulated draw must see them too
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], len(mjds) // 2),
            add_noise=True, add_correlated_noise=True, rng=rng,
            flags={"be": "GUPPI"})

    model.F0.value += 1e-9
    fit = DownhillGLSFitter(toas, model)
    fit.fit_toas()
    print(f"chi2/dof = {fit.stats.reduced_chi2:.3f} in "
          f"{fit.stats.iterations} iterations")

    res = Residuals(toas, fit.model)
    print(f"whitened RMS {res.rms_weighted() * 1e6:.2f} us")
    noise = fit.get_noise_resids()
    print(f"ML red-noise realization spans "
          f"{(noise.max() - noise.min()) * 1e6:.2f} us")

    epoch = res.ecorr_average()
    print(f"epoch-averaged residuals: {len(epoch['mjds'])} epochs, "
          f"RMS {np.std(epoch['time_resids']) * 1e6:.2f} us")


if __name__ == "__main__":
    main()
