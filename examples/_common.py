"""Shared example preamble: backend pinning + repo-root sys.path.

Import this FIRST in every example, before any other pint_tpu or jax
device use:

    import _common  # noqa: F401  (examples/ on sys.path when run
                    # as `python examples/foo.py`)

Examples default to the CPU backend, pinned BEFORE first device use —
the axon sitecustomize pre-imports jax, so env vars alone are too
late, and an unreachable accelerator tunnel HANGS rather than errors
(CLAUDE.md). Pass --tpu (or set PINT_TPU_EXAMPLES_ACCEL=1) to run on
the default accelerator backend instead; the fit step then uses the
TPU production configuration automatically.
"""
import os
import sys

import jax

if "--tpu" in sys.argv:
    sys.argv.remove("--tpu")
elif os.environ.get("PINT_TPU_EXAMPLES_ACCEL", "").lower() in \
        ("", "0", "off", "false"):  # 0/off = disabled, matching the
    # PINT_TPU_JIT_CACHE / PINT_TPU_TEST_JIT_CACHE convention
    jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

DATADIR = os.path.join(REPO_ROOT, "tests", "datafile")
