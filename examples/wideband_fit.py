"""Wideband fitting: TOAs carrying their own DM measurements
(-pp_dm/-pp_dme flags) fitted as one stacked [time; DM] system
(reference: the PINT wideband/J1713 workflow).

Usage: python examples/wideband_fit.py
"""
import io
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

import numpy as np                                # noqa: E402

from pint_tpu.models import get_model             # noqa: E402
from pint_tpu.simulation import make_fake_toas_fromMJDs  # noqa: E402
from pint_tpu.wideband_fitter import WidebandDownhillFitter  # noqa: E402

PAR = """
PSR J1713+0747
RAJ 17:13:49.53 1
DECJ 07:47:37.5 1
F0 218.8118437960826 1
F1 -4.08e-16 1
DM 15.99 1
DM1 1e-5 1
PEPOCH 54500
POSEPOCH 54500
DMEPOCH 54500
TZRMJD 54500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY ELL1
PB 67.8251 1
A1 32.34242 1
TASC 54500.2 1
EPS1 3.9e-5 1
EPS2 -7.4e-5 1
DMEFAC -fe wide 1.1
"""


def main():
    rng = np.random.default_rng(17)
    n = 600
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        mjds = np.sort(rng.uniform(53000, 56000, n))
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=0.5,
            freq_mhz=np.tile([1400.0, 2100.0], n // 2),
            add_noise=True, rng=rng, flags={"fe": "wide"})
        # attach the wideband DM channel: each TOA measures DM too
        dm_truth = 15.99 + 1e-5 * (mjds - 54500.0) / 365.25
        for f, dm in zip(toas.flags, dm_truth):
            f["pp_dm"] = repr(float(dm + rng.normal(0.0, 2e-4)))
            f["pp_dme"] = "2e-4"
        toas._touch()  # flags changed in place: bump the cache serial

    model.F0.value += 5e-11
    model.DM.value += 3e-4

    fit = WidebandDownhillFitter(toas, model)
    fit.fit_toas()
    print(f"wideband fit: chi2/dof = {fit.stats.reduced_chi2:.3f} "
          f"over {2 * n} stacked TOA+DM measurements, "
          f"{fit.stats.iterations} iterations")
    print(f"DM  = {model.DM.value:.6f} +- {fit.errors['DM']:.6f} "
          f"(truth 15.990000)")
    print(f"time RMS {np.std(fit.resids.time_resids) * 1e6:.2f} us; "
          f"DM-channel chi2 {fit.chi2_dm:.1f}")


if __name__ == "__main__":
    main()
