"""Batch-fit a small pulsar-timing array: every pulsar's GLS solve in
ONE vmapped device call per iteration (the TPU-first replacement for
per-pulsar process pools; reference workflow: fitting a PTA's pulsars
independently).

Usage: python examples/pta_batch.py [npulsars]
"""
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

import io                                         # noqa: E402

import numpy as np                                # noqa: E402

from pint_tpu.models import get_model             # noqa: E402
from pint_tpu.parallel import fit_pta             # noqa: E402
from pint_tpu.simulation import make_fake_toas_uniform  # noqa: E402


def build_pulsar(k, rng):
    f0 = 150.0 + 37.0 * (k % 11)
    par = f"""
PSR J{1000 + 7 * k:04d}+{k:02d}42
RAJ {(k * 37) % 24:02d}:12:33.4 1
DECJ {(k * 11) % 60:02d}:07:02.5 1
F0 {f0!r} 1
F1 {-(1 + k % 5) * 1e-16!r} 1
DM {5.0 + 0.7 * k:.2f}
PEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""
    model = get_model(io.StringIO(par))
    toas = make_fake_toas_uniform(
        54000, 56000, 80, model, error_us=1.0, add_noise=True,
        rng=rng)
    truth = {"F0": model.F0.value, "F1": model.F1.value}
    model.F0.value += 3e-10  # perturb before the batch fit
    return model, toas, truth


def main():
    n_psr = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    rng = np.random.default_rng(1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pulsars = [build_pulsar(k, rng) for k in range(n_psr)]
        results = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=2)
    stats = fit_pta.last_stats
    n_ok = sum(
        1 for (m, t, truth), r in zip(pulsars, results)
        if abs(m.F0.value - truth["F0"]) < 5 * r["errors"]["F0"])
    print(f"{n_psr} pulsars, {stats['ntoa_total']} TOAs: device solve "
          f"{stats['device_solve_s'] * 1e3:.0f} ms, "
          f"{stats['toas_per_sec']:.0f} TOA/s")
    print(f"F0 recovered within 5 sigma: {n_ok}/{n_psr}")


if __name__ == "__main__":
    main()
