"""Fit the NGC6440E fixture end-to-end (reference: the PINT
"Fit NGC6440E" example): load par+tim, fit, print the summary table
and post-fit statistics.

Usage: python examples/fit_ngc6440e.py [par tim]
"""
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _common  # noqa: F401,E402  (backend pin + repo path)

from pint_tpu import get_model_and_toas          # noqa: E402
from pint_tpu.fitter import Fitter               # noqa: E402
from pint_tpu.residuals import Residuals         # noqa: E402


def main():
    if len(sys.argv) == 2:
        sys.exit("need BOTH a par and a tim file (or neither for the "
                 "shipped NGC6440E fixture)")
    if len(sys.argv) > 2:
        par, tim = sys.argv[1], sys.argv[2]
    else:
        par = os.path.join(_common.DATADIR, "NGC6440E.par")
        tim = os.path.join(_common.DATADIR, "NGC6440E.tim")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model, toas = get_model_and_toas(par, tim)

    pre = Residuals(toas, model)
    print(f"{toas.ntoas} TOAs, prefit RMS "
          f"{pre.rms_weighted() * 1e6:.2f} us")

    fit = Fitter.auto(toas, model)
    fit.fit_toas()
    fit.print_summary()
    print(f"\npostfit chi2/dof = {fit.stats.reduced_chi2:.3f}, "
          f"wall {fit.stats.wall_time_s * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
