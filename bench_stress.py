"""Realistic-scale stress problem (VERDICT r4 item 7): a
NANOGrav-like single pulsar — 10k TOAs over 12 yr, ~100 free DMX
windows, 5 receivers each carrying its own EFAC/EQUAD/ECORR, per-
receiver JUMPs and FDJUMPs, ELL1 binary, achromatic red noise + DM
noise — fit end-to-end with the production downhill configuration.
This exercises maskParameter scaling and compile-key behavior at
real-PTA free-parameter counts (~124 free / 125 design columns),
which the 40-parameter north-star shape never does. Reference fixture analog: the NANOGrav
9/12.5-yr per-pulsar par/tim pairs (SURVEY §4.1).

Run: python bench_stress.py  (prints one JSON line; shares bench.py's
hang-proof probe/fallback protocol). The slow-marked test
tests/test_stress_fixture.py runs the same build at reduced size.
"""

from __future__ import annotations

import io
import json
import sys
import time
import warnings

RECEIVERS = ("rcvr800", "rcvr1400", "rcvr2100", "guppi", "puppi")


def build_stress_problem(ntoa=10_000, ndmx=100, seed=7,
                         span=(53000.0, 57383.0), dm_noise=True):
    """(model, toas, truth): simulated NANOGrav-like dataset with
    injected noise drawn from the model's own covariance.

    ``dm_noise=False`` drops the PLDMNoise term — required for the
    wideband variant: attach_wideband_dm generates DM measurements
    from the DETERMINISTIC model DM, so a DM-noise realization
    injected into the arrival times would contradict the DM channel
    (the times say DM wiggles, the channel says it doesn't) and
    inflate chi2 by construction."""
    import numpy as np

    from bench import _clustered_mjds
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    span0, span1 = span
    par = [
        "PSR J1600-3053x",
        "RAJ 16:00:51.90 1", "DECJ -30:53:49.3 1",
        "PMRA -0.95 1", "PMDEC -6.9 1", "PX 0.5 1",
        "F0 277.9377112429746 1", "F1 -7.3387e-16 1",
        "DM 52.33", "DM1 0", "DM2 0",
        "PEPOCH 55000", "POSEPOCH 55000", "DMEPOCH 55000",
        "TZRMJD 55000.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "BINARY ELL1", "PB 14.348466 1", "A1 8.8016531 1",
        "TASC 55000.2 1", "EPS1 2.0e-4 1", "EPS2 -1.7e-4 1",
        "M2 0.27 1", "SINI 0.87 1",
    ]
    # per-receiver white noise (maskParameter families); the DM-side
    # scalings only engage in wideband mode (attach_wideband_dm)
    for i, r in enumerate(RECEIVERS):
        par.append(f"EFAC -be {r} {1.0 + 0.05 * i}")
        par.append(f"EQUAD -be {r} {0.1 + 0.05 * i}")
        par.append(f"ECORR -be {r} {0.4 + 0.1 * i}")
    par.append("DMEFAC -be rcvr1400 1.1")
    par.append("DMEQUAD -be guppi 1e-4")
    # per-receiver JUMP (first receiver is the un-jumped reference)
    for r in RECEIVERS[1:]:
        par.append(f"JUMP -be {r} 1e-6 1")
    # per-receiver FDJUMP order 1+2 on two receivers (profile
    # evolution per backend)
    for r in RECEIVERS[3:]:
        par.append(f"FDJUMP -be {r} 1e-6 1")
        par.append(f"FD2JUMP -be {r} 5e-7 1")
    # global FD
    par.append("FD1 1e-5 1")
    par.append("FD2 -4e-6 1")
    # red + DM noise
    par.append("TNREDAMP -14.2")
    par.append("TNREDGAM 3.8")
    par.append("TNREDC 30")
    if dm_noise:
        par.append("TNDMAMP -13.6")
        par.append("TNDMGAM 2.9")
        par.append("TNDMC 30")
    # ~ndmx free DMX windows tiling the span
    import numpy as _np

    edges = _np.linspace(span0, span1, ndmx + 1)
    for i in range(ndmx):
        par.append(f"DMX_{i + 1:04d} 0.0 1")
        par.append(f"DMXR1_{i + 1:04d} {edges[i]:.4f}")
        par.append(f"DMXR2_{i + 1:04d} {edges[i + 1]:.4f}")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par) + "\n"))
        rng = np.random.default_rng(seed)
        mjds = _clustered_mjds(span0, span1, ntoa)
        # 4 distinct sub-bands per receiver epoch cluster with
        # per-TOA channel jitter — REQUIRED, not decoration: with
        # only two distinct frequencies {offset, FD1, FD2} (and each
        # receiver's {JUMP, FDJUMP, FD2JUMP}) span a two-point space,
        # making the normal matrix exactly singular and the
        # Cholesky-only device step garbage-prone. Clustered epochs
        # so the per-receiver ECORR quantization has real structure;
        # flags passed INTO the simulation so the flag-selected
        # noise models shape the injected draw
        freqs = (np.tile([430.0, 820.0, 1400.0, 2100.0], ntoa // 4)
                 * (1.0 + rng.uniform(-0.06, 0.06, ntoa)))
        flags = [{"be": RECEIVERS[(i // 4) % len(RECEIVERS)]}
                 for i in range(ntoa)]
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=0.3, freq_mhz=freqs,
            add_noise=True, add_correlated_noise=True, rng=rng,
            flags=flags)
    truth = {"F0": model.F0.value, "PB": model.PB.value}
    # perturb so the fit has real work to do
    model.F0.add_delta(3e-11)
    model.get_param("JUMP1").value += 2e-7
    model.invalidate_cache(params_only=True)
    return model, toas, truth


def attach_wideband_dm(model, toas, rng=None):
    """Attach per-TOA wideband DM measurements (-pp_dm/-pp_dme flags)
    consistent with the model's own DM at each TOA, plus white
    measurement noise — turning the stress problem into a wideband
    joint [time; DM] fit (reference: the NANOGrav wideband data
    convention)."""
    import numpy as np

    rng = rng or np.random.default_rng(17)
    dm = model.total_dm(toas)
    # quoted per-TOA DM sigma is 2e-4; the injected draw must come
    # from the MODEL's DM-channel covariance, i.e. the
    # DMEFAC/DMEQUAD-scaled sigma (self-consistency contract of this
    # fixture) — set the flags first so scaled_dm_uncertainty sees
    # the quoted values, then perturb by the scaled draw
    for f in toas.flags:
        f["pp_dme"] = "2e-4"
        f["pp_dm"] = "0"  # placeholder until the draw below
    sig = np.asarray(model.scaled_dm_uncertainty(toas), np.float64)
    for i, f in enumerate(toas.flags):
        # repr(float(...)): numpy-2 scalar repr is "np.float64(x)",
        # which the flag consumers can't parse back
        f["pp_dm"] = repr(float(dm[i] + rng.normal(0.0, sig[i])))


def main():
    import os

    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        from bench import accelerator_responsive, cpu_fallback_env

        if not accelerator_responsive():
            print("accelerator unresponsive; re-running on CPU",
                  file=sys.stderr)
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    import jax

    jax.config.update("jax_enable_x64", True)
    from pint_tpu.config import enable_compile_cache

    enable_compile_cache(
        "PINT_TPU_BENCH_JIT_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))

    wideband = "--wideband" in sys.argv

    t0 = time.perf_counter()
    model, toas, truth = build_stress_problem(dm_noise=not wideband)
    if wideband:
        attach_wideband_dm(model, toas)
    build_s = time.perf_counter() - t0
    nfree = len(model.free_params)
    print(f"built: {toas.ntoas} TOAs, {nfree} free params "
          f"wideband={wideband} ({build_s:.0f}s)", file=sys.stderr)

    from pint_tpu.gls import DeviceDownhillGLSFitter

    # warm-up fit on a structurally identical model so the timed run
    # measures the fit, not the one-time XLA compile (the compile key
    # covers structure only; a rebuilt model reuses it)
    import io as _io

    from pint_tpu.models import get_model as _gm

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        warm_model = _gm(_io.StringIO(model.as_parfile()))
    DeviceDownhillGLSFitter(toas, warm_model,
                            wideband=wideband).fit_toas(maxiter=12)
    print("warm-up fit done", file=sys.stderr)

    t0 = time.perf_counter()
    fit = DeviceDownhillGLSFitter(toas, model, wideband=wideband)
    chi2 = fit.fit_toas(maxiter=12)
    wall = time.perf_counter() - t0
    dof = fit.stats.dof
    ok = abs(model.F0.value - truth["F0"]) < \
        5 * float(model.F0.uncertainty)
    rec = {"metric": "stress_nanograv_like_10k_fit"
                     + ("_wideband" if wideband else ""),
           "value": round(toas.ntoas * fit.stats.iterations / wall, 1),
           "unit": "TOA/s", "ntoa": toas.ntoas, "nfree": nfree,
           "fit_wall_s": round(wall, 2),
           "iterations": fit.stats.iterations,
           "chi2_dof": round(chi2 / dof, 4),
           "f0_recovered_5sigma": bool(ok),
           "backend": jax.default_backend()}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
