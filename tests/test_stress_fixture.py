"""NANOGrav-like realistic-scale stress fixture (VERDICT r4 item 7):
the bench_stress builder at reduced size as a suite-runnable test,
plus the full 10k/100-DMX production fit as a slow-marked test.
Exercises maskParameter scaling (5 receivers x EFAC/EQUAD/ECORR +
JUMPs + FDJUMPs + ~NDMX DMX windows) and compile-key behavior at
free-parameter counts nothing else in the suite reaches.
"""

import sys

import numpy as np
import pytest

sys.path.insert(0, str(__import__("pathlib").Path(__file__).parents[1]))

from bench_stress import RECEIVERS, build_stress_problem  # noqa: E402


class TestReducedStress:
    @pytest.fixture(scope="class")
    def problem(self):
        return build_stress_problem(ntoa=1600, ndmx=30, seed=11)

    def test_structure(self, problem):
        model, toas, truth = problem
        nfree = len(model.free_params)
        assert toas.ntoas == 1600
        # 30 DMX + 13 astro/spin/binary + 4 JUMP + 2 FD + 4 FDJUMP
        assert nfree >= 30 + 13 + 4 + 2 + 4
        # every receiver's maskParameters selected a nonempty subset
        import collections

        cnt = collections.Counter(f["be"] for f in toas.flags)
        assert set(cnt) == set(RECEIVERS)
        assert min(cnt.values()) > 100

    def test_production_downhill_fit_recovers(self, problem):
        from pint_tpu.gls import DeviceDownhillGLSFitter

        model, toas, truth = problem
        fit = DeviceDownhillGLSFitter(toas, model)
        chi2 = fit.fit_toas(maxiter=12)
        dof = toas.ntoas - len(model.free_params) - 1
        assert np.isfinite(chi2)
        assert 0.7 < chi2 / dof < 1.3
        assert abs(model.F0.value - truth["F0"]) < \
            5 * float(model.F0.uncertainty)
        # scaled uncertainties per receiver actually differ (EFAC
        # family engaged)
        sig = model.scaled_toa_uncertainty(toas)
        by = {}
        for s, f in zip(np.asarray(sig), toas.flags):
            by.setdefault(f["be"], []).append(s)
        means = sorted(float(np.mean(v)) for v in by.values())
        assert means[-1] > means[0] * 1.1


@pytest.mark.slow
def test_full_stress_fit_10k():
    """The full 10k-TOA / ~100-DMX / ~124-free-parameter production
    fit end-to-end (also available standalone: python bench_stress.py
    emits its TOA/s JSON line)."""
    from pint_tpu.gls import DeviceDownhillGLSFitter

    model, toas, truth = build_stress_problem()
    nfree = len(model.free_params)
    assert nfree >= 120
    fit = DeviceDownhillGLSFitter(toas, model)
    chi2 = fit.fit_toas(maxiter=12)
    dof = toas.ntoas - nfree - 1
    assert 0.8 < chi2 / dof < 1.2
    assert abs(model.F0.value - truth["F0"]) < \
        5 * float(model.F0.uncertainty)


def test_reduced_stress_wideband_fit():
    """The stress problem as a wideband joint [time; DM] fit (flags
    attached by attach_wideband_dm, self-consistent with the model's
    own DM): the production device wideband path converges with sane
    chi2 over the stacked dof."""
    from bench_stress import attach_wideband_dm

    from pint_tpu.gls import DeviceDownhillGLSFitter

    model, toas, truth = build_stress_problem(ntoa=1600, ndmx=30,
                                              seed=12, dm_noise=False)
    attach_wideband_dm(model, toas)
    fit = DeviceDownhillGLSFitter(toas, model, wideband=True)
    chi2 = fit.fit_toas(maxiter=12)
    dof = fit.stats.dof
    assert dof == 2 * toas.ntoas - len(model.free_params) - 1
    assert 0.8 < chi2 / dof < 1.2
    assert abs(model.F0.value - truth["F0"]) < \
        5 * float(model.F0.uncertainty)
