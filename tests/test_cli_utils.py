"""CLIs end-to-end, TCB->TDB conversion, derived quantities, analysis
utils, model transforms (reference: src/pint/scripts/ + utils.py +
derived_quantities.py + modelutils.py; test strategy SURVEY.md §4.6)."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_uniform

PAR = """
PSR J0012+0012
RAJ 03:30:00.0 1
DECJ 22:00:00.0 1
F0 312.0 1
F1 -4e-15 1
PEPOCH 55500
POSEPOCH 55500
DM 21.0 1
DMEPOCH 55500
TZRMJD 55500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def _write_fixture(tmp_path, seed=0):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        rng = np.random.default_rng(seed)
        from pint_tpu.toa import merge_TOAs

        tA = make_fake_toas_uniform(55000, 56000, 40, model,
                                    error_us=1.0, freq_mhz=1400.0,
                                    add_noise=True, rng=rng)
        tB = make_fake_toas_uniform(55001, 55999, 40, model,
                                    error_us=1.0, freq_mhz=820.0,
                                    add_noise=True, rng=rng)
        toas = merge_TOAs([tA, tB])
    par = tmp_path / "fix.par"
    tim = tmp_path / "fix.tim"
    par.write_text(model.as_parfile())
    toas.write_TOA_file(tim)
    return model, toas, par, tim


# ----------------------------------------------------------- pintempo


def test_pintempo_end_to_end(tmp_path, capsys):
    from pint_tpu.scripts.pintempo import main

    model, toas, par, tim = _write_fixture(tmp_path)
    out = tmp_path / "post.par"
    rc = main([str(par), str(tim), "--outfile", str(out),
               "--fitter", "wls", "--maxiter", "2"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "Postfit" in text or "post" in text.lower() or \
        "chi2" in text
    m2 = get_model(str(out))
    assert m2.F0.value == pytest.approx(model.F0.value, abs=1e-9)


# --------------------------------------------------------------- zima


def test_zima_roundtrip(tmp_path, capsys):
    from pint_tpu.scripts.zima import main

    model, toas, par, tim = _write_fixture(tmp_path)
    sim = tmp_path / "sim.tim"
    rc = main([str(par), str(sim), "--ntoa", "25", "--startMJD",
               "55100", "--duration", "300", "--addnoise",
               "--seed", "7"])
    assert rc == 0
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    t2 = get_TOAs(str(sim), model=model)
    assert t2.ntoas == 25
    r = Residuals(t2, model)
    # simulated with 1 us noise: residual rms should be of that order
    assert 0.2e-6 < r.rms_weighted() < 5e-6


# ------------------------------------------------------------ pintbary


def test_pintbary(capsys):
    from pint_tpu.scripts.pintbary import main

    rc = main(["56000.0", "--obs", "gbt", "--ra", "03:30:00.0",
               "--dec", "22:00:00.0"])
    assert rc == 0
    out = capsys.readouterr().out
    line = out.strip().splitlines()[-1]
    bat = float(line.split("->")[1])
    # TDB-UTC ~ 69 s plus Roemer +-500 s: within 0.01 d of input
    assert abs(bat - 56000.0) < 0.01


# ----------------------------------------------------------- TCB<->TDB


def test_tcb_conversion_roundtrip():
    from pint_tpu.models.tcb_conversion import (
        IFTE_K,
        T0_MJD,
        convert_tcb_tdb,
    )

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR))
    m_tcb = convert_tcb_tdb(m, backwards=True)
    assert m_tcb.UNITS.value == "TCB"
    # frequency scales DOWN going to TCB (TCB seconds are shorter)
    assert m_tcb.F0.value < m.F0.value
    assert m_tcb.F0.value == pytest.approx(m.F0.value / IFTE_K,
                                           rel=1e-15)
    assert m_tcb.DM.value > m.DM.value
    # epoch maps through the fixed point
    assert m_tcb.PEPOCH.value == pytest.approx(
        T0_MJD + (m.PEPOCH.value - T0_MJD) * IFTE_K, abs=1e-8)
    back = convert_tcb_tdb(m_tcb)
    assert back.F0.value == pytest.approx(m.F0.value, rel=1e-15)
    assert back.PEPOCH.value == pytest.approx(m.PEPOCH.value, abs=1e-9)


def test_get_model_converts_tcb(tmp_path):
    par_tcb = PAR.replace("UNITS TDB", "UNITS TCB")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = get_model(io.StringIO(par_tcb))
    assert m.UNITS.value == "TDB"
    assert any("TCB" in str(x.message) for x in w)
    # refusal path still available
    with pytest.raises(ValueError):
        get_model(io.StringIO(par_tcb), allow_tcb=False)


def test_tcb2tdb_cli(tmp_path):
    from pint_tpu.scripts.tcb2tdb import main

    src = tmp_path / "in.par"
    dst = tmp_path / "out.par"
    src.write_text(PAR.replace("UNITS TDB", "UNITS TCB"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main([str(src), str(dst)])
    assert rc == 0
    m = get_model(str(dst))
    assert m.UNITS.value == "TDB"


# ---------------------------------------------------- compare_parfiles


def test_compare_parfiles_cli(tmp_path, capsys):
    from pint_tpu.scripts.compare_parfiles import main

    p1 = tmp_path / "a.par"
    p2 = tmp_path / "b.par"
    p1.write_text(PAR)
    p2.write_text(PAR.replace("F0 312.0", "F0 312.00001"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main([str(p1), str(p2)])
    assert rc == 0
    assert "F0" in capsys.readouterr().out


# --------------------------------------------------- derived quantities


def test_derived_quantities_closed_form():
    import pint_tpu.derived_quantities as dq

    # PSR B1913+16-like: Pb=0.3230 d, x=2.3418 lt-s
    f = dq.mass_funct(0.322997, 2.3418)
    assert f == pytest.approx(0.1322, rel=1e-3)
    # m_c from known masses/inclination solves the cubic consistently
    mc = dq.companion_mass(0.322997, 2.3418, i_deg=47.2, mp=1.441)
    f2 = dq.mass_funct2(1.441, mc, 47.2)
    assert f2 == pytest.approx(f, rel=1e-9)
    # GR omdot for B1913+16: 4.226595 deg/yr at masses 1.4398+1.3886
    w = dq.omdot(1.4398, 1.3886, 0.322997448918, 0.6171334)
    assert w == pytest.approx(4.2266, rel=2e-3)
    # GR pbdot for B1913+16 ~= -2.40263e-12
    pb = dq.pbdot(1.4398, 1.3886, 0.322997448918, 0.6171334)
    assert pb == pytest.approx(-2.40263e-12, rel=2e-3)
    # gamma for B1913+16 ~= 4.307 ms
    g = dq.gamma(1.4398, 1.3886, 0.322997448918, 0.6171334)
    assert g == pytest.approx(4.307e-3, rel=2e-3)
    # spin quantities: Crab-like F0=30 Hz, F1=-3.86e-10
    age = dq.pulsar_age(29.946923, -3.77535e-10)
    assert age == pytest.approx(1254, rel=0.01)  # years
    b = dq.pulsar_B(29.946923, -3.77535e-10)
    assert b == pytest.approx(3.8e12, rel=0.05)
    edot = dq.pulsar_edot(29.946923, -3.77535e-10)
    assert edot == pytest.approx(4.46e31, rel=0.05)
    # shklovskii: mu=10 mas/yr at 1 kpc
    a = dq.shklovskii_factor(10.0, 1.0)
    assert a == pytest.approx(2.43e-19, rel=0.01)


def test_ftest_and_weighted_mean():
    from pint_tpu.utils import FTest, weighted_mean

    # large chi2 drop for 1 dof -> tiny probability
    assert FTest(200.0, 100, 120.0, 99) < 1e-8
    # no improvement -> 1.0
    assert FTest(100.0, 100, 100.0, 99) == 1.0
    m, e = weighted_mean([1.0, 3.0], [1.0, 1.0])
    assert m == pytest.approx(2.0)
    assert e == pytest.approx(1.0 / np.sqrt(2.0))
    m2, _ = weighted_mean([1.0, 3.0], [1.0, 1e6])
    assert m2 == pytest.approx(1.0, abs=1e-6)


def test_dmxparse(tmp_path):
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.utils import dmxparse

    par = PAR.replace("DM 21.0 1", "DM 21.0") + (
        "DMX_0001 0.0 1\nDMXR1_0001 55000\nDMXR2_0001 55500\n"
        "DMX_0002 0.0 1\nDMXR1_0002 55500.5\nDMXR2_0002 56000\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(2)
        from pint_tpu.toa import merge_TOAs

        toas = merge_TOAs([
            make_fake_toas_uniform(55000, 56000, 40, model,
                                   error_us=1.0, freq_mhz=1400.0,
                                   add_noise=True, rng=rng),
            make_fake_toas_uniform(55001, 55999, 40, model,
                                   error_us=1.0, freq_mhz=820.0,
                                   add_noise=True, rng=rng)])
        f = WLSFitter(toas, model)
        f.fit_toas(maxiter=2)
    d = dmxparse(f)
    assert d["dmxs"].shape == (2,)
    assert np.all(d["dmx_verrs"] > 0)
    assert d["dmxeps"][0] == pytest.approx(55250.0)
    assert d["bins"] == ["0001", "0002"]


def test_model_ecliptic_equatorial_roundtrip():
    from pint_tpu.modelutils import (
        model_ecliptic_to_equatorial,
        model_equatorial_to_ecliptic,
    )
    from pint_tpu.residuals import Residuals

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR.replace(
            "RAJ 03:30:00.0 1", "RAJ 03:30:00.0 1\nPMRA 11.0 1"
        ).replace("DECJ 22:00:00.0 1",
                  "DECJ 22:00:00.0 1\nPMDEC -7.0 1")))
        toas = make_fake_toas_uniform(55000, 56000, 30, m,
                                      error_us=1.0)
    mec = model_equatorial_to_ecliptic(m)
    assert "AstrometryEcliptic" in mec.components
    r1 = np.asarray(Residuals(toas, m).time_resids)
    r2 = np.asarray(Residuals(toas, mec).time_resids)
    # same sky position: residuals agree to sub-ns
    np.testing.assert_allclose(r1, r2, atol=2e-9)
    back = model_ecliptic_to_equatorial(mec)
    assert back.get_param("RAJ").value == pytest.approx(
        m.get_param("RAJ").value, abs=1e-12)
    assert back.get_param("PMRA").value == pytest.approx(11.0,
                                                        rel=1e-9)
    assert back.get_param("PMDEC").value == pytest.approx(-7.0,
                                                          rel=1e-9)


def test_as_ecl_as_icrs_methods():
    """TimingModel.as_ECL/as_ICRS (reference method names) delegate to
    the modelutils conversions, honor the ECL convention argument, and
    return self when already in the target frame."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR.replace(
            "RAJ 03:30:00.0 1", "RAJ 03:30:00.0 1\nPMRA 11.0 1")))
    me = m.as_ECL("IERS2003")
    assert "AstrometryEcliptic" in me.components
    assert me.ECL.value == "IERS2003"
    assert me.as_ECL("IERS2003") is me  # same convention: self
    # DIFFERENT convention must convert, not silently return self
    me10 = me.as_ECL("IERS2010")
    assert me10 is not me and me10.ECL.value == "IERS2010"
    assert me10.ELONG.value != me.ELONG.value
    back = me.as_ICRS()
    assert back.get_param("RAJ").value == pytest.approx(
        m.get_param("RAJ").value, abs=1e-12)
    assert back.as_ICRS() is back
    with pytest.raises(ValueError, match="convention"):
        m.as_ECL("NOTACONV")
