"""Serve-fleet unit oracles (ISSUE 19).

Deterministic, mesh-light tests for the fleet building blocks:
validated config parsers ($PINT_TPU_POOLS / lease TTL / heartbeat),
the N-pool capacity router and its /healthz ``health_block``, the
journal ownership protocol (lease / heartbeat / owner-stamped admits
/ rehome / compaction keeping liveness), torn-record hardening, and
the ``FleetFront`` fence + re-home machinery driven by hand (engines
in sync mode, manual sweeps — the threaded chaos oracle lives in
tests/test_runtime_faults.py, the bit-identity and AOT oracles in
tests/test_serve_restart.py).
"""

import json
import time

import pytest

from pint_tpu.runtime import Fault, FaultPlan, reset_runtime
from pint_tpu.serve import (
    EngineKilled,
    FitStepRequest,
    FleetFront,
    WorkerLease,
)
from pint_tpu.serve.journal import RequestJournal
from pint_tpu.serve.router import CapacityRouter
from pint_tpu.serve.workload import synth_pulsar


@pytest.fixture(autouse=True)
def clean_runtime():
    reset_runtime()
    yield
    reset_runtime()


@pytest.fixture(scope="module")
def stock():
    from pint_tpu.parallel.pta import build_problem

    pulsars = {k: synth_pulsar(k, 40, base=4300) for k in (0, 1)}
    return {k: build_problem(t, m) for k, (m, t) in pulsars.items()}


def _factory(stock):
    def factory(payload):
        return FitStepRequest(problem=stock[payload["k"]],
                              payload=payload)

    return factory


def _fit(stock, k):
    return FitStepRequest(problem=stock[k], payload={"k": k})


def _front(stock, tmp_path, n=2, **kw):
    """A hand-driven front: engines in SYNC mode (never started),
    leases never heartbeating on their own, no sweeper thread —
    every state transition in these tests is an explicit call."""
    kw.setdefault("heartbeat_s", 3600.0)
    kw.setdefault("lease_ttl_s", 7200.0)
    return FleetFront(_factory(stock), n=n,
                      journal=str(tmp_path / "fleet.jsonl"),
                      start=False, **kw)


# ---------------------------------------------------------------- config


def test_fleet_config_parsers(monkeypatch):
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_POOLS", raising=False)
    assert config.pool_spec() is None
    monkeypatch.setenv("PINT_TPU_POOLS", "device,aux,host")
    assert config.pool_spec() == ("device", "aux", "host")
    # missing a structural pool / malformed names: warn-and-ignore,
    # never half-applied
    monkeypatch.setenv("PINT_TPU_POOLS", "device,aux")
    assert config.pool_spec() is None
    monkeypatch.setenv("PINT_TPU_POOLS", "device,AUX,host")
    assert config.pool_spec() is None
    monkeypatch.setenv("PINT_TPU_POOLS", "device,host,device")
    assert config.pool_spec() is None

    monkeypatch.setenv("PINT_TPU_FLEET_LEASE_TTL_S", "nope")
    assert config.fleet_lease_ttl_s() == 15.0
    monkeypatch.setenv("PINT_TPU_FLEET_LEASE_TTL_S", "6")
    assert config.fleet_lease_ttl_s() == 6.0
    # heartbeat at/above the TTL is clamped to TTL/3 — a heartbeat
    # slower than the lease it renews expires every healthy worker
    monkeypatch.setenv("PINT_TPU_FLEET_HEARTBEAT_S", "10")
    assert config.fleet_heartbeat_s() == pytest.approx(2.0)
    monkeypatch.setenv("PINT_TPU_FLEET_HEARTBEAT_S", "1.5")
    assert config.fleet_heartbeat_s() == 1.5

    monkeypatch.setenv("PINT_TPU_FLEET_WORKERS", "-2")
    assert config.fleet_workers() == 3
    monkeypatch.setenv("PINT_TPU_FLEET_WORKERS", "5")
    assert config.fleet_workers() == 5


# ---------------------------------------------------------------- router


class _FakeSup:
    """Deterministic pool_health stand-in: breaker state per pool by
    fiat, so demotion logic is tested without tripping real
    breakers."""

    def __init__(self, open_pools=()):
        self.open_pools = set(open_pools)

    def pool_health(self, pools=None):
        out = {"device": {"backend": "cpu",
                          "open": "device" in self.open_pools,
                          "inflight": 0},
               "host": {"backend": "cpu", "open": False}}
        for name in pools or ():
            out[name] = {"backend": f"pool:{name}",
                         "open": name in self.open_pools,
                         "inflight": 0}
        return out


def test_router_n_pools_order_and_pick():
    sup = _FakeSup()
    r = CapacityRouter(supervisor=sup, pools=("device", "aux", "host"))
    assert r._order == ("device", "aux", "host")
    # ties prefer the device pool (the two-pool behavior)
    assert r.pick("gls", 100) == "device"
    # a faster learned device-class pool wins
    r.seed_rate("aux", "gls", 1e12)
    assert r.pick("gls", 100) == "aux"
    # an OPEN breaker demotes ONLY its pool
    sup.open_pools = {"aux"}
    assert r.pick("gls", 100) == "device"
    # every device-class pool open -> host demotion of last resort
    sup.open_pools = {"device", "aux"}
    assert r.pick("gls", 100) == "host"
    assert r.pools["host"].demotions == 1
    # accounting runs per named pool
    r.issued("aux", nreq=2, rows=64, kind="gls")
    r.finished("aux", "gls", rows=64, wall_s=0.01)
    snap = r.snapshot()
    assert snap["aux"]["dispatches"] == 1
    assert snap["aux"]["rows"] == 64


def test_router_health_block_shape():
    sup = _FakeSup(open_pools={"aux"})
    r = CapacityRouter(supervisor=sup, pools=("device", "aux", "host"))
    r.seed_rate("device", "gls", 1000.0)
    r.issued("device", nreq=1, rows=8, kind="gls")
    h = r.health_block()
    assert set(h) == {"device", "aux", "host"}
    assert h["aux"]["open"] is True
    assert h["device"]["open"] is False
    assert h["device"]["rows_per_s"] == {"gls": 1000.0}
    assert h["device"]["inflight_rows"] == 8
    assert h["host"]["inflight_rows"] == 0


# --------------------------------------------------------------- journal


def test_journal_ownership_protocol(tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath)
    lease = WorkerLease(j, "w0", heartbeat_s=3600.0)
    WorkerLease(j, "w1", heartbeat_s=3600.0)
    t0 = j.workers()["w0"]
    time.sleep(0.01)
    lease.beat()
    beats = j.workers()
    assert set(beats) == {"w0", "w1"}
    assert beats["w0"] > t0          # newest beat wins
    j.admit("r1", {"k": 0}, worker="w0")
    j.admit("r2", {"k": 1}, worker="w1")
    j.admit("r3", {"k": 0})          # legacy ownerless admit
    assert [r["rid"] for r in j.unacknowledged()] == \
        ["r1", "r2", "r3"]
    assert [r["rid"] for r in j.unacknowledged(owner="w0")] == ["r1"]
    # rehome moves ownership in the log (last mark wins)
    j.rehome("r1", "w1")
    assert [r["rid"] for r in j.unacknowledged(owner="w1")] == \
        ["r1", "r2"]
    assert j.unacknowledged(owner="w0") == []
    counts = j.counts()
    assert counts["workers"] == 2 and counts["torn"] == 0
    # compaction preserves ownership AND one newest beat per worker
    j.compact()
    assert j.counts()["compactions"] == 1
    assert set(j.workers()) == {"w0", "w1"}
    assert [r["rid"] for r in j.unacknowledged(owner="w1")] == \
        ["r1", "r2"]
    j.close()
    # ...and the rewritten journal reads back identically
    j2 = RequestJournal(jpath)
    assert set(j2.workers()) == {"w0", "w1"}
    assert [r["rid"] for r in j2.unacknowledged(owner="w1")] == \
        ["r1", "r2"]
    j2.close()


def test_journal_torn_records_warn_and_skip(tmp_path):
    """ISSUE 19 satellite: a torn tail record AND torn records
    interleaved around compaction are warn-and-skip (counted on
    ``pint_tpu_journal_torn_records``), never a raise."""
    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath)
    j.admit("r1", {"k": 0}, worker="w0")
    j.admit("r2", {"k": 1})
    j.ack("r2", "served")
    j.close()
    with open(jpath, "r+") as fh:
        text = fh.read()
        fh.seek(0)
        # a corrupt line in the MIDDLE (bit rot / interleaved torn
        # write) and a non-dict record
        lines = text.splitlines()
        lines.insert(1, '{"op": "admit", "rid": "half')
        lines.insert(2, "[1, 2, 3]")
        fh.write("\n".join(lines) + "\n")
        # and a crash-torn tail
        fh.write('{"op": "ack", "rid": "r1", "sta')
    j2 = RequestJournal(jpath)
    assert [r["rid"] for r in j2.unacknowledged()] == ["r1"]
    assert j2.counts()["torn"] == 3
    # the same damaged line is deduped across repeated scans (every
    # unacknowledged()/counts() call rescans the file)
    j2.unacknowledged()
    assert j2.counts()["torn"] == 3
    # compaction drops the damage; the rewritten file is clean
    j2.compact()
    recs = [json.loads(x) for x in open(jpath)]
    assert all(r["op"] in ("admit", "heartbeat") for r in recs)
    j2.close()
    j3 = RequestJournal(jpath)
    assert [r["rid"] for r in j3.unacknowledged()] == ["r1"]
    assert j3.counts()["torn"] == 0
    j3.close()


# ----------------------------------------------------------------- fleet


def test_fleet_kill_worker_rehomes_onto_survivor(stock, tmp_path):
    front = _front(stock, tmp_path, n=2)
    f0 = front.submit(_fit(stock, 0))     # round-robin: w0
    f1 = front.submit(_fit(stock, 1))     # w1
    assert front.live_workers() == ["w0", "w1"]
    assert front.journal.counts()["unacknowledged"] == 2

    front.kill_worker("w0")
    assert front.live_workers() == ["w1"]
    # the corpse's future is unresolved, its journal entry unacked —
    # exactly what a process death leaves behind
    assert not f0.done()
    with pytest.raises(EngineKilled):
        front.workers["w0"].engine.submit(_fit(stock, 0))

    moved = front.sweep()
    assert moved == 1
    snap = front.snapshot()
    assert snap["workers"] == {"w0": "rehomed", "w1": "live"}
    assert snap["counters"]["worker_kills"] == 1
    assert snap["counters"]["rehomed"] == 1
    # a second sweep must NOT re-home again (dead -> rehomed latch)
    assert front.sweep() == 0

    front.workers["w1"].engine.flush()
    r0, r1 = f0.result(timeout=30), f1.result(timeout=30)
    assert r0.chi2 > 0 and r1.chi2 > 0
    # every accepted request reached a terminal ack: zero lost
    assert front.journal.counts()["unacknowledged"] == 0
    # the fleet keeps serving on the survivor
    f2 = front.submit(_fit(stock, 0))
    front.workers["w1"].engine.flush()
    assert f2.result(timeout=30).chi2 > 0
    front.stop()


def test_fleet_lease_expiry_fault_and_outage(stock, tmp_path):
    front = _front(stock, tmp_path, n=2)
    f0 = front.submit(_fit(stock, 0))     # w0
    # forced lease_expire (kind-scoped at fleet.lease/<id>): the
    # sweep fences w0 WITHOUT killing it first — the fence inside
    # the sweep is what keeps the transfer safe
    plan = FaultPlan([Fault(match="fleet.lease/w0",
                            kind="lease_expire")])
    with plan.active():
        moved = front.sweep()
    assert moved == 1
    assert front.live_workers() == ["w1"]
    assert front.snapshot()["counters"]["lease_expiries"] == 1
    front.workers["w1"].engine.flush()
    assert f0.result(timeout=30).chi2 > 0

    # heartbeat staleness: every worker silent past the TTL is a
    # fleet-wide outage — nobody to re-home onto, submits raise
    assert front.sweep(now=time.time() + 1e6) == 0
    assert front.live_workers() == []
    with pytest.raises(EngineKilled, match="no live workers"):
        front.submit(_fit(stock, 0))
    front.stop()


def test_fleet_metrics_view_and_health_blocks(stock, tmp_path):
    front = _front(stock, tmp_path, n=2)
    f0 = front.submit(_fit(stock, 0))
    front.workers["w0"].engine.flush()
    f0.result(timeout=30)
    snap = front.metrics.snapshot()
    assert set(snap["workers"]) == {"w0", "w1"}
    assert snap["submitted"] == 1        # fleet-wide sum
    assert snap["fleet"]["live"] == ["w0", "w1"]
    assert snap["fleet"]["journal"]["unacknowledged"] == 0
    assert isinstance(front.metrics.restart_info, dict)
    assert "[w0]" in front.metrics.report()
    blocks = front.health_blocks()
    assert set(blocks) == {"w0", "w1"}
    assert set(blocks["w0"]) >= {"device", "host"}
    front.stop()


def test_fleet_single_worker_fault_free_matches_engine(stock,
                                                       tmp_path):
    """Acceptance guard: a fault-free single-worker fleet is the old
    engine — same bucket composition, bit-identical results, zero
    fence/re-home activity."""
    import numpy as np

    from pint_tpu.serve import ServeEngine

    front = _front(stock, tmp_path, n=1)
    futs = [front.submit(_fit(stock, k)) for k in (0, 1)]
    front.workers["w0"].engine.flush()
    got = [f.result(timeout=30) for f in futs]

    eng = ServeEngine()
    refs = [eng.submit(FitStepRequest(problem=stock[k]))
            for k in (0, 1)]
    eng.flush()
    ref = [f.result(timeout=0) for f in refs]
    eng.stop()

    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a.dparams),
                                      np.asarray(b.dparams))
        np.testing.assert_array_equal(np.asarray(a.cov),
                                      np.asarray(b.cov))
        assert a.chi2 == b.chi2
    snap = front.snapshot()
    assert snap["counters"] == \
        {"rehomed": 0, "lease_expiries": 0, "worker_kills": 0}
    assert snap["workers"] == {"w0": "live"}
    front.stop()


def test_fleet_requires_a_journal(stock, monkeypatch):
    monkeypatch.delenv("PINT_TPU_JOURNAL", raising=False)
    with pytest.raises(ValueError, match="replicated log"):
        FleetFront(_factory(stock), n=2, journal=None, start=False)
