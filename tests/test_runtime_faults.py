"""Fault-tolerant dispatch acceptance suite (ISSUE 4).

Every axon-tunnel failure mode the runtime supervisor exists for —
silent hangs, transient errors, NaN readback, RTT drift — injected
deterministically at the dispatch boundary (``runtime.faults``) on
the CPU mesh, asserting the behaviors CLAUDE.md promises:

- an injected hang returns via HOST FAILOVER, bit-identical to the
  direct host path, bounded by the configured deadline;
- transient errors retry, repeated failures trip the per-backend
  circuit breaker, a bounded half-open probe closes it on recovery;
- a ServeEngine drain under mid-batch backend death completes every
  future (failed over — zero hung futures);
- injected RTT drift triggers a re-measure and a NEW power-of-two
  steps-per-dispatch K without adding a compile key (asserted via
  ``analysis.Sanitizer``).
"""

import copy
import time
import warnings

import numpy as np
import pytest

import bench
from pint_tpu import config
from pint_tpu.runtime import (
    CLOSED,
    OPEN,
    DispatchSupervisor,
    DispatchTimeout,
    Fault,
    FaultPlan,
    breaker_for,
    get_supervisor,
    reset_runtime,
)


@pytest.fixture(autouse=True)
def clean_runtime():
    """A tripped breaker, leftover counters or a configured tracer
    must never leak across tests (breakers are process-global by
    design; the tracer is the process-global obs instance)."""
    from pint_tpu import obs

    reset_runtime()
    obs.reset()
    yield
    reset_runtime()
    obs.reset()


def _north_star_shaped(n=400, ndmx=4, seed=9):
    """The north-star problem's component mix (astrometry + spin +
    frozen DM taylor + free DMX + per-group JUMPs + EFAC/EQUAD/ECORR
    + power-law red noise) at test size."""
    span0, span1 = 53000.0, 57000.0
    par = [
        "PSR J0001+0001",
        "RAJ 12:00:00.0 1", "DECJ 30:00:00.0 1",
        "PMRA 2.0 1", "PMDEC -3.0 1", "PX 1.2 1",
        "F0 300.123456789 1", "F1 -1.0e-15 1", "F2 1e-26 1",
        "DM 20.0", "DM1 1e-4", "DM2 1e-6",
        "PEPOCH 55000", "POSEPOCH 55000", "DMEPOCH 55000",
        "TZRMJD 55000.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "EFAC -be X 1.1", "EQUAD -be X 0.3", "ECORR -be X 1.2",
        "TNREDAMP -13.7", "TNREDGAM 3.5", "TNREDC 10",
        "JUMP -grp g1 1e-6 1",
    ]
    bench._add_dmx(par, span0, span1, ndmx)
    mjds = bench._clustered_mjds(span0, span1, n)
    freqs = np.tile([1400.0, 1400.0, 820.0, 820.0], n // 4)
    model, toas = bench._make_model_toas(
        par, mjds, freqs, seed=seed,
        flag_sets={"be": lambda i: "X",
                   "grp": lambda i: f"g{i % 2}"})
    model.F0.add_delta(1e-10)
    model.invalidate_cache(params_only=True)
    return model, toas


# ------------------------------------------------------ hang failover


def test_injected_hang_fails_over_bit_identical_and_bounded(
        monkeypatch):
    """THE acceptance oracle: under an injected wedge, the
    north-star-shaped device fit returns via host failover,
    bit-identical to the direct host path, bounded by the configured
    deadline — never an unbounded block."""
    from pint_tpu.gls import DeviceDownhillGLSFitter, DownhillGLSFitter

    model, toas = _north_star_shaped()
    ref_model = copy.deepcopy(model)
    # the direct host path = the failover target; running it first
    # also warms every host compile, so the bounded-wall assertion
    # below measures the failover machinery, not XLA
    ref = DownhillGLSFitter(toas, ref_model)
    ref_chi2 = ref.fit_toas()

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "300")
    hang_s = 30.0
    plan = FaultPlan([Fault(match="gls.fit", kind="hang",
                            seconds=hang_s)])
    t0 = time.monotonic()
    with plan.active():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            fit = DeviceDownhillGLSFitter(toas, model)
            chi2 = fit.fit_toas()
    wall = time.monotonic() - t0
    # the injected hang is 30 s: an unbounded block would eat it all
    assert wall < hang_s - 5.0
    assert ("gls.fit_step", "hang") in plan.applied

    # bit-identical to the direct host path (same code, same state)
    assert chi2 == ref_chi2
    for name in model.free_params:
        assert model.get_param(name).value == \
            ref_model.get_param(name).value, name
        assert model.get_param(name).uncertainty == \
            ref_model.get_param(name).uncertainty, name
    np.testing.assert_array_equal(
        fit.parameter_covariance_matrix,
        ref.parameter_covariance_matrix)

    snap = get_supervisor().snapshot()
    assert snap["timeouts"] >= 1
    assert snap["failovers"] >= 1
    assert snap["abandoned_workers"] >= 1


def test_timeout_without_fallback_raises_bounded(monkeypatch):
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "150")
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="solo", kind="hang", seconds=3.0)])
    t0 = time.monotonic()
    with plan.active():
        with pytest.raises(DispatchTimeout):
            sup.dispatch(lambda: 1, key="solo")
    assert time.monotonic() - t0 < 1.5
    assert sup.metrics.timeouts == 1


# ------------------------------------------------ classify + breaker


def test_transient_errors_retry_then_succeed(monkeypatch):
    monkeypatch.setenv("PINT_TPU_DISPATCH_BACKOFF_MS", "1")
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="rt", kind="error", count=2)])
    with plan.active():
        assert sup.dispatch(lambda: 7, key="rt") == 7
    assert sup.metrics.transient_errors == 2
    assert sup.metrics.retries == 2
    assert breaker_for("cpu").state == CLOSED  # success reset it


def test_fatal_errors_reraise_untouched():
    """A caller bug (bad shapes, a TypeError) must NOT retry, NOT
    trip the breaker and NOT fail over — it is not an infra
    failure."""
    sup = DispatchSupervisor()

    def boom():
        raise TypeError("bad operand")

    with pytest.raises(TypeError):
        sup.dispatch(boom, key="fatal", fallback=lambda: "host")
    assert sup.metrics.failovers == 0
    assert sup.metrics.retries == 0
    assert breaker_for("cpu").state == CLOSED


def test_breaker_trips_short_circuits_and_recovers(monkeypatch):
    """Repeated failures trip OPEN (subsequent dispatches degrade to
    host WITHOUT touching the backend); after the cooldown a bounded
    half-open probe + one successful trial close it again."""
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("PINT_TPU_BREAKER_COOLDOWN_S", "0.05")
    monkeypatch.setenv("PINT_TPU_DISPATCH_RETRIES", "0")
    sup = DispatchSupervisor()
    calls = []

    def device():
        calls.append(1)
        return 42

    plan = FaultPlan([Fault(match="brk", kind="error")],
                     probe_ok=False)
    with plan.active():
        for _ in range(3):
            assert sup.dispatch(device, key="brk",
                                fallback=lambda: "host") == "host"
        br = breaker_for("cpu")
        assert br.state == OPEN
        assert br.trips == 1
        # OPEN: short-circuit — the device fn is never touched
        n_before = len(calls)
        assert sup.dispatch(device, key="brk",
                            fallback=lambda: "host") == "host"
        assert len(calls) == n_before
        assert sup.metrics.breaker_rejections >= 1
        # probe says still dead after cooldown: stays OPEN, escalated
        time.sleep(0.07)
        assert sup.dispatch(device, key="brk",
                            fallback=lambda: "host") == "host"
        assert br.state == OPEN
        # scripted recovery: faults clear, the bounded probe answers
        plan.clear()
        plan.probe_ok = True
        time.sleep(br.cooldown_s + 0.02)
        assert sup.dispatch(device, key="brk",
                            fallback=lambda: "host") == 42
        assert br.state == CLOSED
    assert sup.metrics.breaker_recoveries == 1


def test_half_open_trial_failure_reopens(monkeypatch):
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("PINT_TPU_BREAKER_COOLDOWN_S", "0.03")
    monkeypatch.setenv("PINT_TPU_DISPATCH_RETRIES", "0")
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="ho", kind="error")],
                     probe_ok=True)  # probe lies: trial still fails
    with plan.active():
        with pytest.raises(Exception):
            sup.dispatch(lambda: 1, key="ho")
        br = breaker_for("cpu")
        assert br.state == OPEN
        time.sleep(0.05)
        # probe passes -> half-open trial -> injected failure -> OPEN
        with pytest.raises(Exception):
            sup.dispatch(lambda: 1, key="ho")
        assert br.state == OPEN
        assert br.trips == 2


def test_fatal_during_half_open_does_not_strand_breaker(monkeypatch):
    """A caller bug raised during the half-open trial carries no
    backend-health verdict: the breaker must return to OPEN (and
    re-probe after the cooldown), never dangle in HALF_OPEN where it
    rejects everything forever."""
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("PINT_TPU_BREAKER_COOLDOWN_S", "0.03")
    monkeypatch.setenv("PINT_TPU_DISPATCH_RETRIES", "0")
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="fho", kind="error", count=1)],
                     probe_ok=True)
    with plan.active():
        with pytest.raises(Exception):
            sup.dispatch(lambda: 1, key="fho")  # transient: trips
        br = breaker_for("cpu")
        assert br.state == OPEN
        time.sleep(0.05)

        def bug():
            raise TypeError("caller bug during the trial")

        with pytest.raises(TypeError):
            sup.dispatch(bug, key="fho")  # half-open trial, fatal
        assert br.state == OPEN  # aborted, NOT stranded half-open
        time.sleep(0.05)
        assert sup.dispatch(lambda: 9, key="fho") == 9
        assert br.state == CLOSED


def test_degenerate_system_failover_uses_svd_mirror(monkeypatch):
    """Host failover of a SINGULAR system (two exactly-collinear DMX
    windows) must degrade to the eigh mirror with the same
    DegeneracyWarning the device path emits — not die inside the
    Cholesky mirror."""
    import io

    from pint_tpu.fitter import DegeneracyWarning
    from pint_tpu.gls import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = (
        "PSR J0000+0009\nRAJ 12:00:00.0\nDECJ 30:00:00.0\n"
        "F0 61.0 1\nF1 -1e-15 1\nDM 20.0 1\nPEPOCH 55000\n"
        "POSEPOCH 55000\nTZRMJD 55000.01\nTZRSITE @\nTZRFRQ 1400\n"
        "UNITS TDB\nTNREDAMP -13.0\nTNREDGAM 3.0\nTNREDC 5\n"
        "DMX_0001 0.0 1\nDMXR1_0001 54000\nDMXR2_0001 56000\n"
        "DMX_0002 0.0 1\nDMXR1_0002 54000\nDMXR2_0002 56000\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54100, 55900, 80, m, error_us=1.0, add_noise=True,
            freq_mhz=np.tile([1400.0, 820.0], 40),
            rng=np.random.default_rng(21))
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "300")
    plan = FaultPlan([Fault(match="gls.", kind="hang", seconds=10.0)])
    with plan.active():
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            fit = GLSFitter(t, m)
            chi2 = fit.fit_toas()
    assert np.isfinite(chi2)
    assert np.all(np.isfinite(fit.parameter_covariance_matrix))
    assert any(w.category is DegeneracyWarning for w in rec)
    assert get_supervisor().snapshot()["failovers"] >= 1


def test_fitter_auto_consults_breaker(monkeypatch):
    """Fitter.auto on a (faked) TPU backend must route to the host
    fitters while the backend's breaker is OPEN."""
    import jax

    from pint_tpu.fitter import Fitter
    from pint_tpu.gls import DeviceDownhillGLSFitter
    from pint_tpu.serve.workload import synth_pulsar

    m, t = synth_pulsar(0, 40, base=1900)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("PINT_TPU_HOST_SOLVE_MAX_TOA", "10")
    fit = Fitter.auto(t, m)
    assert isinstance(fit, DeviceDownhillGLSFitter)
    br = breaker_for("tpu")
    for _ in range(br.threshold):
        br.on_result(False)
    assert br.state == OPEN
    fit2 = Fitter.auto(t, m)
    assert not isinstance(fit2, DeviceDownhillGLSFitter)


# ------------------------------------------------------ NaN readback


def test_injected_nan_fails_over_to_host(monkeypatch):
    """NaN garbage from the device step is classified as a
    non-finite step and the fit fails over to the SVD-capable host
    fitter instead of raising into the caller."""
    from pint_tpu.gls import DeviceDownhillGLSFitter, DownhillGLSFitter

    model, toas = _north_star_shaped(seed=11)
    ref_model = copy.deepcopy(model)
    ref_chi2 = DownhillGLSFitter(toas, ref_model).fit_toas()

    plan = FaultPlan([Fault(match="gls.fit", kind="nan")])
    with plan.active():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            fit = DeviceDownhillGLSFitter(toas, model)
            chi2 = fit.fit_toas()
    assert chi2 == ref_chi2
    for name in model.free_params:
        assert model.get_param(name).value == \
            ref_model.get_param(name).value, name
    assert get_supervisor().snapshot()["failovers"] >= 1


# -------------------------------------------------- serve mid-batch


def test_serve_drain_completes_every_future_under_backend_death(
        monkeypatch):
    """Mid-batch backend death during a coalesced drain: every
    admitted future completes (failed over to the host solve), zero
    hung futures, and the degradation is labeled in the metrics."""
    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.workload import build_workload

    fresh = build_workload(12, sizes=(40, 90, 150), base=1700,
                           prebuild=True, entry_name="FAULT")
    # reference pass, no faults: warms compiles AND gives the oracle
    ref_eng = ServeEngine()
    ref_futs = [ref_eng.submit(r) for r in fresh()]
    ref_eng.flush()
    ref_res = [f.result(timeout=0) for f in ref_futs]

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "250")
    eng = ServeEngine()
    # first dispatch survives, then the backend dies mid-drain
    plan = FaultPlan([Fault(match="serve.", kind="hang",
                            seconds=5.0, after=1)])
    with plan.active():
        futs = [eng.submit(r) for r in fresh()]
        eng.flush()
    assert all(f.done() for f in futs)  # ZERO hung futures
    res = [f.result(timeout=0) for f in futs]
    for a, b in zip(res, ref_res):
        if hasattr(a, "phase_int"):
            tot = (np.asarray(a.phase_int) - np.asarray(b.phase_int)
                   + np.asarray(a.phase_frac)
                   - np.asarray(b.phase_frac))
            assert np.all(np.abs(tot) < 1e-9)
        else:
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-8)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == len(futs)
    disp = snap["dispatch"]
    assert disp["failovers"] >= 1
    assert disp["timeouts"] >= 1
    # the human report labels the degradation
    assert "DEGRADED" in eng.metrics.report()


# ------------------------------------------------ chaos (ISSUE 8)


def test_chaos_overload_tenant_burst_backend_death(monkeypatch,
                                                   tmp_path):
    """ISSUE-8 chaos oracle: injected backend death MID-BURST + a
    quota-exceeding tenant + injected admission overload, all at
    once. Required outcome: zero hung futures, every request
    accounted served / shed / failover in the metrics (nothing
    silently dropped), results for served requests still correct,
    counters honest.

    ISSUE-10 extension: the chaos run happens under the tracer, and
    the resulting trace must tell the SAME story — every submitted
    request (raise-path sheds included) resolves to exactly one
    terminal span with correct parent->child causality, zero orphan
    spans, failover events present, and the export parses as Chrome
    trace-event JSON."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.serve import ServeEngine, ServeOverload
    from pint_tpu.serve.request import TenantOverQuota
    from pint_tpu.serve.workload import build_workload

    fresh = build_workload(12, sizes=(40, 90), base=2700,
                           prebuild=True, entry_name="CHAOS")
    # reference pass (no faults): warms compiles AND gives the oracle
    ref_eng = ServeEngine()
    ref_futs = [ref_eng.submit(r) for r in fresh()]
    ref_eng.flush()
    ref_res = [f.result(timeout=0) for f in ref_futs]

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "250")
    tracer = obs.configure(enabled=True)
    eng = ServeEngine()
    plan = FaultPlan([
        # the GLS backend dies after its first dispatch of the burst
        Fault(match="serve.gls", kind="hang", seconds=5.0, after=1),
        # tenant "noisy" is bursting past quota the whole time
        Fault(match="serve.admit/noisy", kind="tenant_burst"),
        # and two admissions see injected capacity exhaustion
        Fault(match="serve.admit/capacity", kind="overload",
              after=6, count=2),
    ])
    reqs = fresh()
    for i, r in enumerate(reqs):
        if i % 6 == 5:
            r.tenant = "noisy"
    shed_quota = shed_overload = 0
    futs, labels = [], []
    t0 = time.monotonic()
    with plan.active():
        for r in reqs:
            try:
                futs.append((r, eng.submit(r)))
            except TenantOverQuota:
                shed_quota += 1
                labels.append("shed")
            except ServeOverload:
                shed_overload += 1
                labels.append("shed")
        eng.flush()
    wall = time.monotonic() - t0
    assert wall < 5.0 - 1.0  # bounded by failover, not the hang
    # ZERO hung futures: every admitted request resolved
    assert all(f.done() for _, f in futs)
    served = 0
    ref_by_idx = {id(r): res for r, res in zip(reqs, ref_res)}
    for r, f in futs:
        res = f.result(timeout=0)  # labeled failover, never raises
        served += 1
        labels.append("served")
        ref = ref_by_idx[id(r)]
        if hasattr(res, "phase_int"):
            tot = (np.asarray(res.phase_int) - np.asarray(ref.phase_int)
                   + np.asarray(res.phase_frac)
                   - np.asarray(ref.phase_frac))
            assert np.all(np.abs(tot) < 1e-9)
        else:
            assert res.chi2 == pytest.approx(ref.chi2, rel=1e-8)
    # conservation: every request accounted, nothing silent
    assert served + shed_quota + shed_overload == len(reqs)
    assert shed_quota >= 1       # the noisy tenant really shed
    assert shed_overload >= 1    # the injected overload really shed
    snap = eng.metrics.snapshot()
    assert snap["completed"] == served
    adm = snap["admission"]
    assert adm["shed_quota"] == shed_quota
    assert adm["injected_overload"] == 2
    assert adm["tenants"]["noisy"]["shed"] == shed_quota
    disp = snap["dispatch"]
    assert disp["failovers"] >= 1  # the dead backend was failed over
    assert disp["timeouts"] >= 1
    assert "DEGRADED" in eng.metrics.report()
    assert "SHED" in eng.metrics.report()

    # --- ISSUE 10: the trace is the same story, causally ---------
    try:
        path = str(tmp_path / "chaos_trace.json")
        tracer.export(path)
        doc = _json.load(open(path, encoding="utf-8"))
        evs = doc["traceEvents"]
        assert evs and all(
            isinstance(e["name"], str) and e["ph"] in ("X", "i")
            and isinstance(e["ts"], (int, float)) for e in evs)
        ids = {e["args"]["span"] for e in evs}
        orphans = [e for e in evs
                   if e["args"].get("parent") is not None
                   and e["args"]["parent"] not in ids]
        assert orphans == []            # zero orphan spans
        terms = [e for e in evs if e["name"] == "serve.terminal"]
        # EVERY submitted request — served, quota-shed at the raise
        # path, overload-rejected — resolved to exactly ONE terminal
        assert len(terms) == len(reqs)
        statuses = [e["args"]["status"] for e in terms]
        assert statuses.count("served") == served
        assert statuses.count("shed:quota") == shed_quota
        assert statuses.count("shed:overload") == shed_overload
        roots = {e["args"]["span"]: e for e in evs
                 if e["name"] == "serve.request"}
        for e in terms:
            assert e["args"]["parent"] in roots
            assert e["args"]["trace"] == \
                roots[e["args"]["parent"]]["args"]["trace"]
        # the injected backend death shows up as failover telemetry
        names = {e["name"] for e in evs}
        assert "dispatch.failover" in names
        assert "dispatch.timeout" in names
    finally:
        obs.reset()


def test_fleet_chaos_worker_kill_mid_burst(tmp_path):
    """ISSUE 19 chaos oracle: 3 workers over one shared journal, a
    seeded ``worker_kill`` landing MID-BURST. Required outcome: zero
    lost requests — the killed worker's queued admits re-home onto
    survivors, every ORIGINAL future resolves with the survivor's
    result, the journal ends fully acknowledged — and the trace
    tells the same story: every request root resolves to exactly one
    ``serve.terminal``, zero orphan spans."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.serve import FitStepRequest
    from pint_tpu.serve.fleet import FleetFront
    from pint_tpu.serve.workload import synth_pulsar

    problems = {}
    for k in (0, 1):
        m, t = synth_pulsar(k, 40, base=5200)
        problems[k] = build_problem(t, m)

    def factory(payload):
        return FitStepRequest(problem=problems[payload["k"]],
                              payload=payload)

    # per-problem reference (fault-free single engine)
    from pint_tpu.serve import ServeEngine

    ref = {}
    ref_eng = ServeEngine()
    for k in (0, 1):
        f = ref_eng.submit(FitStepRequest(problem=problems[k]))
        ref_eng.flush()
        ref[k] = f.result(timeout=0)
    ref_eng.stop()

    tracer = obs.configure(enabled=True)
    front = FleetFront(factory, n=3,
                       journal=str(tmp_path / "fleet.jsonl"),
                       heartbeat_s=3600.0, lease_ttl_s=7200.0,
                       start=False)
    # one fault lookup per submit while w1 is live (the key is
    # kind- and worker-scoped): the kill lands on submit #7, with
    # two of w1's requests still queued
    plan = FaultPlan([Fault(match="fleet.worker/w1",
                            kind="worker_kill", after=6)])
    reqs = [FitStepRequest(problem=problems[i % 2],
                           payload={"k": i % 2})
            for i in range(12)]
    with plan.active():
        futs = [front.submit(r) for r in reqs]
    assert front.live_workers() == ["w0", "w2"]
    assert front.snapshot()["counters"]["worker_kills"] == 1
    assert front.sweep() == 2           # w1 held submits #2 and #5
    for wid in ("w0", "w2"):
        front.workers[wid].engine.flush()
    # ZERO lost requests: every submitted future resolves, correctly
    assert all(f.done() for f in futs)
    for r, f in zip(reqs, futs):
        res = f.result(timeout=0)
        assert res.chi2 == pytest.approx(
            ref[r.payload["k"]].chi2, rel=1e-8)
    assert front.journal.counts()["unacknowledged"] == 0
    snap = front.snapshot()
    assert snap["workers"] == \
        {"w0": "live", "w1": "rehomed", "w2": "live"}
    assert snap["counters"]["rehomed"] == 2

    # --- the trace is the same story, causally -------------------
    try:
        path = str(tmp_path / "fleet_trace.json")
        tracer.export(path)
        doc = _json.load(open(path, encoding="utf-8"))
        evs = doc["traceEvents"]
        ids = {e["args"]["span"] for e in evs}
        orphans = [e for e in evs
                   if e["args"].get("parent") is not None
                   and e["args"]["parent"] not in ids]
        assert orphans == []
        roots = {e["args"]["span"] for e in evs
                 if e["name"] == "serve.request"}
        terms = [e for e in evs if e["name"] == "serve.terminal"]
        # every request root — the 12 originals plus the 2 survivor
        # replays — resolves to exactly ONE terminal, all served
        assert len(terms) == len(roots) == len(reqs) + 2
        assert len({e["args"]["parent"] for e in terms}) == \
            len(terms)
        assert all(e["args"]["status"] == "served" for e in terms)
        # the fence left its mark
        names = {e["name"] for e in evs}
        assert "fleet.rehome" in names
    finally:
        obs.reset()
    front.stop()


# ------------------------------------------- GWB sweep (ISSUE 17)


def test_gwb_sweep_survives_mid_sweep_backend_death(monkeypatch,
                                                    tmp_path):
    """ISSUE-17 acceptance: the device dies MID-GWB-SWEEP — the
    block assembly and the first sweep chunk serve on device, every
    later chunk hangs. The request must complete via LABELED host
    failover from the chunk boundary (values identical to the
    no-fault reference), bounded by the watchdog deadline, with
    exactly ONE terminal span for the submitted request."""
    import io as _io
    import json as _json

    from pint_tpu import obs
    from pint_tpu.models import get_model
    from pint_tpu.serve import GWBRequest, ServeEngine
    from pint_tpu.simulation import make_fake_toas_uniform

    def mk(psr, f0, n, seed, ra, dec):
        par = (f"PSR {psr}\nRAJ {ra} 1\nDECJ {dec} 1\n"
               f"F0 {f0} 1\nF1 -1e-15 1\nPEPOCH 55000\n"
               f"POSEPOCH 55000\nDM {10 + seed} 1\nTZRMJD 55000.1\n"
               f"TZRSITE @\nTZRFRQ 1400\nUNITS TDB")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(_io.StringIO(par))
            t = make_fake_toas_uniform(
                54500, 55500, n, m, error_us=1.0, add_noise=True,
                rng=np.random.default_rng(seed))
        return t, m

    pairs = [mk("J0001+21", 101.1, 30, 21, "12:01:00.0",
                "21:00:00.0"),
             mk("J0430-10", 317.9, 40, 22, "04:30:00.0",
                "-10:00:00.0"),
             mk("J1820+55", 218.5, 36, 23, "18:20:00.0",
                "55:00:00.0")]
    la = np.linspace(-15.0, -13.5, 10)
    ga = np.full(10, 13.0 / 3.0)

    # reference pass, no faults: warms every compile + the oracle
    ref_eng = ServeEngine()
    ref = ref_eng.submit(GWBRequest(pairs=pairs, log10A=la,
                                    gamma=ga, nfreq=2)) \
        .result(timeout=120)

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "250")
    tracer = obs.configure(enabled=True)
    eng = ServeEngine()
    # chunk 0 of the sweep serves on device (the blocks key
    # "pta.gwb_blocks" never matches) — every later chunk hangs:
    # the death is genuinely MID-sweep
    hang_s = 8.0
    plan = FaultPlan([Fault(match="serve.gwb", kind="hang",
                            seconds=hang_s, after=1)])
    req = GWBRequest(pairs=pairs, log10A=la, gamma=ga, nfreq=2,
                     rid="gwb-chaos", payload={"kind": "gwb"})
    t0 = time.monotonic()
    with plan.active():
        fut = eng.submit(req)
        eng.flush()
    wall = time.monotonic() - t0
    assert wall < hang_s - 1.0    # bounded by failover, not the hang
    assert fut.done()
    res = fut.result(timeout=0)   # labeled failover, never raises
    # chunk-boundary failover: the host mirror finishes the sweep,
    # values identical to the healthy reference
    np.testing.assert_allclose(res.logL, ref.logL, rtol=1e-9)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == 1
    disp = snap["dispatch"]
    assert disp["failovers"] >= 1
    assert disp["timeouts"] >= 1
    assert "DEGRADED" in eng.metrics.report()

    # the trace tells the same story: exactly ONE terminal span,
    # served, and the unit is labeled host-failover
    path = str(tmp_path / "gwb_chaos_trace.json")
    tracer.export(path)
    evs = _json.load(open(path, encoding="utf-8"))["traceEvents"]
    terms = [e for e in evs if e["name"] == "serve.terminal"]
    assert len(terms) == 1
    assert terms[0]["args"]["status"] == "served"
    units = [e for e in evs if e["name"] == "serve.unit"]
    assert [u["args"]["used_pool"] for u in units] == \
        ["host-failover"]
    names = {e["name"] for e in evs}
    assert "dispatch.failover" in names and \
        "dispatch.timeout" in names


# ------------------------------------------------- pipelined drain


def test_pipelined_drain_survives_mid_pipeline_death(monkeypatch):
    """ISSUE 7 acceptance: the serve engine's double-buffered drain
    (pipeline_depth=2) with the backend dying MID-PIPELINE — two
    batches in flight when the wedge hits — still completes every
    admitted future via labeled host failover: zero hung futures,
    results identical to the no-fault reference, supervisor counters
    carrying the degradation."""
    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.workload import build_workload

    fresh = build_workload(12, sizes=(40, 90, 150), base=2300,
                           prebuild=True, entry_name="PIPE")
    # reference pass (sync engine, no faults): oracle + warm compiles
    ref_eng = ServeEngine(pipeline_depth=1)
    ref_futs = [ref_eng.submit(r) for r in fresh()]
    ref_eng.flush()
    ref_res = [f.result(timeout=0) for f in ref_futs]

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "250")
    eng = ServeEngine(pipeline_depth=2)
    # the first dispatch survives; every later one hangs — with two
    # in flight, BOTH outstanding dispatches are wedged at once
    plan = FaultPlan([Fault(match="serve.", kind="hang",
                            seconds=8.0, after=1)])
    t0 = time.monotonic()
    with plan.active():
        futs = [eng.submit(r) for r in fresh()]
        eng.flush()
    wall = time.monotonic() - t0
    assert wall < 8.0 - 1.0          # bounded, not the hang duration
    assert all(f.done() for f in futs)   # ZERO hung futures
    res = [f.result(timeout=0) for f in futs]
    for a, b in zip(res, ref_res):
        if hasattr(a, "phase_int"):
            tot = (np.asarray(a.phase_int) - np.asarray(b.phase_int)
                   + np.asarray(a.phase_frac)
                   - np.asarray(b.phase_frac))
            assert np.all(np.abs(tot) < 1e-9)
        else:
            # host failover result == the direct host path (the
            # fallback IS pta_solve_np; reference ran on device —
            # same algebra to solver rounding)
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-8)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == len(futs)
    disp = snap["dispatch"]
    assert disp["failovers"] >= 2     # both in-flight batches failed
    assert disp["timeouts"] >= 2      # ... by watchdog timeout
    assert disp["max_inflight"] >= 2  # the pipeline was really deep
    assert ("serve.", "hang") not in plan.applied  # sanity: keys real
    assert any(k.startswith("serve.") for k, _ in plan.applied)
    assert "DEGRADED" in eng.metrics.report()


def test_async_fatal_error_propagates_through_future():
    """A caller bug inside an async dispatch re-raises untouched at
    result() — no retry, no failover, no breaker verdict (the same
    classification contract as the sync path)."""
    sup = DispatchSupervisor()

    def boom():
        raise TypeError("bad operand")

    fut = sup.dispatch_async(boom, key="afatal",
                             fallback=lambda: "host")
    with pytest.raises(TypeError):
        fut.result()
    assert sup.metrics.failovers == 0
    assert breaker_for("cpu").state == CLOSED


# ------------------------------------------------------- RTT drift


def test_rtt_drift_remeasures_and_repicks_pow2_k(monkeypatch):
    """Observed wall deviating >2x from the RTT x steps prediction
    re-measures the RTT and re-picks the power-of-two K — with NO new
    compile key (executable cache unchanged, per analysis.Sanitizer).
    """
    import jax
    import jax.numpy as jnp

    from pint_tpu.analysis import Sanitizer

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "5000")
    config._RTT_MS.clear()
    config._RTT_MS["tpu"] = 124.0  # the session-start measurement
    old_k = config.auto_steps_per_dispatch()
    assert old_k == 16

    jitted = jax.jit(lambda x: x + 1.0)
    x = jnp.asarray(1.0)
    float(jitted(x))  # warm: the compile happens outside the test
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="drift", kind="rtt_drift",
                            factor=5e4)])
    try:
        with Sanitizer() as san:
            san.watch(jitted, "step")
            # first call warms the dispatch key (cold calls carry the
            # compile allowance and get no drift verdict by design)
            sup.dispatch(jitted, x, key="drift", steps=1)
            assert sup.metrics.rtt_remeasures == 0
            with plan.active():
                out = sup.dispatch(jitted, x, key="drift", steps=1)
            assert float(np.asarray(out)) == 2.0
            assert san.compiles() == 0  # no model rebuilds either
            growth = san.executable_growth()["step"]
        assert growth in (0, None)  # executable cache size unchanged
        assert sup.metrics.rtt_remeasures == 1
        new_k = config.auto_steps_per_dispatch()
        assert sup.metrics.last_k == new_k
        assert new_k in (4, 8, 16, 32)
        assert new_k != old_k  # CPU-real RTT << the drifted 124 ms
        assert config._RTT_MS["tpu"] < 124.0  # actually re-measured
    finally:
        config._RTT_MS.clear()


def test_no_drift_verdict_inside_window(monkeypatch):
    """A wall within [1/2x, 2x] of prediction must NOT re-measure."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "5000")
    config._RTT_MS.clear()
    jitted = jax.jit(lambda x: x * 2.0)
    x = jnp.asarray(3.0)
    float(jitted(x))
    sup = DispatchSupervisor()
    try:
        real_guarded = sup._guarded_call

        def slow(fn, args, kw, deadline_s, pre_sleep, nan):
            # pad the wall to ~the predicted 8 ms: ratio lands near
            # 1.0 regardless of scheduler noise on the real ~0.3 ms
            time.sleep(0.008)
            return real_guarded(fn, args, kw, deadline_s, pre_sleep,
                                nan)

        monkeypatch.setattr(sup, "_guarded_call", slow)
        config._RTT_MS["tpu"] = 8.0
        sup.dispatch(jitted, x, key="ok", steps=1)  # warms the key
        sup.dispatch(jitted, x, key="ok", steps=1)  # verdict run
        assert sup.metrics.rtt_remeasures == 0
    finally:
        config._RTT_MS.clear()


def test_no_drift_for_healthy_chained_dispatch(monkeypatch):
    """A healthy chained dispatch's wall is rtt + K*t_step — far
    below the fully-serial rtt*K bound. The drift window is anchored
    on the fixed cost, so the happy chained path must never trigger
    a re-measure (the naive wall/(rtt*K) ratio would fire on EVERY
    such dispatch)."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "5000")
    config._RTT_MS.clear()
    jitted = jax.jit(lambda x: x * 3.0)
    x = jnp.asarray(2.0)
    float(jitted(x))
    sup = DispatchSupervisor()
    real = sup._guarded_call

    def padded(fn, args, kw, dl, ps, nan):
        time.sleep(0.06)  # ~ rtt + K*t_step with t_step << rtt
        return real(fn, args, kw, dl, ps, nan)

    monkeypatch.setattr(sup, "_guarded_call", padded)
    try:
        config._RTT_MS["tpu"] = 40.0  # wall 60ms in [20, 2*40*16]
        sup.dispatch(jitted, x, key="chain", steps=16)  # warms key
        sup.dispatch(jitted, x, key="chain", steps=16)  # verdict run
        assert sup.metrics.rtt_remeasures == 0
    finally:
        config._RTT_MS.clear()


def test_no_drift_verdict_for_pipelined_dispatches(monkeypatch):
    """ISSUE 7 satellite fix: a PIPELINED dispatch's wall includes
    queuing behind the work it overlapped — once overlapped, wall
    per dispatch is no longer RTT-dominated, so the >2x drift
    detector must not fire on it (the same wall at depth=1 IS a
    legitimate over-run verdict)."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    sup = DispatchSupervisor()
    sup._seen.add("pk")   # warmed key: drift verdicts are live
    config._RTT_MS.clear()
    config._RTT_MS["tpu"] = 8.0
    try:
        # 200 ms wall vs an 8 ms x 1-step prediction: >2x over-run —
        # but issued at depth 2, so NO verdict
        sup._note_wall("pk", 1, 0.2, "tpu", depth=2)
        assert sup.metrics.rtt_remeasures == 0
        # the identical wall unoverlapped: the verdict fires
        sup._note_wall("pk", 1, 0.2, "tpu", depth=1)
        assert sup.metrics.rtt_remeasures == 1
    finally:
        config._RTT_MS.clear()


def test_transient_classification_is_narrow():
    """Connection-class and timeout errors are infra; filesystem
    OSErrors are caller bugs and must NOT retry or trip breakers."""
    from pint_tpu.runtime.supervisor import _is_transient

    assert _is_transient(ConnectionResetError("peer reset"))
    assert _is_transient(BrokenPipeError("pipe"))
    assert _is_transient(TimeoutError("socket timed out"))
    assert not _is_transient(FileNotFoundError("missing.clk"))
    assert not _is_transient(PermissionError("denied"))
    assert not _is_transient(ValueError("bad shape"))


# ------------------------------------------------- labeled artifacts


def test_pinned_dispatches_bypass_the_breaker(monkeypatch):
    """Host-pinned solves carry no accelerator-health evidence: an
    OPEN TPU breaker must not reroute them, and their successes must
    not close it."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    sup = DispatchSupervisor()
    br = breaker_for("tpu")
    for _ in range(br.threshold):
        br.on_result(False)
    assert br.state == OPEN
    assert sup.dispatch(lambda: 5, key="pin", pinned=True) == 5
    assert br.state == OPEN  # NOT closed by host-CPU evidence
    assert sup.metrics.breaker_rejections == 0  # NOT rerouted either


def test_bench_artifact_carries_dispatch_counters():
    rec = bench.attach_dispatch_counters({"metric": "x"})
    snap = rec["dispatch_supervisor"]
    for k in ("dispatches", "retries", "timeouts", "failovers",
              "breaker_rejections", "breakers"):
        assert k in snap
    # setdefault semantics: a record carried from a subprocess (the
    # late TPU probe) keeps ITS counters — this process's all-zero
    # snapshot must not erase the degradation label
    foreign = {"metric": "x",
               "dispatch_supervisor": {"failovers": 7}}
    assert bench.attach_dispatch_counters(foreign)[
        "dispatch_supervisor"] == {"failovers": 7}


def test_runtime_env_knobs_parse(monkeypatch):
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "1234")
    assert config.dispatch_deadline_ms() == 1234.0
    monkeypatch.delenv("PINT_TPU_DISPATCH_DEADLINE_MS")
    assert config.dispatch_deadline_ms() is None
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "5")
    assert config.breaker_threshold() == 5
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "banana")
    assert config.breaker_threshold() == 3  # warned, defaulted


# ------------------------------------- metrics plane (ISSUE 11)


def test_chaos_registry_parity_and_slo_burn_before_breaker(
        monkeypatch, tmp_path):
    """ISSUE-11 chaos oracle: an injected latency regression (every
    dispatch wedged past its watchdog deadline, served via labeled
    host failover) must fire EXACTLY ONE ``slo_burn:*`` flight dump
    BEFORE the breaker opens — the post-mortem starts while the
    regression is happening, not at the breaker-open autopsy. A
    /metrics scrape MID-BURST returns a parseable exposition
    consistent with the final counter story, and at the end every
    counter in the engine's snapshot blocks reads back through the
    registry with identical values (parity across a chaos run)."""
    import urllib.request

    from pint_tpu import obs
    from pint_tpu.obs import metrics as om
    from pint_tpu.obs import slo
    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.workload import build_workload

    fresh = build_workload(2, sizes=(40, 90), base=7100,
                           prebuild=True, entry_name="SLOCHAOS")
    # env BEFORE any dispatch: the per-backend breaker reads its
    # threshold at construction (first dispatch constructs it)
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "400")
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "12")
    monkeypatch.setenv("PINT_TPU_DISPATCH_RETRIES", "0")
    # reference pass: warm every compile so the healthy-phase e2e
    # sits far inside the SLO objective
    ref_eng = ServeEngine()
    futs = [ref_eng.submit(r) for r in fresh()]
    ref_eng.flush()
    for f in futs:
        f.result(timeout=0)

    obs.configure(enabled=False, flight_dir=str(tmp_path))
    eng = ServeEngine()
    # e2e SLO: objective at the 2^18 us bucket edge (262.144 ms) —
    # warm healthy requests are ~ms, a deadline-timed-out dispatch
    # is >= 400 ms, one octave above the objective
    spec = slo.SLOSpec(
        name="e2e_p99", type="latency",
        metric="pint_tpu_serve_latency_seconds",
        labels={"scope": eng.metrics.scope, "metric": "e2e"},
        objective_ms=262.144, target=0.9,
        fast_s=10.0, slow_s=30.0, burn=2.0,
        min_events=4, min_samples=2)
    clock = {"t": 0.0}
    wd = slo.SLOWatchdog(specs=[spec], interval_s=5.0,
                         clock=lambda: clock["t"])
    srv = om.MetricsServer(port=0).start()

    def drive_and_tick():
        fs = [eng.submit(r) for r in fresh()]
        eng.flush()
        for f in fs:
            f.result(timeout=0)
        fired = wd.tick(now=clock["t"])
        clock["t"] += 5.0
        return fired

    def scrape():
        url = f"http://127.0.0.1:{srv.port}/metrics"
        return urllib.request.urlopen(url, timeout=10).read() \
            .decode("utf-8")

    def prom_value(text, name, **labels):
        want = {f'{k}="{v}"' for k, v in labels.items()}
        for line in text.splitlines():
            if not line.startswith(name + "{"):
                continue
            body = line.split("{", 1)[1].rsplit("}", 1)[0]
            if want <= set(body.split(",")):
                return float(line.rsplit(" ", 1)[1])
        return None

    served = 0
    try:
        # healthy phase: cover the slow window with good traffic
        for _ in range(7):
            assert drive_and_tick() == []
            served += 2
        # degraded phase: every dispatch wedges past the deadline
        plan = FaultPlan([Fault(match="serve.", kind="hang",
                                seconds=5.0)])
        fired_at = None
        with plan.active():
            for i in range(3):
                fired = drive_and_tick()
                served += 2
                if fired:
                    fired_at = i
                    break
            assert fired_at is not None, "SLO never fired"
            # the burn fired BEFORE the breaker opened
            assert not breaker_for("cpu").is_open
            slo_dumps = list(tmp_path.glob("flight-*slo_burn*.json"))
            assert len(slo_dumps) == 1
            doc = __import__("json").loads(slo_dumps[0].read_text())
            assert doc["reason"] == "slo_burn:e2e_p99"
            # mid-burst scrape: parseable, consistent direction
            mid = scrape()
            mid_timeouts = prom_value(
                mid, "pint_tpu_dispatch_timeouts_total",
                scope=eng.supervisor.metrics.scope)
            assert mid_timeouts is not None and mid_timeouts >= 1
            # keep failing until the breaker opens (12 consecutive
            # unit timeouts; each flush times out ~2 units)
            for _ in range(10):
                if breaker_for("cpu").is_open:
                    break
                fs = [eng.submit(r) for r in fresh()]
                eng.flush()
                for f in fs:
                    f.result(timeout=0)
                served += 2
            assert breaker_for("cpu").is_open
        # exactly one slo_burn dump, and it predates breaker-open
        slo_dumps = list(tmp_path.glob("flight-*slo_burn*.json"))
        brk_dumps = list(tmp_path.glob("flight-*breaker_open*.json"))
        assert len(slo_dumps) == 1
        assert len(brk_dumps) >= 1
        import os as _os

        assert _os.path.getmtime(slo_dumps[0]) <= \
            min(_os.path.getmtime(p) for p in brk_dumps)
        # final counter story: scrape == registry == snapshot
        snap = eng.metrics.snapshot()
        final = scrape()
        reg = om.get_registry()
        for name in ("submitted", "completed", "failed"):
            want = snap[name]
            assert reg.value(f"pint_tpu_serve_{name}_total",
                             scope=eng.metrics.scope) == want, name
            assert prom_value(
                final, f"pint_tpu_serve_{name}_total",
                scope=eng.metrics.scope) == want, name
        disp = snap["dispatch"]
        sscope = eng.supervisor.metrics.scope
        for name in ("timeouts", "failovers", "dispatches",
                     "breaker_rejections"):
            assert prom_value(
                final, f"pint_tpu_dispatch_{name}_total",
                scope=sscope) == disp[name], name
        assert mid_timeouts <= disp["timeouts"]
        assert snap["completed"] == served  # zero silent drops
        assert disp["timeouts"] >= 12
        assert disp["failovers"] >= 12
    finally:
        srv.close()


# ------------------------------------- numerical health (ISSUE 14)


def test_injected_nan_fires_one_numerics_dump_with_causal_span(
        monkeypatch, tmp_path):
    """ISSUE-14 chaos oracle: an injected NaN readback must produce
    EXACTLY ONE ``numerics:nonfinite`` flight dump (the recorder's
    per-reason rate limit asserted by observing more NaNs inside the
    window), a labeled ``health`` event on the causal fit/dispatch
    trace, registry incident counters that agree, the failover
    counter story UNCHANGED from the pre-health oracle, and a
    zero-orphan Perfetto-parseable export."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.gls import DeviceDownhillGLSFitter, DownhillGLSFitter
    from pint_tpu.obs import health as oh
    from pint_tpu.obs import metrics as om

    model, toas = _north_star_shaped(seed=17)
    ref_model = copy.deepcopy(model)
    ref_chi2 = DownhillGLSFitter(toas, ref_model).fit_toas()

    tracer = obs.configure(enabled=True, flight_dir=str(tmp_path))
    mon = oh.configure(enabled=True)
    plan = FaultPlan([Fault(match="gls.fit", kind="nan")])
    with plan.active():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            fit = DeviceDownhillGLSFitter(toas, model)
            chi2 = fit.fit_toas()
    # the failover story is UNCHANGED: bit-identical host result
    assert chi2 == ref_chi2
    assert get_supervisor().snapshot()["failovers"] >= 1
    # exactly one dump for the episode...
    dumps = list(tmp_path.glob("flight-*numerics_nonfinite*.json"))
    assert len(dumps) == 1
    doc = _json.loads(dumps[0].read_text())
    assert doc["reason"] == "numerics:nonfinite"
    # ...and the rate limit holds for further incidents in-window
    incidents0 = int(om.get_registry().total(
        "pint_tpu_health_incidents_total"))
    assert incidents0 >= 1
    import numpy as _np

    mon.observe("fit.device", {"values": [_np.array([_np.nan])]},
                key="gls.fit_step")
    assert int(om.get_registry().total(
        "pint_tpu_health_incidents_total")) == incidents0 + 1
    assert len(list(
        tmp_path.glob("flight-*numerics_nonfinite*.json"))) == 1

    # the trace carries the labeled verdict on the causal story
    path = str(tmp_path / "nan_trace.json")
    tracer.export(path)
    evs = _json.load(open(path, encoding="utf-8"))["traceEvents"]
    ids = {e["args"]["span"] for e in evs}
    orphans = [e for e in evs
               if e["args"].get("parent") is not None
               and e["args"]["parent"] not in ids]
    assert orphans == []
    health_evs = [e for e in evs if e["name"] == "health"
                  and e["args"].get("ok") is False]
    assert health_evs, "no labeled health verdict in the trace"
    fit_spans = {e["args"]["span"]: e for e in evs
                 if e["name"] == "fit.device"}
    he = health_evs[0]
    # the verdict parents under the device-fit span whose dispatch
    # produced the NaN — same trace as the dispatch span
    assert he["args"]["parent"] in fit_spans
    disp = [e for e in evs if e["name"].startswith("dispatch/gls.fit")
            and e["args"]["trace"] == he["args"]["trace"]]
    assert disp, "no causal dispatch span in the health trace"
    assert any(e["name"] == "health.incident" for e in evs)


def test_cg_budget_exhaustion_fires_one_numerics_dump(tmp_path):
    """The second injected numerics fault class: a CG starved of its
    iteration budget must yield exactly one ``numerics:cg_budget``
    dump, the cg_budget_exhausted counter, and a health event on the
    stream.solve span."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.obs import health as oh
    from pint_tpu.obs import metrics as om
    from pint_tpu.parallel.streaming import StreamingGLS

    model, toas = _north_star_shaped(seed=19, n=200)
    tracer = obs.configure(enabled=True, flight_dir=str(tmp_path))
    oh.configure(enabled=True)
    sg = StreamingGLS(model, toas, chunk=64, health=True)
    state = sg.accumulate(sg.th0, sg.tl0)
    out = sg.solve(state, budget=2)   # starved: cannot converge
    assert int(out[6]) >= 2           # it really hit the budget
    dumps = list(tmp_path.glob("flight-*numerics_cg_budget*.json"))
    assert len(dumps) == 1
    doc = _json.loads(dumps[0].read_text())
    assert doc["reason"] == "numerics:cg_budget"
    reg = om.get_registry()
    assert reg.total(
        "pint_tpu_health_cg_budget_exhausted_total") == 1
    assert reg.total("pint_tpu_health_incidents_total") >= 1
    path = str(tmp_path / "cg_trace.json")
    tracer.export(path)
    evs = _json.load(open(path, encoding="utf-8"))["traceEvents"]
    stream_spans = {e["args"]["span"] for e in evs
                    if e["name"] == "stream.solve"}
    hevs = [e for e in evs if e["name"] == "health"
            and e["args"].get("parent") in stream_spans]
    assert hevs and hevs[0]["args"]["ok"] is False
    assert "cg_budget" in (hevs[0]["args"].get("reasons") or "")


# ------------------------------------- lock sanitizer (ISSUE 18)


def test_lock_inversion_fires_one_lockorder_dump(tmp_path):
    """ISSUE-18 seeded concurrency fault #1: an A->B / B->A
    acquisition-order inversion under $PINT_TPU_LOCK_TRACE must
    produce EXACTLY ONE labeled ``lockorder:<edge>`` flight dump —
    repeating the inversion in-episode stays latched (the
    numerics:<reason> once-per-episode pattern)."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.obs import metrics as om
    from pint_tpu.runtime import locks

    obs.configure(enabled=True, flight_dir=str(tmp_path))
    locks.configure(enabled=True)
    a = locks.make_lock("chaos.A")
    b = locks.make_lock("chaos.B")
    with a:
        with b:
            pass
    for _ in range(4):  # the inversion, repeated: one incident
        with b:
            with a:
                pass
    dumps = list(tmp_path.glob("flight-*lockorder*.json"))
    assert len(dumps) == 1
    doc = _json.loads(dumps[0].read_text())
    assert doc["reason"] == "lockorder:chaos.B->chaos.A"
    assert locks.status()["cycles_fired"] == 1
    assert int(om.get_registry().total(
        "pint_tpu_lock_incidents_total")) == 1


def test_dispatch_under_engine_lock_fires_one_lockheld_dump(
        tmp_path):
    """ISSUE-18 seeded concurrency fault #2: a REAL supervised
    dispatch issued while the thread holds an engine-marked traced
    lock (the G16 part-3 bug, runtime edition) fires exactly one
    ``lockheld:<name>`` dump via the supervisor's
    check_dispatch_clear hook; the dispatch itself still completes
    (detection, not prevention) and a clear thread stays silent."""
    import json as _json

    from pint_tpu import obs
    from pint_tpu.obs import metrics as om
    from pint_tpu.runtime import locks

    obs.configure(enabled=True, flight_dir=str(tmp_path))
    locks.configure(enabled=True)
    eng = locks.make_rlock("serve.engine", engine=True)
    sup = DispatchSupervisor()
    with eng:
        assert sup.dispatch(lambda: 11, key="under_lock") == 11
        assert sup.dispatch(lambda: 12, key="under_lock") == 12
    dumps = list(tmp_path.glob("flight-*lockheld*.json"))
    assert len(dumps) == 1
    doc = _json.loads(dumps[0].read_text())
    assert doc["reason"] == "lockheld:serve.engine"
    assert locks.status()["held_fired"] == 1
    # released: further dispatches are clean, no second episode
    assert sup.dispatch(lambda: 13, key="under_lock") == 13
    assert len(list(tmp_path.glob("flight-*lockheld*.json"))) == 1
