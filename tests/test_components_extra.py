"""Tests for the extra component zoo (reference analogs:
tests/test_glitch.py, test_model_wave.py, test_wavex.py, test_fd.py,
test_solar_wind.py): parsing/routing, physical behavior, and fit
recovery where applicable."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

BASE = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 100.0 1
F1 -1e-15 1
PEPOCH 55000.0
POSEPOCH 55000.0
DM 30.0 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""


def _model(extra=""):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(BASE + extra))


def _sim(m, n=100, span=(54500, 55500), **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return make_fake_toas_uniform(span[0], span[1], n, m,
                                      error_us=1.0, **kw)


# ----------------------------------------------------------- glitch


def test_glitch_parsing_and_phase_step():
    m0 = _model()
    t = _sim(m0, n=200)
    m = _model("GLEP_1 55000.0\nGLPH_1 0.1\nGLF0_1 2e-8\nGLF1_1 -1e-16\n")
    assert m.components["Glitch"].glitch_ids == [1]
    ph1 = m.phase(t)
    ph0 = m0.phase(t)
    full1 = np.asarray(ph1.int) + np.asarray(ph1.frac)
    full0 = np.asarray(ph0.int) + np.asarray(ph0.frac)
    mjd = t.get_mjds()
    pre = mjd < 55000.0
    d = full1 - full0  # turns, unwrapped
    # before the glitch the difference is a pure constant (the TZR
    # anchor at 55000.1 is post-glitch, shifting all phases equally;
    # 1e-6 floor = f64 eps on the ~4e9-turn reconstructed phase)
    assert np.ptp(d[pre]) < 1e-6
    # phase step + spin-up after: offset from the pre-glitch level is
    # >= GLPH = 0.1 turns, growing with time (GLF0 term)
    dphi = d[~pre] - d[pre].mean()
    assert np.all(dphi > 0.09)
    assert dphi[-1] > dphi[0] + 1e-3


def test_glitch_decay_term():
    m = _model("GLEP_1 55000.0\nGLF0D_1 1e-8\nGLTD_1 100.0\n")
    m0 = _model()
    t = _sim(m0, n=200)
    r1 = np.asarray(Residuals(t, m, subtract_mean=False).time_resids)
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).time_resids)
    mjd = t.get_mjds()
    pre = mjd < 55000.0
    dphi = ((r1 - r0) - (r1 - r0)[pre].mean()) * 100.0
    # asymptote: GLF0D * tau = 1e-8 * 100*86400 = 0.0864 turns
    late = mjd > 55450
    np.testing.assert_allclose(dphi[late], 0.0864, rtol=0.02)
    assert np.all(np.abs(dphi[pre]) < 1e-9)


def test_glitch_requires_epoch():
    with pytest.raises(ValueError, match="GLEP"):
        _model("GLPH_1 0.1\n")


def test_glitch_fit_recovery():
    from pint_tpu.fitter import DownhillWLSFitter

    m = _model("GLEP_1 55000.0\nGLPH_1 0.0 1\nGLF0_1 1e-8 1\n")
    rng = np.random.default_rng(4)
    t = _sim(m, n=150, add_noise=True, rng=rng)
    truth = {"GLF0_1": 1e-8, "GLPH_1": 0.0}
    m.get_param("GLF0_1").add_delta(3e-10)
    m.invalidate_cache(params_only=True)
    f = DownhillWLSFitter(t, m)
    f.fit_toas(maxiter=15)
    for k, v in truth.items():
        err = f.errors[k]
        assert abs(m.get_param(k).value - v) < 5 * err, k


# ------------------------------------------------------------- wave


def test_wave_parsing_and_offsets():
    om = 2 * np.pi / 500.0  # rad/day, 500-day period
    m = _model(f"WAVEEPOCH 55000\nWAVE_OM {om:.10f}\n"
               "WAVE1 1e-5 -2e-5\nWAVE2 3e-6 0.0\n")
    comp = m.components["Wave"]
    assert comp.wave_ids == [1, 2]
    m0 = _model()
    t = _sim(m0, n=120)
    r1 = np.asarray(Residuals(t, m, subtract_mean=False).time_resids)
    r0 = np.asarray(Residuals(t, m0, subtract_mean=False).time_resids)
    def w(dt_days):
        return (1e-5 * np.sin(om * dt_days)
                - 2e-5 * np.cos(om * dt_days)
                + 3e-6 * np.sin(2 * om * dt_days))

    dt_days = t.get_mjds() - 55000.0
    # phase = -F0 w(t), anchored at the TZR epoch (55000.1): residuals
    # shift by -(w(t) - w(tzr))
    expect = -(w(dt_days) - w(0.1))
    # 1e-9 floor: expectation uses UTC days where the model uses TDB
    # (~69 s offset x dw/dt ~ 3e-10)
    np.testing.assert_allclose(r1 - r0, expect, atol=1e-9)


# ------------------------------------------------------------ wavex


def test_wavex_delay_and_fit():
    from pint_tpu.fitter import WLSFitter

    m = _model("WXEPOCH 55000\nWXFREQ_0001 0.002\n"
               "WXSIN_0001 5e-6 1\nWXCOS_0001 -3e-6 1\n")
    assert m.components["WaveX"].wavex_ids == [(1, "0001")]
    rng = np.random.default_rng(6)
    t = _sim(m, n=150, add_noise=True, rng=rng)
    truth = {"WXSIN_0001": 5e-6, "WXCOS_0001": -3e-6}
    m.get_param("WXSIN_0001").add_delta(2e-6)
    m.invalidate_cache(params_only=True)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    for k, v in truth.items():
        err = f.errors[k]
        assert abs(m.get_param(k).value - v) < 5 * err, k


def test_dmwavex_scales_with_frequency():
    m = _model("DMWXEPOCH 55000\nDMWXFREQ_0001 0.002\n"
               "DMWXSIN_0001 1e-3\nDMWXCOS_0001 0.0\n")
    m0 = _model()
    tA = _sim(m0, n=60, freq_mhz=1400.0)
    tB = _sim(m0, n=60, freq_mhz=700.0)
    dA = np.asarray(m.delay(tA)) - np.asarray(m0.delay(tA))
    dB = np.asarray(m.delay(tB)) - np.asarray(m0.delay(tB))
    # nu^-2 scaling: factor 4 at half the frequency (small deviations
    # from the Doppler-shifted barycentric frequency)
    np.testing.assert_allclose(dB / dA, 4.0, rtol=5e-3)


# --------------------------------------------------------------- FD


def test_fd_delay():
    m = _model("FD1 1e-5\nFD2 -3e-6\n")
    assert m.components["FD"].fd_ids == [1, 2]
    m0 = _model()
    t = _sim(m0, n=40, freq_mhz=700.0)
    d = np.asarray(m.delay(t)) - np.asarray(m0.delay(t))
    # barycentric freq ≈ 700 MHz (small doppler); ln(0.7) = -0.3567
    lf = np.log(0.7)
    expect = 1e-5 * lf - 3e-6 * lf * lf
    np.testing.assert_allclose(d, expect, rtol=1e-3)


# ------------------------------------------------------- solar wind


def test_solar_wind_conjunction_spike():
    """DM_sw peaks when the pulsar is nearest the Sun on the sky; an
    ecliptic-plane pulsar sees the spike once per year (SURVEY.md A.4
    oracle). Scale: NE_SW=8 cm^-3 gives ~6e-3 pc/cm^3 near rho~25deg."""
    m = _model("NE_SW 8.0\n")
    m0 = _model()
    t = _sim(m0, n=365, span=(55000, 55365))
    d = np.asarray(m.delay(t)) - np.asarray(m0.delay(t))
    assert np.all(d > 0)
    # one clear annual peak, contrast > 3x
    assert d.max() > 3 * np.median(d)
    # reasonable magnitude at 1400 MHz: delay = K*DM/nu^2;
    # median DM_sw ~ 1e-4..1e-2 pc/cm^3 → delay 0.2..20 us
    assert 1e-8 < np.median(d) < 1e-4


def test_solar_wind_fit_recovery():
    from pint_tpu.fitter import WLSFitter

    m = _model("NE_SW 8.0 1\n")
    rng = np.random.default_rng(12)
    t = _sim(m, n=200, span=(55000, 55730), add_noise=True, rng=rng)
    m.get_param("NE_SW").add_delta(2.0)
    m.invalidate_cache(params_only=True)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    err = f.errors["NE_SW"]
    assert abs(m.get_param("NE_SW").value - 8.0) < 5 * err


# ------------------------------------------------- par round trips


def test_extra_components_parfile_roundtrip():
    par_extra = ("GLEP_1 55000.0\nGLPH_1 0.1\nGLF0_1 2e-8\n"
                 "FD1 1e-5\nFD2 -3e-6\nNE_SW 8.0\n"
                 "WXEPOCH 55000\nWXFREQ_0001 0.002\n"
                 "WXSIN_0001 5e-6\nWXCOS_0001 -3e-6\n")
    m = _model(par_extra)
    out = m.as_parfile()
    m2 = get_model(io.StringIO(out))
    for nm in ("GLPH_1", "GLF0_1", "FD1", "FD2", "NE_SW",
               "WXFREQ_0001", "WXSIN_0001"):
        assert m2.get_param(nm).value == pytest.approx(
            m.get_param(nm).value, rel=1e-12), nm
