"""Precision upgrades: EOP (dUT1/polar motion) hooks and the
topocentric TDB-TT term (reference: astropy/IERS machinery the
reference leans on — SURVEY.md §2b liberfa row, A.3)."""

import warnings

import numpy as np
import pytest

from pint_tpu.time import frames


@pytest.fixture(autouse=True)
def _clean_eop():
    yield
    frames.clear_eop()


GBT = np.array([882589.65, -4924872.32, 3943729.35])


def test_dut1_rotates_position():
    utc = np.array([55000.0, 55000.3])
    tt = utc + 66.184 / 86400.0
    p0, v0 = frames.itrf_to_gcrs_posvel(GBT, utc, tt)
    dut1 = 0.3
    frames.set_eop(np.array([54000.0, 56000.0]),
                   np.array([dut1, dut1]))
    p1, v1 = frames.itrf_to_gcrs_posvel(GBT, utc, tt)
    d = np.linalg.norm(p1 - p0, axis=-1)
    # |dr| = omega * dut1 * rho (equatorial projection ~ 5e6 m)
    rho = np.hypot(GBT[0], GBT[1])
    expect = 7.292115e-5 * dut1 * rho
    np.testing.assert_allclose(d, expect, rtol=1e-3)


def test_polar_motion_shifts_position():
    utc = np.array([55000.0])
    tt = utc + 66.184 / 86400.0
    p0, _ = frames.itrf_to_gcrs_posvel(GBT, utc, tt)
    xp = 0.2  # arcsec
    frames.set_eop(np.array([54000.0, 56000.0]),
                   np.zeros(2), xp_arcsec=np.full(2, xp),
                   yp_arcsec=np.zeros(2))
    p1, _ = frames.itrf_to_gcrs_posvel(GBT, utc, tt)
    d = np.linalg.norm(p1 - p0)
    # small rotation: |dr| ~ xp * |r| (within a geometry factor)
    xr = xp * np.pi / 180 / 3600 * np.linalg.norm(GBT)
    assert 0.3 * xr < d < 1.5 * xr
    # interpolation outside the table holds edge values (no blowups)
    p2, _ = frames.itrf_to_gcrs_posvel(GBT, np.array([60000.0]),
                                       np.array([60000.001]))
    assert np.all(np.isfinite(p2))


def test_topocentric_tdb_term():
    """Ground-site TDB carries the Moyer (v_earth . r_obs)/c^2 term:
    diurnal, amplitude <= ~2.1 us, absent at the geocenter."""
    from pint_tpu.toa import get_TOAs_array

    # quarter-day sampling over two days resolves the diurnal
    mjds = 55000.0 + np.arange(0, 2, 0.125)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t_gbt = get_TOAs_array(mjds, obs="gbt", freqs=1400.0,
                               errors=1.0)
        t_geo = get_TOAs_array(mjds, obs="geocenter", freqs=1400.0,
                               errors=1.0)
    d_gbt = (t_gbt.tdb_day + t_gbt.tdb_frac[0] + t_gbt.tdb_frac[1]
             - t_gbt.get_mjds()) * 86400.0
    d_geo = (t_geo.tdb_day + t_geo.tdb_frac[0] + t_geo.tdb_frac[1]
             - t_geo.get_mjds()) * 86400.0
    topo = d_gbt - d_geo
    assert np.max(np.abs(topo)) < 2.3e-6
    assert np.max(np.abs(topo)) > 0.5e-6
    # diurnal: sign flips within a day
    assert topo.max() > 0 and topo.min() < 0
    # geocenter itself has no topocentric term: pure FB series there
    from pint_tpu.time import scales

    tt = scales.utc_mjd_to_tt_mjd(t_geo.mjd_day, t_geo.mjd_frac)
    fb = scales.tdb_minus_tt_seconds(t_geo.mjd_day
                                     + t_geo.mjd_frac[0])
    np.testing.assert_allclose(
        d_geo, 66.184 + fb, atol=5e-6)
