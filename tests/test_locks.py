"""runtime.locks — the traced-lock sanitizer (ISSUE 18, dynamic half
of the G16 concurrency plane; ARCHITECTURE.md "Concurrency
correctness plane").

Disarmed — the production default — the factories return BARE stdlib
primitives (the off state is the stdlib, not a wrapper with a
branch). Armed (`$PINT_TPU_LOCK_TRACE` / `locks.configure`) they
paint per-thread acquisition order into the process lock-order
graph, fire ONE labeled incident per episode (``lockorder:<edge>``
on an inversion, ``lockheld:<name>`` on a dispatch issued under an
engine lock) and record hold/wait histograms into the obs.metrics
registry. ``obs.reset()`` drops the graph, the latches and the
arming cache — the isolation contract the autouse fixture leans on.
The end-to-end seeded-fault oracles (flight dumps through a REAL
supervised dispatch) live in tests/test_runtime_faults.py.
"""

import threading

import pytest

from pint_tpu import obs
from pint_tpu.obs import metrics as om
from pint_tpu.runtime import locks


@pytest.fixture(autouse=True)
def clean_locks(monkeypatch):
    """Fresh graph/latches/arming cache per test; the env default
    must not leak in from the outer shell."""
    monkeypatch.delenv("PINT_TPU_LOCK_TRACE", raising=False)
    obs.reset()
    yield
    obs.reset()


# ------------------------------------------------- disarmed = stdlib


def test_disarmed_factories_return_bare_stdlib_primitives():
    """The production default: no wrapper, no branch — the exact
    stdlib types (bench's <1% north-star band is this property)."""
    locks.configure(enabled=False)
    lk = locks.make_lock("t.bare")
    rk = locks.make_rlock("t.bare_r")
    assert type(lk) is type(threading.Lock())
    assert type(rk) is type(threading.RLock())
    cv = locks.make_condition(rk)
    assert isinstance(cv, threading.Condition)
    with cv:
        cv.notify_all()
    # nothing painted: the bare primitives never touch the graph
    with lk:
        pass
    assert locks.status()["edges"] == 0
    assert locks.held_locks() == []


def test_env_default_is_disarmed():
    """No $PINT_TPU_LOCK_TRACE (the fixture guarantees it) and no
    configure override -> the lazy _armed() resolves to off."""
    assert type(locks.make_lock("t.env")) is type(threading.Lock())
    assert locks.status()["armed"] is False


# ------------------------------------------- armed graph + tracking


def test_armed_lock_paints_acquisition_order():
    locks.configure(enabled=True)
    a = locks.make_lock("t.A")
    b = locks.make_lock("t.B")
    assert isinstance(a, locks.TracedLock)
    with a:
        assert locks.held_locks() == ["t.A"]
        with b:
            assert locks.held_locks() == ["t.A", "t.B"]
    assert locks.held_locks() == []
    assert locks.lock_graph_edges() == {"t.A": ["t.B"]}
    st = locks.status()
    assert st["armed"] and st["cycles_fired"] == 0
    # hold-time histogram rides the registry
    assert "pint_tpu_lock_hold_seconds" in om.get_registry().render()


def test_reentrant_rlock_is_one_held_entry_no_self_edge():
    locks.configure(enabled=True)
    r = locks.make_rlock("t.R")
    with r:
        with r:  # re-acquire: bumps the count, paints nothing
            assert locks.held_locks() == ["t.R"]
        assert locks.held_locks() == ["t.R"]
    assert locks.held_locks() == []
    assert locks.lock_graph_edges() == {}


def test_sibling_instances_of_one_name_share_a_node():
    """Discipline is a property of the lock CLASS: two engines'
    `serve.engine` locks are one graph node, and nesting them is
    not a self-edge (no false inversion)."""
    locks.configure(enabled=True)
    a1 = locks.make_lock("t.same")
    a2 = locks.make_lock("t.same")
    with a1:
        with a2:
            pass
    assert locks.lock_graph_edges() == {}
    assert locks.status()["cycles_fired"] == 0


def test_condition_protocol_over_traced_rlock():
    """threading.Condition(TracedRLock): wait() fully releases
    through _release_save (the held entry drops so a waiter does not
    hold the engine node) and re-registers via _acquire_restore."""
    locks.configure(enabled=True)
    cv = locks.make_condition(locks.make_rlock("t.cv"))
    state = {"woke": False, "held_in_wait": None}

    def waiter():
        with cv:
            cv.wait(timeout=5)
            state["woke"] = True
            state["held_in_wait"] = locks.held_locks()

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    # hand the cv to the waiter, then notify
    for _ in range(500):
        with cv:
            cv.notify_all()
        th.join(timeout=0.01)
        if not th.is_alive():
            break
    th.join(timeout=5)
    assert not th.is_alive() and state["woke"]
    assert state["held_in_wait"] == ["t.cv"]
    assert locks.held_locks() == []


# -------------------------------------------------- incident firing


def test_inversion_fires_exactly_one_incident_per_episode(tmp_path):
    obs.configure(enabled=True, flight_dir=str(tmp_path))
    locks.configure(enabled=True)
    a = locks.make_lock("t.A")
    b = locks.make_lock("t.B")
    with a:
        with b:
            pass
    for _ in range(3):  # repeat the inversion: latched after one
        with b:
            with a:
                pass
    st = locks.status()
    assert st["cycles_fired"] == 1
    assert int(om.get_registry().total(
        "pint_tpu_lock_incidents_total")) == 1
    dumps = list(tmp_path.glob("flight-*lockorder*.json"))
    assert len(dumps) == 1


def test_obs_reset_drops_graph_latches_and_rearms():
    locks.configure(enabled=True)
    a = locks.make_lock("t.A")
    b = locks.make_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert locks.status()["cycles_fired"] == 1
    obs.reset()  # new episode: graph + latches + arming cache gone
    assert locks.status() == {"armed": False, "edges": 0, "nodes": 0,
                              "cycles_fired": 0, "held_fired": 0}
    locks.configure(enabled=True)
    # existing traced locks keep working and repaint a fresh graph
    with b:
        with a:
            pass
    with a:
        with b:
            pass
    assert locks.status()["cycles_fired"] == 1


def test_check_dispatch_clear_fires_once_per_lock_name():
    locks.configure(enabled=True)
    eng = locks.make_rlock("t.engine", engine=True)
    leaf = locks.make_lock("t.leaf")  # non-engine: never flags
    assert locks.check_dispatch_clear("t") is True
    with leaf:
        assert locks.check_dispatch_clear("t") is True
    with eng:
        assert locks.check_dispatch_clear("t") is False
        assert locks.check_dispatch_clear("t") is False  # latched
    assert locks.status()["held_fired"] == 1
    assert int(om.get_registry().total(
        "pint_tpu_lock_incidents_total")) == 1
    assert locks.check_dispatch_clear("t") is True  # released


def test_contention_wait_rides_the_registry_histogram():
    locks.configure(enabled=True)
    lk = locks.make_lock("t.cont")
    lk.acquire()
    state = {}

    def contender():
        with lk:
            state["got"] = True

    th = threading.Thread(target=contender, daemon=True)
    th.start()
    th.join(timeout=0.05)  # let it block on the held lock
    lk.release()
    th.join(timeout=5)
    assert state.get("got")
    assert "pint_tpu_lock_wait_seconds" in om.get_registry().render()


# --------------------- watcher single-instance guard (shell level)


def test_tpu_watcher_double_launch_one_survivor(tmp_path):
    """Process-level mutual exclusion for tools/tpu_watcher.sh: two
    launches leave EXACTLY ONE survivor — the second sees the held
    flock and exits 0 immediately with a log line saying so (a
    respawned watcher must never race a live one over the stage
    list: double-append + double-commit of ledger lines). The script
    is copied into a tmp repo dir so its repo-local lockfile is
    isolated from any real watcher on this machine, and a fake
    `python` shim (exit 7) keeps the survivor inert in its
    probe-failed sleep loop — no jax, no git."""
    import os
    import shutil
    import subprocess
    import time

    if shutil.which("flock") is None:
        pytest.skip("flock unavailable")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fake_repo = tmp_path / "repo"
    (fake_repo / "tools").mkdir(parents=True)
    script = fake_repo / "tools" / "tpu_watcher.sh"
    shutil.copy(os.path.join(repo, "tools", "tpu_watcher.sh"), script)
    shim = tmp_path / "bin"
    shim.mkdir()
    (shim / "python").write_text("#!/bin/sh\nexit 7\n")
    (shim / "python").chmod(0o755)
    env = dict(os.environ, PATH=f"{shim}:{os.environ['PATH']}",
               SLEEP_S="60", PROBE_TIMEOUT="5")
    p1 = subprocess.Popen(["bash", str(script)], env=env,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL)
    try:
        lockfile = fake_repo / ".tpu_watcher.lock"
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            assert p1.poll() is None, \
                "first watcher exited instead of holding the lock"
            probe = subprocess.run(
                ["flock", "-n", str(lockfile), "true"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            if probe.returncode != 0:
                break  # p1 owns the flock
            time.sleep(0.1)
        else:
            pytest.fail("first watcher never took the lockfile")
        second = subprocess.run(["bash", str(script)], env=env,
                                timeout=30, capture_output=True,
                                text=True)
        assert second.returncode == 0
        assert p1.poll() is None, "the survivor died"
        with open("/tmp/tpu_watcher_repo.log") as fh:
            assert "another tpu_watcher holds" in fh.read()
    finally:
        p1.terminate()
        try:
            p1.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p1.kill()
