"""Runtime sanitizer: the first direct test of the CLAUDE.md
invariant "invalidate_cache(params_only=True) must NOT drop the jit".
A regression here (value updates re-tracing the phase chain) once
cost a full retrace per fitter iteration and no test failed — now the
compile count is asserted, at both the model layer
(TimingModel._get_compiled via Sanitizer) and the executable layer
(jax.jit cache size on the production fit step)."""

import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu.analysis import Sanitizer
from pint_tpu.analysis.sanitizer import SanitizerError
from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

PAR = """PSR J1234+5678
RAJ 12:34:00.0 1
DECJ 56:47:00.0 1
F0 250.0123456789 1
F1 -2.0e-15 1
DM 15.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.05
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def _problem(n=120):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        toas = make_fake_toas_uniform(
            54500, 55500, n, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n // 2),
            add_noise=True, rng=np.random.default_rng(7))
    # simulation warms the compiled phase — start the tests cold so
    # build counts are deterministic (first evaluation == build 1)
    model.invalidate_cache()
    return model, toas


def test_params_only_sweep_compiles_once():
    """3-value parameter sweep with params_only invalidation: exactly
    ONE phase build, however many evaluations."""
    model, toas = _problem()
    with Sanitizer() as san:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            Residuals(toas, model).time_resids
            for delta in (1e-11, 1e-11, -2e-11):
                model.F0.add_delta(delta)
                model.invalidate_cache(params_only=True)
                Residuals(toas, model).time_resids
    assert san.compiles("phase") == 1, san.builds


def test_structure_change_bumps_compile_count():
    """Freezing a parameter changes the free set (a trace static) —
    the sanitizer must see a SECOND build; a full invalidate_cache()
    likewise drops the jit."""
    model, toas = _problem()
    with Sanitizer() as san:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            Residuals(toas, model).time_resids
            model.F1.frozen = True  # structure change
            model.invalidate_cache(params_only=True)
            Residuals(toas, model).time_resids
            assert san.compiles("phase") == 2, san.builds
            model.invalidate_cache()  # full drop: retrace expected
            Residuals(toas, model).time_resids
    assert san.compiles("phase") == 3, san.builds


def test_production_fit_step_recompile_free():
    """ISSUE 3 acceptance: the production fit step's executable cache
    stays at ONE entry across a 3-value parameter sweep (values enter
    as runtime args; the trace must not re-key)."""
    model, toas = _problem()
    step_fn, args, names = build_fit_step(model, toas)
    jitted = jax.jit(step_fn)
    out = jitted(*args)
    jax.block_until_ready(out)
    assert jitted._cache_size() == 1
    san = Sanitizer()
    san.watch(jitted, "fit_step")
    for delta in (1e-11, 2e-11, -3e-11):
        model.F0.add_delta(delta)
        model.invalidate_cache(params_only=True)
        _, _, th, tl, fh, fl = model._pack()
        new_args = (jnp.asarray(th), jnp.asarray(tl),
                    jnp.asarray(fh), jnp.asarray(fl)) + args[4:]
        out = jitted(*new_args)
    jax.block_until_ready(out)
    assert jitted._cache_size() == 1
    assert san.executable_growth()["fit_step"] == 0
    # a changed operand STRUCTURE (dtype here) is a legitimate new
    # executable — the counter must see it, or it could never have
    # caught the regression in the first place
    jitted(jnp.asarray(th, jnp.float32), *new_args[1:])
    assert jitted._cache_size() == 2
    assert san.executable_growth()["fit_step"] == 1


def test_wrap_flags_host_operands_and_nans():
    san = Sanitizer(nan_check=True)

    def dispatch(x):
        return x * 2.0

    guarded = san.wrap(dispatch, "d")
    guarded(jnp.ones(3))
    assert not san.host_crossings
    san.assert_no_host_crossings()
    guarded(np.ones(3))  # host ndarray crossing into a dispatch
    assert san.host_crossings == [("d", 1)]
    with pytest.raises(SanitizerError):
        san.assert_no_host_crossings()
    bad = san.wrap(lambda: jnp.array([np.nan]), "nanfn")
    with pytest.raises(SanitizerError):
        bad()


def test_wrap_walks_nested_pytree_and_opaque_operands():
    """Regression (ISSUE 6 satellite): the host-operand scan must
    descend NESTED structures — dicts/tuples of operands reach the
    serve bucket dispatch — including objects that are not registered
    pytrees (request/entry dataclasses), which tree_leaves treats as
    one opaque leaf, hiding their member arrays entirely."""
    import types

    san = Sanitizer()
    guarded = san.wrap(lambda *a, **k: 0, "nested")
    # nested dict/tuple pytree operands: 3 host arrays
    guarded({"M": np.ones(3), "aux": (np.ones(2), jnp.ones(2))},
            extra=[np.ones(1)])
    assert san.host_crossings == [("nested", 3)]
    san.reset()
    # an opaque (non-pytree) request-like object hiding arrays —
    # jax.tree_util.tree_leaves sees ONE leaf (the object) and zero
    # ndarrays; the walker must find both
    req = types.SimpleNamespace(mjds=np.ones(4),
                                entry=types.SimpleNamespace(
                                    coeffs=np.ones(5), f0=1.0))
    guarded(req)
    assert san.host_crossings == [("nested", 2)]
    san.reset()
    # np.ndarray SUBCLASSES count too (the old check used `type is`)
    guarded(np.ones((2, 2)).view(np.matrix))
    assert san.host_crossings == [("nested", 1)]
    san.reset()
    # device arrays, scalars and strings never count
    guarded(jnp.ones(3), 1.0, "label", flag=True)
    assert not san.host_crossings


def test_recompile_guard_fixture(recompile_guard):
    """The conftest fixture wires a Sanitizer around the test body."""
    model, toas = _problem(60)
    recompile_guard.reset()  # _problem's simulation warm-up counted
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        Residuals(toas, model).time_resids
        model.DM.add_delta(1e-6)
        model.invalidate_cache(params_only=True)
        Residuals(toas, model).time_resids
    assert recompile_guard.compiles("phase") == 1
