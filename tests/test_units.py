"""Build-time unit discipline (SURVEY §5 last open row): parameter
unit strings are checked against per-component dimension specs at
model-build time — a component wired with wrong units fails before
anything is traced, with a clear error."""

import io
import warnings

import pytest

from pint_tpu.models import get_model
from pint_tpu.models.spindown import Spindown
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.units import (
    DIMENSIONLESS,
    UnitError,
    check_model_units,
    parse_unit,
)


class TestUnitAlgebra:
    @pytest.mark.parametrize("a,b", [
        ("s", "sec"), ("d", "MJD"), ("Hz", "1/s"),
        ("pc cm^-3 / yr^2", "pc cm-3 yr^-2"),
        ("mas/yr", "rad / s"),      # same dimension, different scale
        ("ls/s", ""),               # lt-s is time-valued: T/T = 1
        ("Hz/s^2", "s^-3"),
    ])
    def test_equivalent_dimensions(self, a, b):
        assert parse_unit(a) == parse_unit(b)

    @pytest.mark.parametrize("a,b", [
        ("s", "Hz"), ("d", "deg"), ("pc cm^-3", "pc"),
        ("Hz/s", "Hz/s^2"), ("Msun", "s"),
    ])
    def test_distinct_dimensions(self, a, b):
        assert parse_unit(a) != parse_unit(b)

    def test_dimensionless_forms(self):
        for t in (None, "", "1", "s/s"):
            assert parse_unit(t) == DIMENSIONLESS

    def test_unknown_atom_raises(self):
        with pytest.raises(UnitError, match="unknown unit atom"):
            parse_unit("furlong/fortnight")


class TestModelUnitCheck:
    def test_wrong_units_component_fails_at_build(self):
        """The 'Done' criterion: a deliberately-wrong-units component
        fails at build time with a clear error."""

        class BadSpindown(Spindown):
            register = False

            def __init__(self):
                super().__init__()
                # F1 in Hz (should be Hz/s): the classic ladder slip
                self.params["F1"].units = "Hz"

        comp = BadSpindown()
        comp.F0.value = 100.0
        comp.params["F1"].value = -1e-15
        m = TimingModel([comp])
        comp.params["PEPOCH"].value = 55000.0
        with pytest.raises(UnitError, match="F1.*requires"):
            m.validate()

    def test_epoch_in_wrong_units_fails(self):
        class BadEpoch(Spindown):
            register = False

            def __init__(self):
                super().__init__()
                self.params["PEPOCH"].units = "yr^2"

        comp = BadEpoch()
        comp.F0.value = 100.0
        comp.params["PEPOCH"].value = 55000.0
        m = TimingModel([comp])
        with pytest.raises(UnitError, match="PEPOCH"):
            m.validate()

    def test_real_models_pass(self):
        """Every registered family used together validates — the spec
        and the actual parameter declarations agree."""
        par = """PSR J0
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
PMRA 2.0 1
PMDEC -3.0 1
PX 1.2 1
F0 300.1 1
F1 -1e-15 1
F2 1e-26 1
DM 20.0 1
DM1 1e-4 1
DMX_0001 0.0 1
DMXR1_0001 53000
DMXR2_0001 57000
PEPOCH 55000
POSEPOCH 55000
DMEPOCH 55000
UNITS TDB
BINARY BT_piecewise
PB 1.2
A1 3.5
T0 55000.2
ECC 0.01
OM 40.0
T0X_0001 55000.2002 1
XR1_0001 54800
XR2_0001 55200
"""
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(io.StringIO(par))
        check_model_units(m)  # idempotent re-check


class TestParserExtensions:
    def test_sqrt_and_log10_forms(self):
        # (note: '/' always splits division, so fractional exponents
        # must be decimal: 'yr^-0.5', not 'yr^-1/2')
        assert parse_unit("us/sqrt(yr)") == parse_unit("us yr^-0.5")
        assert parse_unit("sqrt(s)") == parse_unit("s^0.5")
        assert parse_unit("sqrt(s)") != parse_unit("s")
        for t in ("log10", "log10(s)", "log10(strain)", "strain"):
            assert parse_unit(t) == DIMENSIONLESS, t

    def test_mask_units_match_component_declarations(self):
        """MASK_UNITS (the par-file builder's table) must stay in sync
        with each component's own add_* declaration — the drift hazard
        of having two declaration sites, made a checked invariant."""
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.model_builder import MASK_UNITS
        from pint_tpu.models.noise import EcorrNoise, ScaleToaError

        ste = ScaleToaError()
        for pre in ("EFAC", "EQUAD", "TNEQ"):
            p = ste.add_noise_param(pre, "-be", "X", 1.0)
            assert parse_unit(p.units) == parse_unit(
                MASK_UNITS[pre]), pre
        p = EcorrNoise().add_ecorr("-be", "X", 1.0)
        assert parse_unit(p.units) == parse_unit(MASK_UNITS["ECORR"])
        jp = PhaseJump().add_jump(key="-be", key_value=("X",),
                                  value=0.0)
        assert parse_unit(jp.units) == parse_unit(MASK_UNITS["JUMP"])
