"""Serving-layer oracle + behavior tests (ISSUE 2).

The load-bearing invariant: a coalesced batched dispatch must be
equivalent to sequential single-request execution within the repo's
existing oracle budgets (rtol 1e-9 on GLS outputs — XLA compiles a
distinct executable per batch size, so fusion/reduction order is not
bit-stable across batch shapes; <10 ps of phase on the polyco path,
where FMA fusion wobbles the last ulp) — while the executable count
stays bounded by the shape-class count, never the request count.
"""

import io
import time
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.polycos import PolycoEntry
from pint_tpu.serve import (
    DeadlineExceeded,
    FitStepRequest,
    PhasePredictRequest,
    ResidualsRequest,
    ServeEngine,
    ServeOverload,
)
from pint_tpu.simulation import make_fake_toas_uniform

# 10 ps expressed in turns at this f0 — the repo-wide phase budget
F0_DEMO = 200.0
TEN_PS_TURNS = 1e-11 * F0_DEMO


def _mk(k, ntoa, noise=False):
    extra = "EFAC -be X 1.2\nECORR -be X 1.0\n" if noise else ""
    par = (f"PSR J{1200 + k}\nRAJ 12:0{k % 10}:00.0 1\n"
           f"DECJ 30:0{k % 10}:00.0 1\nF0 {150.0 + 31.0 * k} 1\n"
           f"F1 -1e-15 1\nPEPOCH 55000\nPOSEPOCH 55000\n"
           f"DM {10 + k} 1\nTZRMJD 55000.1\nTZRSITE @\nTZRFRQ 1400\n"
           f"UNITS TDB\n{extra}")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54000, 56000, ntoa, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(k))
        if noise:
            for f in t.flags:
                f["be"] = "X"
    m.F0.add_delta(1e-10)
    m.invalidate_cache(params_only=True)
    return m, t


@pytest.fixture(scope="module")
def zoo():
    """Six pulsars across three TOA buckets (64/128/256), one with a
    correlated-noise basis so GLS classes differ in q too."""
    return [_mk(0, 50), _mk(1, 60), _mk(2, 100), _mk(3, 120),
            _mk(4, 200), _mk(5, 90, noise=True)]


def _entry(seed=0):
    return PolycoEntry(
        psrname="DEMO", tmid=55000.0 + seed, rphase_int=1e9,
        rphase_frac=0.25, f0=F0_DEMO, obs="@", span_min=60.0,
        coeffs=np.array([0.02, 1e-3, -2e-5, 1e-7]))


def _mixed_requests(zoo):
    reqs = []
    for m, t in zoo:
        reqs.append(FitStepRequest(t, m))
        reqs.append(ResidualsRequest(t, m))
    for s in range(3):
        mjds = 55000.0 + s + np.linspace(-0.01, 0.01, 16 + 8 * s)
        reqs.append(PhasePredictRequest(_entry(s), mjds))
    return reqs


def _clone(req):
    if isinstance(req, PhasePredictRequest):
        return PhasePredictRequest(req.entry, req.mjds)
    return type(req)(req.toas, req.model)


def test_coalesced_matches_sequential(zoo):
    """The acceptance oracle: one coalesced flush == one-at-a-time
    dispatch, across >= 3 TOA buckets and all three request kinds,
    with executables bounded by the shape-class count."""
    reqs = _mixed_requests(zoo)
    seq = ServeEngine()
    seq_res = []
    for r in reqs:
        fut = seq.submit(_clone(r))
        seq.flush()  # every request dispatches alone
        seq_res.append(fut.result(timeout=0))

    co = ServeEngine()
    futs = [co.submit(r) for r in reqs]
    co.flush()  # everything coalesces
    co_res = [f.result(timeout=0) for f in futs]

    for a, b in zip(co_res, seq_res):
        if hasattr(a, "phase_int"):
            tot = (np.asarray(a.phase_int) - np.asarray(b.phase_int)) \
                + (np.asarray(a.phase_frac) - np.asarray(b.phase_frac))
            assert np.all(np.abs(tot) < TEN_PS_TURNS)
        elif hasattr(a, "dparams"):
            np.testing.assert_allclose(a.dparams, b.dparams,
                                       rtol=1e-9, atol=1e-18)
            np.testing.assert_allclose(np.diag(a.cov), np.diag(b.cov),
                                       rtol=1e-9)
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-9)
            assert a.chi2r == pytest.approx(b.chi2r, rel=1e-9)
        else:
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-9)
            # host-assembled residual vector: genuinely identical
            np.testing.assert_array_equal(a.time_resids, b.time_resids)

    snap = co.metrics.snapshot()
    assert snap["completed"] == len(reqs)
    # >= 3 distinct GLS TOA buckets were exercised
    gls_buckets = {k[1] for k in co.metrics.buckets if k[0] == "gls"}
    assert len(gls_buckets) >= 3
    # the bound the subsystem exists for
    assert snap["compile_count"] <= snap["bucket_count"]
    assert snap["compile_count"] < len(reqs)
    # coalescing actually coalesced: fewer dispatches than requests
    assert sum(b.batches for b in co.metrics.buckets.values()) \
        < len(reqs)
    # engine-attributed jit cache agrees with the class accounting
    jit_n = co.cache.jit_cache_size()
    if jit_n is not None:
        assert jit_n <= snap["compile_count"]


def test_pipelined_drain_matches_sync(zoo):
    """ISSUE 7: the double-buffered drain (pipeline_depth > 1, the
    next shape-class batch issued while the current one executes)
    must be result-equivalent to the synchronous drain, actually
    keep >= 2 dispatches in flight, and label its configuration in
    the metrics snapshot."""
    reqs = _mixed_requests(zoo)
    sync = ServeEngine(pipeline_depth=1)
    futs = [sync.submit(_clone(r)) for r in reqs]
    sync.flush()
    sync_res = [f.result(timeout=0) for f in futs]

    pipe = ServeEngine(pipeline_depth=3)
    futs = [pipe.submit(r) for r in reqs]
    pipe.flush()
    pipe_res = [f.result(timeout=0) for f in futs]

    for a, b in zip(pipe_res, sync_res):
        if hasattr(a, "phase_int"):
            tot = (np.asarray(a.phase_int) - np.asarray(b.phase_int)) \
                + (np.asarray(a.phase_frac) - np.asarray(b.phase_frac))
            assert np.all(np.abs(tot) < TEN_PS_TURNS)
        elif hasattr(a, "dparams"):
            np.testing.assert_allclose(a.dparams, b.dparams,
                                       rtol=1e-9, atol=1e-18)
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-9)
        else:
            assert a.chi2 == pytest.approx(b.chi2, rel=1e-9)

    snap = pipe.metrics.snapshot()
    assert snap["completed"] == len(reqs)
    assert snap["pipeline_depth"] == 3
    # the drain really pipelined: >= 2 dispatches were in flight
    assert snap["dispatch"]["max_inflight"] >= 2
    assert snap["dispatch"]["async_dispatches"] >= 2
    # the sync engine never pipelined anything
    assert sync.metrics.snapshot()["dispatch"]["async_dispatches"] == 0
    # donation state is labeled either way
    assert isinstance(snap["donation"], bool)


def test_serve_matches_host_oracles(zoo):
    """Served results vs the single-pulsar host oracles: fit step vs
    gls._gls_kernel, residuals chi2 vs Residuals.chi2, phase vs
    PolycoEntry.abs_phase."""
    import jax.numpy as jnp

    from pint_tpu.gls import _gls_kernel
    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.residuals import Residuals

    eng = ServeEngine()
    m, t = zoo[2]
    mjds = 55000.0 + np.linspace(-0.01, 0.01, 24)
    f_fit = eng.submit(FitStepRequest(t, m))
    f_res = eng.submit(ResidualsRequest(t, m))
    f_ph = eng.submit(PhasePredictRequest(_entry(), mjds))
    eng.flush()

    pr = build_problem(t, m)
    x, cov, chi2, _, _, ok = _gls_kernel(
        jnp.asarray(pr.M), jnp.asarray(pr.F), jnp.asarray(pr.phi),
        jnp.asarray(pr.r), jnp.asarray(pr.nvec))
    assert bool(ok)
    rf = f_fit.result(timeout=0)
    np.testing.assert_allclose(rf.dparams, -np.asarray(x),
                               rtol=1e-8, atol=1e-15)
    np.testing.assert_allclose(np.diag(rf.cov), np.diag(np.asarray(cov)),
                               rtol=1e-8)
    assert rf.chi2 == pytest.approx(float(chi2), rel=1e-8)

    rr = f_res.result(timeout=0)
    host = Residuals(t, m)
    assert rr.chi2 == pytest.approx(host.chi2, rel=1e-8)
    np.testing.assert_allclose(rr.time_resids, host.calc_time_resids(),
                               rtol=0, atol=1e-12)

    rp = f_ph.result(timeout=0)
    pi, pf = _entry().abs_phase(mjds)
    tot = (np.asarray(rp.phase_int) - pi) \
        + (np.asarray(rp.phase_frac) - pf)
    assert np.all(np.abs(tot) < TEN_PS_TURNS)


def test_compile_count_stays_bounded_under_traffic(zoo):
    """Many distinct request sizes, few shape classes: repeat mixed
    traffic through one engine and assert the executable count never
    tracks the request count."""
    eng = ServeEngine()
    futs = []
    for rep in range(3):
        for m, t in zoo:
            futs.append(eng.submit(FitStepRequest(t, m)))
        eng.flush()
    for f in futs:
        f.result(timeout=0)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == 3 * len(zoo)
    assert snap["compile_count"] <= snap["bucket_count"]
    assert snap["compile_count"] <= 4  # 3 white buckets + 1 noise class


def test_backpressure_queue_cap(zoo):
    m, t = zoo[0]
    eng = ServeEngine(queue_cap=2)
    eng.submit(FitStepRequest(t, m))
    eng.submit(ResidualsRequest(t, m))
    with pytest.raises(ServeOverload):
        eng.submit(FitStepRequest(t, m))
    assert eng.metrics.rejected == 1
    eng.flush()
    assert eng.metrics.completed == 2


def test_deadline_expires_in_queue(zoo):
    m, t = zoo[0]
    eng = ServeEngine()
    fut = eng.submit(FitStepRequest(t, m, deadline_s=1e-4))
    live = eng.submit(ResidualsRequest(t, m))
    time.sleep(0.02)
    eng.flush()
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    assert live.result(timeout=0).chi2 > 0
    assert eng.metrics.deadline_missed == 1


def test_oversize_falls_back_to_single(zoo):
    """A request bigger than every bucket edge is still served (at
    the next power-of-two shape) and still matches the oracle."""
    m, t = zoo[4]  # 200 TOAs
    eng = ServeEngine(bucket_edges=(64,))
    small_m, small_t = zoo[0]
    futs = [eng.submit(FitStepRequest(t, m)),
            eng.submit(FitStepRequest(small_t, small_m))]
    eng.flush()
    big = futs[0].result(timeout=0)
    ref_eng = ServeEngine()
    ref = ref_eng.submit(FitStepRequest(t, m)).result()
    np.testing.assert_array_equal(big.dparams, ref.dparams)
    assert eng.metrics.fallback_single == 1
    assert futs[1].result(timeout=0).chi2 > 0


def test_oversize_shared_class_coalesces(zoo):
    """ISSUE-4 satellite: oversize requests landing on the SAME
    fallback shape class share ONE padded dispatch instead of going
    one-at-a-time; the executable bound (<= bucket count + oversize
    classes) holds and results still match a dedicated engine."""
    m, t = zoo[4]  # 200 TOAs > the only configured edge
    eng = ServeEngine(bucket_edges=(64,))
    futs = [eng.submit(FitStepRequest(t, m)) for _ in range(3)]
    eng.flush()
    res = [f.result(timeout=0) for f in futs]
    assert eng.metrics.fallback_single == 3
    # exactly one dispatch served the whole shared oversize class
    fb = [b for k, b in eng.metrics.buckets.items() if k[1] == 256]
    assert len(fb) == 1 and fb[0].batches == 1 and fb[0].requests == 3
    snap = eng.metrics.snapshot()
    assert snap["compile_count"] <= snap["bucket_count"]
    ref = ServeEngine().submit(FitStepRequest(t, m)).result()
    for r in res:
        np.testing.assert_allclose(r.dparams, ref.dparams,
                                   rtol=1e-9, atol=1e-18)
        assert r.chi2 == pytest.approx(ref.chi2, rel=1e-9)


def test_mesh_engine_matches_local(zoo):
    """An engine sharding the batch axis over the 8-virtual-device
    mesh agrees with the local engine (same tolerance as the pta
    mesh test)."""
    import jax
    from jax.sharding import Mesh

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("pulsar",))
    local = ServeEngine()
    sharded = ServeEngine(mesh=mesh)
    for eng in (local, sharded):
        eng.futs = [eng.submit(FitStepRequest(t, m))
                    for m, t in zoo[:4]]
        eng.flush()
    for fl, fs in zip(local.futs, sharded.futs):
        a, b = fl.result(timeout=0), fs.result(timeout=0)
        np.testing.assert_allclose(a.dparams, b.dparams,
                                   rtol=1e-9, atol=1e-18)
        assert a.chi2 == pytest.approx(b.chi2, rel=1e-9)
    # batch axis padded to a mesh multiple
    assert all(k[-1] % ndev == 0 for k in sharded.metrics.buckets)


def test_threaded_engine_coalesces(zoo):
    """start()/stop() loop: a burst submitted while the loop holds
    the window open lands in few dispatches and every future
    resolves."""
    eng = ServeEngine(window_s=0.05).start()
    try:
        futs = [eng.submit(FitStepRequest(t, m))
                for m, t in zoo[:4] for _ in range(2)]
        res = [f.result(timeout=30) for f in futs]
    finally:
        eng.stop()
    assert all(np.isfinite(r.chi2) for r in res)
    assert eng.metrics.completed == len(futs)


def test_fitter_auto_serve_route(zoo):
    """Fitter.auto(serve=engine) fits through the engine and lands on
    the same parameters as the direct batched fitter (fit_pta)."""
    import copy

    from pint_tpu.fitter import Fitter
    from pint_tpu.parallel import fit_pta
    from pint_tpu.serve.scheduler import ServeGLSFitter

    m, t = _mk(7, 80)
    m_ref = copy.deepcopy(m)
    eng = ServeEngine()
    f = Fitter.auto(t, m, serve=eng)
    assert isinstance(f, ServeGLSFitter)
    chi2 = f.fit_toas(maxiter=3)
    ref = fit_pta([(t, m_ref)], maxiter=3)
    # serve reports chi2 AT the fitted point (Residuals.chi2
    # semantics); fit_pta reports the final linearized post-fit chi2
    # — distinct quantities that coincide only at convergence
    assert chi2 == pytest.approx(ref[0]["chi2"], rel=1e-6)
    for name in m.free_params:
        err = ref[0]["errors"][name]
        assert abs(m.get_param(name).value
                   - m_ref.get_param(name).value) < 1e-6 * err, name
        assert f.errors[name] == pytest.approx(err, rel=1e-6)
    with pytest.raises(ValueError, match="exclusive"):
        Fitter.auto(t, m, serve=eng, device=True)


def test_fitter_serve_rejects_wideband(zoo):
    """Wideband TOAs must NOT be silently fit narrowband-only
    through the serve route."""
    from pint_tpu.fitter import Fitter

    m, t = _mk(8, 40)
    for f in t.flags:
        f["pp_dm"] = "1.0e-4"
        f["pp_dme"] = "1.0e-5"
    eng = ServeEngine()
    with pytest.raises(ValueError, match="wideband"):
        Fitter.auto(t, m, serve=eng)


def test_empty_engine_snapshot_is_strict_json():
    """An engine that served nothing must still emit parseable JSON
    (percentiles null, not the bare NaN token)."""
    import json

    eng = ServeEngine()
    snap = json.loads(eng.metrics.to_json())
    assert snap["p50_ms"] is None and snap["p99_ms"] is None
    assert snap["completed"] == 0
    eng.metrics.report()  # must not raise either


def test_failed_dispatch_does_not_count_a_compile(zoo):
    """A dispatch that raises must fail its group's futures without
    recording a shape class the cache never built."""
    m, t = zoo[0]
    eng = ServeEngine()
    eng.cache._gls = None  # force the dispatch to blow up
    fut = eng.submit(FitStepRequest(t, m))
    eng.flush()
    with pytest.raises(TypeError):
        fut.result(timeout=0)
    assert eng.metrics.failed == 1
    assert eng.metrics.compile_count == 0


def test_daemon_demo_smoke(capsys):
    """scripts/pint_serve --demo: every synthesized request answers
    ok and the session snapshot keeps the executable bound."""
    import json

    from pint_tpu.scripts.pint_serve import main

    assert main(["--demo", "12", "--window-ms", "2"]) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    snap = lines[-1]
    assert snap["metric"] == "serve_session"
    results = [x for x in lines[:-1]]
    assert len(results) == 12 and all(r["ok"] for r in results)
    assert snap["completed"] == 12
    assert snap["compile_count"] <= snap["bucket_count"]


def test_daemon_demo_sheds_overload_instead_of_crashing(capsys):
    """PR-3 review bug (deferred to ISSUE 4): demo mode left
    ServeOverload unhandled — a backpressured submit crashed the
    daemon. Queue cap 1 + a long window guarantees the burst
    overloads; every request must still get a result line (ok or a
    shed report) and the session snapshot must still print LAST."""
    import json

    from pint_tpu.scripts.pint_serve import main

    assert main(["--demo", "12", "--queue-cap", "1",
                 "--window-ms", "60"]) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    snap = lines[-1]
    assert snap["metric"] == "serve_session"
    results = lines[:-1]
    assert len(results) == 12
    shed = [r for r in results if not r["ok"]]
    assert all("ServeOverload" in r["error"] for r in shed)
    assert snap["completed"] == 12 - len(shed)
    assert snap["rejected"] == len(shed)


def test_phase_partial_submit_counts_semaphore_correctly():
    """PR-3 review bug (deferred to ISSUE 4): a phase request fanning
    out over several polyco segments that hit backpressure MID-FAN
    returned a count excluding the already-admitted segments, so the
    daemon's pending semaphore undercounted and the session snapshot
    could race still-pending results. The count must equal the
    requests actually submitted; the shed remainder goes through the
    uncounted report path."""
    import types

    from pint_tpu.scripts import pint_serve

    class StubEngine:
        def __init__(self, cap):
            self.cap = cap
            self.submitted = []

        def submit(self, req):
            if len(self.submitted) >= self.cap:
                raise ServeOverload("full")
            self.submitted.append(req)
            return req.future

    mjds = [55000.0, 55000.001, 55000.04, 55000.041, 55000.08]
    seg_min = 60.0
    pad = seg_min / 1440.0
    lo = round(min(mjds) - pad, 6)
    hi = round(max(mjds) + pad, 6)
    pcs = types.SimpleNamespace(
        entries=[_entry(0), _entry(1), _entry(2)],
        _entry_for=lambda m: np.array([0, 0, 1, 1, 2]))
    cache = {("polyco", "fake.par", "@", lo, hi, seg_min): pcs}
    eng = StubEngine(cap=2)
    emitted, reported = [], []
    n = pint_serve._submit_line(
        eng, cache, {"kind": "phase", "par": "fake.par", "id": "r1",
                     "mjds": mjds},
        emitted.append, reported.append)
    # 3 segments, cap 2: two admitted (and counted), one shed
    assert n == 2
    assert len(eng.submitted) == 2
    assert len(reported) == 1
    assert reported[0]["segments_submitted"] == 2
    assert reported[0]["segments_shed"] == 1
    assert "ServeOverload" in reported[0]["error"]


def test_workload_builder_shared_by_bench_and_demo():
    """ISSUE-4 satellite: ONE workload builder. bench_serve and the
    demo daemon both delegate to serve.workload."""
    import bench_serve

    from pint_tpu.scripts.pint_serve import _demo_requests
    from pint_tpu.serve.request import Request

    reqs = _demo_requests(9)
    assert len(reqs) == 9
    kinds = {k for k, _ in reqs}
    assert kinds == {"fit_step", "residuals", "phase"}
    assert all(isinstance(r, Request) for _, r in reqs)
    fresh = bench_serve.build_workload(9)
    bench_reqs = fresh()
    assert len(bench_reqs) == 9
    # bench mode prebuilds problems (the serving-state hot path)
    assert any(getattr(r, "problem", None) is not None
               for r in bench_reqs)
    # demo mode assembles at dispatch
    assert all(getattr(r, "problem", None) is None for _, r in reqs)


# ------------------------------------------- admission (ISSUE 8)


def test_expired_request_shed_while_queued(zoo):
    """ISSUE-8 satellite regression: a deadline-dead request is
    expired IN QUEUE (at the next admission touch) with the
    shed_expired counter — not discovered at drain time after
    consuming queue capacity the whole while."""
    m, t = zoo[0]
    eng = ServeEngine()
    doomed = eng.submit(FitStepRequest(t, m, deadline_s=0.01))
    time.sleep(0.03)
    live = eng.submit(ResidualsRequest(t, m))  # sweep fires here
    assert doomed.done()  # failed BEFORE any flush/dispatch
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=0)
    snap = eng.metrics.snapshot()
    assert snap["admission"]["shed_expired"] == 1
    assert snap["deadline_missed"] == 1
    eng.flush()
    assert live.result(timeout=0).chi2 > 0


def test_tenant_quota_sheds_bursting_tenant(zoo):
    """Per-tenant token buckets: a bursting tenant is shed with
    TenantOverQuota while other tenants keep being admitted — one
    noisy tenant cannot starve the deployment."""
    from pint_tpu.serve import TenantOverQuota

    m, t = zoo[0]
    eng = ServeEngine(tenant_qps=0.001, tenant_burst=2)
    ok_a = eng.submit(FitStepRequest(t, m, tenant="noisy"))
    ok_b = eng.submit(ResidualsRequest(t, m, tenant="noisy"))
    with pytest.raises(TenantOverQuota):
        eng.submit(FitStepRequest(t, m, tenant="noisy"))
    ok_c = eng.submit(FitStepRequest(t, m, tenant="quiet"))
    eng.flush()
    for f in (ok_a, ok_b, ok_c):
        assert f.result(timeout=0).chi2 > 0
    adm = eng.metrics.snapshot()["admission"]
    assert adm["shed_quota"] == 1
    assert adm["tenants"]["noisy"] == {"admitted": 2, "shed": 1}
    assert adm["tenants"]["quiet"] == {"admitted": 1, "shed": 0}


def test_deadline_aware_shed_policy(zoo):
    """The shed policy: at capacity, shed the request that will miss
    its deadline ANYWAY (a doomed queued victim, or the doomed
    newcomer itself) — and NEVER one that can still make it; with
    nobody provably doomed, plain backpressure."""
    m, t = zoo[0]
    eng = ServeEngine(queue_cap=2, shed_policy="deadline")
    # teach the router a glacial service rate so predicted waits
    # dwarf any deadline below
    eng.router.seed_rate("device", "gls", 1.0)
    doomed = eng.submit(FitStepRequest(t, m, deadline_s=5.0))
    live = eng.submit(ResidualsRequest(t, m))  # no deadline: safe
    # at capacity: the doomed queued request is shed, the newcomer
    # (no deadline — can always "make it") is admitted in its place
    new = eng.submit(FitStepRequest(t, m))
    assert doomed.done()
    with pytest.raises(DeadlineExceeded):
        doomed.result(timeout=0)
    assert eng.admission.shed_deadline == 1
    # at capacity again: nobody queued is doomed (no deadlines), but
    # the NEWCOMER cannot make its own deadline — shed it (a labeled
    # failed future, not a transport error)
    doomed2 = eng.submit(FitStepRequest(t, m, deadline_s=0.5))
    assert doomed2.done()
    with pytest.raises(DeadlineExceeded):
        doomed2.result(timeout=0)
    assert eng.admission.shed_deadline == 2
    # nobody doomed anywhere: honest backpressure
    with pytest.raises(ServeOverload):
        eng.submit(FitStepRequest(t, m))
    eng.flush()
    assert live.result(timeout=0).chi2 > 0
    assert new.result(timeout=0).chi2 > 0


def test_shed_policy_wait_is_position_aware(zoo):
    """Review fix: a queued candidate's predicted wait counted EVERY
    other queued request's rows — batch-mates and requests queued
    BEHIND it included — so a head-of-queue request that was about
    to be served on time could be declared doomed and shed,
    violating the never-shed-a-survivor invariant. Waits are now
    prefix sums in dispatch order (only rows AHEAD count)."""
    m, t = zoo[0]
    eng = ServeEngine(queue_cap=3, shed_policy="deadline")
    head_req = FitStepRequest(t, m, deadline_s=2.0)
    head = eng.submit(head_req)
    eng.submit(FitStepRequest(t, m))       # behind: no deadline
    eng.submit(ResidualsRequest(t, m))     # behind: no deadline
    rows = head_req.problem.M.shape[0]
    # one request's rows per second: head's own wait ~1 s, within
    # its 2 s budget — but the OLD all-queued-rows estimate (~3 s)
    # declared it doomed
    eng.router.seed_rate("device", "gls", float(rows))
    with pytest.raises(ServeOverload):
        eng.submit(FitStepRequest(t, m))   # at capacity, no deadline
    assert not head.done()                 # head was NOT shed
    assert eng.admission.shed_deadline == 0
    eng.flush()


def test_reject_policy_restores_plain_backpressure(zoo):
    """shed_policy="reject": queued requests are never touched, the
    newcomer is rejected — the pre-ISSUE-8 behavior, pinnable."""
    m, t = zoo[0]
    eng = ServeEngine(queue_cap=1, shed_policy="reject")
    eng.router.seed_rate("device", "gls", 1.0)
    # 60 s deadline: provably doomed under the 1-row/s seeded rate
    # (the deadline policy WOULD shed it), but nowhere near expiring
    # in queue during the test
    queued = eng.submit(FitStepRequest(t, m, deadline_s=60.0))
    with pytest.raises(ServeOverload):
        eng.submit(FitStepRequest(t, m))
    assert not queued.done()  # the doomed one was NOT shed
    assert eng.admission.shed_deadline == 0


# ---------------------------------------------- router (ISSUE 8)


def test_breaker_demotion_routes_to_host_pool(zoo):
    """An OPEN device breaker demotes the pool: units route straight
    to the host mirrors as PLANNED capacity (no per-dispatch
    watchdog-timeout-then-failover dance), labeled in the router
    block, and results match the device path."""
    from pint_tpu.runtime import OPEN, breaker_for, reset_runtime

    reset_runtime()
    try:
        m, t = zoo[2]
        ref = ServeEngine().submit(FitStepRequest(t, m)).result()
        eng = ServeEngine()
        br = breaker_for("cpu")
        for _ in range(br.threshold):
            br.on_result(False)
        assert br.state == OPEN
        futs = [eng.submit(FitStepRequest(t, m)),
                eng.submit(ResidualsRequest(t, m))]
        eng.flush()
        res = [f.result(timeout=0) for f in futs]
        np.testing.assert_allclose(res[0].dparams, ref.dparams,
                                   rtol=1e-8, atol=1e-15)
        assert res[0].chi2 == pytest.approx(ref.chi2, rel=1e-8)
        snap = eng.metrics.snapshot()
        rt = snap["router"]
        assert rt["host"]["dispatches"] >= 1
        assert rt["host"]["demotions"] >= 1
        assert rt["device"]["dispatches"] == 0
        # routed, not failed over: the supervisor never even saw the
        # broken backend
        assert snap["dispatch"]["failovers"] == 0
        assert snap["dispatch"]["breaker_rejections"] == 0
        assert "pools:" in eng.metrics.report()
    finally:
        reset_runtime()


def test_router_steers_by_learned_rates(zoo):
    """With BOTH pools' rates learned, the router sends a unit to the
    predicted-faster pool — host CPU as concurrent capacity, not just
    a failover target."""
    m, t = zoo[0]
    eng = ServeEngine()
    eng.router.seed_rate("host", "gls", 1e12)
    eng.router.seed_rate("device", "gls", 1e-3)
    fut = eng.submit(FitStepRequest(t, m))
    eng.flush()
    assert fut.result(timeout=0).chi2 > 0
    rt = eng.metrics.snapshot()["router"]
    assert rt["host"]["dispatches"] == 1
    assert rt["device"]["dispatches"] == 0
    # host never learned = device preferred (no guessing on no
    # evidence): a fresh engine routes everything to the device
    eng2 = ServeEngine()
    fut = eng2.submit(FitStepRequest(t, m))
    eng2.flush()
    fut.result(timeout=0)
    assert eng2.metrics.snapshot()["router"]["host"]["dispatches"] == 0


# ------------------------------------- daemon lifecycle (ISSUE 8)


def test_daemon_graceful_shutdown_sheds_queued(capsys, tmp_path):
    """ISSUE-8 satellite: SIGTERM/SIGINT used to drop queued JSONL
    requests on the floor. Now the bounded drain sheds them with an
    explicit {"status": "shed", "reason": "shutdown"} line each, the
    journal acks them terminally, and the session snapshot still
    prints LAST."""
    import json
    import os

    from pint_tpu.scripts.pint_serve import _Shutdown, main

    datadir = os.path.join(os.path.dirname(__file__), "datafile")
    par = os.path.join(datadir, "NGC6440E.par")
    tim = os.path.join(datadir, "NGC6440E.tim")
    jpath = str(tmp_path / "journal.jsonl")

    def feed():
        yield json.dumps({"kind": "fit_step", "par": par,
                          "tim": tim, "id": "a"}) + "\n"
        yield json.dumps({"kind": "residuals", "par": par,
                          "tim": tim, "id": "b"}) + "\n"
        raise _Shutdown("SIGTERM")  # the signal handler's raise

    # a huge window keeps both requests queued when the signal lands;
    # drain timeout 0 = shed everything still queued
    assert main(["--window-ms", "60000", "--drain-timeout-s", "0",
                 "--journal", jpath], stdin=feed()) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    snap = lines[-1]
    assert snap["metric"] == "serve_session"
    assert snap["shutdown_signal"] == "SIGTERM"
    shed = [x for x in lines if x.get("status") == "shed"]
    assert sorted(x["id"] for x in shed) == ["a", "b"]
    assert all(x["reason"] == "shutdown" for x in shed)
    assert snap["admission"]["shed_shutdown"] == 2
    # terminal journal acks: the client was told, no replay owed
    acks = [json.loads(x)["status"] for x in open(jpath)
            if json.loads(x)["op"] == "ack"]
    assert acks == ["shed:shutdown", "shed:shutdown"]


def test_daemon_startup_shutdown_sheds_pending_stdin(capsys,
                                                     monkeypatch):
    """Verification finding on the ISSUE-8 graceful-shutdown
    satellite: the handlers were installed AFTER the multi-second
    pint_tpu/jax import, so a SIGTERM during startup hit the default
    handler — process killed, lines already written to stdin
    silently dropped (observed live: exit -15, 60 lines, zero shed
    lines). Handlers now install before the heavy imports and a
    startup-window shutdown sheds every pending line explicitly."""
    import json

    import pint_tpu.serve as serve_mod
    from pint_tpu.scripts.pint_serve import _Shutdown, main

    def dies_in_ctor(*a, **k):
        raise _Shutdown("SIGTERM")  # the handler's raise, mid-ctor

    monkeypatch.setattr(serve_mod, "ServeEngine", dies_in_ctor)
    feed = [json.dumps({"kind": "fit_step", "par": "x.par",
                        "tim": "x.tim", "id": "a"}) + "\n",
            json.dumps({"kind": "phase", "entry": "DEMO",
                        "mjds": [55000.0], "id": "b"}) + "\n",
            "# comment\n", "\n"]
    assert main([], stdin=feed) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    shed = [x for x in lines if x.get("status") == "shed"]
    assert sorted(x["id"] for x in shed) == ["a", "b"]
    assert all(x["reason"] == "shutdown" for x in shed)
    ev = [x for x in lines if x.get("event") == "shutdown"]
    assert ev and ev[-1]["during"] == "startup" and \
        ev[-1]["shed"] == 2


# ---------------------------------------------------------- config


def test_serve_bucket_env_knob(monkeypatch):
    from pint_tpu import config

    monkeypatch.setenv("PINT_TPU_SERVE_BUCKETS", "128, 32,512")
    assert config.serve_bucket_edges() == (32, 128, 512)
    monkeypatch.setenv("PINT_TPU_SERVE_BUCKETS", "banana")
    assert config.serve_bucket_edges()[0] == 64  # defaults, warned
    monkeypatch.delenv("PINT_TPU_SERVE_BUCKETS")
    edges = config.serve_bucket_edges()
    assert edges[0] == 64 and edges[-1] == 16384


def test_issue8_env_knobs(monkeypatch):
    from pint_tpu import config

    monkeypatch.setenv("PINT_TPU_TENANT_QPS", "12.5")
    assert config.tenant_qps() == 12.5
    assert config.tenant_burst() == 25.0  # default 2x, >= 1
    monkeypatch.setenv("PINT_TPU_TENANT_BURST", "4")
    assert config.tenant_burst() == 4.0
    monkeypatch.delenv("PINT_TPU_TENANT_QPS")
    assert config.tenant_qps() == 0.0  # disabled by default
    monkeypatch.setenv("PINT_TPU_SHED_POLICY", "reject")
    assert config.shed_policy() == "reject"
    monkeypatch.setenv("PINT_TPU_SHED_POLICY", "banana")
    assert config.shed_policy() == "deadline"  # warned, defaulted
    monkeypatch.delenv("PINT_TPU_SHED_POLICY")
    assert config.shed_policy() == "deadline"
    assert config.aot_dir() is None
    monkeypatch.setenv("PINT_TPU_AOT_DIR", "/tmp/x")
    assert config.aot_dir() == "/tmp/x"
    assert config.journal_path() is None
    monkeypatch.setenv("PINT_TPU_JOURNAL", "/tmp/j.jsonl")
    assert config.journal_path() == "/tmp/j.jsonl"
    monkeypatch.setenv("PINT_TPU_SERVE_DRAIN_TIMEOUT_S", "7")
    assert config.serve_drain_timeout_s() == 7.0


def test_rtt_env_read_before_cache(monkeypatch):
    """ADVICE r5 satellite: a mid-process $PINT_TPU_DISPATCH_RTT_MS
    change must take effect even after the per-backend measurement
    cached, and an unparsable value must warn, not silently stick."""
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    measured = config.dispatch_rtt_ms()  # populates the cache
    assert measured > 0
    monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", "123.5")
    assert config.dispatch_rtt_ms() == 123.5
    monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", "fast")
    assert config.dispatch_rtt_ms() == measured  # cache, with warning
    assert ("PINT_TPU_DISPATCH_RTT_MS", "fast") in config._WARNED_ENV
