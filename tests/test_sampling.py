"""Device-native posterior sampling acceptance suite (ISSUE 9).

The subsystem's load-bearing invariants, all on the CPU mesh:

- the whole-chain-on-device ``lax.scan`` kernel is BIT-IDENTICAL to
  the host-loop sampler (the same chunk program compiled at K=1)
  because the PRNG streams are positional — chunked multi-dispatch
  included;
- the GP noise-sampled likelihood equals the fixed-noise
  ``BayesianTiming`` at pinned hyperparameters, and equals a
  re-CONSTRUCTED fixed-noise likelihood at moved hyperparameters
  (the in-trace phi/Cholesky/logdet recompute is exactly the
  reference's re-construction);
- a ``PosteriorRequest`` through the ServeEngine is bit-identical to
  the direct ``sample_problems`` path at the same shape class and
  seed, and the sampled linearized posterior converges on the GLS
  solution it linearizes;
- chaos: backend death mid-chain degrades to a LABELED host failover
  with zero hung futures (the chunk boundary is the failover
  boundary);
- admission (ISSUE-9 satellite): predicted waits price each kind at
  its own learned rate, so a doomed posterior chain is shed while a
  fit step with the same deadline is served.
"""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.models.priors import GaussianPrior
from pint_tpu.runtime import Fault, FaultPlan, reset_runtime
from pint_tpu.simulation import (make_fake_toas_fromMJDs,
                                 make_fake_toas_uniform)


@pytest.fixture(autouse=True)
def clean_runtime():
    reset_runtime()
    yield
    reset_runtime()


PAR = """
PSR J0006+0006
RAJ 06:00:00.0
DECJ 20:00:00.0
F0 220.0 1
F1 -1.5e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 15.0
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""

NOISE_EXTRA = """EFAC -be X 1.1
ECORR -be X 0.8
TNREDAMP -13.5
TNREDGAM 3.0
TNREDC 5
"""


def _mk(ntoa=60, noise=False, seed=11):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        par = PAR + (NOISE_EXTRA if noise else "")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(seed)
        if noise:
            # pairs of TOAs 0.01 d apart -> real ECORR epochs
            # (quantization_buckets drops singleton buckets, nmin=2)
            centers = np.linspace(54001, 55999, ntoa // 2)
            mjds = (centers[:, None]
                    + np.array([0.0, 0.01])[None, :]).ravel()
            toas = make_fake_toas_fromMJDs(
                mjds, model, error_us=1.0, freq_mhz=1400.0,
                add_noise=True, rng=rng)
            for f in toas.flags:
                f["be"] = "X"
        else:
            toas = make_fake_toas_uniform(
                54000, 56000, ntoa, model, error_us=1.0,
                freq_mhz=1400.0, add_noise=True, rng=rng)
    return model, toas


@pytest.fixture(scope="module")
def posterior():
    """A fixed-noise DevicePosterior with proper Gaussian priors so
    every overdispersed walker starts finite."""
    from pint_tpu.sampling import DevicePosterior

    model, toas = _mk()
    for name in ("F0", "F1"):
        p = model.get_param(name)
        p.prior = GaussianPrior(p.value,
                                max(abs(p.value) * 1e-9, 1e-18))
    return DevicePosterior(model, toas)


@pytest.fixture(scope="module")
def noise_pair():
    """(model, toas) with EFAC + ECORR + power-law red noise — the
    sampled-hyperparameter surfaces."""
    return _mk(ntoa=50, noise=True, seed=23)


# ---------------------------------------------------- kernel contract


def test_kernel_validates():
    from pint_tpu.sampling import build_stretch_chunk

    lp = lambda x: -0.5 * (x ** 2).sum(axis=-1)  # noqa: E731
    with pytest.raises(ValueError):
        build_stretch_chunk(lp, 7, 2, 16)     # odd walkers
    with pytest.raises(ValueError):
        build_stretch_chunk(lp, 2, 2, 16)     # < 2*ndim
    with pytest.raises(ValueError):
        build_stretch_chunk(lp, 8, 2, 16, thin=5)  # 5 !| 16


def test_sampler_validates(posterior):
    from pint_tpu.sampling import DeviceEnsembleSampler

    with pytest.raises(ValueError):
        DeviceEnsembleSampler(3, 2, posterior.lnpost_batch)
    s = DeviceEnsembleSampler(8, posterior.nparams,
                              posterior.lnpost_batch)
    with pytest.raises(ValueError):
        s.run_mcmc(np.zeros((4, 2)), 8)       # wrong p0 shape
    with pytest.raises(ValueError):
        s.run_mcmc(posterior.init_walkers(8), 8, mode="bogus")


# ------------------------------------ scan == host_loop (THE oracle)


def _fresh_sampler(posterior, nwalkers=8, thin=1):
    from pint_tpu.sampling import DeviceEnsembleSampler

    return DeviceEnsembleSampler(nwalkers, posterior.nparams,
                                 posterior.lnpost_batch, thin=thin)


def test_scan_bit_identical_to_host_loop(posterior):
    """The tentpole oracle: one whole-chain ``lax.scan`` dispatch vs
    one dispatch PER STEP, identical positional PRNG stream →
    bitwise-equal chains, lnprob, acceptance and final ensemble."""
    p0 = posterior.init_walkers(8, rng=np.random.default_rng(5))
    host = _fresh_sampler(posterior)
    pos_h = host.run_mcmc(p0, 48, seed=7, mode="host_loop")
    scan = _fresh_sampler(posterior)
    pos_s = scan.run_mcmc(p0, 48, seed=7, mode="scan")
    assert host.dispatches == 48
    assert scan.dispatches == 1           # whole chain, one dispatch
    np.testing.assert_array_equal(pos_h, pos_s)
    np.testing.assert_array_equal(host.chain, scan.chain)
    np.testing.assert_array_equal(host.lnprob, scan.lnprob)
    assert host.naccepted == scan.naccepted
    assert 0 < scan.acceptance_fraction <= 1.0


def test_chunked_multi_dispatch_bit_identical(posterior, monkeypatch):
    """A long chain split across chunks (offset-advanced positional
    PRNG) is bitwise the single-chunk/host-loop chain — the serve
    layer's bounded-deadline chunking changes nothing numerically."""
    monkeypatch.setenv("PINT_TPU_CHAIN_CHUNK", "16")
    p0 = posterior.init_walkers(8, rng=np.random.default_rng(5))
    chunked = _fresh_sampler(posterior)
    chunked.run_mcmc(p0, 48, seed=7, mode="scan")
    assert chunked.dispatches == 3
    monkeypatch.delenv("PINT_TPU_CHAIN_CHUNK")
    host = _fresh_sampler(posterior)
    host.run_mcmc(p0, 48, seed=7, mode="host_loop")
    np.testing.assert_array_equal(chunked.chain, host.chain)
    np.testing.assert_array_equal(chunked.lnprob, host.lnprob)
    assert chunked.naccepted == host.naccepted


def test_thinned_chain_matches_strided_full(posterior):
    """thin=4 emits exactly every 4th state of the thin=1 chain
    (same PRNG stream — thinning only bounds the D2H readback)."""
    p0 = posterior.init_walkers(8, rng=np.random.default_rng(2))
    full = _fresh_sampler(posterior)
    full.run_mcmc(p0, 32, seed=3, mode="scan")
    thin = _fresh_sampler(posterior, thin=4)
    thin.run_mcmc(p0, 32, seed=3, mode="scan")
    assert thin.chain.shape[0] == 8
    np.testing.assert_array_equal(thin.chain, full.chain[3::4])
    np.testing.assert_array_equal(thin.lnprob, full.lnprob[3::4])
    # host_loop honors thin too (review fix: it used to emit the
    # un-thinned chain, a different SHAPE than its scan counterpart)
    hthin = _fresh_sampler(posterior, thin=4)
    hthin.run_mcmc(p0, 32, seed=3, mode="host_loop")
    np.testing.assert_array_equal(hthin.chain, thin.chain)
    np.testing.assert_array_equal(hthin.lnprob, thin.lnprob)
    with pytest.raises(ValueError):
        thin.run_mcmc(p0, 30, seed=3)     # 4 does not divide 30


def test_device_sampler_moments_match_wls(posterior):
    """Statistical sanity on top of the bitwise oracles: the sampled
    posterior's center stays on the injected model truth within the
    posterior scatter (prior sigma ~1e-9 relative)."""
    s = _fresh_sampler(posterior, nwalkers=16)
    p0 = posterior.init_walkers(16, rng=np.random.default_rng(8))
    s.run_mcmc(p0, 300, seed=1, mode="scan")
    flat = s.get_chain(discard=100, flat=True)
    for k in range(posterior.nparams):
        sig = np.std(flat[:, k])
        assert sig > 0
        assert abs(np.mean(flat[:, k]) - posterior.theta0[k]) \
            < 5 * sig


# ----------------------------------------- noise-sampled likelihood


def test_sampled_noise_matches_fixed_at_pinned(noise_pair):
    """CPU oracle: at hyperparameters pinned to the model's current
    values the traced noise-sampled likelihood IS the fixed-noise
    ``BayesianTiming`` likelihood."""
    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.sampling import SampledNoiseLikelihood

    model, toas = noise_pair
    bt = BayesianTiming(model, toas)
    sn = SampledNoiseLikelihood(model, toas)
    assert sn.labels == ["ECORR1.log10", "PLRedNoise.log10_A",
                         "PLRedNoise.gamma"]
    np.testing.assert_allclose(
        sn.eta0, [np.log10(0.8), -13.5, 3.0], rtol=1e-12)
    rng = np.random.default_rng(3)
    th0 = bt.theta0.copy()
    for _ in range(3):
        th = th0 + 1e-10 * rng.standard_normal(len(th0)) * th0
        assert sn.lnlikelihood(th, sn.eta0) == pytest.approx(
            bt.lnlikelihood(th), rel=1e-9)


def test_sampled_noise_matches_reconstruction(noise_pair):
    """The strong oracle: moving (log10_A, gamma, ECORR) in eta
    equals RE-CONSTRUCTING the fixed-noise likelihood at the moved
    hyperparameters — the in-trace phi / per-epoch variance / Sff
    Cholesky / logdet recompute is exactly the reference's
    construction-time computation."""
    from pint_tpu.bayesian import BayesianTiming
    from pint_tpu.sampling import SampledNoiseLikelihood

    model, toas = noise_pair
    sn = SampledNoiseLikelihood(model, toas)
    eta1 = sn.eta0 + np.array([0.1, 0.3, -0.4])
    m2 = copy.deepcopy(model)
    m2.get_param("ECORR1").value = 10.0 ** eta1[0]
    m2.get_param("TNREDAMP").value = eta1[1]
    m2.get_param("TNREDGAM").value = eta1[2]
    m2.invalidate_cache()
    bt2 = BayesianTiming(m2, toas)
    th0 = bt2.theta0.copy()
    th1 = th0.copy()
    th1[0] += 2e-10
    for th in (th0, th1):
        assert sn.lnlikelihood(th, eta1) == pytest.approx(
            bt2.lnlikelihood(th), rel=1e-9)
    # and the hyperparameters genuinely move the likelihood
    assert sn.lnlikelihood(th0, eta1) != \
        pytest.approx(sn.lnlikelihood(th0, sn.eta0), rel=1e-12)


def test_noise_sampled_posterior_chain(noise_pair):
    """End-to-end: a DevicePosterior with sample_noise=True runs the
    whole-chain kernel over timing + noise dimensions, scan ==
    host_loop bitwise, and the noise dimensions actually mix."""
    from pint_tpu.sampling import (
        DeviceEnsembleSampler,
        DevicePosterior,
    )

    model, toas = noise_pair
    post = DevicePosterior(model, toas, sample_noise=True)
    assert post.param_labels[post.ntiming:] == [
        "ECORR1.log10", "PLRedNoise.log10_A", "PLRedNoise.gamma"]
    W = 2 * post.nparams + 2
    p0 = post.init_walkers(W, rng=np.random.default_rng(4),
                           scatter=0.2)
    scan = DeviceEnsembleSampler(W, post.nparams, post.lnpost_batch)
    scan.run_mcmc(p0, 24, seed=9, mode="scan")
    host = DeviceEnsembleSampler(W, post.nparams, post.lnpost_batch)
    host.run_mcmc(p0, 24, seed=9, mode="host_loop")
    np.testing.assert_array_equal(scan.chain, host.chain)
    assert np.all(np.isfinite(scan.lnprob))
    assert scan.naccepted > 0
    # the sampled red-noise amplitude dimension moved off its start
    lgA = scan.chain[:, :, post.ntiming + 1]
    assert np.ptp(lgA) > 0


def test_mcmc_fitter_sample_noise(noise_pair):
    """MCMCFitter as a thin consumer: sample_noise=True reports the
    hyperparameter posterior in ``noise_estimates`` and never writes
    it into the timing model; mode='host' refuses sample_noise."""
    from pint_tpu.mcmc_fitter import MCMCFitter

    model, toas = noise_pair
    m = copy.deepcopy(model)
    mc = MCMCFitter(toas, m, nwalkers=4, sample_noise=True,
                    rng=np.random.default_rng(6))
    chi2 = mc.fit_toas(nsteps=30)
    assert np.isfinite(chi2)
    assert set(mc.noise_estimates) == {
        "ECORR1.log10", "PLRedNoise.log10_A", "PLRedNoise.gamma"}
    for v in mc.noise_estimates.values():
        assert np.isfinite(v["median"]) and v["std"] >= 0
    # the timing model's noise parameters are untouched
    assert m.get_param("TNREDAMP").value == -13.5
    with pytest.raises(ValueError):
        MCMCFitter(toas, m, mode="host", sample_noise=True)


# ------------------------------------------------- serve integration


def _problems(nreq=2):
    from pint_tpu.parallel.pta import build_problem

    out = []
    for k in range(nreq):
        par = PAR.replace("F0 220.0", f"F0 {220.0 + 30 * k}")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(io.StringIO(par))
            t = make_fake_toas_uniform(
                54000, 56000, 40 + 10 * k, m, error_us=1.0,
                freq_mhz=1400.0, add_noise=True,
                rng=np.random.default_rng(30 + k))
        out.append(build_problem(t, m))
    return out


def test_served_posterior_bit_identical_to_direct():
    """A coalesced PosteriorRequest bucket == the direct
    ``sample_problems`` path at the same shape class and seeds (a
    request's PRNG stream depends only on its own seed, never on its
    batch position)."""
    from pint_tpu import config
    from pint_tpu.sampling import sample_problems
    from pint_tpu.serve import PosteriorRequest, ServeEngine
    from pint_tpu.serve.bucket import posterior_shape_class

    problems = _problems(2)
    W, nsteps, thin = 8, 40, 1
    eng = ServeEngine()
    futs = [eng.submit(PosteriorRequest(
        problem=copy.copy(pr), nwalkers=W, nsteps=nsteps,
        seed=100 + k, thin=thin))
        for k, pr in enumerate(problems)]
    eng.flush()
    served = [f.result(timeout=0) for f in futs]

    K = config.chain_chunk_steps(nsteps, thin=thin)
    keys = {posterior_shape_class(
        pr.M.shape[0], pr.M.shape[1], pr.F.shape[1], W, K, thin,
        eng.bucket_edges) for pr in problems}
    assert len(keys) == 1                   # one class: coalesced
    (_, nb, pb, qb, _, _, _), = keys
    direct = sample_problems(
        problems, W, nsteps, seeds=[100, 101], thin=thin,
        shape=(eng._batch_pad(2), nb, pb, qb))
    for res, (chain, lnp, acc) in zip(served, direct):
        np.testing.assert_array_equal(res.chain, chain)
        np.testing.assert_array_equal(res.lnprob, lnp)
        assert res.acceptance_fraction == pytest.approx(acc)
    snap = eng.metrics.snapshot()
    assert snap["completed"] == 2
    assert snap["router"]["device"]["rows_per_s"].get("posterior")


def test_sampled_linearized_posterior_matches_gls():
    """The serve kernel's statistical oracle: the chain's sample
    moments converge on the GLS ``dparams``/``cov`` of the SAME
    linearized problem (the chain explores the exact Gaussian the
    solve reports)."""
    from pint_tpu.parallel.pta import pta_solve_np, stack_problems
    from pint_tpu.sampling import sample_problems

    (pr,) = _problems(1)
    chain, lnp, acc = sample_problems(
        [pr], nwalkers=16, nsteps=600, seeds=[42])[0]
    dparams, cov = pta_solve_np(stack_problems([pr]))[:2]
    sig = np.sqrt(np.diagonal(cov[0]))
    flat = chain[200:].reshape(-1, chain.shape[-1])
    assert 0.1 < acc < 0.95
    err = np.abs(flat.mean(axis=0) - dparams[0])
    assert np.all(err < 0.5 * sig)
    ratio = flat.std(axis=0) / sig
    assert np.all((0.5 < ratio) & (ratio < 2.0))


def test_posterior_request_validates():
    from pint_tpu.serve import PosteriorRequest

    (pr,) = _problems(1)
    with pytest.raises(ValueError):
        PosteriorRequest(problem=pr, nwalkers=7)   # odd
    with pytest.raises(ValueError):
        PosteriorRequest(problem=pr, nsteps=0)
    with pytest.raises(ValueError):
        PosteriorRequest(problem=pr, nsteps=10, thin=3)
    # under-walkered ensemble: the serve kernel traces ndim, so the
    # guard fires at problem assembly (review fix — a 4-walker chain
    # over >2 dims silently never leaves its affine hull); the direct
    # oracle surface guards identically
    with pytest.raises(ValueError, match="2\\*ndim"):
        PosteriorRequest(problem=pr, nwalkers=4).ensure_problem()
    from pint_tpu.sampling import sample_problems
    with pytest.raises(ValueError, match="2\\*ndim"):
        sample_problems([pr], nwalkers=4, nsteps=8, seeds=[1])
    r = PosteriorRequest(problem=pr, nwalkers=8, nsteps=100)
    assert r.walker_steps == 800
    assert r.kind == "posterior"


def test_posterior_summary_convention():
    """PosteriorResult.summary() reports per-parameter corrections in
    the dparams convention, keyed by design-column names."""
    from pint_tpu.serve import PosteriorRequest, ServeEngine

    (pr,) = _problems(1)
    eng = ServeEngine()
    fut = eng.submit(PosteriorRequest(problem=copy.copy(pr),
                                      nwalkers=8, nsteps=40, seed=5))
    eng.flush()
    res = fut.result(timeout=0)
    s = res.summary()
    assert set(s) == set(pr.names)
    assert s["Offset"]["std"] >= 0
    assert res.flat().shape == (40 * 8, pr.M.shape[1])


# -------------------------------------------------- chaos + admission


def test_posterior_chaos_mid_chain_backend_death(monkeypatch):
    """ISSUE-9 chaos oracle: the backend dies between chain chunks —
    every future completes via LABELED host failover (the chunk
    boundary is the failover boundary; the chain continues from the
    carried ensemble state), bit-identical on the CPU mesh, zero hung
    futures, honest counters."""
    from pint_tpu.serve import PosteriorRequest, ServeEngine

    monkeypatch.setenv("PINT_TPU_CHAIN_CHUNK", "16")
    problems = _problems(2)

    def submit_all(eng):
        return [eng.submit(PosteriorRequest(
            problem=copy.copy(pr), nwalkers=8, nsteps=48,
            seed=200 + k)) for k, pr in enumerate(problems)]

    # reference pass (no faults): warms compiles AND gives the oracle
    ref_eng = ServeEngine()
    ref_futs = submit_all(ref_eng)
    ref_eng.flush()
    ref = [f.result(timeout=0) for f in ref_futs]

    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "300")
    eng = ServeEngine()
    # chunk 0 survives on the device; the backend wedges from chunk 1
    plan = FaultPlan([Fault(match="serve.posterior", kind="hang",
                            seconds=5.0, after=1)])
    with plan.active():
        futs = submit_all(eng)
        eng.flush()
    assert all(f.done() for f in futs)        # ZERO hung futures
    for f, r in zip(futs, ref):
        res = f.result(timeout=0)             # labeled, never raises
        np.testing.assert_array_equal(res.chain, r.chain)
        np.testing.assert_array_equal(res.lnprob, r.lnprob)
        assert res.acceptance_fraction == r.acceptance_fraction
    snap = eng.metrics.snapshot()
    disp = snap["dispatch"]
    assert disp["failovers"] >= 1 and disp["timeouts"] >= 1
    assert "DEGRADED" in eng.metrics.report()


def test_posterior_admission_priced_at_posterior_rate():
    """ISSUE-9 satellite regression: the admission wait for a queued
    posterior chain uses the POSTERIOR kind's learned rate — the
    doomed chain is shed at admission while a fit step with the SAME
    deadline is served. (Under the old single-rate estimate the
    chain's walker-steps were priced at the ~free GLS rate: nobody
    looked doomed and the fit step was backpressure-rejected.)"""
    from pint_tpu.serve import (
        DeadlineExceeded,
        FitStepRequest,
        PosteriorRequest,
        ResidualsRequest,
        ServeEngine,
    )

    (pr,) = _problems(1)
    m, t = _mk(ntoa=50, seed=77)
    eng = ServeEngine(queue_cap=2, shed_policy="deadline")
    eng.router.seed_rate("device", "gls", 1e6)       # rows/s: fast
    eng.router.seed_rate("device", "posterior", 10.0)  # glacial
    # sanity: the same-size work is priced per kind
    assert eng.router.predicted_wait_s(1600, kind="posterior") > \
        eng.router.predicted_wait_s(1600, kind="gls")
    filler = eng.submit(ResidualsRequest(t, m))      # no deadline
    # 8*200 = 1600 walker-steps at 10/s = 160 s wait >> 30 s budget
    post = eng.submit(PosteriorRequest(
        problem=copy.copy(pr), nwalkers=8, nsteps=200,
        deadline_s=30.0))
    # at capacity: the doomed queued CHAIN is the shed victim, and
    # the fit step with the identical deadline takes its place
    fit = eng.submit(FitStepRequest(t, m, deadline_s=30.0))
    assert post.done()
    with pytest.raises(DeadlineExceeded):
        post.result(timeout=0)
    assert eng.admission.shed_deadline == 1
    eng.flush()
    assert fit.result(timeout=0).chi2 > 0            # SERVED
    assert filler.result(timeout=0).chi2 > 0


def test_ecorr_prior_log10_change_of_variables(noise_pair):
    """Review fix: a prior declared over the LINEAR ECORR value
    (microseconds) must be transformed to the sampled log10
    coordinate with its Jacobian — p_eta(eta) = p_v(10^eta) 10^eta
    ln10 — not evaluated raw at the log10 value."""
    from pint_tpu.models.priors import (
        GaussianPrior,
        Log10TransformedPrior,
    )
    from pint_tpu.sampling import DevicePosterior, SampledNoiseLikelihood

    base = GaussianPrior(0.8, 0.1)          # over ECORR in us
    for eta in (-0.2, np.log10(0.8), 0.1):
        v = 10.0 ** eta
        expect = float(base.logpdf(v)) + np.log(v * np.log(10.0))
        got = float(Log10TransformedPrior(base).logpdf(eta))
        assert got == pytest.approx(expect, rel=1e-12)

    model, toas = noise_pair
    m = copy.deepcopy(model)
    m.get_param("ECORR1").prior = GaussianPrior(0.8, 0.1)
    sn = SampledNoiseLikelihood(m, toas)
    assert isinstance(sn.priors[0], Log10TransformedPrior)
    post = DevicePosterior(m, toas, sample_noise=True)
    # the posterior's prior sum picks up the transformed density:
    # moving eta by +0.1 in log10 changes lnpost by the transformed
    # prior delta plus the likelihood delta, and the density peaks
    # near log10(0.8), not at eta=0.8
    i = post.ntiming                         # ECORR1.log10 slot
    e0 = float(post.theta0[i])
    assert e0 == pytest.approx(np.log10(0.8))
    tp = Log10TransformedPrior(base)
    assert float(tp.logpdf(np.log10(0.8))) > float(tp.logpdf(0.8))


def test_daemon_posterior_quantizes_walkers(tmp_path, capsys):
    """Review fix: nwalkers/thin ride EXACTLY in the posterior
    compile key, so the daemon pow2-quantizes client values (a
    client sweeping nwalkers 33,34,35... must not force one XLA
    compile per request)."""
    import json
    import os

    from pint_tpu.scripts.pint_serve import main

    datadir = os.path.join(os.path.dirname(__file__), "datafile")
    par = os.path.join(datadir, "NGC6440E.par")
    tim = os.path.join(datadir, "NGC6440E.tim")
    recs = [
        {"kind": "posterior", "id": "q1", "par": par, "tim": tim,
         "nwalkers": 18, "nsteps": 33, "thin": 3, "seed": 2},
        # under-walkered ask: the daemon floors W at the problem's
        # 2*ndim+2 (review fix — a default request must never
        # hard-fail the ensemble guard on a wide model)
        {"kind": "posterior", "id": "q2", "par": par, "tim": tim,
         "nwalkers": 2, "nsteps": 16, "seed": 3},
    ]
    assert main(["--window-ms", "2"],
                stdin=iter(json.dumps(r) for r in recs)) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    res = [x for x in lines if x.get("id") == "q1"]
    assert len(res) == 1 and res[0]["ok"]
    # 18 walkers -> 32, thin 3 -> 4, nsteps 33 -> next multiple of 4
    assert res[0]["nsteps"] == 36
    assert "F0" in res[0]["posterior"]
    res2 = [x for x in lines if x.get("id") == "q2"]
    assert len(res2) == 1 and res2[0]["ok"]


def test_posterior_progress_acks_journaled(tmp_path, monkeypatch):
    """A journalable multi-chunk posterior request writes one
    non-terminal ``progress`` mark per chunk dispatch between its
    admit and its terminal ack — the post-crash journal scan shows
    how far a dead chain got (replay restarts it from scratch)."""
    import json

    from pint_tpu.serve import PosteriorRequest, ServeEngine

    monkeypatch.setenv("PINT_TPU_CHAIN_CHUNK", "16")
    (pr,) = _problems(1)
    jpath = str(tmp_path / "j.jsonl")
    eng = ServeEngine(journal=jpath)
    fut = eng.submit(PosteriorRequest(
        problem=copy.copy(pr), nwalkers=8, nsteps=48, seed=1,
        payload={"kind": "posterior"}))
    eng.flush()
    fut.result(timeout=0)
    recs = [json.loads(x) for x in open(jpath)]
    assert [r["op"] for r in recs] == \
        ["admit", "progress", "progress", "progress", "ack"]
    assert [r["steps"] for r in recs if r["op"] == "progress"] == \
        [16, 32, 48]
    assert recs[-1]["status"] == "served"
    eng.stop()


# ------------------------------------- host sampler boundary (G11)


def test_host_sampler_copies_logp_at_boundary():
    """ISSUE-9 small fix: ``EnsembleSampler`` must take an OWNED copy
    of log_prob_batch's return — a zero-copy numpy view of a jax
    device buffer dangles once donation reuses the memory. Simulated
    here by a posterior callable that recycles ONE backing buffer
    (what a donated device buffer looks like from numpy): the chain
    must equal the fresh-array oracle bitwise."""
    from pint_tpu.sampler import EnsembleSampler

    icov = np.linalg.inv(np.array([[2.0, 0.6], [0.6, 1.0]]))

    def fresh(x):
        x = np.atleast_2d(x)
        return -0.5 * np.einsum("si,ij,sj->s", x, icov, x)

    buf = np.empty(64)

    def recycled(x):
        out = fresh(x)
        view = buf[:len(out)]
        view[:] = out
        return view                       # same memory every call

    p0 = np.random.default_rng(1).standard_normal((8, 2))
    a = EnsembleSampler(8, 2, fresh, rng=np.random.default_rng(9))
    a.run_mcmc(p0.copy(), 60)
    b = EnsembleSampler(8, 2, recycled, rng=np.random.default_rng(9))
    b.run_mcmc(p0.copy(), 60)
    np.testing.assert_array_equal(a.chain, b.chain)
    np.testing.assert_array_equal(a.lnprob, b.lnprob)
    assert a.naccepted == b.naccepted
