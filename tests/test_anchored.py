"""Anchored delta-phase fit step (the TPU-safe phase engine): the host
computes the exact reference once; the device evaluates only small
differences via ops/taylor.taylor_powdiff, so no ~1e10-turn
intermediate exists and 2^-48 working precision (TPU emulated f64)
yields full residual accuracy. On CPU (IEEE f64) the anchored and
direct-dd paths must agree to sub-ps residual level — that equality is
the oracle here; the TPU benefit is by construction (magnitudes), not
re-measurable on CPU."""

import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step, build_sharded_fit_step
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 300.123456789 1
F1 -1.0e-15 1
DM 20.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def _problem(extra="", n=400, seed=11):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BASE + extra))
        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(53001, 56999, n))
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n // 2),
            add_noise=True, rng=rng)
        for i, f in enumerate(toas.flags):
            f["be"] = "X" if i % 2 else "Y"  # JUMP -be Y hits only
            # half the TOAs (a full-coverage jump is collinear with
            # the Offset column — singular by construction)
    return model, toas


CASES = {
    "isolated-f2": "F2 1e-26 1\nPMRA 2.0 1\nPMDEC -3 1\nPX 1.2 1\n",
    "ecorr-red": ("EFAC -be X 1.1\nEQUAD -be X 0.3\nECORR -be X 1.2\n"
                  "TNREDAMP -13.7\nTNREDGAM 3.5\nTNREDC 10\n"),
    "ell1-short-pb": ("BINARY ELL1\nPB 0.38 1\nA1 1.42 1\n"
                      "TASC 54999.93 1\nEPS1 1e-5 1\nEPS2 -2e-5 1\n"),
    "glitch-wave-jump": ("GLEP_1 55200\nGLPH_1 0.2 1\nGLF0_1 1e-7 1\n"
                         "WAVE_OM 0.005\nWAVE1 0.01 -0.02\n"
                         "JUMP -be Y 1e-6 1\n"),
}


@pytest.mark.parametrize("extra", list(CASES.values()),
                         ids=list(CASES.keys()))
def test_anchored_equals_direct(extra):
    """At the anchor AND under a compensated perturbation, with the
    same two compiled steps (compile count is what the suite's wall
    time is made of). The anchored path receives the exact delta; the
    direct path gets it folded into its dd pair with compensation."""
    model, toas = _problem(extra)
    free = model.free_params
    sD, aD, _ = build_fit_step(model, toas, anchored=False,
                               jac_f32=False)
    sA, aA, _ = build_fit_step(model, toas, anchored=True,
                               jac_f32=False)
    jD, jA = jax.jit(sD), jax.jit(sA)

    # --- at the anchor ---
    oD = jD(*aD)
    oA = jA(*aA)
    rD, rA = np.asarray(oD[3]), np.asarray(oA[3])
    assert np.max(np.abs(rD - rA)) < 1e-11  # 10 ps
    assert abs(float(oD[2]) - float(oA[2])) < 1e-6 * abs(
        float(oD[2])) + 1e-9
    sig = np.sqrt(np.diag(np.asarray(oD[1])))
    assert np.max(np.abs(np.asarray(oD[0]) - np.asarray(oA[0]))
                  / sig) < 1e-4

    # --- perturbed (same compiled steps, new arguments) ---
    dth = np.zeros(len(free))
    dth[free.index("F0")] = 3e-10
    dth[free.index("F1")] = -2e-18
    dth[free.index("DM")] = 1e-5
    th = np.asarray(aD[0])
    tl = np.asarray(aD[1])
    th2 = th + dth
    tl2 = tl + (dth - (th2 - th))
    oD = jD(*((jnp.asarray(th2), jnp.asarray(tl2)) + aD[2:]))
    oA = jA(*((jnp.asarray(dth),) + aA[1:]))
    rD, rA = np.asarray(oD[3]), np.asarray(oA[3])
    assert np.max(np.abs(rD - rA)) < 1e-11
    assert abs(float(oD[2]) - float(oA[2])) < 1e-6 * abs(
        float(oD[2])) + 1e-9


def test_anchored_with_f32_jacobian():
    """The production TPU configuration: anchored phase + f32 Jacobian
    + f32 MXU matmuls vs the plain f64 direct step."""
    model, toas = _problem(CASES["ell1-short-pb"] + "F2 1e-26 1\n")
    sD, aD, _ = build_fit_step(model, toas, anchored=False,
                               jac_f32=False, matmul_f32=False)
    sA, aA, _ = build_fit_step(model, toas, anchored=True,
                               jac_f32=True, matmul_f32=True)
    oD = jax.jit(sD)(*aD)
    oA = jax.jit(sA)(*aA)
    sig = np.sqrt(np.diag(np.asarray(oD[1])))
    assert np.max(np.abs(np.asarray(oD[0]) - np.asarray(oA[0]))
                  / sig) < 1e-2
    assert np.max(np.abs(np.asarray(oD[3]) - np.asarray(oA[3]))) < 1e-11


def test_anchored_sharded_equals_unsharded():
    from jax.sharding import Mesh

    model, toas = _problem(CASES["ecorr-red"], n=200)
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-virtual-device conftest mesh")
    mesh = Mesh(np.array(devs[:8]).reshape(8), ("toa",))
    jitted, dev_args, _ = build_sharded_fit_step(
        model, toas, mesh, anchored=True, jac_f32=True)
    sA, aA, _ = build_fit_step(model, toas, anchored=True,
                               jac_f32=True)
    oS = jitted(*dev_args)
    oU = jax.jit(sA)(*aA)
    # f32 reductions reorder across shards: compare parameter steps
    # against their uncertainties, not bitwise
    sig = np.sqrt(np.diag(np.asarray(oU[1])))
    assert np.max(np.abs(np.asarray(oS[0]) - np.asarray(oU[0]))
                  / sig) < 1e-3
    assert abs(float(oS[2]) - float(oU[2])) < 1e-5 * abs(float(oU[2]))


def test_supports_anchored_gating():
    model, toas = _problem()
    assert model.supports_anchored()
    model.get_param("PEPOCH").frozen = False
    assert not model.supports_anchored()
    model.get_param("PEPOCH").frozen = True
    # anchored=True on an unsupported model silently falls back
    model2, toas2 = _problem()
    model2.get_param("PEPOCH").frozen = False
    s, a, _ = build_fit_step(model2, toas2, anchored=True)
    out = jax.jit(s)(*a)
    assert np.isfinite(float(out[2]))

def test_grid_chisq_anchored_matches(monkeypatch):
    """grid_chisq varies FROZEN params through the step's fh/fl slots:
    with anchored on (the TPU default) the surface must match the
    direct path — the bug class this guards against is the anchored fn
    baking build-time frozen values and returning a flat surface."""
    from pint_tpu.gridutils import grid_chisq

    model, toas = _problem(n=150)
    f0 = model.F0.value
    grid = np.linspace(f0 - 2e-9, f0 + 2e-9, 5)
    # force BOTH modes explicitly: on a TPU backend (or with the env
    # preset) the 'direct' pass would otherwise silently be anchored
    # too and the comparison vacuous
    monkeypatch.setenv("PINT_TPU_ANCHORED", "off")
    c_direct = grid_chisq(model, toas, ["F0"], [grid])
    monkeypatch.setenv("PINT_TPU_ANCHORED", "on")
    c_anch = grid_chisq(model, toas, ["F0"], [grid])
    assert np.ptp(c_direct) > 1.0           # a real surface
    np.testing.assert_allclose(c_anch, c_direct,
                               rtol=1e-6, atol=1e-6)
