"""Numerical-health plane acceptance suite (ISSUE 14).

The contracts CLAUDE.md/ISSUE 14 promise:

- in-trace health taps cost ZERO additional dispatches and, when
  disarmed (the default), record NOTHING and leave the step programs
  byte-identical (compile-key invariance: arming health must not
  recompile when parameter VALUES change — the flag is a static
  compile-key bit, like donation);
- ``HealthMonitor.observe`` evaluates every tap against the
  validated ``$PINT_TPU_HEALTH*`` thresholds (warn-and-ignore
  parsers), feeds the registry, and fires rate-limited
  ``numerics:<reason>`` flight dumps on incident;
- shadow-oracle sampling replays a completed solve on the numpy
  mirror and records device-vs-host drift in sigma — and the
  DETECTOR DETECTS: a forced-f32 solve demonstrably exceeds the
  default band while the exact-f64 replay sits decades below it;
- the streaming CG's effort (iterations used, final relative
  residual) surfaces on the fitter result object and artifacts
  instead of dying on device.
"""

import io
import time
import warnings

import numpy as np
import pytest

from pint_tpu import config, obs
from pint_tpu.obs import health as oh
from pint_tpu.obs import metrics as om
from pint_tpu.runtime import reset_runtime


@pytest.fixture(autouse=True)
def clean_obs():
    """A configured monitor/tracer/registry must never leak across
    tests (the obs.reset isolation contract)."""
    obs.reset()
    reset_runtime()
    yield
    obs.reset()
    reset_runtime()


PAR = (
    "PSR J0000+0014\nRAJ 12:00:00.0 1\nDECJ 30:00:00.0 1\n"
    "F0 61.0 1\nF1 -1e-15 1\nDM 20.0 1\nPEPOCH 55000\n"
    "POSEPOCH 55000\nTZRMJD 55000.01\nTZRSITE @\nTZRFRQ 1400\n"
    "UNITS TDB\nTNREDAMP -13.0\nTNREDGAM 3.0\nTNREDC 5\n")


def _mk(n=200, seed=3):
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR))
        t = make_fake_toas_uniform(
            54000, 56000, n, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(seed))
    return m, t


# ------------------------------------------------- validated parsers


def test_health_env_parsers_warn_and_ignore(monkeypatch):
    monkeypatch.delenv("PINT_TPU_HEALTH", raising=False)
    assert config.health_enabled() is False
    monkeypatch.setenv("PINT_TPU_HEALTH", "on")
    assert config.health_enabled() is True
    monkeypatch.setenv("PINT_TPU_HEALTH", "banana")
    assert config.health_enabled() is False   # warned, stays off
    assert config.health_enabled(True) is True  # explicit flag wins

    monkeypatch.setenv("PINT_TPU_SHADOW_RATE", "256")
    assert config.shadow_rate() == 256
    monkeypatch.setenv("PINT_TPU_SHADOW_RATE", "-3")
    assert config.shadow_rate() == 0
    monkeypatch.setenv("PINT_TPU_SHADOW_RATE", "pear")
    assert config.shadow_rate() == 0

    monkeypatch.setenv("PINT_TPU_HEALTH_DRIFT_SIGMA", "2e-2")
    assert config.health_drift_sigma() == 2e-2
    monkeypatch.setenv("PINT_TPU_HEALTH_DRIFT_SIGMA", "-1")
    assert config.health_drift_sigma() == 1e-5
    monkeypatch.setenv("PINT_TPU_HEALTH_DRIFT_SIGMA", "inf")
    assert config.health_drift_sigma() == 1e-5

    monkeypatch.setenv("PINT_TPU_HEALTH_CHI2_FACTOR", "0.5")
    assert config.health_chi2_factor() == 4.0   # must be > 1
    monkeypatch.setenv("PINT_TPU_HEALTH_CHI2_FACTOR", "8")
    assert config.health_chi2_factor() == 8.0

    monkeypatch.setenv("PINT_TPU_HEALTH_CG_BUDGET_FRAC", "2.0")
    assert config.health_cg_budget_frac() == 1.0   # clamped
    monkeypatch.setenv("PINT_TPU_HEALTH_CG_BUDGET_FRAC", "0.5")
    assert config.health_cg_budget_frac() == 0.5


# ------------------------------------------------ off-path contract


def test_disarmed_observe_records_nothing(monkeypatch):
    monkeypatch.delenv("PINT_TPU_HEALTH", raising=False)
    monkeypatch.delenv("PINT_TPU_SHADOW_RATE", raising=False)
    v = oh.observe("fit.device", {"values": [np.array([np.nan])]})
    assert v == {"ok": True, "checked": False}
    assert oh.status() is None
    reg = om.get_registry()
    assert reg.total("pint_tpu_health_incidents_total") == 0
    # no gauge/histogram rows were created either
    g = reg.get("pint_tpu_health_last_value")
    assert g is None or g.series() == []


# ----------------------------------------------------- thresholds


def test_thresholds_and_verdicts(tmp_path):
    obs.configure(enabled=True, flight_dir=str(tmp_path))
    mon = oh.configure(enabled=True)
    reg = om.get_registry()

    # clean observation: no incident, gauges recorded
    v = mon.observe("fit.device",
                    {"hv": np.array([0.0, 2.5, 100.0])},
                    key="k")
    assert v["ok"] and v["checked"]
    assert reg.value("pint_tpu_health_last_value",
                     kind="fit.device",
                     signal="max_resid_sigma") == 2.5

    # non-finite appearance
    v = mon.observe("fit.device",
                    {"values": [np.array([1.0, np.nan])]}, key="k")
    assert not v["ok"] and v["reasons"] == ["nonfinite"]

    # CG budget exhaustion
    v = mon.observe("stream.solve",
                    {"cg_iters": 64, "cg_budget": 64,
                     "cg_rel_residual": 1e-3, "ok": False})
    assert set(v["reasons"]) == {"cg_budget", "solver_not_ok"}
    assert reg.total(
        "pint_tpu_health_cg_budget_exhausted_total") == 1

    # chi2 blow-up (default factor 4)
    v = mon.observe("fit.device",
                    {"chi2": 500.0, "chi2_prev": 100.0})
    assert v["reasons"] == ["chi2_blowup"]
    assert mon.observe("fit.device",
                       {"chi2": 101.0, "chi2_prev": 100.0})["ok"]

    # whitened-residual garbage threshold
    v = mon.observe("fit.device", {"max_resid_sigma": 1e12})
    assert v["reasons"] == ["resid_sigma"]

    # drift beyond band
    v = mon.observe("gls", {"drift_sigma": 1.0}, pool="shadow")
    assert v["reasons"] == ["drift"]
    assert reg.total(
        "pint_tpu_health_shadow_drift_exceeded_total") == 1

    st = mon.status()
    assert st["armed"] is True
    assert st["incidents"] == int(reg.total(
        "pint_tpu_health_incidents_total")) >= 5
    assert st["last_incident"]["reason"] == "drift"
    assert st["last_incident"]["age_s"] >= 0.0
    # worst recent verdict per (pool, kind)
    assert st["worst"]["shadow/gls"]["ok"] is False
    assert "drift" in st["drift"].get("gls", {}).get(
        "log2_us_buckets", {"_": 1}) or True  # histogram populated
    assert st["cg_iters"]["stream.solve"]["count"] == 1


def test_incident_flight_dump_rate_limited(tmp_path):
    obs.configure(enabled=True, flight_dir=str(tmp_path))
    mon = oh.configure(enabled=True)
    for _ in range(4):
        mon.observe("fit.device",
                    {"values": [np.array([np.nan])]}, key="k")
    # four incidents, ONE dump (the recorder's per-reason limit)
    assert int(om.get_registry().total(
        "pint_tpu_health_incidents_total")) == 4
    dumps = list(tmp_path.glob("flight-*numerics_nonfinite*.json"))
    assert len(dumps) == 1
    import json

    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "numerics:nonfinite"
    assert doc["extra"]["kind"] == "fit.device"


# ------------------------------------ compile-key invariance (taps)


def test_arming_health_does_not_recompile_on_param_change():
    """The health flag is a STATIC compile-key bit: the armed step
    serves every parameter VALUE from one executable (the
    invalidate_cache(params_only) discipline), and arming adds no
    extra dispatches — one supervised dispatch returns the health
    vector alongside the step outputs."""
    import jax

    from pint_tpu.analysis import Sanitizer
    from pint_tpu.parallel import build_fit_step

    model, toas = _mk(n=120)
    fn, args, _ = build_fit_step(model, toas, health=True)
    jitted = jax.jit(fn)
    out = jitted(*args)
    assert len(out) == 5              # ... the hv rides the dispatch
    import jax.numpy as jnp

    with Sanitizer() as san:
        san.watch(jitted, "step")
        jitted(*args)
        th2 = np.asarray(args[0]).copy()
        th2[0] += 1e-9                # new parameter VALUES
        jitted(jnp.asarray(th2), *args[1:])
        assert san.compiles() == 0
        growth = san.executable_growth()["step"]
    assert growth in (0, None)


def test_health_tap_zero_extra_dispatches():
    """Dispatch-count oracle: an armed fit observes health from the
    SAME supervised dispatches a disarmed fit issues."""
    import copy

    from pint_tpu.gls import DeviceDownhillGLSFitter
    from pint_tpu.runtime import get_supervisor

    model, toas = _mk(n=120)
    m2 = copy.deepcopy(model)

    def run(mdl, armed):
        oh.configure(enabled=armed)
        reset_runtime()
        fit = DeviceDownhillGLSFitter(toas, mdl, health=armed)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            fit.fit_toas(maxiter=3)
        return get_supervisor().snapshot()["dispatches"]

    base = run(model, False)
    armed = run(m2, True)
    assert armed == base


# ----------------------------------------------- shadow sampling


def test_shadow_due_is_deterministic():
    mon = oh.configure(enabled=True, shadow_rate=4)
    got = [mon.shadow_due("k") for _ in range(9)]
    assert got == [True, False, False, False,
                   True, False, False, False, True]
    assert mon.shadow_due("other")   # per-key counters


def test_shadow_detector_detects_unsanctioned_f32(monkeypatch,
                                                  tmp_path):
    """THE drift acceptance: the exact-f64 replay sits far below the
    default band, and an UNSANCTIONED f32 demotion — forced at the
    kernel (a G9-class bug the config cannot see, so the
    route-aware auto band stays at the tight f64 default) — exceeds
    it (measured ~1.5e-4 sigma vs 1e-5) and fires the drift
    incident + flight dump through the supervisor's shadow
    scheduler. Deterministic: shadow_due fires on the first
    dispatch per key; the test only waits for the background replay
    to land."""
    import jax.numpy as jnp

    from pint_tpu.gls import _gls_kernel, gls_solve_np
    from pint_tpu.residuals import Residuals
    from pint_tpu.runtime import get_supervisor

    monkeypatch.delenv("PINT_TPU_GLS_MATMUL", raising=False)
    monkeypatch.delenv("PINT_TPU_JAC", raising=False)
    model, toas = _mk(n=200, seed=11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = np.asarray(Residuals(toas, model).time_resids)
        M, _, _ = model.designmatrix(toas)
        nvec = np.asarray(
            model.scaled_toa_uncertainty(toas) ** 2)
        F = np.asarray(model.noise_model_designmatrix(toas))
        phi = np.asarray(model.noise_model_basis_weight(toas))
    obs.configure(enabled=False, flight_dir=str(tmp_path))
    mon = oh.configure(enabled=True, shadow_rate=1)
    assert mon.drift_band == 1e-5   # the f64-route auto default
    sup = get_supervisor()

    def run(f32mm):
        out = _gls_kernel(jnp.asarray(M), jnp.asarray(F),
                          jnp.asarray(phi), jnp.asarray(r),
                          jnp.asarray(nvec), f32mm=f32mm)
        return tuple(np.asarray(o) for o in out)

    def shadow(out):
        if not bool(np.asarray(out[5])):
            return None
        mx, _, _, _ = gls_solve_np(M, F, phi, r, nvec)
        return oh.drift_sigma(out[0], out[1], mx)

    def wait_replays(n):
        t0 = time.monotonic()
        while mon._c_shadow.total() < n and \
                time.monotonic() - t0 < 60.0:
            time.sleep(0.02)
        assert mon._c_shadow.total() >= n, "shadow never replayed"

    # f64 leg: drift is the replay floor, decades below the band
    sup.dispatch(run, False, key="shadow.f64", shadow=shadow,
                 shadow_kind="gls")
    wait_replays(1)
    assert int(om.get_registry().total(
        "pint_tpu_health_shadow_drift_exceeded_total")) == 0

    # unsanctioned-f32 leg: the detector detects
    sup.dispatch(run, True, key="shadow.f32", shadow=shadow,
                 shadow_kind="gls")
    wait_replays(2)
    assert int(om.get_registry().total(
        "pint_tpu_health_shadow_drift_exceeded_total")) >= 1
    st = mon.status()
    assert st["last_incident"]["reason"] == "drift"
    assert list(tmp_path.glob("flight-*numerics_drift*.json"))


def test_drift_band_auto_follows_precision_routes(monkeypatch):
    """The route-aware default: a sanctioned f32 route raises the
    auto band above the documented f32 agreement, so a healthy TPU
    production worker never flaps /healthz on its own quantization;
    an explicit env pin always wins."""
    monkeypatch.delenv("PINT_TPU_HEALTH_DRIFT_SIGMA", raising=False)
    monkeypatch.delenv("PINT_TPU_GLS_MATMUL", raising=False)
    monkeypatch.delenv("PINT_TPU_JAC", raising=False)
    assert config.health_drift_sigma() == 1e-5   # cpu, f64 routes
    monkeypatch.setenv("PINT_TPU_GLS_MATMUL", "f32")
    assert config.health_drift_sigma() == 2e-2
    monkeypatch.setenv("PINT_TPU_GLS_MATMUL", "f64")
    assert config.health_drift_sigma() == 1e-5
    monkeypatch.setenv("PINT_TPU_JAC", "f32")
    assert config.health_drift_sigma() == 2e-2
    monkeypatch.delenv("PINT_TPU_JAC", raising=False)
    # patch the backend PEEK, not jax.default_backend: the resolver
    # deliberately refuses to initialize a backend (a wedged tunnel
    # hangs discovery), so in a fresh process the real peek is None
    monkeypatch.setattr(config, "_backend_if_initialized",
                        lambda: "tpu")
    assert config.health_drift_sigma() == 2e-2   # auto-f32 on TPU
    monkeypatch.setenv("PINT_TPU_HEALTH_DRIFT_SIGMA", "3e-4")
    assert config.health_drift_sigma() == 3e-4   # explicit pin wins


def test_streaming_shadow_replays_same_state():
    """The streaming finalize's shadow replays the SAME accumulated
    state through the numpy CG mirror — exact-f64, so the drift is
    the mirror floor, never an incident."""
    from pint_tpu.parallel.streaming import StreamingGLS

    model, toas = _mk(n=240)
    mon = oh.configure(enabled=True, shadow_rate=1)
    sg = StreamingGLS(model, toas, chunk=64, health=True)
    state = sg.accumulate(sg.th0, sg.tl0)
    out = sg.solve(state)
    assert out[5]     # ok
    t0 = time.monotonic()
    while mon._c_shadow.total() < 1 and \
            time.monotonic() - t0 < 60.0:
        time.sleep(0.02)
    assert mon._c_shadow.total() >= 1
    assert int(om.get_registry().total(
        "pint_tpu_health_shadow_drift_exceeded_total")) == 0
    # the CG effort rode the same dispatch into the registry
    st = mon.status()
    assert st["cg_iters"]["stream.solve"]["count"] >= 1


# --------------------------------------- solver-effort surfacing


def test_streaming_fitter_reports_solver_effort():
    from pint_tpu.gls import StreamingGLSFitter

    model, toas = _mk(n=240)
    fit = StreamingGLSFitter(toas, model, chunk=64)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fit.fit_toas(maxiter=6)
    assert fit.passes == len(fit.cg_iters_per_pass)
    assert fit.cg_budget == 8 * (len(fit.model.free_params) + 2)
    assert all(0 < it <= fit.cg_budget
               for it in fit.cg_iters_per_pass)
    assert fit.cg_rel_residual is not None
    assert fit.cg_rel_residual < 1e-6


# ------------------------------------------------ surfaces (healthz)


def test_healthz_and_snapshot_carry_the_verdict_block():
    mon = oh.configure(enabled=True)
    mon.observe("gls.solve", {"values": [np.array([np.nan])]},
                pool="device", key="gls.solve")
    h = om.default_health()
    assert h["numerics"]["incidents"] == 1
    assert h["numerics"]["worst"]["device/gls.solve"]["ok"] is False
    # an unresolved numerics verdict degrades /healthz like an open
    # breaker
    assert h["ok"] is False

    from pint_tpu.serve import ServeEngine

    eng = ServeEngine()
    snap = eng.metrics.snapshot()
    assert snap["health"]["incidents"] == 1
    assert snap["health"]["last_incident"]["reason"] == "nonfinite"


def test_snapshot_health_block_absent_when_disarmed(monkeypatch):
    monkeypatch.delenv("PINT_TPU_HEALTH", raising=False)
    monkeypatch.delenv("PINT_TPU_SHADOW_RATE", raising=False)
    from pint_tpu.serve import ServeEngine

    eng = ServeEngine()
    assert "health" not in eng.metrics.snapshot()


# ------------------------------------- review-fix regressions (PR 14)


def test_shadow_only_arming_records_drift():
    """$PINT_TPU_SHADOW_RATE without $PINT_TPU_HEALTH is a
    documented configuration (drift sampling only): the replayed
    drift must be RECORDED and thresholded, not silently dropped by
    the disarmed-observe fast path."""
    mon = oh.configure(enabled=False, shadow_rate=8)
    v = mon.observe("gls", {"drift_sigma": 1.0}, pool="shadow")
    assert v["checked"] and v["reasons"] == ["drift"]
    assert int(om.get_registry().total(
        "pint_tpu_health_shadow_drift_exceeded_total")) == 1
    assert oh.status() is not None    # armed via the shadow rate
    # non-drift signals stay on the zero-record fast path
    assert mon.observe("fit.device", {"chi2": 1.0}) == \
        {"ok": True, "checked": False}


def test_bad_verdict_ages_out_of_healthz():
    """One transient incident must not degrade /healthz for the life
    of the process: after the TTL, the next good observation clears
    the (pool, kind) verdict."""
    mon = oh.configure(enabled=True)
    mon.observe("gls.solve", {"values": [np.array([np.nan])]})
    assert om.default_health()["ok"] is False
    # inside the TTL a good verdict does NOT clear it (flapping
    # episodes stay visible to probes)...
    mon.observe("gls.solve", {"values": [np.array([1.0])]})
    st = mon.status()
    assert st["worst"]["device/gls.solve"]["ok"] is False
    assert st["worst"]["device/gls.solve"]["last_good_age_s"] >= 0.0
    # ...but past the TTL it does (simulated by aging the record)
    with mon._lock:
        mon._worst[("device", "gls.solve")]["t"] -= \
            oh._WORST_TTL_S + 1.0
    mon.observe("gls.solve", {"values": [np.array([1.0])]})
    assert mon.status()["worst"]["device/gls.solve"]["ok"] is True
    assert om.default_health()["ok"] is True


def test_degenerate_svd_fallback_is_not_an_incident():
    """The DESIGNED degenerate route (Cholesky ok=False ->
    warn_degenerate -> successful SVD retry) must not fire a
    numerics incident — the handled fallback is the product working,
    not a number going bad."""
    from pint_tpu.fitter import DegeneracyWarning
    from pint_tpu.gls import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = PAR + (
        "DMX_0001 0.0 1\nDMXR1_0001 54000\nDMXR2_0001 56000\n"
        "DMX_0002 0.0 1\nDMXR1_0002 54000\nDMXR2_0002 56000\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54100, 55900, 80, m, error_us=1.0, add_noise=True,
            freq_mhz=np.tile([1400.0, 820.0], 40),
            rng=np.random.default_rng(23))
    oh.configure(enabled=True)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        chi2 = GLSFitter(t, m).fit_toas(maxiter=1)
    assert np.isfinite(chi2)
    assert any(w.category is DegeneracyWarning for w in rec)
    assert int(om.get_registry().total(
        "pint_tpu_health_incidents_total")) == 0


def test_cg_budget_single_source_of_truth():
    from pint_tpu.parallel.streaming import StreamingGLS

    model, toas = _mk(n=120)
    sg = StreamingGLS(model, toas, chunk=64)
    assert sg.default_budget == 8 * (sg.p + 1)
    from pint_tpu.gls import StreamingGLSFitter

    fit = StreamingGLSFitter(toas, model, chunk=64)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fit.fit_toas(maxiter=2)
    assert fit.cg_budget == sg.default_budget


def test_armed_step_arity_is_handled_by_every_consumer(monkeypatch):
    """grid_chisq consumes the raw fit step: with health ARMED via
    env its 5-tuple must not break the 4-name unpack (the call site
    the PR-14 review caught; the multichip dryrun shares the [:4]
    idiom)."""
    from pint_tpu.gridutils import grid_chisq

    model, toas = _mk(n=100)
    monkeypatch.setenv("PINT_TPU_HEALTH", "on")
    model.F0.frozen = True
    model.invalidate_cache()
    f0 = float(model.F0.value)
    grid = grid_chisq(model, toas, ["F0"],
                      [np.array([f0 - 1e-9, f0, f0 + 1e-9])],
                      maxiter=1)
    assert grid.shape == (3,)
    assert np.all(np.isfinite(np.asarray(grid)))


def test_nonfinite_shadow_drift_is_an_incident_not_a_crash():
    """A non-finite drift is exactly the failure the shadow exists
    to catch: it must fire the drift incident (and never crash the
    recording path — int(inf) used to raise OverflowError inside
    the log2 bucketing, silently killing the daemon thread)."""
    mon = oh.configure(enabled=True, shadow_rate=1)
    mon.shadow_replay("gls", "k", lambda: float("inf"), wait=True)
    mon.shadow_replay("gls", "k", lambda: float("nan"), wait=True)
    reg = om.get_registry()
    assert int(reg.total(
        "pint_tpu_health_shadow_drift_exceeded_total")) == 2
    assert mon.status()["last_incident"]["reason"] == "drift"
    # the histogram holds only the (zero) finite samples
    assert mon.status().get("drift", {}).get(
        "gls", {"count": 0})["count"] == 0


def test_failed_chol_result_is_not_shadowed():
    """The designed degenerate route (ok=False -> SVD retry) must
    not be drifted against the mirror: the shadow closure declines
    (returns None), so a degenerate fit under full shadow sampling
    yields zero drift verdicts and zero false incidents."""
    from pint_tpu.gls import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = PAR + (
        "DMX_0001 0.0 1\nDMXR1_0001 54000\nDMXR2_0001 56000\n"
        "DMX_0002 0.0 1\nDMXR1_0002 54000\nDMXR2_0002 56000\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54100, 55900, 80, m, error_us=1.0, add_noise=True,
            freq_mhz=np.tile([1400.0, 820.0], 40),
            rng=np.random.default_rng(29))
    mon = oh.configure(enabled=True, shadow_rate=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        GLSFitter(t, m).fit_toas(maxiter=1)
    # the replays that ran all declined (ok=False) or measured the
    # f64 floor; none may have produced a drift verdict
    t0 = time.monotonic()
    while time.monotonic() - t0 < 5.0 and \
            any(th.name.startswith("pint-shadow")
                for th in __import__("threading").enumerate()):
        time.sleep(0.05)
    assert int(om.get_registry().total(
        "pint_tpu_health_shadow_drift_exceeded_total")) == 0
    assert int(om.get_registry().total(
        "pint_tpu_health_incidents_total")) == 0
