"""event_optimize CLI end-to-end, sampler autocorrelation
diagnostics, and photonphase --plotfile (reference:
src/pint/scripts/event_optimize.py MCMC + autocorr checks)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.io.fits import write_events_fits
from pint_tpu.models import get_model

NICER_MJDREF = (56658, 7.775925925925926e-4)

PAR = """
PSR J0030+0451
RAJ 00:30:27.4
DECJ 04:51:39.7
F0 205.53069927 1
F1 -4.3e-16
PEPOCH 56500
POSEPOCH 56500
DM 4.33
DMEPOCH 56500
TZRMJD 56500.0
TZRSITE @
TZRFRQ inf
UNITS TDB
"""


@pytest.fixture(scope="module")
def model():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(PAR))


def _write_pulsed_events(path, model, n=1500, seed=2, width=0.02):
    rng = np.random.default_rng(seed)
    mjd0, mjd1 = 56450.0, 56550.0
    f0 = model.F0.value
    base = rng.uniform(mjd0, mjd1, n)
    pulsed = rng.uniform(size=n) < 0.8
    phi_t = np.where(pulsed,
                     np.mod(0.4 + width * rng.standard_normal(n), 1.0),
                     rng.uniform(size=n))
    pep = model.PEPOCH.value
    dt = (base - pep) * 86400.0
    k = np.floor(dt * f0)
    f1 = model.F1.value or 0.0
    tsec = (k + phi_t) / f0 - 0.5 * f1 / f0 * ((k + phi_t) / f0) ** 2
    mjd = pep + tsec / 86400.0
    mjdrefi, mjdreff = NICER_MJDREF
    times = np.sort(((mjd - mjdrefi) - mjdreff) * 86400.0)
    write_events_fits(path, {"TIME": times}, header_extra={
        "TIMESYS": "TDB", "TIMEREF": "SOLARSYSTEM",
        "MJDREFI": mjdrefi, "MJDREFF": mjdreff, "TELESCOP": "NICER",
        "TIMEZERO": 0.0, "TIMEUNIT": "s"})


def test_event_optimize_with_template_file(tmp_path, model, capsys):
    from pint_tpu.scripts.event_optimize import main
    from pint_tpu.templates import make_template, write_template

    ev = tmp_path / "ev.fits"
    _write_pulsed_events(ev, model)
    par = tmp_path / "m.par"
    par.write_text(model.as_parfile())
    tfile = tmp_path / "prof.txt"
    write_template(make_template([("gaussian", 0.8, 0.4, 0.02)]),
                   str(tfile))
    out = tmp_path / "opt.par"
    chains = tmp_path / "chains.npz"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main([str(ev), str(par), "--mission", "nicer",
                   "--template", str(tfile),
                   "--nwalkers", "8", "--nsteps", "40",
                   "--seed", "5",
                   "--outfile", str(out),
                   "--chains-npz", str(chains)])
    assert rc == 0
    txt = capsys.readouterr().out
    assert "Read template" in txt
    assert "autocorr" in txt
    m2 = get_model(str(out))
    # F0 stays near truth (the sampler must not wander off)
    assert m2.F0.value == pytest.approx(205.53069927, abs=5e-7)
    d = np.load(chains)
    assert d["chain"].shape == (40, 8, 1)
    assert d["lnprob"].shape == (40, 8)
    assert list(d["labels"]) == ["F0"]
    assert d["tau"].shape == (1,)


def test_autocorr_time_scaling():
    """White-noise chains have tau ~= 1; strongly correlated chains
    have tau >> 1."""
    from pint_tpu.sampler import EnsembleSampler

    s = EnsembleSampler.__new__(EnsembleSampler)
    s.ndim = 2
    rng = np.random.default_rng(0)
    white = rng.standard_normal((2000, 8, 1))
    # AR(1) with phi=0.95 -> tau ~ (1+phi)/(1-phi) ~ 39
    ar = np.empty((2000, 8, 1))
    ar[0] = rng.standard_normal((8, 1))
    for t in range(1, 2000):
        ar[t] = 0.95 * ar[t - 1] + rng.standard_normal((8, 1))
    s.chain = np.concatenate([white, ar], axis=2)
    tau = s.get_autocorr_time()
    assert tau[0] < 3.0
    assert tau[1] > 15.0
    assert not s.converged(factor=1000.0)  # ar chain too short at 1000x


def test_photonphase_plotfile(tmp_path, model):
    pytest.importorskip("matplotlib")
    from pint_tpu.scripts.photonphase import main

    ev = tmp_path / "ev.fits"
    _write_pulsed_events(ev, model, n=800)
    par = tmp_path / "m.par"
    par.write_text(model.as_parfile())
    png = tmp_path / "phaseogram.png"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rc = main([str(ev), str(par), "--plotfile", str(png)])
    assert rc == 0
    assert png.exists() and png.stat().st_size > 1000
