"""Hybrid analytic/AD Jacobian oracle: closed-form design columns
(TimingModel.linear_design_columns) must equal jax.jacfwd of the
direct phase chain to rounding, and the hybrid fit step must
reproduce the full-AD step. Reference anchor: src/pint/models/
timing_model.py designmatrix (the reference's analytic d_phase_d_*
chains are exactly what these closed forms re-derive)."""
import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.ops.dd import DD, dd_frac
from pint_tpu.parallel import build_fit_step
from pint_tpu.simulation import make_fake_toas_uniform

SINK_PAR = """
PSR J1744-9999
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
PMRA 2.0 1
PMDEC -3.0 1
PX 1.0 1
F0 61.0 1
F1 -1e-15 1
DM 20.0 1
DM1 1e-4 1
PEPOCH 55000
POSEPOCH 55000
DMEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
JUMP -be X 1e-5 1
DMX_0001 1e-4 1
DMXR1_0001 54000
DMXR2_0001 55000
DMX_0002 -2e-4 1
DMXR1_0002 55000.001
DMXR2_0002 56000
WXEPOCH 55000
WXFREQ_0001 0.002
WXSIN_0001 1e-5 1
WXCOS_0001 -2e-5 1
DMWXEPOCH 55000
DMWXFREQ_0001 0.003
DMWXSIN_0001 1e-4 1
DMWXCOS_0001 2e-4 1
GLEP_1 54800
GLPH_1 0.1 1
GLF0_1 1e-8 1
GLF1_1 -1e-16 1
GLF0D_1 1e-8 1
GLTD_1 50
PWEP_1 54600
PWSTART_1 54300
PWSTOP_1 54700
PWPH_1 0.02 1
PWF0_1 2e-8 1
BINARY ELL1
PB 10.0 1
A1 5.0 1
TASC 55000.1 1
EPS1 1e-5 1
EPS2 -2e-5 1
"""

EXPECT_LINEAR = {
    "F1",  # spin phase is linear in F1+; F0 stays on AD (other
    # components scale their phases by it — Spindown docstring)
    "DM", "DM1", "DMX_0001", "DMX_0002", "JUMP1",
    "WXSIN_0001", "WXCOS_0001", "DMWXSIN_0001", "DMWXCOS_0001",
    "GLPH_1", "GLF0_1", "GLF1_1", "GLF0D_1",
    "PWPH_1", "PWF0_1",  # production-flag combos in
    # test_step_matches_full_ad now exercise PW claims too
}


@pytest.fixture(scope="module")
def sink():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(SINK_PAR))
        toas = make_fake_toas_uniform(
            54100, 55900, 150, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(11))
        for i, f in enumerate(toas.flags):
            f["be"] = "X" if i % 3 else "Y"
        m.get_cache(toas)
    return m, toas


def test_linear_claims(sink):
    m, _ = sink
    assert m.linear_design_names() == EXPECT_LINEAR


def test_columns_match_jacfwd(sink):
    """Every closed-form column equals the jacfwd column to rounding
    — including the TZR-row subtraction and the binary's response to
    pre-binary delay shifts (the stage-sensitivity JVP)."""
    m, toas = sink
    phase_fn, (free, frozen) = m._build_phase_fn()
    cache = m.get_cache(toas)
    fr, fz, th, tl, fh, fl = m._pack()
    batch = cache["batch"]
    sc = {k: v for k, v in cache.items() if k != "batch"}
    th, tl, fh, fl = map(jnp.asarray, (th, tl, fh, fl))

    def phase_f64(thx):
        ph, _ = phase_fn(thx, tl, fh, fl, batch, sc)
        f = dd_frac(ph)
        return f.hi + f.lo

    jacfull = np.asarray(jax.jacfwd(phase_f64)(th))
    pv = {nm: DD(th[i], tl[i]) for i, nm in enumerate(fr)}
    pv.update({nm: DD(fh[j], fl[j]) for j, nm in enumerate(fz)})
    lin = m.linear_design_names()
    cols = m.linear_design_columns(pv, batch, sc, lin)
    assert set(cols) == lin
    for nm in sorted(lin):
        a = np.asarray(cols[nm])
        b = jacfull[:, fr.index(nm)]
        scale = max(np.max(np.abs(b)), 1e-300)
        # the DM column is a cancellation remnant (TZR at the same
        # frequency subtracts a near-equal constant), so also accept
        # machine-eps-level ABSOLUTE agreement vs the pre-cancellation
        # magnitude (~K/nu^2 * S ~ 0.3 here)
        ok = (np.max(np.abs(a - b)) / scale < 1e-12
              or np.max(np.abs(a - b)) < 1e-13)
        assert ok, (nm, np.max(np.abs(a - b)), scale)


@pytest.mark.parametrize("flags", [
    dict(),                                    # plain f64
    dict(anchored=True),                       # anchored f64
    dict(anchored=True, jac_f32=True,
         matmul_f32=True),                     # full production config
])
def test_step_matches_full_ad(sink, flags):
    """The hybrid step's (dparams, cov, chi2, resids) match the
    full-AD step built with identical flags. DM/DM1 are frozen here:
    free full-span DMX windows make a free DM exactly collinear
    (singular normal matrix in BOTH builds — the bench.py modeling
    note)."""
    par = SINK_PAR.replace("DM 20.0 1", "DM 20.0") \
                  .replace("DM1 1e-4 1", "DM1 1e-4")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(
            54100, 55900, 150, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(11))
        for i, f in enumerate(toas.flags):
            f["be"] = "X" if i % 3 else "Y"
    fn_h, args_h, names = build_fit_step(m, toas, hybrid_jac=True,
                                         **flags)
    fn_f, args_f, _ = build_fit_step(m, toas, hybrid_jac=False,
                                     **flags)
    out_h = jax.jit(fn_h)(*args_h)
    out_f = jax.jit(fn_f)(*args_f)
    dp_h, dp_f = np.asarray(out_h[0]), np.asarray(out_f[0])
    sig = np.sqrt(np.abs(np.diag(np.asarray(out_f[1]))))
    # columns agree to rounding (test_columns_match_jacfwd), but the
    # solve amplifies eps-level differences by the condition number —
    # this sink's columns span ~20 decades and carry several
    # near-collinear pairs (glitch vs F1, WaveX vs binary). In f64
    # that amplification stays below 1e-4 sigma. At f32 column
    # precision the same amplification acts on ~1e-7 quantization:
    # the hybrid-vs-AD delta is bounded by the f32 config's own error
    # scale on a model this degenerate (its documented contract is
    # <1e-2 sigma at benchmark conditioning), so 5e-2 sigma here.
    tol_sig = 5e-2 if flags.get("jac_f32") else 1e-4
    assert np.max(np.abs(dp_h - dp_f) / np.where(sig > 0, sig, 1.0)) \
        < tol_sig
    assert float(out_h[2]) == pytest.approx(float(out_f[2]),
                                            rel=1e-6)
    np.testing.assert_allclose(np.asarray(out_h[3]),
                               np.asarray(out_f[3]),
                               rtol=0, atol=1e-12)


def test_f32mm_degeneracy_rescue(sink):
    """On a near-rank-deficient model the f32-accumulated normal
    matrix can lose positive definiteness and NaN the Cholesky; the
    in-kernel lax.cond retry with f64-accumulated matmuls must
    produce a finite step (this exact sink reproduced the NaN before
    the retry existed)."""
    par = SINK_PAR.replace("DM 20.0 1", "DM 20.0") \
                  .replace("DM1 1e-4 1", "DM1 1e-4")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(
            54100, 55900, 150, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(11))
        for i, f in enumerate(toas.flags):
            f["be"] = "X" if i % 3 else "Y"
    fn, args, _ = build_fit_step(m, toas, matmul_f32=True,
                                 hybrid_jac=False)
    out = jax.jit(fn)(*args)
    assert np.all(np.isfinite(np.asarray(out[0])))
    assert np.all(np.isfinite(np.asarray(out[1])))
    assert np.isfinite(float(out[2]))


SINK2_PAR = """
PSR J0002-0002
RAJ 06:00:00.0 1
DECJ -5:00:00.0 1
F0 305.0 1
F1 -3e-16 1
DM 11.0
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
NE_SW 6.0 1
FD1 1e-5 1
FD1JUMP -be X 2e-5 1
CM 0.02 1
CM1 1e-10 1
TNCHROMIDX 4.0
CMX_0001 0.01 1
CMXR1_0001 54000
CMXR2_0001 55200
CMWXEPOCH 55000
CMWXFREQ_0001 0.0015
CMWXSIN_0001 0.003 1
CMWXCOS_0001 -0.002 1
SWXDM_0001 1e-4 1
SWXR1_0001 54000
SWXR2_0001 56000
PWEP_1 55000
PWSTART_1 54500
PWSTOP_1 55500
PWPH_1 0.01 1
PWF0_1 1e-8 1
PWF1_1 -1e-17 1
"""

EXPECT_LINEAR2 = {
    "F1",
    "NE_SW", "FD1", "FD1JUMP1", "CM", "CM1", "CMX_0001",
    "CMWXSIN_0001", "CMWXCOS_0001", "SWXDM_0001",
    "PWPH_1", "PWF0_1", "PWF1_1",
}


def test_chromatic_solar_fd_columns_match_jacfwd():
    """The chromatic/solar-wind/FD claim families against jacfwd
    (two observing frequencies so the nu-scalings are exercised)."""
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(SINK2_PAR))
        mjds = np.linspace(54100, 55900, 120)
        freqs = np.tile([1400.0, 820.0], 60)
        toas = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0, freq_mhz=freqs, add_noise=True,
            rng=np.random.default_rng(21))
        for i, f in enumerate(toas.flags):
            f["be"] = "X" if i % 2 else "Y"
        m.get_cache(toas)
    assert m.linear_design_names() == EXPECT_LINEAR2
    phase_fn, (free, frozen) = m._build_phase_fn()
    cache = m.get_cache(toas)
    fr, fz, th, tl, fh, fl = m._pack()
    batch = cache["batch"]
    sc = {k: v for k, v in cache.items() if k != "batch"}
    th, tl, fh, fl = map(jnp.asarray, (th, tl, fh, fl))

    def phase_f64(thx):
        ph, _ = phase_fn(thx, tl, fh, fl, batch, sc)
        f = dd_frac(ph)
        return f.hi + f.lo

    jacfull = np.asarray(jax.jacfwd(phase_f64)(th))
    pv = {nm: DD(th[i], tl[i]) for i, nm in enumerate(fr)}
    pv.update({nm: DD(fh[j], fl[j]) for j, nm in enumerate(fz)})
    cols = m.linear_design_columns(pv, batch, sc, EXPECT_LINEAR2)
    for nm in sorted(EXPECT_LINEAR2):
        a = np.asarray(cols[nm])
        b = jacfull[:, fr.index(nm)]
        scale = max(np.max(np.abs(b)), 1e-300)
        ok = (np.max(np.abs(a - b)) / scale < 1e-12
              or np.max(np.abs(a - b)) < 1e-13)
        assert ok, (nm, np.max(np.abs(a - b)), scale)


def test_env_off_disables(sink, monkeypatch):
    m, toas = sink
    monkeypatch.setenv("PINT_TPU_HYBRID_JAC", "off")
    from pint_tpu.parallel.fit_step import _use_hybrid_jac

    assert _use_hybrid_jac(None) is False
    monkeypatch.setenv("PINT_TPU_HYBRID_JAC", "on")
    assert _use_hybrid_jac(None) is True


def test_phoff_column(sink):
    """PHOFF (apply_to_tzr=False) gets a -1 column with no TZR
    subtraction — the exact form whose absence made PHOFF silently
    inert once before (CLAUDE.md)."""
    par = SINK_PAR.replace("JUMP -be X 1e-5 1", "PHOFF 0.01 1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(
            54100, 55900, 60, m, error_us=1.0,
            rng=np.random.default_rng(12))
    assert "PHOFF" in m.linear_design_names()
    phase_fn, (free, _) = m._build_phase_fn()
    cache = m.get_cache(toas)
    fr, fz, th, tl, fh, fl = m._pack()
    th, tl, fh, fl = map(jnp.asarray, (th, tl, fh, fl))
    sc = {k: v for k, v in cache.items() if k != "batch"}
    pv = {nm: DD(th[i], tl[i]) for i, nm in enumerate(fr)}
    pv.update({nm: DD(fh[j], fl[j]) for j, nm in enumerate(fz)})
    cols = m.linear_design_columns(pv, cache["batch"], sc, {"PHOFF"})
    np.testing.assert_allclose(np.asarray(cols["PHOFF"]), -1.0)
