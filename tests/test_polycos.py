"""Polycos (reference: src/pint/polycos.py): generated blocks must
reproduce the full timing chain's absolute phase to sub-µturn inside
their spans, the spin frequency must match d_phase_d_toa, and the
TEMPO-format file round-trips."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.polycos import Polycos

PAR = """PSR J1234+56
RAJ 12:34:00.0
DECJ 56:00:00.0
F0 218.811843796
F1 -4.08e-16
PEPOCH 55000
DM 15.99
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


@pytest.fixture(scope="module")
def model():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(PAR))


@pytest.fixture(scope="module")
def polycos(model):
    return Polycos.generate_polycos(model, 55000.0, 55000.25, "gbt",
                                    seg_length_min=60.0, ncoeff=12,
                                    obsfreq_mhz=1400.0)


def test_polycos_match_full_chain(model, polycos):
    """Random epochs inside the span: polyco phase == model.phase to
    sub-µturn (the TEMPO folding requirement)."""
    from pint_tpu.toa import get_TOAs_array

    rng = np.random.default_rng(0)
    mjds = np.sort(rng.uniform(55000.003, 55000.247, 40))
    pi, pf = polycos.eval_abs_phase(mjds)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        toas = get_TOAs_array(mjds, obs="gbt", freqs=1400.0,
                              errors=1.0)
        ph = model.phase(toas, abs_phase=True)
    full_int = np.asarray(ph.int)
    full_frac = np.asarray(ph.frac)
    # compare total phase difference mod 1 (int/frac conventions may
    # split differently around the wrap)
    d = (pi + pf) - (full_int + full_frac)
    d = d - np.round(d)
    assert np.max(np.abs(d)) < 1e-6  # turns


def test_polycos_spin_freq(model, polycos):
    """eval_spin_freq matches the full-pipeline d_phase_d_toa."""
    from pint_tpu.toa import get_TOAs_array

    mjds = np.linspace(55000.02, 55000.23, 9)
    f_poly = polycos.eval_spin_freq(mjds)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        toas = get_TOAs_array(mjds, obs="gbt", freqs=1400.0,
                              errors=1.0)
    f_full = model.d_phase_d_toa(toas)
    np.testing.assert_allclose(f_poly, f_full, rtol=1e-9)
    # and the topocentric Doppler is visible (not a constant F0)
    assert np.ptp(f_poly) / 218.8 > 1e-7


def test_polyco_file_roundtrip(tmp_path, polycos):
    p = tmp_path / "polyco.dat"
    polycos.write_polyco_file(str(p))
    back = Polycos.read_polyco_file(str(p))
    assert len(back.entries) == len(polycos.entries)
    mjds = np.linspace(55000.01, 55000.24, 25)
    pi1, pf1 = polycos.eval_abs_phase(mjds)
    pi2, pf2 = back.eval_abs_phase(mjds)
    d = (pi1 + pf1) - (pi2 + pf2)
    d = d - np.round(d)
    # RPHASE carries 6 decimals in the TEMPO layout
    assert np.max(np.abs(d)) < 5e-6
    f1 = polycos.eval_spin_freq(mjds)
    f2 = back.eval_spin_freq(mjds)
    np.testing.assert_allclose(f1, f2, rtol=1e-12)
