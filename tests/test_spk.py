"""SPK/DAF reader test against a synthetic kernel built in-test.

No real .bsp ships in this environment, so we construct a minimal valid
little-endian DAF/SPK file with one type-2 segment whose Chebyshev
coefficients encode a known trajectory, and check the reader + evaluator
reproduce it (including the center-chain walk)."""

import numpy as np

from pint_tpu.ephemeris.spk import SPKEphemeris


def _write_daf_spk(path, segments):
    """segments: list of (target, center, init, intlen, coeffs(n,3,deg))."""
    # Layout: record 1 = file record; record 2 = summary record;
    # record 3 = name record; data from record 4.
    nd, ni = 2, 6
    ss = nd + (ni + 1) // 2
    data_words = []
    seg_meta = []
    word_ptr = 3 * 128 + 1  # 1-based word index of first data word
    for target, center, init, intlen, coeffs in segments:
        n, ncomp, deg = coeffs.shape
        rsize = 2 + ncomp * deg
        start = word_ptr
        for i in range(n):
            mid = init + (i + 0.5) * intlen
            rad = intlen / 2.0
            data_words.extend([mid, rad])
            data_words.extend(coeffs[i].ravel().tolist())
        data_words.extend([init, intlen, float(rsize), float(n)])
        end = start + n * rsize + 4 - 1
        word_ptr = end + 1
        et0, et1 = init, init + n * intlen
        seg_meta.append((et0, et1, target, center, 1, 2, start, end))

    # file record
    fr = bytearray(1024)
    fr[0:8] = b"DAF/SPK "
    fr[8:12] = np.int32(nd).tobytes()
    fr[12:16] = np.int32(ni).tobytes()
    fr[16:76] = b"synthetic kernel".ljust(60)
    fr[76:80] = np.int32(2).tobytes()   # FWARD
    fr[80:84] = np.int32(2).tobytes()   # BWARD
    fr[84:88] = np.int32(word_ptr).tobytes()  # FREE
    fr[88:96] = b"LTL-IEEE"
    # summary record
    sr = np.zeros(128)
    sr[0] = 0.0  # next
    sr[1] = 0.0  # prev
    sr[2] = float(len(seg_meta))
    for i, (et0, et1, tgt, ctr, frame, typ, start, end) in enumerate(seg_meta):
        off = 3 + i * ss
        sr[off] = et0
        sr[off + 1] = et1
        ints = np.array([tgt, ctr, frame, typ, start, end], dtype=np.int32)
        sr[off + 2:off + 5] = np.frombuffer(ints.tobytes(), dtype=np.float64)
    nr = b" " * 1024  # name record
    body = np.array(data_words, dtype=np.float64).tobytes()
    with open(path, "wb") as f:
        f.write(bytes(fr))
        f.write(sr.tobytes())
        f.write(nr)
        f.write(body)


def test_spk_roundtrip(tmp_path):
    # EMB wrt SSB: quadratic trajectory x = 1e6 + 5 t_rel km (per comp
    # scaled), encoded in Chebyshev basis per 86400-s interval
    init = 0.0
    intlen = 86400.0
    n = 4
    deg = 4
    coeffs_emb = np.zeros((n, 3, deg))
    coeffs_moon = np.zeros((n, 3, deg))
    for i in range(n):
        # pos(s) = a + b·T1(s) + c·T2(s), s in [-1,1]
        coeffs_emb[i, 0, :3] = [1.0e6 + i, 50.0, 7.0]
        coeffs_emb[i, 1, :3] = [2.0e6 - i, -30.0, 3.0]
        coeffs_emb[i, 2, :3] = [5.0e5, 10.0, 0.5]
        coeffs_moon[i, 0, :3] = [3.8e5, 5.0, 0.0]
    path = tmp_path / "synthetic.bsp"
    _write_daf_spk(str(path), [
        (3, 0, init, intlen, coeffs_emb),     # EMB wrt SSB
        (399, 3, init, intlen, coeffs_moon),  # "Earth" wrt EMB
    ])
    eph = SPKEphemeris(str(path))
    # mid of interval 1: s=0 → pos = a - c (T2(0)=-1)
    tdb_mjd = 51544.5 + 1.5  # ET = 1.5 days → interval 1 center
    p, v = eph.ssb_posvel(3, tdb_mjd)
    want_x = (1.0e6 + 1 - 7.0) * 1e3
    np.testing.assert_allclose(p[0, 0], want_x, rtol=1e-14)
    # velocity: d/det [b T1 + c T2] = (b + 4 c s)/rad; s=0 → b/rad
    np.testing.assert_allclose(v[0, 0], 50.0 / (intlen / 2) * 1e3, rtol=1e-12)
    # chain: earth = EMB + moon-segment offset
    pe, _ = eph.ssb_posvel("earth", tdb_mjd)
    np.testing.assert_allclose(pe[0, 0], want_x + (3.8e5 + 5 * 0 - 0) * 1e3,
                               rtol=1e-14)
    # interior point: day 0.75 → interval 0, s = +0.5 →
    # f = a + b·T1(0.5) + c·T2(0.5) = a + 0.5·b − 0.5·c
    p2, _ = eph.ssb_posvel(3, 51544.5 + 0.75)
    want2 = (1.0e6 + 0 + 0.5 * 50.0 - 0.5 * 7.0) * 1e3
    np.testing.assert_allclose(p2[0, 0], want2, rtol=1e-14)


def test_spk_rejects_garbage(tmp_path):
    p = tmp_path / "bad.bsp"
    p.write_bytes(b"NOT A DAF" + b"\0" * 2000)
    try:
        SPKEphemeris(str(p))
        assert False, "should have raised"
    except ValueError as e:
        assert "not an SPK" in str(e)
