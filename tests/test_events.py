"""Photon/event vertical: FITS I/O round-trip, event loading, event
statistics, template ML recovery, and the photonphase CLI end-to-end
(reference: src/pint/event_toas.py, eventstats.py, templates/,
scripts/photonphase.py; test pattern per SURVEY.md §4.6)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.eventstats import h_sig, hm, hmw, sf_hm, sig2sigma, z2m
from pint_tpu.io.fits import read_events_fits, read_fits, write_events_fits
from pint_tpu.models import get_model
from pint_tpu.templates import (
    LCFitter,
    LCGaussian,
    LCLorentzian,
    LCTemplate,
    LCVonMises,
)

NICER_MJDREF = (56658, 7.775925925925926e-4)


# ---------------------------------------------------------------- FITS


def test_fits_roundtrip(tmp_path):
    path = tmp_path / "ev.fits"
    rng = np.random.default_rng(0)
    times = np.sort(rng.uniform(0, 1e6, 500))
    weights = rng.uniform(0.1, 1.0, 500).astype(np.float32)
    pha = rng.integers(0, 256, 500)
    write_events_fits(path, {"TIME": times, "WEIGHT": weights,
                             "PHA": pha},
                      header_extra={"TIMESYS": "TDB",
                                    "MJDREFI": 56658,
                                    "MJDREFF": NICER_MJDREF[1],
                                    "TELESCOP": "NICER"})
    cols, header = read_events_fits(path)
    np.testing.assert_allclose(cols["TIME"], times, rtol=0, atol=0)
    np.testing.assert_allclose(cols["WEIGHT"], weights, rtol=1e-7)
    assert np.all(cols["PHA"] == pha)
    assert header["TIMESYS"] == "TDB"
    assert header["MJDREFI"] == 56658
    hdus = read_fits(path)
    assert len(hdus) == 2  # primary + events


def test_fits_file_size_is_block_aligned(tmp_path):
    path = tmp_path / "b.fits"
    write_events_fits(path, {"TIME": np.arange(3.0)})
    assert path.stat().st_size % 2880 == 0


# ------------------------------------------------------- event loading


@pytest.fixture(scope="module")
def pulsar_model():
    par = """
PSR J0030+0451
RAJ 00:30:27.4
DECJ 04:51:39.7
F0 205.53069927
F1 -4.3e-16
PEPOCH 56500
POSEPOCH 56500
DM 4.33
DMEPOCH 56500
TZRMJD 56500.0
TZRSITE @
TZRFRQ inf
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(par))


def _write_pulsed_events(path, model, n=2000, seed=1, weights=False,
                         frac_pulsed=0.7, width=0.03):
    """Simulate barycentric photon arrival times whose phases follow a
    Gaussian peak at phi=0.3 (+ uniform background) under ``model``."""
    rng = np.random.default_rng(seed)
    mjd0, mjd1 = 56400.0, 56600.0
    f0 = model.F0.value
    # draw target phases, then place photons on the model's phase grid:
    # t = t0 + (k + phi)/f0 to f64 accuracy is plenty for event tests
    base = rng.uniform(mjd0, mjd1, n)
    pulsed = rng.uniform(size=n) < frac_pulsed
    phi_t = np.where(pulsed,
                     np.mod(0.3 + width * rng.standard_normal(n), 1.0),
                     rng.uniform(size=n))
    pep = model.PEPOCH.value
    dt = (base - pep) * 86400.0
    k = np.floor(dt * f0)
    f1 = model.F1.value or 0.0
    tsec = (k + phi_t) / f0 - 0.5 * f1 / f0 * ((k + phi_t) / f0) ** 2
    mjd = pep + tsec / 86400.0
    mjdrefi, mjdreff = NICER_MJDREF
    times = ((mjd - mjdrefi) - mjdreff) * 86400.0
    cols = {"TIME": np.sort(times)}
    if weights:
        w = np.where(pulsed, rng.uniform(0.5, 1.0, n),
                     rng.uniform(0.0, 0.5, n))
        cols["WEIGHT"] = w[np.argsort(times)]
    write_events_fits(path, cols, header_extra={
        "TIMESYS": "TDB", "TIMEREF": "SOLARSYSTEM",
        "MJDREFI": mjdrefi, "MJDREFF": mjdreff, "TELESCOP": "NICER",
        "TIMEZERO": 0.0, "TIMEUNIT": "s"})


def test_load_fits_toas_phases_cluster(tmp_path, pulsar_model):
    from pint_tpu.event_toas import load_NICER_TOAs

    path = tmp_path / "nicer.fits"
    _write_pulsed_events(path, pulsar_model, n=1500, frac_pulsed=1.0,
                         width=0.01)
    toas = load_NICER_TOAs(path)
    assert toas.ntoas == 1500
    assert all(o == "barycenter" for o in toas.obs)
    phases = np.mod(np.asarray(pulsar_model.phase(toas).frac), 1.0)
    # simulated peak at 0.3 with width 0.01 (spindown phase only: the
    # quadratic F1 inversion is approximate at the <1e-3 cycle level)
    d = np.abs(np.mod(phases - 0.3 + 0.5, 1.0) - 0.5)
    assert np.median(d) < 0.02


def test_load_fits_toas_rejects_tt(tmp_path):
    from pint_tpu.event_toas import load_fits_TOAs

    path = tmp_path / "tt.fits"
    write_events_fits(path, {"TIME": np.arange(10.0)},
                      header_extra={"TIMESYS": "TT", "MJDREFI": 56658,
                                    "MJDREFF": NICER_MJDREF[1]})
    with pytest.raises(NotImplementedError):
        load_fits_TOAs(path)


def test_event_weights_flag_roundtrip(tmp_path, pulsar_model):
    from pint_tpu.event_toas import get_event_weights, load_fits_TOAs

    path = tmp_path / "w.fits"
    _write_pulsed_events(path, pulsar_model, n=200, weights=True)
    toas = load_fits_TOAs(path, mission="nicer", weightcolumn="WEIGHT")
    w = get_event_weights(toas)
    assert w is not None and w.shape == (200,)
    assert np.all((w >= 0) & (w <= 1))


# ---------------------------------------------------------- eventstats


def test_z2m_uniform_null():
    rng = np.random.default_rng(2)
    phases = rng.uniform(size=20000)
    # under the null Z^2_m ~ chi^2_{2m}: mean 2m
    assert z2m(phases, m=2) < 20.0
    assert hm(phases) < 30.0


def test_z2m_strong_signal():
    rng = np.random.default_rng(3)
    phases = np.mod(0.5 + 0.02 * rng.standard_normal(2000), 1.0)
    z = z2m(phases, m=2)
    h = hm(phases)
    assert z > 1000.0
    assert h > 1000.0
    assert h_sig(h) > 10.0


def test_hmw_weights_suppress_background():
    rng = np.random.default_rng(4)
    sig = np.mod(0.2 + 0.02 * rng.standard_normal(500), 1.0)
    bkg = rng.uniform(size=5000)
    phases = np.concatenate([sig, bkg])
    w = np.concatenate([np.full(500, 0.9), np.full(5000, 0.05)])
    h_w = hmw(phases, w)
    h_unw = hm(phases)
    assert h_w > h_unw  # weighting recovers the buried signal


def test_sig2sigma_values():
    from scipy.stats import norm

    assert sig2sigma(norm.sf(3.0)) == pytest.approx(3.0, rel=1e-9)
    assert sig2sigma(norm.sf(8.0)) == pytest.approx(8.0, rel=1e-6)
    # tiny probabilities go through the log-asymptotic branch
    assert sig2sigma(1e-320) == pytest.approx(38.3, abs=0.5)
    assert sf_hm(50.0) == pytest.approx(np.exp(-20.0))


# ----------------------------------------------------------- templates


def test_template_pdf_normalized():
    for prim in (LCGaussian(), LCVonMises(), LCLorentzian()):
        t = LCTemplate([prim], norms=[0.6], locs=[0.4], widths=[0.05])
        grid = np.linspace(0, 1, 20001)[:-1]
        integral = np.mean(t(grid))
        assert integral == pytest.approx(1.0, rel=1e-3), prim.name


def test_template_random_matches_pdf():
    t = LCTemplate([LCGaussian()], norms=[0.8], locs=[0.35],
                   widths=[0.04])
    rng = np.random.default_rng(5)
    draws = t.random(40000, rng=rng)
    hist, edges = np.histogram(draws, bins=50, range=(0, 1),
                               density=True)
    centers = 0.5 * (edges[:-1] + edges[1:])
    np.testing.assert_allclose(hist, t(centers), atol=0.35)


def test_lcfitter_recovers_injected_template():
    truth = LCTemplate([LCGaussian()], norms=[0.65], locs=[0.3],
                       widths=[0.03])
    rng = np.random.default_rng(6)
    phases = truth.random(8000, rng=rng)
    fit_t = LCTemplate([LCGaussian()], norms=[0.4], locs=[0.35],
                       widths=[0.06])
    fitter = LCFitter(fit_t, phases)
    ll0 = fitter.loglikelihood()
    res = fitter.fit()
    assert res["loglikelihood"] > ll0
    assert fit_t.locs[0] == pytest.approx(0.3, abs=0.005)
    assert fit_t.widths[0] == pytest.approx(0.03, abs=0.005)
    assert fit_t.norms[0] == pytest.approx(0.65, abs=0.05)


def test_lcfitter_weighted():
    truth = LCTemplate([LCVonMises()], norms=[0.7], locs=[0.6],
                       widths=[0.05])
    rng = np.random.default_rng(7)
    sig = truth.random(3000, rng=rng)
    bkg = rng.uniform(size=3000)
    phases = np.concatenate([sig, bkg])
    w = np.concatenate([np.full(3000, 0.95), np.full(3000, 0.05)])
    fit_t = LCTemplate([LCVonMises()], norms=[0.5], locs=[0.55],
                       widths=[0.08])
    fitter = LCFitter(fit_t, phases, weights=w)
    fitter.fit()
    assert fit_t.locs[0] == pytest.approx(0.6, abs=0.01)


# ------------------------------------------------------------- the CLI


def test_photonphase_cli(tmp_path, pulsar_model):
    from pint_tpu.scripts.photonphase import main

    ev = tmp_path / "events.fits"
    _write_pulsed_events(ev, pulsar_model, n=1200, frac_pulsed=0.8,
                         width=0.02)
    par = tmp_path / "model.par"
    par.write_text(pulsar_model.as_parfile())
    out = tmp_path / "out.fits"
    npz = tmp_path / "phases.npz"
    rc = main([str(ev), str(par), "--outfile", str(out),
               "--npz", str(npz)])
    assert rc == 0
    cols, header = read_events_fits(out)
    assert "PULSE_PHASE" in cols
    assert np.all((cols["PULSE_PHASE"] >= 0)
                  & (cols["PULSE_PHASE"] < 1))
    d = np.load(npz)
    np.testing.assert_allclose(d["phases"], cols["PULSE_PHASE"])
    # the pulsation must be detected
    from pint_tpu.eventstats import hm

    assert hm(cols["PULSE_PHASE"]) > 100.0
