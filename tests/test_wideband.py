"""Wideband fitting tests (reference analogs:
tests/test_widebandTOA_fitting.py, test_wideband_dm_data.py,
test_dmefac_dmequad.py): DM-channel flag handling, DM residuals, joint
fit recovery incl. DMX windows, DMJUMP semantics, DMEFAC scaling, and
the wideband-vs-narrowband DM-uncertainty improvement."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.fitter import Fitter
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.wideband import DMResiduals, get_wideband_dm, has_wideband_dm
from pint_tpu.wideband_fitter import (
    WidebandDownhillFitter,
    WidebandTOAFitter,
)

PAR = """PSR J1713+0747
RAJ 17:13:49.53 1
DECJ 07:47:37.5 1
F0 218.811843796082 1
F1 -4.08e-16 1
PEPOCH 55000.0
POSEPOCH 55000.0
DM 15.97 1
DMEPOCH 55000.0
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
DMX_0001 0.0 1
DMXR1_0001 54490.0
DMXR2_0001 54750.0
DMX_0002 0.0 1
DMXR1_0002 54750.1
DMXR2_0002 55010.0
"""


def _sim_wb(par=PAR, n=120, dm_err=2e-4, seed=3, dm_offsets=None):
    """Simulate narrowband TOAs, then attach synthetic -pp_dm channels:
    model DM + optional injected offsets + Gaussian noise at dm_err."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        rng = np.random.default_rng(seed)
        t = make_fake_toas_uniform(54500, 55500, n, m, error_us=1.0,
                                   add_noise=True, rng=rng)
        # set any flags BEFORE the model caches its selection masks
        offsets = dm_offsets(t) if dm_offsets is not None else 0.0
        dm_true = DMResiduals(t, m).model_dm() + offsets
        dm_meas = dm_true + rng.standard_normal(t.ntoas) * dm_err
        for i, f in enumerate(t.flags):
            f["pp_dm"] = f"{dm_meas[i]:.10f}"
            f["pp_dme"] = f"{dm_err:g}"
    return m, t


def test_flag_parsing_and_detection():
    m, t = _sim_wb(n=20)
    assert has_wideband_dm(t)
    dm, dme = get_wideband_dm(t)
    assert dm.shape == (20,) and np.all(dme == 2e-4)
    r = DMResiduals(t, m)
    assert np.std(r.resids) < 3 * 2e-4
    assert 0.3 < r.chi2 / t.ntoas < 3.0


def test_missing_dme_raises():
    m, t = _sim_wb(n=10)
    for f in t.flags:
        f.pop("pp_dme")
    with pytest.raises(ValueError, match="pp_dme"):
        get_wideband_dm(t)


def test_auto_picks_wideband():
    m, t = _sim_wb(n=20)
    f = Fitter.auto(t, m)
    assert isinstance(f, WidebandDownhillFitter)


def test_wideband_fit_recovers_dm_and_dmx():
    m, t = _sim_wb(n=150, seed=8)
    truth = {n: m.get_param(n).value
             for n in ("DM", "DMX_0001", "DMX_0002", "F0")}
    m.DM.add_delta(3e-3)
    m.get_param("DMX_0001").add_delta(1e-3)
    m.F0.add_delta(5e-11)
    m.invalidate_cache(params_only=True)
    f = WidebandTOAFitter(t, m)
    f.fit_toas(maxiter=3)
    for k, v in truth.items():
        err = f.errors.get(k)
        assert err is not None and err > 0, k
        assert abs(m.get_param(k).value - v) < 5 * err, \
            (k, m.get_param(k).value - v, err)


def test_wideband_downhill_matches_plain():
    m1, t = _sim_wb(n=100, seed=5)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m2 = get_model(io.StringIO(PAR))
    for m in (m1, m2):
        m.DM.add_delta(2e-3)
        m.invalidate_cache(params_only=True)
    WidebandTOAFitter(t, m1).fit_toas(maxiter=3)
    WidebandDownhillFitter(t, m2).fit_toas(maxiter=10)
    assert m1.DM.value == pytest.approx(m2.DM.value, abs=5e-7)


def test_wideband_constrains_dm_better_than_narrowband():
    """Single-frequency narrowband data cannot constrain DM (degenerate
    with offset); the DM channel restores the constraint."""
    m, t = _sim_wb(n=100, seed=11)
    fw = WidebandTOAFitter(t, m)
    fw.fit_toas()
    wb_err = fw.errors["DM"]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m2 = get_model(io.StringIO(PAR))
    from pint_tpu.fitter import WLSFitter

    fn = WLSFitter(t, m2)
    fn.fit_toas()
    nb_err = fn.errors["DM"]
    assert wb_err < 0.1 * nb_err, (wb_err, nb_err)
    # and the wideband DM error is of order dm_err/sqrt(N)
    assert wb_err < 5 * 2e-4 / np.sqrt(100)


def test_dmjump_shifts_measured_dm():
    """DMJUMP enters the model-side DM with a minus sign (reference:
    DispersionJump.jump_dm), so a subset whose measured DM reads HIGH
    by b fits DMJUMP = -b."""
    par = PAR + "DMJUMP -fe L-wide 0.0 1\n"
    offset = 5e-3

    def inject(t):
        # half the TOAs are L-wide: their *measured* DM is offset
        out = np.zeros(t.ntoas)
        for i, f in enumerate(t.flags):
            if i % 2 == 0:
                f["fe"] = "L-wide"
                out[i] = offset
            else:
                f["fe"] = "S-wide"
        return out

    m, t = _sim_wb(par=par, n=120, seed=13, dm_offsets=inject)
    f = WidebandTOAFitter(t, m)
    f.fit_toas(maxiter=3)
    dmj = m.get_param("DMJUMP1")
    err = f.errors["DMJUMP1"]
    assert abs(dmj.value - (-offset)) < 5 * err, (dmj.value, err)


def test_dmefac_scales_dm_errors():
    par = PAR + "DMEFAC -fe L-wide 2.5\n"
    m, t = _sim_wb(par=par, n=40, seed=2)
    for f in t.flags:
        f["fe"] = "L-wide"
    sig = m.scaled_dm_uncertainty(t)
    np.testing.assert_allclose(sig, 2.5 * 2e-4)


def test_wideband_toa_residuals_class():
    """WidebandTOAResiduals combines the TOA and DM channels
    (reference: residuals.WidebandTOAResiduals)."""
    from pint_tpu.wideband import (CombinedResiduals, DMResiduals,
                                   WidebandTOAResiduals)

    model, toas = _sim_wb()
    wr = WidebandTOAResiduals(toas, model)
    assert wr.chi2 == pytest.approx(wr.toa.chi2 + wr.dm.chi2)
    assert wr.resids.shape == (2 * toas.ntoas,)
    assert wr.dof == 2 * toas.ntoas - len(model.free_params) - 1
    assert wr.reduced_chi2 == pytest.approx(wr.chi2 / wr.dof)
    # generic combiner works over arbitrary channels
    cr = CombinedResiduals([wr.toa, DMResiduals(toas, model)])
    assert cr.chi2 == pytest.approx(wr.chi2)
