"""The shipped examples must keep running end-to-end — they are the
switching user's first contact (MIGRATION.md/examples). Subprocess
runs on the CPU backend; marked slow (compile-dominated)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = ["fit_ngc6440e", "simulate_and_fit", "noise_gls_fit",
            "wideband_fit", "photon_events", "pta_batch"]


@pytest.mark.slow
@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    env = dict(os.environ)
    # strip the accelerator vars: examples pin CPU themselves, but a
    # wedged tunnel must not be able to hang the subprocess either
    for k in list(env):
        if k.startswith("PALLAS_AXON"):
            env.pop(k)
    env.pop("PINT_TPU_EXAMPLES_ACCEL", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.strip(), "example produced no output"
