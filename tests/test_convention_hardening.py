"""Convention-hardening tests (SURVEY A.4/A.5/A.8 warn-items): checks
that would fail under a sign/convention error shared by the simulator
and the fitter — the failure mode the self-generated golden fixtures
cannot catch. Each expected value here is rebuilt in the test from the
published equations and independent inputs, not by calling the
implementation under test.

- Solar Shapiro: conjunction spike sign/location/amplitude vs the
  closed form -2 T_sun ln(r - r.n) (Backer & Hellings convention).
- Dispersion: the delay must use the Doppler-shifted BARYCENTRIC
  frequency nu_topo (1 - v.n/c); a flipped sign doubles the annual
  modulation and fails.
- DDK: the Kopeikin K95/K96 delta-i/delta-omega must enter the orbit
  with the published signs; checked against finite-difference partials
  of the plain DD delay times test-side-evaluated K95/K96 expressions.
"""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

C_M_S = 299792458.0
T_SUN = 4.925490947e-6
DMCONST_S = 1.0 / 2.41e-4  # s MHz^2 / (pc cm^-3), reference convention
SECS_PER_YEAR = 365.25 * 86400.0
MAS_TO_RAD = np.pi / 180 / 3600 / 1000
PC_LS = 3.0856775814913673e16 / C_M_S


def _mk(par, mjds, freqs=1400.0, seed=0):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        toas = make_fake_toas_fromMJDs(
            np.asarray(mjds, float), model, error_us=1.0,
            freq_mhz=freqs, add_noise=False,
            rng=np.random.default_rng(seed))
    return model, toas


BASE = """PSR TEST
RAJ 00:00:00.0
DECJ 00:00:00.0
F0 100.0
F1 0.0
PEPOCH 55000
POSEPOCH 55000
UNITS TDB
PLANET_SHAPIRO 0
"""


class TestSolarShapiroConjunction:
    def test_spike_at_conjunction_positive_and_closed_form(self):
        """Pulsar on the ecliptic at RA=0: solar conjunction in late
        March must produce a POSITIVE delay spike (extra light-travel
        time), peaked on the minimum sun-pulsar-angle day, matching
        -2 T ln(r - r.n) evaluated from the geometry inputs."""
        mjds = np.arange(55000.0, 55365.0)
        m_with, toas = _mk(BASE, mjds)
        m_wo, _ = _mk(BASE.replace("PSR TEST", "PSR TEST2"), mjds)
        m_wo.remove_component("SolarSystemShapiro")
        d = np.asarray(m_with.delay(toas)) - np.asarray(m_wo.delay(toas))
        batch = m_with.get_cache(toas)["batch"]
        sun = np.asarray(batch.obs_sun_pos)          # obs->sun, lt-s
        n = np.array([1.0, 0.0, 0.0])                # RA=0,DEC=0
        r = np.linalg.norm(sun, axis=-1)
        rcos = sun @ n
        ang = np.arccos(rcos / r)
        i_conj = int(np.argmin(ang))
        # conjunction in the window (pulsar at vernal equinox point:
        # sun passes it around MJD 55278, late March 2010)
        assert 250 < i_conj < 300
        # the spike is the global max and positive vs the annual median
        assert int(np.argmax(d)) == i_conj
        spike = d[i_conj] - np.median(d)
        assert spike > 0
        # closed form from the same geometry inputs
        expect = -2 * T_SUN * np.log(r - rcos)
        np.testing.assert_allclose(d - d.mean(), expect - expect.mean(),
                                   atol=2e-9)
        # magnitude sanity: at a ~deg-scale minimum angle, 10-300 us
        assert 1e-6 < spike < 1e-3

    def test_unit_geometry_sign(self):
        """Pure function check on synthetic geometry: behind-the-sun
        ray delayed MORE than the anti-solar direction."""
        from pint_tpu.models.solar_system_shapiro import shapiro_delay

        obs_sun = np.array([[499.0, 0.0, 0.0]])
        towards = np.asarray(shapiro_delay(
            obs_sun, np.array([[0.9999, 0.0141, 0.0]]), T_SUN))
        away = np.asarray(shapiro_delay(
            obs_sun, np.array([[-1.0, 0.0, 0.0]]), T_SUN))
        assert towards[0] > away[0] + 1e-5  # tens of us difference


class TestDispersionBarycentricFrequency:
    def test_doppler_sign_and_magnitude(self):
        """Dispersion delay = K DM / nu_bary^2 with nu_bary =
        nu_topo (1 - v.n/c). The annual Doppler modulation is ~1e-4
        relative; using +v.n (or the topocentric nu) fails at 2x
        (or 1x) that scale."""
        par = BASE + "DM 30.0\n"
        mjds = np.arange(55000.0, 55365.0, 2.0)
        m_dm, toas = _mk(par, mjds)
        m_0, _ = _mk(BASE.replace("PSR TEST", "PSR T3") + "DM 0.0\n",
                     mjds)
        disp = np.asarray(m_dm.delay(toas)) - np.asarray(m_0.delay(toas))
        batch = m_dm.get_cache(toas)["batch"]
        vdotn = np.asarray(batch.ssb_obs_vel) @ np.array([1.0, 0, 0.0])
        nu_b = 1400.0 * (1.0 - vdotn)
        expect = DMCONST_S * 30.0 / nu_b ** 2
        np.testing.assert_allclose(disp, expect, rtol=1e-12)
        # the flipped convention is clearly excluded
        wrong = DMCONST_S * 30.0 / (1400.0 * (1.0 + vdotn)) ** 2
        assert np.max(np.abs(disp - wrong)) > 50 * np.max(
            np.abs(disp - expect) + 1e-15)
        # and the modulation is real (annual, ~2e-4 peak-to-peak rel.)
        assert np.ptp(disp) / np.mean(disp) > 1e-4


DDK_PAR = """PSR TESTK
RAJ 06:00:00.0
DECJ 20:00:00.0
PMRA {pmra}
PMDEC {pmdec}
PX {px}
F0 100.0
PEPOCH 55000
POSEPOCH 55000
UNITS TDB
PLANET_SHAPIRO 0
BINARY {binary}
PB 40.0
A1 20.0
T0 55000.0
ECC 0.1
OM 30.0
M2 0.3
{incl}
"""


class TestDDKKopeikin:
    KIN, KOM = 60.0, 40.0

    def _delays(self, binary, px=5.0, pmra=0.0, pmdec=0.0, k96=True,
                dx=0.0, dom=0.0):
        incl = (f"KIN {self.KIN}\nKOM {self.KOM}\nK96 {int(k96)}"
                if binary == "DDK" else
                f"SINI {np.sin(np.radians(self.KIN)):.12f}")
        par = DDK_PAR.format(binary=binary, px=px, pmra=pmra,
                             pmdec=pmdec, incl=incl)
        mjds = np.arange(55000.0, 55365.0, 3.0)
        model, toas = _mk(par, mjds, seed=7)
        if dx or dom:
            model.A1.value += dx
            model.OM.value += np.degrees(dom)
            model.invalidate_cache(params_only=True)
        return model, toas, np.asarray(model.delay(toas))

    def test_k95_k96_signs_vs_published_expressions(self):
        """delta(DDK - DD) must equal dD/dx * dx_K + dD/dom * dom_K
        with dx_K, dom_K evaluated from the published K95+K96
        expressions REBUILT HERE (sky basis, signs and all) — a sign
        flip anywhere in the Kopeikin wiring breaks the match."""
        px, pmra, pmdec = 5.0, 30.0, -20.0
        kin = np.radians(self.KIN)
        kom = np.radians(self.KOM)
        m_ddk, toas, d_ddk = self._delays("DDK", px, pmra, pmdec)
        _, _, d_dd = self._delays("DD", px, pmra, pmdec)
        delta = d_ddk - d_dd

        # finite-difference partials of the DD delay
        hx, hom = 1e-4, 1e-6
        _, _, d_dx = self._delays("DD", px, pmra, pmdec, dx=hx)
        _, _, d_dom = self._delays("DD", px, pmra, pmdec, dom=hom)
        dD_dx = (d_dx - d_dd) / hx
        dD_dom = (d_dom - d_dd) / hom

        # published K95/K96, built from scratch
        batch = m_ddk.get_cache(toas)["batch"]
        a0 = np.radians(90.0)    # RAJ 06:00
        d0 = np.radians(20.0)
        I0 = np.array([-np.sin(a0), np.cos(a0), 0.0])
        J0 = np.array([-np.sin(d0) * np.cos(a0),
                       -np.sin(d0) * np.sin(a0), np.cos(d0)])
        rvec = np.asarray(batch.ssb_obs_pos)
        d_ls = PC_LS * 1e3 / px
        dI, dJ = rvec @ I0, rvec @ J0
        di = (dI * np.sin(kom) - dJ * np.cos(kom)) / d_ls
        dom_k = -(dI * np.cos(kom) + dJ * np.sin(kom)) / (
            d_ls * np.sin(kin))
        tdb = np.asarray(batch.tdb_day) + np.asarray(batch.tdb_frac.hi)
        dt = (tdb - 55000.0) * 86400.0
        mu_a = pmra * MAS_TO_RAD / SECS_PER_YEAR
        mu_d = pmdec * MAS_TO_RAD / SECS_PER_YEAR
        di = di + (-mu_a * np.sin(kom) + mu_d * np.cos(kom)) * dt
        dom_k = dom_k + (mu_a * np.cos(kom) + mu_d * np.sin(kom)) \
            / np.sin(kin) * dt
        x0 = 20.0
        dx_k = x0 * (np.sin(kin + di) / np.sin(kin) - 1.0)

        pred = dD_dx * dx_k + dD_dom * dom_k
        # also the Shapiro s = sin(kin+di) shift — tiny at these
        # magnitudes, absorbed by the tolerance
        scale = np.max(np.abs(delta))
        assert scale > 1e-9  # the effect is actually present
        np.testing.assert_allclose(delta, pred, atol=0.02 * scale)

    def test_k95_scales_linearly_with_px(self):
        # per-PX DD baselines: PX also drives the astrometric
        # parallax delay, which must cancel out of each difference
        _, _, dd2 = self._delays("DD", px=2.0)
        _, _, dd4 = self._delays("DD", px=4.0)
        _, _, d1 = self._delays("DDK", px=2.0, k96=False)
        _, _, d2 = self._delays("DDK", px=4.0, k96=False)
        e1 = d1 - dd2
        e2 = d2 - dd4
        # K95 ~ PX (d = 1/PX): doubling PX doubles the correction
        np.testing.assert_allclose(e2, 2.0 * e1,
                                   atol=0.01 * np.max(np.abs(e1)))

    def test_k96_off_removes_secular_drift(self):
        px, pmra, pmdec = 3.0, 40.0, 25.0
        _, _, d_dd = self._delays("DD", px, pmra, pmdec)
        _, _, d_on = self._delays("DDK", px, pmra, pmdec, k96=True)
        _, _, d_off = self._delays("DDK", px, pmra, pmdec, k96=False)
        drift_on = (d_on - d_dd)
        drift_off = (d_off - d_dd)
        # with K96 the PM term grows over the year; without it the
        # correction is purely annual-periodic (no secular envelope)
        assert np.max(np.abs(drift_on)) > 3 * np.max(np.abs(drift_off))
