"""Native C++ MJD parser: bit-identical to the Python dd parser and
substantially faster (pint_tpu/native/; host-runtime acceleration in
the role astropy's C time parser plays for the reference)."""

import time

import numpy as np
import pytest

from pint_tpu.native import mjdparse_native, native_available
from pint_tpu.time.mjd import parse_mjd_string, parse_mjd_strings

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no g++ toolchain")


def _random_mjd_strings(n, rng):
    days = rng.integers(40000, 60000, n)
    out = []
    for d in days:
        nd = int(rng.integers(0, 25))
        frac = "".join(rng.choice(list("0123456789"), nd)) if nd else ""
        out.append(f"{d}.{frac}" if frac else str(d))
    return out


def test_native_bit_identical():
    rng = np.random.default_rng(0)
    strs = _random_mjd_strings(3000, rng)
    strs += ["-1234.5", "58000.000000000000000001", "0.5", "58000"]
    d_n, (h_n, l_n) = mjdparse_native(strs)
    d_p = np.empty(len(strs))
    h_p = np.empty(len(strs))
    l_p = np.empty(len(strs))
    for i, s in enumerate(strs):
        d_p[i], (h_p[i], l_p[i]) = parse_mjd_string(s)
    assert np.array_equal(d_n, d_p)
    assert np.array_equal(h_n, h_p)  # exact — same dd operations
    assert np.array_equal(l_n, l_p)


def test_native_rejects_bad_strings():
    with pytest.raises(ValueError):
        mjdparse_native(["58000.5", "not_a_number"])
    with pytest.raises(ValueError):
        mjdparse_native(["58000.5e3"])


def test_parse_mjd_strings_uses_native_and_is_faster():
    rng = np.random.default_rng(1)
    strs = [f"{d}.{f:016d}" for d, f in zip(
        rng.integers(50000, 60000, 20000),
        rng.integers(0, 10 ** 16, 20000))]
    t_native = min(
        _timed(lambda: parse_mjd_strings(strs)) for _ in range(3))
    t_python = min(
        _timed(lambda: parse_mjd_strings(strs, use_native=False))
        for _ in range(2))
    d1, (h1, l1) = parse_mjd_strings(strs)
    d2, (h2, l2) = parse_mjd_strings(strs, use_native=False)
    assert np.array_equal(d1, d2)
    assert np.array_equal(h1, h2)
    assert np.array_equal(l1, l2)
    # min-of-N and a loose factor: correctness is the hard assert
    assert t_native < t_python / 2, \
        f"native {t_native:.3f}s vs python {t_python:.3f}s"


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
