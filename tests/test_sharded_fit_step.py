"""The flagship TOA-axis sharded fit step (build_sharded_fit_step) must
agree with the unsharded step on the conftest 8-device virtual CPU mesh
— the multi-chip sequence-parallel path the driver dry-runs
(reference algorithm: src/pint/fitter.py GLSFitter.fit_toas; sharding
design: SURVEY.md §2c TP/SP row).
"""

import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step, build_sharded_fit_step
from pint_tpu.simulation import (
    make_fake_toas_fromMJDs,
    make_fake_toas_uniform,
)
from pint_tpu.toa import merge_TOAs


@pytest.fixture(scope="module")
def problem():
    par = [
        "PSR J0001+0001",
        "RAJ 11:00:00.0 1",
        "DECJ 20:00:00.0 1",
        "F0 250.0 1",
        "F1 -2e-15 1",
        "PEPOCH 55000",
        "POSEPOCH 55000",
        "DM 15.0 1",
        "DMEPOCH 55000",
        "TZRMJD 55000.1",
        "TZRSITE @",
        "TZRFRQ 1400",
        "UNITS TDB",
        "EFAC -be X 1.1",
        "ECORR -be X 0.7",
        "TNREDAMP -13.5",
        "TNREDGAM 3.0",
        "TNREDC 5",
    ]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par) + "\n"))
        rng = np.random.default_rng(7)
        tA = make_fake_toas_uniform(54001, 55901, 40, model, error_us=1.0,
                                    freq_mhz=1400.0, add_noise=True, rng=rng)
        tB = make_fake_toas_uniform(54002, 55902, 37, model, error_us=1.5,
                                    freq_mhz=820.0, add_noise=True, rng=rng)
        toas = merge_TOAs([tA, tB])  # 77 TOAs: forces padding to 80
        for f in toas.flags:
            f["be"] = "X"
    return model, toas


def test_sharded_matches_unsharded(problem):
    model, toas = problem
    ndev = len(jax.devices())
    assert ndev == 8, "conftest must provide 8 virtual devices"

    step_fn, args, names = build_fit_step(model, toas)
    dp0, cov0, chi20, r0 = jax.jit(step_fn)(*args)

    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("toa",))
    jitted, dev_args, names_s = build_sharded_fit_step(model, toas, mesh)
    dp1, cov1, chi21, r1 = jitted(*dev_args)

    assert names == names_s
    np.testing.assert_allclose(np.asarray(dp1), np.asarray(dp0),
                               rtol=1e-7, atol=1e-14)
    np.testing.assert_allclose(np.asarray(cov1), np.asarray(cov0),
                               rtol=1e-7)
    assert float(chi21) == pytest.approx(float(chi20), rel=1e-8)
    # padded residual rows are exactly zero (valid mask)
    r1 = np.asarray(r1)
    np.testing.assert_allclose(r1[: toas.ntoas], np.asarray(r0),
                               rtol=1e-7, atol=1e-12)
    np.testing.assert_allclose(r1[toas.ntoas:], 0.0, atol=0.0)


@pytest.mark.slow
@pytest.mark.parametrize("n", [32768, 131072])
def test_long_context_sharded_step(n):
    """SURVEY §5 long-context: the TOA axis is the sequence axis and
    the sharded Woodbury must scale to N far beyond a single shard's
    comfort — 32k and 131k TOAs block-sharded over the 8-device mesh,
    with the normal-equation reduction riding psum (the ring-reduce
    over ICI on real hardware). Oracle: same chi2 and parameter step
    as the unsharded build."""
    par = [
        "PSR J0002+0002", "RAJ 09:00:00.0 1", "DECJ 10:00:00.0 1",
        "F0 311.0 1", "F1 -3e-15 1", "PEPOCH 55000",
        "POSEPOCH 55000", "DM 21.0 1", "DMEPOCH 55000",
        "TZRMJD 55000.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "EFAC -be X 1.05", "TNREDAMP -13.6", "TNREDGAM 3.2",
        "TNREDC 15",
    ]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par) + "\n"))
        rng = np.random.default_rng(13)
        mjds = np.sort(rng.uniform(53000, 57000, n))
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n // 2),
            add_noise=True, rng=rng, flags={"be": "X"})
    model.F0.value += 1e-10

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("toa",))
    jit_sh, args_sh, names_sh = build_sharded_fit_step(model, toas,
                                                       mesh)
    out_sh = jit_sh(*args_sh)
    fn, args, names = build_fit_step(model, toas)
    out = jax.jit(fn)(*args)

    assert names_sh == names
    assert float(out_sh[2]) == pytest.approx(float(out[2]), rel=1e-9)
    # per-parameter sigma scaling: one global atol would be vacuous
    # for small-scale columns (F1 sigma ~1e-18 vs DM sigma ~1e-4)
    sig = np.sqrt(np.abs(np.diag(np.asarray(out[1]))))
    sig = np.where(sig > 0, sig, 1.0)
    np.testing.assert_allclose(
        (np.asarray(out_sh[0]) - np.asarray(out[0])) / sig, 0.0,
        atol=1e-6)


def test_sharded_step_improves_chi2(problem):
    """One accepted sharded GLS step from a perturbed point lowers the
    basis-marginalized chi2 (end-to-end sanity of the sharded path)."""
    import copy

    from pint_tpu.residuals import Residuals

    model, toas = problem
    m = copy.deepcopy(model)
    m.get_param("F0").add_delta(3e-10)
    m.invalidate_cache(params_only=True)
    chi2_before = Residuals(toas, m).chi2

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("toa",))
    jitted, dev_args, names = build_sharded_fit_step(m, toas, mesh)
    dp, cov, chi2, r = jitted(*dev_args)
    dp = np.asarray(dp)
    for name, dx in zip(names, dp):
        if name == "Offset":
            continue
        m.get_param(name).add_delta(float(dx))
    m.invalidate_cache(params_only=True)
    chi2_after = Residuals(toas, m).chi2
    assert chi2_after < chi2_before
    assert abs(m.F0.value - model.F0.value) < 1e-11
