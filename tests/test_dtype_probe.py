"""Differential validation of graftflow's static dtype predictions
(ISSUE 6 tentpole): the analyzer predicts, per production
build_fit_step configuration, which precision boundaries fire and
with which dtypes; the Sanitizer dtype probe records what the trace
ACTUALLY does; this test asserts they agree. The analyzer tests the
code, the runtime tests the analyzer — if either the registry's flag
expressions or the step's demotion plumbing drifts, the two sides
disagree and this fails in the fast lane.

Trace-only (jax.eval_shape): no compile, no dispatch, so the probe is
cheap enough to sweep every flag combination."""

import io
import warnings

import jax
import numpy as np
import pytest

from pint_tpu.analysis import Sanitizer, graftflow
from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 300.123456789 1
F1 -1.0e-15 1
DM 20.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


@pytest.fixture(scope="module")
def problem():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BASE))
        rng = np.random.default_rng(3)
        mjds = np.sort(rng.uniform(54001, 55999, 60))
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], 30),
            add_noise=True, rng=rng)
    return model, toas


# every production-relevant corner: the all-f64 oracle shape, the
# full TPU production stack, and the two mixed configs that pin the
# flag coupling (jac32 without f32mm; f32mm without jac32)
CONFIGS = [
    dict(anchored=False, jac_f32=False, matmul_f32=False,
         hybrid_jac=False),
    dict(anchored=True, jac_f32=True, matmul_f32=True,
         hybrid_jac=True),
    dict(anchored=False, jac_f32=True, matmul_f32=False,
         hybrid_jac=True),
    dict(anchored=True, jac_f32=False, matmul_f32=True,
         hybrid_jac=False),
    dict(anchored=False, jac_f32=False, matmul_f32=True,
         hybrid_jac=True),
]


@pytest.mark.parametrize("flags", CONFIGS,
                         ids=lambda f: "-".join(
                             k for k, v in f.items() if v) or "f64")
def test_static_predictions_match_traced_dtypes(problem, flags):
    model, toas = problem
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step_fn, args, _ = build_fit_step(model, toas, **flags)
    san = Sanitizer()
    with san.dtype_probe():
        jax.eval_shape(step_fn, *args)
    observed = san.observed_profile()
    # graftflow's `hybrid` flag means "enabled AND the model claims
    # columns" — the conjunction the caller owns (predict_profile doc)
    hybrid_active = bool(flags["hybrid_jac"]) and \
        bool(model.linear_design_names())
    predicted = graftflow.predict_profile(
        jac32=flags["jac_f32"], f32mm=flags["matmul_f32"],
        anchored=flags["anchored"], hybrid=hybrid_active)
    assert predicted, "registry PROBES table is empty"
    for label, pred in predicted.items():
        obs = observed.get(label)
        assert (obs is not None) == pred["active"], (
            f"{label}: graftflow predicts "
            f"active={pred['active']} under {flags}, trace says "
            f"{'fired' if obs else 'silent'}")
        if pred["active"]:
            assert pred["dtype"] in obs["dtypes"], (
                f"{label}: predicted dtype {pred['dtype']}, traced "
                f"{sorted(obs['dtypes'])} under {flags}")
    # no boundary fired that the registry does not know about
    assert set(observed) <= set(predicted)


def test_probe_records_only_tracers(problem):
    """Host-side build work (the anchored reference's numpy dd32
    splits) must not pollute the profile: with no trace inside the
    context, nothing is recorded even though build_fit_step itself
    calls dd_to_dd32 on host values."""
    model, toas = problem
    san = Sanitizer()
    with san.dtype_probe():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            build_fit_step(model, toas, anchored=True, jac_f32=True)
    assert san.dtype_records == []
