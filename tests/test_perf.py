"""Performance-attribution plane acceptance suite (ISSUE 15).

The contracts CLAUDE.md promises for the perf plane:

- compile ledger: every supervised first_call lands an entry; the
  registry counter and the snapshot are the SAME number (derived
  view, the ISSUE-11 parity discipline); JSONL persistence reads
  back as ``prior`` after a restart; AOT-restored serve classes are
  ledgered with ``aot_restored=True``;
- dispatch-wall decomposition: armed, the four phases telescope to
  (at most) the dispatch wall; disarmed, ZERO rows are recorded and
  the snapshot carries no ``perf`` block;
- roofline blocks derive from ledger cost ÷ measured walls against
  the per-backend peak table (bench's constants must match it);
- profiler windows: bounded (clamped to $PINT_TPU_PROFILE_MAX_S),
  rate-limited per reason, zero records when disarmed; an slo_burn
  episode auto-opens EXACTLY one window cross-linked to the
  episode's flight dump; a window open across an injected backend
  death never wedges the dispatch path and still ends in a labeled
  status with parseable metadata;
- the profiling scoreboard's phase rows are registry-shared and
  cleared by ``obs.reset()``.
"""

import json
import os
import time

import numpy as np
import pytest

from pint_tpu import obs
from pint_tpu.obs import metrics as om
from pint_tpu.obs import perf
from pint_tpu.runtime import (
    DispatchSupervisor,
    Fault,
    FaultPlan,
    reset_runtime,
)


@pytest.fixture(autouse=True)
def clean_obs():
    """A configured plane (or tripped breaker) must never leak
    across tests — the obs.reset() isolation contract."""
    obs.reset()
    reset_runtime()
    yield
    obs.reset()
    reset_runtime()


# ------------------------------------------------------------- ledger


def test_ledger_registry_vs_snapshot_parity():
    led = perf.get_ledger()
    led.record("k1", backend="cpu", compile_wall_s=0.5, flops=1e9,
               bytes_accessed=2e8)
    led.record("k1", compile_wall_s=0.6)   # merge, not a new compile
    led.record("k2", backend="cpu", aot_restored=True)
    snap = led.snapshot()
    assert snap["compiles"] == 2
    assert int(om.get_registry().total(
        "pint_tpu_perf_compiles_total")) == snap["compiles"]
    assert int(om.get_registry().total(
        "pint_tpu_perf_aot_restored_total")) == snap["aot_restored"] \
        == 1
    assert snap["entries"]["k2"]["aot_restored"] is True
    # the merge updated the wall in place — entry and gauge agree
    assert snap["entries"]["k1"]["compile_wall_s"] == 0.6
    assert om.get_registry().value(
        "pint_tpu_perf_compile_wall_seconds", key="k1") == 0.6
    assert om.get_registry().value(
        "pint_tpu_perf_cost_flops", key="k1") == 1e9


def test_ledger_jsonl_persists_and_restores_as_prior(tmp_path):
    p = str(tmp_path / "ledger.jsonl")
    perf.configure(ledger_path=p)
    perf.get_ledger().record("a", backend="cpu", compile_wall_s=0.1,
                             flops=5.0)
    perf.get_ledger().record("b", backend="cpu", compile_wall_s=0.2)
    lines = [json.loads(x) for x in
             open(p, encoding="utf-8").read().splitlines()]
    assert {r["key"] for r in lines} == {"a", "b"}
    # a restarted worker reads the file back as prior entries —
    # visible by key, NOT counted against this process's registry
    obs.reset()
    perf.configure(ledger_path=p)
    led = perf.get_ledger()
    snap = led.snapshot()
    assert snap["compiles"] == 0 and snap["prior"] == 2
    assert led.get("a")["flops"] == 5.0


def test_supervisor_first_call_feeds_the_ledger():
    sup = DispatchSupervisor()
    sup.dispatch(lambda: 1.0, key="unit.first")
    sup.dispatch(lambda: 2.0, key="unit.first")  # no second entry
    entry = perf.get_ledger().get("unit.first")
    assert entry is not None
    assert entry["compile_wall_s"] >= 0.0
    assert perf.get_ledger().snapshot()["compiles"] == 1


def test_cost_probe_on_a_real_jit_and_roofline_block():
    import jax

    f = jax.jit(lambda x: x @ x)
    x = np.zeros((64, 64))
    jax.block_until_ready(f(x))
    perf.note_compile("unit.mm", backend="cpu", kind="test",
                      jitted=f, args=(x,))
    entry = perf.get_ledger().get("unit.mm")
    assert entry and entry.get("flops", 0) > 0
    blk = perf.roofline_block("unit.mm", 1e-3, "cpu")
    assert blk["source"] == "compile_ledger"
    assert blk["gflops_achieved"] == pytest.approx(
        entry["flops"] / 1e-3 / 1e9, rel=0.01)
    # achieved fraction only where a peak is declared (no fabricated
    # host "peak"); bench's historical constants must match the table
    assert "achieved_frac_flops" not in blk
    import bench

    assert bench.V5E_PEAK_FLOPS == perf.PEAKS["tpu"]["flops"]
    assert bench.V5E_PEAK_HBM_BPS == perf.PEAKS["tpu"]["bytes_per_s"]
    # the gauges landed
    assert om.get_registry().value(
        "pint_tpu_perf_achieved_gflops", key="unit.mm") == \
        blk["gflops_achieved"]


# ------------------------------------------------- wall decomposition


def test_decomposition_phases_sum_to_at_most_the_wall():
    perf.configure(enabled=True)
    sup = DispatchSupervisor()

    def payload():
        time.sleep(0.01)
        return np.zeros(8)

    t0 = time.perf_counter()
    sup.dispatch(payload, key="unit.decomp", guard=True)
    wall = time.perf_counter() - t0
    snap = sup.metrics.perf.snapshot()
    import jax

    row = snap[f"{jax.default_backend()}/unit.decomp"]
    phases = ("queue_wait", "host_assembly", "device_wall",
              "collect")
    assert all(row[p]["count"] == 1 for p in phases)
    total_s = sum(row[p]["mean_ms"] for p in phases) / 1e3
    assert total_s <= wall + 1e-3
    # the payload sleep must land INSIDE the host_assembly phase
    # (the worker's fn wall), not be lost to the residual phases
    assert row["host_assembly"]["mean_ms"] >= 9.0
    # the supervisor snapshot carries the block
    assert "perf" in sup.metrics.snapshot()


def test_decomposition_disarmed_records_nothing():
    sup = DispatchSupervisor()
    sup.dispatch(lambda: np.zeros(4), key="unit.off", guard=True)
    assert len(sup.metrics.perf) == 0
    assert "perf" not in sup.metrics.snapshot()


# --------------------------------------------------- profiler windows


def test_window_disarmed_is_a_labeled_refusal_with_zero_records(
        tmp_path, monkeypatch):
    monkeypatch.delenv("PINT_TPU_PROFILE_DIR", raising=False)
    res = perf.request_window(1, reason="t")
    assert res["ok"] is False and "armed" in res["error"]
    # nothing recorded anywhere: no counters, no files
    assert om.get_registry().total(
        "pint_tpu_perf_profile_windows_total") == 0
    assert om.get_registry().total(
        "pint_tpu_perf_profile_suppressed_total") == 0
    assert perf.auto_window("breaker_open") is None


def test_window_bounded_and_rate_limited(tmp_path):
    d = str(tmp_path / "prof")
    perf.configure(profile_dir=d, max_s=0.2)
    res = perf.request_window(99, reason="t")   # clamped to max_s
    assert res["ok"] and res["seconds"] <= 0.2
    # a second request while open (or inside the per-reason rate
    # limit) is refused and counted
    res2 = perf.request_window(1, reason="t")
    assert res2["ok"] is False
    assert om.get_registry().total(
        "pint_tpu_perf_profile_suppressed_total") == 1
    t0 = time.time()
    while perf.get_profiler().status()["open"] is not None and \
            time.time() - t0 < 10:
        time.sleep(0.05)
    meta = json.load(open(os.path.join(res["dir"], "window.json"),
                          encoding="utf-8"))
    assert meta["status"] in ("closed", "aborted", "abandoned")
    assert meta["reason"] == "t"
    # even after the close, the same reason stays rate-limited
    res3 = perf.request_window(0.05, reason="t")
    assert res3["ok"] is False and "rate-limited" in res3["error"]


def test_slo_burn_opens_exactly_one_crosslinked_window(tmp_path):
    """The chaos-oracle acceptance: one slo_burn episode -> exactly
    one auto profiler window, cross-linked to the episode's flight
    dump, with Perfetto-parseable span export."""
    from pint_tpu.obs.slo import SLOSpec, SLOWatchdog

    fdir = str(tmp_path / "flight")
    pdir = str(tmp_path / "prof")
    obs.configure(enabled=True, flight_dir=fdir)
    perf.configure(profile_dir=pdir, max_s=0.2)
    spec = SLOSpec(name="unit_ratio", type="ratio",
                   bad=["unit_bad_total"], total=["unit_all_total"],
                   budget=0.01, fast_s=10.0, slow_s=30.0,
                   min_events=1, min_samples=1)
    bad = om.counter("unit_bad_total")
    allc = om.counter("unit_all_total")
    wd = SLOWatchdog(specs=[spec], interval_s=1.0)
    allc.inc(10)
    wd.tick(now=0.0)
    bad.inc(10)
    allc.inc(10)
    fired = wd.tick(now=40.0)
    assert fired == ["unit_ratio"]
    windows = [x for x in os.listdir(pdir)
               if x.startswith("window-")]
    assert len(windows) == 1
    # burning on: the episode is latched — no second fire, and the
    # window count stays one
    bad.inc(10)
    allc.inc(10)
    assert wd.tick(now=80.0) == []
    assert len([x for x in os.listdir(pdir)
                if x.startswith("window-")]) == 1
    # wait out the window close, then check the cross-links
    t0 = time.time()
    while perf.get_profiler().status()["open"] is not None and \
            time.time() - t0 < 10:
        time.sleep(0.05)
    wdir = os.path.join(pdir, windows[0])
    meta = json.load(open(os.path.join(wdir, "window.json"),
                          encoding="utf-8"))
    assert meta["reason"] == "slo_burn:unit_ratio"
    assert meta["status"] in ("closed", "aborted", "abandoned")
    extra = meta.get("extra") or {}
    flight = extra.get("flight")
    assert flight and os.path.exists(flight), meta
    fdoc = json.load(open(flight, encoding="utf-8"))
    assert fdoc["reason"].startswith("slo_burn:")
    # Perfetto-parseable span export rides the window dir (tracing
    # was armed): the Chrome trace-event wrapper with causal ids
    spath = os.path.join(wdir, "spans.json")
    assert os.path.exists(spath)
    sdoc = json.load(open(spath, encoding="utf-8"))
    assert isinstance(sdoc["traceEvents"], list)
    for e in sdoc["traceEvents"]:
        assert e["ph"] in ("X", "i") and "ts" in e


def test_window_survives_injected_backend_death(tmp_path):
    """Chaos: a profile window open across an injected backend death
    must never wedge the drain — the dispatch fails over on its own
    deadline, and the window still ends in a labeled status with
    parseable metadata."""
    d = str(tmp_path / "prof")
    perf.configure(profile_dir=d, max_s=0.3)
    res = perf.request_window(0.3, reason="chaos")
    assert res["ok"]
    plan = FaultPlan([Fault(match="unit.dead", kind="hang",
                            seconds=2.0)])
    sup = DispatchSupervisor()
    with plan.active():
        os.environ["PINT_TPU_DISPATCH_DEADLINE_MS"] = "200"
        try:
            out = sup.dispatch(lambda: np.ones(3), key="unit.dead",
                               fallback=lambda: np.zeros(3))
        finally:
            os.environ.pop("PINT_TPU_DISPATCH_DEADLINE_MS", None)
    np.testing.assert_array_equal(out, np.zeros(3))
    assert sup.metrics.failovers == 1
    t0 = time.time()
    while perf.get_profiler().status()["open"] is not None and \
            time.time() - t0 < 10:
        time.sleep(0.05)
    meta = json.load(open(os.path.join(res["dir"], "window.json"),
                          encoding="utf-8"))
    assert meta["status"] in ("closed", "aborted", "abandoned")


def test_breaker_open_fires_an_auto_window(tmp_path, monkeypatch):
    """The breaker-open incident trigger: tripping the breaker opens
    one auto window (flight-recorder pattern) and never raises into
    the dispatch path."""
    pdir = str(tmp_path / "prof")
    perf.configure(profile_dir=pdir, max_s=0.2)
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "1")
    plan = FaultPlan([Fault(match="unit.trip", kind="error",
                            count=8)])
    sup = DispatchSupervisor()
    with plan.active():
        out = sup.dispatch(lambda: 1.0, key="unit.trip",
                           fallback=lambda: -1.0)
    assert out == -1.0
    windows = [x for x in os.listdir(pdir)
               if x.startswith("window-")]
    assert len(windows) == 1
    assert "breaker_open" in windows[0]


# ------------------------------------------------- AOT restore ledger


def test_aot_restored_classes_are_ledgered(tmp_path):
    """A warm restart's restored executables land in the ledger with
    aot_restored=True, keyed as the scheduler's dispatch-key
    spelling (``serve.<kind>/<class>``) so a later first_call merges
    into the same entry. Exercised directly against AotStore (the
    full engine round-trip is test_serve_restart's oracle) with a
    tiny exported kernel — the ledgering path is restore_all's."""
    import jax
    import jax.numpy as jnp

    from pint_tpu.serve.journal import AotStore

    d = str(tmp_path / "aot")
    store = AotStore(d)
    f = jax.jit(lambda a: a * 2.0 + 1.0)
    aval = jax.ShapeDtypeStruct((8,), jnp.float64)
    jax.block_until_ready(f(np.zeros(8)))
    store.save("gls", (64, 8, 0, 1), f, (aval,))
    assert store.exported == 1
    obs.reset()          # fresh plane: the restart's process state
    reset_runtime()
    store2 = AotStore(d)
    assert store2.restore_all() == 1
    snap = perf.get_ledger().snapshot()
    restored = {k: e for k, e in snap["entries"].items()
                if e.get("aot_restored")}
    assert list(restored) == ["serve.gls/64/8/0/1"]
    assert snap["aot_restored"] == 1
    # the spelling matches the scheduler's dispatch key, so the
    # supervisor's later first_call MERGES rather than minting a
    # second entry
    perf.note_compile("serve.gls/64/8/0/1", compile_wall_s=0.25)
    snap = perf.get_ledger().snapshot()
    assert snap["compiles"] == 1
    e = snap["entries"]["serve.gls/64/8/0/1"]
    assert e["aot_restored"] is True and \
        e["compile_wall_s"] == 0.25


# ------------------------------------------- scoreboard unification


def test_scoreboard_rows_are_registry_shared_and_reset_clears():
    from pint_tpu.profiling import scoreboard

    scoreboard.reset()
    with scoreboard.phase("unit-phase"):
        pass
    assert scoreboard.counts["unit-phase"] == 1
    hist = om.get_registry().get("pint_tpu_scoreboard_seconds")
    assert hist is not None
    rows = [h for key, h in hist.rows()
            if ("phase", "unit-phase") in key]
    assert len(rows) == 1
    # the SAME object: registry row and scoreboard row can never
    # disagree (parity by construction, the row_factory discipline)
    assert rows[0] is scoreboard._rows["unit-phase"]
    assert rows[0].count == 1
    obs.reset()
    assert scoreboard.totals == {}
    # fresh phases re-register against the fresh registry
    with scoreboard.phase("unit-phase"):
        pass
    assert scoreboard.counts["unit-phase"] == 1
    hist2 = om.get_registry().get("pint_tpu_scoreboard_seconds")
    assert hist2 is not None and hist2 is not hist


def test_serve_snapshot_carries_the_scoreboard_block():
    from pint_tpu.profiling import annotate
    from pint_tpu.serve.metrics import ServeMetrics

    with annotate("unit.region"):
        pass
    snap = ServeMetrics().snapshot()
    assert "unit.region" in snap.get("scoreboard", {})


# ------------------------------------------------------- obs surface


def test_obs_status_carries_the_perf_block():
    perf.get_ledger().record("k", backend="cpu", compile_wall_s=0.1)
    st = obs.status()
    assert st["perf"]["compiles"] == 1
    assert st["perf"]["decomposition_armed"] is False


def test_perf_enabled_env_parser(monkeypatch):
    from pint_tpu import config

    monkeypatch.setenv("PINT_TPU_PERF", "on")
    assert config.perf_enabled() is True
    monkeypatch.setenv("PINT_TPU_PERF", "definitely")
    assert config.perf_enabled() is False   # warn-and-ignore
    monkeypatch.setenv("PINT_TPU_PROFILE_MAX_S", "-3")
    assert config.profile_max_s() == 30.0   # warn-and-ignore
    monkeypatch.setenv("PINT_TPU_PROFILE_MAX_S", "7.5")
    assert config.profile_max_s() == 7.5
    monkeypatch.setenv("PINT_TPU_PROFILE_DIR", "")
    assert config.profile_dir() is None
    monkeypatch.setenv("PINT_TPU_COMPILE_LEDGER", "")
    assert config.compile_ledger_path() is None
