"""Bayesian interface + chi2 grids (reference: src/pint/bayesian.py,
src/pint/models/priors.py, src/pint/gridutils.py; oracle per SURVEY.md
§4: posterior curvature must match the least-squares covariance on
simulated data)."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.bayesian import BayesianTiming
from pint_tpu.gridutils import grid_chisq, grid_chisq_derived
from pint_tpu.models import get_model
from pint_tpu.models.priors import GaussianPrior, UniformPrior
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs


@pytest.fixture(scope="module")
def fitted():
    par = """
PSR J0005+0005
RAJ 08:00:00.0
DECJ 25:00:00.0
F0 180.0 1
F1 -2.5e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 12.0
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(21)
        tA = make_fake_toas_uniform(54000, 56000, 60, model,
                                    freq_mhz=1400.0, add_noise=True,
                                    rng=rng)
        tB = make_fake_toas_uniform(54005, 55995, 60, model,
                                    freq_mhz=820.0, add_noise=True,
                                    rng=rng)
        toas = merge_TOAs([tA, tB])
        from pint_tpu.fitter import WLSFitter

        m = copy.deepcopy(model)
        f = WLSFitter(toas, m)
        f.fit_toas(maxiter=2)
    return m, toas, f


# ------------------------------------------------------------- priors


def test_prior_logpdfs():
    u = UniformPrior(0.0, 2.0)
    assert float(u.logpdf(1.0)) == pytest.approx(-np.log(2.0))
    assert float(u.logpdf(3.0)) == -np.inf
    assert float(u.ppf(0.25)) == pytest.approx(0.5)
    g = GaussianPrior(1.0, 2.0)
    assert float(g.logpdf(1.0)) == pytest.approx(
        -np.log(2.0 * np.sqrt(2 * np.pi)))
    assert float(g.ppf(0.5)) == pytest.approx(1.0, abs=1e-12)


def test_parameter_prior_hook(fitted):
    m, _, _ = fitted
    p = m.get_param("F0")
    assert p.prior_logpdf() == 0.0  # improper flat default
    p.prior = GaussianPrior(p.value, 1e-9)
    assert p.prior_logpdf(p.value) > 0  # sharp prior has big density
    p.prior = None


# --------------------------------------------------- likelihood shape


def test_lnlikelihood_peaks_at_fit(fitted):
    m, toas, f = fitted
    bt = BayesianTiming(m, toas)
    th0 = bt.theta0.copy()
    ll0 = bt.lnlikelihood(th0)
    i = bt.param_labels.index("F0")
    sig = f.errors["F0"]
    for off in (-5 * sig, 5 * sig):
        th = th0.copy()
        th[i] += off
        assert bt.lnlikelihood(th) < ll0


def test_posterior_matches_wls_covariance(fitted):
    """The lnlike curvature along F0 equals the WLS information with
    the other timing params fixed and the phase offset profiled out
    (the likelihood subtracts the weighted mean, i.e. ML-fits the
    offset): curv = A_ii - A_i0^2 / A_00 with A = cov^-1 over
    [Offset, free...]."""
    m, toas, f = fitted
    bt = BayesianTiming(m, toas)
    th0 = bt.theta0.copy()
    i = bt.param_labels.index("F0")
    names = ["Offset"] + list(m.free_params)
    A = np.linalg.inv(f.parameter_covariance_matrix)
    ii, oo = names.index("F0"), names.index("Offset")
    info = A[ii, ii] - A[ii, oo] ** 2 / A[oo, oo]
    h = 1.0 / np.sqrt(info)
    # F0 perturbations quantize to ulp(F0) (~0.09 sigma); use the
    # ACTUAL applied offsets in a non-uniform 3-point stencil
    thm, thp = th0.copy(), th0.copy()
    thm[i] -= h
    thp[i] += h
    qm, qp = thm[i] - th0[i], thp[i] - th0[i]
    ll0 = bt.lnlikelihood(th0)
    llm = bt.lnlikelihood(thm)
    llp = bt.lnlikelihood(thp)
    # non-uniform 3-point second derivative
    curv = -2.0 * (qm * (llp - ll0) - qp * (llm - ll0)) \
        / (qp * qm * (qp - qm))
    assert curv == pytest.approx(info, rel=0.02)


def test_lnprior_and_posterior(fitted):
    m, toas, _ = fitted
    bt = BayesianTiming(m, toas)
    th0 = bt.theta0.copy()
    assert bt.lnprior(th0) == 0.0
    f0 = m.F0.value
    m.get_param("F0").prior = UniformPrior(f0 - 1e-6, f0 + 1e-6)
    bt2 = BayesianTiming(m, toas)
    assert bt2.lnprior(th0) == pytest.approx(-np.log(2e-6))
    th_bad = th0.copy()
    th_bad[bt2.param_labels.index("F0")] += 1.0
    assert bt2.lnposterior(th_bad) == -np.inf
    # prior_transform round-trips the cube
    m.get_param("F1").prior = UniformPrior(-3e-15, -2e-15)
    bt3 = BayesianTiming(m, toas)
    x = bt3.prior_transform(np.full(bt3.nparams, 0.5))
    assert x[bt3.param_labels.index("F0")] == pytest.approx(f0)
    m.get_param("F0").prior = None
    m.get_param("F1").prior = None


def test_batch_lnlikelihood_matches_scalar(fitted):
    m, toas, f = fitted
    bt = BayesianTiming(m, toas)
    rng = np.random.default_rng(5)
    sig = np.array([f.errors[p] for p in bt.param_labels])
    thetas = bt.theta0[None, :] + sig[None, :] * \
        rng.standard_normal((16, bt.nparams))
    batch = bt.lnlikelihood_batch(thetas)
    scalar = np.array([bt.lnlikelihood(t) for t in thetas])
    np.testing.assert_allclose(batch, scalar, rtol=1e-10)


def test_lnlikelihood_gls_consistent_with_chi2(fitted):
    """With correlated noise, lnlike differences equal -chi2/2
    differences of the marginalized GLS chi2."""
    m0, toas0, _ = fitted
    par = m0.as_parfile() + """
EFAC -be X 1.1
ECORR -be X 0.8
TNREDAMP -13.5
TNREDGAM 3.0
TNREDC 5
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
    for f in toas0.flags:
        f["be"] = "X"
    from pint_tpu.residuals import Residuals

    bt = BayesianTiming(m, toas0)
    th0 = bt.theta0.copy()
    i = bt.param_labels.index("F0")
    th1 = th0.copy()
    th1[i] += 2e-10
    dll = bt.lnlikelihood(th1) - bt.lnlikelihood(th0)
    chi0 = Residuals(toas0, m).chi2
    m2 = copy.deepcopy(m)
    # perturb by the ACTUAL f64-representable offset theta carries
    m2.get_param("F0").add_delta(float(th1[i] - th0[i]))
    m2.invalidate_cache(params_only=True)
    chi1 = Residuals(toas0, m2).chi2
    assert dll == pytest.approx(-0.5 * (chi1 - chi0), rel=1e-6)


# --------------------------------------------------------------- grids


def test_grid_chisq_minimum_at_fit(fitted):
    m, toas, f = fitted
    sig0, sig1 = f.errors["F0"], f.errors["F1"]
    f0, f1 = m.F0.value, m.F1.value
    g0 = f0 + np.linspace(-3, 3, 9) * sig0
    g1 = f1 + np.linspace(-3, 3, 9) * sig1
    chi2 = grid_chisq(m, toas, ("F0", "F1"), (g0, g1), maxiter=2)
    assert chi2.shape == (9, 9)
    kmin = np.unravel_index(np.argmin(chi2), chi2.shape)
    assert kmin == (4, 4)  # grid center = fitted values
    # chi2 rises by ~1 at the 1-sigma contour along each axis when the
    # other params are refit: use the MARGINAL uncertainty
    assert chi2[4, 4] < chi2[8, 4] and chi2[4, 4] < chi2[4, 8]
    # index 8 = +3 sigma -> profile dchi2 ~= 9 (up to the f64 grid
    # coordinates' ulp quantization of F0, ~0.07 sigma)
    dchi_3sig = chi2[8, 4] - chi2[4, 4]
    assert dchi_3sig == pytest.approx(9.0, rel=0.15)


def test_grid_chisq_64x64_one_call(fitted):
    m, toas, f = fitted
    sig0 = f.errors["F0"]
    g0 = m.F0.value + np.linspace(-2, 2, 64) * sig0
    g1 = m.F1.value + np.linspace(-2, 2, 64) * f.errors["F1"]
    chi2 = grid_chisq(m, toas, ("F0", "F1"), (g0, g1), maxiter=1)
    assert chi2.shape == (64, 64)
    assert np.all(np.isfinite(chi2))


def test_grid_chisq_derived(fitted):
    m, toas, f = fitted
    sig0 = f.errors["F0"]
    # grid over spin period P = 1/F0 via a derived transform
    p0 = 1.0 / m.F0.value
    pgrid = p0 + np.linspace(-1, 1, 5) * sig0 / m.F0.value ** 2
    chi2, vals = grid_chisq_derived(
        m, toas, ("F0",), (lambda P: 1.0 / P,), (pgrid,), maxiter=1)
    assert chi2.shape == (5,)
    assert np.argmin(chi2) == 2
    np.testing.assert_allclose(vals[0], 1.0 / pgrid)
