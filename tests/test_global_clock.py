"""Global clock-corrections mirror machinery (reference:
src/pint/observatory/global_clock_corrections.py, download replaced by
a local mirror per the zero-egress build)."""

import os
import time
import warnings

import numpy as np
import pytest

from pint_tpu.observatory.global_clock_corrections import (
    Index,
    get_clock_correction_file,
    set_clock_mirror,
    update_clock_files,
)

CLK = "# UTC(gbt) UTC\n50000.0 0.0\n60000.0 1e-6\n"


@pytest.fixture()
def mirror(tmp_path):
    d = tmp_path / "mirror"
    (d / "T2runtime" / "clock").mkdir(parents=True)
    (d / "T2runtime" / "clock" / "gbt2gps.clk").write_text(CLK)
    (d / "time_gbt.dat").write_text("  50000.0 0.0\n")
    set_clock_mirror(str(d))
    yield d
    set_clock_mirror(None)


def test_index_discovers_files(mirror):
    idx = Index()
    assert "gbt2gps.clk" in idx
    assert "time_gbt.dat" in idx
    assert idx["gbt2gps.clk"].path.endswith("gbt2gps.clk")


def test_index_txt_controls_contents_and_intervals(mirror):
    (mirror / "index.txt").write_text(
        "# name interval_days\n"
        "T2runtime/clock/gbt2gps.clk 7\n"
        "missing.clk 7\n")
    with pytest.warns(UserWarning, match="lacks it"):
        idx = Index()
    assert "gbt2gps.clk" in idx
    assert "time_gbt.dat" not in idx  # not listed
    assert idx["gbt2gps.clk"].update_interval_days == 7


def test_staleness_warns_and_raises(mirror):
    path = mirror / "T2runtime" / "clock" / "gbt2gps.clk"
    old = time.time() - 400 * 86400
    os.utime(path, (old, old))
    with pytest.warns(UserWarning, match="days old"):
        p = get_clock_correction_file("gbt2gps.clk")
    assert os.path.exists(p)
    with pytest.raises(RuntimeError, match="refresh the mirror"):
        get_clock_correction_file("gbt2gps.clk", limits="error")
    report = None
    with pytest.warns(UserWarning, match="stale clock files"):
        report = update_clock_files()
    assert report["gbt2gps.clk"] is False
    assert report["time_gbt.dat"] is True


def test_no_mirror_is_a_loud_error(tmp_path, monkeypatch):
    set_clock_mirror(None)
    monkeypatch.delenv("PINT_TPU_CLOCK_DIR", raising=False)
    with pytest.raises(FileNotFoundError, match="no network access"):
        Index()


def test_fresh_file_resolves_and_evaluates(mirror):
    from pint_tpu.observatory.clock import ClockFile

    p = get_clock_correction_file("gbt2gps.clk")
    cf = ClockFile.read(p, fmt="tempo2")
    v = cf.evaluate(np.array([55000.0]))
    assert 0.0 < v[0] < 1e-6
