"""Matrix-free streaming GLS oracles (ISSUE 12).

CPU equality oracles for the chunked normal-equation accumulator +
preconditioned-CG solve (``pint_tpu.parallel.streaming``): chunk-size
invariance (the same answer at every chunk K), CG-vs-dense-Cholesky
equality against the one-shot ``build_fit_step`` kernel, the
StreamingGLSFitter-vs-DownhillGLSFitter fit equality, ``Fitter.auto``
routing, the validated config parsers, the labeled host-mirror
failover, and the serve-side AppendTOAsRequest (rank update vs the
combined-set oracle, basis alignment, chaos failover)."""

import copy
import io
import warnings

import jax
import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs
from pint_tpu.toa import merge_TOAs

@pytest.fixture(autouse=True)
def clean_runtime():
    """Breakers are process-global; a tripped one (the failover
    tests) must never leak across tests — the test_runtime_faults
    isolation pattern (obs.reset also swaps the metric registry)."""
    from pint_tpu import obs
    from pint_tpu.runtime import reset_runtime

    reset_runtime()
    obs.reset()
    yield
    reset_runtime()
    obs.reset()


PAR = """PSR J1744-1134
RAJ 17:44:29.39 1
DECJ -11:34:54.6 1
PMRA 18.8 1
PMDEC -9.4 1
F0 245.4261196 1
F1 -5.38e-16 1
DM 3.14 1
PEPOCH 54500
POSEPOCH 54500
TZRMJD 54500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
EFAC -be X 1.1
EQUAD -be X 0.4
TNREDAMP -13.5
TNREDGAM 2.9
TNREDC 8
"""

PAR_ECORR = PAR + "ECORR -be X 1.1\n"


def _mk(par, n=600, seed=3, span=(53500.0, 56500.0),
        clustered=False):
    rng = np.random.default_rng(seed)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        if clustered:
            nclu = n // 4
            centers = np.linspace(span[0] + 1, span[1] - 1, nclu)
            offs = np.linspace(0.0, 0.02, 4)
            mjds = (centers[:, None] + offs[None, :]).ravel()
        else:
            mjds = np.sort(rng.uniform(*span, n))
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], len(mjds) // 2),
            add_noise=True, rng=rng)
        for f in toas.flags:
            f["be"] = "X"
    return model, toas


def _dense_oracle(model, toas, **flags):
    from pint_tpu.parallel import build_fit_step

    step, args, names = build_fit_step(model, toas, anchored=False,
                                       jac_f32=False,
                                       matmul_f32=False, **flags)
    out = jax.jit(step)(*args)
    return (np.asarray(out[0]), np.asarray(out[1]), float(out[2]),
            names)


def _stream(model, toas, chunk, **flags):
    from pint_tpu.parallel.streaming import StreamingGLS

    sg = StreamingGLS(model, toas, chunk=chunk, anchored=False,
                      jac_f32=False, matmul_f32=False, **flags)
    state = sg.accumulate(sg.th0, sg.tl0)
    return sg, sg.solve(state)


def test_cg_matches_dense_cholesky():
    """The matrix-free CG solution equals the dense one-shot kernel
    (dparams, covariance, bases-marginalized chi2) at f64."""
    model, toas = _mk(PAR)
    dpD, covD, chi2D, names = _dense_oracle(model, toas)
    sig = np.sqrt(np.abs(np.diag(covD)))
    sg, (dp, cov, chi2, chi2r, xf, ok, iters, resid) = _stream(
        model, toas, 128)
    assert ok
    assert iters <= 8 * (len(names) + 1)
    assert np.max(np.abs(dp - dpD) / sig) < 1e-8
    assert abs(chi2r - chi2D) < 1e-9 * abs(chi2D)
    assert np.max(np.abs(cov - covD)
                  / np.outer(sig, sig)) < 1e-8


def test_chunk_size_invariance():
    """Same answer at every chunk K — including a K that does not
    divide N (padded final chunk)."""
    model, toas = _mk(PAR, n=600)
    results = {}
    for chunk in (64, 100, 256, 1024):
        _, (dp, cov, chi2, chi2r, xf, ok, iters, resid) = _stream(
            model, toas, chunk)
        assert ok, chunk
        results[chunk] = (dp, chi2r)
    ref_dp, ref_chi = results[1024]
    sig = np.sqrt(np.abs(np.diag(cov)))
    for chunk, (dp, chi) in results.items():
        assert np.max(np.abs(dp - ref_dp) / sig) < 1e-9, chunk
        assert abs(chi - ref_chi) < 1e-10 * abs(ref_chi), chunk


def test_ecorr_boundary_carry():
    """ECORR epochs straddling chunk boundaries are downdated
    exactly (the Sherman-Morrison boundary carry): clustered epochs
    of 4 TOAs with chunk sizes that split them mid-epoch."""
    model, toas = _mk(PAR_ECORR, n=400, clustered=True)
    dpD, covD, chi2D, names = _dense_oracle(model, toas)
    sig = np.sqrt(np.abs(np.diag(covD)))
    for chunk in (66, 128):   # 66: every chunk boundary mid-epoch
        _, (dp, cov, chi2, chi2r, xf, ok, iters, resid) = _stream(
            model, toas, chunk)
        assert ok
        assert np.max(np.abs(dp - dpD) / sig) < 1e-8, chunk
        assert abs(chi2r - chi2D) < 1e-9 * abs(chi2D), chunk


def test_numpy_mirror_matches_device():
    """The host failover mirror (chunked numpy accumulate + numpy
    CG) reproduces the device path."""
    model, toas = _mk(PAR_ECORR, n=400, clustered=True)
    sg, (dp, cov, chi2, chi2r, xf, ok, iters, resid) = _stream(
        model, toas, 128)
    dpn, covn, chin, chirn, xfn, okn, _, _ = sg.solve_np()
    assert okn
    sig = np.sqrt(np.abs(np.diag(cov)))
    assert np.max(np.abs(dpn - dp) / sig) < 1e-7
    assert abs(chirn - chi2r) < 1e-8 * abs(chi2r)


def test_production_flags_streaming():
    """The forced TPU production trio (anchored + f32 Jacobian +
    f32 Gram) streams within the f32 discipline of the dense step."""
    from pint_tpu.parallel import build_fit_step
    from pint_tpu.parallel.streaming import StreamingGLS

    model, toas = _mk(PAR, n=600)
    step, args, names = build_fit_step(model, toas, anchored=True,
                                       jac_f32=True, matmul_f32=True)
    out = jax.jit(step)(*args)
    dpD = np.asarray(out[0])
    sig = np.sqrt(np.abs(np.diag(np.asarray(out[1]))))
    sg = StreamingGLS(model, toas, chunk=128, anchored=True,
                      jac_f32=True, matmul_f32=True)
    state = sg.accumulate(sg.th0, sg.tl0)
    dp, cov, chi2, chi2r, xf, ok, iters, resid = sg.solve(state)
    assert ok
    assert np.max(np.abs(dp - dpD) / sig) < 3e-2
    assert abs(chi2r - float(out[2])) < 1e-5 * abs(float(out[2]))


def test_streaming_fitter_matches_downhill():
    """StreamingGLSFitter converges to the DownhillGLSFitter fit."""
    from pint_tpu.gls import DownhillGLSFitter, StreamingGLSFitter

    model, toas = _mk(PAR, n=600)
    m1, m2 = copy.deepcopy(model), copy.deepcopy(model)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c1 = DownhillGLSFitter(toas, m1).fit_toas(maxiter=10)
    f2 = StreamingGLSFitter(toas, m2, chunk=128, anchored=False,
                            jac_f32=False, matmul_f32=False)
    c2 = f2.fit_toas(maxiter=10)
    assert abs(c1 - c2) < 1e-6 * abs(c1)
    for n in m1.free_params:
        e = m1.get_param(n).uncertainty or 1.0
        assert abs(m1.get_param(n).value
                   - m2.get_param(n).value) / e < 1e-4, n
    assert f2.passes >= 2
    assert f2.stats is not None and f2.stats.converged


def test_fitter_auto_routing(monkeypatch):
    """Fitter.auto picks the streaming path above the threshold,
    honors 0 = off and the explicit flag."""
    from pint_tpu.fitter import Fitter
    from pint_tpu.gls import DownhillGLSFitter, StreamingGLSFitter

    model, toas = _mk(PAR, n=600)
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "500")
    assert isinstance(Fitter.auto(toas, copy.deepcopy(model)),
                      StreamingGLSFitter)
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "0")
    assert isinstance(Fitter.auto(toas, copy.deepcopy(model)),
                      DownhillGLSFitter)
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "500")
    assert isinstance(
        Fitter.auto(toas, copy.deepcopy(model), streaming=False),
        DownhillGLSFitter)
    monkeypatch.delenv("PINT_TPU_STREAM_MIN_TOA", raising=False)
    assert isinstance(
        Fitter.auto(toas, copy.deepcopy(model), streaming=True),
        StreamingGLSFitter)


def test_config_parsers_validated(monkeypatch):
    """The ISSUE 12 knobs go through warn-and-ignore validated
    parsers, never raw env reads; a pinned chunk rounds UP to a
    power of two so a typo can never un-quantize the compile keys."""
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_STREAM_CHUNK", raising=False)
    assert config.stream_chunk(100_000) == 16384
    assert config.stream_chunk(1_000_000) == 65536
    assert config.stream_chunk(1000) == 4096
    monkeypatch.setenv("PINT_TPU_STREAM_CHUNK", "3000")
    assert config.stream_chunk(10_000) == 4096   # rounded up pow2
    monkeypatch.setenv("PINT_TPU_STREAM_CHUNK", "bogus")
    assert config.stream_chunk(100_000) == 16384  # warned + auto
    monkeypatch.setenv("PINT_TPU_STREAM_CHUNK", "-5")
    assert config.stream_chunk(100_000) == 16384
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "nope")
    assert config.solve_streaming() == 200_000
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "-1")
    assert config.solve_streaming() == 200_000
    monkeypatch.setenv("PINT_TPU_STREAM_MIN_TOA", "12345")
    assert config.solve_streaming() == 12345


def test_streaming_failover_is_labeled(monkeypatch):
    """A wedged backend (injected hang past the watchdog deadline)
    fails the whole streaming fit over to the numpy mirror —
    warned, counted, and equal to the direct dense fit."""
    from pint_tpu.gls import StreamingGLSFitter
    from pint_tpu.runtime import faults, get_supervisor

    model, toas = _mk(PAR, n=400)
    m = copy.deepcopy(model)
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "300")
    plan = faults.FaultPlan(
        [faults.Fault(match="stream", kind="hang", seconds=10.0)])
    f = StreamingGLSFitter(toas, m, chunk=128, anchored=False,
                           jac_f32=False, matmul_f32=False)
    with plan.active():
        with pytest.warns(RuntimeWarning, match="failed over"):
            chi2 = f.fit_toas(maxiter=6)
    assert np.isfinite(chi2)
    assert get_supervisor().snapshot()["failovers"] >= 1
    # equality vs the direct dense fit
    from pint_tpu.gls import DownhillGLSFitter

    m2 = copy.deepcopy(model)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c2 = DownhillGLSFitter(toas, m2).fit_toas(maxiter=6)
    assert abs(chi2 - c2) < 1e-6 * abs(c2)


# ---------------------------------------------------------- serving


def _mk_append(n0=800, nnew=48, seed=11):
    rng = np.random.default_rng(seed)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        mjds = np.sort(rng.uniform(53500, 56000, n0))
        toas0 = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n0 // 2),
            add_noise=True, rng=rng)
        mjds2 = np.sort(rng.uniform(56001, 56030, nnew))
        toas_new = make_fake_toas_fromMJDs(
            mjds2, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], nnew // 2),
            add_noise=True, rng=rng)
        for t in (toas0, toas_new):
            for f in t.flags:
                f["be"] = "X"
    return model, toas0, toas_new


def test_append_rank_update_matches_combined_oracle():
    """Cold build + warm append == a fresh solve over the combined
    set (basis pinned to the cold span/epoch): the O(new-TOA)
    re-convergence is exact, not approximate."""
    from pint_tpu.serve import AppendTOAsRequest, ServeEngine
    from pint_tpu.serve.append import build_append_rows
    from pint_tpu.parallel.streaming import stream_solve_np

    model, toas0, toas_new = _mk_append()
    eng = ServeEngine()
    r1 = eng.submit(AppendTOAsRequest(
        "psr", toas=toas0, model=model,
        cold=True)).result(timeout=60)
    assert r1.cold and r1.ntoa_total == toas0.ntoas
    r2 = eng.submit(AppendTOAsRequest("psr", toas=toas_new,
                                      model=model)).result(timeout=60)
    assert not r2.cold
    assert r2.ntoa_total == toas0.ntoas + toas_new.ntoas
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        comb = merge_TOAs([toas0, toas_new])
    entry = eng.append_store.get("psr")
    pr = build_append_rows(comb, model, tspan=entry.tspan,
                           tref=entry.tref)
    dpO, covO, chi2O, chi2rO, _, okO, _, _ = stream_solve_np(
        pr.M, pr.F, pr.phi, pr.r, pr.nvec, 512,
        incoffset=pr.submean)
    assert okO
    sig = np.sqrt(np.abs(np.diag(covO)))
    assert np.max(np.abs(r2.dparams - dpO) / sig) < 1e-7
    assert abs(r2.chi2r - chi2rO) < 1e-8 * abs(chi2rO)
    snap = eng.metrics.snapshot()["append"]
    assert snap["cold_builds"] == 1 and snap["rank_updates"] == 1


def test_append_state_contracts():
    """Cold is EXPLICIT: an unspecified-cold append against a
    missing state fails with StateMissing (it must never
    self-promote to a cold build — a tail batch racing an in-flight
    cold build would otherwise install a tail-only state); ECORR
    models are rejected; an explicit second cold build REBUILDS the
    state from scratch (the re-linearization path)."""
    from pint_tpu.serve import (
        AppendTOAsRequest,
        ServeEngine,
        StateMissing,
    )

    model, toas0, toas_new = _mk_append(n0=200, nnew=16)
    eng = ServeEngine()
    # unspecified cold == warm: missing state is an error, not an
    # implicit cold build
    with pytest.raises(StateMissing):
        eng.submit(AppendTOAsRequest(
            "ghost", toas=toas_new,
            model=model)).result(timeout=60)
    with pytest.raises(StateMissing):
        eng.submit(AppendTOAsRequest(
            "ghost", toas=toas_new, model=model,
            cold=False)).result(timeout=60)
    # ECORR models rejected at assembly
    me, te = _mk(PAR_ECORR, n=64, clustered=True)
    fut = eng.submit(AppendTOAsRequest("ec", toas=te, model=me,
                                       cold=True))
    with pytest.raises(ValueError, match="ECORR"):
        fut.result(timeout=60)
    # cold build, warm extend, then explicit cold REBUILD resets
    r1 = eng.submit(AppendTOAsRequest(
        "dup", toas=toas0, model=model,
        cold=True)).result(timeout=60)
    assert r1.cold
    r2 = eng.submit(AppendTOAsRequest(
        "dup", toas=toas_new, model=model)).result(timeout=60)
    assert r2.ntoa_total == toas0.ntoas + toas_new.ntoas
    r3 = eng.submit(AppendTOAsRequest(
        "dup", toas=toas0, model=model,
        cold=True)).result(timeout=60)
    assert r3.cold and r3.ntoa_total == toas0.ntoas


def test_append_chaos_mid_append_failover():
    """Mid-append backend death: the append dispatch fails over to
    the host mirror — labeled in the supervisor counters, future
    resolves with the SAME answer, zero hung futures."""
    from pint_tpu.runtime import faults, get_supervisor
    from pint_tpu.serve import AppendTOAsRequest, ServeEngine

    model, toas0, toas_new = _mk_append(n0=300, nnew=32)
    eng = ServeEngine()
    r1 = eng.submit(AppendTOAsRequest(
        "psr", toas=toas0, model=model,
        cold=True)).result(timeout=60)
    assert r1.cold
    before = get_supervisor().snapshot()["failovers"] + \
        eng.supervisor.snapshot()["failovers"]
    plan = faults.FaultPlan(
        [faults.Fault(match="serve.append", kind="error")])
    with plan.active():
        r2 = eng.submit(AppendTOAsRequest(
            "psr", toas=toas_new, model=model)).result(timeout=120)
    assert not r2.cold
    assert r2.ntoa_total == toas0.ntoas + toas_new.ntoas
    after = get_supervisor().snapshot()["failovers"] + \
        eng.supervisor.snapshot()["failovers"]
    assert after > before
    # and the state is intact: a clean follow-up append still works
    _, _, toas_more = _mk_append(n0=300, nnew=32, seed=12)
    r3 = eng.submit(AppendTOAsRequest(
        "psr", toas=toas_more, model=model)).result(timeout=60)
    assert r3.ntoa_total == r2.ntoa_total + toas_more.ntoas


def test_append_journal_ack():
    """Payload-carrying append requests journal like every kind:
    admitted before dispatch, acked served on completion."""
    from pint_tpu.serve import AppendTOAsRequest, ServeEngine
    from pint_tpu.serve.journal import RequestJournal

    model, toas0, _ = _mk_append(n0=200, nnew=16)
    j = RequestJournal.__new__(RequestJournal)
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        j = RequestJournal(d + "/j.jsonl")
        eng = ServeEngine(journal=j)
        fut = eng.submit(AppendTOAsRequest(
            "psr", toas=toas0, model=model, cold=True, rid="r1",
            payload={"kind": "append", "key": "psr"}))
        fut.result(timeout=60)
        counts = j.counts()
        assert counts["admitted"] == 1
        assert counts["acked"] == 1
