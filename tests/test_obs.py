"""Observability acceptance suite (ISSUE 10).

The structured-telemetry contracts CLAUDE.md promises:

- span parent/child integrity across a PIPELINED serve drain (depth
  >= 2): every submitted request resolves to a terminal span with
  zero orphan spans, and the export loads in Perfetto's trace-event
  parser (validated structurally);
- an injected hang -> failover shows the timeout / breaker /
  failover events in causal order under the dispatch span;
- histogram quantiles against a known sample set (upper-edge,
  one-octave resolution bound);
- the flight recorder dumps on a ``runtime.faults`` breaker-open
  plan (and is armed by the flight dir alone, tracing off);
- the tracer-off hot path emits ZERO records.
"""

import json
import time

import numpy as np
import pytest

from pint_tpu import obs
from pint_tpu.runtime import (
    DispatchSupervisor,
    Fault,
    FaultPlan,
    reset_runtime,
)


@pytest.fixture(autouse=True)
def clean_obs():
    """A configured tracer (or tripped breaker) must never leak
    across tests."""
    obs.reset()
    reset_runtime()
    yield
    obs.reset()
    reset_runtime()


def _assert_chrome_trace(path):
    """Structural validation against Perfetto's trace-event parser
    requirements: a JSON object with a ``traceEvents`` list whose
    members carry name/ph/ts/pid/tid (and dur for complete events) —
    plus this repo's causal contract: every parent reference
    resolves inside the file (zero orphan spans)."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert isinstance(doc, dict)
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    ids = set()
    for e in evs:
        assert isinstance(e["name"], str)
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float))
        ids.add(e["args"]["span"])
    orphans = [e for e in evs
               if e["args"].get("parent") is not None
               and e["args"]["parent"] not in ids]
    assert orphans == [], f"orphan spans: {orphans[:3]}"
    return evs


# ------------------------------------------------------------- tracer


def test_span_nesting_context_and_export(tmp_path):
    t = obs.configure(enabled=True)
    with obs.span("root", kind="test") as root:
        root.event("marker", x=1)
        with obs.span("child") as child:
            assert child.trace_id == root.trace_id
            assert obs.current() == child.ctx
    assert obs.current() is None
    path = str(tmp_path / "trace.json")
    n = t.export(path)
    evs = _assert_chrome_trace(path)
    assert n == len(evs) == 3
    by_name = {e["name"]: e for e in evs}
    assert by_name["child"]["args"]["parent"] == \
        by_name["root"]["args"]["span"]
    assert by_name["marker"]["args"]["parent"] == \
        by_name["root"]["args"]["span"]


def test_attach_propagates_context_across_threads():
    import threading

    obs.configure(enabled=True)
    out = {}
    with obs.span("issuer") as sp:
        ctx = obs.current()

        def worker():
            with obs.attach(ctx):
                with obs.span("worker_side") as w:
                    out["trace"] = w.trace_id
                    out["parent"] = w.parent_id

        th = threading.Thread(target=worker)
        th.start()
        th.join()
    assert out["trace"] == sp.trace_id
    assert out["parent"] == sp.span_id


def test_tracer_off_hot_path_emits_zero_records():
    obs.reset()  # env-driven: $PINT_TPU_TRACE unset in the suite
    assert not obs.recording()
    sp = obs.span("anything", key="x")
    assert sp is obs.NOOP_SPAN
    with sp as s:
        s.event("nope")
    obs.event("also_nope")
    obs.record_span("still_nope", 0.0, 1.0)
    assert len(obs.get_tracer()) == 0
    # a full supervised dispatch with tracing off: still zero
    sup = DispatchSupervisor()
    assert sup.dispatch(lambda: 41, key="off.path") == 41
    assert len(obs.get_tracer()) == 0


def test_ring_bounds_and_drop_accounting():
    t = obs.configure(enabled=True, ring_size=16)
    for i in range(50):
        obs.event(f"e{i}")
    assert len(t) == 16
    assert t.dropped == 34
    names = [r["name"] for r in t.records()]
    assert names == [f"e{i}" for i in range(34, 50)]  # newest kept


def test_jsonl_stream_mode(tmp_path):
    stream = str(tmp_path / "spans.jsonl")
    obs.configure(enabled=True, stream=stream)
    with obs.span("streamed", tag="s"):
        pass
    obs.event("inst")
    lines = [json.loads(x) for x in
             open(stream, encoding="utf-8").read().splitlines()]
    assert [r["name"] for r in lines] == ["streamed", "inst"]
    assert lines[0]["ph"] == "X" and lines[1]["ph"] == "i"


# --------------------------------------------------------- histograms


def test_histogram_quantiles_against_known_samples():
    from pint_tpu.obs import LatencyHistogram

    h = LatencyHistogram()
    samples_ms = list(range(1, 101))     # 1..100 ms, uniform
    for ms in samples_ms:
        h.record(ms / 1e3)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["max_ms"] == 100.0
    assert abs(snap["mean_ms"] - np.mean(samples_ms)) < 1e-6
    # upper-edge quantiles: within one octave above the true value,
    # never below it (the conservative-bound contract)
    for q in (50, 90, 99):
        true = float(np.percentile(samples_ms, q))
        got = h.quantile_ms(q)
        assert true <= got <= 2.0 * true, (q, true, got)
    # empty histogram: no NaNs, JSON-safe
    empty = LatencyHistogram()
    assert empty.quantile_ms(99) is None
    assert empty.snapshot() == {"count": 0}
    json.dumps(empty.snapshot())


def test_histogram_set_keys_and_snapshot():
    from pint_tpu.obs import HistogramSet

    hs = HistogramSet()
    hs.record(("device", "gls", "64"), "e2e", 0.004)
    hs.record(("device", "gls", "64"), "queue_wait", 0.001)
    hs.record(("host", "phase", "128"), "e2e", 0.020)
    snap = hs.snapshot()
    assert set(snap) == {"device/gls/64", "host/phase/128"}
    assert set(snap["device/gls/64"]) == {"e2e", "queue_wait"}
    json.dumps(snap)


# ------------------------------------------------ supervisor tracing


def test_hang_failover_spans_in_causal_order(monkeypatch):
    """Injected hang: the dispatch span carries dispatch.timeout ->
    breaker/failover children in causal (timestamp) order, parented
    under the SAME dispatch span, which is itself a child of the
    caller's context span."""
    monkeypatch.setenv("PINT_TPU_DISPATCH_DEADLINE_MS", "150")
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "1")
    t = obs.configure(enabled=True)
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="obs.hang", kind="hang",
                            seconds=5.0)])
    with plan.active():
        with obs.span("caller.fit") as caller:
            out = sup.dispatch(lambda: 1, key="obs.hang",
                               fallback=lambda: "host")
    assert out == "host"
    recs = t.records()
    disp = [r for r in recs if r["name"] == "dispatch/obs.hang"]
    assert len(disp) == 1
    dspan = disp[0]["args"]["span"]
    # the dispatch span parents under the caller's span
    caller_rec = next(r for r in recs if r["name"] == "caller.fit")
    assert disp[0]["args"]["parent"] == caller_rec["args"]["span"]
    assert disp[0]["args"]["trace"] == caller_rec["args"]["trace"]
    events = {r["name"]: r for r in recs if r["ph"] == "i"}
    for name in ("dispatch.timeout", "breaker.open",
                 "dispatch.failover"):
        assert name in events, (name, sorted(events))
        assert events[name]["args"]["parent"] == dspan
    assert events["dispatch.timeout"]["ts"] <= \
        events["breaker.open"]["ts"] <= \
        events["dispatch.failover"]["ts"]
    # the NEXT dispatch short-circuits on the open breaker — a
    # labeled breaker.reject under its own dispatch span
    with plan.active():
        assert sup.dispatch(lambda: 1, key="obs.hang",
                            fallback=lambda: "host2") == "host2"
    rej = [r for r in t.records() if r["name"] == "breaker.reject"]
    assert rej


def test_supervisor_latency_histograms_in_snapshot():
    sup = DispatchSupervisor()
    sup.dispatch(lambda: time.sleep(0.002) or 7, key="obs.lat")
    sup.dispatch(lambda: 7, key="obs.lat")
    snap = sup.snapshot()
    lat = snap["latency"]
    key = "cpu/obs.lat"
    assert key in lat
    assert lat[key]["dispatch_wall"]["count"] == 2
    json.dumps(snap)


# ------------------------------------------------- flight recorder


def test_flight_recorder_dumps_on_breaker_open_plan(tmp_path,
                                                    monkeypatch):
    """A runtime.faults plan that trips the breaker OPEN must leave
    a flight dump in the armed dir — and arming the dir alone (no
    $PINT_TPU_TRACE) must turn on ring recording so the dump has a
    populated black box."""
    monkeypatch.setenv("PINT_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("PINT_TPU_DISPATCH_RETRIES", "0")
    fdir = str(tmp_path / "flight")
    obs.configure(enabled=False, flight_dir=fdir)
    assert obs.recording()  # armed recorder implies ring recording
    sup = DispatchSupervisor()
    plan = FaultPlan([Fault(match="obs.brk", kind="error")])
    with plan.active():
        assert sup.dispatch(lambda: 1, key="obs.brk",
                            fallback=lambda: "host") == "host"
    f = obs.get_flight()
    assert f is not None and f.dumps == 1
    dumps = sorted((tmp_path / "flight").glob("flight-*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "breaker_open"
    assert doc["extra"]["breaker"]["state"] == "open"
    # the dump fires MID-dispatch (at the open transition), so the
    # black box holds the dispatch span's child events — the
    # enclosing "dispatch/obs.brk" span completes only afterwards
    names = {e["name"] for e in doc["events"]}
    assert "dispatch.transient_error" in names
    assert "breaker.open" in names
    status = obs.status()
    assert status["flight"]["dumps"] == 1
    assert status["flight"]["last_reason"] == "breaker_open"


def test_flight_dump_rate_limited_per_reason(tmp_path):
    obs.configure(enabled=True, flight_dir=str(tmp_path))
    assert obs.flight_dump("storm") is not None
    assert obs.flight_dump("storm") is None        # inside interval
    assert obs.flight_dump("other") is not None    # distinct reason
    assert obs.get_flight().suppressed == 1


def test_shed_burst_triggers_flight_dump(tmp_path):
    from pint_tpu.serve.admission import _BURST_N, AdmissionController

    obs.configure(enabled=True, flight_dir=str(tmp_path))
    adm = AdmissionController(policy="reject")
    for _ in range(_BURST_N):
        adm.note_shed("deadline")
    assert adm.shed_bursts == 1
    # the dump runs on a detached daemon thread (several note_shed
    # call sites hold the engine lock — a disk fsync there would
    # stall admission during the exact storm being recorded)
    deadline = time.monotonic() + 5.0
    dumps = []
    while time.monotonic() < deadline:
        dumps = list(tmp_path.glob("flight-*shed_burst*.json"))
        if dumps:
            break
        time.sleep(0.01)
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["extra"]["admission"]["shed_bursts"] == 1


# ------------------------------------------------ serve integration


def _workload(n, base):
    from pint_tpu.serve.workload import build_workload

    return build_workload(n, sizes=(40, 90), base=base,
                          prebuild=True, entry_name="OBS")


def test_pipelined_drain_span_integrity(tmp_path):
    """THE tracing acceptance oracle: a pipelined drain (depth 2)
    produces a trace in which every submitted request resolves to a
    terminal span, parent/child causality is intact (zero orphans),
    per-request queue spans link to their unit's trace, and the
    export parses as Chrome trace-event JSON."""
    from pint_tpu.serve import ServeEngine

    fresh = _workload(10, base=3300)
    t = obs.configure(enabled=True)
    eng = ServeEngine(pipeline_depth=2)
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)
    path = str(tmp_path / "serve.json")
    t.export(path)
    evs = _assert_chrome_trace(path)
    by_name: dict = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    roots = by_name.get("serve.request", [])
    terms = by_name.get("serve.terminal", [])
    assert len(roots) == len(futs)
    assert len(terms) == len(futs)
    assert all(e["args"]["status"] == "served" for e in terms)
    # each terminal parents under its request root, same trace
    root_by_span = {e["args"]["span"]: e for e in roots}
    for e in terms:
        parent = root_by_span[e["args"]["parent"]]
        assert e["args"]["trace"] == parent["args"]["trace"]
    # queue spans parent under request roots AND carry the unit
    # trace id they dispatched in
    unit_traces = {e["args"]["trace"]
                   for e in by_name.get("serve.unit", [])}
    queues = by_name.get("serve.queue", [])
    assert len(queues) == len(futs)
    for e in queues:
        assert e["args"]["parent"] in root_by_span
        assert e["args"]["unit"] in unit_traces
    # units carry route decisions and issue/collect halves
    assert by_name.get("serve.route")
    assert by_name.get("serve.issue")
    assert by_name.get("serve.collect")
    # supervised dispatch spans joined the same tracer
    assert any(n.startswith("dispatch/serve.") for n in by_name)
    # pipelining really engaged
    assert eng.metrics.snapshot()["dispatch"]["max_inflight"] >= 2


def test_serve_latency_histograms_per_pool_kind_class():
    from pint_tpu.serve import ServeEngine

    fresh = _workload(8, base=3500)
    eng = ServeEngine()
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)
    lat = eng.metrics.snapshot()["latency"]
    assert lat, "latency block empty"
    for key, metrics in lat.items():
        pool, kind = key.split("/")[:2]
        assert pool in ("device", "host", "host-failover")
        assert kind in ("gls", "phase", "posterior")
        assert set(metrics) == {"queue_wait", "dispatch_wall", "e2e"}
        for m in metrics.values():
            assert m["count"] >= 1
    # total e2e samples == completed requests
    tot = sum(m["e2e"]["count"] for m in lat.values())
    assert tot == len(futs)


def test_shed_requests_get_terminal_spans():
    """Shed paths resolve to labeled terminal spans too: quota shed
    at the raise path, deadline shed through the future."""
    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.request import (
        DeadlineExceeded,
        TenantOverQuota,
    )

    t = obs.configure(enabled=True)
    fresh = _workload(3, base=3700)
    eng = ServeEngine(tenant_qps=0.001, tenant_burst=1.0)
    reqs = fresh()
    for r in reqs:
        r.tenant = "noisy"
    futs = []
    shed_quota = 0
    for r in reqs:
        try:
            futs.append(eng.submit(r))
        except TenantOverQuota:
            shed_quota += 1
    assert shed_quota >= 1
    # an already-expired deadline: shed in queue at the next touch
    dead = _workload(1, base=3800)()[0]
    dead.deadline_s = 1e-9
    fut = eng.submit(dead)
    time.sleep(0.002)
    eng.flush()
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    for f in futs:
        f.result(timeout=5)
    recs = t.records()
    statuses = [r["args"]["status"] for r in recs
                if r["name"] == "serve.terminal"]
    assert statuses.count("shed:quota") == shed_quota
    assert "shed:deadline" in statuses
    assert statuses.count("served") == len(futs)
    # conservation: one terminal per submit attempt
    assert len(statuses) == len(reqs) + 1


# ------------------------------------------------------ the daemon


def test_daemon_stats_request_answers_inline(capsys, tmp_path):
    """Acceptance: {"kind": "stats"} answers with histogram
    quantiles + flight status without perturbing in-flight batches —
    and without journaling the introspection line."""
    from pint_tpu.scripts.pint_serve import main

    journal = str(tmp_path / "j.jsonl")
    assert main(["--journal", journal],
                stdin=[json.dumps({"kind": "stats", "id": "s1"})]) \
        == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    stats = [x for x in lines if x.get("kind") == "stats"]
    assert len(stats) == 1
    s = stats[0]
    assert s["ok"] and s["id"] == "s1"
    assert "latency" in s
    assert "obs" in s and "trace" in s["obs"]
    assert "dispatch" in s
    # never journaled: nothing to replay
    content = open(journal, encoding="utf-8").read()
    assert '"stats"' not in content


# ----------------------------------------------------- config knobs


def test_obs_env_knobs(monkeypatch):
    from pint_tpu import config

    assert config.trace_enabled() is False
    monkeypatch.setenv("PINT_TPU_TRACE", "on")
    assert config.trace_enabled() is True
    monkeypatch.setenv("PINT_TPU_TRACE_RING", "512")
    assert config.trace_ring_size() == 512
    monkeypatch.setenv("PINT_TPU_TRACE_RING", "banana")
    assert config.trace_ring_size() == 16384  # warned, defaulted
    monkeypatch.setenv("PINT_TPU_FLIGHT_DIR", "/tmp/f")
    assert config.flight_dir() == "/tmp/f"


def test_dispatch_rtt_override_validated(monkeypatch):
    """ISSUE 10 satellite: $PINT_TPU_DISPATCH_RTT_MS is validated
    BEFORE the per-backend cache — finite positive floats only; a
    typo or out-of-range value warns and is ignored (never silently
    poisons deadline predictions)."""
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_DISPATCH_RTT_MS", raising=False)
    assert config.dispatch_rtt_override_ms() is None
    monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", "42.5")
    assert config.dispatch_rtt_override_ms() == 42.5
    assert config.dispatch_rtt_ms() == 42.5  # cache never consulted
    for bad in ("banana", "-5", "0", "nan", "inf"):
        monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", bad)
        assert config.dispatch_rtt_override_ms() is None, bad
    # the supervisor's peek sees the same validated view
    from pint_tpu.runtime.supervisor import DispatchSupervisor as DS

    monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", "not-a-number")
    assert DS._peek_rtt_ms("cpu") == config.dispatch_rtt_ms()


# ------------------------------------------------------- satellites


def test_mjd_to_calendar_exact():
    """ISSUE 10 satellite: the exact MJD->calendar conversion the
    pintk day-of-year axis now uses — leap years, century rules and
    year boundaries against datetime itself."""
    import datetime

    from pint_tpu.time.mjd import mjd_to_calendar

    rng = np.random.default_rng(7)
    mjds = np.concatenate([
        [51544, 51543, 51909, 51910, 58848, 60400, 40587, 59580],
        rng.integers(-20000, 120000, 2000),   # ~1804 to ~2187
    ])
    yr, mo, dom, doy = mjd_to_calendar(mjds)
    for k, m in enumerate(mjds):
        d = datetime.date(1858, 11, 17) + datetime.timedelta(
            days=int(m))
        assert (yr[k], mo[k], dom[k]) == (d.year, d.month, d.day), m
        assert doy[k] == d.timetuple().tm_yday, m
    # the old 365.25-approximation failure mode: Dec 31 of a non-leap
    # year must be day 365, never a fabricated 366
    y, _, _, doy2 = mjd_to_calendar([51909.9])  # 2000-12-31 (leap)
    assert y[0] == 2000 and doy2[0] == 366
    y, _, _, doy3 = mjd_to_calendar([52274.0])  # 2001-12-31
    assert y[0] == 2001 and doy3[0] == 365
