"""Component-zoo tail tests: troposphere, CM/CMX/CMWaveX, IFUNC,
piecewise spindown, SWX, FDJump, PLChrom/PLSW noise (reference test
strategy: SURVEY.md §4.2/4.4 — designmatrix-vs-FD + simulate->fit
recovery per component; FDJUMP must never silently drop)."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs

BASE = """
PSR J0009+0009
RAJ 06:30:00.0
DECJ 30:00:00.0
F0 150.0 1
F1 -1e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 18.0
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


# TZR-free base for model-vs-model difference tests: with an absolute
# phase anchor, each model's OWN delay at the TZR point enters as a
# constant offset that the per-component "expect" arrays don't model
BASE_NOTZR = "\n".join(ln for ln in BASE.splitlines()
                       if not ln.startswith("TZR")) + "\n"


def _mk(extra: str = "", base: str = BASE):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(base + extra))


def _toas(model, n=60, obs="gbt", two_band=True, seed=0,
          add_noise=False):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rng = np.random.default_rng(seed)
        tA = make_fake_toas_uniform(54001, 55999, n - n // 2, model,
                                    error_us=1.0, obs=obs,
                                    freq_mhz=1400.0,
                                    add_noise=add_noise, rng=rng)
        tB = make_fake_toas_uniform(54002, 55998, n // 2, model,
                                    error_us=1.0, obs=obs,
                                    freq_mhz=820.0,
                                    add_noise=add_noise, rng=rng)
        return merge_TOAs([tA, tB]) if two_band else tA


def _r(model, toas, subtract_mean=True):
    return np.asarray(Residuals(toas, model,
                                subtract_mean=subtract_mean).time_resids)


def _recovery(extra, pname, delta, n=60, seed=3, base=BASE,
              two_band=True):
    """Simulate with truth, perturb pname, refit, require recovery."""
    from pint_tpu.fitter import DownhillWLSFitter

    truth = _mk(extra, base=base)
    toas = _toas(truth, n=n, seed=seed, add_noise=True,
                 two_band=two_band)
    m = copy.deepcopy(truth)
    m.get_param(pname).add_delta(delta)
    m.invalidate_cache(params_only=True)
    f = DownhillWLSFitter(toas, m)
    f.fit_toas()
    truthv = truth.get_param(pname).value
    assert abs(m.get_param(pname).value - truthv) \
        < 5 * f.errors[pname], pname
    return f


# --------------------------------------------------------- troposphere


def test_troposphere_delay_properties():
    m_on = _mk("CORRECT_TROPOSPHERE Y\n", base=BASE_NOTZR)
    m_off = _mk("CORRECT_TROPOSPHERE N\n", base=BASE_NOTZR)
    assert "TroposphereDelay" in m_on.components
    toas = _toas(m_off, n=40, obs="gbt")
    r_on = _r(m_on, toas, subtract_mean=False)
    r_off = _r(m_off, toas, subtract_mean=False)
    # a positive delay LOWERS the phase residual: d = -delay
    d = r_off - r_on
    # zenith hydrostatic delay ~7.7 ns; mapped delay is larger and
    # always positive (adds path)
    assert np.all(d > 5e-9)
    assert np.all(d < 1e-6)
    # a source transiting near zenith at GBT (dec ~ +38.4) maps closer
    # to the zenith delay than a low-elevation one
    lowdec = BASE_NOTZR.replace("DECJ 30:00:00.0", "DECJ -15:00:00.0")
    m2_on = _mk("CORRECT_TROPOSPHERE Y\n", base=lowdec)
    m2_off = _mk("CORRECT_TROPOSPHERE N\n", base=lowdec)
    d2 = _r(m2_off, toas, subtract_mean=False) - \
        _r(m2_on, toas, subtract_mean=False)
    assert np.median(d2) > np.median(d)


def test_troposphere_zero_at_barycenter():
    m_on = _mk("CORRECT_TROPOSPHERE Y\n", base=BASE_NOTZR)
    m_off = _mk("CORRECT_TROPOSPHERE N\n", base=BASE_NOTZR)
    toas = _toas(m_off, n=20, obs="barycenter")
    np.testing.assert_allclose(_r(m_on, toas, subtract_mean=False),
                               _r(m_off, toas, subtract_mean=False),
                               atol=1e-15)


# ----------------------------------------------------------- chromatic


def test_chromatic_cm_scaling():
    """CM delay scales as nu^-alpha and reduces to the DM law at
    alpha=2 (with the 1 GHz reference convention)."""
    m = _mk("CM 0.02\nTNCHROMIDX 4\nCMEPOCH 55000\n", base=BASE_NOTZR)
    toas = _toas(m, n=40)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    f = np.asarray(toas.freq_mhz)
    ratio = d[f < 1000].mean() / d[f > 1000].mean()  # sign cancels
    assert ratio == pytest.approx((1400.0 / 820.0) ** 4, rel=0.05)


def test_chromatic_cm_recovery():
    _recovery("CM 0.02 1\nTNCHROMIDX 4\nCMEPOCH 55000\n", "CM", 1e-3)


def test_cmx_windows():
    m = _mk("CMX_0001 0.05 1\nCMXR1_0001 54000\nCMXR2_0001 54800\n"
            "CMX_0002 -0.02 1\nCMXR1_0002 54800.5\nCMXR2_0002 56000\n",
            base=BASE_NOTZR)
    assert "ChromaticCMX" in m.components
    toas = _toas(m, n=40)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    d = -d  # positive delay lowers the residual
    mjd = toas.get_mjds()
    lo = np.asarray(toas.freq_mhz) < 1000
    assert np.all(d[(mjd < 54800) & lo] > 0)
    assert np.all(d[(mjd > 54801) & lo] < 0)


def test_cmx_recovery():
    _recovery("CMX_0001 0.05 1\nCMXR1_0001 54000\nCMXR2_0001 56000\n",
              "CMX_0001", 2e-3)


def test_cmwavex_delay():
    m = _mk("CMWXEPOCH 55000\nCMWXFREQ_0001 0.005\n"
            "CMWXSIN_0001 0.01 1\nCMWXCOS_0001 0.0\n")
    assert "CMWaveX" in m.components
    _recovery("CMWXEPOCH 55000\nCMWXFREQ_0001 0.005\n"
              "CMWXSIN_0001 0.01 1\nCMWXCOS_0001 0.0 1\n",
              "CMWXSIN_0001", 1e-3)


# --------------------------------------------------------------- ifunc


def test_ifunc_linear_interpolation():
    m = _mk("SIFUNC 2\nIFUNC1 54000 0.0\nIFUNC2 55000 1e-5\n"
            "IFUNC3 56000 0.0\n", base=BASE_NOTZR)
    assert "IFunc" in m.components
    toas = _toas(m, n=40)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    mjd = toas.get_mjds()
    expect = np.interp(mjd, [54000, 55000, 56000], [0.0, 1e-5, 0.0])
    np.testing.assert_allclose(d, expect, atol=2e-11)


def test_ifunc_constant_mode():
    m = _mk("SIFUNC 0\nIFUNC1 54000 1e-5\nIFUNC2 55500 3e-5\n",
            base=BASE_NOTZR)
    toas = _toas(m, n=30)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    mjd = toas.get_mjds()
    expect = np.where(np.abs(mjd - 54000) < np.abs(mjd - 55500),
                      1e-5, 3e-5)
    np.testing.assert_allclose(d, expect, atol=2e-11)


# ------------------------------------------------- piecewise spindown


def test_piecewise_spindown_window():
    m = _mk("PWEP_1 55000\nPWSTART_1 54800\nPWSTOP_1 55200\n"
            "PWF0_1 1e-9\nPWF1_1 0\nPWF2_1 0\n", base=BASE_NOTZR)
    assert "PiecewiseSpindown" in m.components
    toas = _toas(m, n=60)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    mjd = toas.get_mjds()
    inside = (mjd >= 54800) & (mjd <= 55200)
    # extra phase PWF0 * dt / F0 seconds inside the window, 0 outside
    dt = (mjd - 55000.0) * 86400.0
    expect = np.where(inside, 1e-9 * dt / 150.0, 0.0)
    # expect uses UTC-days dt; the component uses barycentric seconds
    # (up to ~500 s earlier) -> ~4e-9 s slop at the window edges
    np.testing.assert_allclose(d, expect, atol=5e-9)


def test_piecewise_spindown_recovery():
    _recovery("PWEP_1 55000\nPWSTART_1 54300\nPWSTOP_1 55700\n"
              "PWF0_1 1e-9 1\n", "PWF0_1", 3e-10)


# ------------------------------------------------------------- SWX


def test_swx_windows_and_recovery():
    m = _mk("SWXDM_0001 1e-4 1\nSWXR1_0001 54000\nSWXR2_0001 56000\n",
            base=BASE_NOTZR)
    assert "SolarWindDispersionX" in m.components
    toas = _toas(m, n=50)
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    d = -d  # positive delay lowers the residual
    lo = np.asarray(toas.freq_mhz) < 1000
    assert np.all(d[lo] > 0)
    # normalized geometry: max delay equals DMconst*SWXDM/nu^2
    from pint_tpu.models.dispersion import DMconst

    assert d[lo].max() == pytest.approx(
        DMconst * 1e-4 / 820.0 ** 2, rel=0.05)
    _recovery("SWXDM_0001 1e-4 1\nSWXR1_0001 54000\nSWXR2_0001 56000\n",
              "SWXDM_0001", 3e-5)


# ------------------------------------------------------------ FDJump


def test_fdjump_not_silently_dropped():
    m = _mk("FDJUMP -grp L 1e-5 1\n")
    assert "FDJump" in m.components
    assert len(m.components["FDJump"].fdjumps) == 1


def test_fdjump_applies_to_selected_toas():
    m = _mk("FD1JUMP -grp L 1e-5 1\nFD2JUMP -grp L 3e-6 1\n",
            base=BASE_NOTZR)
    toas = _toas(m, n=40)
    for i, fl in enumerate(toas.flags):
        fl["grp"] = "L" if i % 2 == 0 else "S"
    m0 = _mk("", base=BASE_NOTZR)
    d = _r(m, toas, subtract_mean=False) - _r(m0, toas,
                                              subtract_mean=False)
    sel = np.array([fl["grp"] == "L" for fl in toas.flags])
    f = np.asarray(toas.freq_mhz)
    logf = np.log(f / 1000.0)
    expect = np.where(sel, 1e-5 * logf + 3e-6 * logf ** 2, 0.0)
    # positive delay lowers the residual; the component evaluates at
    # the Doppler-shifted barycentric frequency (|dv/c| ~ 1e-4)
    np.testing.assert_allclose(-d, expect, atol=5e-9)


def test_fdjump_recovery():
    from pint_tpu.fitter import DownhillWLSFitter

    truth = _mk("FD1JUMP -grp L 1e-5 1\n")
    toas = _toas(truth, n=60, add_noise=False)
    for i, fl in enumerate(toas.flags):
        fl["grp"] = "L" if i % 2 == 0 else "S"
    rng = np.random.default_rng(5)
    from pint_tpu.simulation import zero_residuals

    toas = zero_residuals(toas, truth)
    m = copy.deepcopy(truth)
    m.get_param("FD1JUMP1").add_delta(5e-6)
    m.invalidate_cache()
    f = DownhillWLSFitter(toas, m)
    f.fit_toas()
    assert abs(m.get_param("FD1JUMP1").value - 1e-5) < 1e-8


# ----------------------------------------------------- new noise terms


def test_plchromnoise_basis():
    m = _mk("CM 0.0\nTNCHROMIDX 4\nCMEPOCH 55000\n"
            "TNCHROMAMP -13.0\nTNCHROMGAM 3.0\nTNCHROMC 8\n")
    assert "PLChromNoise" in m.components
    toas = _toas(m, n=40)
    F = m.noise_model_designmatrix(toas)
    phi = m.noise_model_basis_weight(toas)
    assert F.shape == (40, 16)
    assert phi.shape == (16,)
    # rows at lower frequency have (1400/820)^4 larger amplitude
    f = np.asarray(toas.freq_mhz)
    hi_rows = np.abs(F[f > 1000]).max()
    lo_rows = np.abs(F[f < 1000]).max()
    assert lo_rows / hi_rows == pytest.approx((1400 / 820) ** 4,
                                              rel=0.2)


def test_plswnoise_basis():
    m = _mk("NE_SW 4.0\nTNSWAMP -13.0\nTNSWGAM 2.0\nTNSWC 5\n")
    assert "PLSWNoise" in m.components
    toas = _toas(m, n=30)
    F = m.noise_model_designmatrix(toas)
    assert F.shape == (30, 10)
    assert np.all(np.isfinite(F))
    # GLS fitter runs with it
    from pint_tpu.gls import GLSFitter

    f = GLSFitter(toas, copy.deepcopy(m))
    chi2 = f.fit_toas()
    assert np.isfinite(chi2)


# ----------------------------------------------- par round trip (all)


def test_tail_components_parfile_roundtrip():
    extras = [
        "CORRECT_TROPOSPHERE Y\n",
        "CM 0.02 1\nCM1 1e-10\nTNCHROMIDX 4\nCMEPOCH 55000\n",
        "CMX_0001 0.05 1\nCMXR1_0001 54000\nCMXR2_0001 56000\n",
        "SIFUNC 2\nIFUNC1 54000 0.0\nIFUNC2 56000 1e-5\n",
        "PWEP_1 55000\nPWSTART_1 54800\nPWSTOP_1 55200\nPWF0_1 1e-9\n",
        "SWXDM_0001 1e-4\nSWXR1_0001 54000\nSWXR2_0001 56000\n",
        "FD1JUMP -grp L 1e-5\n",
    ]
    for extra in extras:
        m = _mk(extra)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m2 = get_model(io.StringIO(m.as_parfile()))
        toas = _toas(m, n=16)
        for fl in toas.flags:
            fl["grp"] = "L"
        np.testing.assert_allclose(
            _r(m, toas, subtract_mean=False),
            _r(m2, toas, subtract_mean=False), atol=1e-12,
            err_msg=extra)


def test_swx_feeds_wideband_dm_channel():
    """SWX DM must flow into dm_total_device/build_dm_fn (reference:
    SWX dm_value summed into total DM for the wideband DM channel) —
    it was delay-only until round 5."""
    m = _mk("SWXDM_0001 1e-4 1\nSWXR1_0001 54000\nSWXR2_0001 56000\n",
            base=BASE_NOTZR)
    toas = _toas(m, n=40)
    m0 = _mk("", base=BASE_NOTZR)
    dm_fn, free = m.build_dm_fn(toas)
    dm0_fn, _ = m0.build_dm_fn(toas)
    import jax.numpy as jnp

    _, _, th, *_ = m._pack()
    _, _, th0, *_ = m0._pack()
    d = np.asarray(dm_fn(jnp.asarray(th)) - dm0_fn(jnp.asarray(th0)))
    assert d.max() == pytest.approx(1e-4, rel=1e-6)  # window max = SWXDM
    assert d.min() >= 0.0
