"""Every symbol MIGRATION.md promises must import (the map is the
switching user's contract; a stale row is a broken promise)."""
import importlib

import pytest

MAP = [
    ("pint_tpu.models", "get_model"),
    ("pint_tpu", "get_model_and_toas"),
    ("pint_tpu.toa", "get_TOAs"),
    ("pint_tpu.toa", "get_TOAs_array"),
    ("pint_tpu.toa", "merge_TOAs"),
    ("pint_tpu.toa", "save_pickle"),
    ("pint_tpu.toa", "load_pickle"),
    ("pint_tpu.residuals", "Residuals"),
    ("pint_tpu.residuals", "WidebandTOAResiduals"),
    ("pint_tpu.residuals", "CombinedResiduals"),
    ("pint_tpu.residuals", "DMResiduals"),
    ("pint_tpu.fitter", "Fitter"),
    ("pint_tpu.fitter", "WLSFitter"),
    ("pint_tpu.fitter", "DownhillWLSFitter"),
    ("pint_tpu.gls", "GLSFitter"),
    ("pint_tpu.gls", "DownhillGLSFitter"),
    ("pint_tpu.gls", "DeviceDownhillGLSFitter"),
    ("pint_tpu.wideband_fitter", "WidebandTOAFitter"),
    ("pint_tpu.wideband_fitter", "WidebandDownhillFitter"),
    ("pint_tpu.pint_matrix", "DesignMatrix"),
    ("pint_tpu.pint_matrix", "CovarianceMatrix"),
    ("pint_tpu.simulation", "make_fake_toas_uniform"),
    ("pint_tpu.simulation", "make_fake_toas_fromMJDs"),
    ("pint_tpu.simulation", "make_fake_toas_fromtim"),
    ("pint_tpu.simulation", "calculate_random_models"),
    ("pint_tpu.bayesian", "BayesianTiming"),
    ("pint_tpu.mcmc_fitter", "MCMCFitter"),
    ("pint_tpu.sampler", "EnsembleSampler"),
    ("pint_tpu.gridutils", "grid_chisq"),
    ("pint_tpu.gridutils", "grid_chisq_derived"),
    ("pint_tpu.templates", "LCTemplate"),
    ("pint_tpu.templates", "LCFitter"),
    ("pint_tpu.templates", "LCGaussian"),
    ("pint_tpu.eventstats", "hm"),
    ("pint_tpu.eventstats", "hmw"),
    ("pint_tpu.eventstats", "z2m"),
    ("pint_tpu.eventstats", "sig2sigma"),
    ("pint_tpu.eventstats", "h_sig"),
    ("pint_tpu.event_toas", "load_event_TOAs"),
    ("pint_tpu.event_toas", "load_fits_TOAs"),
    ("pint_tpu.observatory", "get_observatory"),
    ("pint_tpu.observatory", "TopoObs"),
    ("pint_tpu.models.parameter", "maskParameter"),
    ("pint_tpu.models.parameter", "prefixParameter"),
    ("pint_tpu.models.parameter", "funcParameter"),
    ("pint_tpu.models.parameter", "pairParameter"),
    ("pint_tpu.models.model_builder", "guess_binary_model"),
    ("pint_tpu.models.model_builder", "parse_parfile"),
    ("pint_tpu.polycos", "Polycos"),
    ("pint_tpu.derived_quantities", "companion_mass"),
    ("pint_tpu.derived_quantities", "pmtot"),
    ("pint_tpu.binaryconvert", "convert_binary"),
    ("pint_tpu.utils", "FTest"),
    ("pint_tpu.utils", "dmxparse"),
    ("pint_tpu.utils", "dmx_ranges"),
    ("pint_tpu.utils", "wavex_setup"),
    ("pint_tpu.utils", "get_highest_density_range"),
    ("pint_tpu.modelutils", "model_equatorial_to_ecliptic"),
    ("pint_tpu.plot_utils", "phaseogram"),
    ("pint_tpu.logging", "setup"),
    ("pint_tpu.config", "runtimefile"),
    ("pint_tpu.pintk.pulsar", "Pulsar"),
    ("pint_tpu.parallel", "build_fit_step"),
    ("pint_tpu.parallel", "build_sharded_fit_step"),
    ("pint_tpu.parallel", "fit_pta"),
]

SCRIPTS = ["pintempo", "zima", "photonphase", "fermiphase",
           "event_optimize", "pintbary", "tcb2tdb",
           "compare_parfiles", "convert_parfile", "t2binary2pint",
           "pintpublish"]


@pytest.mark.parametrize("mod,sym", MAP,
                         ids=[f"{m}.{s}" for m, s in MAP])
def test_symbol_exists(mod, sym):
    assert getattr(importlib.import_module(mod), sym) is not None


@pytest.mark.parametrize("script", SCRIPTS)
def test_cli_main_exists(script):
    m = importlib.import_module(f"pint_tpu.scripts.{script}")
    assert callable(m.main)
