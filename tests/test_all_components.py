"""Systematic cross-component checks (reference test patterns:
tests/test_model_derivatives.py — analytic derivatives vs finite
differences for EVERY fittable parameter — and
test_all_component_and_parameters.py — every registered component
instantiates and round-trips)."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs

# a kitchen-sink model touching most component families at once
SINK_PAR = """
PSR J9999+4321
RAJ 04:37:15.8 1
DECJ 47:15:09.1 1
PMRA 121.4 1
PMDEC -71.5 1
PX 2.6 1
F0 173.6879458 1
F1 -1.7e-15 1
F2 1.0e-26 1
PEPOCH 55000
POSEPOCH 55000
DM 2.64 1
DM1 1e-4 1
DMEPOCH 55000
DMX_0001 1e-3 1
DMXR1_0001 54490
DMXR2_0001 54760
NE_SW 8.0 1
FD1 1e-5 1
FD2 -5e-6 1
GLEP_1 54900
GLPH_1 0.1 1
GLF0_1 1e-8 1
PWEP_1 54650
PWSTART_1 54550
PWSTOP_1 54750
PWPH_1 0.02 1
PWF0_1 2e-8 1
WXEPOCH 55000
WXFREQ_0001 0.005
WXSIN_0001 1e-6 1
WXCOS_0001 1e-6 1
DMWXEPOCH 55000
DMWXFREQ_0001 0.003
DMWXSIN_0001 1e-4 1
DMWXCOS_0001 2e-4 1
CM 0.02 1
TNCHROMIDX 4
CMEPOCH 55000
CMX_0001 1e-3 1
CMXR1_0001 54800
CMXR2_0001 55100
CMWXEPOCH 55000
CMWXFREQ_0001 0.004
CMWXSIN_0001 1e-4 1
CMWXCOS_0001 5e-5 1
SWXDM_0001 1e-4 1
SWXR1_0001 55000
SWXR2_0001 55300
FDJUMP -grp a 2e-5 1
JUMP -grp a 1e-5 1
PHOFF 0.01 1
BINARY ELL1
PB 5.7410459 1
A1 3.3667144 1
TASC 54800.1 1
EPS1 1.2e-5 1
EPS2 -2.1e-5 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""

# finite-difference step per parameter name (fallback: relative
# 1e-7). Parameters whose residual response is LINEAR take large
# steps: the FD error there is pure round-off noise ~eps/h with no
# curvature penalty, and a small h drowns tiny columns (PX, PM, NE_SW)
# in f64 noise.
FD_STEPS = {
    "F0": 1e-11, "F1": 1e-22, "F2": 1e-31,
    "RAJ": 1e-9, "DECJ": 1e-9, "PMRA": 1e-1, "PMDEC": 1e-1,
    "PX": 1e-1, "DM": 1e-6, "DM1": 1e-4, "DMX_0001": 1e-6,
    "NE_SW": 1e-1, "FD1": 1e-7, "FD2": 1e-7,
    "GLPH_1": 1e-7, "GLF0_1": 1e-12,
    "PWPH_1": 1e-7, "PWF0_1": 1e-12,
    "WXSIN_0001": 1e-6, "WXCOS_0001": 1e-6,
    "DMWXSIN_0001": 1e-5, "DMWXCOS_0001": 1e-5,
    "CM": 1e-5, "CMX_0001": 1e-5,
    "CMWXSIN_0001": 1e-5, "CMWXCOS_0001": 1e-5,
    "SWXDM_0001": 1e-5, "FDJUMP1": 1e-7,
    "JUMP1": 1e-7, "PHOFF": 1e-6,
    "PB": 1e-8, "A1": 1e-7, "TASC": 1e-8,
    "EPS1": 1e-8, "EPS2": 1e-8,
}


@pytest.fixture(scope="module")
def sink():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(SINK_PAR))
        tA = make_fake_toas_uniform(54500, 55500, 25, model,
                                    error_us=1.0, freq_mhz=1400.0)
        tB = make_fake_toas_uniform(54510, 55490, 25, model,
                                    error_us=1.0, freq_mhz=430.0)
        toas = merge_TOAs([tA, tB])
        for f in toas.flags:
            f["grp"] = "a"
        # flags must exist before the model caches selection masks
        model.invalidate_cache()
    return model, toas


def test_every_free_param_derivative_vs_fd(sink):
    """jacfwd design-matrix column == central finite difference of the
    residuals, for EVERY free parameter of the kitchen-sink model (the
    reference's most valuable test pattern, SURVEY §4.2)."""
    model, toas = sink
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        M, names, units = model.designmatrix(toas, incoffset=False)
    M = np.asarray(M)
    assert len(names) == len(model.free_params) == 35
    failures = []
    for pname in names:
        j = names.index(pname)
        p = model.get_param(pname)
        h = FD_STEPS.get(pname,
                         max(abs(p.value or 0.0) * 1e-7, 1e-9))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            p.add_delta(h)
            model.invalidate_cache(params_only=True)
            rp = np.asarray(Residuals(toas, model,
                                      subtract_mean=False).time_resids)
            p.add_delta(-2 * h)
            model.invalidate_cache(params_only=True)
            rm = np.asarray(Residuals(toas, model,
                                      subtract_mean=False).time_resids)
            p.add_delta(h)
            model.invalidate_cache(params_only=True)
        fd = (rp - rm) / (2 * h)
        scale = np.max(np.abs(fd)) + 1e-30
        if not np.allclose(M[:, j], fd, rtol=5e-5, atol=5e-6 * scale):
            err = np.max(np.abs(M[:, j] - fd)) / scale
            failures.append(f"{pname}: rel {err:.2e}")
    assert not failures, failures


def test_all_registered_components_instantiate():
    """Every registered (concrete) component constructs, exposes its
    category, and its parameters format par lines without error
    (reference: test_all_component_and_parameters.py)."""
    import pint_tpu.models  # populate the registry  # noqa: F401
    from pint_tpu.models.timing_model import (Component,
                                              component_types)

    abstract = {"DelayComponent", "PhaseComponent", "Component",
                "NoiseComponent"}
    seen = 0
    for name, cls in sorted(component_types.items()):
        if name in abstract:
            continue
        comp = cls()
        assert isinstance(comp, Component), name
        assert isinstance(getattr(comp, "category", ""), str), name
        for pname, p in comp.params.items():
            line = p.as_parfile_line()
            assert isinstance(line, str), (name, pname)
        seen += 1
    assert seen >= 35  # the zoo really is registered


def test_sink_model_parfile_roundtrip(sink):
    """as_parfile of the kitchen-sink model rebuilds to the same
    free-parameter values."""
    model, _ = sink
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m2 = get_model(io.StringIO(model.as_parfile()))
    assert set(m2.free_params) == set(model.free_params)
    for nm in model.free_params:
        v1 = model.get_param(nm).value
        v2 = m2.get_param(nm).value
        assert v2 == pytest.approx(v1, rel=1e-12), nm


def test_sink_model_deepcopy_independent(sink):
    """deepcopy safety (reference: test_copy.py): mutating the copy
    never leaks into the original."""
    model, toas = sink
    m2 = copy.deepcopy(model)
    m2.get_param("F0").add_delta(1e-6)
    m2.invalidate_cache(params_only=True)
    assert model.F0.value != m2.F0.value
    r1 = Residuals(toas, model).rms_weighted()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r2 = Residuals(toas, m2).rms_weighted()
    assert r2 > r1 * 10  # the copy's perturbation is visible only there

def test_production_fit_step_across_component_zoo():
    """The TPU production configuration (anchored + f32 Jacobian +
    f32-MXU) must survive the kitchen-sink model — every component
    family at once — and agree with the plain f64 direct step. This is
    the guard that a component added/changed without dtype discipline
    (a bare f64 constant, an unreduced large angle, an unscaled
    column) cannot silently break the path the real chip runs."""
    import jax

    from pint_tpu.parallel import build_fit_step

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(SINK_PAR))
        rng = np.random.default_rng(21)
        # six frequency bands: the constant-in-time frequency-shape
        # columns {offset, FD1 logv, FD2 log^2 v, DM v^-2, CM v^-4}
        # span a 5-dim function space — with only 4 distinct
        # frequencies they are exactly collinear and the normal
        # matrix is singular; 6 bands leave rank margin
        toas = make_fake_toas_uniform(
            54100, 55900, 300, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0, 2100.0, 430.0,
                              327.0, 3000.0], 50),
            rng=rng)
        for i, f in enumerate(toas.flags):
            f["grp"] = "a" if i % 3 else "b"
    sD, aD, names = build_fit_step(model, toas, anchored=False,
                                   jac_f32=False, matmul_f32=False)
    sP, aP, _ = build_fit_step(model, toas, anchored=True,
                               jac_f32=True, matmul_f32=True)
    oD = jax.jit(sD)(*aD)
    oP = jax.jit(sP)(*aP)
    sig = np.sqrt(np.diag(np.asarray(oD[1])))
    assert np.all(np.isfinite(np.asarray(oP[0])))
    assert np.all(np.isfinite(sig))
    # residuals identical to sub-ns; steps within the f32 discipline
    assert np.max(np.abs(np.asarray(oD[3]) - np.asarray(oP[3]))) < 1e-10
    assert np.max(np.abs(np.asarray(oD[0]) - np.asarray(oP[0]))
                  / sig) < 3e-2, names
    assert abs(float(oD[2]) - float(oP[2])) < 1e-5 * abs(float(oD[2]))


def test_streaming_gls_across_component_zoo():
    """ISSUE 12: the chunked streaming accumulator + CG solve must
    agree with the dense one-shot Cholesky step across the kitchen-
    sink model — every component family at once, PHOFF (no implicit
    offset/mean) included. A component whose design columns stream
    differently than they solve densely (a chunk-shape dependence, a
    baked global reduction) fails here."""
    import jax

    from pint_tpu.parallel import build_fit_step
    from pint_tpu.parallel.streaming import StreamingGLS

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(SINK_PAR))
        rng = np.random.default_rng(22)
        toas = make_fake_toas_uniform(
            54100, 55900, 300, model, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0, 2100.0, 430.0,
                              327.0, 3000.0], 50),
            rng=rng)
        for i, f in enumerate(toas.flags):
            f["grp"] = "a" if i % 3 else "b"
    sD, aD, names = build_fit_step(model, toas, anchored=False,
                                   jac_f32=False, matmul_f32=False)
    oD = jax.jit(sD)(*aD)
    dpD = np.asarray(oD[0])
    sig = np.sqrt(np.abs(np.diag(np.asarray(oD[1]))))
    sg = StreamingGLS(model, toas, chunk=64, anchored=False,
                      jac_f32=False, matmul_f32=False)
    state = sg.accumulate(sg.th0, sg.tl0)
    dp, cov, chi2, chi2r, xf, ok, iters, resid = sg.solve(state)
    assert ok
    assert np.max(np.abs(dp - dpD) / sig) < 1e-6, names
    assert abs(chi2r - float(oD[2])) < 1e-8 * abs(float(oD[2]))


def test_phoff_is_actually_fittable():
    """PHOFF replaces the implicit offset column AND the implicit mean
    subtraction (reference: PhaseOffset semantics). Regression for the
    production-sweep finding: PHOFF applied to the TZR row too (or
    mean-subtracted away) is silently inert — simulate with a nonzero
    PHOFF and recover it."""
    from pint_tpu.fitter import DownhillWLSFitter

    par = """PSR J1
RAJ 10:12:33.43 1
DECJ 53:07:02.5 1
F0 310.0 1
F1 -5e-16 1
PEPOCH 55000
DM 9.0
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
PHOFF 0.0 1
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m_t = get_model(io.StringIO(par.replace("PHOFF 0.0 1",
                                                "PHOFF 0.013 1")))
        toas = make_fake_toas_uniform(
            54000, 56000, 300, m_t, error_us=1.0,
            rng=np.random.default_rng(5), add_noise=True)
        m = get_model(io.StringIO(par))
    # the design matrix must NOT carry the implicit offset column
    _, names, _ = m.designmatrix(toas)
    assert "Offset" not in names and "PHOFF" in names
    fit = DownhillWLSFitter(toas, m)
    fit.fit_toas()
    p = m.get_param("PHOFF")
    assert abs(p.value - 0.013) < 5 * max(p.uncertainty, 1e-6)
