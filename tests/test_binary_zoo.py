"""Binary zoo tail: DDH, DDGR, DDK, ELL1k + convert_binary
(reference: src/pint/models/binary_dd.py, binary_ddk.py,
binary_ell1.py, binaryconvert.py; test strategy per SURVEY.md §4.2:
analytic/limit cross-checks + jacfwd-vs-finite-difference)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.binaryconvert import convert_binary
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

TSUN = 4.925490947e-6


def _model(binary: str, extra: str = "", f0="310.0") -> str:
    return f"""
PSR J1012+5307
RAJ 10:12:33.43
DECJ 53:07:02.5
PMRA 2.6
PMDEC -25.5
PX 1.2
F0 {f0} 1
F1 -5e-16
PEPOCH 55000
POSEPOCH 55000
DM 9.0
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY {binary}
{extra}
"""


def _mk(binary, extra):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(_model(binary, extra)))


def _resids(model, toas):
    return np.asarray(Residuals(toas, model,
                                subtract_mean=True).time_resids)


def _toas(model, n=80, seed=0):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rng = np.random.default_rng(seed)
        return make_fake_toas_uniform(54100, 55900, n, model,
                                      error_us=1.0, rng=rng)


DD_KEPLER = """PB 0.6
A1 1.45 1
T0 55000.2
ECC 0.02 1
OM 47.0 1
GAMMA 1e-4
M2 0.3
"""


def test_ddh_matches_dd():
    """DDH with (H3, STIG) mapped from (M2, SINI) gives the same delay
    as DD (Freire & Wex 2010 exact orthometric mapping)."""
    sini = 0.95
    m2 = 0.3
    cosi = np.sqrt(1 - sini ** 2)
    stig = sini / (1 + cosi)
    h3 = TSUN * m2 * stig ** 3
    mdd = _mk("DD", DD_KEPLER + f"SINI {sini}\n")
    mddh = _mk("DDH", DD_KEPLER.replace("M2 0.3\n", "")
               + f"H3 {h3:.12e}\nSTIG {stig:.12f}\n")
    toas = _toas(mdd)
    r1, r2 = _resids(mdd, toas), _resids(mddh, toas)
    np.testing.assert_allclose(r1, r2, atol=2e-12)


def test_ddgr_matches_dd_with_computed_pk():
    """DDGR's internally computed post-Keplerian parameters match a DD
    model given the same values explicitly."""
    mtot, m2, pb_d, ecc, a1 = 2.8, 1.3, 0.4, 0.17, 2.34
    n = 2 * np.pi / (pb_d * 86400.0)
    m = TSUN * mtot
    m2s = TSUN * m2
    m1 = m - m2s
    arr = (m / n ** 2) ** (1 / 3)
    omdot = 3 * n ** (5 / 3) * m ** (2 / 3) / (1 - ecc ** 2)  # rad/s
    gamma = ecc * m2s * (m1 + 2 * m2s) * n ** (-1 / 3) * m ** (-4 / 3)
    sini = a1 * m ** (2 / 3) * n ** (2 / 3) / m2s
    fe = (1 + 73 / 24 * ecc ** 2 + 37 / 96 * ecc ** 4) \
        * (1 - ecc ** 2) ** -3.5
    pbdot = -(192 * np.pi / 5) * n ** (5 / 3) * m1 * m2s \
        * m ** (-1 / 3) * fe
    dr = (3 * m1 ** 2 + 6 * m1 * m2s + 2 * m2s ** 2) / (arr * m)
    dth = (3.5 * m1 ** 2 + 6 * m1 * m2s + 2 * m2s ** 2) / (arr * m)
    omdot_degyr = np.degrees(omdot) * 86400.0 * 365.25

    kepler = (f"PB {pb_d}\nA1 {a1}\nT0 55000.1\nECC {ecc}\nOM 30.0\n")
    mgr = _mk("DDGR", kepler + f"MTOT {mtot}\nM2 {m2}\n")
    mdd = _mk("DD", kepler
              + f"M2 {m2}\nSINI {sini:.15f}\nGAMMA {gamma:.15e}\n"
              + f"OMDOT {omdot_degyr:.12f}\nPBDOT {pbdot:.9e}\n"
              + f"DR {dr:.15e}\nDTH {dth:.15e}\n")
    toas = _toas(mgr)
    np.testing.assert_allclose(_resids(mgr, toas), _resids(mdd, toas),
                               atol=5e-11)


def test_ddgr_simulate_fit_recovers_mtot():
    kepler = "PB 0.4\nA1 2.34 1\nT0 55000.1 1\nECC 0.17 1\nOM 30.0 1\n"
    truth = _mk("DDGR", kepler + "MTOT 2.8 1\nM2 1.3\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rng = np.random.default_rng(4)
        toas = make_fake_toas_uniform(54100, 55900, 150, truth,
                                      error_us=1.0, add_noise=True,
                                      rng=rng)
    import copy

    from pint_tpu.fitter import DownhillWLSFitter

    m = copy.deepcopy(truth)
    m.get_param("MTOT").add_delta(1e-4)
    m.invalidate_cache(params_only=True)
    f = DownhillWLSFitter(toas, m)
    f.fit_toas()
    assert abs(m.MTOT.value - 2.8) < 5 * f.errors["MTOT"]
    assert f.errors["MTOT"] < 1e-4


def test_ell1k_reduces_to_ell1():
    base = ("PB 0.2\nA1 0.9 1\nTASC 55000.05\nEPS1 1.1e-5\n"
            "EPS2 -0.4e-5\nM2 0.2\nSINI 0.9\n")
    m1 = _mk("ELL1", base)
    m2 = _mk("ELL1k", base + "OMDOT 0.0\nLNEDOT 0.0\n")
    toas = _toas(m1)
    np.testing.assert_allclose(_resids(m1, toas), _resids(m2, toas),
                               atol=1e-13)


def test_ell1k_omdot_matches_eps_dots_short_term():
    """For small OMDOT over a short span, the exact ELL1k rotation
    linearizes to ELL1's EPS1DOT/EPS2DOT drifts."""
    eps1, eps2 = 1.1e-5, -0.4e-5
    omdot_degyr = 1.5
    omdot = np.radians(omdot_degyr) / (365.25 * 86400.0)  # rad/s
    # d(eps1)/dt = eps2*omdot, d(eps2)/dt = -eps1*omdot
    base = "PB 0.2\nA1 0.9\nTASC 55000.05\n"
    mk_ = _mk("ELL1k", base + f"EPS1 {eps1}\nEPS2 {eps2}\n"
              f"OMDOT {omdot_degyr}\nLNEDOT 0.0\n")
    m_l = _mk("ELL1", base + f"EPS1 {eps1}\nEPS2 {eps2}\n"
              f"EPS1DOT {eps2 * omdot:.6e}\nEPS2DOT {-eps1 * omdot:.6e}\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        toas = make_fake_toas_uniform(54950, 55050, 60, mk_,
                                      error_us=1.0,
                                      rng=np.random.default_rng(1))
    # agreement to the 2nd-order rotation term x*e*(omdot*dt)^2/2
    np.testing.assert_allclose(_resids(mk_, toas), _resids(m_l, toas),
                               atol=1e-9)


DDK_KEPLER = """PB 0.6
A1 1.45 1
T0 55000.2
ECC 0.02
OM 47.0
M2 0.3
"""


def _zero_astrometry(par: str, px: str = "1e-9") -> str:
    return par.replace("PMRA 2.6", "PMRA 0.0").replace(
        "PMDEC -25.5", "PMDEC 0.0").replace("PX 1.2", f"PX {px}")


def test_ddk_limits_to_dd():
    """PX -> 0 (infinite distance) and PM = 0 kill the Kopeikin terms:
    DDK == DD with SINI = sin(KIN). (Astrometry zeroed identically on
    both sides so only the binary differs.)"""
    kin = 71.0
    sini = np.sin(np.radians(kin))
    par_ddk = _zero_astrometry(_model(
        "DDK", DDK_KEPLER + f"KIN {kin}\nKOM 90.0\nK96 0\n"))
    par_dd = _zero_astrometry(_model(
        "DD", DDK_KEPLER + f"SINI {sini:.15f}\n"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mddk = get_model(io.StringIO(par_ddk))
        mdd = get_model(io.StringIO(par_dd))
    toas = _toas(mdd)
    np.testing.assert_allclose(_resids(mddk, toas), _resids(mdd, toas),
                               atol=1e-11)


def test_ddk_annual_orbital_parallax_signature():
    """With PX on, the DDK-DD residual difference is nonzero and scales
    linearly with PX (the K95 annual-orbital parallax terms)."""
    kin = 71.0
    sini = np.sin(np.radians(kin))
    toas = None
    diffs = []
    for px in (1.0, 2.0):
        par_ddk = _zero_astrometry(_model(
            "DDK", DDK_KEPLER + f"KIN {kin}\nKOM 35.0\nK96 0\n"),
            px=str(px))
        par_dd = _zero_astrometry(_model(
            "DD", DDK_KEPLER + f"SINI {sini:.15f}\n"), px=str(px))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mddk = get_model(io.StringIO(par_ddk))
            mdd = get_model(io.StringIO(par_dd))
        if toas is None:
            toas = _toas(mdd)
        d = _resids(mddk, toas) - _resids(mdd, toas)
        d -= d.mean()
        diffs.append(np.sqrt(np.mean(d ** 2)))
    assert diffs[0] > 1e-10  # AOP signature present (sub-us but real)
    # corrections scale as 1/d = PX
    assert diffs[1] / diffs[0] == pytest.approx(2.0, rel=0.05)


def test_ddk_proper_motion_term_grows_with_time():
    kin = 71.0
    par = _model("DDK", DDK_KEPLER + f"KIN {kin}\nKOM 35.0\nK96 1\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mk96 = get_model(io.StringIO(par))
        mk95 = get_model(io.StringIO(par.replace("K96 1", "K96 0")))
    toas = _toas(mk95)
    d = np.abs(_resids(mk96, toas) - _resids(mk95, toas))
    # secular: grows away from T0
    assert d[-1] > d[len(d) // 2]
    assert d.max() > 1e-9


def test_ddk_designmatrix_vs_finite_difference():
    par = _model("DDK", DDK_KEPLER.replace("A1 1.45 1", "A1 1.45")
                 + "KIN 71.0 1\nKOM 35.0 1\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
    toas = _toas(m)
    M, names, _ = m.designmatrix(toas, incoffset=False)
    M = np.asarray(M)
    import copy

    # steps sized so the FD rides above the dd phase-collapse quantum
    # (~7e-15 s in residual) but below nonlinearity
    for pname, step in (("KIN", 1e-2), ("KOM", 1e-2)):
        j = names.index(pname)
        mp = copy.deepcopy(m)
        mp.get_param(pname).add_delta(step)
        mp.invalidate_cache(params_only=True)
        mm = copy.deepcopy(m)
        mm.get_param(pname).add_delta(-step)
        mm.invalidate_cache(params_only=True)
        rp = np.asarray(Residuals(toas, mp,
                                  subtract_mean=False).time_resids)
        rm = np.asarray(Residuals(toas, mm,
                                  subtract_mean=False).time_resids)
        fd = (rp - rm) / (2 * step)
        scale = np.max(np.abs(fd)) + 1e-30
        np.testing.assert_allclose(M[:, j] / scale, fd / scale,
                                   atol=5e-3, err_msg=pname)


# ------------------------------------------------------ convert_binary


def test_convert_ell1_dd_roundtrip():
    base = ("PB 0.2\nA1 0.9 1\nTASC 55000.05\nEPS1 1.1e-5 1\n"
            "EPS2 -0.4e-5 1\nM2 0.2\nSINI 0.9\n")
    m = _mk("ELL1", base)
    m.get_param("EPS1").uncertainty = 1e-8
    m.get_param("EPS2").uncertainty = 1e-8
    mdd = convert_binary(m, "DD")
    assert "BinaryDD" in mdd.components
    ecc = np.hypot(1.1e-5, -0.4e-5)
    assert mdd.get_param("ECC").value == pytest.approx(ecc, rel=1e-12)
    assert mdd.get_param("ECC").uncertainty is not None
    back = convert_binary(mdd, "ELL1")
    assert back.get_param("EPS1").value == pytest.approx(1.1e-5,
                                                         rel=1e-10)
    assert back.get_param("EPS2").value == pytest.approx(-0.4e-5,
                                                         rel=1e-10)
    assert back.get_param("TASC").value == pytest.approx(55000.05,
                                                         abs=1e-9)


def test_convert_ell1_dd_residuals_agree():
    """ELL1 and its DD conversion agree at small e (SURVEY.md A.8e:
    ~ns at e <= 1e-4; ELL1 is an O(e^2) expansion so the bound scales
    as x e^2)."""
    base = ("PB 0.2\nA1 0.9\nTASC 55000.05\nEPS1 0.7e-5\n"
            "EPS2 -0.7e-5\nM2 0.2\nSINI 0.9\n")
    m = _mk("ELL1", base)
    mdd = convert_binary(m, "DD")
    toas = _toas(m)
    r1, r2 = _resids(m, toas), _resids(mdd, toas)
    assert np.max(np.abs(r1 - r2)) < 2e-9


def test_convert_ell1h_m2sini():
    base = ("PB 0.2\nA1 0.9\nTASC 55000.05\nEPS1 1.1e-5\n"
            "EPS2 -0.4e-5\n")
    m = _mk("ELL1", base + "M2 0.2 1\nSINI 0.9\n")
    mh = convert_binary(m, "ELL1H")
    sini = 0.9
    stig = sini / (1 + np.sqrt(1 - sini ** 2))
    assert mh.get_param("STIG").value == pytest.approx(stig, rel=1e-12)
    assert mh.get_param("H3").value == pytest.approx(
        TSUN * 0.2 * stig ** 3, rel=1e-12)
    # delays identical (exact mapping)
    toas = _toas(m)
    np.testing.assert_allclose(_resids(m, toas), _resids(mh, toas),
                               atol=1e-12)
    back = convert_binary(mh, "ELL1")
    assert back.get_param("M2").value == pytest.approx(0.2, rel=1e-12)
    assert back.get_param("SINI").value == pytest.approx(0.9, rel=1e-12)


def test_convert_dd_dds():
    m = _mk("DD", DD_KEPLER + "SINI 0.95\n")
    mdds = convert_binary(m, "DDS")
    assert mdds.get_param("SHAPMAX").value == pytest.approx(
        -np.log(1 - 0.95), rel=1e-12)
    toas = _toas(m)
    np.testing.assert_allclose(_resids(m, toas), _resids(mdds, toas),
                               atol=1e-13)
    back = convert_binary(mdds, "DD")
    assert back.get_param("SINI").value == pytest.approx(0.95,
                                                        rel=1e-12)


def test_convert_unknown_raises():
    m = _mk("ELL1", "PB 0.2\nA1 0.9\nTASC 55000.05\nEPS1 1e-5\n"
            "EPS2 1e-5\n")
    with pytest.raises(ValueError):
        convert_binary(m, "NOPE")


def test_binary_parfile_roundtrip_new_models():
    for binary, extra in (
            ("DDH", DD_KEPLER.replace("M2 0.3\n", "")
             + "H3 1e-7\nSTIG 0.7\n"),
            ("DDGR", "PB 0.4\nA1 2.34\nT0 55000.1\nECC 0.17\nOM 30.0\n"
             "MTOT 2.8\nM2 1.3\n"),
            ("DDK", DDK_KEPLER + "KIN 71.0\nKOM 35.0\n"),
            ("ELL1k", "PB 0.2\nA1 0.9\nTASC 55000.05\nEPS1 1e-5\n"
             "EPS2 1e-5\nOMDOT 1.5\nLNEDOT 0.0\n")):
        m = _mk(binary, extra)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m2 = get_model(io.StringIO(m.as_parfile()))
        toas = _toas(m, n=20)
        np.testing.assert_allclose(_resids(m, toas), _resids(m2, toas),
                                   atol=1e-12, err_msg=binary)
