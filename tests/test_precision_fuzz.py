"""Property-based precision fuzzing (SURVEY §4.3; reference:
tests/test_precision.py pattern, hypothesis replaced by an in-repo
seeded harness — no external dependency).

Oracle: ``fractions.Fraction`` — exact rational arithmetic represents
both decimal MJD strings and f64 values exactly, so every bound below
is against ground truth, not another float library.

Covers: 1e5 random MJD strings (1960-2040, 0-19 fraction digits)
round-tripped through parse -> format at <0.1 ns; bitwise agreement of
the native C++ parser (native/mjdparse.cpp) with its pure-Python twin
on the same volume; dd add/mul/horner vs the exact oracle across
log-uniform magnitudes; leap-second-day boundary sweeps.
"""

from fractions import Fraction

import numpy as np
import pytest

from pint_tpu.ops import dd_np
from pint_tpu.time.mjd import (
    mjd_to_str,
    parse_mjd_string,
    parse_mjd_strings,
)
from pint_tpu.time.scales import tt_mjd_to_utc_mjd, utc_mjd_to_tt_mjd

RNG = np.random.default_rng(20260730)
N_STRINGS = 100_000

# one shared corpus: day in 1960-2040, fraction with 0..19 digits
_DAYS = RNG.integers(36934, 66154, N_STRINGS)
_NDIG = RNG.integers(0, 20, N_STRINGS)
_FRACDIGITS = [
    "".join(RNG.choice(list("0123456789"), nd)) if nd else ""
    for nd in _NDIG
]
CORPUS = [
    f"{d}.{f}" if f else str(d)
    for d, f in zip(_DAYS, _FRACDIGITS)
]


def _exact(s: str) -> Fraction:
    if "." in s:
        ip, fp = s.split(".", 1)
        return Fraction(int(ip)) + Fraction(int(fp) if fp else 0,
                                            10 ** len(fp))
    return Fraction(int(s))


def _dd_value(day, hi, lo) -> Fraction:
    return Fraction(float(day)) + Fraction(float(hi)) + \
        Fraction(float(lo))


class TestMjdStringFuzz:
    def test_parse_exactness_sampled(self):
        """2000-sample exact-oracle check: parsed (day, dd frac) within
        1e-16 day (~10 ps) of the decimal string's exact value."""
        idx = RNG.choice(N_STRINGS, 2000, replace=False)
        bound = Fraction(1, 10 ** 16)
        for i in idx:
            s = CORPUS[i]
            day, frac = parse_mjd_string(s)
            err = abs(_dd_value(day, frac[0], frac[1]) - _exact(s))
            assert err < bound, (s, float(err))

    def test_roundtrip_full_volume(self):
        """All 1e5: parse -> format(19 digits) -> reparse reproduces
        the identical dd pair (a fixed point after one trip)."""
        days, (fhi, flo) = parse_mjd_strings(CORPUS, use_native=False)
        idx = RNG.choice(N_STRINGS, 1500, replace=False)
        for i in idx:
            s2 = mjd_to_str(days[i], (fhi[i], flo[i]), ndigits=19)
            d2, f2 = parse_mjd_string(s2)
            v1 = _dd_value(days[i], fhi[i], flo[i])
            v2 = _dd_value(d2, f2[0], f2[1])
            # 19 emitted digits -> agreement to 1e-19 day (80 fs)
            assert abs(v1 - v2) < Fraction(2, 10 ** 19), CORPUS[i]

    def test_native_bitwise_full_volume(self):
        """The C++ parser must agree BITWISE with the Python twin on
        the whole 1e5 corpus (the native kernel's contract)."""
        from pint_tpu.native import mjdparse_native, native_available

        if not native_available():
            pytest.skip("native kernel unavailable (no g++?)")
        d_py, (hi_py, lo_py) = parse_mjd_strings(CORPUS,
                                                 use_native=False)
        out = mjdparse_native(CORPUS)
        assert out is not None
        d_c, (hi_c, lo_c) = out
        for a, b in ((d_py, d_c), (hi_py, hi_c), (lo_py, lo_c)):
            assert np.array_equal(a.view(np.uint64), b.view(np.uint64))

    def test_malformed_rejected(self):
        for bad in ("", ".", "5a.3", "1_5.0", "+55000.1", "55 000.1",
                    "1" * 19 + ".5"):
            with pytest.raises(ValueError):
                parse_mjd_string(bad)

    def test_long_fractions_truncate_consistently(self):
        """>30 fraction digits: both parsers truncate at 30 — digits
        beyond are below 1e-30 day and must not shift the dd pair."""
        s30 = "55000." + "123456789012345678901234567890"
        s40 = s30 + "9999999999"
        d1, f1 = parse_mjd_string(s30)
        d2, f2 = parse_mjd_string(s40)
        assert d1 == d2 and f1 == f2


class TestDDArithmeticFuzz:
    N = 3000

    def _rand_dd(self, n, lo_mag=-25, hi_mag=25):
        mag = 10.0 ** RNG.uniform(lo_mag, hi_mag, n)
        hi = RNG.uniform(-1, 1, n) * mag
        lo = RNG.uniform(-1, 1, n) * mag * 2.0 ** -53
        # renormalize so (hi, lo) is a valid dd pair
        return dd_np.dd(hi, lo)

    def test_add_vs_exact(self):
        a = self._rand_dd(self.N)
        b = self._rand_dd(self.N)
        s = dd_np.add(a, b)
        # error bound 2^-104 * (|a| + |b|): the accurate-add bound is
        # relative to the operand magnitudes (cancellation can't be
        # beaten by any fixed-width representation)
        for i in RNG.choice(self.N, 400, replace=False):
            ea = Fraction(float(a[0][i])) + Fraction(float(a[1][i]))
            eb = Fraction(float(b[0][i])) + Fraction(float(b[1][i]))
            got = Fraction(float(s[0][i])) + Fraction(float(s[1][i]))
            bound = Fraction(2) ** -102 * (abs(ea) + abs(eb))
            assert abs(got - (ea + eb)) <= bound

    def test_mul_vs_exact(self):
        a = self._rand_dd(self.N, -12, 12)
        b = self._rand_dd(self.N, -12, 12)
        p = dd_np.mul(a, b)
        for i in RNG.choice(self.N, 400, replace=False):
            ea = Fraction(float(a[0][i])) + Fraction(float(a[1][i]))
            eb = Fraction(float(b[0][i])) + Fraction(float(b[1][i]))
            got = Fraction(float(p[0][i])) + Fraction(float(p[1][i]))
            bound = Fraction(2) ** -100 * abs(ea * eb)
            assert abs(got - ea * eb) <= bound

    def test_horner_spindown_vs_exact(self):
        """The actual spindown use: phase = F0*dt + F1*dt^2/2 at
        pulsar magnitudes (dt ~ 1e8 s, F0 ~ 300 Hz -> 3e10 turns),
        good to well under 1e-9 turns."""
        dt_v = RNG.uniform(-1.6e8, 1.6e8, 500)
        f0, f1, f2 = 339.31568728824, -1.614e-13, 1.2e-24
        ph = dd_np.taylor_horner(dd_np.dd(dt_v), [
            dd_np.dd(0.0), dd_np.dd(f0), dd_np.dd(f1), dd_np.dd(f2)])
        for i in RNG.choice(500, 100, replace=False):
            x = Fraction(float(dt_v[i]))
            exact = (Fraction(f0) * x + Fraction(f1) * x * x / 2
                     + Fraction(f2) * x ** 3 / 6)
            got = Fraction(float(ph[0][i])) + Fraction(float(ph[1][i]))
            assert abs(got - exact) < Fraction(1, 10 ** 12)  # turns

    def test_jax_host_twins_agree(self):
        """ops.dd (jax) and ops.dd_np (numpy) must agree bitwise on
        CPU — the host mirror IS the device algorithm."""
        import jax.numpy as jnp

        from pint_tpu.ops.dd import DD, dd_add, dd_mul, dd_sub

        a = self._rand_dd(1000)
        b = self._rand_dd(1000)
        for np_op, jx_op in ((dd_np.add, dd_add),
                             (dd_np.mul, dd_mul),
                             (dd_np.sub, dd_sub)):
            rn = np_op(a, b)
            rj = jx_op(DD(jnp.asarray(a[0]), jnp.asarray(a[1])),
                       DD(jnp.asarray(b[0]), jnp.asarray(b[1])))
            assert np.array_equal(np.asarray(rj.hi), rn[0])
            assert np.array_equal(np.asarray(rj.lo), rn[1])


class TestLeapBoundarySweep:
    # leap-second adoption days (UTC midnight steps)
    STEPS = [41499.0, 50630.0, 51179.0, 57204.0, 57754.0]

    def test_utc_tt_roundtrip_dense_near_steps(self):
        """UTC->TT->UTC is the identity to <1e-12 day (86 ns) on a
        dense sweep bracketing each leap step, including the last
        pulsar-convention second of the long day."""
        for step in self.STEPS:
            eps = np.concatenate([
                -10.0 ** np.arange(-12.0, -1.0),
                10.0 ** np.arange(-12.0, -1.0)])
            mjd = step + eps
            day = np.floor(mjd)
            frac = mjd - day
            tt = utc_mjd_to_tt_mjd(day, dd_np.dd(frac))
            tt_f = dd_np.to_f64(tt)
            td = np.floor(tt_f)
            d2, f2 = tt_mjd_to_utc_mjd(td, tt_f - td)
            back = d2 + f2
            assert np.max(np.abs(back - mjd)) < 1e-12, step

    def test_offset_steps_exactly_one_second(self):
        """TT-UTC increases by exactly 1 s across each adoption
        midnight (the pulsar-MJD convention keeps frac uniform)."""
        for step in self.STEPS:
            before = utc_mjd_to_tt_mjd(step - 1, dd_np.dd(0.999))
            after = utc_mjd_to_tt_mjd(step, dd_np.dd(0.001))
            gap_s = (dd_np.to_f64(after) - dd_np.to_f64(before)) * 86400
            # 0.002 day of elapsed pulsar-UTC plus the extra SI second
            assert abs(gap_s - (0.002 * 86400 + 1.0)) < 1e-6, step
