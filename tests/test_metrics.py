"""Metrics-plane acceptance suite (ISSUE 11).

The contracts CLAUDE.md promises for the registry / exposition / SLO
watchdog / regression-gate stack:

- registry-vs-snapshot PARITY: every counter in the supervisor /
  admission / router / serve artifact blocks is readable through the
  process registry with identical values (derived views, not double
  bookkeeping);
- the Prometheus text exposition parses (minimal parser here) and
  round-trips: parsed sample values equal registry reads, histogram
  buckets are cumulative and consistent with _count;
- a /metrics scrape NEVER takes the engine lock (proven by scraping
  while this test holds it);
- SLO burn-rate math on synthetic series: fast+slow windows must
  BOTH burn to fire, a one-sample spike does not fire, one fire per
  burn episode;
- validated config parsers (f32_mode, no_pallas, SLO knobs) warn
  and ignore bad values per the dispatch_rtt_override_ms convention;
- tools/bench_regress.py verdicts (pass/fail/skip) and the
  artifact-embedded regress block.
"""

import json
import threading
import urllib.request

import pytest

from pint_tpu import obs
from pint_tpu.obs import metrics as om
from pint_tpu.obs import slo
from pint_tpu.runtime import DispatchSupervisor, reset_runtime


@pytest.fixture(autouse=True)
def clean_obs():
    """Registry/watchdog/tracer/breaker state must never leak across
    tests (obs.reset() swaps the registry and stops the watchdog —
    the test_obs.py autouse pattern extended to the metrics plane)."""
    obs.reset()
    reset_runtime()
    yield
    obs.reset()
    reset_runtime()


# ------------------------------------------------------ registry core


def test_registry_types_and_labels():
    reg = om.get_registry()
    c = reg.counter("t_events_total", "help text")
    c.inc(pool="device")
    c.inc(2, pool="host")
    assert c.value(pool="device") == 1
    assert c.value(pool="host") == 2
    assert c.total() == 3
    assert reg.counter("t_events_total") is c  # get-or-create
    with pytest.raises(TypeError):
        reg.gauge("t_events_total")  # type conflict
    g = reg.gauge("t_depth")
    g.set(7)
    g.set_max(3)          # watermark: never goes down
    assert g.value() == 7
    g.set_max(11)
    assert g.value() == 11
    h = reg.histogram("t_lat_seconds")
    h.observe(0.004, kind="gls")
    assert h.row(kind="gls").count == 1
    # bound children are the hot-path handles
    b = reg.counter("t_bumps_total").child(scope="s1")
    b.inc()
    b.inc(3)
    assert b.value() == 4
    # counters are monotonic
    with pytest.raises(TypeError):
        b.set(0)


def test_pull_gauge_stops_exporting_when_producer_dies():
    """A set_fn gauge whose producer yields None (dead weakref,
    absent feature) must DROP its series, not freeze the last
    sampled value forever — and resume if the producer returns."""
    g = om.gauge("t_pull")
    state = {"v": 5.0}
    g.set_fn(lambda: state["v"], scope="e1")
    assert dict(g.series())[(("scope", "e1"),)] == 5.0
    state["v"] = None             # producer died
    assert g.series() == []       # stale sample gone
    assert "t_pull{" not in om.render()
    state["v"] = 7.0              # transient: resumes
    assert dict(g.series())[(("scope", "e1"),)] == 7.0


def test_shed_rate_slo_fires_on_pure_quota_shed_storm():
    """Review fix: quota sheds never reach `submitted`, so the
    shed-rate SLO uses `attempts` as denominator — a 100%-shed
    storm must fire, not evaluate to None."""
    from pint_tpu.serve import ServeEngine
    from pint_tpu.serve.request import TenantOverQuota

    spec = next(s for s in slo.default_specs()
                if s.name == "shed_rate")
    spec.fast_s, spec.slow_s, spec.burn = 10.0, 30.0, 2.0
    clock = {"t": 0.0}
    wd = slo.SLOWatchdog(specs=[spec], interval_s=5.0,
                         clock=lambda: clock["t"])
    fresh = _workload(2, base=6700)
    eng = ServeEngine(tenant_qps=1000.0,
                      tenant_burst=100.0)  # healthy first

    def tick(noisy=False):
        fired = []
        for r in fresh():
            r.tenant = "noisy" if noisy else "calm"
            try:
                eng.submit(r)
            except TenantOverQuota:
                pass
        eng.flush()
        fired = wd.tick(now=clock["t"])
        clock["t"] += 5.0
        return fired

    for _ in range(8):
        assert tick() == []
    # pure-shed storm: drain the noisy tenant's bucket every tick
    from pint_tpu.runtime import Fault, FaultPlan

    plan = FaultPlan([Fault(match="serve.admit/noisy",
                            kind="tenant_burst")])
    fired = []
    with plan.active():
        for _ in range(6):
            fired += tick(noisy=True)
    assert fired == ["shed_rate"]
    assert eng.metrics.attempts > eng.metrics.submitted


def test_registry_reset_isolation():
    om.counter("t_old_total").inc()
    old = om.get_registry()
    om.reset()
    assert om.get_registry() is not old
    assert om.get_registry().value("t_old_total") == 0.0


# ------------------------------------------------------- exposition


def _parse_prom(text):
    """Minimal Prometheus text-format 0.0.4 parser: returns
    ({(name, labels_frozenset): value}, {name: type})."""
    samples = {}
    types = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        meta, sval = line.rsplit(" ", 1)
        if "{" in meta:
            name, lbl = meta.split("{", 1)
            assert lbl.endswith("}"), line
            items = []
            body = lbl[:-1]
            while body:
                k, rest = body.split("=", 1)
                assert rest.startswith('"')
                # labels in this suite contain no escaped quotes
                v, body = rest[1:].split('"', 1)
                body = body.lstrip(",")
                items.append((k, v))
            key = (name, frozenset(items))
        else:
            key = (meta, frozenset())
        assert key not in samples, f"duplicate sample {key}"
        samples[key] = float(sval)
    return samples, types


def test_exposition_parses_and_round_trips():
    reg = om.get_registry()
    reg.counter("rt_events_total", "ev").inc(5, pool="device",
                                             kind="gls")
    reg.gauge("rt_depth").set(3.5, scope="e1")
    h = reg.histogram("rt_lat_seconds")
    for ms in (0.5, 1.0, 3.0, 700.0):
        h.observe(ms / 1e3, kind="gls")
    text = reg.render()
    samples, types = _parse_prom(text)
    assert types["rt_events_total"] == "counter"
    assert types["rt_depth"] == "gauge"
    assert types["rt_lat_seconds"] == "histogram"
    # round-trip: parsed values == registry reads
    assert samples[("rt_events_total",
                    frozenset({("pool", "device"),
                               ("kind", "gls")}))] == 5
    assert samples[("rt_depth",
                    frozenset({("scope", "e1")}))] == 3.5
    # histogram: cumulative buckets, +Inf == _count, _sum consistent
    buckets = sorted(
        (float(dict(k[1])["le"]) if dict(k[1])["le"] != "+Inf"
         else float("inf"), v)
        for k, v in samples.items() if k[0] == "rt_lat_seconds_bucket")
    counts = [v for _, v in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert counts[-1] == 4
    count = samples[("rt_lat_seconds_count",
                     frozenset({("kind", "gls")}))]
    assert count == 4
    s = samples[("rt_lat_seconds_sum", frozenset({("kind", "gls")}))]
    assert s == pytest.approx(0.7045, rel=1e-6)
    # every sample in the exposition has a le-monotone position for
    # its value: the 700 ms sample is only in buckets >= ~1.05 s edge
    below_ms = [le for le, v in buckets if v < 4]
    assert below_ms and max(below_ms) < 1.1


def test_label_escaping():
    reg = om.get_registry()
    reg.counter("esc_total").inc(key='we"ird\nname\\x')
    text = reg.render()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("esc_total{"))
    assert '\\"' in line and "\\n" in line and "\\\\" in line
    # the raw newline must NOT appear inside the sample line
    assert "\n" not in line


# ---------------------------------------------------------- parity


def test_supervisor_registry_snapshot_parity():
    sup = DispatchSupervisor()
    for _ in range(3):
        assert sup.dispatch(lambda: 1, key="par.k") == 1
    snap = sup.snapshot()
    reg = om.get_registry()
    scope = sup.metrics.scope
    for name in ("dispatches", "guarded", "retries", "timeouts",
                 "failovers", "breaker_rejections"):
        assert reg.value(f"pint_tpu_dispatch_{name}_total",
                         scope=scope) == snap[name], name
    assert snap["dispatches"] == 3
    # first-call compile wall gauge exists for the key
    assert reg.value("pint_tpu_compile_wall_seconds",
                     scope=scope, key="par.k") > 0.0
    # the dispatch-wall histogram row is SHARED with the snapshot
    lat = snap["latency"]["cpu/par.k"]["dispatch_wall"]
    m = reg.get("pint_tpu_dispatch_wall_seconds")
    row = m.row(scope=scope, pool="cpu", key="par.k",
                metric="dispatch_wall")
    assert row.count == lat["count"] == 3


def _workload(n, base):
    from pint_tpu.serve.workload import build_workload

    return build_workload(n, sizes=(40, 90), base=base,
                          prebuild=True, entry_name="METR")


def test_serve_engine_registry_snapshot_parity():
    from pint_tpu.serve import ServeEngine

    fresh = _workload(8, base=6100)
    eng = ServeEngine()
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)
    snap = eng.metrics.snapshot()
    reg = om.get_registry()
    # attempts == submitted on a shed-free run (the shed-rate SLO
    # denominator counts submit() entries BEFORE any shed decision)
    assert snap["attempts"] == snap["submitted"] == len(futs)
    for name in ("attempts", "submitted", "completed", "rejected",
                 "failed", "deadline_missed", "fallback_single"):
        assert reg.value(f"pint_tpu_serve_{name}_total",
                         scope=eng.metrics.scope) == snap[name], name
    adm = snap["admission"]
    for name in ("shed_expired", "shed_deadline", "shed_quota",
                 "shed_overload", "shed_shutdown", "shed_bursts",
                 "injected_overload"):
        assert reg.value(f"pint_tpu_admission_{name}_total",
                         scope=eng.admission.scope) == adm[name], name
    rt = snap["router"]
    for pool in ("device", "host"):
        for name in ("dispatches", "requests", "rows", "demotions"):
            assert reg.value(f"pint_tpu_router_{name}_total",
                             scope=eng.router.scope,
                             pool=pool) == rt[pool][name], (pool,
                                                           name)
    # per-bucket counters: sum across classes == engine totals
    reqs = sum(b.requests for b in eng.metrics.buckets.values())
    assert reqs == snap["completed"]
    tot = om.get_registry().get(
        "pint_tpu_serve_bucket_requests_total")
    assert sum(v for k, v in tot.series()
               if ("scope", eng.metrics.scope) in k) == reqs
    # e2e histogram rows shared with the registry
    m = reg.get("pint_tpu_serve_latency_seconds")
    e2e = sum(h.count for h in m.matching(
        {"scope": eng.metrics.scope, "metric": "e2e"}))
    assert e2e == len(futs)


# ------------------------------------------------- exposition server


def test_metrics_server_scrape_and_healthz():
    om.counter("srv_events_total").inc(7)
    srv = om.MetricsServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=10).read().decode()
        samples, types = _parse_prom(text)
        assert samples[("srv_events_total", frozenset())] == 7
        with urllib.request.urlopen(base + "/healthz",
                                    timeout=10) as r:
            h = json.loads(r.read().decode())
            ctype = r.headers.get("Content-Type")
        assert h["ok"] is True
        assert ctype == "application/json"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=10)
    finally:
        srv.close()


def test_scrape_never_blocks_on_the_engine_lock():
    """THE fleet-readiness contract: /metrics and /healthz answer
    while the serve engine lock is HELD (a scrape that needed it
    would deadlock here and time out). ISSUE 19: the ``pools``
    block is the router's ``health_block()`` — per-pool breaker
    state, learned EWMA rates, in-flight depth — for every NAMED
    pool, still engine-lock-free."""
    from pint_tpu.serve import ServeEngine

    fresh = _workload(4, base=6300)
    eng = ServeEngine(pipeline_depth=2,
                      pools=("device", "aux", "host"))
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)

    def _health():
        h = om.default_health()
        h["pools"] = eng.router.health_block()
        return h

    srv = om.MetricsServer(port=0, health_fn=_health).start()
    out = {}
    try:
        assert eng._lock.acquire(timeout=5)
        try:
            def scrape():
                base = f"http://127.0.0.1:{srv.port}"
                out["metrics"] = urllib.request.urlopen(
                    base + "/metrics", timeout=10).read().decode()
                out["health"] = json.loads(urllib.request.urlopen(
                    base + "/healthz", timeout=10).read().decode())

            th = threading.Thread(target=scrape, daemon=True)
            th.start()
            th.join(timeout=10)
            assert not th.is_alive(), \
                "scrape blocked while the engine lock was held"
        finally:
            eng._lock.release()
    finally:
        srv.close()
    samples, _ = _parse_prom(out["metrics"])
    key = ("pint_tpu_serve_completed_total",
           frozenset({("scope", eng.metrics.scope)}))
    assert samples[key] == len(futs)
    pools = out["health"]["pools"]
    assert set(pools) == {"device", "aux", "host"}
    assert pools["host"]["open"] is False
    assert pools["aux"]["open"] is False
    assert "breaker" in pools["aux"]
    # the device pool served the burst: a learned rate + empty queue
    assert pools["device"]["rows_per_s"]
    assert pools["device"]["inflight_rows"] == 0


def test_scrape_chaos_with_lock_sanitizer_armed():
    """ISSUE-18 chaos extension of the scrape contract: the same
    burst-then-scrape-under-held-engine-lock drill with
    $PINT_TPU_LOCK_TRACE armed BEFORE the engine is built, so every
    serve/obs lock is traced and the REAL acquisition graph gets
    painted. Asserts: the burst completes, the scrape still answers
    while the (now traced) engine lock is held, the painted graph
    has ZERO lock-order cycles and ZERO dispatch-under-engine-lock
    incidents, no lock incident dump fired, and obs.reset() returns
    the sanitizer to a clean slate (the isolation contract)."""
    from pint_tpu.runtime import locks

    locks.configure(enabled=True)
    from pint_tpu.serve import ServeEngine

    fresh = _workload(4, base=6350)
    eng = ServeEngine(pipeline_depth=2)  # built ARMED: traced locks
    assert isinstance(eng._lock, locks.TracedRLock)
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)

    srv = om.MetricsServer(port=0,
                           health_fn=om.default_health).start()
    out = {}
    try:
        assert eng._lock.acquire(timeout=5)
        try:
            def scrape():
                base = f"http://127.0.0.1:{srv.port}"
                out["metrics"] = urllib.request.urlopen(
                    base + "/metrics", timeout=10).read().decode()

            th = threading.Thread(target=scrape, daemon=True)
            th.start()
            th.join(timeout=10)
            assert not th.is_alive(), \
                "scrape blocked while the traced engine lock was held"
        finally:
            eng._lock.release()
    finally:
        srv.close()
    st = locks.status()
    assert st["armed"] is True
    assert st["edges"] > 0, "armed burst painted no graph"
    assert st["cycles_fired"] == 0, locks.lock_graph_edges()
    assert st["held_fired"] == 0
    assert om.get_registry().total(
        "pint_tpu_lock_incidents_total") == 0
    # the traced-lock histograms surfaced through the scrape itself
    assert "pint_tpu_lock_hold_seconds" in out["metrics"]
    # clean-slate isolation: reset drops graph, latches and arming
    obs.reset()
    assert locks.status() == {"armed": False, "edges": 0, "nodes": 0,
                              "cycles_fired": 0, "held_fired": 0}


# ---------------------------------------------------- SLO watchdog


def _latency_spec(**kw):
    base = dict(name="p99", type="latency",
                metric="syn_lat_seconds",
                labels={"metric": "e2e"},
                objective_ms=8.192,   # = 2^13 us bucket edge
                target=0.9, fast_s=10.0, slow_s=30.0, burn=2.0,
                min_events=4, min_samples=2)
    base.update(kw)
    return slo.SLOSpec(**base)


def test_slo_burn_rate_math_on_synthetic_series(tmp_path):
    obs.configure(enabled=False, flight_dir=str(tmp_path))
    reg = om.get_registry()
    row = reg.histogram("syn_lat_seconds").row(metric="e2e",
                                               kind="gls")
    clock = {"t": 0.0}
    wd = slo.SLOWatchdog(specs=[_latency_spec()], interval_s=5.0,
                         registry=reg,
                         clock=lambda: clock["t"])

    def tick_with(good=0, bad=0):
        for _ in range(good):
            row.record(0.001)          # 1 ms — inside objective
        for _ in range(bad):
            row.record(0.5)            # 500 ms — way outside
        fired = wd.tick(now=clock["t"])
        clock["t"] += 5.0
        return fired

    # windows not covered yet: even all-bad traffic cannot fire
    assert tick_with(bad=10) == []
    # healthy traffic long enough to cover the slow window
    for _ in range(8):
        assert tick_with(good=10) == []
    # ONE-sample spike: fast window burns, slow does not -> no fire
    assert tick_with(bad=10) == []
    assert tick_with(good=10) == []    # recovered
    # sustained regression: fires EXACTLY ONCE (latched)
    fired = []
    for _ in range(6):
        fired += tick_with(bad=10)
    assert fired == ["p99"]
    assert wd.fires == 1
    # the flight recorder got the slo_burn dump
    dumps = list(tmp_path.glob("flight-*slo_burn*p99*.json"))
    assert len(dumps) == 1
    doc = json.loads(dumps[0].read_text())
    assert doc["reason"] == "slo_burn:p99"
    assert doc["extra"]["slo"]["burning"] is True
    # recovery clears the latch; a NEW burn episode fires again
    for _ in range(8):
        tick_with(good=10)
    for _ in range(6):
        tick_with(bad=10)
    assert wd.fires == 2
    st = wd.status()
    assert st["armed"] and st["fires"] == 2
    assert st["specs"][0]["name"] == "p99"


def test_slo_ratio_and_gauge_specs():
    reg = om.get_registry()
    bad = reg.counter("syn_shed_total")
    tot = reg.counter("syn_submitted_total")
    g = reg.gauge("syn_overhead_frac")
    specs = [
        slo.SLOSpec(name="shed", type="ratio",
                    bad=["syn_shed_total"],
                    total=["syn_submitted_total"], budget=0.05,
                    fast_s=10.0, slow_s=20.0, burn=2.0,
                    min_events=4),
        slo.SLOSpec(name="overhead", type="gauge",
                    metric="syn_overhead_frac", objective=0.1,
                    budget=0.5, fast_s=10.0, slow_s=20.0, burn=1.5),
    ]
    clock = {"t": 0.0}
    wd = slo.SLOWatchdog(specs=specs, interval_s=5.0, registry=reg,
                         clock=lambda: clock["t"])

    def tick(shed=0, total=0, frac=0.0):
        bad.inc(shed)
        tot.inc(total)
        g.set(frac)
        fired = wd.tick(now=clock["t"])
        clock["t"] += 5.0
        return fired

    for _ in range(6):
        assert tick(shed=0, total=10, frac=0.02) == []
    fired = []
    for _ in range(5):
        fired += tick(shed=5, total=10, frac=0.4)
    assert sorted(set(fired)) == ["overhead", "shed"]
    assert fired.count("shed") == 1  # latched


def test_slo_default_specs_and_config_parsing(monkeypatch):
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_SLO", raising=False)
    assert config.slo_enabled() is False
    assert config.slo_specs() == []
    monkeypatch.setenv("PINT_TPU_SLO", "on")
    assert config.slo_enabled() is True
    names = [s.name for s in config.slo_specs()]
    assert "shed_rate" in names and "e2e_p99_gls" in names
    # inline JSON: invalid entries warn-and-drop, valid ones survive
    monkeypatch.setenv("PINT_TPU_SLO", json.dumps([
        {"name": "ok", "type": "ratio", "bad": ["a"],
         "total": ["b"]},
        {"name": "broken", "type": "latency"},     # no metric
        {"type": "gauge", "metric": "m"},          # no name
    ]))
    got = config.slo_specs()
    assert [s.name for s in got] == ["ok"]
    # garbage value: warns, watchdog stays off
    monkeypatch.setenv("PINT_TPU_SLO", "/no/such/file.json")
    assert config.slo_specs() == []
    assert config.slo_enabled() is False
    # interval validation
    monkeypatch.setenv("PINT_TPU_SLO_INTERVAL_S", "2.5")
    assert config.slo_interval_s() == 2.5
    monkeypatch.setenv("PINT_TPU_SLO_INTERVAL_S", "-3")
    assert config.slo_interval_s() == 10.0
    monkeypatch.setenv("PINT_TPU_SLO_INTERVAL_S", "banana")
    assert config.slo_interval_s() == 10.0


def test_slo_maybe_start_idempotent(monkeypatch):
    monkeypatch.setenv("PINT_TPU_SLO", "on")
    monkeypatch.setenv("PINT_TPU_SLO_INTERVAL_S", "60")
    w1 = slo.maybe_start()
    w2 = slo.maybe_start()
    assert w1 is w2 is slo.get_watchdog()
    assert slo.status()["armed"] is True
    slo.reset()
    assert slo.get_watchdog() is None


# ------------------------------------------- validated env parsers


def test_f32_mode_parser_behavior_preserving(monkeypatch):
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_JAC", raising=False)
    assert config.f32_mode("PINT_TPU_JAC") is None        # auto
    assert config.f32_mode("PINT_TPU_JAC", flag=True) is True
    assert config.f32_mode("PINT_TPU_JAC", flag=False) is False
    for v, want in (("f32", True), ("on", True), ("1", True),
                    ("f64", False), ("off", False), ("0", False)):
        monkeypatch.setenv("PINT_TPU_JAC", v)
        assert config.f32_mode("PINT_TPU_JAC") is want, v
    monkeypatch.setenv("PINT_TPU_JAC", "banana")
    assert config.f32_mode("PINT_TPU_JAC") is None  # warned, auto
    # the fit_step resolver sees the same view (CPU backend -> auto
    # resolves False)
    from pint_tpu.parallel.fit_step import _resolve_f32

    assert _resolve_f32(None, "PINT_TPU_JAC") is False
    monkeypatch.setenv("PINT_TPU_JAC", "f32")
    assert _resolve_f32(None, "PINT_TPU_JAC") is True


def test_no_pallas_parser(monkeypatch):
    from pint_tpu import config
    from pint_tpu.ops.pallas_kernels import pallas_available

    monkeypatch.delenv("PINT_TPU_NO_PALLAS", raising=False)
    assert config.no_pallas() is False
    for v in ("1", "on", "true", "yes"):
        monkeypatch.setenv("PINT_TPU_NO_PALLAS", v)
        assert config.no_pallas() is True, v
        assert pallas_available() is False
    for v in ("0", "off", "false", "no"):
        monkeypatch.setenv("PINT_TPU_NO_PALLAS", v)
        assert config.no_pallas() is False, v
    monkeypatch.setenv("PINT_TPU_NO_PALLAS", "banana")
    assert config.no_pallas() is False  # warned, ignored


def test_metrics_port_parser(monkeypatch):
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_METRICS_PORT", raising=False)
    assert config.metrics_port() is None
    monkeypatch.setenv("PINT_TPU_METRICS_PORT", "0")
    assert config.metrics_port() == 0
    monkeypatch.setenv("PINT_TPU_METRICS_PORT", "9095")
    assert config.metrics_port() == 9095
    monkeypatch.setenv("PINT_TPU_METRICS_PORT", "99999")
    assert config.metrics_port() is None
    monkeypatch.setenv("PINT_TPU_METRICS_PORT", "banana")
    assert config.metrics_port() is None


# ------------------------------------------------- the serve daemon


def test_daemon_metrics_port_flag_and_registry_stats(capsys,
                                                     monkeypatch):
    from pint_tpu.scripts.pint_serve import main

    monkeypatch.delenv("PINT_TPU_METRICS_PORT", raising=False)
    assert main(["--metrics-port", "0"],
                stdin=[json.dumps({"kind": "stats",
                                   "id": "s1"})]) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    events = [x for x in lines
              if x.get("event") == "metrics_server"]
    assert len(events) == 1 and events[0]["port"] > 0
    stats = next(x for x in lines if x.get("kind") == "stats")
    assert "registry" in stats
    assert any(k.startswith("pint_tpu_serve_")
               for k in stats["registry"])
    session = next(x for x in lines
                   if x.get("metric") == "serve_session")
    assert session["metrics_port"] == events[0]["port"]


# --------------------------------------------------- bench_regress


def _load_bench_regress():
    import importlib.util
    import os

    p = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "bench_regress.py")
    spec = importlib.util.spec_from_file_location("_t_bregress", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_regress_verdicts(tmp_path):
    br = _load_bench_regress()
    baseline = {"artifacts": {
        "m1": {"only_backend": "cpu", "fields": {
            "value": {"baseline": 100.0, "rel_tol": 0.5,
                      "direction": "higher"},
            "wall_ms": {"max": 50},
            "nested.x": {"min": 1},
        }}}}
    ok = {"metric": "m1", "backend": "cpu", "value": 80.0,
          "wall_ms": 10, "nested": {"x": 2}}
    assert br.evaluate(ok, baseline)["verdict"] == "pass"
    slow = dict(ok, value=40.0)       # < 100*(1-0.5)
    v = br.evaluate(slow, baseline)
    assert v["verdict"] == "fail"
    assert any(c["verdict"] == "fail" and c["field"] == "value"
               for c in v["checks"])
    hot = dict(ok, wall_ms=80)
    assert br.evaluate(hot, baseline)["verdict"] == "fail"
    # missing field skips its check, never fails the record
    missing = {"metric": "m1", "backend": "cpu", "value": 90.0}
    assert br.evaluate(missing, baseline)["verdict"] == "pass"
    # wrong backend / unknown metric skip
    tpu = dict(ok, backend="tpu")
    assert br.evaluate(tpu, baseline)["verdict"] == "skip"
    assert br.evaluate({"metric": "zzz"}, baseline)["verdict"] \
        == "skip"
    # last_json_line: the committed wire contract
    text = "log line\n{broken\n" + json.dumps(ok) + "\n"
    assert br.last_json_line(text)["metric"] == "m1"
    assert br.last_json_line("no json at all") is None
    # CLI over an artifact file against the COMMITTED baseline:
    # a north-star-shaped record inside its bands passes
    art = tmp_path / "a.json"
    art.write_text(json.dumps({
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "backend": "cpu", "value": 300000.0, "step_ms": 30.0,
        "vs_baseline": 120.0}) + "\n")
    assert br.main([str(art)]) == 0
    art.write_text(json.dumps({
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "backend": "cpu", "value": 5000.0, "step_ms": 30.0,
        "vs_baseline": 120.0}) + "\n")
    assert br.main([str(art)]) == 1


def test_bench_artifact_embeds_regress_block():
    import bench

    rec = bench.attach_regress({
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "backend": "cpu", "value": 300000.0, "step_ms": 30.0,
        "vs_baseline": 120.0})
    assert rec["regress"]["verdict"] == "pass"
    # unknown metric: labeled skip, never a failure
    rec2 = bench.attach_regress({"metric": "unknown_thing"})
    assert rec2["regress"]["verdict"] == "skip"
    # setdefault: a subprocess-carried verdict is not overwritten
    rec3 = bench.attach_regress({
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "regress": {"verdict": "fail"}})
    assert rec3["regress"] == {"verdict": "fail"}


# ------------------------------------------------------ new gauges


def test_aot_hit_miss_and_compile_gauges(tmp_path, monkeypatch):
    """AOT restore hits/misses ride the registry and the snapshot;
    jit-cache-size pull gauge produces samples at scrape time."""
    from pint_tpu.serve import ServeEngine

    fresh = _workload(3, base=6500)
    aot = str(tmp_path / "aot")
    eng = ServeEngine(aot_dir=aot)
    futs = [eng.submit(r) for r in fresh()]
    eng.flush()
    for f in futs:
        f.result(timeout=0)
    snap = eng.metrics.snapshot()["restart"]["aot"]
    assert snap["exported"] >= 1
    assert snap["misses"] >= 1        # cold engine: no restored hits
    assert snap["hits"] == 0
    # warm restart: the restored classes now HIT
    eng2 = ServeEngine(aot_dir=aot)
    futs2 = [eng2.submit(r) for r in fresh()]
    eng2.flush()
    for f in futs2:
        f.result(timeout=0)
    snap2 = eng2.metrics.snapshot()["restart"]["aot"]
    assert snap2["restored"] >= 1
    assert snap2["hits"] >= 1
    reg = om.get_registry()
    assert reg.total("pint_tpu_aot_hits_total") >= 1
    # pull gauges render at scrape time
    text = reg.render()
    assert "pint_tpu_jit_cache_size" in text
    assert "pint_tpu_serve_compile_count" in text
