"""External-truth validation of the time/astro kernels (SURVEY §4
implication (e)). Zero-egress caveat, stated honestly: no almanac files
or ERFA/astropy exist anywhere on this image (re-verified), so the
anchors here are (a) published CONSTANTS embedded independently of the
implementation (leap-second table entries, GMST at J2000, obliquity
values, TT-TAI), and (b) an INDEPENDENT-METHOD cross-check of TDB-TT:
numerically integrating the defining relativistic rate
(v^2/2 + U_ext)/c^2 with the in-repo ephemeris and comparing against
the Fairhead-Bretagnon series. The integration shares no code or
coefficients with the series, so a sign, phase, or frequency error in
either side would show up at the 1.7 ms level; agreement is limited to
~50 us by planetary terms the two-body-dominated integrand can't see
(indirect Jupiter/Saturn perturbations of Earth's orbit).
"""

import numpy as np
import pytest

from pint_tpu.ephemeris.kepler import ssb_posvel
from pint_tpu.time.frames import (
    clear_eop,
    earth_rotation_angle,
    gmst06,
    itrf_to_gcrs_posvel,
    obliquity06,
)
from pint_tpu.time.leapseconds import tai_minus_utc
from pint_tpu.time.scales import (
    TT_MINUS_TAI,
    tdb_minus_tt_seconds,
)

C_M_S = 299792458.0
GM_SUN = 1.32712440018e20     # m^3/s^2 (IAU 2015 nominal)
GM_JUP = 1.26686534e17
GM_SAT = 3.7931187e16


class TestPublishedConstants:
    def test_tt_minus_tai_exact(self):
        # TT = TAI + 32.184 s by definition (IAU 1991)
        assert TT_MINUS_TAI == 32.184

    @pytest.mark.parametrize("mjd,expected", [
        (41317.0, 10.0),   # 1972-01-01, first integer offset
        (41499.0, 11.0),   # 1972-07-01
        (44239.0, 19.0),   # 1980-01-01
        (50630.0, 31.0),   # 1997-07-01
        (51179.0, 32.0),   # 1999-01-01
        (53736.0, 33.0),   # 2006-01-01
        (54832.0, 34.0),   # 2009-01-01
        (56109.0, 35.0),   # 2012-07-01
        (57204.0, 36.0),   # 2015-07-01
        (57754.0, 37.0),   # 2017-01-01 (current through 2026)
    ])
    def test_leap_second_table_anchors(self, mjd, expected):
        """TAI-UTC at published adoption dates (IERS Bulletin C)."""
        assert tai_minus_utc(mjd) == expected
        # and the day before each step is one less (except the first)
        if expected > 10.0:
            assert tai_minus_utc(mjd - 1) == expected - 1.0

    def test_gmst_at_j2000(self):
        """GMST(J2000 UT1) = 18h 41m 50.54841s = 67310.54841 s of time
        (published epoch value; Meeus/IAU). The IAU2006 expression
        differs from the 1982 one by <2 mas here."""
        h = float(gmst06(51544.5, 51544.5)) * 24.0 / (2 * np.pi)
        assert abs(h * 3600.0 - 67310.54841) < 0.01  # seconds of time

    def test_era_j2000_anchor(self):
        """ERA(J2000 UT1) = 2*pi*0.7790572732640 (IAU 2000 defining
        constant, Capitaine et al. 2000)."""
        era = float(earth_rotation_angle(51544.5))
        assert abs(era - 2 * np.pi * 0.7790572732640) < 1e-12

    def test_era_sidereal_rate(self):
        """d(ERA)/dt = 1.00273781191135448 rev/UT1-day exactly."""
        e0 = float(earth_rotation_angle(55000.0))
        e1 = float(earth_rotation_angle(55001.0))
        rate = ((e1 - e0) / (2 * np.pi)) % 1.0
        assert abs(rate - 0.00273781191135448) < 1e-13

    def test_obliquity_j2000(self):
        """eps_0 = 84381.406 arcsec (IAU 2006/IERS 2010)."""
        eps = float(obliquity06(51544.5)) * 180 * 3600 / np.pi
        assert abs(eps - 84381.406) < 1e-9
        # and the per-convention table used by AstrometryEcliptic
        from pint_tpu.models.astrometry import AstrometryEcliptic

        tbl = AstrometryEcliptic._OBLIQUITY
        assert tbl["IERS2010"] == 84381.406
        assert tbl["IAU1976"] == 84381.448
        assert tbl["IERS2003"] == 84381.4059


class TestTdbTtIndependentIntegration:
    # every major-body direct potential at Earth (IAU/DE-grade GM)
    GM = {
        "sun": GM_SUN, "jupiter": GM_JUP, "saturn": GM_SAT,
        "venus": 3.24858592e14, "mars": 4.282837e13,
        "mercury": 2.2031868e13, "uranus": 5.793939e15,
        "neptune": 6.836529e15,
    }

    def test_series_matches_physical_integral(self):
        """Integrate d(TDB-TT)/dt = (v_E^2/2 + Σ GM_i/r_Ei)/c^2
        (periodic part, all major bodies) with the in-repo analytic
        ephemeris over 12 yr and compare to the FB series. The annual
        term is 1.657 ms; a sign flip, phase error >~0.3 deg, a wrong
        coefficient >~2 us, or frequency misassignment in the series
        would exceed the 5 us gate. The residual floor (~4.5 us,
        synodic-period content at 399/584-day beats) is the Keplerian
        ephemeris's missing indirect planetary perturbations of
        Earth's own orbit — not series truncation: extending the
        series from 36 to 83 terms (round 5) moved this residual by
        <2 ns while changing the series itself by up to 0.59 us."""
        mjd = np.arange(53005.0, 53005.0 + 12 * 365.25, 0.5)
        pe, ve = ssb_posvel("earth", mjd)
        rate = np.sum(ve * ve, -1) / 2
        for body, gm in self.GM.items():
            pb, _ = ssb_posvel(body, mjd)
            rate = rate + gm / np.linalg.norm(pe - pb, axis=-1)
        rate = rate / C_M_S ** 2
        rate = rate - rate.mean()
        dt_s = 0.5 * 86400.0
        integ = np.concatenate(
            [[0.0], np.cumsum((rate[1:] + rate[:-1]) / 2) * dt_s])
        integ -= integ.mean()
        series = tdb_minus_tt_seconds(mjd)
        series = series - series.mean()
        # detrend the residual secular + quadratic drift (mean-rate
        # removal over a non-integer number of periods leaves a small
        # polynomial leak); the comparison is about periodic content
        x = (mjd - mjd.mean()) / np.ptp(mjd)
        diff = integ - series
        diff -= np.polyval(np.polyfit(x, diff, 2), x)
        assert np.max(np.abs(diff)) < 5e-6
        # and the two annual amplitudes agree to ~2% (ephemeris grade)
        ph = 2 * np.pi * (mjd - 51544.5) / 365.25636
        amp = [2 * abs(np.mean(s * np.exp(-1j * ph))) for s in
               (integ, series)]
        assert abs(amp[0] - amp[1]) < 0.02 * amp[1]
        assert abs(amp[1] - 1.657e-3) < 0.05e-3

    def test_series_term_groups_consistent(self):
        """Structural checks of the embedded FB tables: amplitudes
        positive and roughly sorted (a transcription slip that turned
        0.048e-6 into 0.48e-6 would break monotonicity by 10x), t^k
        groups contribute at their expected scale at |t| = 25 yr, and
        the t^1 leading term is the published 102.156724 us."""
        from pint_tpu.time.scales import _FB_T0, _FB_T1, _FB_T2

        a0 = _FB_T0[:, 0]
        assert np.all(a0 > 0)
        # no term more than 3x larger than any earlier term (ordering
        # is approximate across the 30/31 boundary, gross slips fail)
        running_min = np.minimum.accumulate(a0)
        assert np.all(a0 <= 3.0 * running_min)
        assert abs(_FB_T1[0, 0] - 102.156724e-6) < 1e-12
        # t^1 group at t=0.025 millennia contributes <= ~2.6 us,
        # t^2 group <= ~3 ns
        t = 0.025
        assert np.sum(_FB_T1[:, 0]) * t < 3e-6
        assert np.sum(_FB_T2[:, 0]) * t * t < 4e-9

    def test_nutation_published_anchors(self):
        """IAU2000 published constants and behavior of the extended
        nutation series: the principal-term coefficients are the
        defining values, the planetary bias matches 2000B, and the
        evaluated series stays inside the physical envelope (|dpsi|
        <~19", |deps| <~10") over an 18.6-yr node period while
        actually reaching the principal amplitude."""
        from pint_tpu.time.frames import (
            _NUT_PLANETARY_EPS,
            _NUT_PLANETARY_PSI,
            _NUT_TERMS,
            nutation00b_truncated,
        )

        assert _NUT_TERMS[0][5] == -17.2064161   # psi sin(Om) [as]
        assert _NUT_TERMS[0][8] == 9.2052331     # eps cos(Om) [as]
        assert _NUT_TERMS[1][5] == -1.3170906    # 2F-2D+2Om term
        assert _NUT_PLANETARY_PSI == -0.000135
        assert _NUT_PLANETARY_EPS == 0.000388
        mjd = np.arange(51544.5, 51544.5 + 6795.0, 5.0)  # one node rev
        dpsi, deps = nutation00b_truncated(mjd)
        as_ = 180.0 * 3600.0 / np.pi
        assert np.max(np.abs(dpsi)) * as_ < 19.5
        assert np.max(np.abs(deps)) * as_ < 10.5
        assert np.max(np.abs(dpsi)) * as_ > 16.0
        assert np.max(np.abs(deps)) * as_ > 8.5

    def test_annual_phase_sign(self):
        """TDB-TT ~ +1.657 ms * sin(g), g = Earth's mean anomaly: the
        rate is extremal at perihelion, so the VALUE crosses zero at
        peri/aphelion and peaks at g = +90 deg (early April) /
        troughs at g = 270 deg (early October) — the classic sign
        convention (Moyer; Expl. Suppl.) that a flipped series would
        invert."""
        # 2004: g=90 deg near Apr 5 (MJD 53100), g=270 near Oct 3
        apr = float(tdb_minus_tt_seconds(53100.0))
        oct_ = float(tdb_minus_tt_seconds(53281.0))
        assert apr > 1.0e-3    # near +1.66 ms
        assert oct_ < -1.0e-3  # near -1.66 ms
        # zero crossings near perihelion (Jan 4) and aphelion (Jul 5)
        assert abs(float(tdb_minus_tt_seconds(53008.0))) < 2.5e-4
        assert abs(float(tdb_minus_tt_seconds(53191.0))) < 2.5e-4


class TestEopLoading:
    def _finals_line(self, y, m, d, mjd, xp, yp, dut1):
        """Build one IERS finals2000A fixed-width record (synthetic
        values, real layout: MJD cols 8-15, x 19-27, y 38-46,
        UT1-UTC 59-68)."""
        line = [" "] * 80
        line[0:6] = f"{y % 100:02d}{m:2d}{d:2d}"
        line[7:15] = f"{mjd:8.2f}"
        line[16] = "I"
        line[18:27] = f"{xp:9.6f}"
        line[27:36] = f"{0.000009:9.6f}"
        line[37:46] = f"{yp:9.6f}"
        line[46:55] = f"{0.000009:9.6f}"
        line[57] = "I"
        line[58:68] = f"{dut1:10.7f}"
        return "".join(line)

    def test_parse_and_install(self, tmp_path, monkeypatch):
        from pint_tpu.time.eop import install_eop, load_eop_file

        rows = [(20, 1, 1 + i, 58849.0 + i, 0.076 + 0.001 * i,
                 0.282 - 0.001 * i, -0.177 + 0.0002 * i)
                for i in range(7)]
        text = "\n".join(self._finals_line(*r) for r in rows) + "\n"
        p = tmp_path / "finals2000A.all"
        p.write_text(text)
        mjd, xp, yp, dut1 = load_eop_file(str(p))
        assert len(mjd) == 7
        np.testing.assert_allclose(mjd, [58849.0 + i for i in range(7)])
        np.testing.assert_allclose(dut1[0], -0.177, atol=1e-7)
        np.testing.assert_allclose(xp[3], 0.079, atol=1e-6)
        try:
            n, path = install_eop(str(p))
            assert n == 7
            # dUT1 must actually rotate the computed GCRS position
            itrf = np.array([882589.6, -4924872.3, 3943729.4])
            pos1, _ = itrf_to_gcrs_posvel(itrf, 58852.0, 58852.0008)
            clear_eop()
            pos0, _ = itrf_to_gcrs_posvel(itrf, 58852.0, 58852.0008)
            # 0.177 s of rotation ~ 465 m/s * 0.177 ~ 80 m at this lat
            shift = np.linalg.norm(pos1 - pos0)
            assert 20.0 < shift < 200.0
        finally:
            clear_eop()

    def test_mirror_discovery(self, tmp_path, monkeypatch):
        from pint_tpu.time.eop import find_eop_file

        d = tmp_path / "mirror" / "earth"
        d.mkdir(parents=True)
        (d / "finals2000A.all").write_text(
            self._finals_line(20, 1, 1, 58849.0, 0.076, 0.282, -0.177)
            + "\n")
        monkeypatch.setenv("PINT_TPU_CLOCK_DIR",
                           str(tmp_path / "mirror"))
        from pint_tpu.observatory.global_clock_corrections import \
            set_clock_mirror

        set_clock_mirror(None)  # fall through to the env var
        p = find_eop_file()
        assert p is not None and p.endswith("finals2000A.all")

    def test_plain_format(self, tmp_path):
        from pint_tpu.time.eop import load_eop_file

        p = tmp_path / "eop.dat"
        p.write_text("# MJD xp yp dut1\n58849.0 0.076 0.282 -0.177\n"
                     "58850.0 0.077 0.281 -0.1768\n")
        mjd, xp, yp, dut1 = load_eop_file(str(p))
        assert len(mjd) == 2 and dut1[1] == -0.1768
