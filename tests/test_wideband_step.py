"""One-kernel wideband fit step (build_fit_step(wideband=True)): the
stacked [time; DM] GLS iteration as a single XLA program (reference:
WidebandTOAFitter's joint solve, which runs residuals/designmatrix/
solve as separate host phases). Oracle: the host fitter's
_solve_once on the same problem."""

import io
import warnings

import jax
import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step
from pint_tpu.simulation import make_fake_toas_fromMJDs
from pint_tpu.wideband_fitter import WidebandTOAFitter

PAR = """PSR J1713x
RAJ 17:13:49.53 1
DECJ 07:47:37.5 1
F0 218.81 1
F1 -4.08e-16 1
DM 15.99
PEPOCH 54500
TZRMJD 54500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
DMX_0001 0.0 1
DMXR1_0001 53000
DMXR2_0001 54500
DMX_0002 0.0 1
DMXR1_0002 54500
DMXR2_0002 56000
DMEFAC -be X 1.1
DMEQUAD -be X 2e-5
"""


def _problem(n=300, seed=3, extra=""):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR + extra))
        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(53000, 56000, n))
        toas = make_fake_toas_fromMJDs(
            mjds, m, error_us=1.0,
            freq_mhz=np.tile([1400.0, 2100.0], n // 2),
            add_noise=True, rng=rng)
        for f in toas.flags:
            f["be"] = "X"
            f["pp_dm"] = str(15.99 + rng.normal(0, 1e-4))
            f["pp_dme"] = "1e-4"
    return m, toas


class TestWidebandStep:
    def test_matches_host_fitter_f64(self):
        m, toas = _problem()
        fit = WidebandTOAFitter(toas, m)
        x, cov, chi2, noise, names = fit._solve_once()
        sig = np.sqrt(np.diag(cov))
        s, a, names2 = build_fit_step(m, toas, wideband=True,
                                      anchored=False, jac_f32=False)
        out = jax.jit(s)(*a)
        assert names2 == names
        assert np.max(np.abs(x - np.asarray(out[0])) / sig) < 1e-9
        # the step returns the N TIME residuals, not the stacked 2N
        assert np.asarray(out[3]).shape == (toas.ntoas,)

    def test_production_config_agrees(self):
        """anchored + f32 Jacobian + f32 MXU vs the host fitter."""
        m, toas = _problem()
        fit = WidebandTOAFitter(toas, m)
        x, cov, _, _, _ = fit._solve_once()
        sig = np.sqrt(np.diag(cov))
        s, a, _ = build_fit_step(m, toas, wideband=True,
                                 anchored=True, jac_f32=True,
                                 matmul_f32=True)
        out = jax.jit(s)(*a)
        assert np.max(np.abs(x - np.asarray(out[0])) / sig) < 1e-2

    def test_dm_errors_scaled(self):
        """DMEFAC/DMEQUAD must reach the step's DM rows: inflating
        DMEFAC widens DM-sensitive parameter uncertainties."""
        m1, toas1 = _problem()
        m2, toas2 = _problem(
            extra="")  # same par; modify DMEFAC below
        m2.get_param("DMEFAC1").value = 3.0
        m2.invalidate_cache(params_only=True)
        s1, a1, names = build_fit_step(m1, toas1, wideband=True,
                                       anchored=False, jac_f32=False)
        s2, a2, _ = build_fit_step(m2, toas2, wideband=True,
                                   anchored=False, jac_f32=False)
        c1 = np.diag(np.asarray(jax.jit(s1)(*a1)[1]))
        c2 = np.diag(np.asarray(jax.jit(s2)(*a2)[1]))
        j = names.index("DMX_0001")
        assert c2[j] > 2.0 * c1[j]

    def test_sharded_wideband(self):
        from jax.sharding import Mesh

        from pint_tpu.parallel import build_sharded_fit_step

        m, toas = _problem(n=200)
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs the 8-virtual-device conftest mesh")
        mesh = Mesh(np.array(devs[:8]).reshape(8), ("toa",))
        jitted, dev_args, _ = build_sharded_fit_step(
            m, toas, mesh, wideband=True, anchored=True, jac_f32=True)
        sU, aU, _ = build_fit_step(m, toas, wideband=True,
                                   anchored=True, jac_f32=True)
        oS = jitted(*dev_args)
        oU = jax.jit(sU)(*aU)
        sig = np.sqrt(np.diag(np.asarray(oU[1])))
        assert np.max(np.abs(np.asarray(oS[0]) - np.asarray(oU[0]))
                      / sig) < 1e-3


class TestDMNoiseCoupling:
    def test_pldm_couples_into_dm_rows(self):
        """PLDMNoise columns are nonzero in the DM-channel block;
        red-noise columns are zero there; column order matches the
        time-row stacking."""
        m, toas = _problem(extra="TNDMAMP -13.0\nTNDMGAM 3.0\n"
                           "TNDMC 8\nTNREDAMP -14.0\nTNREDGAM 4.0\n"
                           "TNREDC 5\n")
        Ft = m.noise_model_designmatrix(toas)
        Fd = m.noise_model_dm_designmatrix(toas)
        assert Fd.shape == Ft.shape
        pairs = m.noise_model_basis_weight_pairs(toas)
        off = 0
        for name, F, _ in pairs:
            w = F.shape[1]
            blk = Fd[:, off:off + w]
            if name == "PLDMNoise":
                assert np.max(np.abs(blk)) > 0
            else:
                assert np.max(np.abs(blk)) == 0, name
            off += w

    def test_step_matches_fitter_with_pldm(self):
        m, toas = _problem(extra="TNDMAMP -13.0\nTNDMGAM 3.0\n"
                           "TNDMC 8\n")
        fit = WidebandTOAFitter(toas, m)
        x, cov, _, _, _ = fit._solve_once()
        sig = np.sqrt(np.diag(cov))
        s, a, _ = build_fit_step(m, toas, wideband=True,
                                 anchored=False, jac_f32=False)
        out = jax.jit(s)(*a)
        assert np.max(np.abs(x - np.asarray(out[0])) / sig) < 1e-8

    def test_coupling_absorbs_injected_dm_signal(self, monkeypatch):
        """Inject a slow sinusoidal DM(t) into the DM channel; the
        marginalized wideband chi2 with the PLDMNoise coupling must
        beat the same solve with the DM block zeroed (the pre-coupling
        behavior) by a decisive margin — if noise_model_dm_designmatrix
        ever regresses to zeros, this fails."""
        import io as _io

        from pint_tpu.models.timing_model import TimingModel

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m_sim = get_model(_io.StringIO(PAR))
            rng = np.random.default_rng(9)
            mjds = np.sort(rng.uniform(53000, 56000, 240))
            toas = make_fake_toas_fromMJDs(
                mjds, m_sim, error_us=1.0,
                freq_mhz=np.tile([1400.0, 2100.0], 120),
                add_noise=True, rng=rng)
            amp, period = 3e-3, 700.0
            dm_sig = amp * np.sin(2 * np.pi * (mjds - 53000) / period)
            for i, f in enumerate(toas.flags):
                f["be"] = "X"
                f["pp_dm"] = str(15.99 + dm_sig[i]
                                 + rng.normal(0, 1e-4))
                f["pp_dme"] = "1e-4"
            m_fit = get_model(_io.StringIO(
                PAR + "TNDMAMP -12.0\nTNDMGAM 2.0\nTNDMC 12\n"))
        chi2_coupled = WidebandTOAFitter(toas, m_fit)._solve_once()[2]
        orig = TimingModel.noise_model_dm_designmatrix
        monkeypatch.setattr(
            TimingModel, "noise_model_dm_designmatrix",
            lambda self, t, exclude=(): np.zeros_like(
                np.asarray(orig(self, t, exclude=exclude))))
        chi2_zeroed = WidebandTOAFitter(toas, m_fit)._solve_once()[2]
        # the sine is ~27 sigma per DM point: without coupling the GP
        # cannot explain the DM channel and chi2 blows up
        assert chi2_zeroed - chi2_coupled > 1000.0


def test_plsw_couples_and_inf_freq_safe():
    """PLSWNoise also couples into the DM rows, and an
    infinite-frequency TOA row yields zeros (not NaN) in the DM
    block."""
    m, toas = _problem(extra="NE_SW 6.0\nTNSWAMP -6.0\nTNSWGAM 2.0\n"
                       "TNSWC 6\n")
    # make one TOA barycentric/infinite-frequency
    toas.freq_mhz[0] = np.inf
    Fd = m.noise_model_dm_designmatrix(toas)
    assert Fd is not None
    assert np.all(np.isfinite(Fd))
    assert np.max(np.abs(Fd[1:])) > 0       # coupling present
    assert np.max(np.abs(Fd[0])) == 0.0     # inf row zeroed
