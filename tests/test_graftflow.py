"""graftflow rule fixtures (positive AND negative per rule family,
test_graftlint.py style): G9 precision demotions / dd-consumer taint,
G10 trace-constant reads and closure captures — including a fixture
REINTRODUCING the chromatic_index TNCHROMIDX hazard that motivated
the rule — plus the registry/probe hygiene checks and the
--format json / --changed-only CLI satellites. Run standalone with
`pytest -m lint`."""

import json
import os
import textwrap

import pytest

from pint_tpu.analysis import cfg as fcfg
from pint_tpu.analysis import graftflow as gf
from pint_tpu.analysis import graftlint as gl

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flow(src, relpath="pint_tpu/models/_fixture.py", registry=None,
          verify_probes=False):
    """Run the graftflow checks on one snippet module."""
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    seeds = gl.collect_jit_seed_names([m])
    gl.mark_jit_regions(m, seeds[relpath])
    violations, suppressed = gf.run_flow_checks(
        [m], registry=[] if registry is None else registry,
        verify_probe_sites=verify_probes)
    return violations, suppressed


def _rules(violations):
    return sorted({v.rule for v in violations})


# ------------------------------------------------------------------ G9

def test_g9_flags_demotion_outside_registry():
    v, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            return batch.t.astype(jnp.float32)
    """)
    assert "G9" in _rules(v)
    assert "precision_registry" in v[0].msg


def test_g9_flags_string_dtype_spellings():
    """Review regression: astype("float32") / dtype="float32" /
    zeros(n, "float32") are common numpy idiom and must flag like
    the jnp.float32 attribute forms."""
    v, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            a = batch.t.astype("float32")
            b = jnp.asarray(batch.t, dtype="float32")
            c = jnp.zeros(3, "float32")
            return a, b, c
    """)
    assert [x.rule for x in v].count("G9") == 3


def test_g9_flags_f32_ctors_and_dtype_args():
    v, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            a = jnp.float32(0.5)
            b = jnp.asarray(batch.t, dtype=jnp.float32)
            c = jnp.zeros(3, jnp.float32)
            return a, b, c
    """)
    assert [x.rule for x in v].count("G9") == 3


def test_g9_registered_boundary_is_sanctioned_and_stale_fails():
    reg = [dict(file="pint_tpu/models/_fixture.py", func="step",
                flag="jac32", guard="jac32", why="fixture boundary")]
    v, sup = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache, jac32=False):
            if jac32:
                return batch.t.astype(jnp.float32)
            return batch.t
    """, registry=reg)
    assert "G9" not in _rules(v)
    assert any("fixture boundary" in why for _, why in sup)
    # a registry entry matching nothing is itself a violation
    v2, _ = _flow("def host():\n    return 1\n", registry=reg)
    assert [x.rule for x in v2] == ["REGISTRY"]


def test_g9_guard_claim_must_match_the_code():
    """An entry declaring a gate that the code does not actually
    have (no enclosing `if`, no parameter) is a drifted claim."""
    reg = [dict(file="pint_tpu/models/_fixture.py", func="step",
                flag="jac32", guard="jac32", why="fixture")]
    v, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            return batch.t.astype(jnp.float32)
    """, registry=reg)
    assert "G9" in _rules(v)
    assert "drifted" in v[0].msg


def test_g9_dd_consumer_rejects_f32_provenance_in_protected_module():
    v, _ = _flow("""
        import jax.numpy as jnp
        from pint_tpu.ops.dd import DD, dd_add
        def _kernel(pv, r):
            r32 = r.astype(jnp.float32)
            return dd_add(DD(r32, r32), pv["X"])
    """, relpath="pint_tpu/gls.py")
    msgs = [x.msg for x in v if x.rule == "G9"]
    assert any("dd consumer" in m for m in msgs)


def test_g9_dd_consumer_clean_on_f64_and_outside_protected_set():
    clean = _flow("""
        from pint_tpu.ops.dd import DD, dd_add
        def _kernel(pv, r):
            return dd_add(DD(r, r), pv["X"])
    """, relpath="pint_tpu/gls.py")[0]
    assert not [x for x in clean if "dd consumer" in x.msg]
    # same taint in a non-protected module: the demotion still flags
    # (registry) but the consumer rule does not apply there
    outside = _flow("""
        import jax.numpy as jnp
        from pint_tpu.ops.dd import DD, dd_add
        def _kernel(pv, r):
            r32 = r.astype(jnp.float32)
            return dd_add(DD(r32, r32), pv["X"])
    """, relpath="pint_tpu/gridutils.py")[0]
    assert not [x for x in outside if "dd consumer" in x.msg]


def test_g9_taint_survives_branches_and_upcasts():
    """The dataflow half: provenance joins across an if/else (may-
    analysis) and an astype(float64) upcast does not launder it."""
    v, _ = _flow("""
        import jax.numpy as jnp
        from pint_tpu.ops.dd import DD, dd_add
        def _kernel(pv, r, fast):
            x = r
            if fast:
                x = r.astype(jnp.float32)
            y = x.astype(jnp.float64)
            return dd_add(DD(y, y), pv["X"])
    """, relpath="pint_tpu/gls.py")
    assert any("dd consumer" in x.msg for x in v)


def test_g9_taint_survives_method_call_hops():
    """Review regression: a method call on a tainted receiver
    (.reshape/.sum/.ravel) must not launder f32 provenance before it
    reaches a dd consumer."""
    v, _ = _flow("""
        import jax.numpy as jnp
        from pint_tpu.ops.dd import DD, dd_add
        def _kernel(pv, r):
            x = r.astype(jnp.float32)
            y = x.reshape(-1)
            return dd_add(DD(y, y), pv["X"])
    """, relpath="pint_tpu/gls.py")
    assert any("dd consumer" in x.msg for x in v)


def test_g9_guard_check_rejects_the_else_branch():
    """Review regression: a demotion in the ELSE branch of
    `if jac32:` runs exactly when the flag is off — the registry's
    gating claim must not accept it (and `if not jac32:` inverts the
    branches)."""
    reg = [dict(file="pint_tpu/models/_fixture.py", func="step",
                flag="jac32", guard="jac32", why="fixture")]
    wrong_branch, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            jac32 = bool(cache)
            if jac32:
                y = batch.t
            else:
                y = batch.t.astype(jnp.float32)
            return y
    """, registry=reg)
    assert any("drifted" in x.msg for x in wrong_branch)
    inverted_ok, sup = _flow("""
        import jax.numpy as jnp
        def step(pv, batch, cache):
            jac32 = bool(cache)
            if not jac32:
                y = batch.t
            else:
                y = batch.t.astype(jnp.float32)
            return y
    """, registry=reg)
    assert not any(x.rule == "G9" for x in inverted_ok)
    assert sup


def test_g9_flags_mixed_known_dtype_arithmetic():
    v, _ = _flow("""
        import jax.numpy as jnp
        def step(pv, batch):
            a = batch.t.astype(jnp.float32)
            b = batch.t.astype(jnp.float64)
            return a * b
    """)
    assert any("mixed f32 x f64" in x.msg for x in v)


# ----------------------------------------------------------------- G10

CHROMIDX_FIXTURE = """
    from pint_tpu.models.parameter import floatParameter
    class ChromaticFixture(Component):
        '''Reference: fixture.'''
        def __init__(self):
            self.add_param(floatParameter("TNCHROMIDX", units=""))
        def delay(self, pv, batch, cache, ctx, delay_so_far):
            alpha = self.TNCHROMIDX.value
            return batch.freq_mhz ** -alpha
"""


def test_g10_catches_the_tnchromidx_trace_constant_hazard():
    """The incident fixture: reading a float parameter's .value
    inside a traced compute method bakes it — a free TNCHROMIDX
    would go silently stale (the original bug, reintroduced)."""
    v, _ = _flow(CHROMIDX_FIXTURE)
    assert "G10" in _rules(v)
    assert any("TNCHROMIDX" in x.msg for x in v)


def test_g10_catches_the_capture_form_of_the_same_hazard():
    """The closure-capture variant: the value is read on the host
    and captured by the traced inner function — same staleness, one
    hop removed. This is what a naive 'fix' of the direct read
    usually produces."""
    v, _ = _flow("""
        from pint_tpu.models.parameter import floatParameter
        class ChromaticFixture(Component):
            '''Reference: fixture.'''
            def build(self):
                idx = self.TNCHROMIDX.value
                def compute(pv, batch, cache, ctx, tb):
                    return batch.freq_mhz ** -idx
                return compute
    """)
    assert "G10" in _rules(v)
    assert any("captures" in x.msg and "idx" in x.msg for x in v)


def test_g10_sanctions_keyed_kinds_presence_and_frozen_guard():
    v, _ = _flow("""
        from pint_tpu.models.parameter import (boolParameter,
                                               strParameter,
                                               floatParameter)
        class Fix(Component):
            '''Reference: fixture.'''
            def __init__(self):
                self.add_param(boolParameter("K96"))
                self.add_param(strParameter("ECL"))
                self.add_param(floatParameter("STIG", units=""))
                self.add_param(floatParameter("CMEPOCH", units="d"))
            def delay(self, pv, batch, cache, ctx, delay_so_far):
                if self.K96.value:            # bool kind: keyed
                    pass
                frame = self.ECL.value        # str kind: keyed
                if self.STIG.value is not None:   # presence check
                    pass
                return self._epoch(batch)
            def _epoch(self, batch):
                p = self.CMEPOCH
                if not p.frozen:
                    raise ValueError("freeze CMEPOCH")
                return p.value                # frozen-guarded read
    """)
    assert "G10" not in _rules(v)


def test_g10_frozen_guard_is_per_parameter_and_polarity_checked():
    """Review regression: guarding ONE parameter's frozen-ness must
    not sanction .value reads of a DIFFERENT parameter in the same
    function (that would reopen the TNCHROMIDX hole for every later
    addition), and only the refusing polarity (`not X.frozen`)
    counts."""
    other_param = _flow("""
        from pint_tpu.models.parameter import floatParameter
        class Fix(Component):
            '''Reference: fixture.'''
            def delay(self, pv, batch, cache, ctx, d):
                p = self.CMEPOCH
                if not p.frozen:
                    raise ValueError("freeze CMEPOCH")
                return p.value + self.TNCHROMIDX.value
    """)[0]
    msgs = [x.msg for x in other_param if x.rule == "G10"]
    assert any("TNCHROMIDX" in m for m in msgs)
    assert not any("parameter p " in m for m in msgs)
    inverted = _flow("""
        class Fix(Component):
            '''Reference: fixture.'''
            def delay(self, pv, batch, cache, ctx, d):
                p = self.CMEPOCH
                if p.frozen:
                    raise ValueError("inverted guard")
                return p.value
    """)[0]
    assert "G10" in _rules(inverted)
    # review regression: a read BEFORE the guard (early-return path
    # the guard never dominates) is not sanctioned either
    read_first = _flow("""
        class Fix(Component):
            '''Reference: fixture.'''
            def delay(self, pv, batch, cache, ctx, d):
                p = self.CMEPOCH
                if ctx:
                    return p.value
                if not p.frozen:
                    raise ValueError("freeze it")
                return p.value
    """)[0]
    assert [x.rule for x in read_first].count("G10") == 1


def test_g10_capture_clean_when_value_threads_through_args():
    v, _ = _flow("""
        class Fix(Component):
            '''Reference: fixture.'''
            def build(self):
                names = ["F0", "F1"]   # names, not values: fine
                def compute(pv, batch, cache, ctx, tb):
                    return sum(pv[nm].hi for nm in names)
                return compute
    """)
    assert "G10" not in _rules(v)


def test_g10_pack_value_slots_taint_but_name_slots_do_not():
    v, _ = _flow("""
        class Fix(Component):
            '''Reference: fixture.'''
            def build(self, model):
                free, frozen, th, tl, fh, fl = model._pack()
                def compute(pv, batch, cache, ctx, tb):
                    return fh[0] + len(free)
                return compute
    """)
    flagged = [x for x in v if x.rule == "G10"]
    assert any("`fh`" in x.msg for x in flagged)
    assert not any("`free`" in x.msg for x in flagged)


def test_g10_pragma_and_allowlist_suppression():
    """G10 rides the same suppression machinery as G1-G8 — including
    two-digit rule ids in pragmas (regression: the old pragma regex
    only matched G<single digit>)."""
    src = ("class Fix(Component):\n"
           "    '''Reference: fixture.'''\n"
           "    def delay(self, pv, batch, cache, ctx, d):\n"
           "        a = self.TNCHROMIDX.value"
           "  # graftlint: allow G10 -- fixture\n"
           "        return a\n")
    m = gl.ModuleInfo("pint_tpu/models/_fixture.py", src)
    gl.mark_jit_regions(m, gl.collect_jit_seed_names([m])[m.relpath])
    violations, _ = gf.run_flow_checks([m], registry=[],
                                       verify_probe_sites=False)
    report = gl.LintReport(violations=violations)
    gl.apply_suppressions(report, [],
                          {"pint_tpu/models/_fixture.py": src})
    assert not [x for x in report.violations if x.rule == "G10"]
    assert report.suppressed


def test_compile_key_cross_check_fails_on_drift():
    """If TimingModel._compile_key stops covering the fields G10's
    sanctioning leans on, the analyzer itself must fail."""
    src = """
        class TimingModel:
            def _compile_key(self):
                return (tuple(sorted(self.components)),)
    """
    m = gl.ModuleInfo("pint_tpu/models/timing_model.py",
                      textwrap.dedent(src))
    kinds, violations = gf.parse_compile_key([m])
    assert violations, "drifted compile key must be flagged"
    assert all(x.rule == "G10" for x in violations)


def test_probe_table_verification_detects_lost_sites():
    m = gl.ModuleInfo("pint_tpu/parallel/fit_step.py",
                      "def f():\n    return 1\n")
    v = gf.verify_probes([m])
    assert v and all(x.rule == "REGISTRY" for x in v)


def test_predict_profile_matches_registry_flags():
    p = gf.predict_profile(jac32=True, f32mm=False, anchored=False,
                           hybrid=True)
    assert p["dd32_split"]["active"] and \
        p["dd32_split"]["dtype"] == "float32"
    assert p["symm_mm"]["dtype"] == "float32"
    assert not p["symm_mm_f32"]["active"]
    assert p["phase_frac"]["active"]
    p64 = gf.predict_profile()
    assert p64["symm_mm"]["dtype"] == "float64"
    assert not p64["dd32_split"]["active"]


# ----------------------------------------------------------------- G11

def test_g11_flags_read_after_donated_dispatch():
    """The core hazard: a buffer passed at a donated position is
    consumed by the dispatch; reading it afterwards is a
    deleted-array error (or, pipelined, a race)."""
    v, _ = _flow("""
        import jax
        def drive(f, x, b):
            j = jax.jit(f, donate_argnums=(0,))
            y = j(x, b)
            return x + y
    """)
    flagged = [x for x in v if x.rule == "G11"]
    assert flagged and "`x`" in flagged[0].msg
    # the non-donated operand is untouched
    assert not any("`b`" in x.msg for x in flagged)


def test_g11_rebinding_sanctions_the_idiom():
    """``x = j(x)`` rebinds the name from the call's result — the
    sanctioned donation idiom — and a fresh-temporary argument
    (jnp.asarray(x)) never involves a donatable name at all."""
    v, _ = _flow("""
        import jax
        import jax.numpy as jnp
        def drive(f, x, b):
            j = jax.jit(f, donate_argnums=(0, 1))
            x, b = j(x, b)
            out = j(jnp.asarray(x), jnp.asarray(b))
            return x + b + out[0]
    """)
    assert "G11" not in _rules(v)


def test_g11_attribute_products_and_nonliteral_donation():
    """self.x = jax.jit(..., donate_argnums=...) products are
    tracked by attribute name; a NON-literal donate_argnums donates
    conservatively at every position."""
    v, _ = _flow("""
        import jax
        class Cache:
            def __init__(self, f, pos):
                self._k = jax.jit(f, donate_argnums=pos)
            def solve(self, m, r):
                out = self._k(m, r)
                return r, out
    """)
    flagged = [x for x in v if x.rule == "G11"]
    assert flagged and any("`r`" in x.msg for x in flagged)


def test_g11_pragma_suppression():
    src = ("import jax\n"
           "def drive(f, x, b):\n"
           "    j = jax.jit(f, donate_argnums=(0,))\n"
           "    y = j(x, b)\n"
           "    return x + y  # graftlint: allow G11 -- fixture\n")
    m = gl.ModuleInfo("pint_tpu/models/_fixture.py", src)
    gl.mark_jit_regions(m, gl.collect_jit_seed_names([m])[m.relpath])
    violations, _ = gf.run_flow_checks([m], registry=[],
                                       verify_probe_sites=False)
    report = gl.LintReport(violations=violations)
    gl.apply_suppressions(report, [],
                          {"pint_tpu/models/_fixture.py": src})
    assert not [x for x in report.violations if x.rule == "G11"]
    assert report.suppressed


def test_g11_donation_is_live_on_cpu():
    """The runtime fact the rule guards: donation really consumes
    the buffer on this jax/CPU build — a read after the dispatch
    raises, it does not silently succeed."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest

    j = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
    x = jnp.arange(4.0)
    y = j(x)
    assert float(y[0]) == 0.0
    with _pytest.raises(RuntimeError, match="deleted"):
        np_x = x + 1  # noqa: F841 — the read G11 statically forbids


# ------------------------------------------------------ cfg engine

def test_cfg_joins_branches_and_loops():
    import ast

    fn = ast.parse(textwrap.dedent("""
        def f(cond, n):
            x = "a"
            if cond:
                x = "b"
            for i in range(n):
                y = x
            return x
    """)).body[0]
    graph = fcfg.build_cfg(fn)

    def transfer(st, env, is_header):
        if isinstance(st, ast.Assign) and \
                isinstance(st.targets[0], ast.Name):
            v = st.value
            if isinstance(v, ast.Constant):
                env[st.targets[0].id] = {v.value}
            elif isinstance(v, ast.Name):
                env[st.targets[0].id] = set(env.get(v.id, set()))

    def join(a, b):
        return set(a) | set(b)

    in_envs = fcfg.run_dataflow(graph, {}, transfer, join)
    exit_env = in_envs[graph.exit.bid]
    assert exit_env["x"] == {"a", "b"}      # branch join
    assert exit_env.get("y", set()) <= {"a", "b"}  # loop body fact


# ------------------------------------------------- CLI satellites

def test_cli_format_json_emits_jsonl(tmp_path, capsys):
    """--format json: one {file,line,rule,msg} record per line plus
    a summary record (the pre-commit/CI wire format)."""
    pkg = tmp_path / "pint_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import jax\n"
        "def build():\n"
        "    def fn(x):\n"
        "        return x.item()\n"
        "    return jax.jit(fn)\n")
    rc = gl.main(["--root", str(tmp_path), "--no-dynamic",
                  "--format", "json"])
    out = capsys.readouterr().out.strip().splitlines()
    records = [json.loads(line) for line in out]
    assert rc == 1
    assert any(r.get("rule") == "G1" for r in records)
    assert records[-1]["summary"] is True
    assert records[-1]["clean"] is False
    for r in records[:-1]:
        assert set(r) == {"file", "line", "rule", "msg"}


def test_changed_only_tests_change_still_runs_the_zoo(tmp_path,
                                                      capsys):
    """Review regression: a tests/-only change is a dynamic-zoo
    trigger and must NOT take the "no lintable files changed" early
    exit — the zoo checks validate against tests/ content
    (SINK_PAR), so their findings (repo scope) must surface."""
    import subprocess

    (tmp_path / "pint_tpu").mkdir()
    (tmp_path / "pint_tpu" / "ok.py").write_text("x = 1\n")
    (tmp_path / "tests").mkdir()
    subprocess.run(["git", "init", "-q", str(tmp_path)], timeout=30,
                   check=True)
    subprocess.run(["git", "-C", str(tmp_path), "add", "-A"],
                   timeout=30, check=True)
    subprocess.run(["git", "-C", str(tmp_path), "-c",
                    "user.email=t@t", "-c", "user.name=t", "commit",
                    "-q", "-m", "seed"], timeout=30, check=True)
    (tmp_path / "tests" / "test_new.py").write_text("def t():\n"
                                                    "    pass\n")
    rc = gl.main(["--root", str(tmp_path), "--changed-only",
                  "--format", "json"])
    out = capsys.readouterr().out.strip().splitlines()
    records = [json.loads(line) for line in out]
    # the dynamic half ran (this fixture tree has no SINK_PAR, a
    # repo-scope G5 finding) instead of the early clean exit
    assert rc == 1
    assert any(r.get("rule") == "G5" and "SINK_PAR" in r.get("msg", "")
               for r in records)


def test_changed_file_set_reads_git(tmp_path):
    import subprocess

    subprocess.run(["git", "init", "-q", str(tmp_path)], timeout=30,
                   check=True)
    (tmp_path / "a.py").write_text("x = 1\n")
    subprocess.run(["git", "-C", str(tmp_path), "add", "a.py"],
                   timeout=30, check=True)
    subprocess.run(["git", "-C", str(tmp_path), "-c",
                    "user.email=t@t", "-c", "user.name=t", "commit",
                    "-q", "-m", "seed"], timeout=30, check=True)
    (tmp_path / "a.py").write_text("x = 2\n")       # modified
    (tmp_path / "b.py").write_text("y = 1\n")       # untracked
    changed = gl.changed_file_set(str(tmp_path))
    assert changed == {"a.py", "b.py"}


def test_lint_lane_detection():
    """The conftest fast-lane switch: `-m lint` invocations skip the
    8-virtual-device mesh + compile-cache setup (lint tests never
    dispatch)."""
    import conftest

    assert conftest._lint_only_run(["pytest", "-m", "lint"])
    assert conftest._lint_only_run(["pytest", "-q", "-m", "lint",
                                    "tests/"])
    assert not conftest._lint_only_run(["pytest", "-m", "not slow"])
    assert not conftest._lint_only_run(["pytest", "tests/"])
    assert not conftest._lint_only_run(
        ["pytest", "-m", "lint or slow"])
