"""New misc CLIs (convert_parfile, t2binary2pint, pintpublish) and
utils additions (format_uncertainty, dmx_ranges, wavex setup, AIC/BIC,
PosVel). Reference: src/pint/scripts/convert_parfile.py,
t2binary2pint.py, pintpublish.py; src/pint/utils.py."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_uniform

BINPAR = """
PSR J1012+5307
RAJ 10:12:33.43 1
DECJ 53:07:02.6 1
F0 190.2678 1
F1 -6.2e-16 1
PEPOCH 55500
DM 9.02
BINARY ELL1
PB 0.60467 1
A1 0.581816 1
TASC 55000.1 1
EPS1 1e-5 1
EPS2 -2e-5 1
TZRMJD 55500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""

T2PAR = """
PSR J1713+0747
RAJ 17:13:49.53 1
DECJ 07:47:37.5 1
F0 218.8118 1
F1 -4.08e-16 1
PEPOCH 55500
DM 15.99
BINARY T2
PB 67.8251 1
A1 32.3424 1
T0 55000.0 1
ECC 7.49e-5 1
OM 176.19 1
M2 0.29 1
KIN 71.7 1
KOM 91.0 1
UNITS TDB
"""


def test_convert_parfile_binary(tmp_path, capsys):
    from pint_tpu.scripts.convert_parfile import main

    par = tmp_path / "ell1.par"
    par.write_text(BINPAR.strip() + "\n")
    out = tmp_path / "dd.par"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert main([str(par), "-o", str(out), "--binary", "DD"]) == 0
        m = get_model(str(out))
    assert "BinaryDD" in m.components
    # eccentricity recovered from EPS1/EPS2
    ecc = np.hypot(1e-5, 2e-5)
    assert m.get_param("ECC").value == pytest.approx(ecc, rel=1e-6)


def test_convert_parfile_stdout_passthrough(tmp_path, capsys):
    from pint_tpu.scripts.convert_parfile import main

    par = tmp_path / "ell1.par"
    par.write_text(BINPAR.strip() + "\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert main([str(par)]) == 0
    out = capsys.readouterr().out
    assert "BINARY" in out and "ELL1" in out


def test_t2binary2pint_ddk(tmp_path, capsys):
    from pint_tpu.scripts.t2binary2pint import main, t2_to_native_parfile

    converted = t2_to_native_parfile(T2PAR)
    assert "BINARY DDK" in converted
    # IAU -> DT92: KIN 180-71.7, KOM 90-91
    assert "108.3" in converted
    assert "-1.0" in converted

    par = tmp_path / "t2.par"
    par.write_text(T2PAR.strip() + "\n")
    out = tmp_path / "native.par"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert main([str(par), str(out)]) == 0
        m = get_model(str(out))
    assert "BinaryDDK" in m.components
    assert m.get_param("KIN").value == pytest.approx(108.3)


def test_t2binary2pint_non_t2_passthrough():
    from pint_tpu.scripts.t2binary2pint import t2_to_native_parfile

    assert t2_to_native_parfile(BINPAR) == BINPAR


def test_pintpublish(tmp_path, capsys):
    from pint_tpu.scripts.pintpublish import main

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        rng = np.random.default_rng(3)
        toas = make_fake_toas_uniform(55000, 56000, 60, model,
                                      error_us=1.0, freq_mhz=1400.0,
                                      add_noise=True, rng=rng)
    par = tmp_path / "pub.par"
    tim = tmp_path / "pub.tim"
    par.write_text(model.as_parfile())
    toas.write_TOA_file(tim)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert main([str(par), str(tim)]) == 0
    out = capsys.readouterr().out
    assert r"\begin{tabular}" in out
    assert "F0" in out
    assert "Mass function" in out


# ------------------------------------------------------------- utils


def test_format_uncertainty():
    from pint_tpu.utils import format_uncertainty

    assert format_uncertainty(1.234567, 0.000089) == "1.234567(89)"
    assert format_uncertainty(1.234567, 0.00012) == "1.23457(12)"
    assert format_uncertainty(312.5, 2.4) == "312.5(24)"
    assert format_uncertainty(312.5, 24.0) == "312(24)"
    assert format_uncertainty(5.0, None) == "5.0"
    # rounding that bumps a digit: 0.0999 -> shows as (10) at 2 digits
    s = format_uncertainty(1.5, 0.0999)
    assert "(" in s


def test_dmx_ranges_and_add(tmp_path):
    from pint_tpu.utils import add_dmx_ranges, dmx_ranges

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        rng = np.random.default_rng(4)
        toas = make_fake_toas_uniform(55000, 55100, 50, model,
                                      error_us=1.0, rng=rng)
    ranges = dmx_ranges(toas, max_window_days=14.0)
    assert len(ranges) >= 6
    mjds = np.asarray(toas.get_mjds())
    for r1, r2 in ranges:
        assert r2 > r1
        assert r2 - r1 <= 14.0 + 0.3
    # every TOA falls inside exactly one window
    counts = sum(((mjds >= r1) & (mjds <= r2)).astype(int)
                 for r1, r2 in ranges)
    assert np.all(counts == 1)

    n = add_dmx_ranges(model, toas, max_window_days=14.0)
    assert n == len(ranges)
    comp = model.components["DispersionDMX"]
    assert len(comp.dmx_ids) == n


def test_wavex_setup_roundtrip():
    from pint_tpu.residuals import Residuals
    from pint_tpu.utils import wavex_setup

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        freqs = wavex_setup(model, t_span_days=1000.0, n_freqs=3)
        assert freqs == pytest.approx([1e-3, 2e-3, 3e-3])
        comp = model.components["WaveX"]
        assert len(comp.wavex_ids) == 3
        # model still evaluates with the new (zero-amplitude) modes
        rng = np.random.default_rng(5)
        toas = make_fake_toas_uniform(55000, 56000, 30, model,
                                      error_us=1.0, rng=rng)
        r = Residuals(toas, model)
        assert np.all(np.isfinite(r.time_resids))


def test_dmwavex_setup():
    from pint_tpu.utils import dmwavex_setup

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        freqs = dmwavex_setup(model, t_span_days=500.0, n_freqs=2)
    comp = model.components["DMWaveX"]
    got = sorted(comp.params[nm].value for nm in comp.params
                 if nm.startswith("DMWXFREQ_")
                 and comp.params[nm].value is not None)
    assert got == pytest.approx(freqs)


def test_aic_bic():
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.utils import (akaike_information_criterion,
                                bayesian_information_criterion)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        rng = np.random.default_rng(6)
        toas = make_fake_toas_uniform(55000, 56000, 50, model,
                                      error_us=1.0, add_noise=True,
                                      rng=rng)
        f = WLSFitter(toas, model)
        f.fit_toas(maxiter=2)
    aic = akaike_information_criterion(f)
    bic = bayesian_information_criterion(f)
    k = len(model.free_params)
    assert aic == pytest.approx(2 * k + float(f.resids.chi2))
    assert bic > aic  # ln(50) > 2


def test_dmx_ranges_dense_no_overlap():
    """Dense sampling must not produce overlapping windows (two
    degenerate DMX columns)."""
    from pint_tpu.utils import dmx_ranges

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BINPAR))
        rng = np.random.default_rng(8)
        toas = make_fake_toas_uniform(55000, 55040, 800, model,
                                      error_us=1.0, rng=rng)
    ranges = dmx_ranges(toas, max_window_days=14.0)
    mjds = np.asarray(toas.get_mjds())
    counts = sum(((mjds >= r1) & (mjds <= r2)).astype(int)
                 for r1, r2 in ranges)
    assert np.all(counts == 1)
    for (a1, a2), (b1, b2) in zip(ranges, ranges[1:]):
        assert a2 <= b1


def test_add_dmx_noncontiguous_indices():
    """Existing DMX_0003 must survive adding auto windows (index is
    one past the max, not the count)."""
    from pint_tpu.utils import add_dmx_ranges

    par = BINPAR + ("DMX_0001 0.001 1\nDMXR1_0001 54000\n"
                    "DMXR2_0001 54010\n"
                    "DMX_0003 0.003 1\nDMXR1_0003 54500\n"
                    "DMXR2_0003 54510\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(9)
        toas = make_fake_toas_uniform(55000, 55030, 10, model,
                                      error_us=1.0, rng=rng)
        add_dmx_ranges(model, toas, max_window_days=14.0)
    comp = model.components["DispersionDMX"]
    assert comp.params["DMX_0003"].value == pytest.approx(0.003)
    assert comp.params["DMXR1_0003"].value == pytest.approx(54500)
    new_idx = [i for i, _ in comp.dmx_ids]
    assert min(i for i in new_idx if i > 3) == 4


def test_wavex_add_noncontiguous_indices():
    from pint_tpu.models.components_extra import WaveX

    par = BINPAR + ("WXFREQ_0001 0.001\nWXSIN_0001 1e-6 1\n"
                    "WXCOS_0001 1e-6 1\n"
                    "WXFREQ_0003 0.003\nWXSIN_0003 2e-6 1\n"
                    "WXCOS_0003 2e-6 1\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
    comp = model.components["WaveX"]
    idx = comp.add_wavex_component(0.005)
    assert idx == 4
    assert comp.params["WXFREQ_0003"].value == pytest.approx(0.003)


def test_lorentzian_random_matches_pdf():
    """Regression: draws were ~2pi too narrow vs the wrapped-Cauchy
    pdf."""
    from pint_tpu.templates import make_template

    t = make_template([("lorentzian", 0.9, 0.5, 0.03)])
    rng = np.random.default_rng(10)
    draws = t.random(60000, rng=rng)
    hist, edges = np.histogram(draws, bins=50, range=(0, 1),
                               density=True)
    centers = 0.5 * (edges[:-1] + edges[1:])
    np.testing.assert_allclose(hist, t(centers), atol=0.45)


def test_posvel_chaining():
    from pint_tpu.utils import PosVel

    a = PosVel([1, 0, 0], [0, 1, 0], origin="ssb", obj="earth")
    b = PosVel([0, 1, 0], [0, 0, 1], origin="earth", obj="obs")
    c = a + b
    assert c.origin == "ssb" and c.obj == "obs"
    np.testing.assert_allclose(c.pos, [1, 1, 0])
    with pytest.raises(ValueError):
        _ = b + a  # obs -> ssb mismatch
    d = -a
    assert d.origin == "earth" and d.obj == "ssb"
    e = a - a
    assert e.origin == "earth" and e.obj == "earth"
