"""Parameter-system tests (reference analogs: tests/test_parameters.py,
test_parfile_writing.py)."""

import numpy as np
import pytest

from pint_tpu.models.parameter import (
    AngleParameter,
    MJDParameter,
    boolParameter,
    floatParameter,
    maskParameter,
    parse_float_dd,
    prefixParameter,
    split_prefixed_name,
)


def test_split_prefixed_name():
    assert split_prefixed_name("F12") == ("F", "12", 12)
    assert split_prefixed_name("DMX_0001") == ("DMX_", "0001", 1)
    assert split_prefixed_name("GLF0_2") == ("GLF0_", "2", 2)
    with pytest.raises(ValueError):
        split_prefixed_name("RAJ")


def test_parse_float_dd_exact():
    hi, lo = parse_float_dd("61.485476554373152396")
    # reconstruct to 20 digits via Decimal
    from decimal import Decimal

    got = Decimal(hi) + Decimal(lo)
    assert abs(got - Decimal("61.485476554373152396")) < Decimal("1e-25")
    # scientific notation and D-exponents
    hi, lo = parse_float_dd("-1.1815D-15")
    assert hi == pytest.approx(-1.1815e-15)


def test_float_parameter_long_precision():
    p = floatParameter("F0", units="Hz")
    p.from_tokens(["61.485476554373152396", "1", "1e-13"])
    assert not p.frozen
    assert p.uncertainty == 1e-13
    assert p.dd[1] != 0.0  # kept sub-f64 bits
    p.add_delta(1e-9)
    assert p.value == pytest.approx(61.485476554373152396 + 1e-9)


def test_angle_parameter_hms_dms():
    ra = AngleParameter("RAJ", units="H:M:S")
    ra.from_tokens(["17:48:52.75"])
    assert ra.value == pytest.approx(
        (17 + 48 / 60 + 52.75 / 3600) * np.pi / 12)
    dec = AngleParameter("DECJ", units="D:M:S")
    dec.from_tokens(["-20:21:29.0"])
    assert dec.value == pytest.approx(
        -(20 + 21 / 60 + 29.0 / 3600) * np.pi / 180)
    # format round trip
    ra2 = AngleParameter("RAJ", units="H:M:S")
    ra2.from_tokens([ra._format_value()])
    assert ra2.value == pytest.approx(ra.value, abs=1e-15)
    dec2 = AngleParameter("DECJ", units="D:M:S")
    dec2.from_tokens([dec._format_value()])
    assert dec2.value == pytest.approx(dec.value, abs=1e-15)


def test_mjd_parameter():
    p = MJDParameter("PEPOCH")
    p.from_tokens(["53750.000012345678912"])
    day, frac = p.day_frac
    assert day == 53750.0
    assert frac[0] + frac[1] == pytest.approx(1.2345678912e-5, rel=1e-12)
    # formatting keeps precision
    assert p._format_value().startswith("53750.0000123456789")


def test_bool_parameter():
    p = boolParameter("PLANET_SHAPIRO")
    for tok, want in [("Y", True), ("N", False), ("1", True), ("0", False)]:
        p.from_tokens([tok])
        assert p.value is want


class _FakeTOAs:
    def __init__(self, n):
        self.ntoas = n
        self.flags = [{"fe": "L-wide"} if i % 2 else {"fe": "430"}
                      for i in range(n)]
        self.freq_mhz = np.linspace(400, 1500, n)
        self.obs = ["gbt"] * n
        self.names = [f"t{i}" for i in range(n)]
        self._mjds = np.linspace(50000, 51000, n)

    def get_mjds(self):
        return self._mjds


def test_mask_parameter_select():
    t = _FakeTOAs(10)
    p = maskParameter("JUMP", index=1)
    p.from_tokens(["-fe", "L-wide", "0.0002", "1"])
    m = p.select_mask(t)
    assert m.sum() == 5
    assert not p.frozen
    p2 = maskParameter("JUMP", index=2)
    p2.from_tokens(["MJD", "50000", "50500", "1e-4"])
    assert p2.select_mask(t).sum() == np.sum(t.get_mjds() <= 50500)
    p3 = maskParameter("EFAC", index=1)
    p3.from_tokens(["freq", "1000", "2000", "1.1"])
    assert p3.select_mask(t).sum() == np.sum(t.freq_mhz >= 1000)
    p4 = maskParameter("JUMP", index=3)
    p4.from_tokens(["tel", "gbt", "1e-5"])
    assert p4.select_mask(t).all()


def test_mask_parameter_parfile_line():
    p = maskParameter("JUMP", index=1)
    p.from_tokens(["-fe", "L-wide", "0.000216", "1", "2e-06"])
    line = p.as_parfile_line()
    assert line.split() == ["JUMP", "-fe", "L-wide", "0.000216", "1",
                            "2e-06"]


def test_prefix_parameter():
    p = prefixParameter(name="DMX_0007", value=1e-3, units="pc cm^-3")
    assert p.prefix == "DMX_"
    assert p.index == 7
    assert p.name == "DMX_0007"
