"""Ingestion corner-case torture sweep (VERDICT r4 item 6): one
INCLUDE tree exercising every supported .tim command — FORMAT
toggling inside an include, TIME/PHASE accumulation, scoped
EFAC/EQUAD, EMIN/EMAX/FMIN/FMAX cuts on the scaled error, SKIP blocks
(with inert commands inside), JUMP toggle pairs numbered across
include boundaries, END inside an include terminating the whole
stream — asserted against expected TOA counts, flags, and offsets.
Reference: the single linear command loop of src/pint/toa.py.
"""

import numpy as np
import pytest

from pint_tpu.io.tim import parse_tim


def _t(name, freq, mjd, err, site="gbt", extra=""):
    return f"{name} {freq:.3f} {mjd} {err:.3f} {site}{extra}\n"


@pytest.fixture
def torture(tmp_path):
    # deepest include: its FORMAT 1 + TIME must leak back upward
    deep = tmp_path / "deep.tim"
    deep.write_text(
        "FORMAT 1\n"
        "TIME 0.25\n"
        + _t("d1", 1400.0, "53000.100000", 2.0)
    )
    # middle include: free-form until deep.tim switches the stream
    mid = tmp_path / "mid.tim"
    mid.write_text(
        _t("m1", 1400.0, "53000.200000", 2.0)
        + "INCLUDE deep.tim\n"
        + _t("m2", 1400.0, "53000.300000", 2.0)  # inherits FORMAT+TIME
    )
    master = tmp_path / "master.tim"
    master.write_text(
        _t("a1", 1400.0, "53000.000000", 2.0)
        + "TIME 0.5\n"
        + _t("a2", 1400.0, "53000.400000", 2.0)
        + "INCLUDE mid.tim\n"
        # back in master: FORMAT 1 and TIME 0.75 total still in force
        + _t("a3", 1400.0, "53000.500000", 2.0)
        + "PHASE 1\n"
        + _t("a4", 1400.0, "53000.600000", 2.0)
        + "PHASE -1\n"
        # EFAC/EQUAD scoped scaling: err -> sqrt((2*2)^2 + 3^2) = 5
        + "EFAC 2\nEQUAD 3\n"
        + _t("a5", 1400.0, "53000.700000", 2.0)
        + "EFAC 1\nEQUAD 0\n"
        # cuts see the SCALED error: a6 passes, a7 (err 9) cut by EMAX
        + "EMAX 5\n"
        + _t("a6", 1400.0, "53000.800000", 2.0)
        + _t("a7", 1400.0, "53000.810000", 9.0)
        + "EMAX 1e9\nEMIN 1.0\n"
        + _t("a8", 1400.0, "53000.820000", 0.5)   # cut by EMIN
        + "EMIN 0\n"
        # frequency cuts
        + "FMAX 2000\nFMIN 900\n"
        + _t("a9", 820.0, "53000.830000", 2.0)    # cut by FMIN
        + _t("a10", 3000.0, "53000.840000", 2.0)  # cut by FMAX
        + _t("a11", 1400.0, "53000.850000", 2.0)
        + "FMIN 0\nFMAX 1e9\n"
        # SKIP block: TOAs AND commands inert inside
        + "SKIP\n"
        + _t("s1", 1400.0, "53000.860000", 2.0)
        + "TIME 1000\n"
        + "FORMAT 0\n"
        + "NOSKIP\n"
        + _t("a12", 1400.0, "53000.870000", 2.0)
        # JUMP pairs: second block gets a new id
        + "JUMP\n"
        + _t("j1", 1400.0, "53000.880000", 2.0)
        + "JUMP\n"
        + _t("a13", 1400.0, "53000.890000", 2.0)
        + "JUMP\n"
        + _t("j2", 1400.0, "53000.900000", 2.0)
        + "JUMP\n"
    )
    return master


def test_torture_counts_flags_offsets(torture):
    toas = parse_tim(str(torture))
    names = [t.name for t in toas]
    # exact expected survivors in stream order:
    assert names == ["a1", "a2", "m1", "d1", "m2", "a3", "a4", "a5",
                     "a6", "a11", "a12", "j1", "a13", "j2"]
    by = {t.name: t for t in toas}

    # TIME accumulation across the include tree: a1 none; a2 0.5;
    # m1 0.5 (inherited INTO the include); d1 0.75 (deep's +0.25);
    # m2/a3 keep 0.75 after the include returns
    assert "to" not in by["a1"].flags
    assert float(by["a2"].flags["to"]) == 0.5
    assert float(by["m1"].flags["to"]) == 0.5
    assert float(by["d1"].flags["to"]) == 0.75
    assert float(by["m2"].flags["to"]) == 0.75
    assert float(by["a3"].flags["to"]) == 0.75
    # SKIP's TIME 1000 was inert
    assert float(by["a12"].flags["to"]) == 0.75

    # PHASE: only a4 carries a padd turn; PHASE -1 cancelled it after
    assert float(by["a4"].flags["padd"]) == 1.0
    assert "padd" not in by["a5"].flags

    # EFAC/EQUAD scoped scaling
    assert by["a5"].error_us == pytest.approx(5.0)
    assert by["a6"].error_us == pytest.approx(2.0)

    # deep.tim's FORMAT 1 stayed in force for m2/a3... (free-form
    # five-token lines parse identically, but the SKIPped FORMAT 0
    # must NOT have reset it: a12 parsed under Tempo2 tokenization,
    # proven by the line having exactly 5 tokens and surviving)
    # JUMP ids: two distinct blocks, distinct ids
    assert by["j1"].flags["tim_jump"] != by["j2"].flags["tim_jump"]
    assert "tim_jump" not in by["a13"].flags


def test_end_inside_include_terminates_stream(tmp_path):
    sub = tmp_path / "sub.tim"
    sub.write_text("FORMAT 1\n"
                   "s1 1400.000 53000.100000 2.000 gbt\n"
                   "END\n"
                   "s2 1400.000 53000.200000 2.000 gbt\n")
    master = tmp_path / "master.tim"
    master.write_text("FORMAT 1\n"
                      "a1 1400.000 53000.000000 2.000 gbt\n"
                      "INCLUDE sub.tim\n"
                      "a2 1400.000 53000.300000 2.000 gbt\n")
    toas = parse_tim(str(master))
    assert [t.name for t in toas] == ["a1", "s1"]


def test_phase_command_moves_residuals_one_turn():
    """End-to-end: a PHASE 1 command shifts the affected TOAs'
    residuals by exactly one turn (via the -padd flag consumed by
    Residuals), mirroring the reference's phase-command semantics."""
    import io
    import warnings

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.toa import get_TOAs_array

    par = ("PSR J0001+0001\nF0 100.0 1\nPEPOCH 55000\nRAJ 01:00:00\n"
           "DECJ 10:00:00\nDM 10\nTZRMJD 55000.05\nTZRSITE @\n"
           "TZRFRQ 1400\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(55000.0, 55030.0, 20, m,
                                      error_us=1.0, obs="@")
        r0 = Residuals(toas, m, track_mode="nearest",
                       subtract_mean=False).phase_resids
        for f in toas.flags[10:]:
            f["padd"] = "1"
        toas.invalidate_cache() if hasattr(toas, "invalidate_cache") \
            else None
        r1 = Residuals(toas, m, track_mode="nearest",
                       subtract_mean=False).phase_resids
    d = r1 - r0
    np.testing.assert_allclose(d[:10], 0.0, atol=1e-12)
    np.testing.assert_allclose(d[10:], 1.0, atol=1e-12)


def test_padd_device_step_matches_host_residuals():
    """The device fit step must honor -padd exactly like the host
    Residuals (a PHASE command silently inert on the flagship device
    path would make TPU and host converge to different parameters)."""
    import io
    import warnings

    import jax

    from pint_tpu.models import get_model
    from pint_tpu.parallel import build_fit_step
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    par = ("PSR J0002+0002\nF0 150.0 1\nF1 -1e-15 1\nPEPOCH 55000\n"
           "RAJ 02:00:00\nDECJ 12:00:00\nDM 15\nTZRMJD 55000.05\n"
           "TZRSITE @\nTZRFRQ 1400\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(55000.0, 55100.0, 30, m,
                                      error_us=1.0, obs="@")
        for f in toas.flags[15:]:
            f["padd"] = "2"
        host = Residuals(toas, m).time_resids
        step, args, _ = build_fit_step(m, toas)
        dev = np.asarray(jax.jit(step)(*args)[3])
    np.testing.assert_allclose(dev, host, atol=1e-12)
    # and the offset really is ~2 turns between the halves
    gap = np.mean(dev[15:]) - np.mean(dev[:15])
    assert abs(gap - 2.0 / m.F0.value) < 1e-6
