"""Noise-model + GLS fitter tests (reference analogs:
tests/test_gls_fitter.py, test_ecorr_average.py, test_dmefac_dmequad.py,
test_pldmnoise.py): basis construction unit tests, white-noise scaling
semantics, simulate→fit recovery with correlated noise, and agreement
between the jitted TPU kernel, the SVD path, the full-covariance path,
and the pure-numpy reference-algorithm mirror."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.fitter import Fitter
from pint_tpu.gls import DownhillGLSFitter, GLSFitter, gls_solve_np
from pint_tpu.models import get_model
from pint_tpu.models.noise import (
    create_fourier_design_matrix,
    create_quantization_matrix,
    powerlaw,
)
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

PAR_BASE = """PSR J1910+1256
RAJ 19:10:09.70 1
DECJ 12:56:25.5 1
F0 200.65880532 1
F1 -3.9e-16 1
PEPOCH 55000.0
POSEPOCH 55000.0
DM 38.07 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""

NOISE_LINES = """EFAC -be GUPPI 1.1
EQUAD -be GUPPI 0.5
ECORR -be GUPPI 2.0
TNREDAMP -13.5
TNREDGAM 3.5
TNREDC 10
"""


# ---------------------------------------------------------------- unit


def test_quantization_matrix_buckets():
    t = np.array([0.0, 0.001, 0.002, 5.0, 5.001, 20.0])
    U = create_quantization_matrix(t, dt_days=0.5, nmin=2)
    # two epochs of >=2 TOAs; the singleton at day 20 is dropped
    assert U.shape == (6, 2)
    np.testing.assert_array_equal(U[:, 0], [1, 1, 1, 0, 0, 0])
    np.testing.assert_array_equal(U[:, 1], [0, 0, 0, 1, 1, 0])


def test_quantization_matrix_unsorted_input():
    t = np.array([5.0, 0.0, 5.001, 0.001])
    U = create_quantization_matrix(t, dt_days=0.5)
    assert U.shape == (4, 2)
    assert U[1, 0] == 1 and U[3, 0] == 1 and U[0, 1] == 1 and U[2, 1] == 1


def test_fourier_design_matrix():
    t = np.linspace(0, 1000.0, 64)
    F, freqs = create_fourier_design_matrix(t, 3)
    assert F.shape == (64, 6) and freqs.shape == (6,)
    T = t.max() - t.min()
    np.testing.assert_allclose(freqs[:2], 1.0 / T)
    np.testing.assert_allclose(F[:, 0], np.sin(2 * np.pi * t / T))
    np.testing.assert_allclose(F[:, 1], np.cos(2 * np.pi * t / T))


def test_powerlaw_scaling():
    # doubling A quadruples power; gamma steepens low frequencies
    f = np.array([1e-8, 1e-7])
    p1 = powerlaw(f, 1e-14, 3.0)
    p2 = powerlaw(f, 2e-14, 3.0)
    np.testing.assert_allclose(p2 / p1, 4.0)
    assert powerlaw(f, 1e-14, 5.0)[0] / powerlaw(f, 1e-14, 5.0)[1] \
        == pytest.approx(1e5)


# ------------------------------------------------------------ fixtures


def _model(noise=True):
    par = PAR_BASE + (NOISE_LINES if noise else "")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(par))


@pytest.fixture(scope="module")
def sim_noise():
    """Simulated dataset carrying EFAC/EQUAD + ECORR + red noise, with
    clustered same-day TOAs so ECORR has epochs to bite on."""
    m = _model()
    rng = np.random.default_rng(11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.simulation import _rebuild, zero_residuals
        from pint_tpu.toa import get_TOAs_array

        base = np.linspace(54500, 56500, 80)
        mjds = np.concatenate([base, base + 0.002, base + 0.004])
        mjds.sort()
        t = get_TOAs_array(mjds, obs="gbt", freqs=1400.0, errors=1.0)
        for f in t.flags:
            f["be"] = "GUPPI"
        t = zero_residuals(t, m)
        from pint_tpu.simulation import _noise_draw_s
        from pint_tpu.ops import dd_np

        noise_s = _noise_draw_s(t, m, rng, white=True, correlated=True)
        frac = dd_np.add(t.mjd_frac,
                         dd_np.div_f(dd_np.dd(noise_s), 86400.0))
        t = _rebuild(t, t.mjd_day, frac)
        for f in t.flags:
            f["be"] = "GUPPI"
    truth = {n: m.get_param(n).value for n in m.free_params}
    return m, t, truth


# -------------------------------------------------- white-noise scaling


def test_scaled_toa_uncertainty(sim_noise):
    m, t, _ = sim_noise
    sig = m.scaled_toa_uncertainty(t)
    # EFAC 1.1, EQUAD 0.5 us on 1.0 us errors:
    expect = 1.1 * np.sqrt(1.0 + 0.25) * 1e-6
    np.testing.assert_allclose(sig, expect)


def test_noise_basis_shapes(sim_noise):
    m, t, _ = sim_noise
    F = m.noise_model_designmatrix(t)
    phi = m.noise_model_basis_weight(t)
    dims = m.noise_model_dimensions(t)
    assert F.shape[0] == t.ntoas and F.shape[1] == phi.shape[0]
    # 80 epochs of 3 TOAs + 2*10 Fourier modes
    assert dims["EcorrNoise"][1] == 80
    assert dims["PLRedNoise"][1] == 20
    assert np.all(phi > 0)


# ------------------------------------------------------------ solves


def test_gls_matches_numpy_mirror(sim_noise):
    m, t, _ = sim_noise
    f = GLSFitter(t, m)
    r = Residuals(t, m).time_resids
    M, names, _ = f.get_designmatrix()
    nvec = m.scaled_toa_uncertainty(t) ** 2
    F = m.noise_model_designmatrix(t)
    phi = m.noise_model_basis_weight(t)
    from pint_tpu.gls import _gls_kernel, _gls_kernel_fullcov, _gls_kernel_svd
    import jax.numpy as jnp

    args = (jnp.asarray(M), jnp.asarray(F), jnp.asarray(phi),
            jnp.asarray(r), jnp.asarray(nvec))
    x, cov, chi2, noise, _, ok = _gls_kernel(*args)
    assert bool(ok)
    xn, covn, chi2n, noisen = gls_solve_np(M, F, phi, r, nvec)
    np.testing.assert_allclose(np.asarray(x), xn, rtol=1e-8, atol=1e-14)
    np.testing.assert_allclose(float(chi2), chi2n, rtol=1e-8)
    np.testing.assert_allclose(np.asarray(noise), noisen, rtol=1e-6,
                               atol=1e-12)
    # SVD path agrees
    xs, covs, chi2s, _, _ = _gls_kernel_svd(*args)
    np.testing.assert_allclose(np.asarray(xs), xn, rtol=1e-6, atol=1e-13)
    # full-covariance cross-check (dense Woodbury equivalence)
    xf, covf, chi2f, noisef = _gls_kernel_fullcov(*args)
    np.testing.assert_allclose(np.asarray(xf), xn, rtol=1e-6, atol=1e-13)
    np.testing.assert_allclose(float(chi2f), chi2n, rtol=1e-6)


def test_gls_recovers_parameters(sim_noise):
    m, t, truth = sim_noise
    perturb = {"F0": 2e-10, "F1": 5e-18, "DM": 5e-4}
    for k, dx in perturb.items():
        m.get_param(k).add_delta(dx)
    m.invalidate_cache(params_only=True)
    f = DownhillGLSFitter(t, m)
    f.fit_toas(maxiter=10)
    for k in truth:
        err = f.errors.get(k)
        assert err is not None and err > 0
        diff = abs(m.get_param(k).value - truth[k])
        assert diff < 5 * err, (k, diff, err)
    # restore
    for k, v in truth.items():
        m.get_param(k).value = v
    m.invalidate_cache(params_only=True)


def test_gls_chi2_sane(sim_noise):
    m, t, _ = sim_noise
    f = GLSFitter(t, m)
    chi2 = f.fit_toas()
    dof = t.ntoas - len(m.free_params) - 1
    assert 0.5 < chi2 / dof < 2.0, chi2 / dof
    assert f.noise_resids is not None and f.noise_resids.shape == (t.ntoas,)


def test_auto_picks_gls(sim_noise):
    m, t, _ = sim_noise
    f = Fitter.auto(t, m)
    assert isinstance(f, DownhillGLSFitter)
    m2 = _model(noise=False)
    f2 = Fitter.auto(t, m2, downhill=False)
    assert type(f2).__name__ == "WLSFitter"


def test_gls_reduces_to_wls_without_noise():
    """With no noise components, GLS and WLS give identical updates."""
    from pint_tpu.fitter import WLSFitter

    m1, m2 = _model(noise=False), _model(noise=False)
    rng = np.random.default_rng(3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = make_fake_toas_uniform(54500, 55500, 60, m1, error_us=1.0,
                                   add_noise=True, rng=rng)
    for m in (m1, m2):
        m.F0.add_delta(1e-10)
        m.invalidate_cache(params_only=True)
    c1 = GLSFitter(t, m1).fit_toas()
    c2 = WLSFitter(t, m2).fit_toas(maxiter=2)
    assert m1.F0.value == pytest.approx(m2.F0.value, abs=5e-14)
    assert c1 == pytest.approx(
        Residuals(t, m2).chi2, rel=1e-6)
