"""Array-level GWB likelihood plane (ISSUE 17).

Oracles, most fundamental first:

- the Hellings–Downs matrix itself (closed-form values, SPD);
- the BLOCK-DIAGONAL limit: ``gwb_loglik_np`` at Gamma = I must
  equal the sum of per-pulsar marginal likelihoods computed through
  the EXISTING ``parallel.pta._solve_one_np`` path with the GWB
  basis appended as ordinary red noise — the two-stage Schur
  factorization against the one-stage augmented solve;
- a dense brute-force oracle: the full (sum n)^2 joint covariance,
  slogdet + solve, against the blocked Woodbury with a REAL
  cross-correlating Gamma;
- the device path (plain and mesh-sharded block assembly) against
  the numpy mirror over a hyperparameter grid;
- the served ``GWBRequest`` against the direct ``GWBLikelihood``
  path, and registry-vs-snapshot parity of the PTA counters.
"""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel.pta import (
    PulsarProblem,
    _solve_one_np,
    build_problem,
    stack_problems,
)
from pint_tpu.pta import (
    GWBLikelihood,
    PTAMetrics,
    gwb_loglik_np,
    gwb_phi,
    hd_matrix,
    pulsar_positions,
)
from pint_tpu.simulation import make_fake_toas_uniform


def _mk_pair(psr, f0, ntoa, seed, ra, dec):
    par = f"""PSR {psr}
RAJ {ra} 1
DECJ {dec} 1
F0 {f0} 1
F1 -1e-15 1
PEPOCH 55000
POSEPOCH 55000
DM {10 + seed} 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(
            54500, 55500, ntoa, m, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(seed))
    return t, m


@pytest.fixture(scope="module")
def array3():
    """Three pulsars at well-separated sky positions."""
    return [_mk_pair("J0001+21", 101.1, 40, 11,
                     "12:01:00.0", "21:00:00.0"),
            _mk_pair("J0430-10", 317.9, 64, 12,
                     "04:30:00.0", "-10:00:00.0"),
            _mk_pair("J1820+55", 218.5, 50, 13,
                     "18:20:00.0", "55:00:00.0")]


def _synthetic_problems(rng, P, nfreq, tspan, p=4):
    """Hand-built PulsarProblems + aligned common Fourier basis (no
    timing-model machinery — the algebraic oracles work on raw
    matrices)."""
    m = 2 * nfreq
    f = np.arange(1, nfreq + 1) / tspan
    fcols = np.repeat(f, 2)
    probs, Us = [], []
    for k in range(P):
        n, q = 24 + 5 * k, 2 + (k % 2) * 2
        t = np.sort(rng.uniform(0, tspan, n))
        M = rng.normal(size=(n, p))
        r = rng.normal(size=n) * 1e-6
        nvec = 1e-12 * (1 + 0.3 * rng.random(n))
        F = rng.normal(size=(n, q))
        phi = 10.0 ** rng.uniform(-13, -12, q)
        arg = 2 * np.pi * t[:, None] * f[None, :]
        U = np.zeros((n, m))
        U[:, ::2] = np.sin(arg)
        U[:, 1::2] = np.cos(arg)
        names = ["Offset"] + [f"P{j}" for j in range(1, p)]
        probs.append(PulsarProblem(M, r, nvec, F, phi, names[:p]))
        Us.append(U)
    st = stack_problems(probs)
    N = st["M"].shape[1]
    Ust = np.zeros((P, N, m))
    for k, U in enumerate(Us):
        Ust[k, :U.shape[0], :] = U
    return probs, Us, st, Ust, fcols


# -- geometry ----------------------------------------------------------

def test_hd_matrix_closed_form():
    # 90-degree separation: x = 1/2,
    # Gamma = 1.5*(1/2)*ln(1/2) - 1/8 + 1/2 ~= -0.14486
    pos = np.array([[1.0, 0, 0], [0, 1.0, 0]])
    g = hd_matrix(pos)
    x = 0.5
    expect = 1.5 * x * np.log(x) - x / 4 + 0.5
    assert g[0, 0] == g[1, 1] == 1.0
    np.testing.assert_allclose(g[0, 1], expect, rtol=1e-12)
    # coincident pulsars: off-diagonal -> 1/2 (no pulsar term)
    g2 = hd_matrix(np.array([[0, 0, 1.0], [0, 0, 1.0]]))
    np.testing.assert_allclose(g2[0, 1], 0.5, rtol=1e-12)


def test_hd_matrix_spd_for_random_arrays():
    rng = np.random.default_rng(0)
    pos = rng.normal(size=(20, 3))
    pos /= np.linalg.norm(pos, axis=1)[:, None]
    g = hd_matrix(pos)
    np.testing.assert_allclose(g, g.T)
    assert np.all(np.linalg.eigvalsh(g) > 0)


def test_pulsar_positions_from_models(array3):
    pos = pulsar_positions([m for _, m in array3])
    np.testing.assert_allclose(np.linalg.norm(pos, axis=1), 1.0,
                               rtol=1e-12)
    # well-separated by construction
    c = pos @ pos.T
    off = c[~np.eye(3, dtype=bool)]
    assert np.all(off < 0.95)


# -- algebraic oracles -------------------------------------------------

def test_gamma_eye_reduces_to_per_pulsar_sum():
    """Block-diagonal limit (the ISSUE's acceptance oracle): at
    Gamma = I the two-stage Schur likelihood is EXACTLY the sum of
    per-pulsar marginal likelihoods with the GWB basis appended as
    ordinary red noise — asserted through the EXISTING
    ``_solve_one_np`` solve (its chi2 is the quadratic form) plus an
    explicitly assembled logdet, a completely independent
    factorization order."""
    rng = np.random.default_rng(1)
    from scipy.linalg import cho_factor

    tspan = 3.0e8
    probs, Us, st, Ust, fcols = _synthetic_problems(rng, 4, 3, tspan)
    la, ga = -14.3, 4.33
    phi_g = gwb_phi(fcols, tspan, la, ga)
    tot = 0.0
    for k, pr in enumerate(probs):
        n, p = pr.M.shape
        Faug = np.concatenate([pr.F, Us[k]], axis=1)
        phiaug = np.concatenate([pr.phi, phi_g])
        valid, pvalid = np.ones(n), np.ones(p)
        _, _, chi2, _ = _solve_one_np(pr.M, Faug, phiaug, pr.r,
                                      pr.nvec, valid, pvalid)
        w = valid / pr.nvec
        colmax = np.max(np.abs(pr.M), axis=0)
        Ms = pr.M / colmax[None, :]
        norm = np.sqrt(np.sum(Ms * Ms * w[:, None], axis=0))
        Mn = Ms / norm[None, :]
        big = np.concatenate([Mn, Faug], axis=1)
        Sigma = big.T @ (big * w[:, None]) + np.diag(
            np.concatenate([np.zeros(p), 1.0 / phiaug]))
        cf = cho_factor(Sigma, lower=True)
        ld = (np.sum(np.log(pr.nvec)) + np.sum(np.log(phiaug)) +
              2 * np.sum(np.log(np.diagonal(cf[0]))) +
              2 * np.sum(np.log(colmax * norm)))
        tot += -0.5 * (chi2 + ld)
    got = gwb_loglik_np(st, Ust, np.eye(4), fcols, tspan,
                        np.array([la]), np.array([ga]))[0]
    np.testing.assert_allclose(got, tot, rtol=1e-10)


def test_dense_brute_force_hd_oracle():
    """Proper-prior case (no timing-model columns): the blocked
    Woodbury with a REAL HD Gamma must match slogdet + solve on the
    dense (sum n)^2 joint covariance
    C = blockdiag(N + F phi F^T) + Gamma_ab U_a phi_g U_b^T."""
    rng = np.random.default_rng(2)
    tspan = 2.0e8
    P, nfreq = 3, 2
    probs, Us, st, Ust, fcols = _synthetic_problems(
        rng, P, nfreq, tspan, p=0)
    ns = [pr.M.shape[0] for pr in probs]
    pos = rng.normal(size=(P, 3))
    pos /= np.linalg.norm(pos, axis=1)[:, None]
    G = hd_matrix(pos)
    la, ga = -14.0, 13.0 / 3.0
    phi_g = gwb_phi(fcols, tspan, la, ga)
    ntot = sum(ns)
    C = np.zeros((ntot, ntot))
    rfull = np.concatenate([pr.r for pr in probs])
    off = np.cumsum([0] + ns)
    for a in range(P):
        sa = slice(off[a], off[a + 1])
        C[sa, sa] += np.diag(probs[a].nvec) + \
            probs[a].F @ np.diag(probs[a].phi) @ probs[a].F.T
        for b in range(P):
            sb = slice(off[b], off[b + 1])
            C[sa, sb] += G[a, b] * (Us[a] @ np.diag(phi_g)
                                    @ Us[b].T)
    _, ld = np.linalg.slogdet(C)
    dense = -0.5 * (rfull @ np.linalg.solve(C, rfull) + ld)
    got = gwb_loglik_np(st, Ust, G, fcols, tspan,
                        np.array([la]), np.array([ga]))[0]
    np.testing.assert_allclose(got, dense, rtol=1e-9)


# -- device path vs numpy mirror ---------------------------------------

@pytest.fixture(scope="module")
def like3(array3):
    return GWBLikelihood(pairs=array3, nfreq=4)


def _grid():
    la = np.linspace(-15.0, -13.5, 6)
    ga = np.linspace(3.0, 5.5, 6)
    LA, GA = np.meshgrid(la, ga)
    return LA.ravel(), GA.ravel()


def test_device_grid_matches_numpy_mirror(like3):
    la, ga = _grid()
    got = like3.loglik_grid(la, ga)
    assert like3.blocks_info["used_pool"] == "device"
    want = gwb_loglik_np(like3.stacked, like3.U, like3.Gamma,
                         like3.fcols, like3.tspan, la, ga)
    np.testing.assert_allclose(got, want, rtol=1e-9)
    # the sweep is genuinely discriminating across the grid
    assert np.ptp(got) > 1.0


def test_sharded_blocks_match_plain(array3, like3):
    import jax
    from jax.sharding import Mesh

    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("pulsar",))
    sharded = GWBLikelihood(pairs=array3, nfreq=4, mesh=mesh)
    A0, x0, rdr0, ld0 = like3.build_blocks()
    A1, x1, rdr1, ld1 = sharded.build_blocks()
    np.testing.assert_allclose(A1, A0, rtol=1e-9, atol=1e-18)
    np.testing.assert_allclose(x1, x0, rtol=1e-9, atol=1e-18)
    np.testing.assert_allclose(rdr1, rdr0, rtol=1e-10)
    np.testing.assert_allclose(ld1, ld0, rtol=1e-10)
    la, ga = _grid()
    np.testing.assert_allclose(sharded.loglik_grid(la, ga),
                               like3.loglik_grid(la, ga),
                               rtol=1e-9)


def test_host_pool_and_single_point(like3):
    la, ga = np.array([-14.0]), np.array([13.0 / 3.0])
    info = {}
    host = like3.loglik_grid(la, ga, pool="host", info=info)
    dev = like3.loglik_grid(la, ga)
    np.testing.assert_allclose(host, dev, rtol=1e-9)
    assert info["used_pool"] == "host"
    one = like3.loglik(-14.0, 13.0 / 3.0)
    np.testing.assert_allclose(one, dev[0], rtol=1e-12)


def test_grid_progress_and_chunking(like3):
    la, ga = _grid()          # 36 points
    seen = []
    got = like3.loglik_grid(la, ga, chunk=8,
                            progress=seen.append)
    assert seen == [8, 16, 24, 32, 36]
    want = like3.loglik_grid(la, ga, chunk=16)
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_gwb_chunk_config(monkeypatch):
    from pint_tpu import config

    monkeypatch.delenv("PINT_TPU_GWB_CHUNK", raising=False)
    assert config.gwb_chunk() == 8
    monkeypatch.setenv("PINT_TPU_GWB_CHUNK", "6")
    assert config.gwb_chunk() == 8      # pow2 round-up
    monkeypatch.setenv("PINT_TPU_GWB_CHUNK", "32")
    assert config.gwb_chunk() == 32
    monkeypatch.setenv("PINT_TPU_GWB_CHUNK", "1000")
    assert config.gwb_chunk() == 8      # out of band: warned default


# -- metrics -----------------------------------------------------------

def test_pta_metrics_registry_snapshot_parity():
    from pint_tpu.analysis.graftlint import G13_COUNTER_NAMES
    from pint_tpu.obs import metrics as om

    met = PTAMetrics()
    met.bump("gwb_solves", 3)
    met.bump("block_assemblies")
    met.bump("hd_outer_solves", 24)
    snap = met.snapshot()
    assert snap == {"gwb_solves": 3, "block_assemblies": 1,
                    "hd_outer_solves": 24}
    reg = om.get_registry()
    for name, val in snap.items():
        assert reg.value(f"pint_tpu_pta_{name}_total",
                         scope=met.scope) == val, name
        # G13 protects the names: ad-hoc `+= 1` on them lints
        assert name in G13_COUNTER_NAMES, name


def test_likelihood_counts_its_work(array3):
    lk = GWBLikelihood(pairs=array3, nfreq=2)
    la = np.linspace(-14.5, -14.0, 5)
    ga = np.full(5, 4.0)
    lk.loglik_grid(la, ga, chunk=2)
    snap = lk.metrics.snapshot()
    assert snap["block_assemblies"] == 1
    assert snap["gwb_solves"] == 3          # ceil(5/2) chunks
    assert snap["hd_outer_solves"] == 6     # padded executed points
    # blocks cached: a second sweep re-dispatches no assembly
    lk.loglik_grid(la, ga, chunk=4)
    assert lk.metrics.block_assemblies == 1


# -- serving -----------------------------------------------------------

def test_serve_gwb_request_matches_direct(array3, like3):
    from pint_tpu.serve import GWBRequest, GWBResult, ServeEngine

    la, ga = _grid()
    direct = like3.loglik_grid(la, ga)
    eng = ServeEngine(window_s=0.0, max_batch=4)
    r = GWBRequest(pairs=array3, log10A=la, gamma=ga, nfreq=4)
    fut = eng.submit(r)
    res = fut.result(timeout=120)
    assert isinstance(res, GWBResult)
    np.testing.assert_allclose(res.logL, direct, rtol=1e-9)
    assert res.npulsars == 3 and res.nfreq == 4
    best = res.best()
    assert best["logL"] == np.max(res.logL)
    # kind-local accounting: the unit landed in the metrics under
    # its own shape class
    snap = eng.metrics.snapshot()
    assert any(k.startswith("gwb/") for k in snap["per_bucket"])
    assert snap["completed"] == 1


def test_serve_gwb_prebuilt_likelihood_and_validation(like3):
    from pint_tpu.serve import GWBRequest, ServeEngine

    with pytest.raises(ValueError):
        GWBRequest(log10A=[-14.0], gamma=[4.0])   # no array
    with pytest.raises(ValueError):
        GWBRequest(likelihood=like3, log10A=[-14.0, -13.0],
                   gamma=[4.0])                   # ragged grids
    eng = ServeEngine(window_s=0.0)
    r = GWBRequest(likelihood=like3, log10A=[-14.0], gamma=[4.0])
    res = eng.submit(r).result(timeout=120)
    np.testing.assert_allclose(
        res.logL[0], like3.loglik(-14.0, 4.0), rtol=1e-12)
