"""PTA batch-fitting tests (BASELINE.md config #5): the vmapped
batched GLS solve must agree per-pulsar with the single-pulsar
fitters, across heterogeneous TOA counts / parameter sets / noise
models, and work sharded over a pulsar-axis mesh."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel import build_problem, fit_pta, pta_solve, \
    stack_problems
from pint_tpu.simulation import make_fake_toas_uniform


def _mk(psr, f0, ntoa, seed, noise_lines="", perturb=0.0,
        clustered=False):
    par = f"""PSR {psr}
RAJ 12:0{seed % 10}:00.0 1
DECJ 2{seed % 10}:00:00.0 1
F0 {f0} 1
F1 -1e-15 1
PEPOCH 55000
POSEPOCH 55000
DM {10 + seed} 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
{noise_lines}"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        rng = np.random.default_rng(seed)
        if clustered:
            # pairs of same-day TOAs across the span: ECORR epochs of 2
            from pint_tpu.simulation import _noise_draw_s, _rebuild, \
                zero_residuals
            from pint_tpu.toa import get_TOAs_array
            from pint_tpu.ops import dd_np

            base = np.linspace(54500, 55500, ntoa // 2)
            mjds = np.sort(np.concatenate([base, base + 0.002]))
            t = get_TOAs_array(mjds, obs="gbt", freqs=1400.0, errors=1.0)
            if noise_lines:
                for f in t.flags:
                    f["be"] = "X"
            t = zero_residuals(t, m)
            noise_s = _noise_draw_s(t, m, rng, True, False)
            t = _rebuild(t, t.mjd_day, dd_np.add(
                t.mjd_frac, dd_np.div_f(dd_np.dd(noise_s), 86400.0)))
            if noise_lines:
                for f in t.flags:
                    f["be"] = "X"
        else:
            t = make_fake_toas_uniform(54500, 55500, ntoa, m,
                                       error_us=1.0, add_noise=True,
                                       rng=rng)
        if noise_lines:
            for f in t.flags:
                f["be"] = "X"
    truth = {n: m.get_param(n).value for n in m.free_params}
    if perturb:
        m.F0.add_delta(perturb)
        m.invalidate_cache(params_only=True)
    return m, t, truth


@pytest.fixture(scope="module")
def trio():
    """Three heterogeneous pulsars: different N, one with noise."""
    a = _mk("J0001+01", 101.1, 40, 1, perturb=1e-10)
    b = _mk("J0002+02", 317.9, 64, 2, perturb=-2e-10)
    # clustered same-day pairs so ECORR has multi-TOA epochs
    c = _mk("J0003+03", 218.5, 50, 3, perturb=1.5e-10,
            noise_lines="EFAC -be X 1.2\nECORR -be X 1.0\n",
            clustered=True)
    return [a, b, c]


def test_stack_shapes(trio):
    problems = [build_problem(t, m) for m, t, _ in trio]
    st = stack_problems(problems)
    P, N = st["M"].shape[0], st["M"].shape[1]
    assert P == 3 and N == 64
    assert st["valid"].sum() == 40 + 64 + 50
    # pulsar c has an ECORR basis; others padded to its q
    assert st["F"].shape[2] > 0


def test_batched_solve_matches_individual(trio):
    from pint_tpu.gls import _gls_kernel
    import jax.numpy as jnp

    problems = [build_problem(t, m) for m, t, _ in trio]
    st = stack_problems(problems)
    dparams, cov, chi2, _ = pta_solve(st)
    for k, pr in enumerate(problems):
        x, c_ind, chi2_ind, _, _, ok = _gls_kernel(
            jnp.asarray(pr.M), jnp.asarray(pr.F), jnp.asarray(pr.phi),
            jnp.asarray(pr.r), jnp.asarray(pr.nvec))
        assert bool(ok)
        p = pr.M.shape[1]
        np.testing.assert_allclose(dparams[k][:p], -np.asarray(x),
                                   rtol=1e-8, atol=1e-15)
        np.testing.assert_allclose(np.diag(cov[k])[:p],
                                   np.diag(np.asarray(c_ind)),
                                   rtol=1e-8)
        assert chi2[k] == pytest.approx(float(chi2_ind), rel=1e-8)


def test_fit_pta_recovers(trio):
    res = fit_pta([(t, m) for m, t, _ in trio], maxiter=3)
    assert len(res) == 3
    for (m, t, truth), r in zip(trio, res):
        assert r["chi2"] > 0
        for k, v in truth.items():
            err = r["errors"][k]
            assert abs(m.get_param(k).value - v) < 5 * err, (m.name, k)


def test_pta_solve_on_pulsar_mesh():
    import jax
    from jax.sharding import Mesh

    # fresh (un-fit) pulsars: away from convergence the parameter steps
    # are O(perturbation), so plain-vs-sharded comparison is meaningful
    fresh = [_mk("J0011+01", 99.7, 30, 21, perturb=1e-10),
             _mk("J0012+02", 401.3, 48, 22, perturb=-3e-10)]
    problems = [build_problem(t, m) for m, t, _ in fresh]
    st = stack_problems(problems)
    plain = pta_solve(st)
    ndev = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("pulsar",))
    sharded = pta_solve(st, mesh=mesh)
    for a, b in zip(plain, sharded):
        np.testing.assert_allclose(a, b, rtol=1e-9, atol=1e-18)
