"""pintk GUI logic, driven headless through the Pulsar facade and the
state classes (reference behaviors: src/pint/pintk/pulsar.py Pulsar,
plk.py PlkWidget selection/axes, paredit/timedit apply paths)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_uniform

PAR = """
PSR J0613-0200
RAJ 06:13:43.97 1
DECJ -02:00:47.2 1
F0 326.6005670 1
F1 -1.023e-15 1
PEPOCH 55500
DM 38.78
BINARY ELL1
PB 1.198512 1
A1 1.09144 1
TASC 55000.1 1
EPS1 2e-6 1
EPS2 -3e-6 1
TZRMJD 55500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


@pytest.fixture(scope="module")
def psr_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("pintk")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        rng = np.random.default_rng(21)
        toas = make_fake_toas_uniform(55000, 56000, 50, model,
                                      error_us=1.0, freq_mhz=1400.0,
                                      add_noise=True, rng=rng)
    par = d / "psr.par"
    tim = d / "psr.tim"
    par.write_text(model.as_parfile())
    toas.write_TOA_file(tim)
    return str(par), str(tim)


@pytest.fixture()
def psr(psr_files):
    from pint_tpu.pintk import Pulsar

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return Pulsar(*psr_files)


def test_load_and_fit(psr):
    assert psr.all_toas.ntoas == 50
    assert not psr.fitted
    pre_rms = psr.prefit_resids.rms_weighted()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        psr.fit()
    assert psr.fitted
    assert psr.postfit_resids.rms_weighted() <= pre_rms * 1.01
    # undo restores the unfitted state
    assert psr.undo()
    assert not psr.fitted


def test_selection_and_delete(psr):
    psr.select_mjd_range(55000, 55200)
    n_sel = int(psr.selected.sum())
    assert n_sel > 0
    removed = psr.delete_TOAs()
    assert removed == n_sel
    assert psr.all_toas.ntoas == 50 - n_sel
    assert psr.undo()
    assert psr.all_toas.ntoas == 50


def test_jump_unjump_roundtrip(psr):
    psr.select_mjd_range(55400, 55600)
    n_sel = int(psr.selected.sum())
    assert n_sel > 2
    name = psr.jump_selection()
    assert name.startswith("JUMP")
    comp = psr.model.components["PhaseJump"]
    assert name in comp.params
    # jumped TOAs carry the flag
    from pint_tpu.pintk.pulsar import GUI_JUMP_FLAG

    tagged = sum(1 for f in psr.all_toas.flags if GUI_JUMP_FLAG in f)
    assert tagged == n_sel
    # the jump parameter is fittable and absorbs an offset:
    # fitting with the jump free keeps chi2 finite
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        psr.fit()
    assert np.isfinite(float(psr.postfit_resids.chi2))
    removed = psr.unjump_selection()
    assert removed == 1
    tagged = sum(1 for f in psr.all_toas.flags if GUI_JUMP_FLAG in f)
    assert tagged == 0


def test_jump_changes_model(psr):
    """A jumped block with an injected offset is recovered by the
    free JUMP parameter."""
    mjds = np.asarray(psr.all_toas.get_mjds())
    block = (mjds >= 55500)
    # inject a 50 us offset into the block by shifting the TOAs
    from pint_tpu.ops import dd_np

    psr.all_toas.mjd_frac = dd_np.add(
        psr.all_toas.mjd_frac,
        dd_np.div_f(dd_np.dd(np.where(block, 50e-6, 0.0)), 86400.0))
    psr.all_toas.tdb_frac = dd_np.add(
        psr.all_toas.tdb_frac,
        dd_np.div_f(dd_np.dd(np.where(block, 50e-6, 0.0)), 86400.0))
    psr.all_toas._touch()
    psr.select(block)
    psr.jump_selection()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        psr.fit()
    comp = psr.model.components["PhaseJump"]
    jp = comp.params[comp.jumps[-1]]
    assert abs(jp.value) == pytest.approx(50e-6, rel=0.2)


def test_pulse_number_tracking(psr):
    psr.compute_pulse_numbers()
    assert psr.track_mode == "use_pulse_numbers"
    pn = psr.all_toas.get_pulse_numbers()
    assert pn is not None and len(pn) == 50
    r = psr.prefit_resids
    assert np.all(np.isfinite(r.time_resids))
    psr.reset_pulse_numbers()
    assert psr.all_toas.get_pulse_numbers() is None


def test_random_models(psr):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        psr.fit()
        curves = psr.random_models(n=5, rng=np.random.default_rng(3))
    curves = np.asarray(curves)
    assert curves.shape == (5, 50)
    assert np.all(np.isfinite(curves))


def test_plot_data_and_orbital_phase(psr):
    data = psr.plot_data(postfit=False)
    assert set(data) >= {"mjds", "resids_us", "errors_us", "freqs",
                         "obs", "selected", "rms_us", "chi2"}
    assert "orbital_phase" in data  # binary model
    assert np.all((data["orbital_phase"] >= 0)
                  & (data["orbital_phase"] < 1))


def test_plk_state_axes_and_selection(psr):
    from pint_tpu.pintk.plk import PlkState

    st = PlkState(psr)
    x, y, yerr, data = st.xy()
    assert len(x) == len(y) == len(yerr) == 50
    st.xaxis = "orbital_phase"
    x2, _, _, _ = st.xy()
    assert np.all((x2 >= 0) & (x2 < 1))
    st.xaxis = "serial"
    x3, _, _, _ = st.xy()
    assert x3[0] == 0 and x3[-1] == 49
    # box selection in mjd coords
    st.xaxis = "mjd"
    n = st.select_rectangle(55000, 55100)
    assert n == int(psr.selected.sum()) > 0
    n2 = st.select_rectangle(55900, 56000, extend=True)
    assert n2 > n
    # phase y-axis conversion
    st.yaxis = "residual_phase"
    _, yp, _, _ = st.xy()
    f0 = psr.model.F0.value
    np.testing.assert_allclose(yp, y * 1e-6 * f0, rtol=1e-12)
    assert "wrms" in st.title()


def test_color_modes(psr):
    from pint_tpu.pintk.colormodes import COLOR_MODES, point_colors
    from pint_tpu.pintk.plk import PlkState

    st = PlkState(psr)
    _, _, _, data = st.xy()
    for mode in COLOR_MODES:
        cols = point_colors(mode, data)
        assert len(cols) == 50
    with pytest.raises(ValueError):
        point_colors("nope", data)


def test_par_edit_apply(psr):
    from pint_tpu.pintk.paredit import ParEditState

    st = ParEditState(psr)
    text = st.current_text()
    assert "F0" in text
    # edit F0 slightly and apply
    new = text.replace("326.6005670", "326.6005680")
    st.apply(new)
    assert psr.model.F0.value == pytest.approx(326.6005680)
    assert not psr.fitted
    # malformed par raises (GUI surfaces the error)
    with pytest.raises(Exception):
        st.apply("PSR\nF0 not_a_number\n")


def test_tim_edit_roundtrip(psr):
    from pint_tpu.pintk.timedit import TimEditState

    st = TimEditState(psr)
    text = st.current_text()
    assert "FORMAT 1" in text
    # drop the last TOA line and apply
    lines = [ln for ln in text.strip().splitlines()]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        st.apply("\n".join(lines[:-1]) + "\n")
    assert psr.all_toas.ntoas == 49
    assert psr.undo()
    assert psr.all_toas.ntoas == 50


def test_widgets_importable_headless():
    # the Tk widget classes must import (not instantiate) without a
    # display
    from pint_tpu.pintk import plk, paredit, timedit  # noqa: F401

    assert hasattr(plk, "PlkWidget")
    assert hasattr(paredit, "ParWidget")
    assert hasattr(timedit, "TimWidget")


def test_plk_state_zoom_history_and_visible_mask(psr):
    """Zoom state on the headless PlkState (VERDICT r4 item 8): a
    right-drag zoom box narrows the view, zoom_out walks the history
    back, reset_view autoscales, and visible_mask tracks the limits."""
    from pint_tpu.pintk.plk import PlkState

    st = PlkState(psr)
    psr.clear_selection()
    x, y, _, _ = st.xy()
    assert st.visible_mask().all()
    xm = float(np.median(x))
    st.zoom_rectangle(x.min(), xm)
    m1 = st.visible_mask()
    assert 0 < m1.sum() < len(x)
    # zoom further, into the y range too
    st.zoom_rectangle(x.min(), xm, float(np.min(y)),
                      float(np.median(y)))
    m2 = st.visible_mask()
    assert m2.sum() <= m1.sum()
    assert (m2 & ~m1).sum() == 0
    st.zoom_out()
    assert st.visible_mask().sum() == m1.sum()
    st.zoom_out()
    assert st.visible_mask().all() and st.xlim is None
    st.zoom_rectangle(x.min(), xm)
    st.reset_view()
    assert st.xlim is None and not st._view_stack


def test_plk_state_random_models_overlay(psr):
    """Random-models overlay owned by the headless state: curves are
    computed through the facade, align with the plot arrays, and are
    dropped when the TOA set changes under them."""
    from pint_tpu.pintk.plk import PlkState

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        psr.fit()
        st = PlkState(psr)
        st.compute_random_models(n=4, rng=np.random.default_rng(5))
    x, _, _, _ = st.xy()
    pairs = st.overlay_arrays(x)
    assert len(pairs) == 4
    for cx, cy in pairs:
        assert len(cx) == len(cy) == len(x)
        assert np.all(np.isfinite(cy))
    # stale overlay (TOA count changed) is dropped, not mis-plotted
    st.random_curves = [np.zeros(len(x) + 1)]
    assert st.overlay_arrays(x) == []
    assert st.random_curves is None
    st.clear_random_models()
    assert st.overlay_arrays(x) == []


def test_plk_extra_axes(psr):
    """Round-5 axis parity: year, day-of-year, toa_error, elongation
    (reference plk axis choices)."""
    from pint_tpu.pintk.plk import XAXIS_CHOICES, PlkState

    st = PlkState(psr)
    data = psr.plot_data(postfit=False)
    assert "elongation" in data
    assert np.all((data["elongation"] >= 0)
                  & (data["elongation"] <= 180))
    for ax in XAXIS_CHOICES:
        st.set_axis(xaxis=ax)
        x, y, _, _ = st.xy()
        assert len(x) == len(y)
        assert np.all(np.isfinite(x)), ax
    st.set_axis(xaxis="year")
    x, _, _, _ = st.xy()
    assert np.all((x > 1990) & (x < 2040))
    st.set_axis(xaxis="day_of_year")
    x, _, _, _ = st.xy()
    assert np.all((x >= 0) & (x < 367))


def test_fitbox_and_toa_info(psr):
    """Round-5 facade parity: the fitbox param toggle and the
    per-TOA click-info dict (reference: pintk fitbox + plk info)."""
    fp = psr.fittable_params()
    assert "F0" in fp and "PB" in fp and "PSR" not in fp
    before = set(psr.model.free_params)
    try:
        psr.set_fit_params(["F0", "F1"])
        assert set(psr.model.free_params) == {"F0", "F1"}
        with pytest.raises(KeyError):
            psr.set_fit_params(["F0", "NOPE"])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            psr.fit()  # structure change recompiles and still fits
    finally:
        psr.set_fit_params(before)
    info = psr.toa_info(3)
    assert info["index"] == 3
    assert info["freq_mhz"] > 0 and info["error_us"] > 0
    assert isinstance(info["flags"], dict)
    assert np.isfinite(info["resid_us"])


def test_plk_nearest_point_pick(psr):
    """Headless click-pick: nearest_point returns the right index in
    current-axis coordinates and None on empty space (backs the Tk
    middle-click TOA-info popup)."""
    from pint_tpu.pintk.plk import PlkState

    st = PlkState(psr)
    st.set_axis(xaxis="mjd")
    x, y, _, _ = st.xy()
    k = 7
    assert st.nearest_point(float(x[k]), float(y[k])) == k
    # x-only pick (no y): still finds the point
    assert st.nearest_point(float(x[k])) is not None
    # far off the data span: no pick
    assert st.nearest_point(float(x.max() + 10 * np.ptp(x))) is None
    info = psr.toa_info(st.nearest_point(float(x[k]), float(y[k])))
    assert info["index"] == k


def test_plk_nearest_point_zoom_aware(psr):
    """Zoomed pick: normalization and candidate set follow the VIEW,
    so an off-screen point can't win and empty visible space picks
    nothing."""
    from pint_tpu.pintk.plk import PlkState

    st = PlkState(psr)
    st.set_axis(xaxis="serial")
    x, y, _, _ = st.xy()
    # zoom to the first three points only
    st.zoom_rectangle(-0.5, 2.5)
    k = st.nearest_point(2.0, float(y[2]))
    assert k == 2
    # point 30 is outside the view: clicking near the view edge must
    # not return it
    k2 = st.nearest_point(2.5, float(y[30]))
    assert k2 in (None, 0, 1, 2)
    st.reset_view()
