"""guess_binary_model / BINARY T2 builder path + DegeneracyWarning.
Reference anchors: src/pint/models/model_builder.py
(guess_binary_model), src/pint/fitter.py (DegeneracyWarning)."""
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models.model_builder import (
    T2BinaryWarning,
    get_model,
    guess_binary_model,
)


class TestGuessBinaryModel:
    @pytest.mark.parametrize("keys,expect", [
        ({"PB", "A1", "T0", "ECC", "OM"}, "BT"),
        ({"PB", "A1", "T0", "ECC", "OM", "M2", "SINI"}, "DD"),
        ({"PB", "A1", "T0", "ECC", "OM", "GAMMA"}, "DD"),
        ({"PB", "A1", "T0", "ECC", "OM", "SHAPMAX"}, "DDS"),
        ({"PB", "A1", "T0", "ECC", "OM", "MTOT"}, "DDGR"),
        ({"PB", "A1", "T0", "ECC", "OM", "H3", "STIG"}, "DDH"),
        ({"PB", "A1", "T0", "ECC", "OM", "KIN", "KOM"}, "DDK"),
        ({"PB", "A1", "TASC", "EPS1", "EPS2"}, "ELL1"),
        ({"PB", "A1", "TASC", "EPS1", "EPS2", "H3"}, "ELL1H"),
        ({"PB", "A1", "TASC", "EPS1", "EPS2", "LNEDOT"}, "ELL1k"),
        # KIN wins over ELL1 indicators (most specific first)
        ({"PB", "A1", "TASC", "EPS1", "KIN"}, "DDK"),
    ])
    def test_signatures(self, keys, expect):
        assert guess_binary_model(keys) == expect

    def test_builder_loads_t2_par(self):
        par = """
PSR J1012+5307
RAJ 10:12:33.43 1
DECJ 53:07:02.5 1
F0 190.2678376 1
F1 -6.2e-16
DM 9.02
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY T2
PB 0.60467271355 1
A1 0.5818172 1
TASC 55000.1 1
EPS1 1.2e-6 1
EPS2 -3.0e-7 1
"""
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            m = get_model(io.StringIO(par))
        assert any(isinstance(x.message, T2BinaryWarning) for x in w)
        assert "BinaryELL1" in m.components
        assert m.PB.value == pytest.approx(0.60467271355)
        # round-trips with the resolved model name, not T2
        bline = [ln for ln in m.as_parfile().splitlines()
                 if ln.split() and ln.split()[0] == "BINARY"]
        assert bline and bline[0].split()[1] == "ELL1"

    def test_builder_converts_t2_ddk_angles(self):
        """T2 KIN/KOM (IAU convention) must load as DT92 values —
        identical to what t2binary2pint writes (KIN->180-KIN,
        KOM->90-KOM)."""
        par = """
PSR J0437-4715
RAJ 04:37:15.9 1
DECJ -47:15:09.1 1
F0 173.6879458 1
DM 2.64
PEPOCH 55000
POSEPOCH 55000
PX 6.4 1
PMRA 121.4 1
PMDEC -71.5 1
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY T2
PB 5.741 1
A1 3.3667 1
T0 55000.2 1
ECC 1.9e-5 1
OM 1.2 1
KIN 137.56 1
KOM 207.0 1
M2 0.224 1
SINI 0.674 1
"""
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(io.StringIO(par))
        assert "BinaryDDK" in m.components
        # exactly one binary: the stray SINI (DDK derives inclination
        # from KIN) must be dropped with a warning, not spawn ELL1
        assert sum(1 for c in m.components if c.startswith("Binary")) \
            == 1
        assert m.KIN.value == pytest.approx(180.0 - 137.56)
        assert m.KOM.value == pytest.approx(90.0 - 207.0)


class TestDegeneracyWarning:
    def test_collinear_columns_warn_and_solve(self):
        """Two exactly-collinear DMX windows make the normal matrix
        singular: the Cholesky ok-flag must trip, warn, and the SVD
        fallback must still return finite results."""
        from pint_tpu.fitter import DegeneracyWarning
        from pint_tpu.gls import GLSFitter
        from pint_tpu.simulation import make_fake_toas_uniform

        par = """
PSR J0000+0001
RAJ 12:00:00.0
DECJ 30:00:00.0
F0 61.0 1
F1 -1e-15 1
DM 20.0 1
DM1 0.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
TNREDAMP -13.0
TNREDGAM 3.0
TNREDC 5
DMX_0001 0.0 1
DMXR1_0001 54000
DMXR2_0001 56000
DMX_0002 0.0 1
DMXR1_0002 54000
DMXR2_0002 56000
"""
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m = get_model(io.StringIO(par))
            toas = make_fake_toas_uniform(
                54100, 55900, 120, m, error_us=1.0, add_noise=True,
                rng=np.random.default_rng(9))
        fit = GLSFitter(toas, m)
        with pytest.warns(DegeneracyWarning):
            chi2 = fit.fit_toas()
        assert np.isfinite(chi2)
        assert np.all(np.isfinite(np.diag(
            fit.parameter_covariance_matrix)))
