"""DeviceDownhillGLSFitter: whole downhill fits driven by the
one-kernel jitted fit step, parameter state advanced on host in exact
dd. Oracle: the host DownhillGLSFitter on identical problems."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.gls import DeviceDownhillGLSFitter, DownhillGLSFitter
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 300.123456789 1
F1 -1.0e-15 1
DM 20.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
EFAC -be X 1.1
ECORR -be X 1.2
TNREDAMP -13.7
TNREDGAM 3.5
TNREDC 10
"""


def _two_models(extra="", n=600, seed=2):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m1 = get_model(io.StringIO(PAR + extra))
        m2 = get_model(io.StringIO(PAR + extra))
        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(53001, 56999, n))
        toas = make_fake_toas_fromMJDs(
            mjds, m1, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n // 2),
            add_noise=True, rng=rng)
        for f in toas.flags:
            f["be"] = "X"
    for m in (m1, m2):
        m.F0.value += 2e-9
        m.get_param("DM").value += 1e-4
        m.invalidate_cache(params_only=True)
    return m1, m2, toas


class TestDeviceDownhill:
    def test_matches_host_downhill(self):
        m1, m2, toas = _two_models()
        chi2_h = DownhillGLSFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                        jac_f32=False)
        chi2_d = fit_d.fit_toas()
        assert abs(chi2_h - chi2_d) < 1e-6 * abs(chi2_h)
        for n in ("F0", "DM", "RAJ"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) <= 1e-6 * a.uncertainty, n
            assert b.uncertainty == pytest.approx(a.uncertainty,
                                                  rel=1e-6)
        assert fit_d.converged

    def test_production_config(self):
        """anchored + f32 Jacobian: converges to the same optimum
        within a small fraction of sigma."""
        m1, m2, toas = _two_models()
        DownhillGLSFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, anchored=True,
                                        jac_f32=True, matmul_f32=True)
        fit_d.fit_toas()
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 2e-2 * a.uncertainty, n

    def test_wideband_device_fit(self):
        m1, m2, toas = _two_models()
        rng = np.random.default_rng(7)
        for f in toas.flags:
            f["pp_dm"] = str(20.0 + rng.normal(0, 1e-4))
            f["pp_dme"] = "1e-4"
        from pint_tpu.wideband_fitter import WidebandDownhillFitter

        chi2_h = WidebandDownhillFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, wideband=True,
                                        anchored=False, jac_f32=False)
        chi2_d = fit_d.fit_toas()
        assert abs(chi2_h - chi2_d) < 1e-4 * abs(chi2_h)
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 0.05 * a.uncertainty, n
        # wideband dof: chi2 sums over 2N stacked rows
        assert fit_d.stats.dof == 2 * toas.ntoas - \
            len(m2.free_params) - 1
        assert fit_d.get_noise_resids() is not None

    def test_looped_dispatch_matches_iterative(self):
        """steps_per_dispatch=K (the one-dispatch lax.while_loop fit
        with exact host ledger replay) lands on the same optimum as
        the one-dispatch-per-trial path: on CPU both make identical
        accept/halve decisions, so parameters and chi2 agree to
        rounding."""
        m1, m2, toas = _two_models(seed=5)
        f1 = DeviceDownhillGLSFitter(toas, m1, anchored=False,
                                     jac_f32=False)
        chi2_1 = f1.fit_toas(steps_per_dispatch=1)
        f2 = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                     jac_f32=False)
        chi2_2 = f2.fit_toas(steps_per_dispatch=8)
        # the two paths run the SAME decision rules but as different
        # XLA programs (step jit vs while_loop body): at the
        # far-from-optimum start the marginalized chi2 is a large
        # cancellation, so compilation-order differences shift it at
        # ~1e-6 relative (measured: 30867174.5 vs 30867075.7) and the
        # trajectories may split at an accept threshold. The contract
        # is optimum equivalence, not step-for-step identity:
        # measured agreement is <0.01 sigma on every parameter and
        # ~1e-12 relative on uncertainties.
        assert abs(chi2_2 - chi2_1) < 0.5
        assert f2.converged
        assert f2.stats.iterations >= 1
        for n in ("F0", "DM", "RAJ"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) <= 2e-2 * a.uncertainty, n
            assert b.uncertainty == pytest.approx(a.uncertainty,
                                                  rel=1e-6)

    def test_looped_dispatch_production_config(self):
        """The loop composes with anchored + f32 Jacobian + f32 MXU
        (the TPU production configuration it exists to serve)."""
        m1, m2, toas = _two_models(seed=6)
        DownhillGLSFitter(toas, m1).fit_toas()
        fd = DeviceDownhillGLSFitter(toas, m2, anchored=True,
                                     jac_f32=True, matmul_f32=True)
        fd.fit_toas(steps_per_dispatch=6)
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 2e-2 * a.uncertainty, n

    def test_whole_fit_dispatch_tax_oracles(self, monkeypatch):
        """ISSUE 7 tentpole oracles, sharing ONE fixture + host
        reference (they are fast-lane tests; each extra fixture is a
        TOA build plus fresh loop compiles):

        1. whole_fit=True runs damping, acceptance and convergence
           inside ONE lax.while_loop dispatch (maxiter as runtime
           budget) and lands on the stepwise host fitter's optimum —
           the CPU equality contract the <10% dispatch-overhead
           target leans on. Optimum equivalence, not step-for-step
           identity: the two paths run the same decision rules as
           different XLA programs (see
           test_looped_dispatch_matches_iterative), so trajectories
           may split at an accept threshold, landing ~1e-3 relative
           apart on a flat chi2 surface while every parameter agrees
           far inside its uncertainty.
        2. Donation oracle: donate_argnums on the loop's (th, tl)
           state is bit-invisible — chi2 and every fitted value
           identical with donation on and off (donation is REAL on
           this CPU build: the donated buffer is deleted).
        3. Budget oracle: maxiter rides as the RUNTIME budget of the
           compiled loop — the dispatch stops at it exactly, no
           overshoot from the quantized compile-key K.
        4. Pipeline oracle: the pipelined multi-chunk fit (next
           chunk issued async from the device-advanced pair while
           the host replays the ledger) is bit-identical to the
           synchronous chained path on IEEE hardware, and really
           overlaps (async dispatches counted)."""
        import copy

        from pint_tpu.fitter import MaxiterReached
        from pint_tpu.runtime import get_supervisor

        m1, m2, toas = _two_models(n=360, seed=12)
        m_off, m_bud, m_pipe, m_sync = (copy.deepcopy(m2)
                                        for _ in range(4))
        chi2_h = DownhillGLSFitter(toas, m1).fit_toas()

        # 1 — whole fit vs the stepwise host fitter (donation ON)
        monkeypatch.setenv("PINT_TPU_DONATE", "1")
        fd = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                     jac_f32=False)
        chi2_on = fd.fit_toas(whole_fit=True)
        assert abs(chi2_on - chi2_h) < 5e-3 * abs(chi2_h)
        assert fd.converged
        assert fd.step_evals >= fd.stats.iterations >= 1
        for n in ("F0", "DM", "RAJ"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) <= 2e-2 * a.uncertainty, n
            assert b.uncertainty == pytest.approx(a.uncertainty,
                                                  rel=1e-6)

        # 2 — identical fit with donation OFF: bit-identical results
        monkeypatch.setenv("PINT_TPU_DONATE", "0")
        f_off = DeviceDownhillGLSFitter(toas, m_off, anchored=False,
                                        jac_f32=False)
        chi2_off = f_off.fit_toas(whole_fit=True)
        monkeypatch.setenv("PINT_TPU_DONATE", "1")
        assert chi2_off == chi2_on
        for n in m2.free_params:
            assert m2.get_param(n).value == m_off.get_param(n).value, n
            assert m2.get_param(n).uncertainty == \
                m_off.get_param(n).uncertainty, n

        # 3 — runtime budget honored exactly
        f_bud = DeviceDownhillGLSFitter(toas, m_bud, anchored=False,
                                        jac_f32=False)
        try:
            f_bud.fit_toas(whole_fit=True, maxiter=2,
                           required_chi2_decrease=1e-12)
        except MaxiterReached:
            pass
        assert f_bud.stats.iterations <= 2

        # 4 — pipelined chaining == sync chaining, bit for bit
        # (2-iteration chunks + a zero convergence threshold — the
        # loop runs until a step is REJECTED — force multiple chunks
        # so the speculative async issue actually engages)
        base = get_supervisor().snapshot()["async_dispatches"]
        f_pipe = DeviceDownhillGLSFitter(toas, m_pipe,
                                         anchored=False,
                                         jac_f32=False)
        chi2_p = f_pipe.fit_toas(steps_per_dispatch=2, pipeline=True,
                                 required_chi2_decrease=0.0)
        assert get_supervisor().snapshot()["async_dispatches"] > base
        f_sync = DeviceDownhillGLSFitter(toas, m_sync,
                                         anchored=False,
                                         jac_f32=False)
        chi2_s = f_sync.fit_toas(steps_per_dispatch=2,
                                 pipeline=False,
                                 required_chi2_decrease=0.0)
        # identical decision procedure; on a quiet machine the two
        # paths are bitwise identical (the device-advanced pair IS
        # the host replay on IEEE hardware), but under full-suite
        # load XLA:CPU's concurrent dispatch is not bit-stable at
        # the rejection edge the 0.0 threshold drives into — so pin
        # equivalence at far-sub-sigma rather than bit level
        assert chi2_p == pytest.approx(chi2_s, rel=1e-12)
        for n in m_pipe.free_params:
            a, b = m_pipe.get_param(n), m_sync.get_param(n)
            tol = 1e-6 * (a.uncertainty or abs(a.value) or 1.0)
            assert abs(a.value - b.value) <= tol, n

    def test_stats_populated(self):
        _, m2, toas = _two_models(n=200)
        fit = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                      jac_f32=False)
        fit.fit_toas()
        assert fit.stats.iterations >= 1
        assert fit.stats.toas_per_sec > 0
        assert fit.stats.fitter == "DeviceDownhillGLSFitter"


def test_fitter_auto_device_selection():
    """Fitter.auto(device=True) returns the device fitter (narrowband
    and wideband); default on the CPU backend stays with the host
    fitters."""
    from pint_tpu.fitter import Fitter
    from pint_tpu.gls import DownhillGLSFitter

    _, m, toas = _two_models(n=100)
    f = Fitter.auto(toas, m)
    assert isinstance(f, DownhillGLSFitter)
    assert not isinstance(f, DeviceDownhillGLSFitter)
    fd = Fitter.auto(toas, m, device=True)
    assert isinstance(fd, DeviceDownhillGLSFitter)
    assert not fd.wideband
    rng = np.random.default_rng(1)
    for fl in toas.flags:
        fl["pp_dm"] = str(20.0 + rng.normal(0, 1e-4))
        fl["pp_dme"] = "1e-4"
    fw = Fitter.auto(toas, m, device=True)
    assert isinstance(fw, DeviceDownhillGLSFitter) and fw.wideband
    chi2 = fd.fit_toas()
    assert np.isfinite(chi2)


def test_auto_steps_per_dispatch_policy(monkeypatch):
    """Adaptive chaining policy: 1 on the CPU backend; on an
    accelerator, K is sized from the measured dispatch RTT, quantized
    to a power of two in [4, 32] so the noisy tunnel RTT cannot
    generate a fresh compile key per session (VERDICT r4 item 3 —
    nothing adapted the fixed 8 to RTT)."""
    import jax

    from pint_tpu import config

    assert config.auto_steps_per_dispatch() == 1  # CPU backend

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for rtt_ms, expect in [(0.3, 4), (64.0, 8), (124.0, 16),
                           (250.0, 32), (10000.0, 32)]:
        monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", str(rtt_ms))
        config._RTT_MS.clear()
        assert config.auto_steps_per_dispatch() == expect, rtt_ms
    config._RTT_MS.clear()


def test_no_degeneracy_warning_on_healthy_fit():
    """The round-5 degeneracy detector (huge proposed-step-in-sigma
    at convergence -> RuntimeWarning naming the SVD fallback) must
    stay silent on a healthy fit. (The positive case is
    compile-dependent — a near-singular design can produce a
    non-descent Cholesky direction under one XLA build and a benign
    null-step under another, see bench_stress's 2-frequency
    incident — so only the false-positive side is pinned here.)"""
    _, m, toas = _two_models(n=300)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        fit = DeviceDownhillGLSFitter(toas, m, anchored=False,
                                      jac_f32=False)
        fit.fit_toas()
    assert not [x for x in rec if x.category is RuntimeWarning
                and "degenerate" in str(x.message)]
