"""DeviceDownhillGLSFitter: whole downhill fits driven by the
one-kernel jitted fit step, parameter state advanced on host in exact
dd. Oracle: the host DownhillGLSFitter on identical problems."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.gls import DeviceDownhillGLSFitter, DownhillGLSFitter
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_fromMJDs

PAR = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 300.123456789 1
F1 -1.0e-15 1
DM 20.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
EFAC -be X 1.1
ECORR -be X 1.2
TNREDAMP -13.7
TNREDGAM 3.5
TNREDC 10
"""


def _two_models(extra="", n=600, seed=2):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m1 = get_model(io.StringIO(PAR + extra))
        m2 = get_model(io.StringIO(PAR + extra))
        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(53001, 56999, n))
        toas = make_fake_toas_fromMJDs(
            mjds, m1, error_us=1.0,
            freq_mhz=np.tile([1400.0, 820.0], n // 2),
            add_noise=True, rng=rng)
        for f in toas.flags:
            f["be"] = "X"
    for m in (m1, m2):
        m.F0.value += 2e-9
        m.get_param("DM").value += 1e-4
        m.invalidate_cache(params_only=True)
    return m1, m2, toas


class TestDeviceDownhill:
    def test_matches_host_downhill(self):
        m1, m2, toas = _two_models()
        chi2_h = DownhillGLSFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                        jac_f32=False)
        chi2_d = fit_d.fit_toas()
        assert abs(chi2_h - chi2_d) < 1e-6 * abs(chi2_h)
        for n in ("F0", "DM", "RAJ"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) <= 1e-6 * a.uncertainty, n
            assert b.uncertainty == pytest.approx(a.uncertainty,
                                                  rel=1e-6)
        assert fit_d.converged

    def test_production_config(self):
        """anchored + f32 Jacobian: converges to the same optimum
        within a small fraction of sigma."""
        m1, m2, toas = _two_models()
        DownhillGLSFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, anchored=True,
                                        jac_f32=True, matmul_f32=True)
        fit_d.fit_toas()
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 2e-2 * a.uncertainty, n

    def test_wideband_device_fit(self):
        m1, m2, toas = _two_models()
        rng = np.random.default_rng(7)
        for f in toas.flags:
            f["pp_dm"] = str(20.0 + rng.normal(0, 1e-4))
            f["pp_dme"] = "1e-4"
        from pint_tpu.wideband_fitter import WidebandDownhillFitter

        chi2_h = WidebandDownhillFitter(toas, m1).fit_toas()
        fit_d = DeviceDownhillGLSFitter(toas, m2, wideband=True,
                                        anchored=False, jac_f32=False)
        chi2_d = fit_d.fit_toas()
        assert abs(chi2_h - chi2_d) < 1e-4 * abs(chi2_h)
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 0.05 * a.uncertainty, n
        # wideband dof: chi2 sums over 2N stacked rows
        assert fit_d.stats.dof == 2 * toas.ntoas - \
            len(m2.free_params) - 1
        assert fit_d.get_noise_resids() is not None

    def test_looped_dispatch_matches_iterative(self):
        """steps_per_dispatch=K (the one-dispatch lax.while_loop fit
        with exact host ledger replay) lands on the same optimum as
        the one-dispatch-per-trial path: on CPU both make identical
        accept/halve decisions, so parameters and chi2 agree to
        rounding."""
        m1, m2, toas = _two_models(seed=5)
        f1 = DeviceDownhillGLSFitter(toas, m1, anchored=False,
                                     jac_f32=False)
        chi2_1 = f1.fit_toas(steps_per_dispatch=1)
        f2 = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                     jac_f32=False)
        chi2_2 = f2.fit_toas(steps_per_dispatch=8)
        # the two paths run the SAME decision rules but as different
        # XLA programs (step jit vs while_loop body): at the
        # far-from-optimum start the marginalized chi2 is a large
        # cancellation, so compilation-order differences shift it at
        # ~1e-6 relative (measured: 30867174.5 vs 30867075.7) and the
        # trajectories may split at an accept threshold. The contract
        # is optimum equivalence, not step-for-step identity:
        # measured agreement is <0.01 sigma on every parameter and
        # ~1e-12 relative on uncertainties.
        assert abs(chi2_2 - chi2_1) < 0.5
        assert f2.converged
        assert f2.stats.iterations >= 1
        for n in ("F0", "DM", "RAJ"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) <= 2e-2 * a.uncertainty, n
            assert b.uncertainty == pytest.approx(a.uncertainty,
                                                  rel=1e-6)

    def test_looped_dispatch_production_config(self):
        """The loop composes with anchored + f32 Jacobian + f32 MXU
        (the TPU production configuration it exists to serve)."""
        m1, m2, toas = _two_models(seed=6)
        DownhillGLSFitter(toas, m1).fit_toas()
        fd = DeviceDownhillGLSFitter(toas, m2, anchored=True,
                                     jac_f32=True, matmul_f32=True)
        fd.fit_toas(steps_per_dispatch=6)
        for n in ("F0", "DM"):
            a, b = m1.get_param(n), m2.get_param(n)
            assert abs(a.value - b.value) < 2e-2 * a.uncertainty, n

    def test_stats_populated(self):
        _, m2, toas = _two_models(n=200)
        fit = DeviceDownhillGLSFitter(toas, m2, anchored=False,
                                      jac_f32=False)
        fit.fit_toas()
        assert fit.stats.iterations >= 1
        assert fit.stats.toas_per_sec > 0
        assert fit.stats.fitter == "DeviceDownhillGLSFitter"


def test_fitter_auto_device_selection():
    """Fitter.auto(device=True) returns the device fitter (narrowband
    and wideband); default on the CPU backend stays with the host
    fitters."""
    from pint_tpu.fitter import Fitter
    from pint_tpu.gls import DownhillGLSFitter

    _, m, toas = _two_models(n=100)
    f = Fitter.auto(toas, m)
    assert isinstance(f, DownhillGLSFitter)
    assert not isinstance(f, DeviceDownhillGLSFitter)
    fd = Fitter.auto(toas, m, device=True)
    assert isinstance(fd, DeviceDownhillGLSFitter)
    assert not fd.wideband
    rng = np.random.default_rng(1)
    for fl in toas.flags:
        fl["pp_dm"] = str(20.0 + rng.normal(0, 1e-4))
        fl["pp_dme"] = "1e-4"
    fw = Fitter.auto(toas, m, device=True)
    assert isinstance(fw, DeviceDownhillGLSFitter) and fw.wideband
    chi2 = fd.fit_toas()
    assert np.isfinite(chi2)


def test_auto_steps_per_dispatch_policy(monkeypatch):
    """Adaptive chaining policy: 1 on the CPU backend; on an
    accelerator, K is sized from the measured dispatch RTT, quantized
    to a power of two in [4, 32] so the noisy tunnel RTT cannot
    generate a fresh compile key per session (VERDICT r4 item 3 —
    nothing adapted the fixed 8 to RTT)."""
    import jax

    from pint_tpu import config

    assert config.auto_steps_per_dispatch() == 1  # CPU backend

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for rtt_ms, expect in [(0.3, 4), (64.0, 8), (124.0, 16),
                           (250.0, 32), (10000.0, 32)]:
        monkeypatch.setenv("PINT_TPU_DISPATCH_RTT_MS", str(rtt_ms))
        config._RTT_MS.clear()
        assert config.auto_steps_per_dispatch() == expect, rtt_ms
    config._RTT_MS.clear()


def test_no_degeneracy_warning_on_healthy_fit():
    """The round-5 degeneracy detector (huge proposed-step-in-sigma
    at convergence -> RuntimeWarning naming the SVD fallback) must
    stay silent on a healthy fit. (The positive case is
    compile-dependent — a near-singular design can produce a
    non-descent Cholesky direction under one XLA build and a benign
    null-step under another, see bench_stress's 2-frequency
    incident — so only the false-positive side is pinned here.)"""
    _, m, toas = _two_models(n=300)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        fit = DeviceDownhillGLSFitter(toas, m, anchored=False,
                                      jac_f32=False)
        fit.fit_toas()
    assert not [x for x in rec if x.category is RuntimeWarning
                and "degenerate" in str(x.message)]
