"""TimingModel tests: builder routing, phase/delay physics sanity,
design-matrix-vs-finite-difference derivative checks, par round trip
(reference analogs: tests/test_model.py, test_model_derivatives.py,
test_parfile_writing.py)."""

import io
import warnings

import numpy as np
import pytest

import pint_tpu
from pint_tpu.models import get_model, get_model_and_toas
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

PAR = """PSR J1748-2021E
RAJ 17:48:52.75 1
DECJ -20:21:29.0 1
PMRA 3.5
PMDEC -2.1
PX 0.5
F0 61.485476554373152 1
F1 -1.1815e-15 1
PEPOCH 53750.0
POSEPOCH 53750.0
DM 223.9 1
DM1 0.003
DMEPOCH 53750.0
JUMP -fe 430 0.000216 1
TZRMJD 53750.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""

TIM = """FORMAT 1
t1 1400.0 53478.2858714192189 1.0 gbt -fe L-wide
t2 1400.0 53483.2767051885165 1.0 gbt -fe L-wide
t3 428.0 53489.4683897879295 1.5 gbt -fe 430
t4 1400.0 53679.8756457127679 1.0 gbt -fe L-wide
t5 428.0 53900.1234567890123 1.5 gbt -fe 430
"""


@pytest.fixture(scope="module")
def model_and_toas():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model_and_toas(io.StringIO(PAR), io.StringIO(TIM))


def test_builder_components(model_and_toas):
    m, _ = model_and_toas
    for c in ("Spindown", "AstrometryEquatorial", "DispersionDM",
              "PhaseJump", "AbsPhase", "SolarSystemShapiro"):
        assert c in m.components
    assert m.F0.value == pytest.approx(61.485476554373152)
    assert m.JUMP1.key == "-fe"
    assert not m.JUMP1.frozen
    assert set(m.free_params) == {"RAJ", "DECJ", "DM", "F0", "F1", "JUMP1"}


def test_delay_physics(model_and_toas):
    m, t = model_and_toas
    d = np.asarray(m.delay(t))
    # Roemer delay dominates: |d| <= ~501s + dispersion
    disp = pint_tpu.DMconst * 223.9 / 428.0 ** 2
    assert np.all(np.abs(d) < 510 + disp)
    # dispersion: delay(DM) − delay(DM→0) scales as ν⁻² (SURVEY A.8 (d))
    dm0 = m.DM.value
    m.DM.value = 1e-9
    m.invalidate_cache(params_only=True)
    d_nodm = np.asarray(m.delay(t))
    m.DM.value = dm0
    m.invalidate_cache(params_only=True)
    ddisp = d - d_nodm
    freqs = np.asarray(t.get_freqs())
    expect = pint_tpu.DMconst * dm0 / freqs ** 2
    np.testing.assert_allclose(ddisp, expect, rtol=1e-3)


def test_phase_absolute_anchor(model_and_toas):
    m, t = model_and_toas
    ph = m.phase(t, abs_phase=True)
    # TZR at 53750.1: phases O(1e9) turns away
    assert np.all(np.abs(np.asarray(ph.int)) > 1e6)
    assert np.all(np.abs(np.asarray(ph.frac)) <= 0.5)


def test_designmatrix_vs_finite_difference(model_and_toas):
    """The de-facto gradcheck of the reference
    (tests/test_model_derivatives.py): jacfwd columns vs central
    differences on each free parameter."""
    m, t = model_and_toas
    M, names, units = m.designmatrix(t, incoffset=True)
    f0 = m.F0.value

    for name in m.free_params:
        p = m.get_param(name)
        # steps large enough that the f64 delay quantization (~1e-13 s)
        # doesn't pollute the difference; curvature is negligible here
        h = {"RAJ": 1e-9, "DECJ": 1e-9, "DM": 1e-4, "F0": 1e-11,
             "F1": 1e-19, "JUMP1": 1e-8}[name]
        p.add_delta(h)
        m.invalidate_cache(params_only=True)
        ph_plus = np.asarray(m.phase(t).frac)
        int_plus = np.asarray(m.phase(t).int)
        p.add_delta(-2 * h)
        m.invalidate_cache(params_only=True)
        ph_minus = np.asarray(m.phase(t).frac)
        int_minus = np.asarray(m.phase(t).int)
        p.add_delta(h)
        m.invalidate_cache(params_only=True)
        # frac keeps full precision at 1e9 turns; add back any integer
        # crossing between the +h and −h evaluations
        dphase = (ph_plus - ph_minus) + (int_plus - int_minus)
        fd = dphase / (2 * h) / f0
        col = M[:, names.index(name)]
        scale = np.max(np.abs(fd)) or 1.0
        np.testing.assert_allclose(col, fd, rtol=1e-4,
                                   atol=1e-4 * scale,
                                   err_msg=f"derivative mismatch: {name}")


def test_parfile_roundtrip(model_and_toas):
    m, t = model_and_toas
    text = m.as_parfile()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m2 = get_model(io.StringIO(text))
    for name in ("F0", "F1", "DM", "RAJ", "DECJ", "PMRA", "PX", "JUMP1"):
        assert m2.get_param(name).value == pytest.approx(
            m.get_param(name).value, rel=1e-12), name
    assert m2.JUMP1.key == "-fe"
    assert m2.JUMP1.key_value == ["430"]
    # phases agree to sub-ns
    ph1 = np.asarray(m.phase(t).frac)
    ph2 = np.asarray(m2.phase(t).frac)
    np.testing.assert_allclose(ph1, ph2, atol=1e-7)


def test_jump_changes_selected_toas_only(model_and_toas):
    m, t = model_and_toas
    r0 = Residuals(t, m, subtract_mean=False).time_resids
    m.JUMP1.add_delta(1e-4)
    m.invalidate_cache(params_only=True)
    r1 = Residuals(t, m, subtract_mean=False).time_resids
    m.JUMP1.add_delta(-1e-4)
    m.invalidate_cache(params_only=True)
    delta = r1 - r0
    sel = np.array([f.get("fe") == "430" for f in t.flags])
    assert np.allclose(delta[~sel], 0, atol=1e-12)
    assert np.allclose(delta[sel], -1e-4, atol=1e-9)


def test_ecliptic_model():
    par = """PSR J0613-0200
ELONG 93.7990
ELAT -25.4071
F0 326.6005670870222 1
PEPOCH 54500.0
DM 38.778
TZRMJD 54500.0
TZRSITE @
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_uniform(54400, 54600, 20, m, obs="parkes")
    assert "AstrometryEcliptic" in m.components
    r = Residuals(t, m, subtract_mean=False)
    assert np.max(np.abs(r.time_resids)) < 1e-9


def test_roemer_annual_amplitude():
    """Roemer amplitude = 499.005·cos(ecliptic latitude) s
    (SURVEY.md A.8 oracle (b))."""
    par = """PSR TEST
ELONG 120.0
ELAT 0.0
F0 100.0
PEPOCH 55000.0
DM 0.0
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        from pint_tpu.toa import get_TOAs_array

        t = get_TOAs_array(np.linspace(55000, 55365, 80), obs="geocenter",
                           freqs=np.inf, errors=1.0)
    d = np.asarray(m.delay(t))
    amp = (d.max() - d.min()) / 2
    assert amp == pytest.approx(499.005, rel=2e-3)


def test_tcb_converted_by_default_refused_on_request():
    import warnings as _w

    par = "PSR X\nF0 10\nPEPOCH 55000\nUNITS TCB\n"
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        m = get_model(io.StringIO(par))
    assert m.UNITS.value == "TDB"  # converted on load
    with pytest.raises(ValueError, match="TCB"):
        get_model(io.StringIO(par), allow_tcb=False)


def test_jump_flags_to_params():
    """tim-file JUMP blocks (-tim_jump flags) become free JUMP
    parameters selecting exactly the blocked TOAs (reference:
    jump_flags_to_params)."""
    import io as _io

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.toa import get_TOAs

    par = ("PSR J0J0+0J0\nRAJ 5:00:00 1\nDECJ 5:00:00 1\nF0 99.0 1\n"
           "PEPOCH 55500\nDM 5.0\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(_io.StringIO(par))
        rng = np.random.default_rng(12)
        toas = make_fake_toas_uniform(55000, 56000, 30, model,
                                      error_us=1.0, add_noise=True,
                                      rng=rng)
    # write a tim with a JUMP block around the middle ten TOAs
    lines = ["FORMAT 1"]
    mjds = np.asarray(toas.get_mjds())
    for i in range(30):
        if i == 10:
            lines.append("JUMP")
        if i == 20:
            lines.append("JUMP")
        lines.append(f" fake{i} 1400.0 {mjds[i]:.12f} 1.0 @")
    tim = "\n".join(lines) + "\n"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t2 = get_TOAs(_io.StringIO(tim), model=model)
    tagged = [i for i, f in enumerate(t2.flags) if "tim_jump" in f]
    assert tagged == list(range(10, 20))
    new = model.jump_flags_to_params(t2)
    assert len(new) == 1
    assert not new[0].frozen
    comp = model.components["PhaseJump"]
    # idempotent: calling again adds nothing
    assert model.jump_flags_to_params(t2) == []
    # the new JUMP selects exactly the tagged TOAs
    mask = new[0].select_mask(t2)
    assert list(np.flatnonzero(mask)) == tagged


def test_introspection_helpers():
    """get_params_of_type / get_prefix_mapping / components_by_category
    (reference: TimingModel introspection API)."""
    import io as _io

    from pint_tpu.models import get_model

    par = ("PSR JINTRO\nRAJ 1:00:00 1\nDECJ 2:00:00 1\nF0 100 1\n"
           "F1 -1e-15 1\nPEPOCH 55000\nDM 10 1\n"
           "DMX_0001 1e-3 1\nDMXR1_0001 54000\nDMXR2_0001 54100\n"
           "DMX_0003 2e-3 1\nDMXR1_0003 54200\nDMXR2_0003 54300\n"
           "JUMP -grp a 1e-6 1\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(_io.StringIO(par))
    masks = m.get_params_of_type("maskParameter")
    assert "JUMP1" in masks
    dmx = m.get_prefix_mapping("DMX_")
    assert dmx == {1: "DMX_0001", 3: "DMX_0003"}
    fmap = m.get_prefix_mapping("F")
    assert fmap[0] == "F0" and fmap[1] == "F1"
    cats = m.components_by_category
    assert "Spindown" in cats["spindown"]
    assert any("Astrometry" in n for n in cats["astrometry"])


def test_d_phase_d_toa_matches_doppler():
    """Instantaneous topocentric frequency = F0 (1 + v.n/c) to first
    order: d_phase_d_toa (full-pipeline finite difference, reference:
    TimingModel.d_phase_d_toa) must reproduce the Doppler factor built
    independently from the batch velocities."""
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = ("PSR J1\nRAJ 06:00:00.0\nDECJ 20:00:00.0\nF0 310.0\n"
           "F1 -5e-16\nPEPOCH 55000\nDM 9.0\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(
            54000, 56000, 30, m, error_us=1.0, obs="gbt",
            rng=np.random.default_rng(0))
    f = m.d_phase_d_toa(toas)
    batch = m.get_cache(toas)["batch"]
    a0, d0 = np.radians(90.0), np.radians(20.0)
    n = np.array([np.cos(d0) * np.cos(a0), np.cos(d0) * np.sin(a0),
                  np.sin(d0)])
    vdotn = np.asarray(batch.ssb_obs_vel) @ n
    tdb = np.asarray(batch.tdb_day) + np.asarray(batch.tdb_frac.hi)
    dt = (tdb - 55000.0) * 86400.0
    expect = (310.0 + (-5e-16) * dt) * (1.0 + vdotn)
    np.testing.assert_allclose(f, expect, rtol=1e-6)
    # annual Doppler amplitude ~1e-4 relative is present
    assert np.ptp(f) / 310.0 > 5e-5


def test_d_phase_d_param_single_column(model_and_toas):
    """d_phase_d_param (reference API) returns exactly the matching
    designmatrix column (x F0: designmatrix is in seconds/unit)."""
    model, toas = model_and_toas
    M, names, _ = model.designmatrix(toas, incoffset=False)
    for p in ("F0", model.free_params[-1]):
        col = model.d_phase_d_param(toas, p)
        np.testing.assert_allclose(
            col / model.F0.value, M[:, names.index(p)],
            rtol=0, atol=1e-13 * max(1.0, np.max(np.abs(col))))
    with pytest.raises(ValueError):
        model.d_phase_d_param(toas, "DM999")
