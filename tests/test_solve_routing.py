"""Small-problem host routing (pint_tpu.config.solve_device): on an
accelerator backend, tiny solves pin to the host CPU — dispatch
latency (~0.1-0.25 s round-trip over the axon tunnel) dwarfs the
compute. Measured motivation: a 62-TOA WLS fit took 3.4 s over the
tunnel vs 6 ms on host (bench.py config 1, round 4)."""
import io
import warnings

import jax
import pytest

from pint_tpu.config import solve_device


def test_inert_on_cpu_backend():
    # the test env's default backend IS cpu: no routing ever
    assert jax.default_backend() == "cpu"
    assert solve_device(1) is None
    assert solve_device(10 ** 7) is None


@pytest.fixture
def fake_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    yield


def test_small_routes_to_host(fake_tpu, monkeypatch):
    monkeypatch.delenv("PINT_TPU_HOST_SOLVE_MAX_TOA", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    dev = solve_device(62)
    assert dev is not None and dev.platform == "cpu"
    assert solve_device(1024) is None  # at/above threshold


def test_tunnel_raises_threshold(fake_tpu, monkeypatch):
    monkeypatch.delenv("PINT_TPU_HOST_SOLVE_MAX_TOA", raising=False)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert solve_device(5000) is not None  # < 8192 tunnel default
    assert solve_device(8192) is None


def test_env_override(fake_tpu, monkeypatch):
    monkeypatch.setenv("PINT_TPU_HOST_SOLVE_MAX_TOA", "100")
    assert solve_device(99) is not None
    assert solve_device(100) is None
    monkeypatch.setenv("PINT_TPU_HOST_SOLVE_MAX_TOA", "0")
    assert solve_device(1) is None  # 0 disables routing


def test_auto_prefers_host_fitters_for_tiny_problems(monkeypatch):
    """Fitter.auto on a (faked) TPU backend: a tiny problem gets a
    host downhill fitter, a big one the device-resident fitter."""
    import numpy as np

    import pint_tpu.fitter as fitter_mod
    from pint_tpu.fitter import DownhillWLSFitter, Fitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = """
PSR J0000+0042
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 61.0 1
F1 -1e-15 1
DM 20.0
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        toas = make_fake_toas_uniform(
            54000, 56000, 40, model, error_us=1.0,
            rng=np.random.default_rng(7))
    # auto reads jax.default_backend inside fitter.py's module scope
    monkeypatch.setattr(fitter_mod.jax, "default_backend",
                        lambda: "tpu")
    monkeypatch.delenv("PINT_TPU_HOST_SOLVE_MAX_TOA", raising=False)
    fit = Fitter.auto(toas, model)
    assert isinstance(fit, DownhillWLSFitter)
    # the WLS fit still runs end-to-end with the CPU-pinned solve
    fit.fit_toas()
    assert fit.converged
    # ... and a big problem keeps the device-resident fitter: auto
    # must not lose the accelerator path to an over-eager threshold
    monkeypatch.setenv("PINT_TPU_HOST_SOLVE_MAX_TOA", "10")
    from pint_tpu.gls import DeviceDownhillGLSFitter

    assert model.supports_anchored()
    fit_big = Fitter.auto(toas, model)  # 40 TOAs >= threshold 10
    assert isinstance(fit_big, DeviceDownhillGLSFitter)
