"""The fit step's Sherman-Morrison ECORR segment path must agree with
the dense quantization-basis Woodbury solve (the reference's layout:
src/pint/models/noise_model.py EcorrNoise.ecorr_basis_weight_pair into
GLSFitter.fit_toas)."""

import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu.gls import _gls_kernel
from pint_tpu.models import get_model
from pint_tpu.parallel import build_fit_step
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_fromMJDs


@pytest.fixture(scope="module")
def ecorr_problem():
    par = [
        "PSR J0003+0003",
        "RAJ 09:00:00.0 1",
        "DECJ 15:00:00.0 1",
        "F0 150.0 1",
        "F1 -3e-15 1",
        "PEPOCH 55000",
        "POSEPOCH 55000",
        "DM 25.0 1",
        "DMEPOCH 55000",
        "TZRMJD 55000.1",
        "TZRSITE @",
        "TZRFRQ 1400",
        "UNITS TDB",
        "EFAC -be X 1.2",
        "ECORR -be X 1.5",
        "TNREDAMP -13.2",
        "TNREDGAM 2.5",
        "TNREDC 6",
    ]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par) + "\n"))
        rng = np.random.default_rng(11)
        # 30 clusters of 3 TOAs -> 30 real ECORR epochs; two bands
        centers = np.linspace(54001, 55999, 30)
        offsets = np.array([0.0, 0.01, 0.02])
        mjds = (centers[:, None] + offsets[None, :]).ravel()
        freqs = np.tile([1400.0, 820.0, 1400.0], 30)
        toas = make_fake_toas_fromMJDs(mjds, model, error_us=1.0,
                                       freq_mhz=freqs, add_noise=True,
                                       rng=rng)
        for f in toas.flags:
            f["be"] = "X"
    return model, toas


def test_segments_extracted(ecorr_problem):
    model, toas = ecorr_problem
    seg = model.noise_model_ecorr_segments(toas)
    assert seg is not None
    eid, jvar, consumed = seg
    assert consumed == ("EcorrNoise",)
    assert eid.shape == (toas.ntoas,)
    assert jvar.shape == (31,)  # 30 epochs + the 'no epoch' slot
    assert jvar[-1] == 0.0
    assert np.all(eid < 31)
    # every TOA is in a real epoch here and jvar = (1.5us)^2
    assert np.all(eid < 30)
    np.testing.assert_allclose(jvar[:30], (1.5e-6) ** 2)


def test_segment_path_matches_dense(ecorr_problem):
    model, toas = ecorr_problem
    step_fn, args, names = build_fit_step(model, toas)
    dp_seg, cov_seg, chi2_seg, r_seg = jax.jit(step_fn)(*args)

    # dense reference: full stacked basis (ECORR quantization included)
    r = Residuals(toas, model).time_resids
    M, names_d, _ = model.designmatrix(toas, incoffset=True)
    nvec = model.scaled_toa_uncertainty(toas) ** 2
    F = model.noise_model_designmatrix(toas)
    phi = model.noise_model_basis_weight(toas)
    assert F.shape[1] == 30 + 12  # dense path: ECORR cols + 2*TNREDC
    x, cov, chi2, noise, xfull, ok = _gls_kernel(
        jnp.asarray(M), jnp.asarray(F), jnp.asarray(phi),
        jnp.asarray(r), jnp.asarray(nvec))
    assert bool(ok)
    assert names == names_d
    np.testing.assert_allclose(np.asarray(dp_seg), -np.asarray(x),
                               rtol=1e-6, atol=1e-16)
    np.testing.assert_allclose(np.asarray(cov_seg), np.asarray(cov),
                               rtol=1e-5, atol=1e-30)


def test_segment_chi2_matches_marginalized(ecorr_problem):
    """The step's chi2 equals the GLS-marginalized chi2 of the current
    residuals (Residuals.chi2 goes through the dense basis)."""
    model, toas = ecorr_problem
    step_fn, args, names = build_fit_step(model, toas)
    _, _, chi2_seg, _ = jax.jit(step_fn)(*args)
    chi2_dense = Residuals(toas, model).chi2
    assert float(chi2_seg) == pytest.approx(chi2_dense, rel=1e-8)


def test_f32_matmul_path_agrees(ecorr_problem):
    """The f32-MXU normal-equation path (auto-enabled on TPU, where
    f64 matmuls are software-emulated) must agree with the f64 path to
    well below a parameter sigma."""
    model, toas = ecorr_problem
    step64, args64, names = build_fit_step(model, toas,
                                           matmul_f32=False)
    step32, args32, _ = build_fit_step(model, toas, matmul_f32=True)
    dp64, cov64, chi264, _ = jax.jit(step64)(*args64)
    dp32, cov32, chi232, _ = jax.jit(step32)(*args32)
    sigma = np.sqrt(np.diag(np.asarray(cov64)))
    # parameter steps agree to <1e-4 sigma
    np.testing.assert_array_less(
        np.abs(np.asarray(dp32) - np.asarray(dp64)), 1e-4 * sigma)
    # uncertainties agree to 0.1%
    np.testing.assert_allclose(np.sqrt(np.diag(np.asarray(cov32))),
                               sigma, rtol=1e-3)
    assert float(chi232) == pytest.approx(float(chi264), rel=1e-4)
