"""Energy-dependent template tests (reference behaviors:
src/pint/templates/lceprimitives.py slope parameterization)."""

import numpy as np
import pytest

from pint_tpu.templates import LCGaussian, LCTemplate, make_template
from pint_tpu.templates.energy import LCEnergyFitter, LCEnergyTemplate


def test_pdf_normalized_at_each_energy():
    base = make_template([("gaussian", 0.5, 0.3, 0.04),
                          ("vonmises", 0.2, 0.7, 0.05)])
    et = LCEnergyTemplate(base, e0_kev=1.0,
                          dloc=[0.05, -0.02], dlogw=[0.3, 0.0],
                          dlogits=[0.0, 0.4, -0.2])
    grid = np.linspace(0, 1, 10001)[:-1]
    for e in (0.3, 1.0, 5.0):
        vals = et(grid, np.full(grid.shape, e))
        assert np.mean(vals) == pytest.approx(1.0, rel=1e-3), e


def test_base_template_matches_at_e0():
    base = make_template([("gaussian", 0.6, 0.25, 0.03)])
    et = LCEnergyTemplate(base, e0_kev=2.0, dloc=[0.1], dlogw=[0.5])
    grid = np.linspace(0, 1, 501)
    np.testing.assert_allclose(
        et(grid, np.full(grid.shape, 2.0)), base(grid), rtol=1e-10)
    bt = et.base_template()
    np.testing.assert_allclose(bt(grid), base(grid), rtol=1e-10)


def test_peak_moves_with_energy():
    base = make_template([("gaussian", 0.8, 0.4, 0.03)])
    et = LCEnergyTemplate(base, e0_kev=1.0, dloc=[0.1])
    grid = np.linspace(0, 1, 4001)[:-1]
    lo = grid[np.argmax(et(grid, np.full(grid.shape, 0.1)))]
    hi = grid[np.argmax(et(grid, np.full(grid.shape, 10.0)))]
    assert lo == pytest.approx(0.3, abs=0.005)   # x = -1 decade
    assert hi == pytest.approx(0.5, abs=0.005)   # x = +1 decade


def test_energy_fit_recovers_slope():
    rng = np.random.default_rng(31)
    truth = LCEnergyTemplate(
        make_template([("gaussian", 0.7, 0.35, 0.03)]),
        e0_kev=1.0, dloc=[0.08])
    n = 15000
    energies = 10.0 ** rng.uniform(-1, 1, n)  # 0.1..10 keV
    phases = truth.random(n, energies, rng=rng)
    fit = LCEnergyTemplate(
        make_template([("gaussian", 0.5, 0.38, 0.05)]), e0_kev=1.0)
    f = LCEnergyFitter(fit, phases, energies)
    res = f.fit()
    assert res["success"]
    m = fit.m
    dloc = float(fit.theta[4 * m + 2])
    loc0 = float(np.mod(fit.theta[m + 1], 1.0))
    assert loc0 == pytest.approx(0.35, abs=0.01)
    assert dloc == pytest.approx(0.08, abs=0.02)


def test_rejects_multishape_primitives():
    t = make_template([("gaussian2", 0.5, 0.4, [0.02, 0.05])])
    with pytest.raises(ValueError):
        LCEnergyTemplate(t)
