"""graftlint rule fixtures (a seeded positive AND a clean negative per
rule G1-G8) plus the repo-clean gate: the live tree must lint clean,
which is what makes every CLAUDE.md convention a failing test instead
of a code-review hope. Run standalone with `pytest -m lint`."""

import os
import subprocess
import sys
import textwrap
import types

import pytest

from pint_tpu.analysis import graftlint as gl

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_py(src, relpath="pint_tpu/models/_fixture.py"):
    """Run the per-module AST rules on one snippet."""
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    seeds = gl.collect_jit_seed_names([m])
    gl.mark_jit_regions(m, seeds[relpath])
    out = []
    out += gl.check_g1(m)
    out += gl.check_g2(m)
    out += gl.check_g6_python(m)
    out += gl.check_g7(m)
    out += gl.check_g8(m)
    graph = gl.ClassGraph([m])
    out += gl.check_g3(graph)
    out += gl.check_g4_static(graph)
    out += gl.check_g5_static(graph)
    return out


def _rules(violations):
    return sorted({v.rule for v in violations})


# ------------------------------------------------------------------ G1

def test_g1_flags_coercion_in_compute_path():
    v = _lint_py("""
        class Thing(Component):
            def delay(self, pv, batch, cache, ctx, delay_so_far):
                return float(pv["DM"].hi)
    """)
    assert "G1" in _rules(v)


def test_g1_flags_item_in_jitted_closure():
    v = _lint_py("""
        import jax
        def build():
            def fn(x):
                return x.item()
            return jax.jit(fn)
    """)
    assert "G1" in _rules(v)


def test_g1_clean_on_host_code_and_host_attrs():
    v = _lint_py("""
        class Thing(Component):
            def prepare(self, toas, batch, cache, prefix=""):
                return float(toas.ntoas)  # host method: fine
            def delay(self, pv, batch, cache, ctx, delay_so_far):
                x = float(self.DM.value or 0.0)  # host .value: fine
                return x
    """)
    assert "G1" not in _rules(v)


def test_g1_propagates_through_self_calls():
    v = _lint_py("""
        class Thing(Component):
            def helper(self, x):
                return int(x)
            def phase(self, pv, batch, cache, ctx, tb):
                return self.helper(pv["F0"].hi)
    """)
    assert "G1" in _rules(v)


# ------------------------------------------------------------------ G2

def test_g2_flags_numpy_in_traced_models_code():
    v = _lint_py("""
        import numpy as np
        class Thing(Component):
            def delay(self, pv, batch, cache, ctx, delay_so_far):
                return np.clip(pv["DM"].hi, 0, 1)
    """)
    assert "G2" in _rules(v)


def test_g2_ignores_host_paths_and_other_packages():
    clean_host = _lint_py("""
        import numpy as np
        class Thing(Component):
            def prepare(self, toas, batch, cache, prefix=""):
                cache["mask"] = np.zeros(3)
    """)
    assert "G2" not in _rules(clean_host)
    outside_models = _lint_py("""
        import numpy as np
        def fn(x):
            return np.sin(x)
        import jax
        g = jax.jit(fn)
    """, relpath="pint_tpu/serve/_fixture.py")
    assert "G2" not in _rules(outside_models)


# ------------------------------------------------------------------ G3

def test_g3_flags_missing_citation():
    v = _lint_py("""
        class Thing(Component):
            '''A component with no citation at all.'''
    """)
    assert "G3" in _rules(v)


def test_g3_accepts_citation_and_skips_unregistered():
    cited = _lint_py("""
        class Thing(Component):
            '''Does things (reference: src/pint/models/thing.py).'''
    """)
    assert "G3" not in _rules(cited)
    unregistered = _lint_py("""
        class Thing(Component):
            '''No citation.'''
            register = False
    """)
    assert "G3" not in _rules(unregistered)


# ------------------------------------------------------ G4 (static)

def test_g4_static_flags_missing_spec():
    v = _lint_py("""
        class Thing(Component):
            '''Reference: somewhere.'''
            def __init__(self):
                self.add_param(floatParameter("X", units="s"))
    """)
    assert "G4" in _rules(v)


def test_g4_static_accepts_defined_or_inherited_spec():
    own = _lint_py("""
        class Thing(Component):
            '''Reference: somewhere.'''
            def __init__(self):
                self.add_param(floatParameter("X", units="s"))
            def param_dimensions(self):
                return {"X": None}
    """)
    assert "G4" not in _rules(own)
    inherited = _lint_py("""
        class Base(Component):
            register = False
            def param_dimensions(self):
                return {"X": None}
        class Thing(Base):
            '''Reference: somewhere.'''
            def __init__(self):
                self.add_param(floatParameter("X", units="s"))
    """)
    assert "G4" not in _rules(inherited)


# ----------------------------------------------------- G4 (dynamic)

def test_g4_dynamic_flags_uncovered_param():
    from pint_tpu.models.parameter import floatParameter
    from pint_tpu.models.timing_model import PhaseComponent

    class _G4Missing(PhaseComponent):
        register = False

        def __init__(self):
            super().__init__()
            self.add_param(floatParameter("BOGUS", units="s"))

    assert gl.check_g4_dynamic({"_G4Missing": _G4Missing})


def test_g4_dynamic_accepts_covered_param():
    from pint_tpu.models.parameter import floatParameter
    from pint_tpu.models.timing_model import PhaseComponent
    from pint_tpu.units import parse_unit

    class _G4Covered(PhaseComponent):
        register = False

        def __init__(self):
            super().__init__()
            self.add_param(floatParameter("OK", units="s"))

        def param_dimensions(self):
            return {"OK": parse_unit("s")}

    assert not gl.check_g4_dynamic({"_G4Covered": _G4Covered})


# ------------------------------------------------------------------ G5

def test_g5_static_flags_unpaired_hooks():
    v = _lint_py("""
        class Thing(Component):
            '''Reference: somewhere.'''
            def linear_design_names(self):
                return ["X"]
    """)
    assert "G5" in _rules(v)
    paired = _lint_py("""
        class Thing(Component):
            '''Reference: somewhere.'''
            def linear_design_names(self):
                return ["X"]
            def linear_design_local(self, pv, batch, cache, ctx):
                return {}
    """)
    assert "G5" not in _rules(paired)


def test_g5_dynamic_flags_component_absent_from_sink():
    from pint_tpu.models.timing_model import PhaseComponent

    class _Claimy(PhaseComponent):
        register = False

        def linear_design_names(self):
            return ["X"]

        def linear_design_local(self, pv, batch, cache, ctx):
            return {}

    stub_model = types.SimpleNamespace(components={}, free_params=[])
    v = gl.check_g5_dynamic({"_Claimy": _Claimy}, stub_model)
    assert v and v[0].rule == "G5"


# ------------------------------------------------------------------ G6

def test_g6_flags_unbounded_subprocess_and_backend_touch():
    v = _lint_py("""
        import subprocess, jax
        def go():
            subprocess.run(["python", "x.py"])
            return jax.devices()
    """, relpath="tools/_fixture.py")
    assert [x.rule for x in v].count("G6") == 2
    bounded = _lint_py("""
        import subprocess, jax
        def go():
            if not accelerator_responsive(240.0):
                return None
            subprocess.run(["python", "x.py"], timeout=60)
            return jax.devices()
    """, relpath="tools/_fixture.py")
    assert "G6" not in _rules(bounded)


def test_g6_flags_popen_and_from_import_forms():
    popen = _lint_py("""
        import subprocess
        def go():
            return subprocess.Popen(["python", "x.py"]).wait()
    """, relpath="tools/_fixture.py")
    assert "G6" in _rules(popen)
    aliased = _lint_py("""
        from subprocess import run as launch
        def go():
            launch(["python", "x.py"])
    """, relpath="tools/_fixture.py")
    assert "G6" in _rules(aliased)
    aliased_ok = _lint_py("""
        from subprocess import run
        def go():
            run(["python", "x.py"], timeout=60)
    """, relpath="tools/_fixture.py")
    assert "G6" not in _rules(aliased_ok)


def test_g6_ignores_paths_outside_tools_and_scripts():
    v = _lint_py("""
        import subprocess
        subprocess.run(["ls"])
    """, relpath="pint_tpu/models/_fixture.py")
    assert "G6" not in _rules(v)


def _lint_dispatch(src, relpath="pint_tpu/serve/_fixture.py"):
    """Run only the dispatch-layer half of G6 on one snippet."""
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    per, priv = gl.collect_jit_products([m])
    return gl.check_g6_dispatch(m, per[relpath] | priv)


def test_g6_covers_new_serve_modules():
    """ISSUE-8 satellite: the dispatch half of G6 applies to the new
    serve modules (admission/router/journal) — a direct jit-product
    call there is a lint error, same as the rest of the serve layer.
    """
    for mod in ("admission", "router", "journal"):
        rel = f"pint_tpu/serve/{mod}.py"
        assert gl._g6_dispatch_applies(rel), rel
        v = _lint_dispatch("""
            import jax
            primer = jax.jit(lambda x: x + 1)
            def prime(x):
                return primer(x)
        """, relpath=rel)
        assert [x.rule for x in v] == ["G6"], rel


def test_g6_covers_sampling_package():
    """ISSUE-9 satellite: the dispatch half of G6 is pinned over the
    posterior-sampling package — a direct jit-product call there must
    lint (every chain dispatch routes through the supervisor)."""
    for mod in ("kernel", "chain", "likelihood", "posterior",
                "serve_kernel"):
        rel = f"pint_tpu/sampling/{mod}.py"
        assert gl._g6_dispatch_applies(rel), rel
        v = _lint_dispatch("""
            import jax
            chunk = jax.jit(lambda x: x + 1)
            def run_chain(x):
                return chunk(x)
        """, relpath=rel)
        assert [x.rule for x in v] == ["G6"], rel


def test_g6_covers_pta_package():
    """ISSUE-17 satellite: the dispatch half of G6 is pinned over the
    array-likelihood plane (``pint_tpu/pta/``) — a direct call of a
    jit product there must lint, and a ``compile_with_plan(...)``
    product (the sharded plan IS a jitted executable) flags exactly
    the same way."""
    for mod in ("gwb", "shard", "metrics"):
        rel = f"pint_tpu/pta/{mod}.py"
        assert gl._g6_dispatch_applies(rel), rel
    v = _lint_dispatch("""
        import jax
        kernel = jax.jit(lambda x: x + 1)
        def sweep(x):
            return kernel(x)
    """, relpath="pint_tpu/pta/gwb.py")
    assert [x.rule for x in v] == ["G6"]
    v = _lint_dispatch("""
        from pint_tpu.pta.shard import compile_with_plan
        planned = compile_with_plan(lambda x: x, name="k",
                                    ndims_in=(2,), ndims_out=(2,))
        def sweep(x):
            return planned(x)
    """, relpath="pint_tpu/pta/gwb.py")
    assert [x.rule for x in v] == ["G6"]


def test_g6_dispatch_flags_direct_jit_product_call():
    v = _lint_dispatch("""
        import jax
        kernel = jax.jit(lambda x: x + 1)
        def solve(x):
            return kernel(x)
    """)
    assert [x.rule for x in v] == ["G6"]
    assert "DispatchSupervisor" in v[0].msg


def test_g6_dispatch_flags_self_attr_and_immediate_forms():
    v = _lint_dispatch("""
        import jax
        class Cache:
            def __init__(self, f):
                self._k = jax.jit(f)
            def run(self, x):
                return self._k(x)
        def quick(g, x):
            return jax.jit(g)(x)
    """)
    assert [x.rule for x in v] == ["G6", "G6"]


def test_g6_dispatch_flags_attribute_chain_calls():
    """Reaching a jit product through ANY attribute chain (not just
    self.) still bypasses the supervisor and must flag."""
    v = _lint_dispatch("""
        import jax
        class Cache:
            def __init__(self, f):
                self._k = jax.jit(f)
        def sneaky(engine, x):
            return engine.cache._k(x)
    """)
    assert [x.rule for x in v] == ["G6"]


def test_g6_dispatch_supervised_route_is_clean():
    """Passing the jit product as an ARGUMENT to the supervisor is
    the sanctioned route — never flagged; a decorated kernel passed
    the same way is clean too."""
    v = _lint_dispatch("""
        import jax
        from functools import partial

        kernel = jax.jit(lambda x: x + 1)

        @partial(jax.jit, static_argnames=("flag",))
        def decorated(x, flag=False):
            return x

        def solve(sup, x):
            a = sup.dispatch(kernel, x, key="k")
            b = sup.dispatch(decorated, x, kw={"flag": True},
                             key="d")
            return a, b
    """)
    assert not v


def test_g6_dispatch_flags_decorated_kernel_direct_call():
    v = _lint_dispatch("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("f32mm",))
        def _kern(x, f32mm=False):
            return x

        def solve(x):
            return _kern(x, f32mm=True)
    """, relpath="pint_tpu/gls.py")
    assert [x.rule for x in v] == ["G6"]


def test_g6_dispatch_only_applies_to_the_dispatch_layer():
    src = """
        import jax
        kernel = jax.jit(lambda x: x + 1)
        def solve(x):
            return kernel(x)
    """
    assert _lint_dispatch(src, relpath="pint_tpu/gridutils.py") == []
    assert _lint_dispatch(
        src, relpath="pint_tpu/runtime/supervisor.py") == []
    assert _lint_dispatch(
        src, relpath="pint_tpu/parallel/pta.py") != []


def test_g6_shell_requires_timeout_and_joins_continuations():
    bad = gl.check_g6_shell("tools/x.sh", "python tools/capture.py\n")
    assert bad and bad[0].rule == "G6"
    ok = gl.check_g6_shell(
        "tools/x.sh", 'timeout 60 python tools/capture.py\n')
    assert not ok
    continued = gl.check_g6_shell(
        "tools/x.sh", 'timeout "$T" \\\n    python tools/capture.py\n')
    assert not continued


# ------------------------------------------------------------------ G7

def test_g7_flags_config_update_outside_entry_points():
    v = _lint_py("""
        import jax
        jax.config.update("jax_enable_x64", True)
    """)
    assert "G7" in _rules(v)
    sanctioned = _lint_py("""
        import jax
        jax.config.update("jax_enable_x64", True)
    """, relpath="pint_tpu/config.py")
    assert "G7" not in _rules(sanctioned)


def test_g7_catches_from_import_form():
    v = _lint_py("""
        from jax import config
        config.update("jax_enable_x64", False)
    """)
    assert "G7" in _rules(v)
    other_config = _lint_py("""
        from myapp import config
        config.update("verbose", True)
    """)
    assert "G7" not in _rules(other_config)


# ------------------------------------------------------------------ G8

def test_g8_flags_lru_cache_on_method():
    v = _lint_py("""
        import functools
        class Thing:
            @functools.lru_cache(maxsize=8)
            def basis(self, arr):
                return arr
    """)
    assert "G8" in _rules(v)


def test_g8_allows_module_level_lru_cache():
    v = _lint_py("""
        import functools
        @functools.lru_cache()
        def table(n: int):
            return list(range(n))
    """)
    assert "G8" not in _rules(v)


# ------------------------------------------------- suppression layer

def test_pragma_suppresses_only_matching_rule():
    src = ("class Thing(Component):\n"
           "    def delay(self, pv, batch, cache, ctx, d):\n"
           "        return float(pv['DM'].hi)"
           "  # graftlint: allow G1 -- fixture\n")
    report = gl.LintReport(violations=_lint_py(src))
    assert any(v.rule == "G1" for v in report.violations)
    gl.apply_suppressions(
        report, [], {"pint_tpu/models/_fixture.py": src})
    assert not [v for v in report.violations if v.rule == "G1"]
    assert report.suppressed


def test_allowlist_suppresses_and_stale_entries_fail():
    src = ("class Thing(Component):\n"
           "    def delay(self, pv, batch, cache, ctx, d):\n"
           "        return float(pv['DM'].hi)\n")
    report = gl.LintReport(violations=_lint_py(src))
    allow = [dict(rule="G1", file="pint_tpu/models/_fixture.py",
                  match="float(pv['DM'].hi)", why="fixture")]
    gl.apply_suppressions(
        report, allow, {"pint_tpu/models/_fixture.py": src})
    assert not [v for v in report.violations if v.rule == "G1"]
    # a stale entry (matches nothing) must itself be a violation
    report2 = gl.LintReport()
    gl.apply_suppressions(
        report2, [dict(rule="G1", file="nope.py", match="zzz",
                       why="stale")], {})
    assert [v for v in report2.violations if v.rule == "ALLOWLIST"]


def test_allowlist_entry_suppresses_at_most_max_hits():
    """One reviewed justification must not swallow a SECOND, future
    violation that merely shares the substring."""
    mk = lambda line: gl.Violation("G7", "tools/x.py", line,
                                   "jax.config.update() outside ...")
    report = gl.LintReport(violations=[mk(5), mk(50)])
    allow = [dict(rule="G7", file="tools/x.py",
                  match="jax.config.update", why="entry point")]
    gl.apply_suppressions(report, allow, {})
    assert len(report.suppressed) == 1
    assert [v.line for v in report.violations] == [50]


# ------------------------------------------------------ repo gates

def test_repo_clean():
    """THE gate: the live tree lints clean (G1-G8, dynamic checks,
    allowlist with no stale entries). Every future PR inherits the
    conventions as a tier-1 failure instead of a review comment."""
    report = gl.run_lint(REPO)
    assert report.clean, "\n".join(v.format() for v in report.violations)
    assert report.files_scanned > 50


# ----------------------------------------------------------- G12


def _lint_g12(src, relpath="pint_tpu/serve/_fixture.py"):
    """Run only the span-context rule on one snippet."""
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    return gl.check_g12(m)


def test_g12_flags_naked_supervised_dispatch():
    v = _lint_g12("""
        from pint_tpu.runtime import get_supervisor
        def naked(fn):
            return get_supervisor().dispatch(fn, key="x")
    """)
    assert [x.rule for x in v] == ["G12"]
    assert "span context" in v[0].msg


def test_g12_clean_under_with_span_and_attach():
    v = _lint_g12("""
        from pint_tpu import obs
        def wrapped(sup, fn):
            with obs.span("fit"):
                return sup.dispatch(fn, key="x")
        def worker(sup, fn, ctx):
            with obs.attach(ctx):
                return sup.dispatch_async(fn, key="y")
    """)
    assert not v


def test_g12_span_context_propagates_to_callees_and_closures():
    """The fit_toas -> _fit_device pattern (the span opened one
    frame up, same module) and the _issue-closure pattern (the
    dispatch deferred into a collect closure built inside a
    span-bearing function) are both compliant — the same
    approximation class as G10's frozen-guard check."""
    v = _lint_g12("""
        from pint_tpu import obs
        class Fitter:
            def fit_toas(self):
                with obs.span("fit.device"):
                    return self._fit_device()
            def _fit_device(self):
                sup = self.supervisor
                return sup.dispatch(lambda: 1, key="k")
        def build(self):
            with obs.span("issue"):
                fut = self.supervisor.dispatch_async(lambda: 1)
            def collect():
                return self.supervisor.dispatch(lambda: 2)
            return collect
    """)
    assert not v


def test_g12_flags_async_issue_without_context():
    v = _lint_g12("""
        def issue(self, fn):
            return self.supervisor.dispatch_async(fn, key="x")
    """)
    assert [x.rule for x in v] == ["G12"]


def test_g12_ignores_non_supervisor_dispatch_and_other_layers():
    """An unrelated .dispatch() method (an event bus, say) never
    flags, and the rule only applies to the dispatch layer — the
    runtime package itself is exempt by construction."""
    v = _lint_g12("""
        def route(bus, msg):
            return bus.dispatch(msg)
    """)
    assert not v
    v = _lint_g12("""
        def naked(self, fn):
            return self.supervisor.dispatch(fn)
    """, relpath="pint_tpu/runtime/_fixture.py")
    assert not v
    v = _lint_g12("""
        def naked(self, fn):
            return self.supervisor.dispatch(fn)
    """, relpath="pint_tpu/pintk/_fixture.py")
    assert not v


def test_g12_pragma_suppression_works():
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", textwrap.dedent("""
        def naked(sup, fn):
            return sup.dispatch(fn, key="x")  # graftlint: allow G12 -- fixture: context established by the only caller
    """))
    report = gl.LintReport(violations=gl.check_g12(m))
    gl.apply_suppressions(report, [],
                         {"pint_tpu/serve/_fixture.py": m.src})
    assert report.clean
    assert len(report.suppressed) == 1


def test_every_rule_is_documented():
    """The rule table in ARCHITECTURE.md must cover every implemented
    rule id (doc drift check)."""
    arch = open(os.path.join(REPO, "ARCHITECTURE.md")).read()
    for rid in gl.RULES:
        assert rid in arch, f"rule {rid} missing from ARCHITECTURE.md"


@pytest.mark.slow
def test_cli_exit_code():
    """`python -m pint_tpu.analysis.graftlint` exits 0 on the repo
    (subprocess: the exact invocation CI/humans run)."""
    # strip the axon vars too (as tests/test_examples.py does): a
    # wedged tunnel must not be able to hang the subprocess either
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("PALLAS_AXON")}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "pint_tpu.analysis.graftlint",
         "--root", REPO],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# ----------------------------------------------------------- G13


def _lint_g13(src, relpath="pint_tpu/serve/_fixture.py"):
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    return gl.check_g13(m)


def test_g13_flags_attr_and_dict_counter_increments():
    v = _lint_g13("""
    def submit(self, req):
        self.metrics.submitted += 1
        self.admission.shed_quota += 1
        self.counters["shed"] += 1
        self.timeouts = self.timeouts + 1
        self.tally["shed_deadline"] = self.tally.get(
            "shed_deadline", 0) + 1
    """)
    assert [x.rule for x in v] == ["G13"] * 5


def test_g13_clean_on_registry_children_and_non_counters():
    assert _lint_g13("""
    def submit(self, req):
        self._c["submitted"].inc()
        self.metrics.bump("completed", 3)
        self._nqueued += 1            # queue gauge, not a counter
        self.inflight_rows += rows    # backlog gauge
        done += 1                     # plain local tally
        self.wall_s += dt             # not counter-named
    """) == []


def test_g13_fresh_assignment_is_not_an_increment():
    # assigning a SUM of other things is not an increment of the
    # counter itself
    assert _lint_g13("""
    def snapshot(self):
        self.requests = a.requests + b.requests
        out["submitted"] = x + 1
    """) == []


def test_g13_only_applies_to_the_dispatch_layer():
    src = """
    def bump(self):
        self.timeouts += 1
    """
    assert _lint_g13(src, relpath="pint_tpu/serve/_f.py")
    assert _lint_g13(src, relpath="pint_tpu/parallel/_f.py")
    assert not _lint_g13(src, relpath="pint_tpu/runtime/_f.py")
    assert not _lint_g13(src, relpath="pint_tpu/obs/_f.py")
    assert not _lint_g13(src, relpath="pint_tpu/pintk/_f.py")


def test_g13_pragma_suppression_works():
    src = ("def f(self):\n"
           "    self.timeouts += 1"
           "  # graftlint: allow G13 -- fixture: local tally\n")
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", src)
    report = gl.LintReport(violations=gl.check_g13(m))
    gl.apply_suppressions(
        report, [], {"pint_tpu/serve/_fixture.py": src})
    assert report.violations == []
    assert len(report.suppressed) == 1


# ----------------------------------------------------------- G14


def _lint_g14(src, relpath="pint_tpu/serve/_fixture.py",
              seeds=None):
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    gl.mark_jit_regions(m, seeds or set())
    return gl.check_g14(m)


def test_g14_flags_stray_health_metric_outside_health_module():
    src = """
    def collect(self):
        om.counter("pint_tpu_health_incidents_total", "x").inc()
        om.gauge("pint_tpu_health_last_value", "x").set(1.0)
    """
    v = _lint_g14(src)
    assert [x.rule for x in v] == ["G14"] * 2
    # the health module itself is the ONE sanctioned home — its
    # siblings in obs/ are NOT (a stray health metric in metrics.py
    # would fork the vocabulary just the same)
    assert _lint_g14(src, relpath="pint_tpu/obs/health.py") == []
    assert _lint_g14(src, relpath="pint_tpu/obs/metrics.py")
    # non-health metrics are not G14's business
    assert _lint_g14("""
    def collect(self):
        om.counter("pint_tpu_serve_shed_total", "x").inc()
    """) == []


def test_g14_flags_hv_read_without_observe():
    v = _lint_g14("""
    def finish(self, out):
        hv = out[4]
        if hv[0] > 0:
            self.fail()
    """)
    assert [x.rule for x in v] == ["G14"]


def test_g14_clean_when_observe_consumes_the_vector():
    assert _lint_g14("""
    def finish(self, out):
        hv = out[4]
        monitor.observe("fit.device", {"hv": hv})
    """) == []
    # the "hv" signal key alone also marks a tap — and is satisfied
    # by the same-function observe
    assert _lint_g14("""
    def finish(self, out):
        sig = {"hv": out[4]}
        monitor.observe("fit.device", sig)
    """) == []


def test_g14_ancestor_closure_observe_covers_nested_reader():
    # the streaming-accumulate pattern: the dispatch closure unpacks
    # the vector, the BUILDER observes it
    assert _lint_g14("""
    def accumulate(self):
        def run():
            st, hv = kernel()
            return st, hv
        st, hv = dispatch(run)
        monitor.observe("stream.chunk", {"hv": hv})
    """) == []


def test_g14_producer_kernels_are_exempt():
    # the in-trace PRODUCER side (a jitted kernel building hv)
    # cannot call observe — jit-reachable functions are exempt
    assert _lint_g14("""
    @jax.jit
    def step_fn(th):
        hv = jnp.stack([jnp.sum(th)])
        return th, hv
    """, relpath="pint_tpu/parallel/_fixture.py") == []


def test_g14_only_applies_where_it_should():
    src = """
    def finish(self, out):
        hv = out[4]
        return hv
    """
    assert _lint_g14(src, relpath="pint_tpu/parallel/_f.py")
    # runtime/ is the supervisor itself; models/ is not the
    # dispatch layer — neither is in half (b)'s scope
    assert not _lint_g14(src, relpath="pint_tpu/runtime/_f.py")
    assert not _lint_g14(src, relpath="pint_tpu/models/_f.py")


def test_g14_pragma_suppression_works():
    # the violation anchors at the def line (the function is the
    # unit of the rule), so that is where the pragma goes
    src = ("def f(self, out):"
           "  # graftlint: allow G14 -- fixture: consumed upstream\n"
           "    hv = out[4]\n")
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", src)
    gl.mark_jit_regions(m, set())
    report = gl.LintReport(violations=gl.check_g14(m))
    gl.apply_suppressions(
        report, [], {"pint_tpu/serve/_fixture.py": src})
    assert report.violations == []
    assert len(report.suppressed) == 1


def test_g13_vocabulary_covers_the_health_counters():
    # ISSUE 14 satellite: the new counter names are protected
    for name in ("health_incidents", "shadow_replays",
                 "shadow_drift_exceeded", "cg_budget_exhausted"):
        assert name in gl.G13_COUNTER_NAMES, name
    v = _lint_g13("""
    def note(self):
        self.health_incidents += 1
        self.stats["shadow_replays"] += 1
    """)
    assert [x.rule for x in v] == ["G13"] * 2


# ----------------------------------------------------------- G15


def _lint_g15(src, relpath="pint_tpu/serve/_fixture.py"):
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    return gl.check_g15(m)


def test_g15_flags_raw_profiler_trace_control():
    v = _lint_g15("""
    def capture(self):
        jax.profiler.start_trace("/tmp/x")
        self.work()
        jax.profiler.stop_trace()
    """)
    assert [x.rule for x in v] == ["G15"] * 2
    # TraceAnnotation (the annotate() region marker) is NOT trace
    # control — only start/stop windows are G15's business
    assert _lint_g15("""
    def region(self):
        with jax.profiler.TraceAnnotation("x"):
            pass
    """) == []


def test_g15_flags_cost_probe_patterns():
    v = _lint_g15("""
    def probe(self, jitted, args):
        c = jitted.lower(*args).compile()
        ca = compiled.cost_analysis()
        ma = compiled.memory_analysis()
    """)
    assert [x.rule for x in v] == ["G15"] * 3
    # a plain .compile() (no .lower() receiver) is not the probe
    # pattern — re.compile, sre patterns, etc. must never flag
    assert _lint_g15("""
    def other(self):
        pat = re.compile("x")
        low = text.lower()
    """) == []


def test_g15_sanctioned_files_are_exempt():
    src = """
    def capture(self):
        jax.profiler.start_trace("/tmp/x")
        jax.profiler.stop_trace()
        c = jitted.lower(*args).compile().cost_analysis()
    """
    assert _lint_g15(src, relpath="pint_tpu/obs/perf.py") == []
    assert _lint_g15(src, relpath="pint_tpu/profiling.py") == []
    # everywhere else — including obs/ siblings and the dispatch
    # dirs — the rule is pinned
    assert _lint_g15(src, relpath="pint_tpu/obs/metrics.py")
    assert _lint_g15(src, relpath="pint_tpu/parallel/_f.py")
    assert _lint_g15(src, relpath="tools/_f.py")


def test_g15_pragma_suppression_works():
    src = ("def f(self):\n"
           "    jax.profiler.start_trace('/tmp/x')  "
           "# graftlint: allow G15 -- fixture: scripted capture\n")
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", src)
    report = gl.LintReport(violations=gl.check_g15(m))
    gl.apply_suppressions(
        report, [], {"pint_tpu/serve/_fixture.py": src})
    assert report.violations == []
    assert len(report.suppressed) == 1


# ----------------------------------------------------------- G16


def _lint_g16(src, relpath="pint_tpu/serve/_fixture.py", hits=None):
    from pint_tpu.analysis import concurrency as conc
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    return conc.check_g16(m, {} if hits is None else hits)


def test_g16_flags_raw_threading_primitives():
    v = _lint_g16("""
    import threading
    from threading import RLock

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._rl = RLock()
            self._cv = threading.Condition(self._lock)
    """)
    assert [x.rule for x in v] == ["G16"] * 3
    assert "make_lock" in v[0].msg
    assert "make_rlock" in v[1].msg
    assert "make_condition" in v[2].msg


def test_g16_factories_and_other_layers_are_clean():
    src = """
    from pint_tpu.runtime import locks

    class Engine:
        def __init__(self):
            self._lock = locks.make_rlock("serve.engine", engine=True)
            self._cv = locks.make_condition(self._lock)
    """
    assert _lint_g16(src) == []
    raw = """
    import threading

    class Host:
        def __init__(self):
            self._lock = threading.Lock()
    """
    # the rule only applies to the dispatch/serve/runtime/obs layers
    assert _lint_g16(raw, relpath="pint_tpu/pintk/_fixture.py") == []
    assert _lint_g16(raw, relpath="pint_tpu/obs/_fixture.py")
    assert _lint_g16(raw, relpath="pint_tpu/runtime/_fixture.py")


def test_g16_guarded_write_outside_lock_flags():
    """The registry owns ServeEngine._nqueued under _lock (alias
    _cv): an unlocked write — including a mutator call on a guarded
    container — flags; with/cv, *_locked, __init__ and declared
    holders stay clean."""
    v = _lint_g16("""
    class ServeEngine:
        def __init__(self):
            self._nqueued = 0          # __init__: allowed
            self._open = {}

        def submit(self, req):
            self._nqueued += 1         # UNLOCKED: flags
            self._open.pop(req, None)  # UNLOCKED mutator: flags

        def _seal_locked(self, key):
            self._nqueued -= 1         # *_locked suffix: allowed

        def sweep(self):
            with self._cv:
                self._nqueued = 0      # under the declared alias
            with self._lock:
                self._open[1] = 2      # under the owning lock

        def _dispatch_finish(self, unit):
            self._pool_last_collect = 1.0  # declared holder

        def _drain(self):
            with self._dispatch_lock:
                self._pool_last_collect = 2.0
    """, relpath="pint_tpu/serve/scheduler.py")
    assert [x.rule for x in v] == ["G16"] * 2
    assert "_nqueued" in v[0].msg and v[0].line
    assert "_open" in v[1].msg


def test_g16_closure_inside_locked_method_is_allowed():
    assert _lint_g16("""
    class ServeEngine:
        def _expire_locked(self):
            def inner():
                self._nqueued -= 1     # lexically inside *_locked
            inner()
    """, relpath="pint_tpu/serve/scheduler.py") == []


def test_g16_stale_registry_entry_fails_repo_scope():
    from pint_tpu.analysis import concurrency as conc
    from pint_tpu.analysis import lock_registry as reg
    stale = conc.g16_stale_entries({})
    assert len(stale) == len(reg.GUARDED)
    assert all(x.scope == "repo" and "stale" in x.msg for x in stale)
    assert conc.g16_stale_entries(
        {i: 1 for i in range(len(reg.GUARDED))}) == []


def test_g16_blocking_call_under_engine_lock_flags():
    v = _lint_g16("""
    class ServeEngine:
        def bad(self, sup, fn):
            with self._cv:
                return sup.dispatch(fn, key="x")

        def bad_fsync(self):
            with self._lock:
                self._fh.fsync()

        def fine(self, sup, fn):
            with self._dispatch_lock:   # NOT an engine lock
                return sup.dispatch(fn, key="x")

        def fine_outside(self, sup, fn):
            with self._cv:
                pending = fn
            return sup.dispatch(pending, key="x")
    """, relpath="pint_tpu/serve/scheduler.py")
    assert [x.rule for x in v] == ["G16"] * 2
    assert "dispatch" in v[0].msg and "fsync" in v[1].msg


def test_g16_scrape_root_reaching_engine_lock_flags():
    """A metrics handler that calls into the scheduler (directly or
    through a module-alias helper chain) reaches `with self._lock`
    -> flags with the call chain; the isolated handler is clean."""
    from pint_tpu.analysis import concurrency as conc

    sched = gl.ModuleInfo(
        "pint_tpu/serve/scheduler.py", textwrap.dedent("""
        class ServeEngine:
            def snapshot_all(self):
                with self._lock:
                    return dict(self._open)
        """))
    bad = gl.ModuleInfo(
        "pint_tpu/obs/metrics.py", textwrap.dedent("""
        from pint_tpu.serve import scheduler

        def _collect(eng):
            return scheduler.snapshot_all(eng)

        def do_GET(self):
            return _collect(self.eng)

        def default_health():
            return {}
        """))
    v = conc.check_g16_scrape_paths([sched, bad])
    # admission.py snapshot root is absent from the fixture set ->
    # one stale-entry finding rides along with the reachability one
    reach = [x for x in v if "reaches engine-lock" in x.msg]
    assert len(reach) == 1
    assert "do_GET" in reach[0].msg and "_lock" in reach[0].msg
    clean = gl.ModuleInfo(
        "pint_tpu/obs/metrics.py", textwrap.dedent("""
        def do_GET(self):
            return self.registry.render()

        def default_health():
            return {}
        """))
    v2 = conc.check_g16_scrape_paths([sched, clean])
    assert [x for x in v2 if "reaches engine-lock" in x.msg] == []


def test_g16_missing_scrape_root_is_stale():
    from pint_tpu.analysis import concurrency as conc
    v = conc.check_g16_scrape_paths([])
    assert v and all("stale" in x.msg and x.scope == "repo"
                     for x in v)


def test_g16_pragma_suppression_works():
    src = ("import threading\n"
           "def f():\n"
           "    return threading.Lock()"
           "  # graftlint: allow G16 -- fixture: sanctioned raw site\n")
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", src)
    from pint_tpu.analysis import concurrency as conc
    report = gl.LintReport(violations=conc.check_g16(m, {}))
    gl.apply_suppressions(
        report, [], {"pint_tpu/serve/_fixture.py": src})
    assert report.violations == []
    assert len(report.suppressed) == 1


def test_lock_registry_entry_count_pins_drift():
    """Registry size drift must be a conscious edit (the
    precision_registry pattern): update this pin WITH the new
    entry's written justification."""
    from pint_tpu.analysis import lock_registry as reg
    # PR 19 added four entries: RequestJournal._torn_seen and the
    # FleetFront _state/_rr/_inflight trio (all under serve.fleet).
    assert len(reg.GUARDED) == 17
    assert len(reg.ENGINE_LOCKS) == 1
    assert len(reg.SCRAPE_ROOTS) == 3
    assert reg.entry_count() == 21
    for e in reg.GUARDED:
        assert e["why"], e
    for e in reg.ENGINE_LOCKS + reg.SCRAPE_ROOTS:
        assert e["why"], e
    # the dispatch serializer must stay OUT of the engine set: the
    # drain design dispatches while holding it
    assert all("_dispatch_lock" not in e["attrs"]
               for e in reg.ENGINE_LOCKS)


# ----------------------------------------------------------- G17


def _lint_g17(src, relpath="pint_tpu/serve/_fixture.py"):
    from pint_tpu.analysis import concurrency as conc
    m = gl.ModuleInfo(relpath, textwrap.dedent(src))
    return conc.check_g17(m)


def test_g17_flags_raw_env_reads_everywhere():
    src = """
    import os
    from os import environ, getenv

    def f():
        a = os.environ.get("PINT_TPU_X")
        b = os.getenv("PINT_TPU_Y", "0")
        c = environ["PINT_TPU_Z"]
        d = getenv("PINT_TPU_W")
        return a, b, c, d
    """
    v = _lint_g17(src)
    assert [x.rule for x in v] == ["G17"] * 4
    # repo-wide: models/ and tools-adjacent paths flag too
    assert _lint_g17(src, relpath="pint_tpu/models/_fixture.py")
    assert _lint_g17(src, relpath="pint_tpu/observatory/_f.py")


def test_g17_config_is_sanctioned_and_bare_names_need_import():
    src = """
    import os

    def parse():
        return os.environ.get("PINT_TPU_X")
    """
    assert _lint_g17(src, relpath="pint_tpu/config.py") == []
    # bare `environ`/`getenv` names flag ONLY when from-imported
    # from os — a local variable of that name is not an env read
    assert _lint_g17("""
    def f(environ, getenv):
        return environ["X"], getenv("Y")
    """) == []


def test_g17_covers_fleet_module_and_knobs():
    """ISSUE 19 satellite: a raw read of any fleet env knob inside
    serve/fleet.py is a G17 violation — the validated config
    parsers (pool_spec / fleet_lease_ttl_s / fleet_heartbeat_s /
    fleet_workers) are the only sanctioned readers."""
    src = """
    import os

    def sweep_cadence():
        ttl = float(os.environ.get("PINT_TPU_FLEET_LEASE_TTL_S", 15))
        hb = os.getenv("PINT_TPU_FLEET_HEARTBEAT_S")
        pools = os.environ["PINT_TPU_POOLS"]
        return ttl, hb, pools
    """
    v = _lint_g17(src, relpath="pint_tpu/serve/fleet.py")
    assert [x.rule for x in v] == ["G17"] * 3
    # ...and the shipped fleet module is clean: zero raw env reads
    import os as _os

    import pint_tpu.serve.fleet as _fleet
    real = gl.ModuleInfo("pint_tpu/serve/fleet.py",
                         open(_fleet.__file__).read())
    from pint_tpu.analysis import concurrency as conc
    assert conc.check_g17(real) == []
    assert _os.path.basename(_fleet.__file__) == "fleet.py"


def test_g17_pragma_suppression_works():
    src = ("import os\n"
           "def probe():\n"
           "    return dict(os.environ)"
           "  # graftlint: allow G17 -- fixture: whole-env passthrough\n")
    m = gl.ModuleInfo("pint_tpu/serve/_fixture.py", src)
    from pint_tpu.analysis import concurrency as conc
    report = gl.LintReport(violations=conc.check_g17(m))
    gl.apply_suppressions(
        report, [], {"pint_tpu/serve/_fixture.py": src})
    assert report.violations == []
    assert len(report.suppressed) == 1


# ------------------------------------------------- github format


def test_github_annotation_wire_format():
    v = gl.Violation("G16", "pint_tpu/serve/scheduler.py", 42,
                     "bad thing\nsecond line with % and \r")
    line = gl.github_annotation(v)
    assert line.startswith(
        "::error file=pint_tpu/serve/scheduler.py,line=42,"
        "title=graftlint G16::G16: ")
    assert "\n" not in line and "\r" not in line
    assert "%0A" in line and "%0D" in line and "%25" in line
    # repo-scope findings at line 0 pin to 1 so GitHub renders them
    v0 = gl.Violation("G16", "x.py", 0, "stale", scope="repo")
    assert ",line=1," in gl.github_annotation(v0)
