"""PTA scale-up (BASELINE.md config #5): a heterogeneous pulsar batch
— plain, binary (ELL1), and correlated-noise pulsars with different
TOA counts and parameter sets — fit on the 8-device pulsar mesh in one
vmapped device call per iteration, with per-pulsar 1-sigma recovery.
The full 67-pulsar configuration runs as bench_pta.py on real
hardware; this test proves the mechanics at suite-friendly scale."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.parallel import fit_pta
from pint_tpu.simulation import make_fake_toas_uniform


def _mk_pulsar(k: int, family: str):
    f0 = 97.0 + 23.0 * k + 0.1 * (k % 7)
    binary = ""
    noise = ""
    if family == "ell1":
        binary = (f"BINARY ELL1\nPB {0.3 + 0.05 * k}\nA1 1.1 1\n"
                  "TASC 55000.05\nEPS1 1e-5 1\nEPS2 -2e-5 1\n")
    elif family == "noise":
        noise = ("EFAC -be X 1.1\nECORR -be X 0.8\n"
                 "TNREDAMP -13.6\nTNREDGAM 3.0\nTNREDC 4\n")
    par = f"""PSR J{1000 + k}+{k:02d}
RAJ {6 + (k % 12)}:2{k % 6}:00.0 1
DECJ {10 + (k % 40)}:00:00.0 1
F0 {f0} 1
F1 {-1e-15 * (1 + k % 3)} 1
PEPOCH 55000
POSEPOCH 55000
DM {8.0 + k} 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
{binary}{noise}"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        rng = np.random.default_rng(100 + k)
        ntoa = 24 + 8 * (k % 3)
        if family == "noise":
            # same-day pairs so ECORR epochs have >= 2 members
            from pint_tpu.ops import dd_np
            from pint_tpu.simulation import (
                _noise_draw_s,
                _rebuild,
                zero_residuals,
            )
            from pint_tpu.toa import get_TOAs_array

            base = np.linspace(54500, 55500, ntoa // 2)
            mjds = np.sort(np.concatenate([base, base + 0.003]))
            t = get_TOAs_array(mjds, obs="gbt", freqs=1400.0,
                               errors=1.0)
            for fl in t.flags:
                fl["be"] = "X"
            t = zero_residuals(t, m)
            ns = _noise_draw_s(t, m, rng, True, False)
            t = _rebuild(t, t.mjd_day, dd_np.add(
                t.mjd_frac, dd_np.div_f(dd_np.dd(ns), 86400.0)))
            for fl in t.flags:
                fl["be"] = "X"
        else:
            t = make_fake_toas_uniform(54500, 55500, ntoa, m,
                                       error_us=1.0, add_noise=True,
                                       rng=rng)
    truth = {n: m.get_param(n).value for n in m.free_params}
    m.F0.add_delta((1 + k % 4) * 1e-10)
    m.get_param("DM").add_delta(1e-5)
    m.invalidate_cache(params_only=True)
    return m, t, truth


@pytest.mark.slow
def test_pta_heterogeneous_batch_on_mesh():
    import jax
    from jax.sharding import Mesh

    families = (["plain"] * 10) + (["ell1"] * 3) + (["noise"] * 3)
    pulsars = [_mk_pulsar(k, fam) for k, fam in enumerate(families)]
    ndev = len(jax.devices())
    assert ndev == 8
    mesh = Mesh(np.array(jax.devices()).reshape(ndev), ("pulsar",))
    res = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=3,
                  mesh=mesh)
    assert len(res) == 16
    stats = fit_pta.last_stats
    assert stats["npulsars"] == 16
    assert stats["toas_per_sec"] > 0
    n_ok = 0
    for (m, t, truth), r in zip(pulsars, res):
        assert np.isfinite(r["chi2"]) and r["chi2"] > 0
        for pname in ("F0", "DM"):
            err = r["errors"][pname]
            assert err > 0
            if abs(m.get_param(pname).value - truth[pname]) < 5 * err:
                n_ok += 1
    # 2 checks x 16 pulsars; allow a couple of 5-sigma outliers
    assert n_ok >= 30, f"only {n_ok}/32 parameters recovered"
    # binary pulsars: A1/EPS recovered too
    for (m, t, truth), r in list(zip(pulsars, res))[10:13]:
        assert abs(m.get_param("A1").value - truth["A1"]) \
            < 5 * r["errors"]["A1"]
