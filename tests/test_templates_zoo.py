"""Expanded template-zoo tests: multi-shape primitives, file I/O,
Hessian errors, binned fit, profile statistics.

Reference behaviors: src/pint/templates/lcprimitives.py (LCGaussian2,
LCLorentzian2, LCTopHat), lctemplate.py (delta/Delta/rotate), and
lcfitters.py (hessian errors, chi-squared binned path)."""

import numpy as np
import pytest

from pint_tpu.templates import (
    GaussianPrior,
    LCFitter,
    LCGaussian,
    LCGaussian2,
    LCLorentzian2,
    LCTemplate,
    LCTopHat,
    make_template,
    read_template,
    write_template,
)


GRID = np.linspace(0, 1, 20001)[:-1]


@pytest.mark.parametrize("spec", [
    ("gaussian2", [0.03, 0.06]),
    ("lorentzian2", [0.02, 0.05]),
    ("tophat", [0.2]),
])
def test_new_primitives_normalized(spec):
    name, w = spec
    t = make_template([(name, 0.7, 0.45, w)])
    integral = np.mean(t(GRID))
    assert integral == pytest.approx(1.0, rel=2e-2), name


def test_gaussian2_asymmetry():
    t = make_template([("gaussian2", 0.9, 0.5, [0.02, 0.08])])
    pdf = t(GRID)
    peak = GRID[np.argmax(pdf)]
    assert peak == pytest.approx(0.5, abs=0.005)
    # mass right of the peak ~ sr/(sl+sr) of the pulsed part
    pulsed = pdf - pdf.min()
    right = pulsed[(GRID > 0.5) & (GRID < 0.9)].sum()
    left = pulsed[(GRID > 0.1) & (GRID < 0.5)].sum()
    assert right / (right + left) == pytest.approx(0.8, abs=0.05)


def test_template_file_roundtrip(tmp_path):
    t = make_template([
        ("gaussian", 0.5, 0.3, 0.03),
        ("gaussian2", 0.2, 0.7, [0.02, 0.05]),
    ])
    path = tmp_path / "profile.txt"
    write_template(t, str(path))
    t2 = read_template(str(path))
    np.testing.assert_allclose(t2(GRID), t(GRID), rtol=1e-10)
    assert [p.name for p in t2.primitives] == ["gaussian", "gaussian2"]


def test_profile_statistics():
    t = make_template([
        ("gaussian", 0.5, 0.2, 0.03),
        ("gaussian", 0.3, 0.6, 0.05),
    ])
    assert t.delta() == pytest.approx(0.2)
    assert t.Delta() == pytest.approx(0.4)
    fw = t.fwhms()
    assert fw[0] == pytest.approx(2.3548 * 0.03, rel=1e-3)
    t.rotate(0.25)
    assert t.delta() == pytest.approx(0.45)
    # integrate over the whole cycle -> 1
    assert t.integrate(0.0, 1.0) == pytest.approx(1.0, rel=1e-3)


def test_fit_reports_errors():
    truth = LCTemplate([LCGaussian()], norms=[0.6], locs=[0.3],
                       widths=[0.03])
    rng = np.random.default_rng(11)
    phases = truth.random(6000, rng=rng)
    fit_t = LCTemplate([LCGaussian()], norms=[0.4], locs=[0.33],
                       widths=[0.05])
    f = LCFitter(fit_t, phases)
    res = f.fit()
    assert res["success"]
    assert res["theta_err"].shape == fit_t.theta.shape
    assert np.all(np.isfinite(res["theta_err"]))
    # loc is theta[m+1] = theta[2]; 1-sigma should be small and the
    # recovered loc within ~4 sigma of truth
    m = 1
    loc_err = res["theta_err"][m + 1]
    assert 1e-4 < loc_err < 0.01
    assert abs(fit_t.locs[0] - 0.3) < 5 * loc_err + 1e-3


def test_binned_fit_recovers():
    truth = LCTemplate([LCGaussian()], norms=[0.7], locs=[0.55],
                       widths=[0.04])
    rng = np.random.default_rng(12)
    phases = truth.random(20000, rng=rng)
    fit_t = LCTemplate([LCGaussian()], norms=[0.5], locs=[0.5],
                       widths=[0.07])
    f = LCFitter(fit_t, phases)
    res = f.fit_binned(nbins=64)
    assert res["success"]
    assert fit_t.locs[0] == pytest.approx(0.55, abs=0.01)
    assert fit_t.widths[0][0] == pytest.approx(0.04, abs=0.01)


def test_gaussian_prior_pins_location():
    truth = LCTemplate([LCGaussian()], norms=[0.6], locs=[0.3],
                       widths=[0.03])
    rng = np.random.default_rng(13)
    phases = truth.random(2000, rng=rng)
    fit_t = LCTemplate([LCGaussian()], norms=[0.5], locs=[0.42],
                       widths=[0.05])
    # very tight prior holding loc at its (wrong) initial value
    prior = GaussianPrior([2], [0.42], [1e-5])
    f = LCFitter(fit_t, phases, prior=prior)
    f.fit(compute_errors=False)
    assert fit_t.locs[0] == pytest.approx(0.42, abs=1e-3)


def test_gaussian2_ml_recovery():
    truth = make_template([("gaussian2", 0.7, 0.4, [0.02, 0.06])])
    rng = np.random.default_rng(14)
    phases = truth.random(20000, rng=rng)
    fit_t = make_template([("gaussian2", 0.5, 0.42, [0.04, 0.04])])
    f = LCFitter(fit_t, phases)
    res = f.fit(compute_errors=False)
    assert res["loglikelihood"] > -np.inf
    assert fit_t.locs[0] == pytest.approx(0.4, abs=0.01)
    sl, sr = fit_t.widths[0]
    assert sl == pytest.approx(0.02, abs=0.01)
    assert sr == pytest.approx(0.06, abs=0.015)


def test_skewgaussian_normalized_and_skews():
    """LCSkewGaussian: unit integral; exp(alpha)>1 pushes probability
    to later phase; exp(alpha)=1 reduces to the plain Gaussian."""
    import numpy as np

    from pint_tpu.templates import LCGaussian, LCSkewGaussian, LCTemplate

    xs = np.linspace(0, 1, 20001)
    for a in (0.3, 1.0, 3.5):
        t = LCTemplate([LCSkewGaussian()], [0.9], [0.5], [[0.03, a]])
        y = t(xs)
        assert abs(np.trapezoid(y, xs) - 1.0) < 1e-3, a
    sym = LCTemplate([LCSkewGaussian()], [0.9], [0.5], [[0.03, 1.0]])
    plain = LCTemplate([LCGaussian()], [0.9], [0.5], [[0.03]])
    np.testing.assert_allclose(sym(xs), plain(xs), rtol=1e-10)
    skew = LCTemplate([LCSkewGaussian()], [0.9], [0.5], [[0.03, 3.5]])
    y = skew(xs)
    mean = np.trapezoid(xs * (y - y.min()), xs) / np.trapezoid(
        y - y.min(), xs)
    assert mean > 0.5 + 0.005  # tail to later phase
    # random() must draw the skew-normal, not a symmetric fallback
    # (window out the uniform background — its symmetric mass about a
    # shifted mixture mean would pollute the third moment)
    draws = skew.random(50000, rng=np.random.default_rng(3))
    d = draws[(draws > 0.35) & (draws < 0.75)]
    m = d.mean()
    skewness = np.mean((d - m) ** 3) / np.std(d) ** 3
    assert skewness > 0.5  # alpha = log(3.5) > 0: right-skewed


def test_free_fixed_machinery():
    """param_mask + LCFitter(free=): fixed entries must not move, and
    the partial fit still recovers the free ones."""
    import numpy as np

    from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate

    rng = np.random.default_rng(8)
    truth = LCTemplate([LCGaussian(), LCGaussian()], [0.35, 0.25],
                       [0.2, 0.65], [[0.02], [0.05]])
    # draw photons from the truth by rejection
    xs = rng.uniform(0, 1, 40000)
    keep = rng.uniform(0, truth(xs).max() * 1.05, 40000) < truth(xs)
    phases = xs[keep][:8000]
    start = LCTemplate([LCGaussian(), LCGaussian()], [0.35, 0.25],
                       [0.23, 0.65], [[0.02], [0.05]])
    mask = start.param_mask(free_norms=False, free_widths=False,
                            prims=[0])   # only peak-0 location free
    theta_before = np.asarray(start.theta).copy()
    fit = LCFitter(start, phases)
    out = fit.fit(free=mask)
    theta_after = np.asarray(start.theta)
    # fixed entries bitwise unchanged
    np.testing.assert_array_equal(theta_before[~mask],
                                  theta_after[~mask])
    # the free location moved toward the truth
    assert abs(start.locs[0] - 0.2) < 0.01
    assert out["theta_err"][~mask].max() == 0.0


def test_empirical_fourier_and_kde_recover_profile():
    """Both empirical templates (measured, not ML-fit) approximate the
    true two-peak pdf from its own photon draws."""
    import numpy as np

    from pint_tpu.templates import (
        LCEmpiricalFourier,
        LCGaussian,
        LCKernelDensity,
        LCTemplate,
    )

    rng = np.random.default_rng(5)
    truth = LCTemplate([LCGaussian(), LCGaussian()], [0.4, 0.3],
                       [0.25, 0.7], [[0.03], [0.06]])
    phases = truth.random(60000, rng=rng)
    xs = np.linspace(0, 1, 512, endpoint=False)
    ytrue = truth(xs)
    for maker in (lambda: LCEmpiricalFourier.from_phases(phases,
                                                         nharm=24),
                  lambda: LCKernelDensity(phases)):
        t = maker()
        y = t(xs)
        # unit normalization and pointwise agreement at few-percent
        assert abs(np.mean(y) - 1.0) < 0.02
        err = np.max(np.abs(y - ytrue)) / np.max(ytrue)
        assert err < 0.08, type(t).__name__
    # weighted measurement: weighting out half the photons of peak 2
    # suppresses it
    w = np.where(np.abs(phases - 0.7) < 0.15, 0.2, 1.0)
    tw = LCEmpiricalFourier.from_phases(phases, weights=w, nharm=24)
    y = tw(xs)
    assert y[np.argmin(np.abs(xs - 0.25))] > \
        y[np.argmin(np.abs(xs - 0.7))]
