"""Satellite observatories + spacecraft TOAs + phaseogram (reference:
src/pint/observatory/satellite_obs.py, special_locations.py
T2SpacecraftObs, plot_utils.py)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.io.fits import write_events_fits
from pint_tpu.models import get_model

NICER_MJDREF = (56658, 7.775925925925926e-4)

PAR = """
PSR J0030+0451
RAJ 00:30:27.4
DECJ 04:51:39.7
F0 205.53069927
F1 -4.3e-16
PEPOCH 56500
POSEPOCH 56500
DM 4.33
TZRMJD 56500.0
TZRSITE @
TZRFRQ inf
UNITS TDB
"""


def _write_orbit(path, mjd0, mjd1, dt_s=30.0):
    """Circular 550-km LEO in the equatorial plane, ECI meters."""
    mjdrefi, mjdreff = NICER_MJDREF
    t0 = ((mjd0 - mjdrefi) - mjdreff) * 86400.0
    t1 = ((mjd1 - mjdrefi) - mjdreff) * 86400.0
    t = np.arange(t0, t1 + dt_s, dt_s)
    r = 6.921e6  # m
    period = 2 * np.pi * np.sqrt(r ** 3 / 3.986004418e14)
    ang = 2 * np.pi * t / period
    cols = {"TIME": t, "POS_X": r * np.cos(ang),
            "POS_Y": r * np.sin(ang), "POS_Z": np.zeros_like(t)}
    write_events_fits(path, cols, header_extra={
        "TELESCOP": "NICER", "MJDREFI": mjdrefi, "MJDREFF": mjdreff,
        "TIMESYS": "TT"}, extname="SC_DATA")
    return period


def test_satellite_obs_interpolation(tmp_path):
    from pint_tpu.observatory.satellite_obs import SatelliteObs

    orb = tmp_path / "orb.fits"
    period = _write_orbit(orb, 56500.0, 56500.5)
    obs = SatelliteObs("nicertest", str(orb))
    tq = np.array([56500.1, 56500.2])
    p, v = obs.gcrs_posvel(tq, tq)
    np.testing.assert_allclose(np.linalg.norm(p, axis=-1), 6.921e6,
                               rtol=1e-4)
    # orbital speed ~ 2 pi r / P
    np.testing.assert_allclose(np.linalg.norm(v, axis=-1),
                               2 * np.pi * 6.921e6 / period, rtol=1e-3)
    with pytest.raises(ValueError):
        obs.gcrs_posvel(np.array([56600.0]), np.array([56600.0]))


def test_tt_events_with_orbit(tmp_path):
    """Un-barycentered TT photons + orbit file phase up under the model
    that generated them (the full satellite pipeline: TT->UTC clock
    chain, orbit positions, Roemer/Shapiro barycentering)."""
    from pint_tpu.event_toas import load_fits_TOAs
    from pint_tpu.eventstats import hm
    from pint_tpu.simulation import zero_residuals
    from pint_tpu.toa import get_TOAs_array

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        orb = tmp_path / "orb.fits"
        _write_orbit(orb, 56499.9, 56502.1)
        # simulate: pick arrival UTC times at the spacecraft such that
        # the model phase is ~0 there, by zero_residuals on TOAs that
        # use the orbit observatory
        from pint_tpu.observatory.satellite_obs import (
            get_satellite_observatory,
        )

        get_satellite_observatory("nicersim", str(orb))
        rng = np.random.default_rng(3)
        mjds = np.sort(rng.uniform(56500.0, 56502.0, 400))
        toas = get_TOAs_array(mjds, obs="nicersim", freqs=np.inf,
                              errors=1.0)
        toas = zero_residuals(toas, model)
        # photons at phase 0 (+ narrow jitter)
        utc = toas.mjd_day + toas.mjd_frac[0] + toas.mjd_frac[1]
        # convert back to mission TT seconds for the event file
        from pint_tpu.time.scales import TT_MINUS_TAI, tai_minus_utc

        tt = utc + (tai_minus_utc(toas.mjd_day) + TT_MINUS_TAI) / 86400.0
        mjdrefi, mjdreff = NICER_MJDREF
        ev = tmp_path / "ev.fits"
        write_events_fits(ev, {"TIME": ((tt - mjdrefi) - mjdreff)
                               * 86400.0},
                          header_extra={"TIMESYS": "TT",
                                        "TELESCOP": "NICER",
                                        "MJDREFI": mjdrefi,
                                        "MJDREFF": mjdreff})
        t2 = load_fits_TOAs(ev, mission="nicer2",
                            orbit_file=str(orb))
        phases = np.mod(np.asarray(model.phase(t2).frac) + 0.5,
                        1.0) - 0.5
    # all photons at phase ~0 => enormous H-test
    assert np.percentile(np.abs(phases), 90) < 0.02
    assert hm(np.mod(phases, 1.0)) > 1000


def test_tt_events_without_orbit_raise(tmp_path):
    from pint_tpu.event_toas import load_fits_TOAs

    ev = tmp_path / "ev.fits"
    write_events_fits(ev, {"TIME": np.arange(10.0)},
                      header_extra={"TIMESYS": "TT",
                                    "MJDREFI": NICER_MJDREF[0],
                                    "MJDREFF": NICER_MJDREF[1]})
    with pytest.raises(NotImplementedError):
        load_fits_TOAs(ev)


def test_t2spacecraft_obs_flags():
    from pint_tpu.toa import get_TOAs_array

    flags = [{"telx": "0.01", "tely": "-0.02", "telz": "0.005"}
             for _ in range(4)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = get_TOAs_array(np.linspace(56500, 56501, 4),
                           obs="stl_geo", freqs=1400.0, errors=1.0,
                           flags=flags)
    # observatory position = geocenter + flag offset (lt-s)
    # compare against geocenter TOAs
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        tg = get_TOAs_array(np.linspace(56500, 56501, 4),
                            obs="geocenter", freqs=1400.0, errors=1.0)
    d = t.ssb_obs_pos - tg.ssb_obs_pos  # meters
    C = 299792458.0
    np.testing.assert_allclose(
        d, np.tile([0.01 * C, -0.02 * C, 0.005 * C], (4, 1)),
        atol=1.0)
    # missing flags raise
    with pytest.raises(ValueError):
        get_TOAs_array(np.array([56500.0]), obs="stl_geo",
                       freqs=1400.0, errors=1.0, flags=[{}])


def test_phaseogram(tmp_path):
    from pint_tpu.plot_utils import phaseogram, phaseogram_binned

    rng = np.random.default_rng(0)
    mjds = np.sort(rng.uniform(56000, 56100, 2000))
    phases = np.mod(0.3 + 0.03 * rng.standard_normal(2000), 1.0)
    out = tmp_path / "pg.png"
    fig = phaseogram(mjds, phases, plotfile=str(out), title="test")
    assert out.stat().st_size > 5000
    out2 = tmp_path / "pgb.png"
    phaseogram_binned(mjds, phases,
                      weights=rng.uniform(0.2, 1, 2000),
                      plotfile=str(out2))
    assert out2.stat().st_size > 5000
