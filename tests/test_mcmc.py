"""Ensemble sampler + MCMC fitters + logging/config modules
(reference: src/pint/sampler.py, mcmc_fitter.py, logging.py,
config.py; oracle: posterior moments must match the least-squares
covariance on simulated data)."""

import copy
import io
import logging as stdlib_logging
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.sampler import EnsembleSampler
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs


# ------------------------------------------------------------ sampler


def test_sampler_gaussian_target():
    """The ensemble reproduces a 2-D Gaussian's moments."""
    cov = np.array([[2.0, 0.6], [0.6, 1.0]])
    icov = np.linalg.inv(cov)

    def lp(x):
        x = np.atleast_2d(x)
        return -0.5 * np.einsum("si,ij,sj->s", x, icov, x)

    rng = np.random.default_rng(0)
    s = EnsembleSampler(40, 2, lp, rng=rng)
    p0 = rng.standard_normal((40, 2))
    s.run_mcmc(p0, 1500)
    assert 0.2 < s.acceptance_fraction < 0.9
    flat = s.get_chain(discard=500, flat=True)
    est = np.cov(flat.T)
    np.testing.assert_allclose(est, cov, rtol=0.15, atol=0.1)
    assert np.abs(flat.mean(axis=0)).max() < 0.15


def test_sampler_validates():
    def lp(x):
        return np.zeros(len(np.atleast_2d(x)))

    with pytest.raises(ValueError):
        EnsembleSampler(3, 2, lp)  # odd
    with pytest.raises(ValueError):
        EnsembleSampler(2, 2, lp)  # < 2*ndim
    s = EnsembleSampler(8, 2, lambda x: np.full(
        len(np.atleast_2d(x)), -np.inf))
    with pytest.raises(ValueError):
        s.run_mcmc(np.zeros((8, 2)), 5)


# --------------------------------------------------------- MCMCFitter


@pytest.fixture(scope="module")
def fitted_problem():
    par = """
PSR J0014+0014
RAJ 04:30:00.0
DECJ 18:00:00.0
F0 275.0 1
F1 -3e-15 1
PEPOCH 55500
POSEPOCH 55500
DM 14.0
TZRMJD 55500.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(9)
        toas = merge_TOAs([
            make_fake_toas_uniform(55000, 56000, 40, model,
                                   error_us=1.0, freq_mhz=1400.0,
                                   add_noise=True, rng=rng),
            make_fake_toas_uniform(55001, 55999, 40, model,
                                   error_us=1.0, freq_mhz=820.0,
                                   add_noise=True, rng=rng)])
        from pint_tpu.fitter import WLSFitter

        m = copy.deepcopy(model)
        f = WLSFitter(toas, m)
        f.fit_toas(maxiter=2)
    return model, m, toas, f


def test_mcmc_fitter_matches_wls(fitted_problem):
    from pint_tpu.mcmc_fitter import MCMCFitter

    truth, mfit, toas, wls = fitted_problem
    m = copy.deepcopy(mfit)
    mc = MCMCFitter(toas, m, nwalkers=16,
                    rng=np.random.default_rng(1))
    chi2 = mc.fit_toas(nsteps=400)
    assert np.isfinite(chi2)
    assert mc.stats is not None
    assert mc.sampler.acceptance_fraction > 0.1
    for name in ("F0", "F1"):
        # posterior width within a factor ~2 of the WLS sigma and the
        # median consistent with the WLS solution
        assert 0.4 < mc.errors[name] / wls.errors[name] < 2.5, name
        assert abs(m.get_param(name).value
                   - mfit.get_param(name).value) \
            < 4 * wls.errors[name], name


# --------------------------------------------- photon template MCMC


def test_photon_mcmc_recovers_f0(fitted_problem):
    from pint_tpu.mcmc_fitter import PhotonMCMCFitter
    from pint_tpu.templates import LCGaussian, LCTemplate

    truth, _, _, _ = fitted_problem
    rng = np.random.default_rng(4)
    template = LCTemplate([LCGaussian()], norms=[0.7], locs=[0.4],
                          widths=[0.03])
    # photons drawn on the truth model's phase grid
    n = 1500
    base = rng.uniform(55400, 55600, n)
    phi = template.random(n, rng=rng)
    f0 = truth.F0.value
    f1 = truth.F1.value
    pep = truth.PEPOCH.value
    dt = (base - pep) * 86400.0
    k = np.floor(dt * f0)
    tsec = (k + phi) / f0 - 0.5 * f1 / f0 * ((k + phi) / f0) ** 2
    mjd = pep + tsec / 86400.0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.toa import get_TOAs_array

        toas = get_TOAs_array(np.sort(mjd), obs="barycenter",
                              freqs=np.inf, errors=1.0)
    m = copy.deepcopy(truth)
    m.get_param("F1").frozen = True
    m.invalidate_cache()
    fitter = PhotonMCMCFitter(toas, m, template,
                              nwalkers=16,
                              rng=np.random.default_rng(2))
    fitter.fit_toas(nsteps=150, scatter=2e-12)
    # F0 recovered to sub-mHz (phase coherence over 200 d)
    assert abs(m.F0.value - f0) < 5e-8
    assert fitter.errors["F0"] < 1e-7


# ------------------------------------------------------ logging/config


def test_logging_setup_and_dedup(capsys):
    import pint_tpu.logging as plog

    buf = io.StringIO()
    log = plog.setup(level="INFO", sink=buf)
    for _ in range(5):
        log.info("repeated message")
    log.info("other message")
    out = buf.getvalue()
    assert out.count("repeated message") == 1
    assert "other message" in out
    # level filtering
    log.debug("hidden")
    assert "hidden" not in buf.getvalue()
    assert isinstance(log, stdlib_logging.Logger)


def test_config_env_overrides(tmp_path, monkeypatch):
    import pint_tpu.config as cfg

    assert cfg.datadir().name == "pint_tpu"
    assert cfg.clock_dir() is None or cfg.clock_dir().exists() or True
    monkeypatch.setenv("PINT_TPU_CLOCK_DIR", str(tmp_path))
    assert cfg.clock_dir() == tmp_path
    (tmp_path / "time_gbt.dat").write_text("# clock\n")
    assert cfg.runtimefile("time_gbt.dat") == tmp_path / "time_gbt.dat"
    with pytest.raises(FileNotFoundError):
        cfg.runtimefile("nonexistent.dat")


def test_composite_mcmc_joint_posterior(fitted_problem):
    """Composite radio+photon posterior: adding photon data must not
    bias F0 away from truth and should not broaden the radio-only
    posterior (reference: CompositeMCMCFitter)."""
    from pint_tpu.mcmc_fitter import CompositeMCMCFitter
    from pint_tpu.templates import LCGaussian, LCTemplate

    truth, _, toas_radio, _ = fitted_problem
    rng = np.random.default_rng(9)
    template = LCTemplate([LCGaussian()], norms=[0.7], locs=[0.4],
                          widths=[0.03])
    n = 1200
    base = rng.uniform(55400, 55600, n)
    phi = template.random(n, rng=rng)
    f0 = truth.F0.value
    f1 = truth.F1.value
    pep = truth.PEPOCH.value
    dt = (base - pep) * 86400.0
    k = np.floor(dt * f0)
    tsec = (k + phi) / f0 - 0.5 * f1 / f0 * ((k + phi) / f0) ** 2
    mjd = pep + tsec / 86400.0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.toa import get_TOAs_array

        toas_ev = get_TOAs_array(np.sort(mjd), obs="barycenter",
                                 freqs=np.inf, errors=1.0)
        m = copy.deepcopy(truth)
        for nm in m.free_params:
            if nm != "F0":
                m.get_param(nm).frozen = True
        m.invalidate_cache()
        fitter = CompositeMCMCFitter(
            toas_radio, toas_ev, m, template,
            nwalkers=8, rng=np.random.default_rng(10))
        lnmax = fitter.fit_toas(nsteps=60)
    assert np.isfinite(lnmax)
    assert m.F0.value == pytest.approx(truth.F0.value,
                                       abs=5 * m.F0.uncertainty)
    assert 0 < m.F0.uncertainty < 1e-5
