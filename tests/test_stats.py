"""Fit stats object + profiling scoreboard (SURVEY.md §5: metrics and
tracing are first-class; the reference returns a bare chi2 from
src/pint/fitter.py fit_toas — here every fitter attaches FitStats)."""

import io
import json
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.profiling import FitStats, Scoreboard, annotate, scoreboard
from pint_tpu.simulation import make_fake_toas_uniform


@pytest.fixture(scope="module")
def wls_problem():
    par = """
PSR J0002+0002
RAJ 10:00:00.0 1
DECJ 10:00:00.0 1
F0 100.0 1
F1 -1e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 10.0 1
DMEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(par))
        rng = np.random.default_rng(3)
        tA = make_fake_toas_uniform(54000, 56000, 30, model, freq_mhz=1400.0,
                                    add_noise=True, rng=rng)
        tB = make_fake_toas_uniform(54010, 55990, 30, model, freq_mhz=820.0,
                                    add_noise=True, rng=rng)
        from pint_tpu.toa import merge_TOAs

        toas = merge_TOAs([tA, tB])
    return model, toas


def test_wls_fitter_records_stats(wls_problem):
    import copy

    from pint_tpu.fitter import WLSFitter

    model, toas = wls_problem
    f = WLSFitter(toas, copy.deepcopy(model))
    chi2 = f.fit_toas(maxiter=2)
    s = f.stats
    assert isinstance(s, FitStats)
    assert s.fitter == "WLSFitter"
    assert s.ntoa == toas.ntoas
    assert s.nfree == 5  # RAJ DECJ F0 F1 DM
    assert s.chi2 == pytest.approx(chi2)
    assert s.iterations == 2
    assert s.wall_time_s > 0
    assert s.toas_per_sec > 0
    assert s.converged
    # round-trips through JSON
    d = json.loads(s.to_json())
    assert d["dof"] == s.dof
    assert "TOA/s" in str(s)


def test_downhill_fitter_records_stats(wls_problem):
    import copy

    from pint_tpu.fitter import DownhillWLSFitter

    model, toas = wls_problem
    m = copy.deepcopy(model)
    m.get_param("F0").add_delta(2e-10)
    m.invalidate_cache(params_only=True)
    f = DownhillWLSFitter(toas, m)
    f.fit_toas()
    assert f.stats.iterations >= 1
    assert f.stats.converged
    assert f.stats.reduced_chi2 == pytest.approx(
        f.stats.chi2 / f.stats.dof)


def test_scoreboard_phases():
    sb = Scoreboard()
    with sb.phase("alpha"):
        pass
    with sb.phase("alpha"):
        pass
    with sb.phase("beta"):
        pass
    assert sb.counts["alpha"] == 2
    assert sb.counts["beta"] == 1
    rep = sb.report()
    assert "alpha" in rep and "beta" in rep
    sb.reset()
    assert not sb.totals


def test_annotate_feeds_global_scoreboard():
    scoreboard.reset()
    with annotate("unit-test-phase"):
        x = sum(range(100))
    assert x == 4950
    assert scoreboard.counts["unit-test-phase"] == 1


def test_h2sig_alias():
    from pint_tpu.eventstats import h2sig, sf_hm, sig2sigma

    assert h2sig(30.0) == sig2sigma(sf_hm(30.0))
    assert 3.0 < h2sig(30.0) < 6.0
