"""The f32 Jacobian path (parallel/fit_step jac_f32): the design
matrix is computed by re-tracing the phase chain with f32/dd32 inputs
(reference algorithm: src/pint/fitter.py builds the same design matrix
via registered derivative chains in longdouble; here jacfwd over a
dtype-degraded chain, accurate to ~1e-7 of column max — design columns
feed equilibrated normal equations and need only ~1e-6).

Also covers the dd32 substrate: dtype-generic dd ops at f32-pair
precision (~2^-48) and the large-|lo| generalization of
dd_frac/dd_round that dd32 at 1e10-turn magnitudes requires.
"""

import io
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.ops.dd import (
    DD,
    dd,
    dd_add,
    dd_frac,
    dd_mul,
    dd_round,
    dd_to_dd32,
    f64_to_dd32,
    two_prod,
)
from pint_tpu.parallel import build_fit_step
from pint_tpu.simulation import make_fake_toas_fromMJDs

BASE_PAR = """PSR J0000+0000
RAJ 12:00:00.0 1
DECJ 30:00:00.0 1
F0 300.123456789 1
F1 -1.0e-15 1
DM 20.0 1
PEPOCH 55000
POSEPOCH 55000
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def _problem(extra="", n=400, seed=3):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(BASE_PAR + extra))
        rng = np.random.default_rng(seed)
        mjds = np.sort(rng.uniform(53001, 56999, n))
        freqs = np.tile([1400.0, 820.0], n // 2)
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0, freq_mhz=freqs,
            add_noise=True, rng=rng)
    return model, toas


def _compare(model, toas, tol_sigma=1e-2, tol_chi2=1e-6):
    s64, a64, names = build_fit_step(model, toas, jac_f32=False)
    s32, a32, _ = build_fit_step(model, toas, jac_f32=True)
    dp64, cov64, chi64, _ = [np.asarray(x) for x in jax.jit(s64)(*a64)]
    dp32, _, chi32, _ = [np.asarray(x) for x in jax.jit(s32)(*a32)]
    sig = np.sqrt(np.diag(cov64))
    assert np.max(np.abs(dp64 - dp32) / sig) < tol_sigma, names
    assert abs(chi64 - chi32) <= tol_chi2 * abs(chi64)
    return dp64, dp32, sig


class TestDD32Substrate:
    def test_dd32_add_mul_precision(self):
        rng = np.random.default_rng(0)
        x = rng.uniform(-1e8, 1e8, 500)
        y = rng.uniform(-1e3, 1e3, 500)
        a, b = f64_to_dd32(x), f64_to_dd32(y)
        s, p = dd_add(a, b), dd_mul(a, b)
        assert s.hi.dtype == jnp.float32 and p.hi.dtype == jnp.float32
        sv = np.asarray(s.hi, np.float64) + np.asarray(s.lo, np.float64)
        pv = np.asarray(p.hi, np.float64) + np.asarray(p.lo, np.float64)
        # dd32 eps ~ 2^-48 = 3.6e-15
        assert np.max(np.abs(sv - (x + y)) / np.abs(x + y)) < 3e-14
        assert np.max(np.abs(pv - (x * y)) / np.abs(x * y)) < 3e-14

    def test_two_prod_f32_exact(self):
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.uniform(-1, 1, 500), jnp.float32)
        b = jnp.asarray(rng.uniform(-1, 1, 500), jnp.float32)
        tp = two_prod(a, b)
        exact = np.asarray(a, np.float64) * np.asarray(b, np.float64)
        recon = np.asarray(tp.hi, np.float64) + \
            np.asarray(tp.lo, np.float64)
        assert np.max(np.abs(recon - exact)) == 0.0

    def test_frac_round_large_lo(self):
        """dd32 at 1e10 has ulp(hi) = 1024 >> 1: the integer strip must
        handle |lo| spanning hundreds of units."""
        rng = np.random.default_rng(2)
        ph = rng.uniform(1e9, 1e10, 2000)
        a32 = f64_to_dd32(ph)
        fr = dd_frac(a32)
        frv = np.asarray(fr.hi, np.float64) + np.asarray(fr.lo, np.float64)
        truth = ph - np.round(ph)
        # |err| <= magnitude * 2^-48 * small factor
        assert np.max(np.abs(frv - truth)) < 1e-4
        rd = dd_round(a32)
        rdv = np.asarray(rd.hi, np.float64) + np.asarray(rd.lo, np.float64)
        assert np.max(np.abs(rdv - np.round(ph))) == 0.0

    def test_frac_round_f64_unchanged(self):
        ph = np.array([2.0, 5e9, -3e9, 55000.75])
        lo = np.array([1e-20, 0.3e-16, -0.3e-16, 1e-18])
        f = dd_frac(DD(jnp.asarray(ph), jnp.asarray(lo)))
        expect = (ph - np.round(ph)) + lo
        got = np.asarray(f.hi) + np.asarray(f.lo)
        np.testing.assert_allclose(got, expect, rtol=0, atol=1e-30)
        assert np.asarray(dd_round(dd(2.5)).hi) in (2.0, 3.0)

    def test_split_roundtrip(self):
        rng = np.random.default_rng(3)
        x = rng.uniform(-1e10, 1e10, 100)
        a = f64_to_dd32(x)
        back = np.asarray(a.hi, np.float64) + np.asarray(a.lo, np.float64)
        assert np.max(np.abs(back - x) / np.abs(x)) < 3e-14
        d64 = dd(x, rng.uniform(-1e-8, 1e-8, 100))
        a2 = dd_to_dd32(d64)
        v64 = np.asarray(d64.hi) + np.asarray(d64.lo)
        back2 = np.asarray(a2.hi, np.float64) + \
            np.asarray(a2.lo, np.float64)
        assert np.max(np.abs(back2 - v64) / np.abs(v64)) < 3e-14


class TestJac32FitStep:
    def test_isolated_pulsar_with_high_fterms(self):
        """F2..F4 columns reach dt^5/120 ~ 1e38; the per-param scale
        keeps the f32 path in range and exact after unscaling."""
        extra = "F2 1e-26 1\nF3 1e-33 1\nF4 1e-42 1\nPMRA 2.0 1\nPMDEC -3 1\nPX 1.2 1\n"
        model, toas = _problem(extra)
        _compare(model, toas)

    def test_high_order_fterms_f5_f7(self):
        """F5..F7 ride the power-of-two scale window (column in f32
        range AND tangent seed normal after the factorial division)."""
        extra = ("F2 1e-26 1\nF3 1e-33 1\nF4 1e-40 1\nF5 1e-48 1\n"
                 "F6 1e-56 1\nF7 1e-64 1\n")
        model, toas = _problem(extra)
        _compare(model, toas, tol_sigma=3e-2)

    def test_f8_falls_back_to_f64(self):
        """No feasible f32 scale window for F8 at a decade span: the
        build must silently fall back to the f64 Jacobian and still be
        correct."""
        extra = "".join(f"F{i} 1e-{26 + 7 * (i - 2)} 1\n"
                        for i in range(2, 9))
        model, toas = _problem(extra)
        s32, a32, _ = build_fit_step(model, toas, jac_f32=True)
        s64, a64, _ = build_fit_step(model, toas, jac_f32=False)
        dp32 = np.asarray(jax.jit(s32)(*a32)[0])
        dp64 = np.asarray(jax.jit(s64)(*a64)[0])
        np.testing.assert_allclose(dp32, dp64, rtol=1e-12)

    def test_noise_model_ecorr(self):
        extra = ("EFAC -be X 1.1\nEQUAD -be X 0.3\nECORR -be X 1.2\n"
                 "TNREDAMP -13.7\nTNREDGAM 3.5\nTNREDC 10\n")
        model, toas = _problem(extra)
        for f in toas.flags:
            f["be"] = "X"
        toas._touch() if hasattr(toas, "_touch") else None
        _compare(model, toas)

    @pytest.mark.parametrize("binpar", [
        "BINARY ELL1\nPB 0.38 1\nA1 1.42 1\nTASC 54999.93 1\n"
        "EPS1 1e-5 1\nEPS2 -2e-5 1\n",
        "BINARY DD\nPB 67.8 1\nA1 32.3 1\nT0 54999.1 1\nECC 0.27 1\n"
        "OM 120.0 1\nOMDOT 0.01 1\nSINI 0.9 1\nM2 0.3 1\n",
        "BINARY ELL1\nFB0 3.05e-5 1\nFB1 -1e-19 1\nA1 1.42 1\n"
        "TASC 54999.93 1\nEPS1 1e-5 1\nEPS2 -2e-5 1\n",
    ], ids=["ell1-short-pb", "dd-ecc", "ell1-fb"])
    def test_binary(self, binpar):
        model, toas = _problem(binpar)
        _compare(model, toas)

    def test_jacobian_columns_relative(self):
        """Column-level check: every f32 column within 1e-5 of its f64
        twin, relative to the column max (tighter than the step-level
        check, which is condition-number amplified)."""
        from pint_tpu.parallel.fit_step import _split32, _tree_to32

        extra = ("BINARY ELL1\nPB 0.38 1\nA1 1.42 1\nTASC 54999.93 1\n"
                 "EPS1 1e-5 1\nEPS2 -2e-5 1\nPMRA 2.0 1\nPMDEC -3 1\n")
        model, toas = _problem(extra)
        phase_fn, _ = model._build_phase_fn()
        cache = model.get_cache(toas)
        free, _, th, tl, fh, fl = model._pack()
        batch = cache["batch"]
        sc = {k: v for k, v in cache.items() if k != "batch"}

        def p64(thx):
            ph, _ = phase_fn(thx, tl, fh, fl, batch, sc)
            return ph.hi + ph.lo

        jac64 = np.asarray(jax.jacfwd(p64)(jnp.asarray(th)))
        batch32, sc32 = _tree_to32(batch), _tree_to32(sc)
        ua, ub = _split32(jnp.asarray(th), jnp.asarray(tl))
        fa, fb = _split32(jnp.asarray(fh), jnp.asarray(fl))

        def p32(ua_):
            ph, _ = phase_fn(ua_, ub, fa, fb, batch32, sc32)
            return ph.hi + ph.lo

        jac32 = np.asarray(jax.jacfwd(p32)(ua), np.float64)
        assert jac32.dtype == np.float64  # cast after, computed f32
        for j, nm in enumerate(free):
            cmax = np.max(np.abs(jac64[:, j]))
            assert np.max(np.abs(jac64[:, j] - jac32[:, j])) < 1e-5 * cmax, nm

    def test_f32_chain_has_zero_f64_ops(self):
        """The whole f32 phase re-trace must be pure f32: a single
        promotion (e.g. a Python-float divisor typed f64 by a dd
        helper — the dd_div_f bug this guards against) silently drags
        the entire downstream chain back onto emulated f64 on TPU."""
        from pint_tpu.parallel.fit_step import _split32, _tree_to32

        extra = ("F2 1e-26 1\nBINARY ELL1\nPB 0.38 1\nA1 1.42 1\n"
                 "TASC 54999.93 1\nEPS1 1e-5 1\nEPS2 -2e-5 1\n")
        model, toas = _problem(extra, n=100)
        phase_fn, _ = model._build_phase_fn()
        cache = model.get_cache(toas)
        _, _, th, tl, fh, fl = model._pack()
        batch32 = _tree_to32(cache["batch"])
        sc32 = _tree_to32({k: v for k, v in cache.items()
                           if k != "batch"})
        ua, ub = _split32(jnp.asarray(th), jnp.asarray(tl))
        fa, fb = _split32(jnp.asarray(fh), jnp.asarray(fl))

        def p32(u):
            ph, _ = phase_fn(u, ub, fa, fb, batch32, sc32)
            return ph.hi + ph.lo

        assert p32(ua).dtype == jnp.float32
        jaxpr = jax.make_jaxpr(p32)(ua)
        bad = [eqn.primitive.name for eqn in jaxpr.jaxpr.eqns
               for v in eqn.outvars
               if getattr(v.aval, "dtype", None) == jnp.float64]
        assert not bad, f"f64 ops leaked into the f32 chain: {bad[:10]}"

    def test_env_override(self, monkeypatch):
        from pint_tpu.parallel.fit_step import _use_f32_jac

        monkeypatch.setenv("PINT_TPU_JAC", "f32")
        assert _use_f32_jac(None) is True
        monkeypatch.setenv("PINT_TPU_JAC", "f64")
        assert _use_f32_jac(None) is False
        assert _use_f32_jac(True) is True
