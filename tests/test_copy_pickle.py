"""copy.deepcopy / pickle round-trips for warm models and TOAs
(reference test strategy: tests/test_copy.py, test_pickle.py — SURVEY
§4.7). The hard case is a model whose jit caches are WARM: compiled
closures are not picklable, so __getstate__ must drop them and the
copy must re-compile lazily."""
import copy
import os
import pickle
import warnings

import numpy as np
import pytest

from pint_tpu import get_model_and_toas
from pint_tpu.fitter import WLSFitter

DATADIR = os.path.join(os.path.dirname(__file__), "datafile")


@pytest.fixture(scope="module")
def warm():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m, t = get_model_and_toas(
            os.path.join(DATADIR, "NGC6440E.par"),
            os.path.join(DATADIR, "NGC6440E.tim"))
        WLSFitter(t, m).fit_toas()  # warm the jit + TOA caches
    return m, t


def test_deepcopy_model_independent(warm):
    m, t = warm
    m2 = copy.deepcopy(m)
    f0 = m.F0.value
    m2.F0.value += 1e-7
    assert m.F0.value == f0
    chi2 = WLSFitter(t, m2).fit_toas()
    assert np.isfinite(chi2)


def test_pickle_model_roundtrip(warm):
    m, t = warm
    m3 = pickle.loads(pickle.dumps(m))
    assert m3.F0.value == m.F0.value
    assert m3.free_params == m.free_params
    # par round-trip identical text (before the refit moves params)
    assert m3.as_parfile() == m.as_parfile()
    # the copy rebuilds its compiled state and fits
    chi2 = WLSFitter(t, m3).fit_toas()
    assert np.isfinite(chi2)


def test_deepcopy_toas(warm):
    m, t = warm
    t2 = copy.deepcopy(t)
    assert t2.ntoas == t.ntoas
    np.testing.assert_array_equal(t2.mjd_day, t.mjd_day)
    t2.flags[0]["marker"] = "x"
    assert "marker" not in t.flags[0]


def test_pickle_toas_fresh_serial(warm):
    """Raw pickle round-trip (the process-pool path) — and the copy
    must get a FRESH cache serial: a pickled serial could collide
    with a locally created TOAs in the receiving process and poison
    TimingModel.get_cache."""
    m, t = warm
    t2 = pickle.loads(pickle.dumps(t))
    assert t2.ntoas == t.ntoas
    np.testing.assert_array_equal(t2.mjd_frac[0], t.mjd_frac[0])
    assert t2.flags == t.flags
    assert t2.cache_key != t.cache_key
    # usable end-to-end
    chi2 = WLSFitter(t2, pickle.loads(pickle.dumps(m))).fit_toas()
    assert np.isfinite(chi2)


def test_noise_basis_cache_respects_touch():
    """In-place TOAs mutation + _touch() must invalidate the noise
    basis cache (it keyed only on identity + noise params before:
    editing -be flags on the same object returned a STALE basis)."""
    import io

    from pint_tpu.models import get_model

    par = """
PSR TSTALE
RAJ 1:00:00
DECJ 2:00:00
F0 100 1
DM 10
PEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
ECORR -be X 0.5
"""
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    # clustered epochs: ECORR's quantization basis needs multi-TOA
    # observing epochs to produce columns
    centers = np.arange(54000.0, 54006.0)
    mjds = (centers[:, None] + np.linspace(0, 0.02, 4)[None, :]).ravel()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        t = make_fake_toas_fromMJDs(mjds, m, flags={"be": "X"})
    F1 = m.noise_model_designmatrix(t)
    assert F1 is not None and F1.shape[1] > 0  # ECORR basis active
    for f in t.flags:
        f["be"] = "Y"  # ECORR no longer selects anything
    t._touch()
    F2 = m.noise_model_designmatrix(t)
    assert F2 is None or F2.shape[1] == 0
