"""Labeled matrices + funcParameter (reference: src/pint/pint_matrix.py
DesignMatrix/CovarianceMatrix; parameter.funcParameter)."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.models.parameter import funcParameter
from pint_tpu.pint_matrix import (
    CovarianceMatrix,
    DesignMatrix,
    combine_design_matrices_by_param,
    combine_design_matrices_by_quantity,
)
from pint_tpu.simulation import make_fake_toas_uniform

PAR = """
PSR J0020+0020
RAJ 02:00:00.0 1
DECJ 10:00:00.0 1
F0 99.0 1
F1 -1e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 7.0 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
BINARY ELL1
PB 1.2
A1 2.0
TASC 55000.1
EPS1 1e-5
EPS2 2e-5
M2 0.25
SINI 0.92
"""


@pytest.fixture(scope="module")
def fitted():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO(PAR))
        rng = np.random.default_rng(1)
        toas = make_fake_toas_uniform(54500, 55500, 50, model,
                                      error_us=1.0, add_noise=True,
                                      rng=rng)
        from pint_tpu.fitter import WLSFitter

        m = copy.deepcopy(model)
        f = WLSFitter(toas, m)
        f.fit_toas(maxiter=2)
    return m, toas, f


def test_design_matrix_labels(fitted):
    m, toas, f = fitted
    dm = DesignMatrix.from_model(m, toas)
    assert dm.labels[0] == "Offset"
    assert set(dm.derivative_params()) == set(m.free_params)
    assert dm.shape == (toas.ntoas, len(m.free_params) + 1)
    col = dm.get_column("F0")
    M, names, _ = m.designmatrix(toas)
    np.testing.assert_array_equal(col, np.asarray(M)[:,
                                                     names.index("F0")])


def test_covariance_and_correlation(fitted):
    m, toas, f = fitted
    cm = CovarianceMatrix.from_fitter(f)
    corr = cm.to_correlation()
    d = np.diag(corr.matrix)
    np.testing.assert_allclose(d, 1.0, atol=1e-12)
    assert np.all(np.abs(corr.matrix) <= 1.0 + 1e-12)
    txt = cm.prettyprint()
    assert "F0" in txt and "Offset" in txt
    assert "1.000" in txt


def test_combiners(fitted):
    m, toas, f = fitted
    dm = DesignMatrix.from_model(m, toas)
    stacked = combine_design_matrices_by_quantity([dm, dm])
    assert stacked.shape == (2 * toas.ntoas, dm.shape[1])
    other = DesignMatrix(np.ones((toas.ntoas, 1)), ["EXTRA"], ["s"])
    wide = combine_design_matrices_by_param([dm, other])
    assert wide.labels[-1] == "EXTRA"
    with pytest.raises(ValueError):
        combine_design_matrices_by_param([dm, dm])  # duplicate cols


def test_func_parameter(fitted):
    import pint_tpu.derived_quantities as dq

    m, toas, f = fitted
    p = funcParameter("MF", lambda pb, a1: dq.mass_funct(pb, a1),
                      ("PB", "A1"), units="Msun").attach(m)
    assert p.value == pytest.approx(dq.mass_funct(1.2, 2.0))
    assert p.frozen
    assert p.as_parfile_line() == ""
    with pytest.raises(AttributeError):
        p.value = 3.0
    # unattached -> None
    q = funcParameter("MF2", lambda x: x, ("PB",))
    assert q.value is None
