"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(jax.sharding.Mesh + shard_map/pjit) are exercised without TPU hardware —
must be set before jax is first imported anywhere in the test process.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"


def _lint_only_run(argv) -> bool:
    """True when this pytest invocation selects EXACTLY the lint
    marker (`pytest -m lint`). The lint lane is pure AST work — it
    never dispatches — so the 8-virtual-device mesh and the
    persistent compile cache are dead weight there; skipping them is
    what makes the gate run in seconds from a cold process
    (tools/check.sh). Any other marker expression (including
    `lint or ...`) keeps the full setup."""
    for i, a in enumerate(argv):
        if a == "-m" and i + 1 < len(argv) and \
                argv[i + 1].strip() == "lint":
            return True
        if a.startswith("-m=") and a[3:].strip() == "lint":
            return True
    return False


_LINT_ONLY = _lint_only_run(sys.argv)

if not _LINT_ONLY:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The container's sitecustomize pre-imports jax and registers the axon TPU
# plugin before conftest runs, so the env vars above are too late for the
# already-imported module — use config.update, which works as long as no
# backend has been initialized yet (true at collection time).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the suite is jit-compile dominated
# (hundreds of distinct model structures); caching compiled executables
# across runs cuts wall-clock by more than half on a warm cache.
# Skipped in the lint-only lane (_lint_only_run): nothing compiles
# there, and the cache-dir probing is cold-start latency for nothing.
if not _LINT_ONLY:
    from pint_tpu.config import enable_compile_cache  # noqa: E402

    _cache_dir = enable_compile_cache(
        "PINT_TPU_TEST_JIT_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))
    # CLI smoke tests call script main()s, which enable the USER
    # compile cache (config.enable_user_compile_cache) — point it at
    # the test cache so they don't repoint jax's global cache at
    # ~/.cache mid-suite
    if _cache_dir:
        os.environ.setdefault("PINT_TPU_JIT_CACHE", _cache_dir)
    else:
        os.environ.setdefault("PINT_TPU_JIT_CACHE", "0")
else:
    os.environ.setdefault("PINT_TPU_JIT_CACHE", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running scale-up tests")
    config.addinivalue_line(
        "markers", "lint: graftlint static-analysis gate "
        "(fast standalone run: pytest -m lint)")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def recompile_guard():
    """A Sanitizer wired around the test body: assert on
    .compiles()/.builds to pin down jit-rebuild behavior (the
    params_only invariant)."""
    from pint_tpu.analysis import Sanitizer

    with Sanitizer() as san:
        yield san
