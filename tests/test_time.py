"""Tests for the time-scale / Earth-orientation / ephemeris stack.

No astropy/erfa oracle exists in this environment (SURVEY.md §4
implication), so the checks are physical invariants with known values:
leap-second table facts, TDB−TT annual amplitude ~1.657 ms, ERA/GMST
rates, Earth orbital radius ≈ 1 au and speed ≈ 29.8 km/s, site rotation
speed ≈ 465·cos(lat) m/s, MJD string round-trips at sub-ns.
"""

import numpy as np
import pytest

from pint_tpu.ops import dd_np
from pint_tpu.time import (
    earth_rotation_angle,
    gmst06,
    itrf_to_gcrs_posvel,
    mjd_to_str,
    obliquity06,
    parse_mjd_string,
    tai_minus_utc,
    tdb_minus_tt_seconds,
    tt_mjd_to_tdb_mjd,
    utc_mjd_to_tt_mjd,
)
from pint_tpu.time.mjd import parse_mjd_strings
from pint_tpu.ephemeris import get_ephemeris, AnalyticEphemeris


def test_leap_seconds():
    assert tai_minus_utc(41317.0) == 10.0
    assert tai_minus_utc(57753.9) == 36.0  # 2016-12-31
    assert tai_minus_utc(57754.0) == 37.0  # 2017-01-01
    assert tai_minus_utc(60000.0) == 37.0  # 2023, still 37
    np.testing.assert_array_equal(
        tai_minus_utc(np.array([50000.0, 58000.0])), [29.0, 37.0])


def test_utc_to_tt_offset():
    # post-2017: TT-UTC = 69.184 s
    day, frac = parse_mjd_string("58526.0")
    tt = utc_mjd_to_tt_mjd(day, frac)
    assert abs(dd_np.to_f64(tt) - (58526.0 + 69.184 / 86400)) < 1e-12


def test_mjd_string_roundtrip():
    for s in ["58526.123456789012345", "51544.000000000000001",
              "60000.999999999999999", "42000.5"]:
        day, frac = parse_mjd_string(s)
        out = mjd_to_str(day, frac, ndigits=15)
        # compare at the digit level (sub-ns: 1e-15 day = 0.1 ns)
        a = float(s)
        b = float(out)
        assert abs(a - b) < 1e-9  # f64 comparison sanity
        # exact digit check
        want_frac = s.split(".")[1] if "." in s else ""
        got_frac = out.split(".")[1]
        assert got_frac == want_frac.ljust(len(got_frac), "0")[:len(got_frac)]


def test_mjd_parse_precision_vs_longdouble():
    s = "58526.123456789012345678"
    day, frac = parse_mjd_string(s)
    ld = np.longdouble("0.123456789012345678")
    got = np.float64(np.longdouble(frac[0]) + np.longdouble(frac[1]) - ld)
    assert abs(got) < 1e-19  # day-fraction: 1e-19 day ≈ 10 ps


def test_tdb_minus_tt_shape():
    # annual sinusoid, amplitude ≈ 1.657 ms, zero-mean
    mjd = np.linspace(55000, 55365, 366)
    d = tdb_minus_tt_seconds(mjd)
    assert 1.5e-3 < d.max() < 1.8e-3
    assert -1.8e-3 < d.min() < -1.5e-3
    assert abs(d.mean()) < 2e-4
    tdb = tt_mjd_to_tdb_mjd(dd_np.dd(55000.0))
    assert abs(dd_np.to_f64(tdb) - 55000.0) * 86400 < 2e-3


def test_era_and_gmst_rates():
    # ERA advances ~2π·1.0027379 per day
    e0 = earth_rotation_angle(58000.0)
    e1 = earth_rotation_angle(58001.0)
    rate = (e1 - e0) % (2 * np.pi)
    assert abs(rate - 2 * np.pi * 0.00273781191135448) < 1e-10
    g = gmst06(51544.5, 51544.5)
    # GMST at J2000.0 noon ≈ 18h 41m 50s ≈ 4.894961 rad
    assert abs(g - 4.894961212) < 1e-4


def test_obliquity():
    assert abs(obliquity06(51544.5) - 84381.406 * np.pi / (180 * 3600)) < 1e-12


def test_itrf_to_gcrs_geometry():
    # GBT coordinates (SURVEY.md A.9)
    gbt = np.array([882589.65, -4924872.32, 3943729.35])
    mjd = np.linspace(58000, 58001, 25)
    pos, vel = itrf_to_gcrs_posvel(gbt, mjd, mjd + 69.184 / 86400)
    r = np.linalg.norm(gbt)
    # radius preserved by rotations
    np.testing.assert_allclose(np.linalg.norm(pos, axis=1), r, rtol=1e-12)
    # site speed = Ω × ρ_cyl
    rho = np.hypot(gbt[0], gbt[1])
    want_v = 2 * np.pi * 1.00273781191135448 / 86400 * rho
    np.testing.assert_allclose(np.linalg.norm(vel, axis=1), want_v, rtol=1e-6)
    # z oscillates daily with amplitude ρ·sin(axis tilt vs J2000):
    # precession since 2000 is ~16yr × 20″/yr ≈ 320″ → ~8 km at GBT's ρ.
    # (Constant-z holds in the true-of-date frame, not GCRS.)
    assert np.ptp(pos[:, 2]) < 25_000.0
    assert abs(np.mean(pos[:, 2]) - gbt[2]) < 15_000.0
    # one sidereal day ≈ back to start
    pos2, _ = itrf_to_gcrs_posvel(gbt, np.array([58000.0 + 0.9972695663]),
                                  np.array([58000.0 + 0.9972695663]))
    assert np.linalg.norm(pos2[0] - pos[0]) < 2000.0


def test_earth_orbit():
    eph = get_ephemeris()
    mjd = np.linspace(56000, 56365, 100)
    p, v = eph.ssb_posvel("earth", mjd)
    r = np.linalg.norm(p, axis=1)
    AU = 1.495978707e11
    # heliocentric-ish distance ~1 au (SSB offset < 0.01 au)
    assert np.all(np.abs(r / AU - 1.0) < 0.03)
    speed = np.linalg.norm(v, axis=1)
    assert np.all(np.abs(speed - 29780) < 1500)  # m/s, e=0.0167 modulation
    # orbital plane: z-component in equatorial frame oscillates with
    # obliquity tilt: max |z| ≈ sin(23.44°)·au
    assert 0.35 < np.max(np.abs(p[:, 2])) / AU < 0.42


def test_sun_near_ssb():
    eph = AnalyticEphemeris()
    p, _ = eph.ssb_posvel("sun", np.array([57000.0]))
    # Sun-SSB distance is ~0.5-2 solar radii (~7e8 m) era-dependent
    d = np.linalg.norm(p[0])
    assert 1e8 < d < 3e9


def test_jupiter_orbit():
    eph = AnalyticEphemeris()
    p, v = eph.ssb_posvel("jupiter", np.array([57000.0]))
    AU = 1.495978707e11
    assert 4.9 < np.linalg.norm(p[0]) / AU < 5.5
    assert 11000 < np.linalg.norm(v[0]) < 14500


def test_unknown_ephemeris_falls_back_with_warning():
    with pytest.warns(UserWarning, match="analytic"):
        eph = get_ephemeris("DE440")
    assert isinstance(eph, AnalyticEphemeris)


def test_parse_mjd_strings_vector():
    days, (fh, fl) = parse_mjd_strings(["58000.25", "58001.75"])
    np.testing.assert_array_equal(days, [58000.0, 58001.0])
    np.testing.assert_allclose(fh, [0.25, 0.75])
