"""pmtot + simulation flags plumbing (reference:
derived_quantities.pmtot; make_fake_toas_* flags argument)."""
import io
import warnings

import numpy as np
import pytest

from pint_tpu.derived_quantities import pmtot
from pint_tpu.models import get_model
from pint_tpu.simulation import make_fake_toas_uniform

BASE = """
PSR TT
F0 100 1
DM 10
PEPOCH 55000
TZRMJD 55000.01
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""


def _model(extra):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(BASE + extra))


class TestPmtot:
    def test_equatorial(self):
        m = _model("RAJ 1:00:00\nDECJ 2:00:00\nPMRA 3.0\nPMDEC 4.0\n")
        assert pmtot(m) == pytest.approx(5.0)

    def test_ecliptic(self):
        m = _model("ELONG 10.0\nELAT 5.0\nPMELONG 6.0\nPMELAT 8.0\n")
        assert pmtot(m) == pytest.approx(10.0)

    def test_zero_pm_astrometry(self):
        # astrometry present but no measured PM: 0, not an error
        m = _model("RAJ 1:00:00\nDECJ 2:00:00\n")
        assert pmtot(m) == 0.0


class TestSimulationFlags:
    def test_dict_applies_to_all(self):
        m = _model("RAJ 1:00:00\nDECJ 2:00:00\n")
        t = make_fake_toas_uniform(54000, 55000, 5, m,
                                   flags={"be": "X"})
        assert all(f.get("be") == "X" for f in t.flags)

    def test_length_mismatch_raises(self):
        m = _model("RAJ 1:00:00\nDECJ 2:00:00\n")
        with pytest.raises(ValueError, match="flags has 1"):
            make_fake_toas_uniform(54000, 55000, 5, m,
                                   flags=[{"be": "X"}])

    def test_flag_selected_noise_reaches_draw(self):
        """The reason flags exist on the makers: a -be-selected EFAC
        must scale the simulated white-noise draw."""
        m = _model("RAJ 1:00:00\nDECJ 2:00:00\nEFAC -be BIG 10.0\n")
        rng = np.random.default_rng(5)
        t_hot = make_fake_toas_uniform(
            54000, 55000, 400, m, error_us=1.0, add_noise=True,
            rng=rng, flags={"be": "BIG"})
        rng = np.random.default_rng(5)
        t_plain = make_fake_toas_uniform(
            54000, 55000, 400, m, error_us=1.0, add_noise=True,
            rng=rng)
        from pint_tpu.residuals import Residuals

        # the flagged set's raw scatter is ~10x the unflagged one's
        r_hot = np.std(Residuals(t_hot, m).time_resids)
        r_plain = np.std(Residuals(t_plain, m).time_resids)
        assert r_hot > 5 * r_plain
