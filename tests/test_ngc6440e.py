"""End-to-end from committed par/tim files: the NGC6440E-equivalent
fixture (BASELINE.md config #1: 62 TOAs, 6 free params, WLS smoke
test; reference fixture: tests/datafile/NGC6440E.par/.tim). The tim
was generated from the par by this framework's own simulator (SURVEY
§4 'Implication': self-consistency is the offline oracle), so the fit
must recover the par values within uncertainties from the FILES alone.
"""

import os
import warnings

import numpy as np
import pytest

DATADIR = os.path.join(os.path.dirname(__file__), "datafile")
PAR = os.path.join(DATADIR, "NGC6440E.par")
TIM = os.path.join(DATADIR, "NGC6440E.tim")


@pytest.fixture(scope="module")
def loaded():
    from pint_tpu.models import get_model_and_toas

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model_and_toas(PAR, TIM)


def test_load_files(loaded):
    model, toas = loaded
    assert toas.ntoas == 62
    # 5 free params + the implicit Offset column = config #1's "6"
    assert set(model.free_params) == {"RAJ", "DECJ", "F0", "F1", "DM"}
    assert model.name == "J1748-2021E"


def test_prefit_residuals_reasonable(loaded):
    from pint_tpu.residuals import Residuals

    model, toas = loaded
    r = Residuals(toas, model)
    # simulated at the ~13-40 us error level
    assert 2e-6 < r.rms_weighted() < 1e-4
    assert 0.3 < r.reduced_chi2 < 3.0


def test_wls_fit_recovers_parfile(loaded):
    import copy
    import io

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model

    model, toas = loaded
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        truth = get_model(PAR)
    m = copy.deepcopy(model)
    # perturb away from the par values, then require recovery
    m.get_param("F0").add_delta(2e-9)
    m.get_param("DM").add_delta(5e-3)
    m.invalidate_cache(params_only=True)
    f = WLSFitter(toas, m)
    chi2 = f.fit_toas(maxiter=2)
    assert f.resids.reduced_chi2 < 2.0
    for name in ("F0", "F1", "DM"):
        tv = truth.get_param(name).value
        fv = m.get_param(name).value
        err = f.errors[name]
        assert abs(fv - tv) < 5 * err, name
    # published-scale sanity (SURVEY A.8): F0 ~ 61.485 Hz, DM ~ 224
    assert m.F0.value == pytest.approx(61.485476554, abs=1e-6)
    assert m.get_param("DM").value == pytest.approx(223.9, abs=0.3)


def test_pintempo_on_fixture(tmp_path, capsys):
    from pint_tpu.scripts.pintempo import main

    out = tmp_path / "post.par"
    rc = main([PAR, TIM, "--outfile", str(out), "--fitter", "wls",
               "--maxiter", "2"])
    assert rc == 0
    assert "chi2" in capsys.readouterr().out
    assert out.exists()
