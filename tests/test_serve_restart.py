"""Crash-safe restart acceptance (ISSUE 8).

The restart oracle: a killed-and-restarted engine must produce
BIT-IDENTICAL responses for replayed journal entries vs an
uninterrupted run, and a warm restart from the AOT store must serve
its first bucketed request with ZERO new serve-kernel compiles
(Sanitizer ``_cache_size``-asserted). The kill is the injected
``kill_restart`` fault — a simulated SIGKILL at the drain boundary:
in-flight futures die unresolved exactly as a process death would
leave them, and the journal's unacknowledged entries are the replay
set.

Bitwise equivalence holds because (a) the replay factory rebuilds the
identical requests in journal order, so the restarted engine seals
identical buckets (same shape class, same batch pad), and (b) a
restored jax.export artifact is the SAME lowered program XLA compiled
for the uninterrupted engine — deterministic compilation on one
machine gives bit-equal outputs per batch slot.
"""

import json

import numpy as np
import pytest

from pint_tpu.runtime import Fault, FaultPlan, reset_runtime
from pint_tpu.serve import (
    EngineKilled,
    FitStepRequest,
    PhasePredictRequest,
    ServeEngine,
)
from pint_tpu.serve.journal import AotStore, RequestJournal
from pint_tpu.serve.workload import demo_polyco_entry, synth_pulsar


@pytest.fixture(autouse=True)
def clean_runtime():
    reset_runtime()
    yield
    reset_runtime()


@pytest.fixture(scope="module")
def stock():
    """Two small pulsars (prebuilt problems) + one polyco entry —
    enough for two shape classes, deterministic by construction
    (synth_pulsar is seeded)."""
    from pint_tpu.parallel.pta import build_problem

    pulsars = {k: synth_pulsar(k, 40, base=3100) for k in (0, 1)}
    problems = {k: build_problem(t, m)
                for k, (m, t) in pulsars.items()}
    return {"entry": demo_polyco_entry("RESTART"),
            "problems": problems}


def _mk_batch(stock):
    """One mixed batch with journalable payloads; composition is
    FIXED so every run seals identical buckets (same Pb — the
    bitwise-equality precondition)."""
    mjds = (55000.0 + np.linspace(-0.01, 0.01, 24)).tolist()
    return [
        PhasePredictRequest(stock["entry"], np.asarray(mjds),
                            payload={"kind": "phase", "mjds": mjds}),
        FitStepRequest(problem=stock["problems"][0],
                       payload={"kind": "fit", "k": 0}),
        FitStepRequest(problem=stock["problems"][1],
                       payload={"kind": "fit", "k": 1}),
    ]


def _factory(stock):
    def factory(payload):
        if payload["kind"] == "phase":
            return PhasePredictRequest(
                stock["entry"], np.asarray(payload["mjds"]),
                payload=payload)
        return FitStepRequest(
            problem=stock["problems"][payload["k"]], payload=payload)

    return factory


def _assert_bitwise(a, b):
    if hasattr(a, "phase_int"):
        np.testing.assert_array_equal(np.asarray(a.phase_int),
                                      np.asarray(b.phase_int))
        np.testing.assert_array_equal(np.asarray(a.phase_frac),
                                      np.asarray(b.phase_frac))
    else:
        np.testing.assert_array_equal(np.asarray(a.dparams),
                                      np.asarray(b.dparams))
        np.testing.assert_array_equal(np.asarray(a.cov),
                                      np.asarray(b.cov))
        assert a.chi2 == b.chi2 and a.chi2r == b.chi2r


def test_kill_restart_replay_bit_identical_and_warm(tmp_path, stock):
    """THE restart oracle: kill mid-burst -> restart -> replay ->
    bit-identical responses, zero new compiles on the warm engine."""
    from pint_tpu.analysis import Sanitizer

    aot = str(tmp_path / "aot")
    jpath = str(tmp_path / "journal.jsonl")

    # --- engine B: serves batch 1 (compiles + AOT-exports its
    # classes), then dies mid-drain holding batch 2
    eng_b = ServeEngine(aot_dir=aot, journal=jpath)
    b1 = [eng_b.submit(r) for r in _mk_batch(stock)]
    eng_b.flush()
    for f in b1:
        f.result(timeout=0)
    assert eng_b.cache.aot.exported == 2  # phase + gls classes
    b2 = [eng_b.submit(r) for r in _mk_batch(stock)]
    plan = FaultPlan([Fault(match="serve.drain",
                            kind="kill_restart")])
    with plan.active():
        with pytest.raises(EngineKilled):
            eng_b.flush()
    # a SIGKILL leaves futures unresolved and journal entries
    # unacknowledged — that is the replay contract
    assert all(not f.done() for f in b2)
    assert eng_b.journal.counts()["unacknowledged"] == 3
    with pytest.raises(EngineKilled):
        eng_b.submit(_mk_batch(stock)[0])

    # --- reference: an UNINTERRUPTED engine serving batch 1 then
    # batch 2 (same compositions, fresh jit compiles)
    eng_r = ServeEngine()
    r1 = [eng_r.submit(r) for r in _mk_batch(stock)]
    eng_r.flush()
    for f in r1:
        f.result(timeout=0)
    r2 = [eng_r.submit(r) for r in _mk_batch(stock)]
    eng_r.flush()
    ref = [f.result(timeout=0) for f in r2]

    # --- engine C: warm restart — restores+primes the AOT classes,
    # replays the unacknowledged journal entries
    eng_c = ServeEngine(aot_dir=aot, journal=jpath)
    assert eng_c.metrics.restart_info["warm"] is True
    assert eng_c.cache.aot.restored == 2
    with Sanitizer() as san:
        san.watch(eng_c.cache._gls, "gls")
        san.watch(eng_c.cache._phase, "phase")
        futs = eng_c.replay(_factory(stock))
        assert len(futs) == 3
        eng_c.flush()
        res = [f.result(timeout=0) for f in futs]
        growth = san.executable_growth()
    # zero new compiles: the serve kernels' executable caches did not
    # grow — the restored artifacts served the first requests
    assert all(g in (0, None) for g in growth.values()), growth
    assert eng_c.cache.jit_cache_size() in (0, None)
    assert san.compiles() == 0
    # bit-identical to the uninterrupted run, slot by slot
    for a, b in zip(res, ref):
        _assert_bitwise(a, b)
    # the journal is fully acknowledged now; the restart block labels
    # what happened
    assert eng_c.journal.counts()["unacknowledged"] == 0
    snap = eng_c.metrics.snapshot()
    assert snap["restart"]["replayed"] == 3
    assert snap["restart"]["aot"]["restored"] == 2
    assert "restart: warm=True" in eng_c.metrics.report()


def test_state_snapshot_written_on_stop(tmp_path, stock):
    from pint_tpu.serve.journal import load_state

    aot = str(tmp_path / "aot")
    eng = ServeEngine(aot_dir=aot)
    fut = eng.submit(FitStepRequest(problem=stock["problems"][0]))
    eng.flush()
    fut.result(timeout=0)
    eng.stop()
    state = load_state(aot)
    assert state is not None
    assert state["reason"] == "shutdown"
    assert state["metrics"]["completed"] == 1
    # the restarted engine reads the prior shutdown reason
    eng2 = ServeEngine(aot_dir=aot)
    assert eng2.metrics.restart_info["prior_shutdown"] == "shutdown"
    assert eng2.metrics.restart_info["warm"] is True


def test_aot_store_skips_foreign_configuration(tmp_path):
    """Artifacts from another platform / jax version / precision mode
    must be SKIPPED, never mis-served."""
    d = str(tmp_path / "aot")
    store = AotStore(d, donation=False)
    store._write_manifest({"gls/64/8/0/1": {
        "kind": "gls", "key": [64, 8, 0, 1], "file": "missing.bin",
        "avals": [[[1, 4], "float64"]], "donation": False,
        "jax": "0.0.1", "platform": "tpu", "x64": True}})
    fresh = AotStore(d, donation=False)
    assert fresh.restore_all() == 0
    assert fresh.get("gls", (64, 8, 0, 1)) is None


def test_journal_replay_set_and_torn_tail(tmp_path):
    """Unacknowledged = admits with no terminal ack ("replayed" is a
    progress marker, not terminal); a torn tail line from a crash
    mid-write is skipped, not fatal."""
    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath)
    j.admit("r1", {"kind": "x"})
    j.admit("r2", {"kind": "y"})
    j.ack("r1", "served")
    j.admit("r3", {"kind": "z"})
    j.ack("r3", "replayed")  # non-terminal: still owed
    j.close()
    with open(jpath, "a") as fh:
        fh.write('{"op": "admit", "rid": "torn')  # crash mid-write
    j2 = RequestJournal(jpath)
    un = j2.unacknowledged()
    assert [r["rid"] for r in un] == ["r2", "r3"]
    counts = j2.counts()
    assert {k: counts[k] for k in
            ("admitted", "acked", "unacknowledged")} == \
        {"admitted": 3, "acked": 1, "unacknowledged": 2}
    assert counts["compactions"] == 0 and counts["bytes"] > 0
    j2.ack("r2", "shed:shutdown")  # shed is terminal: client told
    j2.ack("r3", "served")
    assert j2.unacknowledged() == []
    j2.close()


def test_journal_compaction_replay_bit_identical(tmp_path, stock):
    """ISSUE 9 satellite: ``compact()`` rewrites the journal to
    exactly the unacknowledged admit records (atomic tmp+rename,
    original lines verbatim, progress marks dropped) — and an engine
    replaying the COMPACTED journal produces bit-identical responses
    to one replaying the uncompacted copy."""
    import shutil

    jpath = str(tmp_path / "j.jsonl")
    jcopy = str(tmp_path / "j_uncompacted.jsonl")
    eng_a = ServeEngine(journal=jpath)
    batch = _mk_batch(stock)
    f0 = eng_a.submit(batch[0])
    eng_a.flush()
    f0.result(timeout=0)             # acked: compaction drops it
    eng_a.submit(batch[1])
    eng_a.submit(batch[2])
    eng_a.journal.progress(batch[1].rid, 1)  # dropped by compaction
    del eng_a                        # simulated SIGKILL: 2 unacked

    shutil.copy(jpath, jcopy)
    j = RequestJournal(jpath)
    before = j.unacknowledged()
    assert len(before) == 2
    j.compact()
    assert j.counts()["compactions"] == 1
    assert j.unacknowledged() == before  # replay set bit-identical
    j.close()
    recs = [json.loads(x) for x in open(jpath)]
    assert [r["op"] for r in recs] == ["admit", "admit"]
    assert recs == before            # original lines verbatim
    assert not (tmp_path / "j.jsonl.tmp").exists()

    eng_b = ServeEngine(journal=jpath)
    futs_b = eng_b.replay(_factory(stock))
    eng_b.flush()
    res_b = [f.result(timeout=0) for f in futs_b]
    eng_c = ServeEngine(journal=jcopy)
    futs_c = eng_c.replay(_factory(stock))
    eng_c.flush()
    res_c = [f.result(timeout=0) for f in futs_c]
    assert len(res_b) == len(res_c) == 2
    for a, b in zip(res_b, res_c):
        _assert_bitwise(a, b)
    eng_b.stop()
    eng_c.stop()


def test_journal_auto_compaction_past_threshold(tmp_path):
    """Compaction auto-triggers when an append pushes the file past
    the byte threshold ($PINT_TPU_JOURNAL_COMPACT_BYTES /
    ``compact_bytes=``); a long-lived journal whose replay set stays
    tiny stays tiny on disk too."""
    import os

    jpath = str(tmp_path / "j.jsonl")
    j = RequestJournal(jpath, compact_bytes=512)
    for i in range(64):
        j.admit(f"r{i}", {"kind": "x", "pad": "y" * 32})
        j.ack(f"r{i}", "served")
    j.admit("tail", {"kind": "x"})   # the one live entry
    assert j.compactions >= 1
    assert [r["rid"] for r in j.unacknowledged()] == ["tail"]
    j.close()
    assert os.path.getsize(jpath) < 4 * 512
    # disabled (0) never compacts
    j2 = RequestJournal(str(tmp_path / "j2.jsonl"), compact_bytes=0)
    for i in range(64):
        j2.admit(f"r{i}", {"kind": "x", "pad": "y" * 32})
        j2.ack(f"r{i}", "served")
    assert j2.compactions == 0
    j2.close()
    # hysteresis (review fix): when the LIVE set itself exceeds the
    # threshold compaction cannot shrink it — the trigger must back
    # off (file doubles) instead of rewriting the whole journal on
    # every append during a backed-up outage
    j3 = RequestJournal(str(tmp_path / "j3.jsonl"), compact_bytes=256)
    for i in range(64):
        j3.admit(f"r{i}", {"kind": "x", "pad": "y" * 32})  # no acks
    assert len(j3.unacknowledged()) == 64
    assert j3.compactions <= 8          # ~log2, not one per append
    j3.close()


def test_replay_does_not_duplicate_admit_records(tmp_path, stock):
    """Review fix: replay() re-submits through submit(), whose
    journal hook wrote a SECOND admit line (full payload, same rid)
    per replayed entry — the journal grew by the payload volume and
    ``admitted`` double-counted on every restart cycle. A replayed
    entry owes only its terminal ack."""
    jpath = str(tmp_path / "journal.jsonl")
    eng_a = ServeEngine(journal=jpath)
    for r in _mk_batch(stock):
        eng_a.submit(r)
    del eng_a  # simulated SIGKILL: admitted, never flushed or acked

    eng_b = ServeEngine(journal=jpath)
    futs = eng_b.replay(_factory(stock))
    assert len(futs) == 3
    eng_b.flush()
    for f in futs:
        f.result(timeout=0)
    ops = [json.loads(x) for x in open(jpath)]
    admits = [o for o in ops if o["op"] == "admit"]
    assert len(admits) == 3  # one per original submit, none added
    j = RequestJournal(jpath)
    counts = j.counts()
    assert {k: counts[k] for k in
            ("admitted", "acked", "unacknowledged")} == \
        {"admitted": 3, "acked": 3, "unacknowledged": 0}
    eng_b.stop()


def test_fleet_rehome_replay_bit_identical_and_warm_aot(tmp_path,
                                                        stock):
    """ISSUE 19 acceptance: a killed fleet worker's unacknowledged
    requests re-home onto a survivor and replay BIT-IDENTICAL to an
    uninterrupted single engine serving the same batch — and when the
    shape classes were ever AOT-exported (by ANY worker into the
    shared store), the survivor serves the re-homed classes with
    ZERO new serve-kernel compiles (Sanitizer-asserted)."""
    from pint_tpu.analysis import Sanitizer
    from pint_tpu.serve.fleet import FleetFront

    aot = str(tmp_path / "aot")

    def mk_front(tag):
        return FleetFront(_factory(stock), n=2,
                          journal=str(tmp_path / f"{tag}.jsonl"),
                          aot_dir=aot, heartbeat_s=3600.0,
                          lease_ttl_s=7200.0, start=False)

    # --- front A: serves one batch so every shape class lands in the
    # SHARED AOT store (whichever worker compiles it, exports it).
    # The extra fit makes round-robin give one worker a TWO-fit gls
    # bucket — the batch class the post-re-home survivor will seal
    front_a = mk_front("ja")
    warm = _mk_batch(stock) + [
        FitStepRequest(problem=stock["problems"][0],
                       payload={"kind": "fit", "k": 0})]
    futs = [front_a.submit(r) for r in warm]
    for w in front_a.workers.values():
        w.engine.flush()
    for f in futs:
        f.result(timeout=30)
    assert sum(w.engine.cache.aot.exported
               for w in front_a.workers.values()) >= 3
    front_a.stop()

    # --- reference: an uninterrupted engine, same batch, one flush
    # (same bucket composition as the post-re-home survivor: its own
    # fit joins the re-homed fit in the one gls bucket)
    eng_r = ServeEngine()
    rfuts = [eng_r.submit(r) for r in _mk_batch(stock)]
    eng_r.flush()
    ref = [f.result(timeout=0) for f in rfuts]
    eng_r.stop()

    # --- front B: warm workers (classes restored+primed at ctor),
    # w0 dies holding phase + one fit; the survivor replays them
    # without a single new compile
    front_b = mk_front("jb")
    for w in front_b.workers.values():
        assert w.engine.cache.aot.restored == 3
        assert w.engine.metrics.restart_info["warm"] is True
    surv = front_b.workers["w1"].engine
    with Sanitizer() as san:
        san.watch(surv.cache._gls, "gls")
        san.watch(surv.cache._phase, "phase")
        futs = [front_b.submit(r) for r in _mk_batch(stock)]
        # round-robin placed phase + fit1 on w0, fit0 on w1
        front_b.kill_worker("w0")
        assert front_b.sweep() == 2
        surv.flush()
        res = [f.result(timeout=30) for f in futs]
        growth = san.executable_growth()
    assert all(g in (0, None) for g in growth.values()), growth
    assert san.compiles() == 0
    for a, b in zip(res, ref):
        _assert_bitwise(a, b)
    # zero lost: every accepted request reached its terminal ack
    assert front_b.journal.counts()["unacknowledged"] == 0
    assert front_b.snapshot()["counters"]["rehomed"] == 2
    front_b.stop()


def test_daemon_replays_unacked_journal(tmp_path, capsys):
    """The daemon's startup replay: a journal left by a killed
    process (admit, no ack) is re-served before stdin, and the
    session snapshot labels the replay."""
    import os

    from pint_tpu.scripts.pint_serve import main

    datadir = os.path.join(os.path.dirname(__file__), "datafile")
    rec = {"kind": "fit_step", "id": "r1",
           "par": os.path.join(datadir, "NGC6440E.par"),
           "tim": os.path.join(datadir, "NGC6440E.tim")}
    jpath = str(tmp_path / "j.jsonl")
    with open(jpath, "w") as fh:
        fh.write(json.dumps({"op": "admit", "rid": "r1",
                             "payload": rec}) + "\n")
    assert main(["--window-ms", "2", "--journal", jpath],
                stdin=iter(())) == 0
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    snap = lines[-1]
    assert snap["metric"] == "serve_session"
    res = [x for x in lines if x.get("id") == "r1"]
    assert len(res) == 1 and res[0]["ok"] and "chi2" in res[0]
    assert snap["restart"]["replayed"] == 1
    # fully acknowledged: a second restart owes nothing
    j = RequestJournal(jpath)
    assert j.unacknowledged() == []
    j.close()
