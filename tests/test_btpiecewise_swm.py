"""BT_piecewise binary and SWM=1 solar wind (the round-3 verdict's
"tail of the tail"; reference: src/pint/models/binary_bt.py
BinaryBTPiecewise / BT_piecewise.py, solar_wind_dispersion.py SWM 1).
Strategy per SURVEY.md §4.2: limit/equivalence cross-checks plus
jacfwd-vs-finite-difference for the new fittable parameters."""

import copy
import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform


def _mk(par: str):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(par))


def _toas(model, n=120, seed=0, start=54100, end=55900):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return make_fake_toas_uniform(
            start, end, n, model, error_us=1.0,
            rng=np.random.default_rng(seed))


BASE = """PSR J1012+5307
RAJ 10:12:33.43
DECJ 53:07:02.5
F0 310.0 1
F1 -5e-16
PEPOCH 55000
POSEPOCH 55000
DM 9.0
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
"""

BT_ORBIT = """PB 1.2
A1 3.5
T0 55000.2
ECC 0.01
OM 40.0
"""


class TestBTPiecewise:
    def test_parses_and_windows_apply(self):
        par = (BASE + "BINARY BT_piecewise\n" + BT_ORBIT
               + "T0X_0001 55000.2002 1\nA1X_0001 3.5004 1\n"
               + "XR1_0001 54800\nXR2_0001 55200\n")
        m = _mk(par)
        assert "BinaryBTPiecewise" in m.components
        toas = _toas(m)
        d_pw = np.asarray(m.delay(toas))
        # plain-BT twins for each side of the window
        m_out = _mk(BASE + "BINARY BT\n" + BT_ORBIT)
        m_in = _mk(BASE + "BINARY BT\n" + BT_ORBIT.replace(
            "T0 55000.2", "T0 55000.2002").replace("A1 3.5", "A1 3.5004"))
        d_out = np.asarray(m_out.delay(toas))
        d_in = np.asarray(m_in.delay(toas))
        batch = m.get_cache(toas)["batch"]
        mjd = np.asarray(batch.tdb_day) + np.asarray(batch.tdb_frac.hi)
        inside = (mjd >= 54800) & (mjd < 55200)
        assert inside.any() and (~inside).any()
        np.testing.assert_allclose(d_pw[inside], d_in[inside],
                                   rtol=0, atol=1e-10)
        np.testing.assert_allclose(d_pw[~inside], d_out[~inside],
                                   rtol=0, atol=1e-10)

    def test_jacfwd_vs_finite_difference(self):
        par = (BASE + "BINARY BT_piecewise\n" + BT_ORBIT
               + "T0X_0001 55000.2002 1\nA1X_0001 3.5004 1\n"
               + "XR1_0001 54800\nXR2_0001 55200\n")
        m = _mk(par)
        toas = _toas(m)
        M, names, _ = m.designmatrix(toas, incoffset=False)
        M = np.asarray(M)
        for pname, step in (("T0X_0001", 2e-6), ("A1X_0001", 1e-5)):
            j = names.index(pname)
            mp = copy.deepcopy(m)
            mp.get_param(pname).add_delta(step)
            mp.invalidate_cache(params_only=True)
            mm = copy.deepcopy(m)
            mm.get_param(pname).add_delta(-step)
            mm.invalidate_cache(params_only=True)
            rp = np.asarray(Residuals(toas, mp,
                                      subtract_mean=False).time_resids)
            rm = np.asarray(Residuals(toas, mm,
                                      subtract_mean=False).time_resids)
            fd = (rp - rm) / (2 * step)
            scale = np.max(np.abs(fd)) + 1e-30
            np.testing.assert_allclose(M[:, j] / scale, fd / scale,
                                       atol=5e-3, err_msg=pname)
            # outside the window the piece parameter only enters via
            # the (in-window) TZR phase anchor: the column is a
            # constant there, with real time dependence only inside
            batch = m.get_cache(toas)["batch"]
            mjd = np.asarray(batch.tdb_day) + \
                np.asarray(batch.tdb_frac.hi)
            outside = ~((mjd >= 54800) & (mjd < 55200))
            assert np.ptp(M[outside, j]) / scale < 1e-9
            assert np.ptp(M[~outside, j]) / scale > 1e-3

    def test_validation(self):
        with pytest.raises(ValueError, match="XR1_/XR2_"):
            _mk(BASE + "BINARY BT_piecewise\n" + BT_ORBIT
                + "T0X_0001 55000.2002 1\n")
        with pytest.raises(ValueError, match="overlap"):
            _mk(BASE + "BINARY BT_piecewise\n" + BT_ORBIT
                + "T0X_0001 55000.2002\nXR1_0001 54800\nXR2_0001 55200\n"
                + "T0X_0002 55000.2001\nXR1_0002 55100\nXR2_0002 55400\n")


SW_BASE = BASE.replace("DM 9.0", "DM 9.0\nNE_SW 8.0 1")


class TestSolarWindSWM1:
    def test_swp2_matches_swm0(self):
        """n_e ~ r^-2 is the SWM-0 closed form: the SWM-1 quadrature
        must reproduce it to quadrature accuracy."""
        m0 = _mk(SW_BASE + "SWM 0\n")
        m1 = _mk(SW_BASE + "SWM 1\nSWP 2.0\n")
        toas = _toas(m0, n=200)
        d0 = np.asarray(m0.delay(toas))
        d1 = np.asarray(m1.delay(toas))
        np.testing.assert_allclose(d1, d0, rtol=1e-9, atol=1e-13)

    def test_steeper_profile_falls_faster(self):
        """Away from conjunction, a steeper density profile (larger
        SWP) gives less DM at 1 AU-scale impact parameters... with the
        1 AU normalization the p-dependence is monotone in the
        geometry; just check order and positivity."""
        m1 = _mk(SW_BASE + "SWM 1\nSWP 2.0\n")
        m2 = _mk(SW_BASE + "SWM 1\nSWP 2.6\n")
        m_off = _mk(SW_BASE.replace("NE_SW 8.0 1", "NE_SW 0.0")
                    + "SWM 0\n")
        toas = _toas(m1, n=100)
        base = np.asarray(m_off.delay(toas))
        d1 = np.asarray(m1.delay(toas)) - base
        d2 = np.asarray(m2.delay(toas)) - base
        assert np.all(d1 > 0) and np.all(d2 > 0)
        # both carry the conjunction spike at the same epoch
        assert abs(int(np.argmax(d1)) - int(np.argmax(d2))) <= 1

    def test_jacfwd_vs_finite_difference_ne_sw_swp(self):
        par = SW_BASE.replace("NE_SW 8.0 1", "NE_SW 8.0 1") \
            + "SWM 1\nSWP 2.3 1\n"
        m = _mk(par)
        toas = _toas(m, n=100)
        M, names, _ = m.designmatrix(toas, incoffset=False)
        M = np.asarray(M)
        for pname, step in (("NE_SW", 1e-3), ("SWP", 1e-4)):
            j = names.index(pname)
            mp = copy.deepcopy(m)
            mp.get_param(pname).add_delta(step)
            mp.invalidate_cache(params_only=True)
            mm = copy.deepcopy(m)
            mm.get_param(pname).add_delta(-step)
            mm.invalidate_cache(params_only=True)
            rp = np.asarray(Residuals(toas, mp,
                                      subtract_mean=False).time_resids)
            rm = np.asarray(Residuals(toas, mm,
                                      subtract_mean=False).time_resids)
            fd = (rp - rm) / (2 * step)
            scale = np.max(np.abs(fd)) + 1e-30
            np.testing.assert_allclose(M[:, j] / scale, fd / scale,
                                       atol=5e-3, err_msg=pname)

    def test_swm1_validation(self):
        with pytest.raises(ValueError, match="SWP"):
            _mk(SW_BASE + "SWM 1\nSWP 0.5\n")
        with pytest.raises(NotImplementedError):
            _mk(SW_BASE + "SWM 2\n")


def test_btpiecewise_parfile_roundtrip():
    """as_parfile keeps the piece windows/values and the rebuilt model
    matches (incl. the MJDParameter dd split of T0X epochs)."""
    par = (BASE + "BINARY BT_piecewise\n" + BT_ORBIT
           + "T0X_0001 55000.20021234567 1\nA1X_0001 3.5004 1\n"
           + "XR1_0001 54800\nXR2_0001 55200\n")
    m = _mk(par)
    m2 = _mk(m.as_parfile())
    assert "BinaryBTPiecewise" in m2.components
    for nm in ("T0X_0001", "A1X_0001", "XR1_0001", "XR2_0001"):
        v1, v2 = m.get_param(nm).value, m2.get_param(nm).value
        assert v2 == pytest.approx(v1, rel=0, abs=1e-12), nm
    # the T0X dd pair survives the round trip to sub-ns
    d1 = m.get_param("T0X_0001").dd
    d2 = m2.get_param("T0X_0001").dd
    assert abs((d1[0] - d2[0]) + (d1[1] - d2[1])) < 1e-13  # days
