"""Ingestion-layer tests: tim parsing, observatory registry, TOA pipeline
(reference test analogs: tests/test_toa_reader.py, test_toa_flag.py,
test_observatory.py)."""

import io

import numpy as np
import pytest

from pint_tpu.io.par import parse_parfile, parfile_dict
from pint_tpu.io.tim import parse_tim, write_tim
from pint_tpu.observatory import get_observatory, list_observatories
from pint_tpu.toa import TOAs, get_TOAs, get_TOAs_array, merge_TOAs

TIM = """FORMAT 1
C a comment
fake.ff 1400.000000 53478.2858714192189 21.710 gbt -be GUPPI -pn 12
fake.ff 1400.000000 53483.2767051885165 21.950 gbt -be GUPPI
fake.ff 428.000000 53489.4683897879295 29.950 @ -fe L-wide
"""


def test_parse_tim_basic():
    toas = parse_tim(TIM)
    assert len(toas) == 3
    assert toas[0].mjd_str == "53478.2858714192189"
    assert toas[0].flags["be"] == "GUPPI"
    assert toas[0].flags["pn"] == "12"
    assert toas[2].obs == "@"
    assert toas[1].error_us == pytest.approx(21.95)


def test_tim_commands():
    text = """FORMAT 1
MODE 1
a 1400 50000.5 1.0 gbt
SKIP
b 1400 50001.5 1.0 gbt
NOSKIP
EFAC 2
c 1400 50002.5 1.0 gbt
END
d 1400 50003.5 1.0 gbt
"""
    toas = parse_tim(text)
    assert [t.name for t in toas] == ["a", "c"]
    assert toas[1].error_us == pytest.approx(2.0)


def test_tim_roundtrip(tmp_path):
    toas = parse_tim(TIM)
    p = tmp_path / "out.tim"
    write_tim(str(p), toas)
    back = parse_tim(str(p))
    assert len(back) == len(toas)
    assert back[0].mjd_str == toas[0].mjd_str
    assert back[0].flags["be"] == "GUPPI"


def test_parse_parfile():
    par = """PSR J1234+5678
F0 61.485476554373 1 1e-10
F1 -1.1815e-15 1
DM 223.9
JUMP -fe L-wide 0.000216 1 0.000002
JUMP -fe 430 0.000181 1
# comment
RAJ 17:48:52.75
"""
    lines = parse_parfile(par)
    d = parfile_dict(lines)
    assert d["F0"][0][0] == "61.485476554373"
    assert len(d["JUMP"]) == 2
    assert d["JUMP"][1][1] == "430"


def test_observatory_registry():
    gbt = get_observatory("gbt")
    assert get_observatory("1") is gbt
    assert get_observatory("GBT") is gbt
    bary = get_observatory("@")
    assert bary.timescale == "tdb"
    assert "meerkat" in list_observatories()
    with pytest.raises(KeyError):
        get_observatory("notasite")


def test_toa_pipeline():
    t = get_TOAs(io.StringIO(TIM), ephem=None)
    assert t.ntoas == 3
    assert t.tdb_day is not None
    # TAI-UTC = 32 s in April 2005 → TDB-UTC ~ 32 + 32.184 s
    delta_day = (t.tdb_day + t.tdb_frac[0]) - t.get_mjds()
    assert np.allclose(delta_day[:2] * 86400, 64.184, atol=0.01)
    # barycentric TOA passes through unchanged
    assert delta_day[2] * 86400 == pytest.approx(0.0, abs=1e-6)
    # Earth orbital position ~ 1 AU from SSB for ground sites, 0 for @
    r = np.linalg.norm(t.ssb_obs_pos, axis=1)
    assert 1.3e11 < r[0] < 1.7e11
    assert r[2] == 0.0
    # orbital speed ~30 km/s
    v = np.linalg.norm(t.ssb_obs_vel, axis=1)
    assert 2.5e4 < v[0] < 3.5e4
    # Sun roughly 1 AU from observer
    rs = np.linalg.norm(t.obs_sun_pos, axis=1)
    assert 1.4e11 < rs[0] < 1.6e11


def test_to_batch():
    t = get_TOAs(io.StringIO(TIM), planets=True)
    b = t.to_batch()
    assert b.ntoas == 3
    assert b.obs_planet_pos.shape == (5, 3, 3)
    # light-seconds: Earth ~ 499 s from SSB
    r = np.linalg.norm(np.asarray(b.ssb_obs_pos), axis=1)
    assert 450 < r[0] < 520
    pn = np.asarray(b.pulse_number)
    assert pn[0] == 12.0 and np.isnan(pn[1])


def test_get_toas_array_and_merge():
    t1 = get_TOAs_array(np.array([55000.1, 55001.2]), obs="parkes",
                        freqs=1400.0, errors=0.5)
    t2 = get_TOAs_array(np.array([55002.3]), obs="parkes", freqs=1400.0)
    m = merge_TOAs([t1, t2])
    assert m.ntoas == 3
    assert m.ssb_obs_pos.shape == (3, 3)
    assert np.all(np.diff(m.get_mjds()) > 0)


def test_select():
    t = get_TOAs(io.StringIO(TIM))
    sub = t.select(np.array([True, False, True]))
    assert sub.ntoas == 2
    assert sub.obs == ["gbt", "barycenter"]
    assert sub.ssb_obs_pos.shape == (2, 3)


def test_write_roundtrip_mjd_precision(tmp_path):
    t = get_TOAs(io.StringIO(TIM))
    p = tmp_path / "rt.tim"
    t.write_TOA_file(str(p))
    back = parse_tim(str(p))
    # MJD strings survive the clock-correction round trip to ~ps
    assert back[0].mjd_str.startswith("53478.28587141921")


def test_toas_npz_cache_roundtrip(tmp_path):
    """usecache: first get_TOAs builds + saves, second loads the npz;
    both produce identical pipelines (reference: usepickle)."""
    import io as _io
    import warnings

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.toa import TOAs, get_TOAs

    par = ("PSR J0001+0001\nRAJ 0:01:00 1\nDECJ 1:00:00 1\n"
           "F0 100.0 1\nPEPOCH 55500\nDM 10.0\nUNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(_io.StringIO(par))
        rng = np.random.default_rng(3)
        t0 = make_fake_toas_uniform(55000, 55100, 20, model,
                                    error_us=1.5, obs="gbt", rng=rng)
    tim = tmp_path / "c.tim"
    t0.write_TOA_file(tim)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        a = get_TOAs(str(tim), ephem="de421", usecache=True)
    caches = list(tmp_path.glob(".c.tim.toacache.npz"))
    assert len(caches) == 1
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        b = get_TOAs(str(tim), ephem="de421", usecache=True)
    np.testing.assert_array_equal(a.mjd_day, b.mjd_day)
    np.testing.assert_array_equal(a.mjd_frac[0], b.mjd_frac[0])
    np.testing.assert_array_equal(a.tdb_frac[1], b.tdb_frac[1])
    np.testing.assert_array_equal(a.ssb_obs_pos, b.ssb_obs_pos)
    assert a.obs == b.obs
    assert a.flags == b.flags
    assert b.clock_applied
    # a knob change invalidates and overwrites IN PLACE (one cache
    # file per tim, never an accumulation of hashed siblings)
    mtime0 = caches[0].stat().st_mtime_ns
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c2 = get_TOAs(str(tim), ephem="de421", usecache=True,
                      include_bipm=False)
    assert len(list(tmp_path.glob(".c.tim*.npz"))) == 1
    assert caches[0].stat().st_mtime_ns != mtime0
    # direct npz round-trip API
    p = tmp_path / "snap.npz"
    a.to_npz(p)
    c = TOAs.from_npz(p)
    assert c.ntoas == a.ntoas
    np.testing.assert_array_equal(c.ssb_obs_vel, a.ssb_obs_vel)


def test_include_jump_blocks_get_distinct_ids(tmp_path):
    """JUMP blocks in INCLUDE'd tim files are physically independent of
    the includer's and must not share -tim_jump ids."""
    from pint_tpu.io.tim import parse_tim

    inner = tmp_path / "inner.tim"
    inner.write_text("FORMAT 1\nJUMP\n in1 1400.0 55010.0 1.0 @\n"
                     "JUMP\n in2 1400.0 55011.0 1.0 @\n")
    outer = tmp_path / "outer.tim"
    outer.write_text("FORMAT 1\nJUMP\n a 1400.0 55000.0 1.0 @\nJUMP\n"
                     f"INCLUDE {inner.name}\n"
                     "JUMP\n b 1400.0 55020.0 1.0 @\nJUMP\n"
                     " c 1400.0 55030.0 1.0 @\n")
    toas = parse_tim(str(outer))
    ids = {t.name: t.flags.get("tim_jump") for t in toas}
    assert ids["a"] == "1"
    assert ids["in1"] == "2"
    assert ids["b"] == "3"
    assert ids["c"] is None
    assert len({v for v in ids.values() if v}) == 3
