"""Fitter tests: simulate→perturb→fit→recover (the strongest available
oracle, SURVEY.md §4), WLS vs Downhill agreement, summary output
(reference analogs: tests/test_fitter.py, test_wls_fitter.py,
test_downhill_fitter.py)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.fitter import DownhillWLSFitter, Fitter, WLSFitter
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs

PAR = """PSR J1748-2021E
RAJ 17:48:52.75 1
DECJ -20:21:29.0 1
F0 61.485476554373152 1
F1 -1.1815e-15 1
PEPOCH 53750.0
POSEPOCH 53750.0
DM 223.9 1
DMEPOCH 53750.0
TZRMJD 53750.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""

PERTURB = {"F0": 3e-9, "F1": 2e-17, "DM": 2e-3, "RAJ": 2e-8,
           "DECJ": 3e-8}


@pytest.fixture(scope="module")
def sim():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR))
        rng = np.random.default_rng(7)
        tA = make_fake_toas_uniform(53400, 54100, 50, m, error_us=1.0,
                                    obs="gbt", freq_mhz=1400.0,
                                    add_noise=True, rng=rng)
        tB = make_fake_toas_uniform(53410, 54090, 30, m, error_us=1.5,
                                    obs="gbt", freq_mhz=428.0,
                                    add_noise=True, rng=rng)
        t = merge_TOAs([tA, tB])
    truth = {n: m.get_param(n).value for n in m.free_params}
    return m, t, truth


def _perturb(m):
    for name, dx in PERTURB.items():
        m.get_param(name).add_delta(dx)
    m.invalidate_cache(params_only=True)


def _restore(m, truth):
    for name, v in truth.items():
        p = m.get_param(name)
        p.value = v
    m.invalidate_cache(params_only=True)


@pytest.mark.parametrize("cls,kw", [
    (WLSFitter, dict(maxiter=3)),
    (DownhillWLSFitter, dict(maxiter=15)),
])
def test_fit_recovers_truth(sim, cls, kw):
    m, t, truth = sim
    _restore(m, truth)
    _perturb(m)
    assert Residuals(t, m).rms_weighted() > 1e-4  # badly perturbed
    f = cls(t, m)
    chi2 = f.fit_toas(**kw)
    assert f.resids.rms_weighted() < 3e-6
    assert chi2 / f.resids.dof < 1.5
    for name, tv in truth.items():
        p = m.get_param(name)
        assert p.uncertainty is not None and p.uncertainty > 0
        pull = (p.value - tv) / p.uncertainty
        assert abs(pull) < 5, f"{name} pull {pull}"
    _restore(m, truth)


def test_fit_idempotent_at_truth(sim):
    """Fitting from the truth moves parameters < 1 sigma."""
    m, t, truth = sim
    _restore(m, truth)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    for name, tv in truth.items():
        p = m.get_param(name)
        assert abs(p.value - tv) < 3 * p.uncertainty
    _restore(m, truth)


def test_auto_picks_wls(sim):
    m, t, _ = sim
    f = Fitter.auto(t, m, downhill=False)
    assert isinstance(f, WLSFitter)
    f2 = Fitter.auto(t, m)
    assert isinstance(f2, DownhillWLSFitter)


def test_summary_runs(sim):
    m, t, truth = sim
    _restore(m, truth)
    f = WLSFitter(t, m)
    f.fit_toas()
    from pint_tpu.fitter import fit_summary

    s = fit_summary(f)
    assert "F0" in s and "chi2" in s
    _restore(m, truth)


def test_simulation_zero_residuals(sim):
    m, t, truth = sim
    _restore(m, truth)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t0 = make_fake_toas_uniform(53500, 53600, 10, m, error_us=1.0,
                                    obs="gbt", add_noise=False)
    r = Residuals(t0, m, subtract_mean=False)
    assert np.max(np.abs(r.time_resids)) < 1e-9
