"""Fitter tests: simulate→perturb→fit→recover (the strongest available
oracle, SURVEY.md §4), WLS vs Downhill agreement, summary output
(reference analogs: tests/test_fitter.py, test_wls_fitter.py,
test_downhill_fitter.py)."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.fitter import DownhillWLSFitter, Fitter, WLSFitter
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.toa import merge_TOAs

PAR = """PSR J1748-2021E
RAJ 17:48:52.75 1
DECJ -20:21:29.0 1
F0 61.485476554373152 1
F1 -1.1815e-15 1
PEPOCH 53750.0
POSEPOCH 53750.0
DM 223.9 1
DMEPOCH 53750.0
TZRMJD 53750.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""

PERTURB = {"F0": 3e-9, "F1": 2e-17, "DM": 2e-3, "RAJ": 2e-8,
           "DECJ": 3e-8}


@pytest.fixture(scope="module")
def sim():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(PAR))
        rng = np.random.default_rng(7)
        tA = make_fake_toas_uniform(53400, 54100, 50, m, error_us=1.0,
                                    obs="gbt", freq_mhz=1400.0,
                                    add_noise=True, rng=rng)
        tB = make_fake_toas_uniform(53410, 54090, 30, m, error_us=1.5,
                                    obs="gbt", freq_mhz=428.0,
                                    add_noise=True, rng=rng)
        t = merge_TOAs([tA, tB])
    truth = {n: m.get_param(n).value for n in m.free_params}
    return m, t, truth


def _perturb(m):
    for name, dx in PERTURB.items():
        m.get_param(name).add_delta(dx)
    m.invalidate_cache(params_only=True)


def _restore(m, truth):
    for name, v in truth.items():
        p = m.get_param(name)
        p.value = v
    m.invalidate_cache(params_only=True)


@pytest.mark.parametrize("cls,kw", [
    (WLSFitter, dict(maxiter=3)),
    (DownhillWLSFitter, dict(maxiter=15)),
])
def test_fit_recovers_truth(sim, cls, kw):
    m, t, truth = sim
    _restore(m, truth)
    _perturb(m)
    assert Residuals(t, m).rms_weighted() > 1e-4  # badly perturbed
    f = cls(t, m)
    chi2 = f.fit_toas(**kw)
    assert f.resids.rms_weighted() < 3e-6
    assert chi2 / f.resids.dof < 1.5
    for name, tv in truth.items():
        p = m.get_param(name)
        assert p.uncertainty is not None and p.uncertainty > 0
        pull = (p.value - tv) / p.uncertainty
        assert abs(pull) < 5, f"{name} pull {pull}"
    _restore(m, truth)


def test_fit_idempotent_at_truth(sim):
    """Fitting from the truth moves parameters < 1 sigma."""
    m, t, truth = sim
    _restore(m, truth)
    f = WLSFitter(t, m)
    f.fit_toas(maxiter=2)
    for name, tv in truth.items():
        p = m.get_param(name)
        assert abs(p.value - tv) < 3 * p.uncertainty
    _restore(m, truth)


def test_auto_picks_wls(sim):
    m, t, _ = sim
    f = Fitter.auto(t, m, downhill=False)
    assert isinstance(f, WLSFitter)
    f2 = Fitter.auto(t, m)
    assert isinstance(f2, DownhillWLSFitter)


def test_summary_runs(sim):
    m, t, truth = sim
    _restore(m, truth)
    f = WLSFitter(t, m)
    f.fit_toas()
    from pint_tpu.fitter import fit_summary

    s = fit_summary(f)
    assert "F0" in s and "chi2" in s
    _restore(m, truth)


def test_simulation_zero_residuals(sim):
    m, t, truth = sim
    _restore(m, truth)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t0 = make_fake_toas_uniform(53500, 53600, 10, m, error_us=1.0,
                                    obs="gbt", add_noise=False)
    r = Residuals(t0, m, subtract_mean=False)
    assert np.max(np.abs(r.time_resids)) < 1e-9


def test_ecorr_average():
    """Epoch-averaged residuals (reference: Residuals.ecorr_average):
    per-ECORR-epoch weighted means with the epoch jitter folded into
    the averaged error."""
    import io as _io

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ("PSR JAVG\nRAJ 2:00:00 1\nDECJ 2:00:00 1\nF0 200.0 1\n"
           "PEPOCH 55000\nDM 15\nEFAC -be X 1.0\nECORR -be X 2.0\n"
           "UNITS TDB\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(_io.StringIO(par))
        rng = np.random.default_rng(3)
        centers = np.linspace(54000, 55000, 10)
        mjds = (centers[:, None]
                + np.array([0.0, 0.01, 0.02, 0.03])[None, :]).ravel()
        # one lone TOA far from every epoch
        mjds = np.concatenate([mjds, [55500.0]])
        t = make_fake_toas_fromMJDs(mjds, m, error_us=1.0,
                                    add_noise=True, rng=rng)
        for f in t.flags:
            f["be"] = "X"
        m.invalidate_cache()
        res = Residuals(t, m)
        avg = res.ecorr_average()
    assert len(avg["mjds"]) == 11  # 10 epochs + 1 unaveraged loner
    assert np.all(np.diff(avg["mjds"]) > 0)
    assert avg["n"].sum() == 41
    # averaged error: sqrt(sigma^2/4 + ecorr^2) for 4 x 1us + 2us
    expect = np.sqrt((1e-6) ** 2 / 4 + (2e-6) ** 2)
    four = avg["n"] == 4
    np.testing.assert_allclose(avg["errors"][four], expect, rtol=1e-6)
    # the loner keeps its single-TOA error, no jitter folded in
    lone = avg["n"] == 1
    np.testing.assert_allclose(avg["errors"][lone], 1e-6, rtol=1e-6)
    # averaged residual equals the hand-computed weighted mean
    idx0 = avg["indices"][0]
    r = res.time_resids
    np.testing.assert_allclose(avg["time_resids"][0],
                               np.mean(r[idx0]), rtol=1e-12)
    # gap-clustering path (no noise model consulted) finds the same
    # epochs here
    avg2 = res.ecorr_average(use_noise_model=False)
    assert len(avg2["mjds"]) == 11
