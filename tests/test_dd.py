"""Property tests for the double-double core vs host numpy longdouble.

Mirrors the reference's precision test layer (tests/test_precision.py,
which fuzzes longdouble/two-double conversions with hypothesis) — here the
oracle is x87 longdouble on the host CPU (eps 1.08e-19), which dd (~1e-32)
must beat.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pint_tpu.ops import (
    DD,
    dd,
    dd_add,
    dd_div,
    dd_frac,
    dd_mul,
    dd_round,
    dd_sub,
    dd_to_f64,
    dd_taylor_horner,
    taylor_horner,
    taylor_horner_deriv,
)
from pint_tpu.ops.dd import dd_sum, dd_int_frac, dd_lt, dd_where
from pint_tpu.phase import Phase

LD = np.longdouble


def _rand_dd(rng, n, scale=1.0):
    hi = rng.uniform(-scale, scale, n)
    lo = hi * rng.uniform(-1e-17, 1e-17, n)
    return dd(jnp.asarray(hi), jnp.asarray(lo)), LD(hi) + LD(lo)


def _as_ld(a: DD):
    return LD(np.asarray(a.hi)) + LD(np.asarray(a.lo))


@pytest.mark.parametrize("op,ldop", [
    (dd_add, lambda a, b: a + b),
    (dd_sub, lambda a, b: a - b),
    (dd_mul, lambda a, b: a * b),
    (dd_div, lambda a, b: a / b),
])
def test_dd_binary_ops_beat_longdouble(rng, op, ldop):
    a, a_ld = _rand_dd(rng, 500, scale=1e9)
    b, b_ld = _rand_dd(rng, 500, scale=1e3)
    got = _as_ld(op(a, b))
    want = ldop(a_ld, b_ld)
    rel = np.abs(np.float64((got - want) / want))
    # longdouble oracle itself has eps 1.08e-19; dd must agree to that level
    assert np.max(rel) < 5e-19


def test_dd_add_exact_cancellation(rng):
    # (big + tiny) - big == tiny exactly
    big = dd(jnp.asarray(1.0e16))
    tiny = dd(jnp.asarray(1e-9))
    r = dd_sub(dd_add(big, tiny), big)
    assert float(dd_to_f64(r)) == 1e-9


def test_dd_mul_splits_exactly():
    # 86400 * mjd keeps sub-ns: mjd = 58526.123456789012345 (beyond f64)
    m = dd(jnp.asarray(58526.0), jnp.asarray(0.123456789012345))
    sec = dd_mul(m, dd(jnp.asarray(86400.0)))
    want = (LD(58526.0) + LD(0.123456789012345)) * LD(86400)
    got = _as_ld(sec)
    assert abs(np.float64(got - want)) < 1e-12  # seconds


def test_round_frac_consistency(rng):
    x, x_ld = _rand_dd(rng, 1000, scale=1e10)
    n, f = dd_int_frac(x)
    # n + f == x exactly (in dd)
    back = dd_add(n, f)
    assert np.array_equal(np.asarray(back.hi), np.asarray(x.hi))
    f64 = np.asarray(dd_to_f64(f))
    assert np.all(np.abs(f64) <= 0.5 + 1e-15)
    # frac matches longdouble computation — to within the *oracle's* own
    # rounding: LD(hi)+LD(lo) at 1e10 magnitude has ulp ≈ 1e10·1.08e-19 ≈
    # 1.1e-9. dd (exact reconstruction asserted above) is strictly better.
    want = x_ld - np.rint(np.float64(x_ld))
    diff = (np.float64(_as_ld(f)) - np.float64(want)) % 1.0
    diff = np.minimum(diff, 1.0 - diff)
    assert np.max(diff) < 2e-9


def test_phase_tracks_1e10_turns():
    # F0 * dt with F0=61.485 Hz, dt=20 yr: phase ~ 3.9e10 turns; a 1e-10 s
    # time shift (≈ 6e-9 turns) must be resolved in frac.
    F0 = 61.4854764249
    dt0 = 631152000.0  # 20 yr in s
    eps = 1e-10
    p1 = dd_mul(dd(jnp.asarray(F0)), dd(jnp.asarray(dt0)))
    p2 = dd_mul(dd(jnp.asarray(F0)), dd(jnp.asarray(dt0), jnp.asarray(eps)))
    df = dd_to_f64(dd_sub(p2, p1))
    assert abs(float(df) - F0 * eps) < 1e-16


def test_taylor_horner_basic():
    dt = jnp.asarray([0.0, 1.0, 2.0])
    # 2 + 3t + 4 t^2/2 + 12 t^3/6
    out = taylor_horner(dt, [2.0, 3.0, 4.0, 12.0])
    want = 2 + 3 * dt + 2 * dt**2 + 2 * dt**3
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-15)
    d1 = taylor_horner_deriv(dt, [2.0, 3.0, 4.0, 12.0], 1)
    want1 = 3 + 4 * dt + 6 * dt**2
    np.testing.assert_allclose(np.asarray(d1), np.asarray(want1), rtol=1e-15)


def test_dd_taylor_horner_vs_longdouble():
    # spindown-like: F0 ~ 61 Hz, F1 ~ -1e-15, dt up to 15 yr
    F0, F1, F2 = 61.4854764249, -1.1813e-15, 2.75e-25
    dts = np.linspace(-2.4e8, 2.4e8, 101)
    dtd = dd(jnp.asarray(dts))
    got = _as_ld(dd_taylor_horner(dtd, [0.0, F0, F1, F2]))
    want = (LD(F0) * LD(dts) + LD(F1) * LD(dts) ** 2 / 2
            + LD(F2) * LD(dts) ** 3 / 6)
    err_turns = np.float64(got - want)
    assert np.max(np.abs(err_turns)) < 1e-8  # ≪ 1 ns at 61 Hz (6e-8 turns/ns)


def test_dd_ops_jit_and_vmap():
    @jax.jit
    def f(x: DD, y: DD):
        return dd_frac(dd_mul(x, y))

    x = dd(jnp.linspace(1e8, 2e8, 64))
    y = dd(jnp.full(64, 61.5))
    out = f(x, y)
    assert out.hi.shape == (64,)
    out2 = jax.vmap(lambda a, b: dd_mul(a, b))(x, y)
    assert out2.hi.shape == (64,)


def test_dd_grad_through_phase():
    # d(frac(F0*dt))/dF0 == dt (mod discontinuities) — the design-matrix path
    dt = 1.2345e8

    def frac_phase(f0):
        p = dd_mul(dd(jnp.asarray(f0)), dd(jnp.asarray(dt)))
        return dd_to_f64(dd_frac(p))

    g = jax.grad(frac_phase)(61.4854764249)
    assert abs(float(g) - dt) / dt < 1e-12


def test_dd_sum_compensated():
    # sum of n large alternating values + tiny ones
    n = 1000
    hi = np.tile([1e10, -1e10], n // 2)
    tiny = np.full(n, 1e-8)
    x = dd(jnp.asarray(hi), jnp.asarray(tiny))
    s = dd_sum(x)
    assert abs(float(dd_to_f64(s)) - n * 1e-8) < 1e-12


def test_dd_comparisons_and_where():
    a = dd(jnp.asarray([1.0, 2.0, 3.0]))
    b = dd(jnp.asarray([1.0, 2.5, 2.0]), jnp.asarray([1e-20, 0.0, 0.0]))
    lt = dd_lt(a, b)
    assert list(np.asarray(lt)) == [True, True, False]
    w = dd_where(lt, a, b)
    np.testing.assert_array_equal(np.asarray(w.hi), [1.0, 2.0, 2.0])


def test_phase_wrapper():
    p = Phase(dd(jnp.asarray([1e9 + 0.25, -3.75])))
    np.testing.assert_array_equal(np.asarray(p.int), [1e9, -4.0])
    np.testing.assert_allclose(np.asarray(p.frac), [0.25, 0.25], atol=1e-16)
    q = p - Phase(dd(jnp.asarray([0.25, 0.25])))
    np.testing.assert_allclose(np.asarray(q.frac), [0.0, 0.0], atol=1e-16)
