"""Property-based fuzz of the .tim command-stream parser (SURVEY §4.3
property-test layer): random interleavings of TOA lines and commands
must preserve the stream invariants however they compose — the parser
state machine (pint_tpu/io/tim.py) has no "weird order" escape
hatches. Complements tests/test_tim_torture.py's exact-value cases.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property fuzz needs hypothesis; the "
    "zero-egress container may not ship it")
import hypothesis.strategies as st  # noqa: E402
from hypothesis import given, settings  # noqa: E402

from pint_tpu.io.tim import parse_tim

# commands the fuzzer interleaves (each a line factory taking rng-ish
# draws; kept to values that keep every TOA parseable)
_toa_counter = [0]


def _toa_line(freq, err):
    _toa_counter[0] += 1
    return (f"t{_toa_counter[0]} {freq:.3f} "
            f"5{3000 + _toa_counter[0] % 999}.{_toa_counter[0] % 10}"
            f"00000 {err:.3f} gbt")


line_strategy = st.one_of(
    st.tuples(st.just("toa"),
              st.floats(400.0, 3000.0, allow_nan=False),
              st.floats(0.5, 9.0, allow_nan=False)),
    st.tuples(st.just("TIME"), st.floats(-2.0, 2.0, allow_nan=False),
              st.just(0)),
    st.tuples(st.just("PHASE"), st.integers(-3, 3), st.just(0)),
    st.tuples(st.just("EFAC"), st.floats(0.5, 3.0, allow_nan=False),
              st.just(0)),
    st.tuples(st.just("EQUAD"), st.floats(0.0, 5.0, allow_nan=False),
              st.just(0)),
    st.tuples(st.just("SKIP"), st.just(0), st.just(0)),
    st.tuples(st.just("NOSKIP"), st.just(0), st.just(0)),
    st.tuples(st.just("JUMP"), st.just(0), st.just(0)),
    st.tuples(st.just("FORMAT"), st.just(1), st.just(0)),
)


@settings(max_examples=60, deadline=None)
@given(st.lists(line_strategy, min_size=1, max_size=40))
def test_tim_stream_invariants(items):
    _toa_counter[0] = 0
    lines = ["FORMAT 1"]
    # replay the command semantics independently to predict flags
    time_off = 0.0
    phase = 0.0
    efac, equad = 1.0, 0.0
    skipping = False
    jump_on = False
    expected = []  # (name, err_scaled, to, padd, jumped)
    for kind, a, b in items:
        if kind == "toa":
            line = _toa_line(a, b)
            lines.append(line)
            if not skipping:
                name = line.split()[0]
                # the line carries %.3f-rounded values; the oracle
                # must start from what the parser actually reads
                b_line = float(f"{b:.3f}")
                err = (b_line * efac) ** 2 + equad ** 2
                expected.append((name, err ** 0.5, time_off, phase,
                                 jump_on))
        else:
            lines.append(f"{kind} {a}".strip()
                         if kind not in ("SKIP", "NOSKIP", "JUMP")
                         else kind)
            if skipping and kind != "NOSKIP":
                continue
            if kind == "TIME":
                time_off += a
            elif kind == "PHASE":
                phase += a
            elif kind == "EFAC":
                efac = a
            elif kind == "EQUAD":
                equad = a
            elif kind == "SKIP":
                skipping = True
            elif kind == "NOSKIP":
                skipping = False
            elif kind == "JUMP":
                jump_on = not jump_on

    toas = parse_tim("\n".join(lines) + "\n")
    assert len(toas) == len(expected)
    for t, (name, err, to, padd, jumped) in zip(toas, expected):
        assert t.name == name
        np.testing.assert_allclose(t.error_us, err, rtol=1e-12)
        if to != 0.0:
            np.testing.assert_allclose(float(t.flags["to"]), to,
                                       rtol=0, atol=1e-12)
        else:
            assert "to" not in t.flags
        if padd != 0.0:
            assert float(t.flags["padd"]) == padd
        else:
            assert "padd" not in t.flags
        assert ("tim_jump" in t.flags) == jumped
