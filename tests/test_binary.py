"""Binary-model tests (reference analogs: tests/test_dd.py,
tests/test_ell1*.py, test_fbx.py, test_model_derivatives.py): Kepler
solver property, cross-model consistency (ELL1 vs BT at tiny e, DD vs
BT with Shapiro off, DDS vs DD), Shapiro conjunction behavior, FB-series
orbits, simulate→fit recovery, and jacfwd-vs-finite-difference
derivative checks."""

import io
import warnings

import numpy as np
import pytest

from pint_tpu.models import get_model
from pint_tpu.models.binary import kepler_E
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import make_fake_toas_uniform

BASE = """PSR J1012+5307
RAJ 10:12:33.43 1
DECJ 53:07:02.5 1
F0 190.2678376220576 1
F1 -6.2e-16 1
PEPOCH 55000.0
POSEPOCH 55000.0
DM 9.02 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400.0
UNITS TDB
"""

ELL1_LINES = """BINARY ELL1
PB 0.60467271355 1
A1 0.5818172 1
TASC 55000.40712 1
EPS1 1.2e-5 1
EPS2 -3.4e-6 1
"""

BT_LINES = """BINARY BT
PB 0.60467271355 1
A1 0.5818172 1
T0 55000.40712 1
ECC 1.0e-5 1
OM 45.0 1
GAMMA 0.0
"""


def _model(extra):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(io.StringIO(BASE + extra))


def _sim(m, n=80, rng=None, **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return make_fake_toas_uniform(54800, 55200, n, m, error_us=1.0,
                                      rng=rng, **kw)


def test_kepler_property():
    rng = np.random.default_rng(0)
    M = rng.uniform(-50, 50, 256)
    for e in (0.0, 1e-5, 0.1, 0.5, 0.9):
        E = np.asarray(kepler_E(M, e))
        np.testing.assert_allclose(E - e * np.sin(E), M, atol=1e-12)


def test_ell1_delay_shape():
    """Roemer delay ~ x sin(Phi): amplitude and periodicity."""
    m = _model(ELL1_LINES)
    t = _sim(m, n=200)
    d = np.asarray(m.delay(t))
    m2 = _model("")  # same model, no binary
    d2 = np.asarray(m2.delay(t))
    binary = d - d2
    x = 0.5818172
    assert np.max(binary) < x * 1.01 and np.max(binary) > x * 0.95
    assert np.min(binary) > -x * 1.01 and np.min(binary) < -x * 0.95


def test_ell1_vs_bt_small_ecc():
    """ELL1 and BT agree to ~ns at e = 1e-5 with matched
    parameterizations (EPS1 = e sin(om), EPS2 = e cos(om), TASC =
    T0 - om/n) — the upstream consistency oracle (SURVEY.md A.8e)."""
    e, om_deg = 1.0e-5, 45.0
    pb = 0.60467271355
    om = np.deg2rad(om_deg)
    eps1, eps2 = e * np.sin(om), e * np.cos(om)
    # Lange mapping: Phi = M + om, i.e. TASC = T0 - om PB/2pi
    t0 = 55000.40712
    tasc = t0 - om * pb / (2 * np.pi)
    mb = _model(BT_LINES)
    me = _model(
        "BINARY ELL1\n"
        f"PB {pb} 1\nA1 0.5818172 1\nTASC {tasc:.12f} 1\n"
        f"EPS1 {eps1:.3e} 1\nEPS2 {eps2:.3e} 1\n")
    t = _sim(mb, n=150)
    db = np.asarray(mb.delay(t))
    de = np.asarray(me.delay(t))
    # agreement to x*e^2 ~ 60 ps level; allow ns
    np.testing.assert_allclose(db, de, atol=2e-9)


def test_dd_vs_bt_no_shapiro():
    """DD with DR=DTH=0, no M2/SINI reduces to BT."""
    dd_lines = BT_LINES.replace("BINARY BT", "BINARY DD")
    mdd = _model(dd_lines)
    mbt = _model(BT_LINES)
    t = _sim(mbt, n=100)
    np.testing.assert_allclose(np.asarray(mdd.delay(t)),
                               np.asarray(mbt.delay(t)), atol=1e-12)


def test_dds_vs_dd_shapmax():
    """DDS with s = 1-exp(-SHAPMAX) matches DD with equivalent SINI."""
    sini = 0.95
    shapmax = -np.log(1.0 - sini)
    dd = BT_LINES.replace("BINARY BT", "BINARY DD") + \
        "M2 0.25 1\nSINI 0.95 1\n"
    dds = BT_LINES.replace("BINARY BT", "BINARY DDS") + \
        f"M2 0.25 1\nSHAPMAX {shapmax:.15f} 1\n"
    mdd, mdds = _model(dd), _model(dds)
    t = _sim(mdd, n=100)
    np.testing.assert_allclose(np.asarray(mdds.delay(t)),
                               np.asarray(mdd.delay(t)), atol=1e-13)


def test_shapiro_peaks_at_conjunction():
    """ELL1 Shapiro delay is largest near Phi = pi/2."""
    m = _model(ELL1_LINES + "M2 0.3 1\nSINI 0.98 1\n")
    m0 = _model(ELL1_LINES)
    t = _sim(m0, n=400)
    shap = np.asarray(m.delay(t)) - np.asarray(m0.delay(t))
    # phase of each TOA
    pb_s = 0.60467271355 * 86400.0
    tasc = 55000.40712
    mjd = t.get_mjds()
    phi = 2 * np.pi * ((mjd - tasc) * 86400.0 % pb_s) / pb_s
    peak_bin = np.abs(phi - np.pi / 2) < 0.3
    away = np.abs(phi - 3 * np.pi / 2) < 0.3
    assert shap[peak_bin].max() > shap[away].max() + 1e-7
    r = 4.925490947e-6 * 0.3
    expect_peak = -2 * r * np.log(1 - 0.98)
    assert abs(shap[peak_bin].max() - shap.min() - expect_peak) \
        < 0.3 * expect_peak


def test_fb_series_matches_pb():
    """FB0 = 1/PB_s orbit reproduces the PB orbit."""
    pb_s = 0.60467271355 * 86400.0
    fb_lines = (
        "BINARY ELL1\n"
        f"FB0 {1.0 / pb_s:.20e} 1\n"
        "A1 0.5818172 1\nTASC 55000.40712 1\n"
        "EPS1 1.2e-5 1\nEPS2 -3.4e-6 1\n")
    m1 = _model(ELL1_LINES)
    m2 = _model(fb_lines)
    assert m2.components["BinaryELL1"].fb_terms == ["FB0"]
    t = _sim(m1, n=80)
    np.testing.assert_allclose(np.asarray(m2.delay(t)),
                               np.asarray(m1.delay(t)), rtol=0, atol=5e-11)


def test_binary_derivatives_vs_finite_difference():
    """jacfwd through the Kepler solve vs central differences. Two
    frequencies so the DM column is not degenerate with the TZR
    anchor."""
    from pint_tpu.toa import merge_TOAs

    m = _model(BT_LINES + "M2 0.2\nSINI 0.9\n")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        tA = make_fake_toas_uniform(54800, 55200, 20, m, error_us=1.0,
                                    freq_mhz=1400.0)
        tB = make_fake_toas_uniform(54810, 55190, 20, m, error_us=1.0,
                                    freq_mhz=430.0)
        t = merge_TOAs([tA, tB])
    M, names, units = m.designmatrix(t, incoffset=False)
    M = np.asarray(M)
    steps = {"PB": 1e-8, "A1": 1e-7, "ECC": 1e-7, "OM": 1e-4,
             "F0": 1e-11, "DM": 1e-5}
    for pname, h in steps.items():
        j = names.index(pname)
        p = m.get_param(pname)
        # add_delta keeps the parameter's dd tail (p.value = v0 + h
        # would round F0 to f64 and noise the finite difference)
        p.add_delta(h)
        m.invalidate_cache(params_only=True)
        rp = Residuals(t, m, subtract_mean=False).time_resids
        p.add_delta(-2 * h)
        m.invalidate_cache(params_only=True)
        rm = Residuals(t, m, subtract_mean=False).time_resids
        p.add_delta(h)
        m.invalidate_cache(params_only=True)
        fd = (np.asarray(rp) - np.asarray(rm)) / (2 * h)
        scale = np.max(np.abs(fd)) + 1e-30
        np.testing.assert_allclose(M[:, j], fd, rtol=2e-5,
                                   atol=2e-6 * scale,
                                   err_msg=pname)


def test_ell1_fit_recovery():
    """Simulate with an ELL1 binary, perturb, refit, recover (the
    config-4 shape without red noise)."""
    from pint_tpu.fitter import DownhillWLSFitter

    m = _model(ELL1_LINES)
    rng = np.random.default_rng(9)
    t = _sim(m, n=120, rng=rng, add_noise=True)
    truth = {n: m.get_param(n).value for n in ("A1", "PB", "EPS1",
                                               "EPS2", "F0")}
    m.A1.add_delta(3e-6)
    m.EPS1.add_delta(2e-6)
    m.F0.add_delta(1e-10)
    m.invalidate_cache(params_only=True)
    f = DownhillWLSFitter(t, m)
    f.fit_toas(maxiter=15)
    for k, v in truth.items():
        err = f.errors.get(k)
        assert err is not None
        assert abs(m.get_param(k).value - v) < 5 * err, k


def test_binary_parfile_roundtrip():
    m = _model(ELL1_LINES + "M2 0.21 1\nSINI 0.97 1\n")
    par = m.as_parfile()
    assert "BINARY" in par and "ELL1" in par
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m2 = get_model(io.StringIO(par))
    for nm in ("PB", "A1", "EPS1", "EPS2", "M2", "SINI"):
        assert m2.get_param(nm).value == pytest.approx(
            m.get_param(nm).value, rel=1e-12), nm
    assert m2.TASC.value == pytest.approx(m.TASC.value, abs=1e-9)
