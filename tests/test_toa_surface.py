"""Reference-API surface tail: Parkes/ITOA tim formats, TOAs.index /
renumber, save_pickle/load_pickle, get_highest_density_range.
Reference anchors: src/pint/toa.py (_toa_format, parse_TOA_line,
TOAs.renumber, save_pickle/load_pickle), src/pint/utils.py
(get_highest_density_range)."""
import io
import os

import numpy as np
import pytest

from pint_tpu.io.tim import parse_tim
from pint_tpu.time.mjd import parse_mjd_string
from pint_tpu.utils import get_highest_density_range


def _parkes_line(name, freq, mjd_str, phoff, err, obs):
    """Build a TEMPO Parkes-format line with the exact column layout:
    name(0:17) freq(25:34) MJD(34:55, '.' at col 41) phase-off(55:63)
    error(63:71) obs(79)."""
    # MJD field: pad the integer part to put '.' at absolute col 41
    day, frac = mjd_str.split(".")
    mjd_field = day.rjust(41 - 34) + "." + frac
    line = (" " + name).ljust(25)[:25]
    line += f"{freq:>9.3f}"[:9]
    line += mjd_field.ljust(21)[:21]
    line += f"{phoff:>8.4f}"[:8]
    line += f"{err:>8.3f}"[:8]
    line = line.ljust(79) + obs
    assert line[41] == "." and len(line) == 80
    return line


class TestParkesFormat:
    def test_parse_basic(self):
        line = _parkes_line("J0437-4715", 1420.405, "50123.4567890123456",
                            0.0, 1.25, "7")
        toas = parse_tim(line + "\n")
        assert len(toas) == 1
        t = toas[0]
        assert t.obs == "7"
        assert t.freq_mhz == pytest.approx(1420.405)
        assert t.error_us == pytest.approx(1.25)
        # MJD survives as an exact decimal string
        d, f = parse_mjd_string(t.mjd_str)
        assert d == 50123
        assert f[0] == pytest.approx(0.4567890123456, abs=1e-15)
        assert "padd" not in t.flags

    def test_phase_offset_raises(self):
        # a nonzero phase offset shifts the TOA by phoff*P0, which a
        # parser cannot apply — the reference raises, so do we
        line = _parkes_line("J1022+1001", 430.0, "48000.25", 0.3125,
                            3.0, "f")
        with pytest.raises(ValueError, match="phase offset"):
            parse_tim(line + "\n")

    def test_not_swallowed_by_format1(self):
        # without a FORMAT 1 header the column signature must win even
        # though the tokens happen to look numeric
        line = _parkes_line("1821", 1400.0, "51000.5", 0.0, 2.0, "3")
        t = parse_tim(line + "\n")[0]
        assert t.obs == "3" and t.name == "1821"

    def test_format1_mode_overrides(self):
        # after FORMAT 1 every line is TEMPO2-tokenized
        src = ("FORMAT 1\n"
               "unk 1400.000 51000.500000 2.000 gbt -be X\n")
        t = parse_tim(src)[0]
        assert t.obs.lower() in ("gbt", "1")  # registry name
        assert t.flags["be"] == "X"


class TestITOAParsed:
    @staticmethod
    def _itoa_line(name, mjd19, err6, freq11, ddm10, obs2):
        # cols (1-based): name 1-2, blank 3-9, MJD 10-28, err 29-34,
        # freq 35-45, DM correction 46-55, blank 56-57, obs 58-59
        line = (f"{name:<2s}" + " " * 7 + f"{mjd19:<19s}"
                + f"{err6:>6s}" + f"{freq11:>11s}" + f"{ddm10:>10s}"
                + "  " + f"{obs2:<2s}")
        assert line[14] == "."
        return line

    def test_itoa_line_parses(self):
        # round 5: ITOA is parsed (beyond the reference, whose
        # parse_TOA_line raises 'not implemented' for it)
        line = self._itoa_line("AA", "50123.8864714985", "5.00",
                               "1420.0000", "0.00", "AO")
        t = parse_tim(line + "\n")[0]
        assert t.name == "AA"
        assert t.mjd_str == "50123.8864714985"
        assert t.error_us == 5.0
        assert t.freq_mhz == 1420.0
        assert t.obs == "AO"
        assert "ddm" not in t.flags

    def test_itoa_ddm_flag_and_blank_guard(self):
        line = self._itoa_line("B1", "50124.1234567890", "2.50",
                               "430.0000", "0.0031", "GB")
        t = parse_tim(line + "\n")[0]
        assert float(t.flags["ddm"]) == 0.0031
        assert t.obs == "GB"
        # a line with content in the must-be-blank cols 3-9 is NOT
        # ITOA and must fail parsing loudly, not be half-swallowed
        bad = "XX  name 50123.8864714985  5.00  1420.0000  0.00 AO"
        assert bad[14] == "."
        with pytest.raises(ValueError, match="unparseable"):
            parse_tim(bad + "\n")

    def test_truncated_itoa_rejected_not_swallowed(self):
        # ADVICE r5: a truncated ITOA-like line (signature matches,
        # column parse fails) used to fall through to the free-form
        # parser with SWAPPED fields (mjd='5.00', freq=50123.88).
        # The implausible-MJD sanity check must fail it at the parse
        # site instead of poisoning the dataset.
        line = "AA       50123.8864714985  5.00  1420.0000 AO"
        assert line[14] == "." and not line[2:9].strip()
        with pytest.raises(ValueError, match="ambiguous ITOA-like"):
            parse_tim(line + "\n")

    def test_freeform_with_itoa_signature_still_parses(self):
        # a short-name free-form line whose frequency decimal point
        # lands in column 15 carries a PLAUSIBLE MJD — the fallback
        # must keep accepting it
        line = "aa       14200.000 50123.886471 2.00 ao"
        assert line[14] == "." and not line[2:9].strip()
        t = parse_tim(line + "\n")[0]
        assert t.mjd_str == "50123.886471"
        assert t.freq_mhz == 14200.0


class TestFormatThreadsThroughInclude:
    def test_included_file_inherits_format1(self, tmp_path):
        # FORMAT applies to the expanded line stream (reference: one
        # linear loop): an included file without its own header must
        # still be TEMPO2-tokenized
        sub = tmp_path / "sub.tim"
        sub.write_text("unk 1400.000 51000.500000 2.000 @ -be Y\n")
        master = tmp_path / "master.tim"
        master.write_text("FORMAT 1\nINCLUDE sub.tim\n")
        toas = parse_tim(os.fspath(master))
        assert len(toas) == 1
        assert toas[0].flags["be"] == "Y"


class TestIndexRenumber:
    def _toas(self):
        from pint_tpu.toa import get_TOAs_array

        return get_TOAs_array(
            50000.0 + np.linspace(0, 10, 8), obs="barycenter",
            errors=1.0)

    def test_index_survives_select(self):
        t = self._toas()
        assert list(t.index) == list(range(8))
        sub = t.select(np.array([0, 2, 5]))
        assert list(sub.index) == [0, 2, 5]

    def test_renumber_index_order(self):
        t = self._toas()
        sub = t.select(np.array([1, 4, 6]))
        sub.renumber(index_order=True)
        assert list(sub.index) == [0, 1, 2]

    def test_renumber_rank_order(self):
        t = self._toas()
        sub = t.select(np.array([6, 1, 4]))  # out of order
        sub.renumber(index_order=False)
        # ranks of [6, 1, 4] -> [2, 0, 1]
        assert list(sub.index) == [2, 0, 1]


class TestPickleRoundTrip:
    def test_save_load(self, tmp_path):
        from pint_tpu.toa import get_TOAs_array, load_pickle, save_pickle

        t = get_TOAs_array(50000.0 + np.arange(5.0), obs="barycenter",
                           errors=2.0)
        p = os.fspath(tmp_path / "toas.pickle")
        save_pickle(t, p)
        t2 = load_pickle(p)
        assert t2.ntoas == 5
        np.testing.assert_array_equal(t2.get_errors(), t.get_errors())
        np.testing.assert_array_equal(t2.mjd_day, t.mjd_day)
        np.testing.assert_array_equal(t2.mjd_frac[0], t.mjd_frac[0])
        # tdb precompute survives
        assert t2.tdb_day is not None

    def test_load_rejects_non_toas(self, tmp_path):
        import pickle

        from pint_tpu.toa import load_pickle

        p = os.fspath(tmp_path / "junk.pickle")
        with open(p, "wb") as fh:
            pickle.dump({"not": "toas"}, fh)
        with pytest.raises(TypeError, match="TOAs"):
            load_pickle(p)


class TestHighestDensityRange:
    def test_dense_cluster_found(self):
        rng = np.random.default_rng(1)
        sparse = rng.uniform(50000, 51000, 50)
        dense = 50500.0 + rng.uniform(0, 2.0, 60)
        lo, hi = get_highest_density_range(
            np.concatenate([sparse, dense]), ndays=7)
        assert lo <= dense.min() and dense.max() <= hi
        assert hi - lo == pytest.approx(7.0)

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            get_highest_density_range([])
