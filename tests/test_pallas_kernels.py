"""Pallas photon-harmonics kernel vs the jnp reference, in interpret
mode (no TPU needed; the real-device path is the same program)."""

import numpy as np
import pytest

import jax.numpy as jnp

from pint_tpu.eventstats import _z2_sums, hmw, z2m
from pint_tpu.ops.pallas_kernels import z2_harmonics_pallas


@pytest.mark.parametrize("n", [1000, 8192, 20000])
@pytest.mark.parametrize("m", [2, 20])
def test_kernel_matches_jnp(n, m):
    rng = np.random.default_rng(1)
    ph = rng.uniform(size=n)
    w = rng.uniform(0.1, 1.0, size=n)
    c, s = z2_harmonics_pallas(ph, w, m=m, interpret=True)
    ks = np.arange(1, m + 1)
    ang = 2 * np.pi * ks[:, None] * ph[None, :]
    c_ref = (w[None, :] * np.cos(ang)).sum(axis=1)
    s_ref = (w[None, :] * np.sin(ang)).sum(axis=1)
    # f32 streaming accumulation: ~1e-4 relative at these N
    np.testing.assert_allclose(np.asarray(c), c_ref,
                               rtol=5e-4, atol=5e-3 * np.sqrt(n))
    np.testing.assert_allclose(np.asarray(s), s_ref,
                               rtol=5e-4, atol=5e-3 * np.sqrt(n))


def test_terms_match_z2_statistic():
    rng = np.random.default_rng(2)
    n = 9000
    # pulsed sample: statistic far from zero
    ph = np.mod(0.3 + 0.04 * rng.standard_normal(n), 1.0)
    w = np.ones(n)
    c, s = z2_harmonics_pallas(ph, w, m=4, interpret=True)
    z2_pallas = float(2.0 * ((np.asarray(c) ** 2
                              + np.asarray(s) ** 2)).sum() / n)
    z2_ref = z2m(ph, m=4)
    assert z2_pallas == pytest.approx(z2_ref, rel=1e-3)


def test_padding_rows_are_inert():
    """n not a multiple of the tile: padded zero-weight rows must not
    bias the sums (cos(0)=1 would leak without the w=0 mask)."""
    rng = np.random.default_rng(3)
    n = 8192 + 17
    ph = rng.uniform(size=n)
    w = rng.uniform(0.5, 1.0, size=n)
    c, s = z2_harmonics_pallas(ph, w, m=3, interpret=True)
    c_ref, s_ref = _z2_sums(jnp.asarray(ph), jnp.asarray(w), 3)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c_ref),
                               rtol=2e-3, atol=0.05)
    np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref),
                               rtol=2e-3, atol=0.05)


def test_m_over_lanes_guard():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="128-lane"):
        z2_harmonics_pallas(np.ones(100), np.ones(100), m=129,
                            interpret=True)
