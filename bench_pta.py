"""PTA array benchmarks: batch fit (BASELINE.md config #5) and the
array-level GWB detection sweep (ISSUE 17).

Default mode measures the 67-pulsar vmapped GLS batch fit
(``pta_batch_fit_throughput``). ``--gwb`` measures the array GWB
likelihood plane: Hellings-Downs block assembly single-device vs
sharded over the full mesh (the scale-out acceptance number — BOTH
walls are recorded), then the chunked (log10_A, gamma) detection
sweep, with roofline / dispatch_supervisor / health / regress blocks
on the LAST-JSON-line artifact (bench.py parity, including the
BENCH_TPU.jsonl provenance merge on CPU-fallback runs):

    python bench_pta.py [--npulsars 67] [--ntoa 100]
    python bench_pta.py --gwb [--nfreq 5] [--grid 8]

The LAST stdout JSON line is the recorded artifact.
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import time
import warnings


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_pulsar(k: int, ntoa: int):
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    binary = ""
    if k % 3 == 1:  # a third of the array is ELL1 binaries
        binary = (f"BINARY ELL1\nPB {0.4 + 0.02 * k}\nA1 1.3 1\n"
                  "TASC 55000.05\nEPS1 1e-5 1\nEPS2 -2e-5 1\n")
    par = f"""PSR J{1000 + k}
RAJ {(k * 17) % 24}:{(k * 7) % 60:02d}:00.0 1
DECJ {-30 + (k % 60)}:00:00.0 1
F0 {120.0 + 11.0 * k} 1
F1 {-1e-15 * (1 + k % 5)} 1
PEPOCH 55000
POSEPOCH 55000
DM {5.0 + 0.7 * k} 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
{binary}"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        rng = np.random.default_rng(k)
        t = make_fake_toas_uniform(54000, 56000, ntoa, m, error_us=1.0,
                                   add_noise=True, rng=rng)
    truth = {"F0": m.F0.value, "DM": m.get_param("DM").value}
    m.F0.add_delta(1e-10)
    m.invalidate_cache(params_only=True)
    return m, t, truth


def run_batch(args) -> dict:
    """BASELINE config #5: one vmapped GLS solve per iteration."""
    from pint_tpu.parallel import fit_pta

    t0 = time.perf_counter()
    pulsars = [build_pulsar(k, args.ntoa)
               for k in range(args.npulsars)]
    log(f"built {len(pulsars)} pulsars in "
        f"{time.perf_counter() - t0:.1f}s")

    res = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=2)
    stats = fit_pta.last_stats
    n_ok = sum(1 for (m, t, truth), r in zip(pulsars, res)
               if abs(m.F0.value - truth["F0"])
               < 5 * r["errors"]["F0"])
    log(f"recovered F0 within 5 sigma: {n_ok}/{len(pulsars)}")
    log(f"stats: {stats}")
    return {
        "metric": "pta_batch_fit_throughput",
        "value": round(stats["toas_per_sec"], 1),
        "unit": "TOA/s",
        "npulsars": args.npulsars,
        "ntoa_total": stats["ntoa_total"],
        "device_solve_s": round(stats["device_solve_s"], 3),
        "recovered": n_ok,
    }


def run_gwb(args) -> dict:
    """Array GWB likelihood plane (ISSUE 17): sharded-vs-single-device
    block assembly walls + the chunked detection sweep, instrumented
    with the roofline / health evidence blocks."""
    import jax
    import numpy as np

    from pint_tpu import config
    from pint_tpu.obs import health as oh
    from pint_tpu.obs import perf as operf
    from pint_tpu.parallel.pta import build_problem
    from pint_tpu.pta import GWBLikelihood
    from pint_tpu.pta.gwb import (
        _OUTER_NDIMS_IN,
        _OUTER_NDIMS_OUT,
        _gwb_outer_batch,
    )
    from pint_tpu.pta.shard import compile_with_plan

    backend = jax.default_backend()
    devices = jax.devices()
    ndev = len(devices)

    t0 = time.perf_counter()
    pulsars = [build_pulsar(k, args.ntoa)
               for k in range(args.npulsars)]
    problems = [build_problem(t, m) for m, t, _ in pulsars]
    log(f"built {len(pulsars)} pulsars in "
        f"{time.perf_counter() - t0:.1f}s")

    # -- block assembly: single-device vs mesh-sharded ----------------
    # The same problems feed both likelihoods, so the ONLY variable is
    # the compile plan (jit(vmap) on one device vs shard_map blocks
    # over the pulsar axis). Warm each plan once (compile excluded),
    # then take the best of `reps` forced rebuilds.
    def timed_blocks(lk, reps=3):
        lk.build_blocks(force=True)  # warm: compile + placement
        best = float("inf")
        for _ in range(reps):
            t1 = time.perf_counter()
            lk.build_blocks(force=True)
            best = min(best, time.perf_counter() - t1)
        return best

    lk_single = GWBLikelihood(problems=problems, nfreq=args.nfreq)
    t_single = timed_blocks(lk_single)
    log(f"block assembly single-device: {t_single * 1e3:.1f} ms "
        f"(P={lk_single.npulsars}, m={lk_single.m})")

    t_shard = None
    lk = lk_single
    if ndev > 1:
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices), ("pulsar",))
        lk_shard = GWBLikelihood(problems=problems, nfreq=args.nfreq,
                                 mesh=mesh)
        t_shard = timed_blocks(lk_shard)
        log(f"block assembly sharded x{ndev}: {t_shard * 1e3:.1f} ms")
        A1 = lk_single.build_blocks()[0]
        A8 = lk_shard.build_blocks()[0]
        consistent = bool(np.allclose(A1, A8, rtol=1e-9, atol=1e-12))
        log(f"sharded blocks match single-device: {consistent}")
        lk = lk_shard
    else:
        consistent = True
        log("single device only; skipping the sharded comparison")

    # -- the detection sweep ------------------------------------------
    g = args.grid
    la2, ga2 = np.meshgrid(np.linspace(-15.5, -13.5, g),
                           np.linspace(2.0, 6.0, g))
    la, ga = la2.ravel(), ga2.ravel()
    K = config.gwb_chunk()
    nchunks = -(-len(la) // K)

    mon = oh.configure(enabled=True)
    lk.loglik_grid(la, ga)  # warm: outer-kernel compile
    t1 = time.perf_counter()
    logL = lk.loglik_grid(la, ga)
    sweep_s = time.perf_counter() - t1
    kbest = int(np.argmax(logL))
    pts_per_s = len(la) / sweep_s
    log(f"sweep {g}x{g} grid in {sweep_s * 1e3:.1f} ms "
        f"({pts_per_s:.1f} points/s, chunk={K}); best "
        f"log10A={la[kbest]:.2f} gamma={ga[kbest]:.2f}")

    mon.observe("bench.gwb_sweep", {"values": [np.asarray(logL)]},
                pool=lk.blocks_info.get("used_pool", "device"),
                key="bench.gwb_sweep")
    health = oh.status()

    rec = {
        "metric": "gwb_sweep",
        "value": round(pts_per_s, 2),
        "unit": "points/s",
        "backend": backend,
        "npulsars": args.npulsars,
        "ntoa": args.ntoa,
        "nfreq": args.nfreq,
        "grid": f"{g}x{g}",
        "chunk": K,
        "sweep_ms": round(sweep_s * 1e3, 2),
        "block_assembly": {
            "single_device_ms": round(t_single * 1e3, 2),
            "sharded_ms": (round(t_shard * 1e3, 2)
                           if t_shard is not None else None),
            "sharded_speedup": (round(t_single / t_shard, 2)
                                if t_shard else None),
            "ndevices": ndev,
            "consistent": consistent,
            "used_pool": lk.blocks_info.get("used_pool"),
        },
        "best": {"log10A": round(float(la[kbest]), 3),
                 "gamma": round(float(ga[kbest]), 3),
                 "logL": round(float(logL[kbest]), 3)},
        "counters": lk.metrics.snapshot(),
    }
    if health is not None:
        rec["health"] = health

    # roofline: the outer Schur kernel is the sweep's hot loop — probe
    # its XLA cost once (same cached plan the driver dispatched) and
    # judge the measured per-chunk wall against the backend peaks.
    try:
        import jax.numpy as jnp

        A, x, rdr_sum, ld_sum = lk.build_blocks()
        kernel = compile_with_plan(
            _gwb_outer_batch, name="pta.gwb_sweep",
            ndims_in=_OUTER_NDIMS_IN, ndims_out=_OUTER_NDIMS_OUT)
        ex = (jnp.asarray(A), jnp.asarray(x), jnp.asarray(rdr_sum),
              jnp.asarray(ld_sum), jnp.asarray(lk.Gamma),
              jnp.asarray(lk.fcols), jnp.asarray(lk.tspan),
              jnp.asarray(la[:K]), jnp.asarray(ga[:K]))
        operf.note_compile("bench.gwb_sweep_chunk", backend=backend,
                           kind="bench", jitted=kernel, args=ex)
        roof = operf.roofline_block("bench.gwb_sweep_chunk",
                                    sweep_s / nchunks, backend)
        if roof:
            rec["roofline"] = roof
        rec["compiles"] = operf.ledger_summary()
    except Exception as e:
        log(f"roofline attribution failed: {e!r}")
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npulsars", type=int, default=67)
    ap.add_argument("--ntoa", type=int, default=100)
    ap.add_argument("--gwb", action="store_true",
                    help="array GWB likelihood plane benchmark")
    ap.add_argument("--nfreq", type=int, default=5,
                    help="GWB basis frequencies (--gwb)")
    ap.add_argument("--grid", type=int, default=8,
                    help="detection sweep grid side (--gwb)")
    args = ap.parse_args()

    import os
    import sys

    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        from bench import accelerator_responsive, cpu_fallback_env

        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    import jax

    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        # CPU run: pin the platform (the sitecustomize-registered TPU
        # plugin otherwise wins) and force the 8-virtual-device mesh
        # (same as tests/conftest.py) so the sharded block-assembly
        # leg is a real scale-out measurement — both only effective
        # BEFORE the backend initializes, so decide from env, not
        # jax.default_backend()
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    backend = jax.default_backend()
    log(f"backend: {backend} ({len(jax.devices())} device(s))")

    rec = run_gwb(args) if args.gwb else run_batch(args)
    rec.setdefault("backend", backend)

    # bench.py parity: dispatch-supervisor counters + lint state +
    # regress verdict on the artifact, and the BENCH_TPU.jsonl
    # provenance merge — an on-chip run appends to the committed
    # ledger, a CPU-fallback run carries the latest on-chip record
    # with provenance instead of silently reporting host-only numbers.
    from bench import (
        attach_dispatch_counters,
        load_tpu_records,
        record_key,
        tpu_record_append,
    )

    if backend == "tpu":
        tpu_record_append(rec)
    else:
        chip = load_tpu_records().get(record_key(rec))
        if chip is not None:
            rec["tpu_on_chip"] = {
                k: chip[k] for k in
                ("value", "sweep_ms", "device_solve_s", "utc",
                 "imported", "provenance") if k in chip}
            rec["tpu_note"] = (
                "TPU unreachable this run; latest committed on-chip "
                f"record from {chip.get('utc', '?')} "
                "(BENCH_TPU.jsonl)")
        elif os.environ.get("PINT_TPU_BENCH_FALLBACK"):
            rec["tpu_note"] = ("TPU unreachable this run; no "
                               "committed on-chip record found")

    print(json.dumps(attach_dispatch_counters(rec)), flush=True)


if __name__ == "__main__":
    main()
