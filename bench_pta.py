"""PTA batch benchmark (BASELINE.md config #5): 67 heterogeneous
pulsars fit as ONE vmapped GLS solve per iteration on the accelerator.

Not part of the driver's bench.py protocol (that measures the single-
pulsar GLS north star); run manually:

    python bench_pta.py [--npulsars 67] [--ntoa 100]

Prints one JSON line {metric, value, unit, npulsars, ...}.
"""

from __future__ import annotations

import argparse
import io
import json
import sys
import time
import warnings


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_pulsar(k: int, ntoa: int):
    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    binary = ""
    if k % 3 == 1:  # a third of the array is ELL1 binaries
        binary = (f"BINARY ELL1\nPB {0.4 + 0.02 * k}\nA1 1.3 1\n"
                  "TASC 55000.05\nEPS1 1e-5 1\nEPS2 -2e-5 1\n")
    par = f"""PSR J{1000 + k}
RAJ {(k * 17) % 24}:{(k * 7) % 60:02d}:00.0 1
DECJ {-30 + (k % 60)}:00:00.0 1
F0 {120.0 + 11.0 * k} 1
F1 {-1e-15 * (1 + k % 5)} 1
PEPOCH 55000
POSEPOCH 55000
DM {5.0 + 0.7 * k} 1
TZRMJD 55000.1
TZRSITE @
TZRFRQ 1400
UNITS TDB
{binary}"""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(io.StringIO(par))
        rng = np.random.default_rng(k)
        t = make_fake_toas_uniform(54000, 56000, ntoa, m, error_us=1.0,
                                   add_noise=True, rng=rng)
    truth = {"F0": m.F0.value, "DM": m.get_param("DM").value}
    m.F0.add_delta(1e-10)
    m.invalidate_cache(params_only=True)
    return m, t, truth


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--npulsars", type=int, default=67)
    ap.add_argument("--ntoa", type=int, default=100)
    args = ap.parse_args()

    import os
    import sys

    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        from bench import accelerator_responsive, cpu_fallback_env

        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    import jax

    jax.config.update("jax_enable_x64", True)
    from pint_tpu.parallel import fit_pta

    log(f"backend: {jax.default_backend()}")
    t0 = time.perf_counter()
    pulsars = [build_pulsar(k, args.ntoa)
               for k in range(args.npulsars)]
    log(f"built {len(pulsars)} pulsars in "
        f"{time.perf_counter() - t0:.1f}s")

    res = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=2)
    stats = fit_pta.last_stats
    n_ok = sum(1 for (m, t, truth), r in zip(pulsars, res)
               if abs(m.F0.value - truth["F0"])
               < 5 * r["errors"]["F0"])
    log(f"recovered F0 within 5 sigma: {n_ok}/{len(pulsars)}")
    log(f"stats: {stats}")
    print(json.dumps({
        "metric": "pta_batch_fit_throughput",
        "value": round(stats["toas_per_sec"], 1),
        "unit": "TOA/s",
        "npulsars": args.npulsars,
        "ntoa_total": stats["ntoa_total"],
        "device_solve_s": round(stats["device_solve_s"], 3),
        "recovered": n_ok,
    }))


if __name__ == "__main__":
    main()
