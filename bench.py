"""North-star benchmark (BASELINE.md): GLS fit iteration throughput —
design-matrix build + whitening + normal equations + Cholesky — on a
10k-TOA, 40-free-parameter model with ECORR + power-law red noise.

Numerator: the single jitted XLA fit step (pint_tpu.parallel.fit_step)
on the default backend (TPU under axon; falls back to CPU elsewhere).
Denominator: the reference algorithm's CPU path — phase/design matrix
evaluated on the CPU backend plus the numpy/scipy Woodbury GLS solve
(pint_tpu.gls.gls_solve_np), mirroring src/pint/fitter.py
GLSFitter.fit_toas (BASELINE.md measurement protocol: the reference
itself is not runnable in this image).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


NTOA = 10_000
NDMX = 28  # 28 DMX + 12 other free params = 40 columns + offset


def build_problem():
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    span0, span1 = 53000.0, 57000.0
    par = [
        "PSR J0000+0000",
        "RAJ 12:00:00.0 1",
        "DECJ 30:00:00.0 1",
        "PMRA 2.0 1",
        "PMDEC -3.0 1",
        "PX 1.2 1",
        "F0 300.123456789 1",
        "F1 -1.0e-15 1",
        "F2 1e-26 1",
        # DM/DM1/DM2 frozen: the free DMX windows cover the full span,
        # so a free DM would be exactly collinear with their sum
        # (singular normal matrix — NANOGrav convention freezes DM)
        "DM 20.0",
        "DM1 1e-4",
        "DM2 1e-6",
        "PEPOCH 55000",
        "POSEPOCH 55000",
        "DMEPOCH 55000",
        "TZRMJD 55000.1",
        "TZRSITE @",
        "TZRFRQ 1400",
        "UNITS TDB",
        "EFAC -be X 1.1",
        "EQUAD -be X 0.3",
        "ECORR -be X 1.2",
        "TNREDAMP -13.7",
        "TNREDGAM 3.5",
        "TNREDC 30",
    ]
    for i in range(4):
        par.append(f"JUMP -grp g{i} 1e-6 1")
    edges = np.linspace(span0, span1, NDMX + 1)
    for i in range(NDMX):
        par.append(f"DMX_{i + 1:04d} 0.0 1")
        par.append(f"DMXR1_{i + 1:04d} {edges[i]:.4f}")
        par.append(f"DMXR2_{i + 1:04d} {edges[i + 1]:.4f}")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par) + "\n"))
        rng = np.random.default_rng(1)
        # Clustered observing epochs so the ECORR quantization basis has
        # real structure: NTOA/4 clusters of 4 TOAs within ~30 min, with
        # inter-cluster gaps far above the 0.5-day bucket threshold
        # (create_quantization_matrix, pint_tpu/models/noise.py).
        ncluster = NTOA // 4
        centers = np.linspace(span0 + 1, span1 - 1, ncluster)
        offsets = np.array([0.0, 0.007, 0.014, 0.021])
        mjds = (centers[:, None] + offsets[None, :]).ravel()
        # Two frequency bands within every cluster: single-band data
        # leaves DM/DM1/DM2 exactly collinear with Offset/F1/F2
        # (singular normal matrix — the round-2 bench crash).
        freqs = np.tile([1400.0, 1400.0, 820.0, 820.0], ncluster)
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=1.0, freq_mhz=freqs,
            add_noise=True, rng=rng)
        for i, f in enumerate(toas.flags):
            f["be"] = "X"
            f["grp"] = f"g{i % 5}"  # g4 matches no JUMP: 4 free jumps
    return model, toas


def time_fn(fn, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def accelerator_responsive(timeout_s: float = 240.0) -> bool:
    """Probe backend init in a subprocess: a wedged TPU tunnel HANGS
    jax.devices() rather than erroring, which would hang the whole
    benchmark. A bounded probe lets us fall back to CPU and still
    produce a valid measurement."""
    import os
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s, capture_output=True,
            env=dict(os.environ))
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def cpu_fallback_env() -> dict:
    """Environment for a clean-CPU re-exec. JAX_PLATFORMS=cpu alone is
    NOT enough: the container's sitecustomize registers the axon TPU
    plugin whenever PALLAS_AXON_POOL_IPS is set and a wedged tunnel
    then hangs even CPU-pinned processes — drop the axon vars entirely
    (same recipe as __graft_entry__.dryrun_multichip)."""
    import os

    env = dict(os.environ)
    for k in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_TPU_GEN",
              "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["PINT_TPU_BENCH_FALLBACK"] = "1"
    return env


def main():
    import os
    import sys

    # only the axon TPU tunnel has the hang-on-init failure mode; on
    # plain hosts skip the probe subprocess entirely
    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable, [sys.executable, __file__],
                       cpu_fallback_env())

    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    backend = jax.default_backend()
    log(f"backend: {backend}, devices: {jax.devices()}")

    model, toas = build_problem()
    nfree = len(model.free_params)
    log(f"N={toas.ntoas} free params={nfree}")

    from pint_tpu.parallel import build_fit_step

    step_fn, args, names = build_fit_step(model, toas)
    jitted = jax.jit(step_fn)
    t0 = time.perf_counter()
    out = jitted(*args)
    jax.block_until_ready(out)
    log(f"compile+first run: {time.perf_counter() - t0:.1f}s "
        f"chi2={float(out[2]):.1f}")

    accel_t = time_fn(lambda: jax.block_until_ready(jitted(*args)))
    log(f"accelerated fit step: {accel_t * 1e3:.1f} ms "
        f"({toas.ntoas / accel_t:.0f} TOA/s)")

    # optional device-trace capture for step attribution (jacfwd phase
    # chain vs matmuls vs Cholesky): view with tensorboard/xprof
    import os

    profdir = os.environ.get("PINT_TPU_PROFILE_DIR")
    if profdir:
        from pint_tpu.profiling import trace

        with trace(profdir):
            jax.block_until_ready(jitted(*args))
        log(f"profile trace written to {profdir}")

    # ---- CPU reference-algorithm path -------------------------------
    cpu = jax.devices("cpu")[0]
    from pint_tpu.gls import gls_solve_np

    with jax.default_device(cpu):
        cpu_args = jax.device_put(args, cpu)
        cpu_jit = jax.jit(step_fn)
        jax.block_until_ready(cpu_jit(*cpu_args))  # warm

        # CPU denominator, reference-style: design matrix + residuals on
        # host, then the numpy/scipy basis-Woodbury solve
        M_, names_, _ = model.designmatrix(toas)
        r_ = np.zeros(toas.ntoas)

        def cpu_once():
            from pint_tpu.residuals import Residuals

            res = Residuals(toas, model)
            r = res.time_resids
            M, _, _ = model.designmatrix(toas)
            nvec = model.scaled_toa_uncertainty(toas) ** 2
            F = model.noise_model_designmatrix(toas)
            phi = model.noise_model_basis_weight(toas)
            model._cache_key = None  # defeat caching: honest rebuild
            model.__dict__.pop("_noise_basis_cache", None)
            return gls_solve_np(np.asarray(M), F, phi, np.asarray(r),
                                nvec)

        cpu_t = time_fn(cpu_once, reps=3)
    log(f"cpu reference path: {cpu_t * 1e3:.1f} ms "
        f"({toas.ntoas / cpu_t:.0f} TOA/s)")

    value = toas.ntoas / accel_t
    print(json.dumps({
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "value": round(value, 1),
        "unit": "TOA/s",
        "vs_baseline": round(cpu_t / accel_t, 2),
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
