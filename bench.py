"""North-star benchmark (BASELINE.md): GLS fit iteration throughput —
design-matrix build + whitening + normal equations + Cholesky — on a
10k-TOA, 40-free-parameter model with ECORR + power-law red noise.

Numerator: the single jitted XLA fit step (pint_tpu.parallel.fit_step)
on the default backend (TPU under axon; falls back to CPU elsewhere).
Denominator: the reference algorithm's CPU path — phase/design matrix
evaluated on the CPU backend plus the numpy/scipy Woodbury GLS solve
(pint_tpu.gls.gls_solve_np), mirroring src/pint/fitter.py
GLSFitter.fit_toas (BASELINE.md measurement protocol: the reference
itself is not runnable in this image).

Prints one JSON line per benchmark config (BASELINE.md configs 1-5),
with the north-star line LAST (the driver records the last line).
When the accelerator is reachable the north-star line carries BOTH
backends' step times (step_ms on the accelerator, cpu_xla_step_ms for
the same XLA program on the host CPU) so the vs_baseline ratio — which
is XLA-vs-numpy by protocol — cannot be misread as a TPU-vs-CPU claim.
After a CPU fallback re-exec, a bounded late probe retries the TPU so
a transiently-wedged tunnel doesn't cost the round's TPU number.
"""

from __future__ import annotations

import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


NTOA = 10_000
NDMX = 28  # 28 DMX + 12 other free params = 40 columns + offset
AXON_VARS = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_TPU_GEN",
             "PALLAS_AXON_REMOTE_COMPILE")

# TPU v5e single-chip public peaks, used for the honest MFU/roofline
# framing of every config: 197 TFLOP/s bf16 on the MXU (f32 matmul
# ~1/2 of that), 819 GB/s HBM. The 10k north-star step does ~0.26
# GFLOP of matmul — VPU/latency-bound, effectively zero MFU; the MXU
# only becomes the bottleneck on the large-N scan / PTA-batch shapes.
# (ISSUE 15: the per-backend table now lives in obs.perf.PEAKS — the
# ledger-derived roofline blocks read it there; these constants stay
# as the historical mfu_pct/hbm_util_pct fields' source and MUST
# match obs.perf.PEAKS["tpu"], test-asserted in tests/test_perf.py.)
V5E_PEAK_FLOPS = 197e12
V5E_PEAK_HBM_BPS = 819e9

# ledger file path override (None = BENCH_TPU.jsonl next to this
# file, the committed default); assign the module global to redirect
TPU_RECORD_PATH = None


def _bench_dir():
    import os

    return os.path.dirname(os.path.abspath(__file__))


def xla_cost(jitted, args):
    """XLA's own cost analysis of the compiled step: total FLOPs and
    bytes accessed. The probe re-lowers and re-compiles (seeded by
    the persistent bench jit cache — acceptable in a measurement
    script, banned on production paths by the perf plane's
    defer_cost discipline). Returns {} when the backend doesn't
    report.

    ISSUE 15: delegates to ``obs.perf.cost_probe`` — the ONE home of
    the lower().compile() probe pattern (graftlint G15); the field
    names here keep the historical artifact shape."""
    from pint_tpu.obs import perf as operf

    c = operf.cost_probe(jitted, args)
    out = {}
    if "flops" in c:
        out["flops"] = c["flops"]
    if "bytes_accessed" in c:
        out["bytes"] = c["bytes_accessed"]
    if not out:
        log("  cost_analysis unavailable (backend did not report)")
    return out


def roofline_fields(jitted, args, step_t, backend):
    """MFU/roofline attribution for one config: per-step FLOPs and
    bytes (XLA cost analysis), achieved GFLOP/s and GB/s, and — on
    TPU — the fraction of v5e peak each represents. The honest
    framing the 'TPU-native' claim needs: a config whose mfu_pct and
    hbm_util_pct are both ~0 is latency/VPU-bound and its win cannot
    come from the MXU."""
    c = xla_cost(jitted, args)
    out = {}
    if "flops" in c:
        out["flops_step"] = round(c["flops"] / 1e9, 4)  # GFLOP
        out["gflops_achieved"] = round(c["flops"] / step_t / 1e9, 1)
        if backend == "tpu":
            out["mfu_pct"] = round(
                100.0 * c["flops"] / step_t / V5E_PEAK_FLOPS, 3)
    if "bytes" in c:
        out["gbytes_step"] = round(c["bytes"] / 1e9, 4)
        out["hbm_gbps_achieved"] = round(c["bytes"] / step_t / 1e9, 1)
        if backend == "tpu":
            out["hbm_util_pct"] = round(
                100.0 * c["bytes"] / step_t / V5E_PEAK_HBM_BPS, 2)
    return out


def attach_dispatch_counters(rec):
    """Embed the runtime dispatch-supervisor counters (retries,
    timeouts, breaker state, failovers) in a benchmark record, so a
    degraded run — breaker-open, host-failover numbers — is labeled
    in the artifact itself, never silently slow. setdefault, never
    assignment: a record carried over from a SUBPROCESS (the late TPU
    probe) already holds that process's counters, and this process's
    all-zero snapshot must not erase its degradation label."""
    try:
        from pint_tpu.runtime import get_supervisor

        rec.setdefault("dispatch_supervisor",
                       get_supervisor().snapshot())
    except Exception as e:  # the artifact must survive a broken import
        log(f"  dispatch counters unavailable: {e!r}")
    rec.setdefault("lint", _lint_state_cached())
    attach_regress(rec)
    return rec


def attach_regress(rec):
    """Embed the perf-regression verdict (tools/bench_regress.py,
    ISSUE 11 satellite): the artifact's fields judged against the
    committed BENCH_BASELINE.json tolerance bands, so a regressed
    record is LABELED at the moment it is produced — the same
    policy as the dispatch-supervisor counters. setdefault + a
    skip-on-any-failure block: the verdict must never be able to
    fail the bench that produces it, and a record with no baseline
    entry (the per-config records) skips with a reason."""
    try:
        import importlib.util
        import os

        p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "bench_regress.py")
        spec = importlib.util.spec_from_file_location(
            "_pint_bench_regress", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rec.setdefault("regress", mod.regress_block(rec))
    except Exception as e:
        rec.setdefault("regress",
                       {"verdict": "skip", "reason": repr(e)})
    return rec


_LINT_STATE = None


def _lint_state_cached():
    """Analyzer-state label for the artifact (graftlint clean bool +
    suppression surface): a record produced from a tree that no
    longer lints clean is flagged in the artifact itself, the same
    degraded-but-labeled policy as the dispatch counters. Cached —
    the static lint pass costs ~a second and every artifact line in
    one run describes the same tree."""
    global _LINT_STATE
    if _LINT_STATE is None:
        try:
            from pint_tpu.analysis import lint_state_safe

            _LINT_STATE = lint_state_safe()
        except Exception as e:  # analyzer package unimportable
            _LINT_STATE = {"clean": None, "error": repr(e)}
        if _LINT_STATE.get("error"):
            log(f"  lint state degraded: {_LINT_STATE['error']}")
    return _LINT_STATE


def tpu_record_append(rec):
    """Append a benchmark record to the committed on-chip ledger
    (BENCH_TPU.jsonl) with a UTC stamp. Called for every record
    measured with backend==tpu — whether by the driver's bench run or
    by tools/tpu_capture.py during a caught tunnel window — so the
    on-chip history survives as a raw, auditable artifact even when
    later driver runs fall back to CPU."""
    import datetime
    import os

    path = TPU_RECORD_PATH or os.path.join(_bench_dir(),
                                           "BENCH_TPU.jsonl")
    stamped = dict(rec)
    stamped.setdefault(
        "utc", datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ"))
    with open(path, "a") as f:
        f.write(json.dumps(stamped) + "\n")


def record_key(d):
    """Composite ledger key: some metrics are families (one record
    per scan N, per attribution variant, per PTA size) — keying by
    metric alone would collapse a family to its last member."""
    return (d.get("metric"), d.get("ntoa"), d.get("variant"),
            d.get("npulsars"))


def load_tpu_records():
    """Latest committed on-chip record per (metric, sub-key), in file
    (= time) order. Lets a CPU-fallback bench run still carry the TPU
    record with provenance instead of silently reporting only host
    numbers."""
    import os

    path = TPU_RECORD_PATH or os.path.join(_bench_dir(),
                                           "BENCH_TPU.jsonl")
    if not os.path.exists(path):
        return {}
    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if d.get("backend") == "tpu" and "metric" in d:
                latest[record_key(d)] = d  # file order == time order
    return latest


def _make_model_toas(par_lines, mjds, freqs, seed=1, error_us=1.0,
                     flag_sets=None):
    import io
    import warnings

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(io.StringIO("\n".join(par_lines) + "\n"))
        rng = np.random.default_rng(seed)
        toas = make_fake_toas_fromMJDs(
            mjds, model, error_us=error_us, freq_mhz=freqs,
            add_noise=True, rng=rng)
        if flag_sets:
            for i, f in enumerate(toas.flags):
                for k, fn in flag_sets.items():
                    f[k] = fn(i)
    return model, toas


def _add_dmx(par, span0, span1, ndmx):
    """Append ndmx free DMX windows tiling [span0, span1]."""
    import numpy as np

    edges = np.linspace(span0, span1, ndmx + 1)
    for i in range(ndmx):
        par.append(f"DMX_{i + 1:04d} 0.0 1")
        par.append(f"DMXR1_{i + 1:04d} {edges[i]:.4f}")
        par.append(f"DMXR2_{i + 1:04d} {edges[i + 1]:.4f}")


def _clustered_mjds(span0, span1, ntoa, per_cluster=4):
    """Clustered observing epochs so the ECORR quantization basis has
    real structure: ntoa/4 clusters within ~30 min, inter-cluster gaps
    far above the 0.5-day bucket threshold."""
    import numpy as np

    ncluster = ntoa // per_cluster
    centers = np.linspace(span0 + 1, span1 - 1, ncluster)
    offsets = np.linspace(0.0, 0.021, per_cluster)
    return (centers[:, None] + offsets[None, :]).ravel()


def build_problem():
    import numpy as np

    span0, span1 = 53000.0, 57000.0
    par = [
        "PSR J0000+0000",
        "RAJ 12:00:00.0 1",
        "DECJ 30:00:00.0 1",
        "PMRA 2.0 1",
        "PMDEC -3.0 1",
        "PX 1.2 1",
        "F0 300.123456789 1",
        "F1 -1.0e-15 1",
        "F2 1e-26 1",
        # DM/DM1/DM2 frozen: the free DMX windows cover the full span,
        # so a free DM would be exactly collinear with their sum
        # (singular normal matrix — NANOGrav convention freezes DM)
        "DM 20.0",
        "DM1 1e-4",
        "DM2 1e-6",
        "PEPOCH 55000",
        "POSEPOCH 55000",
        "DMEPOCH 55000",
        "TZRMJD 55000.1",
        "TZRSITE @",
        "TZRFRQ 1400",
        "UNITS TDB",
        "EFAC -be X 1.1",
        "EQUAD -be X 0.3",
        "ECORR -be X 1.2",
        "TNREDAMP -13.7",
        "TNREDGAM 3.5",
        "TNREDC 30",
    ]
    for i in range(4):
        par.append(f"JUMP -grp g{i} 1e-6 1")
    _add_dmx(par, span0, span1, NDMX)
    mjds = _clustered_mjds(span0, span1, NTOA)
    # Two frequency bands within every cluster: single-band data
    # leaves DM/DM1/DM2 exactly collinear with Offset/F1/F2
    # (singular normal matrix — the round-2 bench crash).
    freqs = np.tile([1400.0, 1400.0, 820.0, 820.0], NTOA // 4)
    return _make_model_toas(
        par, mjds, freqs, seed=1,
        flag_sets={"be": lambda i: "X",
                   "grp": lambda i: f"g{i % 5}"})  # g4 free: 4 jumps


def time_fn(fn, reps=5):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return min(ts)


def accelerator_responsive(timeout_s: float = 240.0) -> bool:
    """Probe backend init in a subprocess: a wedged TPU tunnel HANGS
    jax.devices() rather than erroring, which would hang the whole
    benchmark. A bounded probe lets us fall back to CPU and still
    produce a valid measurement."""
    import os
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s, capture_output=True,
            env=dict(os.environ))
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def cpu_fallback_env() -> dict:
    """Environment for a clean-CPU re-exec. JAX_PLATFORMS=cpu alone is
    NOT enough: the container's sitecustomize registers the axon TPU
    plugin whenever PALLAS_AXON_POOL_IPS is set and a wedged tunnel
    then hangs even CPU-pinned processes — drop the axon vars entirely
    (same recipe as __graft_entry__.dryrun_multichip). The dropped vars
    are stashed so the late TPU re-probe can reconstruct them."""
    import os

    env = dict(os.environ)
    stash = {}
    for k in AXON_VARS:
        if k in env:
            stash[k] = env.pop(k)
    env["PINT_TPU_AXON_STASH"] = json.dumps(stash)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["PINT_TPU_BENCH_FALLBACK"] = "1"
    # keep the driver artifact's stderr tail clean: XLA's CPU AOT
    # loader logs a scary ERROR for every persistent-cache load whose
    # compile-time feature string contains pseudo-features
    # (+prefer-no-scatter) absent from /proc/cpuinfo — even for
    # entries this very process compiled on this very host. The REAL
    # cross-host hazard is closed by the CPU-feature-keyed cache dir
    # (config._host_cache_tag); real failures raise Python-side.
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    return env


def measure_step(model, toas, reps=5, **flags):
    """Jitted fit-step wall time on the default backend; returns
    (step_seconds, chi2, jitted, args, step_fn) — step_fn so
    measure_step_chained can reuse the build instead of repeating the
    full host precompute. Extra flags (wideband, anchored, ...) pass
    through to build_fit_step."""
    import jax

    from pint_tpu.parallel import build_fit_step

    step_fn, args, _ = build_fit_step(model, toas, **flags)
    jitted = jax.jit(step_fn)
    t0 = time.perf_counter()
    out = jitted(*args)
    jax.block_until_ready(out)
    log(f"  compile+first run: {time.perf_counter() - t0:.1f}s "
        f"chi2={float(out[2]):.1f}")
    # forced host read of the step's chi2: on the axon tunnel
    # block_until_ready acks enqueue, not completion (see config4) —
    # a scalar D2H is the only sync primitive that cannot lie. The
    # extra round-trip is part of every real fitter iteration anyway
    # (the downhill accept/reject reads chi2 on host).
    t = time_fn(lambda: float(jitted(*args)[2]), reps)
    return t, float(out[2]), jitted, args, step_fn


def measure_step_chained(built, k=8, reps=3):
    """Amortized per-iteration time: k fit steps chained in ONE
    device program (lax.scan), so the per-dispatch fixed cost —
    dominant over the axon tunnel — is paid once for k iterations.
    This is the throughput a real fit sees with
    DeviceDownhillGLSFitter(steps_per_dispatch=k). A tiny
    chi2-dependent perturbation (~1e-15 of a parameter) chains each
    iteration onto the previous result so XLA cannot CSE the k bodies
    into one. ``built`` is measure_step's (step_fn, args) — reusing
    it skips a second full host precompute of the big problem."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    step_fn, args = built
    th, tl, *rest = args

    def chained(th_, tl_, *rest_):
        def body(carry, _):
            thc = carry
            # [:4]: with $PINT_TPU_HEALTH armed the step returns
            # its in-trace health vector as a fifth output
            _, _, chi2, _ = step_fn(thc, tl_, *rest_)[:4]
            return thc + 1e-18 * chi2, chi2

        _, chis = lax.scan(body, th_, None, length=k)
        return chis

    jitted = jax.jit(chained)
    jax.block_until_ready(jitted(th, tl, *rest))
    t = time_fn(lambda: float(jitted(th, tl, *rest)[-1]), reps)
    return t / k


def measure_step_pipelined(built, k=8, depth=2, reps=3):
    """Pipelined chained dispatches (ISSUE 7): ``depth`` chained
    programs in flight at once, blocking ONLY at result consumption
    (double-buffering on jax's async dispatch — issue all, then
    read). Returns the per-iteration wall amortized over depth*k
    steps; the --scan artifact reports it next to the sync chained
    number as the pipelined-vs-sync column. Distinct starting points
    per in-flight program so XLA cannot collapse them."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    step_fn, args = built
    th, tl, *rest = args

    def chained(th_, tl_, *rest_):
        def body(carry, _):
            thc = carry
            # [:4]: with $PINT_TPU_HEALTH armed the step returns
            # its in-trace health vector as a fifth output
            _, _, chi2, _ = step_fn(thc, tl_, *rest_)[:4]
            return thc + 1e-18 * chi2, chi2

        _, chis = lax.scan(body, th_, None, length=k)
        return chis

    jitted = jax.jit(chained)
    jax.block_until_ready(jitted(th, tl, *rest))
    ths = [th + 1e-15 * (i + 1) for i in range(depth)]

    def once():
        outs = [jitted(t_, tl, *rest) for t_ in ths]  # issue all
        return [float(o[-1]) for o in outs]           # then consume

    once()
    t = time_fn(once, reps)
    return t / (k * depth)


def measure_whole_fit(model, toas, per_step_s=None, reps=3,
                      maxiter=20, depth=2, **flags):
    """Whole-fit-on-device dispatch-overhead measurement (ISSUE 7):
    the ENTIRE downhill fit — damping, acceptance, convergence — as
    ONE lax.while_loop dispatch (build_fit_loop with maxiter as the
    runtime budget; (th, tl) donated when config.donation_enabled).

    The ``dispatch_overhead`` artifact block separates the wall into
    pure step compute and dispatch overhead. Pure step time is
    ``step_evals x per_eval``, with the per-eval cost measured from
    the SAME compiled program by varying only the runtime budget
    (marginal cost between a budget-1 and a full-budget dispatch) —
    comparing against a DIFFERENT program would fold compilation
    artifacts into the "overhead" (measured on XLA:CPU the loop's
    per-eval is ~2x the standalone step: compute nested in while_loop
    bodies is not thread-parallelized there; that honest ratio is
    reported as ``loop_step_ratio`` instead of being laundered into
    the dispatch number). ``overhead_frac`` = (wall − pure)/wall is
    the <10% acceptance target.

    ``depth`` whole fits are additionally issued IN FLIGHT at once
    (async dispatch, block only at consumption): on a high-RTT link
    the fixed dispatch cost overlaps across fits, and
    ``overhead_frac_pipelined`` is the amortized per-fit number a
    serving deployment sees."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pint_tpu import config
    from pint_tpu.parallel import build_fit_loop

    # K=32: the largest quantized compile key of the adaptive
    # chaining (config.auto_steps_per_dispatch) — the same executable
    # a production whole-fit uses; maxiter rides as runtime budget
    loop_fn, args, _ = build_fit_loop(model, toas, max_iter=32,
                                      **flags)
    donate = config.donation_enabled()
    if donate:
        jitted = jax.jit(loop_fn, donate_argnums=(0, 1))
    else:
        jitted = jax.jit(loop_fn)
    th0 = np.asarray(args[0], np.float64)
    tl0 = np.asarray(args[1], np.float64)
    body = args[2:-1]
    budget = min(int(maxiter), 32)

    def dispatch(budget_):
        # fresh (th, tl) device arrays per call: donation consumed
        # the previous pair (graftlint G11 discipline)
        return jitted(jnp.asarray(th0), jnp.asarray(tl0), *body,
                      jnp.asarray(budget_, jnp.int32))

    def once(budget_=budget):
        out = dispatch(budget_)
        return int(out[6]), int(out[10]), float(out[4])

    t0 = time.perf_counter()
    niter, nev, chi2 = once()   # compile + first dispatch
    log(f"  whole-fit compile+first: {time.perf_counter() - t0:.1f}s "
        f"iters={niter} evals={nev} chi2={chi2:.1f}")
    t = time_fn(lambda: once(), reps)
    block = {
        "fit_dispatch_ms": round(t * 1e3, 2),
        "iterations": niter,
        "step_evals": nev,
        "donation": donate,
        "in_flight_depth": 1,   # a converged whole fit IS 1 dispatch
    }
    # marginal per-eval cost: same executable, budget=1 (entry step +
    # the first iteration's line search) vs the full budget
    _, nev1, _ = once(1)
    if nev > nev1:
        t1 = time_fn(lambda: once(1), reps)
        per_eval = max((t - t1) / (nev - nev1), 0.0)
        pure = nev * per_eval
        block["per_eval_ms"] = round(per_eval * 1e3, 3)
        block["pure_step_ms"] = round(pure * 1e3, 2)
        block["overhead_frac"] = round((t - pure) / t, 4)
        if per_step_s:
            block["loop_step_ratio"] = round(per_eval / per_step_s, 2)
    elif per_step_s:
        # degenerate fit (one iteration): fall back to the standalone
        # step as the pure-step reference, labeled as such
        pure = nev * per_step_s
        block["pure_step_ms"] = round(pure * 1e3, 2)
        block["overhead_frac"] = round((t - pure) / t, 4)
        block["pure_step_ref"] = "standalone_step"
    # pipelined whole fits: depth in flight, read only at consumption
    # — the fixed dispatch cost overlaps across fits
    try:
        def pipelined():
            outs = [dispatch(budget) for _ in range(depth)]
            return [float(o[4]) for o in outs]

        pipelined()
        tp = time_fn(pipelined, reps) / depth
        block["fit_dispatch_ms_pipelined"] = round(tp * 1e3, 2)
        block["pipeline_depth"] = depth
        if "pure_step_ms" in block:
            pure_s = block["pure_step_ms"] / 1e3
            block["overhead_frac_pipelined"] = round(
                (tp - pure_s) / tp, 4)
    except Exception as e:
        log(f"  pipelined whole-fit failed: {e!r}")
    # ISSUE 15: ledger the whole-fit loop executable (the probe
    # lowers+compiles — no execution, no donated-buffer consumption;
    # the re-compile cost is fine in a measurement script with the
    # persistent bench jit cache warm) and derive its roofline from
    # the dispatch wall
    try:
        from pint_tpu.obs import perf as operf

        operf.note_compile(
            "bench.whole_fit_loop", backend=jax.default_backend(),
            kind="fit_loop", jitted=jitted,
            args=(jnp.asarray(th0), jnp.asarray(tl0), *body,
                  jnp.asarray(budget, jnp.int32)))
        roof = operf.roofline_block("bench.whole_fit_loop", t,
                                    jax.default_backend())
        if roof is not None:
            block["roofline"] = roof
    except Exception as e:
        log(f"  whole-fit roofline failed: {e!r}")
    return block


def measure_obs_overhead(step_call, reps=5):
    """Tracing-overhead measurement (ISSUE 10 acceptance targets:
    tracer OFF within noise of the uninstrumented wall, <1%; tracer
    ON <5%). Two measurements, one conclusion:

    1. **per-dispatch instrumentation cost**, resolved where it is
       actually measurable: the full supervised-dispatch + span path
       with a TRIVIAL payload, batched x200, tracer off vs on — the
       off/on delta IS the instrumentation cost (a few µs on the CPU
       mesh), independent of payload noise. ``overhead_frac`` is
       that cost against the real step wall — the honest number a
       ~µs effect on a ~50 ms step deserves.
    2. **evidence walls**: the real north-star step through the same
       path, tracer off vs on, ALTERNATING pairs with min-of-each
       (cancels monotonic load drift). On a noisy container the
       run-to-run spread of the step itself (tens of ms here —
       watcher probes, suite runs) dwarfs the µs signal, so these
       are reported as evidence, not divided against each other.

    A fresh DispatchSupervisor keeps the measurement's counters and
    latency histograms self-contained (returned as the artifact's
    ``latency`` block); the global tracer is restored to its
    env-driven state afterwards."""
    from pint_tpu import obs
    from pint_tpu.runtime import DispatchSupervisor

    sup = DispatchSupervisor()

    def once():
        with obs.span("bench.step"):
            sup.dispatch(step_call, key="bench.obs_step")

    def tiny_batch(n=_TINY_N):
        for _ in range(n):
            with obs.span("bench.tiny"):
                sup.dispatch(_noop_payload, key="bench.obs_tiny")

    # force-off legs must be GENUINELY off: an armed env stream or
    # flight dir would otherwise keep recording through the "off"
    # configure (recording = enabled OR stream OR flight), measuring
    # zero delta and vacuously "passing" the acceptance target
    def cfg(on: bool):
        obs.configure(enabled=on, stream=False, flight_dir=False)

    try:
        cfg(False)
        once()                      # warm both dispatch keys
        tiny_batch(2)
        # events per tiny iteration (the instrumented-unit size the
        # measured delta covers — dividing by it gives a per-EVENT
        # cost that composes with any step's event count)
        cfg(True)
        ring0 = len(obs.get_tracer())
        tiny_batch(1)
        events_per_tiny = max(1, len(obs.get_tracer()) - ring0)
        # 1. per-iteration instrumentation cost (trivial payload)
        t_tiny_off = t_tiny_on = float("inf")
        for _ in range(max(2, reps)):
            cfg(False)
            t_tiny_off = min(t_tiny_off, time_fn(tiny_batch, 1))
            cfg(True)
            t_tiny_on = min(t_tiny_on, time_fn(tiny_batch, 1))
        per_iter_us = max(0.0, t_tiny_on - t_tiny_off) \
            / _TINY_N * 1e6
        per_event_us = per_iter_us / events_per_tiny
        # 2. real-step evidence walls (alternating mins)
        cfg(True)
        ring0 = len(obs.get_tracer())
        once()
        events_per_step = len(obs.get_tracer()) - ring0
        t_off = t_on = float("inf")
        for _ in range(max(2, reps)):
            cfg(False)
            t_off = min(t_off, time_fn(once, 1))
            cfg(True)
            t_on = min(t_on, time_fn(once, 1))
        status = obs.get_tracer().status()
        block = {
            # the headline: instrumentation cost of one span+dispatch
            # unit, and the per-event cost scaled by the step's real
            # event count against the step wall
            "per_dispatch_overhead_us": round(per_iter_us, 2),
            "overhead_frac": round(
                per_event_us * 1e-6 * events_per_step / t_off, 6)
            if t_off else None,
            "events_per_step": events_per_step,
            # evidence walls (min over alternating pairs; their raw
            # difference is container noise, not tracer cost)
            "trace_off_step_ms": round(t_off * 1e3, 3),
            "trace_on_step_ms": round(t_on * 1e3, 3),
            "ring_size": status["ring_size"],
        }
        return block, sup.metrics.latency.snapshot()
    finally:
        obs.reset()


def measure_metrics_overhead(step_call, reps=5):
    """Metrics-plane overhead (ISSUE 11 acceptance: metrics-off
    north-star step <1%, metrics-on <5%). The registry counter bumps
    are always-on accounting (they replaced the old attr increments
    one-for-one), so the OFF leg is the production default: registry
    plumbing live, nothing armed. The ON leg arms everything the
    plane can cost at once: the SLO watchdog sampling the registry
    at a 20 ms interval AND a live /metrics scraper hammering the
    exposition server — an adversarially hot pull load, far beyond
    any real Prometheus cadence. Same methodology as
    ``measure_obs_overhead``: the off/on delta on a x200
    tiny-payload batch is the per-dispatch cost, reported against
    the real step wall; the raw step walls ride as evidence."""
    import threading

    from pint_tpu.obs import metrics as om
    from pint_tpu.obs.slo import SLOWatchdog, default_specs
    from pint_tpu.runtime import DispatchSupervisor

    sup = DispatchSupervisor()

    def once():
        sup.dispatch(step_call, key="bench.metrics_step")

    def tiny_batch(n=_TINY_N):
        for _ in range(n):
            sup.dispatch(_noop_payload, key="bench.metrics_tiny")

    once()
    tiny_batch(2)  # warm both dispatch keys
    t_tiny_off = t_off = float("inf")
    for _ in range(max(2, reps)):
        t_tiny_off = min(t_tiny_off, time_fn(tiny_batch, 1))
        t_off = min(t_off, time_fn(once, 1))
    srv = om.MetricsServer(port=0).start()
    wd = SLOWatchdog(specs=default_specs(), interval_s=0.02).start()
    stop = threading.Event()

    def scrape_loop():
        import urllib.request

        url = f"http://127.0.0.1:{srv.port}/metrics"
        while not stop.is_set():
            try:
                urllib.request.urlopen(url, timeout=5).read()
            except Exception:
                pass
            stop.wait(0.02)

    th = threading.Thread(target=scrape_loop, daemon=True,
                          name="bench-metrics-scraper")
    th.start()
    try:
        t_tiny_on = t_on = float("inf")
        for _ in range(max(2, reps)):
            t_tiny_on = min(t_tiny_on, time_fn(tiny_batch, 1))
            t_on = min(t_on, time_fn(once, 1))
    finally:
        stop.set()
        th.join(timeout=2.0)
        wd.stop()
        srv.close()
    per_iter_us = max(0.0, t_tiny_on - t_tiny_off) / _TINY_N * 1e6
    return {
        # one supervised dispatch per north-star step, so the
        # per-dispatch cost against the step wall IS the step frac
        "metrics_per_dispatch_overhead_us": round(per_iter_us, 2),
        "metrics_overhead_frac": round(per_iter_us * 1e-6 / t_off, 6)
        if t_off and t_off != float("inf") else None,
        "metrics_off_step_ms": round(t_off * 1e3, 3),
        "metrics_on_step_ms": round(t_on * 1e3, 3),
    }


def measure_perf_overhead(step_call, reps=5):
    """Perf-plane overhead (ISSUE 15 acceptance: disarmed <1%, armed
    ledger+decomposition <5% on the north-star step). The OFF leg is
    the production default: plane disarmed, every supervised
    dispatch pays one cached-bool read and a branch (profiler
    windows cost literally nothing — no dispatch path consults
    them). The ON leg arms everything the plane can cost PER
    DISPATCH: the wall decomposition (two extra perf_counter reads
    on the guarded worker + four histogram records); the JSONL
    ledger is armed too, but ledger writes are per-COMPILE events
    and the keys are warm here — by design they can never be a
    hot-path cost. Guarded dispatches on both legs (the
    decomposition only exists on the worker path, so the
    thread-spawn cost cancels in the off/on delta). Same methodology as ``measure_obs_overhead``: the
    per-dispatch delta on a x200 tiny-payload batch, reported
    against the real step wall; raw step walls ride as evidence."""
    import os
    import tempfile

    from pint_tpu import obs
    from pint_tpu.obs import perf as operf
    from pint_tpu.runtime import DispatchSupervisor

    sup = DispatchSupervisor()

    def once():
        sup.dispatch(step_call, key="bench.perf_step", guard=True)

    def tiny_batch(n=_TINY_N):
        for _ in range(n):
            sup.dispatch(_noop_payload, key="bench.perf_tiny",
                         guard=True)

    tmp = tempfile.mkdtemp(prefix="pint-perf-bench-")
    ledger = os.path.join(tmp, "ledger.jsonl")
    try:
        operf.configure(enabled=False, ledger_path=False,
                        profile_dir=False)
        once()               # warm both dispatch keys
        tiny_batch(2)
        t_tiny_off = t_off = float("inf")
        t_tiny_on = t_on = float("inf")
        for _ in range(max(2, reps)):
            operf.configure(enabled=False, ledger_path=False,
                            profile_dir=False)
            t_tiny_off = min(t_tiny_off, time_fn(tiny_batch, 1))
            t_off = min(t_off, time_fn(once, 1))
            operf.configure(enabled=True, ledger_path=ledger,
                            profile_dir=False)
            t_tiny_on = min(t_tiny_on, time_fn(tiny_batch, 1))
            t_on = min(t_on, time_fn(once, 1))
        per_iter_us = max(0.0, t_tiny_on - t_tiny_off) \
            / _TINY_N * 1e6
        return {
            # one supervised dispatch per north-star step, so the
            # per-dispatch cost against the step wall IS the frac
            "perf_per_dispatch_overhead_us": round(per_iter_us, 2),
            "perf_overhead_frac": round(per_iter_us * 1e-6 / t_off,
                                        6)
            if t_off and t_off != float("inf") else None,
            "perf_off_step_ms": round(t_off * 1e3, 3),
            "perf_on_step_ms": round(t_on * 1e3, 3),
        }
    finally:
        obs.reset()


def measure_perf_decomposition(step_call, reps=5):
    """Dispatch-wall decomposition evidence (ISSUE 15 acceptance:
    the components must sum to within 10% of the measured wall).
    Runs the real step through a fresh supervisor with the plane
    armed and the GUARDED worker forced (the phase boundaries are
    the worker's fn-return / host-read split), then reads the mean
    of each phase row back from the registry-shared ``perf``
    histogram family. ``sum_frac`` = (sum of phase means) / (mean
    measured wall) — the phases telescope over the dispatch window,
    so a healthy run sits at ~1.0; a large shortfall means the
    decomposition lost track of real time."""
    from pint_tpu import obs
    from pint_tpu.obs import perf as operf
    from pint_tpu.runtime import DispatchSupervisor

    try:
        operf.configure(enabled=True, ledger_path=False,
                        profile_dir=False)
        sup = DispatchSupervisor()

        def once():
            sup.dispatch(step_call, key="bench.decomp", guard=True)

        once()  # first call: compile-allowance path, then steady
        walls = []
        for _ in range(max(2, reps)):
            walls.append(time_fn(once, 1))
        import jax

        pool = jax.default_backend()
        snap = sup.metrics.perf.snapshot()
        row = snap.get(f"{pool}/bench.decomp") or {}
        block = {}
        total_ms = 0.0
        for phase in ("queue_wait", "host_assembly", "device_wall",
                      "collect"):
            h = row.get(phase) or {}
            mean = h.get("mean_ms")
            if mean is None:
                return {"error": f"phase {phase} missing from the "
                                 f"decomposition rows"}
            block[f"{phase}_ms"] = mean
            total_ms += mean
        wall_ms = sum(walls) / len(walls) * 1e3
        block["wall_ms"] = round(wall_ms, 3)
        block["phase_sum_ms"] = round(total_ms, 3)
        # mean over ALL recorded dispatches (incl. the first call)
        # vs the steady-state walls: compare like with like by using
        # the recorded dispatch_wall rows' mean when available
        lat = sup.metrics.latency.snapshot()
        dw = ((lat.get(f"{pool}/bench.decomp") or {})
              .get("dispatch_wall") or {})
        if dw.get("mean_ms"):
            block["dispatch_wall_mean_ms"] = dw["mean_ms"]
            block["sum_frac"] = round(total_ms / dw["mean_ms"], 4)
        else:
            block["sum_frac"] = round(total_ms / wall_ms, 4) \
                if wall_ms else None
        return block
    finally:
        obs.reset()


def measure_lock_trace_overhead(step_call, reps=5):
    """Lock-sanitizer overhead (ISSUE 18 acceptance: disarmed <1%,
    armed <5% on the north-star step). Arming is a CONSTRUCTION-time
    property — the disarmed factories return BARE stdlib primitives,
    so each leg builds a FRESH DispatchSupervisor under its own
    arming state: the off leg's locks are the exact production
    objects, not wrappers with a dormant branch. The ON leg pays the
    full traced path on every dispatch: held-stack push/pop, order-
    graph edge paint, hold/wait histogram records into the registry,
    and the armed ``check_dispatch_clear`` engine scan. Same
    methodology as ``measure_metrics_overhead``: the off/on
    per-dispatch delta on a x200 tiny-payload batch, reported
    against the real step wall; the raw step walls ride as
    evidence."""
    from pint_tpu import obs
    from pint_tpu.runtime import DispatchSupervisor, locks

    def leg(enabled):
        locks.configure(enabled=enabled)
        sup = DispatchSupervisor()

        def once():
            sup.dispatch(step_call, key="bench.lock_step")

        def tiny_batch(n=_TINY_N):
            for _ in range(n):
                sup.dispatch(_noop_payload, key="bench.lock_tiny")

        once()  # warm both dispatch keys
        tiny_batch(2)
        t_tiny = t_step = float("inf")
        for _ in range(max(2, reps)):
            t_tiny = min(t_tiny, time_fn(tiny_batch, 1))
            t_step = min(t_step, time_fn(once, 1))
        return t_tiny, t_step

    try:
        t_tiny_off, t_off = leg(False)
        t_tiny_on, t_on = leg(True)
        per_iter_us = max(0.0, t_tiny_on - t_tiny_off) \
            / _TINY_N * 1e6
        return {
            # one supervised dispatch per north-star step, so the
            # per-dispatch cost against the step wall IS the frac
            "lock_trace_per_dispatch_overhead_us":
                round(per_iter_us, 2),
            "lock_trace_overhead_frac":
                round(per_iter_us * 1e-6 / t_off, 6)
            if t_off and t_off != float("inf") else None,
            "lock_trace_off_step_ms": round(t_off * 1e3, 3),
            "lock_trace_on_step_ms": round(t_on * 1e3, 3),
        }
    finally:
        obs.reset()


def measure_health_overhead(model, toas, reps=5):
    """Numerical-health overhead (ISSUE 14 acceptance: disarmed <1%,
    armed <5% on the north-star step). The OFF leg is the production
    default: $PINT_TPU_HEALTH unset, the step program byte-identical
    to pre-health builds (the flag is a static compile-key bit) and
    every ``HealthMonitor.observe`` a single branch. The ON leg arms
    everything at once: the step REBUILT with the in-trace health
    vector (the extra reductions ride the same dispatch) and the
    monitor evaluating every vector against its thresholds.
    Alternating mins, the ``measure_obs_overhead`` methodology.

    Returns (overhead_block, evidence_block): the first carries the
    ``health_off/on_step_ms`` walls + fraction for the ``obs`` block
    and the perf-regression band; the second is the north-star
    ``health`` block — the armed monitor's status after one
    streaming CG pass and one FORCED shadow replay on the same
    problem (CG-iteration histogram + device-vs-host drift in
    sigma as on-artifact evidence)."""
    import jax
    import numpy as np

    from pint_tpu import obs
    from pint_tpu.obs import health as oh
    from pint_tpu.parallel import build_fit_step
    from pint_tpu.runtime import DispatchSupervisor

    sup = DispatchSupervisor()
    fn_off, args_off, _ = build_fit_step(model, toas, health=False)
    j_off = jax.jit(fn_off)
    fn_on, args_on, _ = build_fit_step(model, toas, health=True)
    j_on = jax.jit(fn_on)

    def once_off():
        sup.dispatch(
            lambda: jax.block_until_ready(j_off(*args_off)),
            key="bench.health_off")

    def once_on():
        out = sup.dispatch(
            lambda: jax.block_until_ready(j_on(*args_on)),
            key="bench.health_on")
        oh.observe("fit.device", {"hv": np.asarray(out[4])},
                   key="bench.health_on")

    try:
        oh.configure(enabled=False)
        once_off()   # warm both compiles + dispatch keys
        oh.configure(enabled=True)
        once_on()
        t_off = t_on = float("inf")
        for _ in range(max(2, reps)):
            oh.configure(enabled=False)
            t_off = min(t_off, time_fn(once_off, 1))
            oh.configure(enabled=True)
            t_on = min(t_on, time_fn(once_on, 1))
        block = {
            "health_off_step_ms": round(t_off * 1e3, 3),
            "health_on_step_ms": round(t_on * 1e3, 3),
            "health_overhead_frac": round(
                max(0.0, t_on - t_off) / t_off, 6) if t_off else None,
        }
        # evidence run: armed monitor + forced shadow (rate 1) on a
        # streaming pass of the SAME problem — populates the CG
        # effort histogram and the device-vs-host drift histogram
        # the north-star artifact embeds
        mon = oh.configure(enabled=True, shadow_rate=1)
        from pint_tpu.parallel.streaming import StreamingGLS

        sg = StreamingGLS(model, toas, health=True)
        state = sg.accumulate(sg.th0, sg.tl0)
        sg.solve(state)
        t0 = time.perf_counter()
        while mon._c_shadow.total() < 1 and \
                time.perf_counter() - t0 < 60.0:
            time.sleep(0.05)   # the replay runs on a daemon thread
        evidence = mon.status()
        evidence["overhead"] = dict(block)
        return block, evidence
    finally:
        obs.reset()


# tiny-payload iterations per timing sample in measure_obs_overhead
# (the ONE constant both the batch default and the per-iteration
# division use — tuning it in one place cannot skew the other)
_TINY_N = 200


def _noop_payload():
    return None


def measure_numpy_mirror(model, toas, reps=3):
    """The reference-algorithm CPU path: residuals + design matrix on
    the CPU backend, numpy/scipy basis-Woodbury solve (dense ECORR
    quantization columns, as the reference carries them)."""
    import jax
    import numpy as np

    from pint_tpu.gls import gls_solve_np
    from pint_tpu.residuals import Residuals

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        def cpu_once():
            res = Residuals(toas, model)
            r = res.time_resids
            M, _, _ = model.designmatrix(toas)
            nvec = model.scaled_toa_uncertainty(toas) ** 2
            F = model.noise_model_designmatrix(toas)
            phi = model.noise_model_basis_weight(toas)
            model._cache_key = None  # defeat caching: honest rebuild
            model.__dict__.pop("_noise_basis_cache", None)
            if F is None:
                F, phi = np.zeros((toas.ntoas, 0)), np.ones(0)
            return gls_solve_np(np.asarray(M), F, phi,
                                np.asarray(r), nvec)

        return time_fn(cpu_once, reps=reps)


# ---------------------------------------------------------------------
# BASELINE.md configs 1-5 (extra JSON lines; north star prints last)
# ---------------------------------------------------------------------


def config1_ngc6440e():
    """Config 1: NGC6440E fixture (62 TOAs, 6 params) — WLS fit."""
    import os
    import warnings

    from pint_tpu import get_model_and_toas
    from pint_tpu.fitter import WLSFitter

    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "tests", "datafile")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model, toas = get_model_and_toas(
            os.path.join(d, "NGC6440E.par"),
            os.path.join(d, "NGC6440E.tim"))
        fit = WLSFitter(toas, model)
        fit.fit_toas()  # warm compile
        t = time_fn(lambda: WLSFitter(toas, model).fit_toas(), reps=3)
    return {"metric": "config1_ngc6440e_wls_fit",
            "value": round(toas.ntoas / t, 1), "unit": "TOA/s",
            "fit_wall_ms": round(t * 1e3, 2)}


def config2_b1855like():
    """Config 2: B1855+09-like — 5k TOAs, ELL1 binary, GLS with
    EFAC/EQUAD/ECORR + red noise + DMX."""
    import numpy as np

    span0, span1 = 53000.0, 56000.0
    par = [
        "PSR B1855+09x", "RAJ 18:57:36.39 1", "DECJ 09:43:17.2 1",
        "PMRA -2.9 1", "PMDEC -5.5 1", "PX 0.3 1",
        "F0 186.49408156698235 1", "F1 -6.2049e-16 1",
        "DM 13.29", "PEPOCH 54500", "POSEPOCH 54500", "DMEPOCH 54500",
        "TZRMJD 54500.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "BINARY ELL1", "PB 12.32717 1", "A1 9.2307805 1",
        "TASC 54500.03 1", "EPS1 -2.15e-5 1", "EPS2 -3.1e-7 1",
        "SINI 0.999 1", "M2 0.25 1",
        "EFAC -be X 1.1", "EQUAD -be X 0.2", "ECORR -be X 0.9",
        "TNREDAMP -14.1", "TNREDGAM 4.1", "TNREDC 20",
    ]
    _add_dmx(par, span0, span1, 12)
    n = 5000
    mjds = _clustered_mjds(span0, span1, n)
    freqs = np.tile([1400.0, 1400.0, 430.0, 430.0], n // 4)
    model, toas = _make_model_toas(par, mjds, freqs, seed=2,
                                   flag_sets={"be": lambda i: "X"})
    t, chi2, jitted2, args, step_fn = measure_step(model, toas)
    per_iter = t
    dispatch_ms = None
    label = "single-dispatch (chained meas. FAILED)"
    try:
        tc = measure_step_chained((step_fn, args), k=8)
        if tc < t:
            per_iter = tc
            dispatch_ms = round(t * 1e3, 2)
            label = "amortized"
        else:
            label = "single-dispatch (faster than chained)"
    except Exception as e:
        log(f"  config2 chained failed: {e!r}")
    tnp = measure_numpy_mirror(model, toas)
    log(f"  config2: step {per_iter * 1e3:.1f} ms {label} "
        f"(dispatch {t * 1e3:.1f}), numpy mirror {tnp * 1e3:.1f} ms")
    rec = {"metric": "config2_b1855like_gls_ecorr_5k",
           "value": round(toas.ntoas / per_iter, 1), "unit": "TOA/s",
           "vs_baseline": round(tnp / per_iter, 2),
           "step_ms": round(per_iter * 1e3, 2)}
    if dispatch_ms is not None:
        rec["dispatch_ms"] = dispatch_ms
    import jax

    # reuse measure_step's jitted object: a fresh jax.jit wrapper has
    # an empty cache and would re-trace + recompile the whole step
    # (multi-minute over the tunnel) just to read the cost analysis
    rec.update(roofline_fields(jitted2, args, per_iter,
                               jax.default_backend()))
    return rec


def config3_j1713like_wideband():
    """Config 3: J1713+0747-like wideband TOAs — wideband downhill fit
    with DMX (stacked time+DM residual blocks)."""
    import numpy as np

    from pint_tpu.wideband_fitter import WidebandDownhillFitter

    span0, span1 = 53000.0, 56000.0
    par = [
        "PSR J1713+0747x", "RAJ 17:13:49.53 1", "DECJ 07:47:37.5 1",
        "PMRA 4.9 1", "PMDEC -3.9 1", "PX 0.85 1",
        "F0 218.8118437960826 1", "F1 -4.08e-16 1",
        "DM 15.99", "PEPOCH 54500", "POSEPOCH 54500", "DMEPOCH 54500",
        "TZRMJD 54500.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "BINARY ELL1", "PB 67.8251 1", "A1 32.34242 1",
        "TASC 54500.2 1", "EPS1 3.9e-5 1", "EPS2 -7.4e-5 1",
        "DMEFAC -be X 1.1", "DMEQUAD -be X 1e-5",
    ]
    _add_dmx(par, span0, span1, 10)
    n = 2000
    rng = np.random.default_rng(3)
    mjds = np.sort(rng.uniform(span0, span1, n))
    freqs = np.tile([1400.0, 2100.0], n // 2)
    model, toas = _make_model_toas(par, mjds, freqs, seed=3,
                                   flag_sets={"be": lambda i: "X"})
    # attach wideband DM measurements (flags -pp_dm / -pp_dme)
    dm0 = 15.99
    for i, f in enumerate(toas.flags):
        f["pp_dm"] = str(dm0 + rng.normal(0, 1e-4))
        f["pp_dme"] = "1e-4"
    model.F0.value += 5e-11
    WidebandDownhillFitter(toas, model).fit_toas()  # warm compiles
    model.F0.value += 5e-11
    fit = WidebandDownhillFitter(toas, model)
    fit.fit_toas()
    wall = fit.stats.wall_time_s
    # the one-kernel wideband iteration (the TPU path; reported under
    # its own metric key — the downhill metric keeps its historical
    # meaning of full-fit throughput including the host loop)
    t_step, _, jitted_w, args_w, step_w = measure_step(model, toas,
                                                       wideband=True)
    per_iter = t_step
    rec3 = {"metric": "config3_j1713like_wideband_step_2k",
            "value": round(toas.ntoas / per_iter, 1), "unit": "TOA/s",
            "step_ms": round(per_iter * 1e3, 2)}
    try:
        tc = measure_step_chained((step_w, args_w), k=8)
        if tc < t_step:
            per_iter = tc
            rec3.update(value=round(toas.ntoas / per_iter, 1),
                        step_ms=round(per_iter * 1e3, 2),
                        dispatch_ms=round(t_step * 1e3, 2))
    except Exception as e:
        log(f"  config3 chained failed: {e!r}")
    import jax

    rec3.update(roofline_fields(jitted_w, args_w,
                                rec3["step_ms"] / 1e3,
                                jax.default_backend()))
    rec3["backend"] = jax.default_backend()
    if rec3["backend"] == "tpu":
        tpu_record_append(rec3)
    print(json.dumps(rec3))
    return {"metric": "config3_j1713like_wideband_downhill_2k",
            "value": round(fit.stats.toas_per_sec, 1), "unit": "TOA/s",
            "fit_wall_ms": round(wall * 1e3, 1),
            "iterations": fit.stats.iterations}


def config4_j0613like_fullcov():
    """Config 4: J0613-0200-like ELL1 + PLRedNoise, dense
    full-covariance GLS (C = N + F phi F^T, O(N^2)) vs the same
    algorithm in numpy — the reference's full_cov=True branch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pint_tpu.gls import _gls_kernel_fullcov
    from pint_tpu.residuals import Residuals

    par = [
        "PSR J0613-0200x", "RAJ 06:13:43.97 1", "DECJ -02:00:47.2 1",
        "PMRA 1.84 1", "PMDEC -10.6 1", "PX 0.9 1",
        "F0 326.6005670074 1", "F1 -1.023e-15 1",
        "DM 38.77 1", "PEPOCH 54500", "POSEPOCH 54500",
        "TZRMJD 54500.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "BINARY ELL1", "PB 1.198512575 1", "A1 1.09144 1",
        "TASC 54500.11 1", "EPS1 3.5e-6 1", "EPS2 -2.5e-6 1",
        "TNREDAMP -13.9", "TNREDGAM 3.1", "TNREDC 15",
    ]
    n = 2000
    rng = np.random.default_rng(4)
    mjds = np.sort(rng.uniform(53000, 56000, n))
    freqs = np.tile([1400.0, 820.0], n // 2)
    model, toas = _make_model_toas(par, mjds, freqs, seed=4)
    r = jnp.asarray(Residuals(toas, model).time_resids)
    M, _, _ = model.designmatrix(toas)
    M = jnp.asarray(M)
    nvec = jnp.asarray(model.scaled_toa_uncertainty(toas) ** 2)
    F = jnp.asarray(model.noise_model_designmatrix(toas))
    phi = jnp.asarray(model.noise_model_basis_weight(toas))
    out = _gls_kernel_fullcov(M, F, phi, r, nvec)
    jax.block_until_ready(out)
    # time with a forced host read of the chi2 scalar: measured on the
    # axon tunnel, block_until_ready returned in ~0.07 ms for this
    # program (plainly not a completed 2k^2 Cholesky) — the remote
    # backend acks enqueue, not completion. float() can't lie.
    t = time_fn(lambda: float(
        _gls_kernel_fullcov(M, F, phi, r, nvec)[2]))

    # numpy mirror of the same dense algebra (scipy cho_factor)
    from scipy.linalg import cho_factor, cho_solve

    Mn_, F_, phi_, r_, nv_ = (np.asarray(M), np.asarray(F),
                              np.asarray(phi), np.asarray(r),
                              np.asarray(nvec))

    def np_once():
        C = np.diag(nv_) + (F_ * phi_[None, :]) @ F_.T
        cf = cho_factor(C, lower=True)
        norm = np.sqrt(np.sum(Mn_ * Mn_, axis=0))
        Mn = Mn_ / norm[None, :]
        CiM = cho_solve(cf, Mn)
        Cir = cho_solve(cf, r_)
        Sigma = Mn.T @ CiM
        b = Mn.T @ Cir
        cf2 = cho_factor(Sigma, lower=True)
        return cho_solve(cf2, b) / norm

    tnp = time_fn(np_once, reps=3)
    log(f"  config4: fullcov kernel {t * 1e3:.1f} ms, numpy "
        f"{tnp * 1e3:.1f} ms (accuracy cross-check, not a perf "
        f"config)")
    # VERDICT weak #6 (ISSUE 12 satellite): config 4's status is
    # recorded IN the artifact — the dense O(N^2) full-covariance
    # kernel exists to cross-check the basis-Woodbury algebra, it
    # never beat numpy at 2k and the streaming matrix-free path
    # (gls_streaming_scan) supersedes it as the large-N story.
    return {"metric": "config4_j0613like_fullcov_gls_2k",
            "value": round(n / t, 1), "unit": "TOA/s",
            "vs_baseline": round(tnp / t, 2),
            "solve_ms": round(t * 1e3, 2),
            "status": "accuracy_cross_check",
            "rationale": ("dense O(N^2) full-covariance solve kept "
                          "as an algebra cross-check only: it never "
                          "beat the numpy mirror at this size, and "
                          "the matrix-free streaming path "
                          "(gls_streaming_scan) is the large-N "
                          "configuration")}


def config5_pta():
    """Config 5: 67-pulsar PTA batch — one vmapped GLS solve per
    iteration across the whole array (bench_pta.py folded into the
    artifact per the round-3 brief)."""
    from bench_pta import build_pulsar

    from pint_tpu.parallel import fit_pta

    t0 = time.perf_counter()
    pulsars = [build_pulsar(k, 100) for k in range(67)]
    log(f"  config5: built 67 pulsars in {time.perf_counter() - t0:.0f}s")
    res = fit_pta([(t, m) for m, t, _ in pulsars], maxiter=2)
    stats = fit_pta.last_stats
    n_ok = sum(1 for (m, t, truth), r in zip(pulsars, res)
               if abs(m.F0.value - truth["F0"]) < 5 * r["errors"]["F0"])
    return {"metric": "config5_pta_batch_67psr",
            "value": round(stats["toas_per_sec"], 1), "unit": "TOA/s",
            "npulsars": 67, "ntoa_total": stats["ntoa_total"],
            "device_solve_ms": round(stats["device_solve_s"] * 1e3, 1),
            "recovered_5sigma": n_ok}


def late_tpu_probe(extra_timeout: float = 900.0):
    """After a CPU fallback, retry the TPU once the heavy work is done:
    a transiently-wedged tunnel shouldn't cost the round's TPU number.
    Runs bench.py --north-star-only in a bounded subprocess with the
    stashed axon env restored; returns its parsed JSON dict or None."""
    import os
    import subprocess

    stash = json.loads(os.environ.get("PINT_TPU_AXON_STASH", "{}"))
    if not stash:
        return None
    env = dict(os.environ)
    env.update(stash)
    env.pop("PINT_TPU_BENCH_FALLBACK", None)
    env.pop("JAX_PLATFORMS", None)
    # cheap bounded probe first — don't spend the subprocess timeout
    # discovering the tunnel is still dead
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=180, capture_output=True, env=env)
        if r.returncode != 0:
            return None
    except subprocess.TimeoutExpired:
        return None
    log("late probe: accelerator responsive again — measuring on TPU")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--north-star-only"],
            timeout=extra_timeout, capture_output=True, text=True,
            env=env)
    except subprocess.TimeoutExpired:
        log("late probe: TPU run timed out")
        return None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if d.get("metric", "").startswith("gls_fit_iteration"):
            return d
    log(f"late probe: no parseable result (rc={r.returncode})")
    return None


def build_problem_streaming():
    """The --scan streaming model: the north-star model WITHOUT
    ECORR. The streaming path handles ECORR (segment boundary carry,
    oracle-tested), but the DENSE host oracle the acceptance gate
    demands (gls_solve_np) would need the quantization basis as
    ~N/4 dense columns — unbuildable at these N. Red noise + EFAC/
    EQUAD keeps q fixed at 2*TNREDC so the oracle stays dense-able
    to 131k while N scales unbounded."""
    import numpy as np

    span0, span1 = 53000.0, 57000.0
    par = [
        "PSR J0000+0001", "RAJ 12:00:00.0 1", "DECJ 30:00:00.0 1",
        "PMRA 2.0 1", "PMDEC -3.0 1", "PX 1.2 1",
        "F0 300.123456789 1", "F1 -1.0e-15 1", "F2 1e-26 1",
        "DM 20.0", "DM1 1e-4", "DM2 1e-6",
        "PEPOCH 55000", "POSEPOCH 55000", "DMEPOCH 55000",
        "TZRMJD 55000.1", "TZRSITE @", "TZRFRQ 1400", "UNITS TDB",
        "EFAC -be X 1.1", "EQUAD -be X 0.3",
        "TNREDAMP -13.7", "TNREDGAM 3.5", "TNREDC 15",
    ]
    _add_dmx(par, span0, span1, NDMX)
    mjds = _clustered_mjds(span0, span1, NTOA)
    freqs = np.tile([1400.0, 1400.0, 820.0, 820.0], NTOA // 4)
    return _make_model_toas(par, mjds, freqs, seed=1,
                            flag_sets={"be": lambda i: "X"})


def _streaming_oracle(model, toas, dp, chi2_fit):
    """Dense host GLS (gls_solve_np — the reference-algorithm numpy
    mirror) vs the streaming CG solution: max |d dparams| in sigma
    and the relative chi2 error. Only callable where the dense
    (N, p+q) host assembly is sane (the <=131k gate)."""
    import numpy as np

    from pint_tpu.gls import gls_solve_np
    from pint_tpu.residuals import Residuals

    r = Residuals(toas, model).time_resids
    M, names, _ = model.designmatrix(toas, incoffset=True)
    nvec = model.scaled_toa_uncertainty(toas) ** 2
    F = model.noise_model_designmatrix(toas)
    phi = model.noise_model_basis_weight(toas)
    x, cov, chi2, _ = gls_solve_np(np.asarray(M), np.asarray(F),
                                   np.asarray(phi), np.asarray(r),
                                   np.asarray(nvec))
    sig = np.sqrt(np.abs(np.diag(cov)))
    # gls_solve_np returns xhat (correction to ADD is -xhat) and
    # the LINEARIZED post-fit chi2 — compare like with like
    return (float(np.max(np.abs(dp - (-x)) / sig)),
            float(abs(chi2_fit - chi2) / abs(chi2)))


def _peak_rss_mb():
    import resource

    return round(resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)


def scan_streaming():
    """--scan extension (ISSUE 12): the matrix-free streaming path's
    N-scaling curve to 1M TOAs on a single chip. Each point is one
    full accumulate+CG pass (the unit a fit iterates); the 1M point
    additionally runs a full StreamingGLSFitter downhill fit. The
    CPU equality oracle (streaming CG vs dense host gls_solve_np) is
    ASSERTED at every size <= 131072 — an oracle failure fails the
    bench loudly rather than shipping a wrong curve."""
    import gc

    import jax
    import numpy as np

    from pint_tpu.parallel.streaming import StreamingGLS

    global NTOA
    out = []
    fit_block = None
    for n in (10_000, 30_000, 100_000, 300_000, 1_000_000):
        NTOA = n
        try:
            model, toas = build_problem_streaming()
            sg = StreamingGLS(model, toas)
            t0 = time.perf_counter()
            state = sg.accumulate(sg.th0, sg.tl0)
            (dp, cov, chi2, chi2r, xf, ok, iters,
             cg_resid) = sg.solve(state)
            wall = time.perf_counter() - t0
            # second pass on the warm compile = the honest per-pass
            # cost a fit iteration pays
            t0 = time.perf_counter()
            state = sg.accumulate(sg.th0, sg.tl0)
            _ = sg.solve(state)
            wall = min(wall, time.perf_counter() - t0)
            P = sg.p + sg.q
            rec = {"metric": "gls_streaming_scan", "ntoa": n,
                   "value": round(n / wall, 1), "unit": "TOA/s",
                   "pass_wall_ms": round(wall * 1e3, 1),
                   "chunk": sg.chunk, "nchunks": sg.nchunks,
                   "cg_iters": int(iters), "cg_ok": bool(ok),
                   "cg_rel_residual": float(f"{cg_resid:.3e}"),
                   "cg_budget": sg.default_budget,
                   "nparam": sg.p, "nbasis": sg.q,
                   "state_bytes": int((P * P + 4 * P + 16) * 8),
                   "peak_rss_mb": _peak_rss_mb(),
                   "backend": jax.default_backend()}
            # ISSUE 15: streaming-chunk roofline from the compile
            # ledger (cost attached by StreamingGLS's first chunk)
            # at the measured per-chunk wall
            try:
                from pint_tpu.obs import perf as operf

                roof = operf.roofline_block(
                    "stream.chunk", wall / max(1, sg.nchunks),
                    rec["backend"])
                if roof is not None:
                    rec["roofline"] = roof
            except Exception:
                pass
            if n <= 131_072:
                worst_sig, chi_rel = _streaming_oracle(
                    model, toas, dp, chi2)
                rec["oracle_max_sigma"] = float(
                    f"{worst_sig:.3e}")
                rec["oracle_chi2_rel"] = float(f"{chi_rel:.3e}")
                assert ok and worst_sig < 1e-6 and chi_rel < 1e-8, (
                    f"streaming oracle FAILED at N={n}: "
                    f"{worst_sig=} {chi_rel=} {ok=}")
                log(f"N={n}: streaming {rec['pass_wall_ms']} ms/pass"
                    f" ({rec['value']:.0f} TOA/s), oracle "
                    f"{worst_sig:.2e} sigma")
            else:
                log(f"N={n}: streaming {rec['pass_wall_ms']} ms/pass"
                    f" ({rec['value']:.0f} TOA/s), chunk "
                    f"{sg.chunk} x {sg.nchunks}")
            if n == 1_000_000:
                # the acceptance headline: a complete million-TOA
                # single-chip downhill fit
                from pint_tpu.gls import StreamingGLSFitter

                import copy as _copy

                fm = _copy.deepcopy(model)
                f = StreamingGLSFitter(toas, fm)
                t0 = time.perf_counter()
                chi2_fit = f.fit_toas(maxiter=8)
                fit_wall = time.perf_counter() - t0
                fit_block = {
                    "fit_wall_s": round(fit_wall, 2),
                    "passes": f.passes,
                    "chi2": round(float(chi2_fit), 2),
                    "reduced_chi2": round(
                        f.stats.reduced_chi2, 4),
                    "converged": bool(f.converged),
                    "toas_per_sec": round(
                        f.stats.toas_per_sec, 1),
                    # solver effort per pass (ISSUE 14 satellite):
                    # the CG iterations each streaming pass spent
                    # vs its runtime budget, plus the final pass's
                    # relative residual — previously computed on
                    # device and discarded
                    "cg_iters_per_pass": f.cg_iters_per_pass,
                    "cg_budget": f.cg_budget,
                    "cg_rel_residual": float(
                        f"{f.cg_rel_residual:.3e}")
                    if f.cg_rel_residual is not None else None}
                log(f"1M-TOA fit: {fit_wall:.1f} s, "
                    f"{f.passes} passes, red-chi2 "
                    f"{f.stats.reduced_chi2:.3f}")
            if rec["backend"] == "tpu":
                tpu_record_append(rec)
            out.append(rec)
        except AssertionError:
            raise
        except Exception as e:
            log(f"  streaming scan point N={n} failed: {e!r}")
            out.append({"metric": "gls_streaming_scan", "ntoa": n,
                        "error": repr(e)})
        finally:
            gc.collect()
    for rec in out:
        print(json.dumps(rec))
    # the banded summary artifact: the 1M point + the fit block +
    # the memory curve, judged by the regress gate
    head = [r for r in out if r.get("ntoa") == 1_000_000
            and "value" in r]
    if head:
        summary = dict(head[0], metric="gls_streaming_scan_1m")
        if fit_block is not None:
            summary["fit"] = fit_block
        summary["memory_curve"] = [
            {"ntoa": r["ntoa"], "peak_rss_mb": r["peak_rss_mb"],
             "state_bytes": r["state_bytes"]}
            for r in out if "peak_rss_mb" in r]
        oracles = [r["oracle_max_sigma"] for r in out
                   if "oracle_max_sigma" in r]
        summary["oracle_worst_sigma"] = max(oracles) if oracles \
            else None
        print(json.dumps(attach_dispatch_counters(summary)))
        if summary["backend"] == "tpu":
            tpu_record_append(summary)


def scan_nscaling():
    """--scan: step time vs N (10k/30k/100k TOAs) on the default
    backend — the MXU-crossover measurement (the TPU's advantage grows
    with N as the matmuls fatten while fixed overheads amortize)."""
    import jax

    global NTOA
    out = []
    for n in (10_000, 30_000, 100_000):
        NTOA = n
        model, toas = build_problem()
        t, chi2, jitted, args, step_fn = measure_step(model, toas,
                                                      reps=3)
        rec = {"metric": "gls_step_nscaling", "ntoa": n,
               "step_ms": round(t * 1e3, 2),
               "value": round(n / t, 1), "unit": "TOA/s",
               "backend": jax.default_backend()}
        try:
            tc = measure_step_chained((step_fn, args), k=8)
            if tc < t:
                rec.update(step_ms=round(tc * 1e3, 2),
                           value=round(n / tc, 1),
                           dispatch_ms=round(t * 1e3, 2))
                label = "amortized"
            else:
                label = "single-dispatch (faster than chained)"
        except Exception as e:
            log(f"  chained scan point failed: {e!r}")
            label = "single-dispatch (chained meas. FAILED)"
        try:
            # pipelined-vs-sync column (ISSUE 7): two chained
            # programs in flight, read only at consumption — what
            # async double-buffered dispatch buys at this N
            tp = measure_step_pipelined((step_fn, args), k=8,
                                        depth=2)
            rec["step_ms_pipelined"] = round(tp * 1e3, 2)
            sync_per = rec["step_ms"] / 1e3
            rec["pipeline_speedup"] = round(sync_per / tp, 2)
        except Exception as e:
            log(f"  pipelined scan point failed: {e!r}")
        rec.update(roofline_fields(jitted, args,
                                   rec["step_ms"] / 1e3,
                                   rec["backend"]))
        log(f"N={n}: {rec['step_ms']} ms {label} "
            f"({rec['value']:.0f} TOA/s), dispatch {t * 1e3:.1f} ms")
        if rec["backend"] == "tpu":
            tpu_record_append(rec)
        out.append(rec)
        del jitted, args, step_fn, model, toas
    for rec in out:
        print(json.dumps(rec))


def main():
    import os
    import sys

    north_star_only = "--north-star-only" in sys.argv

    # only the axon TPU tunnel has the hang-on-init failure mode; on
    # plain hosts skip the probe subprocess entirely
    if not os.environ.get("PINT_TPU_BENCH_FALLBACK") and \
            os.environ.get("PALLAS_AXON_POOL_IPS"):
        if not accelerator_responsive():
            log("accelerator backend unresponsive; re-running on CPU")
            os.execvpe(sys.executable,
                       [sys.executable, __file__] + sys.argv[1:],
                       cpu_fallback_env())

    t_start = time.perf_counter()

    import jax

    jax.config.update("jax_enable_x64", True)
    # persistent XLA compile cache: dedups the per-pulsar compiles of
    # config 5 within a run and warms repeat runs
    from pint_tpu.config import enable_compile_cache

    enable_compile_cache(
        "PINT_TPU_BENCH_JIT_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))

    if "--scan" in sys.argv:
        scan_nscaling()
        # ISSUE 12: the matrix-free streaming curve to 1M TOAs (its
        # banded summary line prints LAST — the --scan artifact)
        scan_streaming()
        return

    backend = jax.default_backend()
    log(f"backend: {backend}, devices: {jax.devices()}")

    model, toas = build_problem()
    nfree = len(model.free_params)
    log(f"N={toas.ntoas} free params={nfree}")

    accel_t, chi2, jitted, args, step_fn = measure_step(model, toas)
    log(f"accelerated fit step [{backend}]: {accel_t * 1e3:.1f} ms "
        f"({toas.ntoas / accel_t:.0f} TOA/s)")

    # amortized per-iteration time with 8 steps per dispatch — the
    # number a real downhill fit sees (steps_per_dispatch=8); on a
    # high-latency tunnel this strips the per-dispatch fixed cost
    chained_ms = None
    try:
        chained_t = measure_step_chained((step_fn, args), k=8)
        chained_ms = round(chained_t * 1e3, 2)
        log(f"chained x8 per-step [{backend}]: {chained_ms} ms "
            f"({toas.ntoas / chained_t:.0f} TOA/s amortized)")
    except Exception as e:
        log(f"chained-step measurement failed: {e!r}")

    # whole-fit-on-device dispatch overhead (ISSUE 7): the <10%
    # acceptance target is machine-checked off this block
    overhead_block = None
    try:
        per_step_ref = (chained_ms / 1e3
                        if chained_ms is not None and
                        chained_ms / 1e3 < accel_t else accel_t)
        overhead_block = measure_whole_fit(model, toas,
                                           per_step_s=per_step_ref)
        log(f"whole-fit dispatch [{backend}]: "
            f"{overhead_block['fit_dispatch_ms']} ms for "
            f"{overhead_block['step_evals']} step evals "
            f"(overhead_frac={overhead_block.get('overhead_frac')})")
    except Exception as e:
        log(f"whole-fit measurement failed: {e!r}")

    # ledger snapshot BEFORE the overhead measurements: each one
    # isolates itself with obs.reset(), which drops the process
    # compile ledger — the executables built so far (the north-star
    # step's supervised keys, the whole-fit loop) are captured here
    # and merged back into the artifact's `compiles` block, so the
    # block keeps its "every executable this process built" meaning
    pre_reset_compiles = None
    try:
        from pint_tpu.obs import perf as _operf

        pre_reset_compiles = _operf.ledger_summary()
    except Exception:
        pass

    # tracing-overhead measurement (ISSUE 10): same step, production
    # supervised path, tracer off vs on — the `obs` block's <1%/<5%
    # acceptance targets, with the per-(pool,key) latency histograms
    # of the measurement run as the `latency` block
    obs_block = lat_block = None
    try:
        obs_block, lat_block = measure_obs_overhead(
            lambda: jax.block_until_ready(jitted(*args)))
        log(f"tracing overhead [{backend}]: off "
            f"{obs_block['trace_off_step_ms']} ms, on "
            f"{obs_block['trace_on_step_ms']} ms "
            f"(frac={obs_block['overhead_frac']}, "
            f"{obs_block['events_per_step']} events/step)")
    except Exception as e:
        log(f"tracing-overhead measurement failed: {e!r}")
    # metrics-plane overhead (ISSUE 11): registry plumbing alone vs
    # SLO watchdog + live /metrics scrape, extending the obs block
    # with the off/on walls as acceptance evidence (<1% / <5%)
    try:
        mblock = measure_metrics_overhead(
            lambda: jax.block_until_ready(jitted(*args)))
        # feed the overhead gauge the SLO's gauge-type spec watches
        if overhead_block is not None and \
                overhead_block.get("overhead_frac") is not None:
            from pint_tpu.obs import metrics as om

            om.gauge("pint_tpu_dispatch_overhead_frac",
                     "whole-fit dispatch overhead fraction "
                     "(pure-step vs wall)").set(
                overhead_block["overhead_frac"])
        if obs_block is None:
            obs_block = mblock
        else:
            obs_block.update(mblock)
        log(f"metrics overhead [{backend}]: off "
            f"{mblock['metrics_off_step_ms']} ms, on "
            f"{mblock['metrics_on_step_ms']} ms "
            f"(frac={mblock['metrics_overhead_frac']})")
    except Exception as e:
        log(f"metrics-overhead measurement failed: {e!r}")
    # numerical-health overhead + evidence (ISSUE 14): disarmed step
    # vs armed in-trace taps + monitor, same methodology; the armed
    # evidence run populates the CG-effort and shadow-drift
    # histograms the artifact's `health` block carries
    health_block = None
    try:
        hblock, health_block = measure_health_overhead(model, toas)
        if obs_block is None:
            obs_block = hblock
        else:
            obs_block.update(hblock)
        log(f"health overhead [{backend}]: off "
            f"{hblock['health_off_step_ms']} ms, on "
            f"{hblock['health_on_step_ms']} ms "
            f"(frac={hblock['health_overhead_frac']})")
    except Exception as e:
        log(f"health-overhead measurement failed: {e!r}")
    # perf-plane overhead + decomposition (ISSUE 15): disarmed vs
    # armed (decomposition + JSONL ledger) on the supervised step —
    # the <1%/<5% acceptance evidence — plus the dispatch-wall
    # decomposition block whose phases must sum to the wall
    decomp_block = None
    try:
        pblock = measure_perf_overhead(
            lambda: jax.block_until_ready(jitted(*args)))
        if obs_block is None:
            obs_block = pblock
        else:
            obs_block.update(pblock)
        log(f"perf-plane overhead [{backend}]: off "
            f"{pblock['perf_off_step_ms']} ms, on "
            f"{pblock['perf_on_step_ms']} ms "
            f"(frac={pblock['perf_overhead_frac']})")
        decomp_block = measure_perf_decomposition(
            lambda: jax.block_until_ready(jitted(*args)))
        log(f"dispatch decomposition [{backend}]: "
            f"{decomp_block}")
    except Exception as e:
        log(f"perf-plane measurement failed: {e!r}")
    # lock-sanitizer overhead (ISSUE 18): disarmed bare-stdlib locks
    # vs the armed traced path, each on a freshly-built supervisor —
    # the concurrency plane's <1%/<5% acceptance evidence
    try:
        lblock = measure_lock_trace_overhead(
            lambda: jax.block_until_ready(jitted(*args)))
        if obs_block is None:
            obs_block = lblock
        else:
            obs_block.update(lblock)
        log(f"lock-trace overhead [{backend}]: off "
            f"{lblock['lock_trace_off_step_ms']} ms, on "
            f"{lblock['lock_trace_on_step_ms']} ms "
            f"(frac={lblock['lock_trace_overhead_frac']})")
    except Exception as e:
        log(f"lock-trace measurement failed: {e!r}")

    # transparency: the f32-Jacobian variant is auto-on only on TPU;
    # when we're on the CPU backend measure it too (it halves the CPU
    # step at <1e-2 sigma agreement — tests/test_jac32.py)
    jac32_ms = None
    if backend == "cpu":
        import jax as _jax

        from pint_tpu.parallel import build_fit_step

        fn2, args2, _ = build_fit_step(model, toas, jac_f32=True)
        j2 = _jax.jit(fn2)
        _jax.block_until_ready(j2(*args2))
        jac32_ms = round(time_fn(
            lambda: _jax.block_until_ready(j2(*args2))) * 1e3, 2)
        log(f"f32-jacobian variant [cpu]: {jac32_ms} ms")
        del fn2, j2, args2  # keep the pre-configs memory release real

    # same XLA program on the host CPU backend, full-f64 flags (the
    # honest backend-vs-backend comparison, reported alongside)
    cpu_xla_ms = None
    if backend != "cpu":
        from pint_tpu.parallel import build_fit_step

        cpu = jax.devices("cpu")[0]
        step_c, args_c, _ = build_fit_step(model, toas,
                                           matmul_f32=False,
                                           jac_f32=False)
        with jax.default_device(cpu):
            cpu_args = jax.device_put(args_c, cpu)
            cpu_jit = jax.jit(step_c)
            jax.block_until_ready(cpu_jit(*cpu_args))
            cpu_xla_t = time_fn(
                lambda: jax.block_until_ready(cpu_jit(*cpu_args)))
        cpu_xla_ms = round(cpu_xla_t * 1e3, 2)
        log(f"same step on CPU-XLA (f64): {cpu_xla_ms} ms")

    # optional device-trace capture for step attribution (validated
    # parser — raw env reads are banned, ISSUE 11/15 convention)
    from pint_tpu.config import profile_dir as _profile_dir

    profdir = _profile_dir()
    if profdir:
        from pint_tpu.profiling import trace

        with trace(profdir):
            jax.block_until_ready(jitted(*args))
        log(f"profile trace written to {profdir}")

    cpu_t = measure_numpy_mirror(model, toas)
    log(f"cpu reference path: {cpu_t * 1e3:.1f} ms "
        f"({toas.ntoas / cpu_t:.0f} TOA/s)")

    # normal-equation matmul FLOPs (the MXU-resident share of the
    # step): Sigma/b assembly 2N(p+q)^2 + ECORR downdate 2*nseg(p+q)^2
    nfree_cols = nfree + 1
    seg = model.noise_model_ecorr_segments(toas)
    nseg = len(seg[1]) if seg is not None else 1
    exclude = seg[2] if seg is not None else ()
    Fb = model.noise_model_designmatrix(toas, exclude=exclude)
    q = 0 if Fb is None else Fb.shape[1]
    mm_flops = (2 * toas.ntoas * (nfree_cols + q) ** 2
                + 2 * nseg * (nfree_cols + q) ** 2)
    log(f"normal-eq matmul flops: {mm_flops / 1e9:.2f} GFLOP -> "
        f"{mm_flops / accel_t / 1e9:.1f} GFLOP/s achieved")

    # headline = amortized per-iteration time. A production fit runs
    # K steps per device dispatch (DeviceDownhillGLSFitter,
    # steps_per_dispatch=8), so the per-dispatch fixed cost — ~230 ms
    # of round-trip latency on the axon tunnel, negligible on a local
    # chip — is paid once per K iterations. The raw single-dispatch
    # time stays visible as dispatch_ms.
    per_iter_t = accel_t
    if chained_ms is not None and chained_ms / 1e3 < accel_t:
        per_iter_t = chained_ms / 1e3
    north = {
        "metric": "gls_fit_iteration_throughput_10k_toas_40p",
        "value": round(toas.ntoas / per_iter_t, 1),
        "unit": "TOA/s",
        "vs_baseline": round(cpu_t / per_iter_t, 2),
        "backend": backend,
        "step_ms": round(per_iter_t * 1e3, 2),
        "dispatch_ms": round(accel_t * 1e3, 2),
        "numpy_mirror_ms": round(cpu_t * 1e3, 1),
        "mm_gflops": round(mm_flops / 1e9, 2),
    }
    if cpu_xla_ms is not None:
        north["cpu_xla_step_ms"] = cpu_xla_ms
    if jac32_ms is not None:
        north["step_ms_jac32"] = jac32_ms
    if chained_ms is not None:
        north["step_ms_chained8"] = chained_ms
    if overhead_block is not None:
        north["dispatch_overhead"] = overhead_block
    if obs_block is not None:
        north["obs"] = obs_block
    if health_block is not None:
        north["health"] = health_block
    if lat_block is not None:
        north["latency"] = lat_block
    north.update(roofline_fields(jitted, args, per_iter_t, backend))
    # ISSUE 15: the ledger-derived attribution blocks — the step's
    # cost lands in the compile ledger ONCE (probe is a cache hit),
    # the `roofline` block is derived from ledger cost ÷ the
    # measured per-iteration wall against the per-backend peak
    # table, and `compiles` summarizes every executable this
    # process built (walls included)
    try:
        from pint_tpu.obs import perf as operf

        operf.note_compile("bench.north_star_step", backend=backend,
                           kind="fit_step", jitted=jitted, args=args)
        roof = operf.roofline_block("bench.north_star_step",
                                    per_iter_t, backend)
        if roof is not None:
            north["roofline"] = roof
        if decomp_block is not None:
            north["dispatch_decomposition"] = decomp_block
        summary = operf.ledger_summary()
        if pre_reset_compiles:
            # merge the pre-reset executables back in (current
            # entries win on key collision — they are the freshest)
            merged = dict(pre_reset_compiles.get("keys", {}))
            merged.update(summary.get("keys", {}))
            summary["keys"] = merged
            summary["compiles"] = len(merged)
            summary["aot_restored"] = sum(
                1 for e in merged.values() if e.get("aot_restored"))
            summary["total_compile_wall_s"] = round(sum(
                e.get("compile_wall_s") or 0.0
                for e in merged.values()), 4)
        north["compiles"] = summary
    except Exception as e:
        log(f"perf attribution blocks failed: {e!r}")

    # provenance merge: carry the latest committed on-chip records
    # (BENCH_TPU.jsonl, written during caught tunnel windows) so a
    # CPU-fallback artifact still shows the TPU state of the art — and
    # says plainly when the chip was unreachable this run.
    onchip = load_tpu_records()
    if backend == "tpu":
        tpu_record_append(north)
    else:
        ns_chip = onchip.get(record_key(north))
        if ns_chip is not None:
            north["tpu_on_chip"] = {
                k: ns_chip[k] for k in
                ("step_ms", "dispatch_ms", "value", "utc",
                 "mfu_pct", "flops_step", "imported", "provenance")
                if k in ns_chip}
            cfg_note = (" — PRE-HYBRID configuration, production "
                        "config not yet measured on chip"
                        if ns_chip.get("imported") else "")
            north["tpu_note"] = (
                "TPU unreachable this run; latest committed on-chip "
                f"record from {ns_chip.get('utc', '?')} "
                f"(BENCH_TPU.jsonl){cfg_note}")
        elif os.environ.get("PINT_TPU_BENCH_FALLBACK"):
            north["tpu_note"] = ("TPU unreachable this run; no "
                                 "committed on-chip record found")

    if north_star_only:
        print(json.dumps(attach_dispatch_counters(north)))
        return
    if backend != "tpu":
        # CPU fallback: replay the committed on-chip records so the
        # driver artifact carries them (fresh-TPU runs skip this —
        # stale lines for metrics about to be measured would only
        # confuse per-metric stdout consumers)
        for rec in onchip.values():
            rec = dict(rec)
            rec.setdefault("provenance", "BENCH_TPU.jsonl")
            print(json.dumps(rec))

    # the driver records the LAST stdout JSON line and may kill this
    # process on its own timeout (measured: configs over the TPU
    # tunnel can take many minutes each, mostly remote compiles). Two
    # defenses: print the north-star line BEFORE the first config and
    # again after every config, so an external kill at any point can
    # never cost the round's headline artifact; and stop starting new
    # configs once the elapsed budget is spent
    # ($PINT_TPU_BENCH_BUDGET_S, measured from main() entry; default
    # 20 min, generous on CPU, binding on a slow tunnel).
    try:
        budget_s = float(
            os.environ.get("PINT_TPU_BENCH_BUDGET_S", 1200))
    except ValueError:
        log("unparseable PINT_TPU_BENCH_BUDGET_S; using 1200s")
        budget_s = 1200.0
    print(json.dumps(attach_dispatch_counters(north)))
    sys.stdout.flush()

    # free the big problem before the extra configs
    del jitted, args, step_fn, model, toas

    for fn in (config1_ngc6440e, config2_b1855like,
               config3_j1713like_wideband, config4_j0613like_fullcov,
               config5_pta):
        if time.perf_counter() - t_start > budget_s:
            log(f"bench budget ({budget_s:.0f}s) spent; skipping "
                f"{fn.__name__} and later configs")
            break
        try:
            t0 = time.perf_counter()
            rec = fn()
            rec["backend"] = backend
            log(f"{rec['metric']}: {rec['value']} {rec['unit']} "
                f"({time.perf_counter() - t0:.0f}s total)")
            if backend == "tpu":
                tpu_record_append(rec)
            print(json.dumps(rec))
        except Exception as e:  # a config failure must not cost the
            log(f"{fn.__name__} failed: {e!r}")  # north-star artifact
        print(json.dumps(attach_dispatch_counters(north)))
        sys.stdout.flush()

    # retry the TPU late if this process is the CPU fallback: the
    # tunnel may have recovered while the heavy work ran
    north_is_foreign = False
    if os.environ.get("PINT_TPU_BENCH_FALLBACK"):
        late = late_tpu_probe()
        if late is not None and late.get("backend") == "tpu":
            log("late TPU probe succeeded; recording TPU north star")
            print(json.dumps(attach_dispatch_counters(north)))  # keep the CPU record visible
            north = late
            north_is_foreign = True  # counters are the SUBPROCESS's

    if not north_is_foreign:
        # final refresh of this process's own counters (the attach is
        # setdefault, so configs-phase activity needs the drop first)
        north.pop("dispatch_supervisor", None)
    print(json.dumps(attach_dispatch_counters(north)))


if __name__ == "__main__":
    main()
