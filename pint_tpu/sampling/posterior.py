"""Traced posterior surfaces for the device-native sampler.

Reference: src/pint/bayesian.py + src/pint/mcmc_fitter.py — the host
fitters evaluate lnposterior on the host per batch; here the WHOLE
lnposterior (jnp-traceable priors from ``models.priors`` + the
noise-marginalized likelihood core) is a traced function the chain
kernel calls inside its ``lax.scan``, so an entire ensemble run is
one dispatch (ROADMAP item 5).

Two modes:

- fixed noise (default): wraps ``BayesianTiming``'s traced likelihood
  closure — hyperparameters frozen at construction, exactly the
  reference's sampling mode;
- ``sample_noise=True``: appends the GP noise hyperparameters
  (PLRedNoise log10_A/gamma, ECORR weights) as sampled dimensions via
  ``SampledNoiseLikelihood`` — phi, the per-epoch variances, the Sff
  Cholesky and the log-determinant recomputed in-trace per walker.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DevicePosterior"]


class DevicePosterior:
    """lnposterior as a traceable batch function (W, ndim) -> (W,).

    ``param_labels`` orders theta: the model's free timing parameters
    (BayesianTiming validates the packed order), then — with
    ``sample_noise`` — the noise labels of
    ``SampledNoiseLikelihood``. ``theta0`` is the current point.
    """

    def __init__(self, model, toas, sample_noise: bool = False):
        from pint_tpu.bayesian import BayesianTiming

        self.model = model
        self.toas = toas
        self.bt = BayesianTiming(model, toas)
        self.sample_noise = bool(sample_noise)
        ntim = self.bt.nparams
        self.ntiming = ntim
        th0_j = jnp.asarray(self.bt.theta0)
        tl0_j = jnp.asarray(self.bt._tl0)
        priors: List = list(self.bt._priors)
        labels = list(self.bt.param_labels)
        theta0 = np.asarray(self.bt.theta0, dtype=np.float64)

        if sample_noise:
            from pint_tpu.sampling.likelihood import (
                SampledNoiseLikelihood,
            )

            self.noise = SampledNoiseLikelihood(model, toas,
                                                bt=self.bt)
            labels += self.noise.labels
            theta0 = np.concatenate([theta0, self.noise.eta0])
            priors += self.noise.priors
            core = self.noise.lnlike_core

            def lnpost_one(theta):
                lp = _prior_sum(priors, theta)
                tl_eff = tl0_j + (theta[:ntim] - th0_j)
                ll = core(tl_eff, theta[ntim:])
                return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)
        else:
            self.noise = None
            core = self.bt._lnlike_core_raw

            def lnpost_one(theta):
                lp = _prior_sum(priors, theta)
                ll = core(tl0_j + (theta - th0_j))
                return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        self.param_labels = labels
        self.nparams = len(labels)
        self.theta0 = theta0
        self._priors = priors
        self.lnpost_one = lnpost_one
        self.lnpost_batch = jax.vmap(lnpost_one)

    def init_scales(self) -> np.ndarray:
        """Per-dimension walker-scatter scales: the parameter's
        quoted uncertainty when it has one, a relative floor
        otherwise; noise dimensions (log10/spectral-index units, all
        O(1)) default to 0.1."""
        scales = np.empty(self.nparams)
        for k, name in enumerate(self.param_labels):
            if k < self.ntiming:
                p = self.model.get_param(name)
                scales[k] = p.uncertainty if p.uncertainty else \
                    max(abs(self.theta0[k]) * 1e-10, 1e-14)
            else:
                scales[k] = 0.1
        return scales

    def init_walkers(self, nwalkers: int,
                     rng: Optional[np.random.Generator] = None,
                     scatter: float = 0.5) -> np.ndarray:
        rng = rng or np.random.default_rng()
        return self.theta0[None, :] + scatter \
            * self.init_scales()[None, :] \
            * rng.standard_normal((nwalkers, self.nparams))


def _prior_sum(priors, theta):
    """Traced sum of per-parameter prior log-densities (None =
    improper flat = exactly 0, the BayesianTiming convention)."""
    lp = jnp.asarray(0.0, jnp.float64)
    for k, p in enumerate(priors):
        if p is not None:
            lp = lp + p.logpdf(theta[k])
    return lp
