"""DeviceEnsembleSampler: supervised whole-chain-on-device runs.

Reference: src/pint/sampler.py (EmceeSampler) — same stretch-move
ensemble as ``pint_tpu.sampler.EnsembleSampler``, but the per-step
host loop (two supervised dispatches PER MCMC STEP — the exact
dispatch-tax shape ISSUE 7 eliminated for fitting) collapses into one
deadline-supervised dispatch per chain CHUNK: the compiled
``lax.scan`` of ``sampling.kernel`` runs K steps in-kernel with the
actual step count as a runtime budget, K drawn from the quantized set
of ``config.chain_chunk_steps`` so compile keys stay bounded.

Modes:

- ``mode="scan"`` (default): whole-chain — ceil(nsteps/K) supervised
  dispatches total;
- ``mode="host_loop"``: the SAME kernel compiled at K=1, one
  supervised dispatch per step. Because the PRNG streams are
  positional (``fold_in(key, global_step)``), the two modes consume
  identical randomness — host_loop is both the CPU bit-equality
  oracle and the baseline ``bench_posterior.py`` measures the
  speedup against.

Every dispatch routes through the runtime ``DispatchSupervisor``
(graftlint G6 is pinned over this package): watchdog deadline scaled
by the chunk's step count, with a host failover that re-runs the
chunk pinned to the host CPU device — bit-identical on a CPU backend,
and on a wedged accelerator the labeled degraded-but-correct path
(same policy as the serve capacity router's host pool).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.sampler import ChainStats

__all__ = ["DeviceEnsembleSampler"]


class DeviceEnsembleSampler(ChainStats):
    """Whole-chain-on-device ensemble sampler.

    ``lnpost_batch`` must be a TRACEABLE (S, ndim) -> (S,) function
    (``DevicePosterior.lnpost_batch``; the host sampler takes a host
    callable instead — that is the API split between the two)."""

    def __init__(self, nwalkers: int, ndim: int, lnpost_batch,
                 a: float = 2.0, thin: int = 1):
        if nwalkers < 2 * ndim or nwalkers % 2:
            raise ValueError(
                "need an even nwalkers >= 2*ndim for ensemble moves")
        self.nwalkers = nwalkers
        self.ndim = ndim
        self.a = float(a)
        self.thin = max(1, int(thin))
        self._lnpost_batch = lnpost_batch
        self._jitted: dict = {}      # chunk K -> jitted chunk fn
        self._lp0_jit = None
        self.chain: Optional[np.ndarray] = None
        self.lnprob: Optional[np.ndarray] = None
        self.naccepted = 0
        self.niterations = 0
        self.mode: Optional[str] = None
        # supervised chunk dispatches — registry-backed (ISSUE 11 /
        # graftlint G13): the per-run attribute read is a derived
        # view of the bound counter child
        from pint_tpu.obs import metrics as om

        self._c_dispatches = om.counter(
            "pint_tpu_chain_dispatches_total",
            "whole-chain-on-device chunk dispatches"
        ).child(scope=om.new_scope("chain"))

        self._dispatch_base = 0

    @property
    def dispatches(self) -> int:
        return int(self._c_dispatches.value()) - self._dispatch_base

    def reset_dispatch_count(self):
        """Zero the per-run ``dispatches`` view (bench repeats).
        The registry counter stays monotonic — only the derived
        per-sampler view rebases."""
        self._dispatch_base = int(self._c_dispatches.value())

    def _chunk(self, k: int):
        import jax

        from pint_tpu.sampling.kernel import build_stretch_chunk

        if k not in self._jitted:
            self._jitted[k] = jax.jit(build_stretch_chunk(
                self._lnpost_batch, self.nwalkers, self.ndim, k,
                thin=self.thin if k > 1 else 1, a=self.a))
        return self._jitted[k]

    def _initial_lp(self, pos: np.ndarray) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from pint_tpu.runtime import get_supervisor

        if self._lp0_jit is None:
            self._lp0_jit = jax.jit(self._lnpost_batch)
        fn = self._lp0_jit

        def run():
            out = np.asarray(fn(jnp.asarray(pos)))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            return out if out.flags.owndata else out.copy()

        def run_pinned():
            with jax.default_device(jax.devices("cpu")[0]):
                return run()

        from pint_tpu import obs

        with obs.span("sampling.lnpost0"):
            return get_supervisor().dispatch(
                run, key="sampling.lnpost0", fallback=run_pinned)

    def run_mcmc(self, p0: np.ndarray, nsteps: int, seed: int = 0,
                 mode: str = "scan",
                 progress: bool = False) -> np.ndarray:
        """Run the ensemble; returns the final (W, ndim) positions,
        stores the thinned chain in ``self.chain``. ``seed`` anchors
        the positional PRNG stream (identical across modes)."""
        import jax
        import jax.numpy as jnp

        from pint_tpu import config
        from pint_tpu.runtime import get_supervisor

        pos = np.array(p0, dtype=np.float64)
        if pos.shape != (self.nwalkers, self.ndim):
            raise ValueError(f"p0 must be {(self.nwalkers, self.ndim)}")
        if nsteps % self.thin:
            raise ValueError("nsteps must be a multiple of thin")
        if nsteps < 1 or nsteps >= 2 ** 31:
            # the positional PRNG offset is an int32: past 2^31 the
            # fold_in streams would wrap and repeat
            raise ValueError("nsteps must be in [1, 2^31)")
        self.mode = mode
        lp = np.array(self._initial_lp(pos), dtype=np.float64)
        if not np.any(np.isfinite(lp)):
            raise ValueError("no walker starts at finite posterior")
        if mode == "host_loop":
            k = 1
        elif mode == "scan":
            k = config.chain_chunk_steps(nsteps, thin=self.thin)
        else:
            raise ValueError(f"unknown mode {mode!r}")
        fn = self._chunk(k)
        sup = get_supervisor()
        thin = self.thin if k > 1 else 1
        chains, lnps = [], []
        done = 0
        seed = int(seed)
        while done < nsteps:
            budget = int(min(k, nsteps - done))
            pos_h, lp_h, off = pos, lp, done

            def run(pos_h=pos_h, lp_h=lp_h, budget=budget, off=off):
                key = jax.random.PRNGKey(seed)
                out = fn(jnp.asarray(pos_h), jnp.asarray(lp_h), key, budget, off)  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
                hs = [np.asarray(o) for o in out]
                return [h if h.flags.owndata else h.copy()
                        for h in hs]

            def run_pinned(run=run):
                # host failover: the SAME chunk re-run pinned to the
                # host CPU device — hang-free planned capacity, the
                # chain continues from the carried (pos, lp) state
                with jax.default_device(jax.devices("cpu")[0]):
                    return run()

            from pint_tpu import obs

            with obs.span("sampling.chunk", steps=int(budget)):
                dinfo: dict = {}
                out = sup.dispatch(run, key="sampling.chain",
                                   steps=budget,
                                   fallback=run_pinned, info=dinfo)
                # health tap (ISSUE 14): the chunk's walker
                # log-posteriors and acceptance count are ALREADY
                # returned by the dispatch — observing them adds
                # zero dispatches. NaN/+inf log-posteriors are the
                # incident class; the acceptance fraction is
                # recorded as a GAUGE only (no default band —
                # healthy stretch ensembles range widely, so a
                # collapse is a dashboard signal, not an incident).
                # Attributed to the pool that ACTUALLY produced the
                # result (the supervisor marks failovers in dinfo)
                from pint_tpu.obs import health as _health

                _health.observe(
                    "posterior.chunk",
                    {"lnpost": out[1],
                     "accept_frac": float(out[2])
                     / max(1, int(budget) * self.nwalkers)},
                    pool="host" if dinfo.get("failover")
                    else "device",
                    key="sampling.chain")
            self._c_dispatches.inc()
            pos = np.asarray(out[0], np.float64)
            lp = np.asarray(out[1], np.float64)
            self.naccepted += int(out[2])
            rows = -(-budget // thin)
            chains.append(np.asarray(out[3])[:rows])
            lnps.append(np.asarray(out[4])[:rows])
            done += budget
            self.niterations += budget * self.nwalkers
            if progress:
                print(f"  chunk done: {done}/{nsteps} "
                      f"acc={self.acceptance_fraction:.2f}")
        self.chain = np.concatenate(chains, axis=0)
        self.lnprob = np.concatenate(lnps, axis=0)
        if mode == "host_loop" and self.thin > 1:
            # the K=1 kernel emits every step; thin on the host so
            # both modes return the same (nsteps//thin, W, ndim)
            # chain (scan rows are the state after each thin block)
            self.chain = self.chain[self.thin - 1::self.thin]
            self.lnprob = self.lnprob[self.thin - 1::self.thin]
        return pos
