"""Batched posterior-chain kernel for the serve layer.

A served posterior request samples the LINEARIZED timing posterior of
one pulsar's ``parallel.pta.PulsarProblem`` — the exact Gaussian whose
mean/covariance the GLS solve reports (bases marginalized via the same
masked Woodbury algebra as ``pta._solve_one``), explored by the
stretch-move chain kernel. Because the likelihood consumes the same
padded (M, F, phi, r, nvec, valid, pvalid) arrays the GLS buckets
consume, a bucket of posterior requests for DIFFERENT pulsars
coalesces into one vmapped dispatch exactly like GLS batches do
(walker/step shape classes bound the executables; ISSUE 9 tentpole).

Per slot the kernel:

1. builds the marginal precision A and rhs b of the scaled parameter
   block by Schur-complementing the noise-basis block out of the
   masked normal matrix (identical scaling/pinning to ``_solve_one``,
   so padded rows/columns are inert and A is well-conditioned);
2. initializes W walkers around the GLS solution, overdispersed by
   2 marginal sigmas (padded parameter dims pinned to exactly 0 —
   stretch moves between zeros stay zero, and the Hastings factor
   uses the REAL dimension count sum(pvalid));
3. runs the shared ``build_stretch_chunk`` scan with a per-slot
   runtime step budget and per-slot PRNG key (a request's stream
   depends only on its own seed, never on its batch position);
4. emits the thinned chain mapped back to physical parameter units
   (the ``dparams`` convention of ``_solve_one``: the correction to
   ADD, sign included).

Oracle: the chain's sample mean/covariance converge on the GLS
``dparams``/``cov`` (tests/test_sampling.py), and a single request
through the ServeEngine is bit-identical to ``sample_problems`` at
the same shape class and seed.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pint_tpu.sampling.kernel import build_stretch_chunk

__all__ = ["make_posterior_slot", "posterior_chunk_driver",
           "sample_problems"]


def make_posterior_slot(W: int, K: int, thin: int = 1,
                        a: float = 2.0, scatter: float = 2.0):
    """Traced one-slot chunk function (vmap it over the batch axis).

    Signature: (M, F, phi, r, nvec, valid, pvalid, key, budget,
    pos_in, lp_in, init, offset) -> (pos, lp, naccept, chain_phys,
    lnprob) with ``init`` a traced bool selecting in-kernel walker
    initialization (chunk 0) over the carried (pos_in, lp_in)."""
    import jax
    import jax.numpy as jnp

    def one(M, F, phi, r, nvec, valid, pvalid, key, budget,
            pos_in, lp_in, init, offset):
        from pint_tpu.parallel.pta import _assemble_normal

        p = M.shape[1]
        # the EXACT joint normal system the GLS solve assembles
        # (shared helper — identical scaling/pinning by construction,
        # not by parallel copies)
        Sigma, b, _, colmax, norm = _assemble_normal(
            M, F, phi, r, nvec, valid, pvalid)
        # Schur-complement the basis block out: A = Spp - SpF Sff^-1
        # SFp is the marginal precision of the scaled parameter block
        # (the same marginalization _solve_one's joint solve encodes)
        q = F.shape[1]
        Spp = Sigma[:p, :p]
        if q:
            SpF = Sigma[:p, p:]
            SFF = Sigma[p:, p:]
            dF = jnp.sqrt(jnp.diagonal(SFF))
            dF = jnp.where((dF == 0) | ~jnp.isfinite(dF), 1.0, dF)
            cfF = jax.scipy.linalg.cho_factor(
                SFF / jnp.outer(dF, dF), lower=True)
            X = jax.scipy.linalg.cho_solve(
                cfF, SpF.T / dF[:, None]) / dF[:, None]   # (q, p)
            A = Spp - SpF @ X
            bn = b[:p] - X.T @ b[p:]
        else:
            A = Spp
            bn = b[:p]
        # re-pin padded dims (the Schur step preserves the pinning,
        # this just keeps it exact against rounding)
        A = A * jnp.outer(pvalid, pvalid) + jnp.diag(1.0 - pvalid)
        bn = bn * pvalid
        d = jnp.sqrt(jnp.diagonal(A))
        d = jnp.where((d == 0) | ~jnp.isfinite(d), 1.0, d)
        cf = jax.scipy.linalg.cho_factor(A / jnp.outer(d, d),
                                         lower=True)
        xhat = jax.scipy.linalg.cho_solve(cf, bn / d) / d
        inv = jax.scipy.linalg.cho_solve(
            cf, jnp.eye(p)) / jnp.outer(d, d)
        sig = jnp.sqrt(jnp.abs(jnp.diagonal(inv)))

        def logp_batch(x):
            # exact Gaussian log-density of the linearized posterior
            # (constant dropped: MH only consumes differences)
            return -0.5 * jnp.einsum("si,ij,sj->s", x, A, x) \
                + x @ bn

        ndim_real = jnp.sum(pvalid)
        chunk = build_stretch_chunk(logp_batch, W, ndim_real, K,
                                    thin=thin, a=a)
        # init stream at the top of the uint32 fold_in range: step
        # streams use fold_in(key, offset+i) with i < 2^31, no overlap
        kinit = jax.random.fold_in(key, 0xFFFFFFFF)
        z = jax.random.normal(kinit, (W, p))
        pos0 = (xhat[None, :] + scatter * sig[None, :] * z) \
            * pvalid[None, :]
        lp0 = logp_batch(pos0)
        pos = jnp.where(init, pos0, pos_in)
        lp = jnp.where(init, lp0, lp_in)
        pos, lp, nacc, chain, lnp = chunk(pos, lp, key, budget,
                                          offset)
        # physical units, dparams sign convention (correction to ADD)
        scale = -pvalid / (colmax * norm)
        return pos, lp, nacc, chain * scale[None, None, :], lnp

    return one


def posterior_chunk_driver(fnv, stacked: dict, seeds, nsteps,
                           W: int, K: int, thin: int,
                           supervisor, key_tag: str,
                           pool: str = "device",
                           sync: bool = True, info: Optional[dict] = None,
                           progress=None):
    """Drive one padded batch through its chunked supervised
    dispatches and return per-slot results.

    ``fnv`` is the jitted vmapped slot kernel; ``seeds``/``nsteps``
    are per-slot. Each chunk is its OWN supervised dispatch (bounded
    watchdog deadline — a long chain can never turn one deadline
    window into an unbounded hang, and a shutdown drain is bounded by
    the in-flight chunk, not the whole chain). ``progress`` (steps
    completed per slot) fires after every chunk — the serve layer
    journals it as a non-terminal progress ack. Returns a zero-arg
    ``collect``; its call yields (chain (P, S_total, W, p), lnprob,
    naccept (P,), rows_done (P,)) host arrays.

    ``pool="host"`` runs every chunk pinned to the host CPU device
    (the capacity router's planned-host-capacity verdict);
    ``pool="device"`` chunks carry a pinned-CPU failover, so a
    backend death mid-chain degrades to a labeled host continuation
    instead of a hung future (the chaos oracle's requirement)."""
    import jax
    import jax.numpy as jnp

    if info is None:
        info = {}
    info.setdefault("pool", pool)
    P = stacked["M"].shape[0]
    seeds = np.asarray(seeds, dtype=np.int64)
    nsteps = np.asarray(nsteps, dtype=np.int64)
    kmax = int(nsteps.max()) if len(nsteps) else 0
    nchunks = max(1, -(-kmax // K))
    pb = stacked["M"].shape[2]
    fell_over = []
    # the read-only problem batch + PRNG key batch are placed on
    # device ONCE per driver, not once per chunk: over the tunnel the
    # repeated H2D of identical (P, N, p) inputs would dominate a
    # deep chain's wall. The pinned-host fallback never reads this
    # cache (its buffers may live on a dead backend) — it rebuilds
    # from the numpy copies, and clears the cache so a later chunk
    # re-places fresh if the device recovers.
    placed: dict = {}

    def _key_batch():
        return np.stack([np.asarray(jax.random.PRNGKey(int(s)))
                         for s in seeds])

    def _chunk_closures(c, pos_h, lp_h):
        """(run, run_pinned, budgets) for chunk ``c`` — the ONE
        dispatch body both the sync loop and the async chunk-0 issue
        path feed to the supervisor."""
        budgets = np.clip(nsteps - c * K, 0, K).astype(np.int32)
        first = c == 0

        def call(st, keys):
            if first:
                pos_in = jnp.zeros((P, W, pb))
                lp_in = jnp.zeros((P, W))
            else:
                pos_in = jnp.asarray(pos_h)
                lp_in = jnp.asarray(lp_h)
            out = fnv(st["M"], st["F"], st["phi"], st["r"], st["nvec"], st["valid"], st["pvalid"], keys, jnp.asarray(budgets), pos_in, lp_in, jnp.asarray(first), jnp.asarray(c * K, jnp.int32))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)
            hs = [np.asarray(o) for o in out]
            return [h if h.flags.owndata else h.copy() for h in hs]

        def run():
            if not placed:
                placed["st"] = {kk: jnp.asarray(v)
                                for kk, v in stacked.items()}
                placed["keys"] = jnp.asarray(_key_batch())
            return call(placed["st"], placed["keys"])

        def run_pinned():
            placed.clear()
            with jax.default_device(jax.devices("cpu")[0]):
                st = {kk: jnp.asarray(v)
                      for kk, v in stacked.items()}
                return call(st, jnp.asarray(_key_batch()))

        return run, run_pinned, budgets

    def chunk_run(c, pos_h, lp_h):
        from pint_tpu import obs

        run, run_pinned, budgets = _chunk_closures(c, pos_h, lp_h)
        with obs.span("posterior.chunk", chunk=c, steps=K,
                      pool=pool):
            if pool == "host":
                out = supervisor.dispatch(
                    run_pinned, key=f"{key_tag}/chunk{c}", steps=K,
                    pinned=True)
                info["used_pool"] = "host"
            else:
                def host_counted():
                    fell_over.append(True)
                    return run_pinned()

                out = supervisor.dispatch(
                    run, key=f"{key_tag}/chunk{c}", steps=K,
                    fallback=host_counted)
        return out, budgets

    def run_chunks():
        pos_h = lp_h = None
        acc = np.zeros(P, np.int64)
        chains: List[np.ndarray] = []
        lnps: List[np.ndarray] = []
        rows_done = np.zeros(P, np.int64)
        for c in range(nchunks):
            out, budgets = chunk_run(c, pos_h, lp_h)
            pos_h = np.asarray(out[0], np.float64)
            lp_h = np.asarray(out[1], np.float64)
            acc += np.asarray(out[2], np.int64)
            chains.append(np.asarray(out[3]))
            lnps.append(np.asarray(out[4]))
            rows_done += budgets // thin
            if progress is not None:
                progress(np.minimum(nsteps, (c + 1) * K))
        if pool != "host":
            info["used_pool"] = "host-failover" if fell_over \
                else "device"
        return _gather(chains, lnps, acc, rows_done)

    def _gather(chains, lnps, acc, rows_done):
        """Per-slot row gather: chunk c's valid rows for slot k are
        its first budget_ck//thin emitted slots (later rows repeat
        the final state under the in-kernel budget mask)."""
        S = K // thin
        chain = np.concatenate(chains, axis=1)
        lnp = np.concatenate(lnps, axis=1)
        rows_total = int(rows_done.max()) if P else 0
        chain_out = np.zeros((P, rows_total, W, pb))
        lnp_out = np.zeros((P, rows_total, W))
        for k in range(P):
            got = 0
            for c in range(len(chains)):
                nkeep = int(np.clip(nsteps[k] - c * K, 0, K)) // thin
                if nkeep == 0:
                    break
                sl = slice(c * S, c * S + nkeep)
                chain_out[k, got:got + nkeep] = chain[k, sl]
                lnp_out[k, got:got + nkeep] = lnp[k, sl]
                got += nkeep
        return chain_out, lnp_out, acc, rows_done

    if sync:
        return run_chunks
    # pipelined drain: chunk 0 of this unit is issued on the
    # supervisor's async pipeline so it overlaps the previous unit's
    # collect; remaining chunks (sequential by construction — each
    # consumes the carried ensemble state) run at collect time
    first_fut = None
    if nchunks >= 1 and pool != "host":
        from pint_tpu import obs

        run0, run0_pinned, _ = _chunk_closures(0, None, None)

        def host_counted0():
            fell_over.append(True)
            return run0_pinned()

        with obs.span("posterior.chunk.issue", chunk=0, steps=K):
            first_fut = supervisor.dispatch_async(
                run0, key=f"{key_tag}/chunk0", steps=K,
                fallback=host_counted0)

    def collect():
        nonlocal first_fut
        if first_fut is None:
            return run_chunks()
        out0 = first_fut.result()
        first_fut = None
        pos_h = np.asarray(out0[0], np.float64)
        lp_h = np.asarray(out0[1], np.float64)
        acc = np.asarray(out0[2], np.int64).copy()
        chains = [np.asarray(out0[3])]
        lnps = [np.asarray(out0[4])]
        rows_done = (np.clip(nsteps, 0, K) // thin).astype(np.int64)
        if progress is not None:
            progress(np.minimum(nsteps, K))
        for c in range(1, nchunks):
            out, budgets = chunk_run(c, pos_h, lp_h)
            pos_h = np.asarray(out[0], np.float64)
            lp_h = np.asarray(out[1], np.float64)
            acc += np.asarray(out[2], np.int64)
            chains.append(np.asarray(out[3]))
            lnps.append(np.asarray(out[4]))
            rows_done += budgets // thin
            if progress is not None:
                progress(np.minimum(nsteps, (c + 1) * K))
        info["used_pool"] = "host-failover" if fell_over \
            else "device"
        return _gather(chains, lnps, acc, rows_done)

    return collect


def sample_problems(problems: Sequence, nwalkers: int, nsteps: int,
                    seeds: Sequence[int], thin: int = 1,
                    shape=None, chunk: Optional[int] = None):
    """Direct (engine-less) batched posterior sampling — the oracle
    surface for the serve path: pad ``problems`` to ``shape``
    ((P, N, p, q), defaults to the batch maxima), run the SAME slot
    kernel at the same (W, K, thin) class, and return per-problem
    (chain (S, W, p_real), lnprob, acceptance_fraction). A
    PosteriorRequest served at the same shape class and seed is
    bit-identical."""
    import jax

    from pint_tpu import config
    from pint_tpu.parallel.pta import stack_problems
    from pint_tpu.runtime import get_supervisor

    problems = list(problems)
    W = int(nwalkers)
    for pr in problems:
        # the slot kernel traces ndim, so build_stretch_chunk cannot
        # check this — an under-walkered stretch ensemble silently
        # never leaves the affine hull of its start positions
        if W % 2 or W < 2 * pr.M.shape[1]:
            raise ValueError(
                f"nwalkers={W} too small for a {pr.M.shape[1]}-dim "
                "problem: need an even nwalkers >= 2*ndim")
    stacked = stack_problems(problems, shape=shape)
    P = stacked["M"].shape[0]
    K = int(chunk) if chunk else config.chain_chunk_steps(
        nsteps, thin=thin)
    fnv = jax.jit(jax.vmap(
        make_posterior_slot(W, K, thin=thin),
        in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, None, None)))
    seeds = list(seeds) + [0] * (P - len(problems))
    nsteps_arr = [nsteps] * len(problems) + [0] * (P - len(problems))
    collect = posterior_chunk_driver(
        fnv, stacked, seeds, nsteps_arr, W, K, thin,
        get_supervisor(), "sampling.post_direct", sync=True)
    chain, lnp, acc, rows = collect()
    out = []
    for k, pr in enumerate(problems):
        p = pr.M.shape[1]
        nrows = int(rows[k])
        # owned copies — a view would pin the whole padded batch
        # buffer (same contract as the served PosteriorResult)
        out.append((np.ascontiguousarray(chain[k, :nrows, :, :p]),
                    lnp[k, :nrows].copy(),
                    float(acc[k]) / max(1, int(nsteps) * W)))
    return out
