"""Whole-chain-on-device affine-invariant ensemble kernel.

Reference: src/pint/sampler.py (EmceeSampler) / Goodman & Weare 2010
— the same stretch move ``pint_tpu.sampler.EnsembleSampler`` runs on
the host, rebuilt as ONE ``lax.scan`` program so an entire ensemble
run is a single deadline-supervised dispatch (the whole-fit pattern
of ISSUE 7, applied to MCMC per ROADMAP item 5): both half-ensemble
updates, the accept/reject, and the ``jax.random`` PRNG threading
all execute in-kernel, with the thinned chain and acceptance counter
as carried outputs.

Design contracts (mirrors ``parallel.build_fit_loop``):

- **quantized compile keys**: the compiled scan length K
  (``config.chain_chunk_steps``) comes from a small power-of-two
  set; the ACTUAL step count rides along as a runtime ``budget``
  argument, so distinct chain lengths never mean distinct
  executables and steps past the budget are skipped by a scalar
  ``lax.cond`` (a true branch skip outside vmap; a masked select
  under the serve layer's batch vmap).
- **positional PRNG**: step i draws all six of its streams from
  ``fold_in(key, offset + i)`` — no carried key state — so a chunked
  chain (offset advancing per chunk) and a host-loop chain (one
  dispatch per step, the dispatch-tax baseline) consume THE
  IDENTICAL stream. The host-loop mode is built from this same
  function at K=1, which is what makes it the bit-equality oracle on
  the CPU mesh (tests/test_sampling.py).
- **thinning**: the emitted chain keeps every ``thin``-th state
  (outer scan of K//thin slots, inner ``fori_loop`` of ``thin``
  steps), bounding the D2H readback for long chains.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["build_stretch_chunk"]


def build_stretch_chunk(logp_batch, nwalkers: int, ndim: int,
                        nsteps: int, thin: int = 1, a: float = 2.0):
    """Build the traced chunk function for one ensemble.

    ``logp_batch``: traceable (half, ndim) -> (half,) log-posterior
    (non-finite values are never accepted — the same -inf prior
    convention as the host sampler). Returns

        chunk(pos, lp, key, budget, offset)
            -> (pos', lp', naccept, chain, lnprob)

    with ``pos`` (W, ndim) f64, ``lp`` (W,), ``key`` a jax PRNG key,
    ``budget``/``offset`` int32 scalars (steps to actually run in
    this chunk / global step index of its first step), ``chain``
    (K//thin, W, ndim) and ``lnprob`` (K//thin, W) — rows past the
    budget repeat the final state and are sliced off by the caller.
    ``naccept`` counts accepted walker moves (budgeted steps only).
    """
    # ndim may be a TRACED scalar (the serve kernel's padded batch:
    # each slot's real dimension count is sum(pvalid), and the
    # Hastings factor z^(d-1) must use the REAL d — padded pinned
    # dims contribute no volume); the walker-count check then falls
    # to the caller, which knows the real dimensions at class time
    if isinstance(ndim, int) and \
            (nwalkers < 2 * ndim or nwalkers % 2):
        raise ValueError(
            "need an even nwalkers >= 2*ndim for ensemble moves")
    if nwalkers % 2:
        raise ValueError("need an even nwalkers")
    if thin < 1 or nsteps % thin:
        raise ValueError("thin must be >= 1 and divide the chunk size")
    half = nwalkers // 2
    nslots = nsteps // thin
    a = float(a)

    def half_move(pos, lp, kz, kp, ku, lo, olo):
        """One stretch-move update of walkers [lo:lo+half] against
        the complementary set [olo:olo+half] (static slices — W and
        the half split are compile-time)."""
        mv = pos[lo:lo + half]
        ot = pos[olo:olo + half]
        # z ~ g(z) prop. 1/sqrt(z) on [1/a, a]
        z = ((a - 1.0) * jax.random.uniform(kz, (half,)) + 1.0) ** 2 \
            / a
        idx = jax.random.randint(kp, (half,), 0, half)
        partners = ot[idx]
        prop = partners + z[:, None] * (mv - partners)
        lp_prop = logp_batch(prop)
        logq = (ndim - 1.0) * jnp.log(z) + lp_prop - lp[lo:lo + half]
        # NaN logq (wild proposal) compares False: never accepted
        accept = jnp.log(jax.random.uniform(ku, (half,))) < logq
        pos = pos.at[lo:lo + half].set(
            jnp.where(accept[:, None], prop, mv))
        lp = lp.at[lo:lo + half].set(
            jnp.where(accept, lp_prop, lp[lo:lo + half]))
        return pos, lp, jnp.sum(accept).astype(jnp.int32)

    def one_step(pos, lp, acc, key, i):
        """Both half-ensemble updates of global step ``i`` — all six
        PRNG streams derive positionally from fold_in(key, i)."""
        k = jax.random.fold_in(key, i)
        kz1, kp1, ku1, kz2, kp2, ku2 = jax.random.split(k, 6)
        pos, lp, n1 = half_move(pos, lp, kz1, kp1, ku1, 0, half)
        pos, lp, n2 = half_move(pos, lp, kz2, kp2, ku2, half, 0)
        return pos, lp, acc + n1 + n2

    def chunk(pos, lp, key, budget, offset):
        pos = jnp.asarray(pos, jnp.float64)
        lp = jnp.asarray(lp, jnp.float64)
        budget = jnp.asarray(budget, jnp.int32)
        offset = jnp.asarray(offset, jnp.int32)

        def outer(carry, o):
            def inner(j, c):
                pos_, lp_, acc_ = c
                local = o * thin + j

                def live(c_):
                    p_, l_, a_ = c_
                    return one_step(p_, l_, a_, key,
                                    offset + local)

                # scalar-pred cond: steps past the runtime budget are
                # SKIPPED (no wasted logp evals for an oversized
                # quantized K); under the serve batch vmap this
                # lowers to a select, which is still correct
                return lax.cond(local < budget, live,
                                lambda c_: c_, c)

            carry = lax.fori_loop(0, thin, inner, carry)
            return carry, (carry[0], carry[1])

        (pos, lp, acc), (chain, lnprob) = lax.scan(
            outer, (pos, lp, jnp.int32(0)), jnp.arange(nslots))
        return pos, lp, acc, chain, lnprob

    return chunk
