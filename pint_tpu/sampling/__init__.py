"""Device-native posterior sampling subsystem (ISSUE 9 / ROADMAP
item 5).

The Bayesian surfaces (``bayesian.py`` / ``sampler.py`` /
``mcmc_fitter.py``) had a vmapped batched posterior but a host-side
Python ensemble loop — two supervised dispatches per MCMC step, the
exact dispatch-tax shape PR 7 eliminated for fitting. This package is
the whole-fit pattern applied to sampling, one module each:

- ``sampling.kernel``: the affine-invariant stretch move (both
  half-ensemble updates, accept/reject, positional jax.random PRNG)
  inside one ``lax.scan`` — a whole ensemble chunk is ONE
  deadline-supervised dispatch, nsteps a RUNTIME budget in quantized
  compile keys (``config.chain_chunk_steps``);
- ``sampling.likelihood``: GP noise-hyperparameter sampling —
  PLRedNoise log10_A/gamma and ECORR weights lifted into the traced
  likelihood (phi, per-epoch variances, Sff Cholesky and logdet
  recomputed in-trace per walker under vmap; PAPERS.md 1202.5932 via
  the 1407.6710 low-rank Woodbury split);
- ``sampling.posterior``: ``DevicePosterior`` — traced priors +
  likelihood as one (W, ndim) -> (W,) batch function, fixed-noise or
  noise-sampled;
- ``sampling.chain``: ``DeviceEnsembleSampler`` — chunked supervised
  whole-chain runs, with a ``host_loop`` mode on the identical
  split-PRNG stream as the CPU bit-equality oracle (and the
  per-step-dispatch baseline ``bench_posterior.py`` measures
  against);
- ``sampling.serve_kernel``: the padded, vmap-across-pulsars batch
  kernel behind the serve layer's ``PosteriorRequest`` path
  (walker/step shape classes, chunked multi-dispatch for long
  chains).

``MCMCFitter``/``PhotonMCMCFitter`` are thin consumers of this
package; graftlint G6 is pinned over it (every device call routes
through ``runtime.DispatchSupervisor``).
"""

from pint_tpu.sampling.chain import DeviceEnsembleSampler  # noqa: F401
from pint_tpu.sampling.kernel import build_stretch_chunk  # noqa: F401
from pint_tpu.sampling.likelihood import (  # noqa: F401
    SampledNoiseLikelihood,
)
from pint_tpu.sampling.posterior import DevicePosterior  # noqa: F401
from pint_tpu.sampling.serve_kernel import (  # noqa: F401
    sample_problems,
)
