"""GP noise-hyperparameter sampled likelihood.

Reference: src/pint/bayesian.py (BayesianTiming) + the standard
red-noise analysis of PAPERS.md 1202.5932 (van Haasteren et al.) with
the low-rank Woodbury evaluation of 1407.6710: the fixed-noise
``BayesianTiming`` freezes ``phi`` and the Woodbury Cholesky at
construction (hyperparameters only move under MCMC there by
re-CONSTRUCTING); here the pieces that depend on the sampled
hyperparameters — the power-law ``phi`` of each PLRedNoise basis, the
per-epoch ECORR variances, the Sff Cholesky and the log-determinant —
are lifted INTO the traced likelihood, so log10_A/gamma and the ECORR
weights become sampled dimensions evaluated per walker under ``vmap``
(the whole ensemble still costs one device program).

What stays static (hyperparameters not sampled here, exactly the
split the Woodbury algebra allows): the white-noise vector ``nvec``
(EFAC/EQUAD), the Fourier/quantization BASES (they depend on the TOA
grid, not on amplitudes), the data-side normal block F^T N^-1 F, and
the per-epoch weight sums the Sherman-Morrison ECORR downdate
consumes. The per-sample recompute is therefore one q x q Cholesky
plus O(q^2) assembly — cheap next to the phase evaluation
(1407.6710's point).

CPU equality oracle: at hyperparameters pinned to the model's current
values, ``lnlike_core(tl_eff, eta0)`` equals the fixed-noise
``BayesianTiming.lnlikelihood`` (tests/test_sampling.py).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.noise import (
    FYR,
    _tdb_seconds,
    create_fourier_design_matrix,
    quantization_buckets,
)
from pint_tpu.models.priors import Log10TransformedPrior

__all__ = ["SampledNoiseLikelihood"]

LN2PI = float(np.log(2.0 * np.pi))


def _powerlaw_traced(f, lgA, gamma):
    """Traced power-law PSD (mirror of models.noise.powerlaw with
    log10-amplitude input): P(f) = A^2/(12 pi^2) f_yr^(gamma-3)
    f^(-gamma)."""
    A2 = 10.0 ** (2.0 * lgA)
    return A2 / (12.0 * jnp.pi ** 2) * FYR ** (gamma - 3.0) \
        * f ** (-gamma)


class SampledNoiseLikelihood:
    """Traced likelihood with PLRedNoise (log10_A, gamma) and ECORR
    (log10 weight) as sampled dimensions.

    ``lnlike_core(tl_eff, eta)`` is the traceable surface: ``tl_eff``
    the dd low-word parameter point (see
    ``bayesian.build_batched_phase_eval``), ``eta`` the noise vector
    laid out as ``labels`` reports — per PLRedNoise component
    ``<comp>.log10_A`` / ``<comp>.gamma``, then one
    ``<ECORR param>.log10`` per active ECORR mask parameter (the
    weight sampled as log10 of the microsecond amplitude). ``eta0``
    holds the model's current values, the pinned-hyperparameter
    oracle point."""

    def __init__(self, model, toas, bt=None):
        from pint_tpu.bayesian import build_batched_phase_eval

        self.model = model
        self.toas = toas
        if bt is not None:
            # reuse the caller's BayesianTiming phase-eval surface
            # (DevicePosterior already built one — rebuilding would
            # double the design-matrix construction AND silently
            # couple two theta0/tl0 copies that must stay identical)
            self.theta0, self.tl0, frac_fn = \
                bt.theta0, bt._tl0, bt._frac_fn
        else:
            self.theta0, self.tl0, frac_fn = build_batched_phase_eval(
                model, toas)

        nvec = jnp.asarray(model.scaled_toa_uncertainty(toas) ** 2)
        w = 1.0 / nvec
        n = toas.ntoas
        logdet_white = float(jnp.sum(jnp.log(nvec)))
        f0 = float(model.F0.value)

        # -- ECORR: segment path with per-epoch variances traced ----
        seg = model.noise_model_ecorr_segments(toas)
        labels: List[str] = []
        eta0: List[float] = []
        priors: List = []
        if seg is not None:
            eid_np, jvar_np, exclude = seg
            nseg = len(jvar_np)          # K + 1 (last slot: no epoch)
            eid = jnp.asarray(eid_np)
            s_seg = jax.ops.segment_sum(w, eid, num_segments=nseg)
            # per-epoch -> ECORR-parameter map, replayed in exactly
            # the enumeration order noise_epoch_segments uses
            # (components in model order, params in ecorrs order,
            # quantization buckets per mask) and VERIFIED against the
            # returned jvar so any future reordering fails loudly
            # instead of silently sampling the wrong epoch's weight
            mjd = toas.get_mjds()
            ep_param: List[int] = []
            ec_params = []
            for c in model.noise_components:
                if not hasattr(c, "noise_epoch_segments"):
                    continue
                for name in getattr(c, "ecorrs", ()):
                    p = c.params[name]
                    if p.value is None:
                        continue
                    idx = np.flatnonzero(p.select_mask(toas))
                    if len(idx) == 0:
                        continue
                    nb = len(quantization_buckets(mjd[idx]))
                    if nb == 0:
                        continue
                    ep_param.extend([len(ec_params)] * nb)
                    ec_params.append(p)
            if len(ep_param) != nseg - 1:
                raise RuntimeError(
                    "ECORR epoch enumeration drifted from "
                    "noise_model_ecorr_segments "
                    f"({len(ep_param)} vs {nseg - 1} epochs)")
            for e, pi in enumerate(ep_param):
                expect = (ec_params[pi].value * 1e-6) ** 2
                if not np.isclose(jvar_np[e], expect, rtol=1e-12):
                    raise RuntimeError(
                        "ECORR epoch->parameter map mismatch at "
                        f"epoch {e}")
            ec_off = len(labels)
            for p in ec_params:
                labels.append(f"{p.name}.log10")
                eta0.append(float(np.log10(p.value)))
                # the parameter's prior is declared over the LINEAR
                # ECORR value (microseconds); the sampled dimension
                # is log10(us), so a set prior needs the
                # change-of-variables Jacobian. None stays the
                # improper flat — flat in log10 is the standard
                # log-uniform choice for a scale hyperparameter.
                pb = getattr(p, "prior", None)
                priors.append(None if pb is None
                              else Log10TransformedPrior(pb))
            ep_param_j = jnp.asarray(np.asarray(ep_param,
                                                dtype=np.int32))
            self._n_ecorr = len(ec_params)
        else:
            eid = s_seg = ep_param_j = None
            nseg = 1
            exclude = ()
            ec_off = 0
            self._n_ecorr = 0

        # -- basis components: static F, phi traced for PLRedNoise --
        pairs = model.noise_model_basis_weight_pairs(toas,
                                                     exclude=exclude)
        if not pairs and seg is None:
            raise ValueError(
                "model has no sampled noise dimensions (no basis "
                "noise component and no ECORR segments)")
        phi_static = []
        rn_slices = []   # (col offset, ncols, freqs, df, eta offset)
        off = 0
        for name, F, phi in pairs:
            comp = {type(c).__name__: c
                    for c in model.noise_components}[name]
            A_g = getattr(comp, "amplitude_gamma", None)
            if A_g is not None and A_g()[0] is not None:
                A, gamma = A_g()
                nmodes = int(comp.TNREDC.value or 30)
                Fc, freqs = create_fourier_design_matrix(
                    _tdb_seconds(toas), nmodes)
                if not np.allclose(Fc, np.asarray(F)):
                    raise RuntimeError(
                        f"{name}: recomputed Fourier basis drifted "
                        f"from noise_basis_weight")
                rn_slices.append((off, F.shape[1],
                                  jnp.asarray(freqs),
                                  float(freqs[0]), len(labels)))
                labels.append(f"{name}.log10_A")
                eta0.append(float(np.log10(A)))
                priors.append(getattr(comp.TNREDAMP, "prior", None)
                              if comp.TNREDAMP.value is not None
                              else None)
                labels.append(f"{name}.gamma")
                eta0.append(float(gamma))
                priors.append(getattr(comp.TNREDGAM, "prior", None)
                              if comp.TNREDGAM.value is not None
                              else None)
            phi_static.append(np.asarray(phi, dtype=np.float64))
            off += F.shape[1]
        if not labels:
            raise ValueError(
                "model has no sampled noise dimensions (no "
                "PLRedNoise amplitude and no ECORR weights)")
        self.labels = labels
        self.eta0 = np.asarray(eta0, dtype=np.float64)
        self.priors = priors
        self.nnoise = len(labels)

        if pairs:
            F_all = jnp.asarray(np.concatenate(
                [np.asarray(F) for _, F, _ in pairs], axis=1))
            Fw = F_all * w[:, None]
            A0 = F_all.T @ Fw           # data block: static
            if eid is not None:
                EF = jax.ops.segment_sum(Fw, eid, num_segments=nseg)
            else:
                EF = None
            phi_static_j = jnp.asarray(np.concatenate(phi_static))
        else:
            F_all = Fw = A0 = EF = phi_static_j = None

        demean = "PhaseOffset" not in model.components
        ec_off_j = ec_off

        def lnlike_core(tl_eff, eta):
            """Traced noise-sampled log-likelihood (see class
            docstring). Mirrors BayesianTiming's fixed-noise core
            with phi / ECORR variances / Sff / logdet recomputed from
            ``eta`` in-trace."""
            eta = jnp.asarray(eta, jnp.float64)
            # per-epoch ECORR variances + Sherman-Morrison terms
            if eid is not None:
                jv_ep = (10.0 ** eta[ec_off_j:ec_off_j
                                     + self._n_ecorr] * 1e-6) ** 2
                jv = jnp.concatenate(
                    [jv_ep[ep_param_j], jnp.zeros(1)])
                g = jv / (1.0 + jv * s_seg)
                logdet_ecorr = jnp.sum(jnp.log1p(jv * s_seg))
            else:
                g = None
                logdet_ecorr = 0.0
            # phi with the sampled power-law slices overwritten
            if phi_static_j is not None:
                phi = phi_static_j
                for coff, ncol, freqs, df, eoff in rn_slices:
                    phi = phi.at[coff:coff + ncol].set(
                        _powerlaw_traced(freqs, eta[eoff],
                                         eta[eoff + 1]) * df)
                # Sff = F^T N_eff^-1 F + phi^-1 (ECORR downdated),
                # Jacobi-preconditioned exactly like the fixed path
                Sff = A0 + jnp.diag(1.0 / phi)
                if EF is not None:
                    Sff = Sff - EF.T @ (g[:, None] * EF)
                dS = jnp.sqrt(jnp.diagonal(Sff))
                Lf = jax.scipy.linalg.cho_factor(
                    Sff / jnp.outer(dS, dS), lower=True)
                logdet = (logdet_white + logdet_ecorr
                          + jnp.sum(jnp.log(phi))
                          + 2.0 * jnp.sum(jnp.log(
                              jnp.diagonal(Lf[0])))
                          + 2.0 * jnp.sum(jnp.log(dS)))
            else:
                dS = Lf = None
                logdet = logdet_white + logdet_ecorr
            lnnorm = -0.5 * logdet - 0.5 * n * LN2PI
            frac = frac_fn(tl_eff)
            if demean:
                wmean = jnp.sum(frac * w) / jnp.sum(w)
                frac = frac - wmean
            r = frac / f0
            rCr = jnp.sum(r * r * w)
            if eid is not None:
                wr_seg = jax.ops.segment_sum(w * r, eid,
                                             num_segments=nseg)
                rCr = rCr - jnp.sum(g * wr_seg ** 2)
            if Fw is not None:
                bF = Fw.T @ r
                if EF is not None:
                    bF = bF - EF.T @ (g * wr_seg)
                bF = bF / dS
                rCr = rCr - bF @ jax.scipy.linalg.cho_solve(Lf, bF)
            return -0.5 * rCr + lnnorm

        self.lnlike_core = lnlike_core
        self._lnlike_jit = jax.jit(lnlike_core)

    def lnlikelihood(self, theta, eta) -> float:
        """Host convenience (oracle surface): evaluate one point,
        supervised like every other device touch in this package."""
        from pint_tpu.runtime import get_supervisor

        tl_eff = self.tl0 + (np.asarray(theta, dtype=np.float64)
                             - self.theta0)
        eta = np.asarray(eta, dtype=np.float64)

        def run():
            return float(self._lnlike_jit(jnp.asarray(tl_eff), jnp.asarray(eta)))  # graftlint: allow G6 -- called inside the supervisor-dispatched closure (watchdog applies)

        from pint_tpu import obs

        with obs.span("sampling.lnlike"):
            return get_supervisor().dispatch(
                run, key="sampling.lnlike")
