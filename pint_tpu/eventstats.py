"""Pulsation-significance statistics over photon phases.

Reference: src/pint/eventstats.py (z2m, hm, hmw, sig2sigma). The Z^2_m
and H-test statistics are trig reductions over the photon axis — one
jitted kernel each; the harmonic axis is a static unroll (m <= 20).

    Z^2_m = (2/W) * sum_{k=1..m} |sum_i w_i e^{2pi i k phi_i}|^2,
    W = sum w_i^2 (weighted; = N unweighted)
    H   = max_{1<=m<=M} (Z^2_m - 4m + 4),  M = 20  (de Jager 1989)

Significance: P(>H) ~= exp(-0.4 H) (de Jager & Busching 2010); Z^2_m is
chi^2 with 2m dof under the null.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["z2m", "hm", "hmw", "h_sig", "sig2sigma", "sf_z2m", "sf_hm", "h2sig"]


@partial(jax.jit, static_argnames=("m",))
def _z2_sums(phases, weights, m: int):
    """Raw weighted trig sums (c_k, s_k), k = 1..m (jnp path)."""
    two_pi_phi = 2.0 * jnp.pi * phases
    ks = jnp.arange(1, m + 1, dtype=phases.dtype)
    ang = ks[:, None] * two_pi_phi[None, :]          # (m, N)
    c = jnp.sum(weights[None, :] * jnp.cos(ang), axis=1)
    s = jnp.sum(weights[None, :] * jnp.sin(ang), axis=1)
    return c, s


# photon count above which the streaming pallas kernel beats XLA's
# materialized (m, N) angle matrix on TPU
_PALLAS_MIN_N = 65536


def _z2_terms(phases, weights, m: int):
    """Per-harmonic |sum|^2 terms scaled by 2/normalization (de Jager
    1989 weighted form). The trig sums come from the pallas streaming
    kernel on TPU for large photon sets (Fermi-scale), jnp elsewhere;
    the normalization is applied in ONE place for both."""
    from pint_tpu.ops.pallas_kernels import (_LANES, pallas_available,
                                             z2_harmonics_pallas)

    if phases.shape[0] >= _PALLAS_MIN_N and m <= _LANES and \
            pallas_available():
        c, s = z2_harmonics_pallas(phases, weights, m=m)
    else:
        c, s = _z2_sums(phases, weights, m)
    norm = jnp.sum(weights ** 2)
    return 2.0 * (c ** 2 + s ** 2) / norm


def z2m(phases, m: int = 2, weights=None) -> float:
    """Z^2_m statistic (reference: eventstats.z2m)."""
    phases = jnp.asarray(phases, dtype=jnp.float64)
    w = (jnp.ones_like(phases) if weights is None
         else jnp.asarray(weights, dtype=jnp.float64))
    return float(jnp.sum(_z2_terms(phases, w, m)))


def hm(phases, m: int = 20) -> float:
    """H-test (reference: eventstats.hm)."""
    return hmw(phases, None, m=m)


def hmw(phases, weights, m: int = 20) -> float:
    """Weighted H-test (reference: eventstats.hmw)."""
    phases = jnp.asarray(phases, dtype=jnp.float64)
    w = (jnp.ones_like(phases) if weights is None
         else jnp.asarray(weights, dtype=jnp.float64))
    terms = _z2_terms(phases, w, m)
    z2 = jnp.cumsum(terms)
    ks = jnp.arange(1, m + 1, dtype=phases.dtype)
    return float(jnp.max(z2 - 4.0 * ks + 4.0))


def sf_hm(h: float) -> float:
    """Null survival probability of the H statistic
    (de Jager & Busching 2010: P ~= exp(-0.4 H))."""
    return float(np.exp(-0.4 * h))


def sf_z2m(z2: float, m: int = 2) -> float:
    """Null survival probability of Z^2_m (chi^2, 2m dof)."""
    from scipy.stats import chi2 as _chi2

    return float(_chi2.sf(z2, 2 * m))


def h_sig(h: float) -> float:
    """H-test significance in Gaussian sigma (computed from
    log P = -0.4 H directly, so huge H never underflows to inf)."""
    return _sigma_from_logsf(-0.4 * float(h))


def sig2sigma(sf: float) -> float:
    """Convert a survival probability to the equivalent one-sided
    Gaussian sigma (reference: eventstats.sig2sigma). Uses log-space
    asymptotics for tiny probabilities."""
    if sf <= 0.0:
        return float("inf")
    return _sigma_from_logsf(np.log(sf))


def _sigma_from_logsf(logsf: float) -> float:
    from scipy.stats import norm as _norm

    if logsf > np.log(1e-300):
        return float(_norm.isf(np.exp(logsf)))
    # asymptotic inversion of the Gaussian tail in log space:
    # sf ~= exp(-x^2/2)/(x sqrt(2pi)) -> x ~= sqrt(-2 ln(sf*sqrt(2pi)x))
    x = np.sqrt(-2.0 * logsf)
    for _ in range(10):
        x = np.sqrt(-2.0 * (logsf + np.log(x * np.sqrt(2 * np.pi))))
    return float(x)


def h2sig(h: float) -> float:
    """Significance in Gaussian sigma of an H-statistic (reference:
    eventstats.h2sig). Delegates to h_sig, which works in log space
    so huge H never underflows to inf."""
    return h_sig(h)
