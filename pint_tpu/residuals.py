"""Residuals: phase and time residuals, chi-square.

Reference: src/pint/residuals.py (Residuals.calc_phase_resids,
calc_time_resids, rms_weighted, chi2). Phase arithmetic stays in
double-double until the fractional part is extracted; everything after
(means, chi2) is f64.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["Residuals"]


class Residuals:
    """Timing residuals of `toas` under `model`.

    track_mode: "nearest" assigns each TOA to the nearest integer pulse;
    "use_pulse_numbers" uses -pn flags (reference: track_mode).
    """

    def __init__(self, toas, model, track_mode: Optional[str] = None,
                 subtract_mean: bool = True, use_weighted_mean: bool = True):
        self.toas = toas
        self.model = model
        if track_mode is None:
            track_mode = ("use_pulse_numbers"
                          if toas.get_pulse_numbers() is not None
                          else "nearest")
        self.track_mode = track_mode
        self.subtract_mean = subtract_mean
        self.use_weighted_mean = use_weighted_mean
        self._phase_resids = None
        self._time_resids = None

    # -- lazy computation ---------------------------------------------

    def calc_phase_resids(self) -> np.ndarray:
        """Residual phase [turns], mean-subtracted (f64)."""
        ph = self.model.phase(self.toas, abs_phase=True)
        if self.track_mode == "use_pulse_numbers":
            pn = self.toas.get_pulse_numbers()
            if pn is None:
                raise ValueError("track_mode=use_pulse_numbers but no "
                                 "-pn flags on these TOAs")
            full = (np.asarray(ph.int) - pn) + np.asarray(ph.frac)
        elif self.track_mode == "nearest":
            full = np.asarray(ph.frac)
        else:
            raise ValueError(f"unknown track_mode {self.track_mode!r}")
        if self.subtract_mean:
            full = full - self._mean(full)
        return full

    def _mean(self, x):
        if not self.use_weighted_mean:
            return x.mean()
        err = self.toas.get_errors()
        if np.any(err == 0):
            return x.mean()
        w = 1.0 / err ** 2
        return np.sum(x * w) / np.sum(w)

    @property
    def phase_resids(self):
        if self._phase_resids is None:
            self._phase_resids = self.calc_phase_resids()
        return self._phase_resids

    def calc_time_resids(self) -> np.ndarray:
        """Residuals in seconds: phase / F0 (reference uses the 'modelF0'
        calctype by default — same thing)."""
        return self.phase_resids / self.model.F0.value

    @property
    def time_resids(self):
        if self._time_resids is None:
            self._time_resids = self.calc_time_resids()
        return self._time_resids

    # -- summary stats -------------------------------------------------

    @property
    def resids_us(self):
        return self.time_resids * 1e6

    def rms_weighted(self) -> float:
        """Weighted RMS [s] (reference: Residuals.rms_weighted)."""
        err_s = self.toas.get_errors() * 1e-6
        if np.any(err_s == 0):
            return float(np.sqrt(np.mean(self.time_resids ** 2)))
        w = 1.0 / err_s ** 2
        r = self.time_resids
        wmean = np.sum(r * w) / np.sum(w)
        return float(np.sqrt(np.sum(w * (r - wmean) ** 2) / np.sum(w)))

    def rms(self) -> float:
        return float(np.sqrt(np.mean(self.time_resids ** 2)))

    @property
    def chi2(self) -> float:
        """chi2 of the residuals. With correlated-noise components this
        is the basis-marginalized GLS chi2 r^T C^-1 r (reference:
        Residuals.calc_chi2 defers to the GLS solve the same way);
        otherwise the white chi2 against scaled TOA errors."""
        if getattr(self.model, "has_correlated_errors", False):
            from pint_tpu.gls import gls_chi2

            return gls_chi2(self.model, self.toas,
                            resids=self.time_resids)
        err_s = self._scaled_errors_s()
        return float(np.sum((self.time_resids / err_s) ** 2))

    def _scaled_errors_s(self):
        scaled = None
        if hasattr(self.model, "scaled_toa_uncertainty"):
            try:
                scaled = self.model.scaled_toa_uncertainty(self.toas)
            except Exception:
                scaled = None
        if scaled is not None:
            return np.asarray(scaled)
        return self.toas.get_errors() * 1e-6

    @property
    def dof(self) -> int:
        return self.toas.ntoas - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof
